#!/usr/bin/env python
"""Randomized fork-choice differential fuzzer — host oracle vs columnar.

Drives seeded random DAG/vote/prune/invalidation interleavings through the
host ProtoArrayForkChoice and the columnar DeviceProtoArrayForkChoice
(numpy engine by default, the jitted device engine with ``--device``) and
exits 1 on ANY divergence: head roots, per-node weights/links, vote
columns, balances, equivocations, or error behaviour.

    python scripts/validate_fork_choice.py --blocks 40 --atts 60 \
        --equivocations 4 --seeds 20
    python scripts/validate_fork_choice.py --device --warmup

Compile-cache note (CPU): the fused device kernel is merkle-scale
(seconds per shape); ``--warmup`` pre-lowers the shape buckets the run
will touch so timing noise stays out of the differential.
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", type=int, default=30,
                    help="block inserts per interleaving")
    ap.add_argument("--atts", type=int, default=40,
                    help="attestation batches per interleaving")
    ap.add_argument("--equivocations", type=int, default=3)
    ap.add_argument("--invalidations", type=int, default=3)
    ap.add_argument("--prunes", type=int, default=2)
    ap.add_argument("--heads", type=int, default=10,
                    help="compared head rounds per interleaving")
    ap.add_argument("--seeds", type=int, default=20,
                    help="number of seeded interleavings")
    ap.add_argument("--seed0", type=int, default=0)
    ap.add_argument("--validators", type=int, default=64)
    ap.add_argument("--device", action="store_true",
                    help="columnar side runs the jitted device engine")
    ap.add_argument("--warmup", action="store_true",
                    help="pre-compile the fused kernel shape buckets")
    args = ap.parse_args()

    from lighthouse_tpu.testing.fork_choice_fuzz import (MismatchError,
                                                         run_fuzz)

    engine = "jit" if args.device else "numpy"
    max_nodes = None
    if args.device:
        # Bound the node count so the jitted shapes stay within the
        # warmed buckets (pow-2 growth would recompile per bucket).
        max_nodes = args.blocks + 8
    if args.warmup and args.device:
        from lighthouse_tpu.fork_choice.device_proto_array import warmup
        t0 = time.perf_counter()
        warmup(max_nodes, args.validators)
        print(json.dumps({"warmup_s": round(time.perf_counter() - t0, 1)}))

    t0 = time.perf_counter()
    try:
        rounds = run_fuzz(
            seeds=range(args.seed0, args.seed0 + args.seeds),
            engine=engine, n_validators=args.validators,
            max_nodes=max_nodes, blocks=args.blocks, atts=args.atts,
            equivocations=args.equivocations,
            invalidations=args.invalidations, prunes=args.prunes,
            head_rounds=args.heads)
    except MismatchError as e:
        print(json.dumps({"result": "MISMATCH", "error": str(e)}))
        return 1
    print(json.dumps({
        "result": "ok", "engine": engine, "seeds": args.seeds,
        "head_rounds_compared": rounds,
        "elapsed_s": round(time.perf_counter() - t0, 1)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
