"""Per-stage profile of the fused TPU BLS batch-verify pipeline.

The production path is ONE jit (`tpu_backend._fused_verify`) with a single
host sync, so end-to-end stage costs can't be timed from outside; this
script times (a) the host marshalling pieces, (b) each kernel queued N×
with one sync (true device cost, amortizing the ~100 ms axon tunnel
roundtrip), and (c) the fused call end-to-end.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
from __graft_entry__ import _enable_compile_cache
_enable_compile_cache()

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.crypto import tpu_backend as TB
from lighthouse_tpu.crypto import pairing_kernel as PK
from lighthouse_tpu.crypto import htc_kernel as HK

N_SETS = 256
S = PK.PREP_S
sks = [bls.SecretKey(0x1000 + i) for i in range(8)]
pks = [k.public_key() for k in sks]
msgs = [b"bench-msg-%02d" % i for i in range(64)]
sets = [bls.SignatureSet(sks[i % 8].sign(msgs[i % 64]), [pks[i % 8]],
                         msgs[i % 64]) for i in range(N_SETS)]

tpu = bls._BACKENDS["tpu"]
assert tpu.verify_signature_sets(sets)  # warm every kernel + the table

# --- host marshalling cost --------------------------------------------------
entries = [(s.signature.point, [k.point for k in s.signing_keys],
            bytes(s.message)) for s in sets]
t0 = time.perf_counter()
messages = [(i // S, i % S, e[2]) for i, e in enumerate(entries)]
u = HK.u_planes_for_messages(messages, 2)
print(f"u_planes (64 msgs × reuse): {(time.perf_counter()-t0)*1e3:8.2f} ms")

# --- per-kernel device cost: queue N, sync once -----------------------------
from lighthouse_tpu.crypto.profiling import profile_stages

for name, val in profile_stages().items():
    if name.startswith("stage_") and name.endswith("_ms"):
        print(f"{name[6:-3]:22s} {val:8.2f} ms/call")

# --- end-to-end fused verify ------------------------------------------------
for _ in range(3):
    t0 = time.perf_counter()
    assert tpu.verify_signature_sets(sets)
    dt = time.perf_counter() - t0
    print(f"fused verify {N_SETS} sets: {dt*1e3:8.1f} ms "
          f"({N_SETS/dt:6.0f} sets/s)")
