"""Differential driver for the PR 20 mesh residency layer.

Runs every mesh-resident subsystem's deterministic scenario on an
N-device virtual CPU mesh AND forced to one device, and requires
bit-identical outputs (the sharded programs reuse the 1-device fold
order, so equality is exact, not approximate).  ``all`` additionally
drives the full modeled slot (registry scatter/rebuild -> packed state
root -> fork-choice head -> slasher ingest) and enforces the warm-slot
transfer budget on the measured slots.

Modes:

    python scripts/validate_mesh.py --devices 8 --subsystem all
        Full differential + modeled-slot run; exit 1 on any digest
        mismatch or budget breach.

    python scripts/validate_mesh.py --devices 8 --subsystem forkchoice
        One subsystem only: tree | registry | packed | forkchoice |
        slasher | all.

    python scripts/validate_mesh.py --devices 8 --warmup
        Compile-cache warmup hook: traces/compiles every mesh program
        the quick tier and the dry run use, so later runs replay
        executables from ``.jax_cache``.

    ... --json
        Emit one machine-readable JSON object (the bench `mesh_slot`
        row shells out with this).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_N_DEV = "8"
if "--devices" in sys.argv:
    _N_DEV = sys.argv[sys.argv.index("--devices") + 1]
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_N_DEV}").strip()
# The process-wide mesh knob sizes get_mesh(); the scenarios flip it to
# 1 themselves for the reference side.
os.environ["LIGHTHOUSE_TPU_MESH_DEVICES"] = _N_DEV

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from lighthouse_tpu.common.compile_cache import enable as _cache_enable  # noqa: E402

_cache_enable(os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache"))

from lighthouse_tpu.parallel import mesh_slot as MS  # noqa: E402
from lighthouse_tpu.parallel.mesh_slot import SUBSYSTEM_CHOICES  # noqa: E402


def main() -> int:
    argv = sys.argv[1:]
    emit_json = "--json" in argv
    warmup = "--warmup" in argv
    subsystem = "all"
    if "--subsystem" in argv:
        subsystem = argv[argv.index("--subsystem") + 1]
    if subsystem not in SUBSYSTEM_CHOICES:
        print(f"validate_mesh: unknown subsystem {subsystem!r} "
              f"(choices: {', '.join(SUBSYSTEM_CHOICES)})",
              file=sys.stderr)
        return 2
    n_dev = int(_N_DEV)
    if not emit_json:
        print(f"devices: {jax.devices()}", flush=True)

    names = ([s for s in SUBSYSTEM_CHOICES if s != "all"]
             if subsystem == "all" else [subsystem])

    if warmup:
        # One pass per scenario at both device counts traces every
        # sharded program into the persistent cache; nothing asserted.
        for name in names:
            MS.check_subsystem(name)
        if subsystem == "all":
            MS.run_slot_model()
            with MS.forced_devices(1):
                MS.run_slot_model()
        print(json.dumps({"warmup": True, "devices": n_dev,
                          "subsystems": names}), flush=True)
        return 0

    out = {"devices": n_dev, "subsystems": {}, "ok": True}
    for name in names:
        res = MS.check_subsystem(name)
        out["subsystems"][name] = res["match"]
        out["ok"] = out["ok"] and res["match"]
        if not emit_json:
            print(f"{name}: {'OK' if res['match'] else 'MISMATCH'} "
                  f"({n_dev}-device vs 1-device)", flush=True)

    if subsystem == "all":
        mesh_run = MS.run_slot_model()
        with MS.forced_devices(1):
            ref_run = MS.run_slot_model()
        slot_ok = mesh_run["digest"] == ref_run["digest"]
        budget_ok = bool(mesh_run["budget"]["ok"]
                         and ref_run["budget"]["ok"])
        out["slot_digest_match"] = slot_ok
        out["slot_budget_ok"] = budget_ok
        out["slot_row_1dev"] = ref_run["rows"][-1]
        out["slot_row_projected"] = MS.projected_slot_row(
            ref_run["rows"][-1], n_dev)
        out["shard_rows"] = {k: len(v)
                             for k, v in mesh_run["shards"].items()}
        out["shards"] = mesh_run["shards"]
        out["ok"] = out["ok"] and slot_ok and budget_ok
        if not emit_json:
            print(f"modeled slot: digest "
                  f"{'OK' if slot_ok else 'MISMATCH'}, budget "
                  f"{'OK' if budget_ok else 'BREACHED'}", flush=True)
            if not budget_ok:
                print(json.dumps({"budget": mesh_run["budget"]}),
                      flush=True)

    if emit_json:
        print(json.dumps(out), flush=True)
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
