"""Two-process localhost testnet over the wire transport.

The seed of the reference's ``testing/simulator``: process A runs a chain
with a validator set and publishes blocks over TCP gossip; process B joins
late with only the genesis state, range-syncs over Req/Resp, then follows
gossip.  Run with no arguments — the script forks itself.

    python scripts/two_node_testnet.py

Exit code 0 iff node B converges to node A's head.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

SLOTS = 8


def _make_chain():
    from lighthouse_tpu.beacon_chain import BeaconChain
    from lighthouse_tpu.crypto import bls as B
    from lighthouse_tpu.store import HotColdDB
    from lighthouse_tpu.testing.harness import StateHarness
    from lighthouse_tpu.types.presets import MINIMAL

    B.set_backend("fake")
    h = StateHarness(n_validators=16, preset=MINIMAL)
    hdr = h.state.latest_block_header.copy()
    hdr.state_root = h.state.tree_hash_root()
    chain = BeaconChain(store=HotColdDB.memory(h.preset, h.spec, h.T),
                        genesis_state=h.state.copy(),
                        genesis_block_root=hdr.tree_hash_root(),
                        preset=h.preset, spec=h.spec, T=h.T)
    return h, chain


def node_a(port_file: str) -> int:
    from lighthouse_tpu.network.transport import WireNetwork

    h, chain = _make_chain()
    net = WireNetwork(chain, name="A")
    with open(port_file, "w") as f:
        f.write(str(net.port))
    # Produce the first half of the chain BEFORE B dials (so B must
    # range-sync), the rest as live gossip.
    for _ in range(SLOTS // 2):
        sb = h.build_block()
        h.apply_block(sb)
        chain.per_slot_task(int(sb.message.slot))
        net.publish_block(sb)
    # Wait for B to connect.
    deadline = time.time() + 30
    while not net.node.peers and time.time() < deadline:
        time.sleep(0.1)
    for _ in range(SLOTS - SLOTS // 2):
        sb = h.build_block()
        h.apply_block(sb)
        chain.per_slot_task(int(sb.message.slot))
        net.publish_block(sb)
        time.sleep(0.2)
    net.node.processor.run_until_idle()
    time.sleep(2.0)  # let B finish importing
    print(json.dumps({"node": "A", "head_slot": chain.head.slot,
                      "head": chain.head.root.hex()}), flush=True)
    return 0


def node_b(port_file: str) -> int:
    from lighthouse_tpu.network.transport import WireNetwork

    _h, chain = _make_chain()
    net = WireNetwork(chain, name="B")
    deadline = time.time() + 30
    while not os.path.exists(port_file) and time.time() < deadline:
        time.sleep(0.1)
    port = int(open(port_file).read())
    peer = net.dial(port)
    # Initial range sync to the peer's head, then follow gossip.
    deadline = time.time() + 60
    while time.time() < deadline:
        target = peer.head_slot()
        if chain.head.slot >= target >= SLOTS:
            break
        if target > chain.head.slot:
            net.node._range_sync(target)
        net.node.processor.run_until_idle()
        time.sleep(0.2)
    print(json.dumps({"node": "B", "head_slot": chain.head.slot,
                      "head": chain.head.root.hex()}), flush=True)
    return 0 if chain.head.slot >= SLOTS else 1


def main() -> int:
    if len(sys.argv) > 1:
        role, port_file = sys.argv[1], sys.argv[2]
        return node_a(port_file) if role == "a" else node_b(port_file)
    import tempfile
    port_file = os.path.join(tempfile.mkdtemp(), "port")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    pa = subprocess.Popen([sys.executable, __file__, "a", port_file],
                          stdout=subprocess.PIPE, text=True, env=env)
    pb = subprocess.Popen([sys.executable, __file__, "b", port_file],
                          stdout=subprocess.PIPE, text=True, env=env)
    out_a, _ = pa.communicate(timeout=180)
    out_b, _ = pb.communicate(timeout=180)
    print(out_a.strip())
    print(out_b.strip())
    a = json.loads([l for l in out_a.splitlines() if l.startswith("{")][-1])
    b = json.loads([l for l in out_b.splitlines() if l.startswith("{")][-1])
    ok = (a["head"] == b["head"] and a["head_slot"] == SLOTS
          and pa.returncode == 0 and pb.returncode == 0)
    print("TESTNET", "CONVERGED" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
