#!/usr/bin/env python
"""Replay one simulated slot through the full pipeline and emit its
trace artifact — the CI-able completeness check for the slot-scope
tracing instrumentation (ISSUE 9).

    JAX_PLATFORMS=cpu python scripts/trace_slot.py --validators 16 \
        --atts 4 [--device] [--out trace.json]

Drives a single in-process node (fake BLS backend by default; pass
``--device`` to keep the configured backend and trace real device
dispatches) through one slot: gossip block arrival → gossip verify →
streamed attestation verification → state transition (per-phase stage
spans from the adapter) → fork-choice apply → head.  Prints a per-stage
summary, optionally writes the Chrome trace-event JSON (open it in
Perfetto / chrome://tracing), and **exits 1 if the assembled trace is
missing any required pipeline stage** — the guard that keeps the
instrumentation honest as the code under it evolves.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--validators", type=int, default=16)
    ap.add_argument("--atts", type=int, default=4,
                    help="attestations gossiped through the streaming "
                         "verify path")
    ap.add_argument("--device", action="store_true",
                    help="keep the configured BLS backend (trace real "
                         "device dispatches; cold compiles may take "
                         "minutes — warm .jax_cache first)")
    ap.add_argument("--out", type=str, default=None,
                    help="write the Chrome trace-event JSON here "
                         "(opens directly in Perfetto)")
    ap.add_argument("--ring", type=int, default=8,
                    help="slot-trace ring size while driving")
    args = ap.parse_args()

    from lighthouse_tpu.common.tracing import PIPELINE_STAGES
    from lighthouse_tpu.testing.trace_drill import drive_traced_slot

    trace, info = drive_traced_slot(
        n_validators=args.validators, n_atts=args.atts,
        device=args.device, ring=args.ring)

    spans = trace["spans"]
    by_id = {s["id"]: s for s in spans}
    by_cat: dict = {}
    for s in spans:
        cat = s["cat"] or "-"
        agg = by_cat.setdefault(cat, {"spans": 0, "ms": 0.0})
        agg["spans"] += 1
        # Only category-ENTRY spans contribute time (a child whose
        # parent is in the same category is already inside its
        # parent's interval — summing both would exceed wall time).
        parent = by_id.get(s["parent"])
        if parent is None or (parent["cat"] or "-") != cat:
            agg["ms"] += s["dur_us"] / 1e3
    print(f"slot {trace['slot']}: {len(spans)} spans "
          f"({info['attestations_published']} attestations streamed, "
          f"{args.validators} validators)")
    for cat in sorted(by_cat):
        agg = by_cat[cat]
        print(f"  {cat:<22} {agg['spans']:>4} spans  "
              f"{agg['ms']:>9.2f} ms (summed)")

    if args.out:
        with open(args.out, "w") as f:
            json.dump(info["chrome_trace"], f)
        print(f"chrome trace written to {args.out} "
              f"({len(info['chrome_trace']['traceEvents'])} events) — "
              "open in Perfetto / chrome://tracing")

    missing = trace["missing_stages"]
    if missing:
        print(f"INCOMPLETE TRACE: missing pipeline stages {missing} "
              f"(required: {list(PIPELINE_STAGES)})", file=sys.stderr)
        return 1
    print("trace complete: all required pipeline stages present "
          f"({list(PIPELINE_STAGES)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
