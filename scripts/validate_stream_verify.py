"""Hostile-drill validator for the streaming verification service.

Replays a deterministic message stream — steady Poisson-ish arrivals at
``--rate`` plus gossip bursts (``--burst``), from
``testing.faults.burst_schedule`` — through a
``beacon_chain.verification_service.VerificationService``, with seeded
fault injection (``--faults``) on the device-dispatch site, and checks
the subsystem's headline claim: **zero valid messages lost** under
injected device failure.  Every message must complete verified (device /
retry / probe / host-fallback path), nothing shed, nothing rejected, and
after a sustained outage the circuit breaker must have re-closed.
Prints one JSON summary (p50/p99 latency vs the SLO, batch-size
histogram, shed/fallback counts, breaker transitions, injector
counters); exit 1 on any loss, exit 0 otherwise.

Flags:
    --messages N   stream length (default 96)
    --rate R       steady arrival rate, messages/s (default 200)
    --burst E:S    every E messages add a burst of S simultaneous
                   arrivals (default 16:8; "0:0" disables)
    --faults SPEC  "RATE[,START:STOP]" — intermittent device-dispatch
                   fail rate, plus an optional sustained-outage window
                   of per-site call sequence numbers (default
                   "0.1,3:9"; "0" disables injection entirely)
    --stall R:S    H2D staging stall: probability R, duration S seconds
                   (default 0:0; exercises the StagedExecutor's
                   sync-staging fallback)
    --slo-ms MS    per-message latency SLO (default 250)
    --max-batch N  bucket dispatch cap (default 32)
    --backend B    bls backend for the drill: fake|python|tpu (default
                   fake — the drill exercises the RESILIENCE machinery;
                   python verifies real host pairings, tpu the device
                   path)
    --keys K       signers per message (default 1)
    --seed S       schedule + injector seed (default 0)
    --compressed   replay arrivals back-to-back instead of against the
                   wall clock (fast; latency percentiles then measure
                   dispatch cost only, not SLO policy)
    --warmup       pre-compile the service's dispatch shapes (every
                   pow-2 bucket width up to --max-batch, --keys signers)
                   through the active backend into ``.jax_cache``, then
                   exit.  Compile-cache note (mirrors tests/conftest.py
                   and scripts/validate_bls_shard.py): cache entries do
                   NOT transfer between processes with different XLA
                   flags — to warm the cache the test suite reads, run

            JAX_PLATFORMS=cpu \
            XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                python scripts/validate_stream_verify.py --warmup \
                    --backend tpu --max-batch 32

Usage:
    python scripts/validate_stream_verify.py
    python scripts/validate_stream_verify.py --rate 2000 --burst 32:16 \
        --faults 0.1,20:28 --slo-ms 50
    python scripts/validate_stream_verify.py --backend python \
        --messages 12 --rate 50 --compressed
"""

import sys; sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))  # noqa: E402

import argparse
import json
import os
import time


def _configure_jax() -> None:
    """Repo-standard persistent compile cache (device backends only)."""
    try:
        import jax
    except Exception:
        return
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)


def _parse_burst(spec: str):
    e, _, s = spec.partition(":")
    return int(e), int(s or 0)


def _parse_faults(spec: str):
    rate_s, _, window = spec.partition(",")
    rate = float(rate_s)
    outage = None
    if window:
        a, _, b = window.partition(":")
        outage = (int(a), int(b))
    return rate, outage


def _warmup(backend: str, max_batch: int, keys: int) -> int:
    """Drive every bucket width the service can dispatch (pow-2 sizes up
    to ``max_batch``) through the active backend once, so a node's first
    streamed dispatch is a persistent-cache hit."""
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.testing.stream_drill import build_sets

    if backend == "tpu":
        from lighthouse_tpu.crypto import tpu_backend  # noqa: F401
    bls.set_backend(backend)
    real = backend != "fake"
    width = 1
    widths = []
    while width <= max_batch:
        widths.append(width)
        width <<= 1
    for w in widths:
        sets = build_sets(w, keys_per_set=keys, real_keys=real)
        t0 = time.monotonic()
        ok = bls.get_backend().verify_signature_sets(sets)
        print(json.dumps({"warmup_width": w, "keys": keys, "ok": bool(ok),
                          "s": round(time.monotonic() - t0, 2)}),
              flush=True)
        if not ok:
            print(f"FAIL: warmup batch of width {w} rejected",
                  file=sys.stderr)
            return 1
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="streaming-verification hostile drill")
    ap.add_argument("--messages", type=int, default=96)
    ap.add_argument("--rate", type=float, default=200.0)
    ap.add_argument("--burst", default="16:8")
    ap.add_argument("--faults", default="0.1,3:9")
    ap.add_argument("--stall", default="0:0")
    ap.add_argument("--slo-ms", type=float, default=250.0)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--backend", default="fake",
                    choices=("fake", "python", "tpu"))
    ap.add_argument("--keys", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--compressed", action="store_true")
    ap.add_argument("--warmup", action="store_true")
    args = ap.parse_args()

    if args.backend == "tpu" or args.warmup:
        _configure_jax()
    if args.warmup:
        return _warmup(args.backend, args.max_batch, args.keys)
    if args.backend == "tpu":
        from lighthouse_tpu.crypto import tpu_backend  # noqa: F401

    from lighthouse_tpu.testing.stream_drill import run_drill

    burst_every, burst_size = _parse_burst(args.burst)
    fail_rate, outage = _parse_faults(args.faults)
    stall_rate_s, _, stall_dur_s = args.stall.partition(":")
    out = run_drill(
        n_messages=args.messages, rate_per_s=args.rate,
        burst_every=burst_every, burst_size=burst_size,
        fail_rate=fail_rate, outage=outage,
        h2d_stall=(float(stall_rate_s), float(stall_dur_s or 0)),
        slo_ms=args.slo_ms, max_batch=args.max_batch,
        keys_per_set=args.keys, backend=args.backend,
        real_keys=args.backend != "fake",
        realtime=not args.compressed, seed=args.seed)
    print(json.dumps(out, indent=2))

    ok = bool(out["zero_loss"])
    breaker = out["envelope"]["breaker"]
    if breaker["trips"] >= 1 and not out["recovered"]:
        print("FAIL: circuit breaker never re-closed after the outage",
              file=sys.stderr)
        ok = False
    print("ZERO-LOSS DRILL PASSED" if ok
          else f"FAIL: {out['lost']} valid message(s) lost "
               f"(shed={out['shed']} rejected={out['rejected']})",
          file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
