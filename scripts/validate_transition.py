"""Differential replay: vectorized state transition vs the scalar oracle.

``--epochs N`` replays N randomized epochs through the single-pass epoch
path (and, with ``--device``, the jitted device sweep) against the
stepwise oracle, diffing every registry column, the balance/score/
participation columns, and the state root on mismatch.  ``--blocks N``
does the same for attestation-heavy blocks through the batched block path
vs the scalar per-attestation loop.  Exit 1 on the first mismatch with a
per-column report — the ``validate_pairing_kernels.py`` idiom for the
state-transition layer.

Usage:
    python scripts/validate_transition.py --epochs 8 [--device] [--seed 3]
    python scripts/validate_transition.py --blocks 4
"""

import sys; sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))  # noqa: E402

import argparse
import os
import time

import numpy as np

from lighthouse_tpu.testing.random_states import (diff_states as _diff_states,
                                                   random_epoch_state as _random_epoch_state)

def validate_epochs(n_epochs: int, n_validators: int, seed: int,
                    device: bool) -> int:
    from lighthouse_tpu.state_transition import per_epoch as PE
    from lighthouse_tpu.types.chain_spec import ChainSpec, ForkName
    from lighthouse_tpu.types.factory import spec_types
    from lighthouse_tpu.types.presets import MINIMAL

    preset = MINIMAL
    T = spec_types(preset)
    fork = ForkName.CAPELLA
    spec = ChainSpec.minimal().with_forks_at_genesis(fork)
    rng = np.random.default_rng(seed)
    failures = 0
    for e in range(n_epochs):
        state = _random_epoch_state(rng, n_validators, T, preset, fork)
        fused = state.copy()
        oracle = state.copy()
        t0 = time.time()
        if device:
            os.environ["LIGHTHOUSE_TPU_EPOCH_DEVICE"] = "1"
        try:
            PE.process_epoch_single_pass(fused, fork, preset, spec, T)
        finally:
            os.environ.pop("LIGHTHOUSE_TPU_EPOCH_DEVICE", None)
        t_fused = time.time() - t0
        t0 = time.time()
        PE.process_epoch_stepwise(oracle, fork, preset, spec, T)
        t_step = time.time() - t0
        diffs = _diff_states(f"epoch {e}", fused, oracle)
        status = "OK" if not diffs else "MISMATCH"
        print(f"epoch {e}: {status}  fused {t_fused * 1e3:.1f} ms "
              f"vs stepwise {t_step * 1e3:.1f} ms", flush=True)
        for line in diffs:
            print("  " + line)
        failures += bool(diffs)
    return failures


def validate_blocks(n_blocks: int, seed: int) -> int:
    from lighthouse_tpu.crypto import bls as B
    from lighthouse_tpu.testing.harness import StateHarness
    from lighthouse_tpu.types.presets import MINIMAL
    from lighthouse_tpu.state_transition import (SignatureStrategy,
                                                 state_transition)

    B.set_backend("fake")
    failures = 0
    h = StateHarness(n_validators=64, preset=MINIMAL)
    h.extend_chain(3, strategy=SignatureStrategy.NO_VERIFICATION)
    for b in range(n_blocks):
        sb = h.build_block()
        fused = h.state.copy()
        oracle = h.state.copy()
        t0 = time.time()
        fused = state_transition(fused, sb, h.preset, h.spec, h.T,
                                 strategy=SignatureStrategy.NO_VERIFICATION)
        t_vec = time.time() - t0
        os.environ["LIGHTHOUSE_TPU_BATCHED_ATTS"] = "0"
        os.environ["LIGHTHOUSE_TPU_SINGLE_PASS_EPOCH"] = "0"
        try:
            t0 = time.time()
            oracle = state_transition(
                oracle, sb, h.preset, h.spec, h.T,
                strategy=SignatureStrategy.NO_VERIFICATION)
            t_sca = time.time() - t0
        finally:
            os.environ.pop("LIGHTHOUSE_TPU_BATCHED_ATTS", None)
            os.environ.pop("LIGHTHOUSE_TPU_SINGLE_PASS_EPOCH", None)
        diffs = _diff_states(f"block {b}", fused, oracle)
        status = "OK" if not diffs else "MISMATCH"
        print(f"block {b} (slot {int(sb.message.slot)}, "
              f"{len(sb.message.body.attestations)} atts): {status}  "
              f"batched {t_vec * 1e3:.1f} ms vs scalar {t_sca * 1e3:.1f} ms",
              flush=True)
        for line in diffs:
            print("  " + line)
        failures += bool(diffs)
        h.apply_block(sb, strategy=SignatureStrategy.NO_VERIFICATION)
    return failures


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=0)
    ap.add_argument("--blocks", type=int, default=0)
    ap.add_argument("--validators", type=int, default=256)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--device", action="store_true",
                    help="route the fused sweep through the jitted kernel")
    args = ap.parse_args()
    if not args.epochs and not args.blocks:
        args.epochs = 8
        args.blocks = 4
    failures = 0
    if args.epochs:
        failures += validate_epochs(args.epochs, args.validators, args.seed,
                                    args.device)
    if args.blocks:
        failures += validate_blocks(args.blocks, args.seed)
    print("RESULT:", "PASS" if failures == 0 else f"{failures} FAILURES")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
