import sys; sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))
import time, numpy as np, jax, jax.numpy as jnp
print("devices:", jax.devices(), flush=True)


def validate_kzg(n_blobs: int, width: int) -> None:
    """--kzg mode: the device KZG reduction (barycentric Fr kernel + 2
    Miller lanes per blob) vs the host RLC fold, on random blobs — valid
    batch, per-blob tamper, and proof-swap must all agree."""
    import random
    from lighthouse_tpu.kzg import device as D, kzg as K
    from lighthouse_tpu.kzg.fr import BLS_MODULUS
    from lighthouse_tpu.kzg.trusted_setup import verification_setup

    t0 = time.time()
    # Verifier-only setup: known-tau commit/prove + verify never read
    # the width-sized g1_lagrange table.
    setup = verification_setup(width)
    rng = random.Random(0)
    blobs, cms, pfs = [], [], []
    for _ in range(n_blobs):
        blob = K.polynomial_to_blob(
            [rng.randrange(BLS_MODULUS) for _ in range(width)])
        cm = K.blob_to_kzg_commitment(blob, setup)
        blobs.append(blob); cms.append(cm)
        pfs.append(K.compute_blob_kzg_proof(blob, cm, setup))
    print(f"setup+fixtures ({n_blobs} blobs, width {width}):",
          round(time.time() - t0, 2), "s", flush=True)

    cases = [
        ("valid", blobs, cms, pfs),
        ("swapped_proofs", blobs, cms, list(reversed(pfs))),
        ("tampered_blob", [blobs[0][:-32] + b"\x00" * 32] + blobs[1:],
         cms, pfs),
    ]
    for name, bs, cs, ps in cases:
        t0 = time.time()
        dev = K.verify_blob_kzg_proof_batch(bs, cs, ps, setup,
                                            use_device=True)
        t_dev = time.time() - t0
        t0 = time.time()
        host = K.verify_blob_kzg_proof_batch(bs, cs, ps, setup,
                                             use_device=False)
        t_host = time.time() - t0
        assert dev == host, f"{name}: device={dev} host={host} DISAGREE"
        from lighthouse_tpu.common import tracing
        print(f"{name}: device={dev} ({round(t_dev, 2)}s) == host "
              f"({round(t_host, 2)}s); "
              f"stages={tracing.stage_split('kzg')}",
              flush=True)
        assert dev == (name == "valid"), f"{name}: wrong verdict {dev}"
    print("kzg device reduction == host fallback OK", flush=True)


if "--kzg" in sys.argv:
    i = sys.argv.index("--kzg")
    n_blobs = int(sys.argv[i + 1]) if len(sys.argv) > i + 1 else 4
    width = int(sys.argv[i + 2]) if len(sys.argv) > i + 2 else 16
    validate_kzg(n_blobs, width)
    sys.exit(0)

from lighthouse_tpu.crypto import curve as C, fields as F, pairing as HP
from lighthouse_tpu.crypto import limb_field as LF, limb_tower as LT
from lighthouse_tpu.crypto import pairing_kernel as PK

def g1_planes(pts, M):
    out = np.zeros((64, M), np.uint32)
    for i, p in enumerate(pts):
        out[0:26, i] = LF.to_mont(p[0]); out[32:58, i] = LF.to_mont(p[1])
    return out

def g2_planes(pts, M):
    out = np.zeros((128, M), np.uint32)
    for i, p in enumerate(pts):
        (x0, x1), (y0, y1) = p
        out[0:26, i] = LF.to_mont(x0); out[32:58, i] = LF.to_mont(x1)
        out[64:90, i] = LF.to_mont(y0); out[96:122, i] = LF.to_mont(y1)
    return out

def lane_fq12(fpl, lane):
    c = [LF.from_mont(np.asarray(fpl[i*32:i*32+26, lane])) for i in range(12)]
    return (((c[0], c[1]), (c[2], c[3]), (c[4], c[5])),
            ((c[6], c[7]), (c[8], c[9]), (c[10], c[11])))

M = 128
p1 = [C.g1_mul(C.G1_GEN, 100 + i) for i in range(3)]
q2 = [C.g2_mul(C.G2_GEN, 200 + i) for i in range(3)]
g1p = jnp.asarray(g1_planes(p1 + [p1[0]]*(M-3), M))
g2p = jnp.asarray(g2_planes(q2 + [q2[0]]*(M-3), M))
t0 = time.time()
fpl = PK.miller_kernel_call(g1p, g2p); fpl.block_until_ready()
print("miller compile+run:", round(time.time()-t0, 2), flush=True)
t0 = time.time()
fpl = PK.miller_kernel_call(g1p, g2p); fpl.block_until_ready()
print("miller 2nd (M=128):", round((time.time()-t0)*1000, 1), "ms", flush=True)
g1p2 = jnp.concatenate([g1p, g1p], axis=1)
g2p2 = jnp.concatenate([g2p, g2p], axis=1)
t0 = time.time()
f2 = PK.miller_kernel_call(g1p2, g2p2); f2.block_until_ready()
print("miller M=256 compile+run:", round(time.time()-t0, 2), flush=True)
t0 = time.time()
f2 = PK.miller_kernel_call(g1p2, g2p2); f2.block_until_ready()
print("miller 2nd (M=256):", round((time.time()-t0)*1000, 1), "ms", flush=True)

# correctness: final-exp(cubed) of lane i vs host oracle
fnp = np.asarray(fpl)
for i in range(3):
    dev_f = lane_fq12(fnp, i)
    got = F.fq12_pow(HP.final_exponentiation(dev_f), 3)
    want = F.fq12_pow(HP.pairing(p1[i], q2[i]), 3)
    assert got == want, f"lane {i} mismatch"
print("miller lanes match host oracle (x3)", flush=True)

# product kernel: lanes [pa,pn] * 126 masked → product over classes
pa = C.g1_mul(C.G1_GEN, 111); qb = C.g2_mul(C.G2_GEN, 222)
pn = C.g1_neg(C.g1_mul(C.G1_GEN, 111*222))
g1c = jnp.asarray(g1_planes([pa, pn] + [pa]*(M-2), M))
g2c = jnp.asarray(g2_planes([qb, C.G2_GEN] + [qb]*(M-2), M))
fc = PK.miller_kernel_call(g1c, g2c)
mask = np.zeros((1, M), np.int32); mask[0, :2] = 1
t0 = time.time()
prod = PK.product_kernel_call(fc, jnp.asarray(mask)); prod.block_until_ready()
print("product kernel compile+run:", round(time.time()-t0, 2), flush=True)
pnp = np.asarray(prod)
acc = F.FQ12_ONE
for i in range(128):
    acc = F.fq12_mul(acc, lane_fq12(pnp, i))
assert HP.final_exponentiation(acc) == F.FQ12_ONE, "product != 1 after final exp"
print("bilinear product check OK", flush=True)
