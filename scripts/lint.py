#!/usr/bin/env python
"""graftlint CLI — run the repo's static-analysis pass.

Usage:
    python scripts/lint.py                 # lint the tree, exit 1 on
                                           # any unwaived finding
    python scripts/lint.py --changed       # only files touched per
                                           # git (fast pre-commit)
    python scripts/lint.py --baseline      # regenerate the waiver
                                           # baseline (justifications
                                           # preserved; NEW entries
                                           # need one written by hand)
    python scripts/lint.py --fix-readme    # re-render the README knob
                                           # table from the registry
    python scripts/lint.py --list          # list checkers

Pure host logic — no jax import, no device: safe anywhere, fast
everywhere (the whole tree lints in ~1 s).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lighthouse_tpu import analysis  # noqa: E402
from lighthouse_tpu.analysis.checkers import readme_drift  # noqa: E402
from lighthouse_tpu.common.knobs import render_knob_table  # noqa: E402


def changed_files() -> list:
    """Lintable files touched per git (staged + unstaged + untracked),
    intersected with the standard lint set."""
    # --untracked-files=all: the default collapses an untracked
    # directory to one "dir/" entry, hiding every file inside it.
    # -z: NUL-separated, UNQUOTED paths (the default C-quotes
    # non-ASCII names, which would never intersect the lint set).
    out = subprocess.run(
        ["git", "-C", REPO, "status", "--porcelain", "-z",
         "--untracked-files=all"],
        capture_output=True, text=True, check=True).stdout
    touched = set()
    fields = iter(out.split("\0"))
    for field in fields:
        if len(field) < 4:
            continue
        touched.add(field[3:])
        if field[0] in "RC":  # rename/copy: next field is the OLD path
            next(fields, None)
    lintable = set(analysis.lint_files(REPO))
    return sorted(touched & lintable)


def fix_readme() -> int:
    path = os.path.join(REPO, "README.md")
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    if readme_drift.committed_table(text) is None:
        print(f"README.md: {readme_drift.BEGIN} … {readme_drift.END} "
              f"markers not found — add them where the knob table "
              f"belongs, then re-run", file=sys.stderr)
        return 1
    new = readme_drift.replace_table(text, render_knob_table())
    if new == text:
        print("README knob table already up to date")
        return 0
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(new)
    print("README knob table re-rendered from the registry")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--changed", action="store_true",
                    help="lint only git-touched files")
    ap.add_argument("--baseline", action="store_true",
                    help="regenerate the waiver baseline")
    ap.add_argument("--fix-readme", action="store_true",
                    help="re-render the README knob table")
    ap.add_argument("--list", action="store_true",
                    help="list registered checkers")
    ap.add_argument("files", nargs="*",
                    help="explicit files to lint (default: the tree)")
    args = ap.parse_args(argv)

    if args.fix_readme:
        return fix_readme()
    if args.baseline and (args.changed or args.files):
        # A subset run sees only a slice of the findings; regenerating
        # from it would silently delete every out-of-subset waiver
        # (and its hand-written justification).
        print("graftlint: --baseline requires a full-tree run "
              "(drop --changed / file arguments)", file=sys.stderr)
        return 2
    if args.list:
        from lighthouse_tpu.analysis import checkers as _  # noqa
        for name in sorted(analysis.CHECKERS):
            print(f"{name:18s} {analysis.CHECKERS[name].doc}")
        return 0

    files = None
    if args.files:
        files = [os.path.relpath(os.path.abspath(f), REPO)
                 .replace(os.sep, "/") for f in args.files]
        unknown = sorted(set(files) - set(analysis.lint_files(REPO)))
        if unknown:
            # A mistyped path silently linting nothing would read as a
            # clean pass — refuse instead.
            for f in unknown:
                print(f"graftlint: {f}: not in the lint set "
                      f"(lighthouse_tpu/, scripts/, bench.py)",
                      file=sys.stderr)
            return 2
    elif args.changed:
        files = changed_files()
        if not files:
            print("graftlint: no lintable files changed")
            return 0

    findings = analysis.run(REPO, files=files)

    if args.baseline:
        try:
            keep = analysis.load_baseline(REPO)
        except analysis.BaselineError:
            # Regenerating FROM a baseline with missing justifications:
            # keep whatever arguments exist, drop nothing silently.
            import json
            with open(os.path.join(REPO, analysis.BASELINE_PATH)) as fh:
                raw = json.load(fh)
            keep = {w.get("key"): w.get("justification") or ""
                    for w in raw.get("waivers", [])
                    if isinstance(w, dict) and w.get("key")}
        n = analysis.write_baseline(REPO, findings, keep)
        missing = sum(1 for f in {f.key for f in findings}
                      if not keep.get(f))
        print(f"baseline written: {n} waivers"
              + (f" ({missing} need a justification written "
                 f"before lint passes)" if missing else ""))
        return 0

    try:
        baseline = analysis.load_baseline(REPO)
    except analysis.BaselineError as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 1

    unwaived, waived, stale = analysis.apply_baseline(findings, baseline)
    for f in unwaived:
        print(f.render())
    if stale and files is None:
        # Only meaningful on full-tree runs: a --changed subset never
        # sees most findings, so most waivers LOOK stale there.
        for key in stale:
            print(f"stale waiver (matches nothing — remove it): {key}",
                  file=sys.stderr)
    scope = f"{len(files)} changed file(s)" if files is not None \
        else "tree"
    print(f"graftlint: {scope}: {len(unwaived)} unwaived, "
          f"{len(waived)} waived"
          + (f", {len(stale)} stale waiver(s)"
             if stale and files is None else ""))
    return 1 if unwaived or (stale and files is None) else 0


if __name__ == "__main__":
    sys.exit(main())
