#!/usr/bin/env python
"""Kill-at-every-op crash-recovery drill — exits 1 on ANY post-restart
divergence from a never-crashed oracle chain.

Default mode wraps the KV store in the fault-injecting
:class:`~lighthouse_tpu.testing.crash_drill.CrashingStore` and, for
EVERY store-op kill point N across a multi-slot import sequence (both
backends), kills the node after op N, restarts from the surviving
bytes, runs startup recovery, finishes the sequence and diffs
head/justified/finalized/per-node fork-choice weights against the
oracle.

    python scripts/validate_crash_recovery.py --slots 32 --seeds 2
    python scripts/validate_crash_recovery.py --slots 32 --sample 8
    python scripts/validate_crash_recovery.py --sigkill --seeds 3

``--sigkill`` adds the real thing: a subprocess imports the same
deterministic sequence into an on-disk SQLite datadir and is SIGKILL'd
mid-import (no cleanup, no atexit — the OS reaps it); the parent then
resumes from the datadir and runs the same comparison.  The fixture is
deterministic (interop keys, no entropy), so parent and child build
bit-identical block sequences.
"""

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, ".")


def _fixture(slots: int):
    from lighthouse_tpu.crypto import bls as B
    B.set_backend("fake")
    from lighthouse_tpu.testing.crash_drill import build_chain_fixture
    return build_chain_fixture(slots=slots)


def _child(datadir: str, slots: int) -> int:
    """SIGKILL-mode child: import the deterministic sequence into an
    on-disk store, reporting progress per import so the parent can time
    its kill.  Never exits cleanly unless it finishes every block."""
    from lighthouse_tpu.store import HotColdDB, SqliteStore
    from lighthouse_tpu.testing.crash_drill import make_chain
    fx = _fixture(slots)
    kv = SqliteStore(os.path.join(datadir, "store.sqlite"))
    store = HotColdDB(kv, fx.preset, fx.spec, fx.T)
    chain = make_chain(store, fx)
    print("READY", flush=True)
    for slot, root, sb in fx.blocks:
        chain.per_slot_task(slot)
        chain.process_block(sb)
        print(f"IMPORTED {slot} {root.hex()}", flush=True)
    print("DONE", flush=True)
    return 0


def _sigkill_round(slots: int, seed: int) -> dict:
    """Spawn the child, SIGKILL it after a seeded number of imports,
    resume from its datadir, finish the sequence, diff vs the oracle."""
    from lighthouse_tpu.beacon_chain import BeaconChain
    from lighthouse_tpu.store import HotColdDB, SqliteStore
    from lighthouse_tpu.testing.crash_drill import (
        MemoryBackend, compare_chains, import_sequence, run_oracle)

    fx = _fixture(slots)
    oracle = run_oracle(fx, MemoryBackend())
    rng = random.Random(seed)
    kill_after = rng.randrange(1, slots)
    with tempfile.TemporaryDirectory() as datadir:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child",
             "--datadir", datadir, "--slots", str(slots)],
            stdout=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        imported = 0
        assert proc.stdout is not None
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            if line.startswith("IMPORTED"):
                imported += 1
                if imported >= kill_after:
                    os.kill(proc.pid, signal.SIGKILL)
                    break
            elif line.startswith("DONE"):
                break
        proc.wait(timeout=60)
        # The restart: a fresh connection against whatever survived.
        kv = SqliteStore(os.path.join(datadir, "store.sqlite"))
        store = HotColdDB(kv, fx.preset, fx.spec, fx.T)
        chain = BeaconChain.from_store(store=store, preset=fx.preset,
                                       spec=fx.spec, T=fx.T)
        report = chain.last_recovery
        import_sequence(chain, fx)
        divergences = compare_chains(chain, oracle)
        kv.close()
    return {
        "seed": seed,
        "killed_after_imports": kill_after,
        "child_rc": proc.returncode,
        "replayed": len(report.replayed) if report else 0,
        "quarantined": len(report.quarantined) if report else 0,
        "divergences": divergences,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", type=int, default=32,
                    help="import-sequence length (≥32 for the "
                    "acceptance drill)")
    ap.add_argument("--seeds", type=int, default=1,
                    help="seeded rounds (kill-point sampling / SIGKILL "
                    "timing vary per seed)")
    ap.add_argument("--seed0", type=int, default=0)
    ap.add_argument("--sample", type=int, default=0,
                    help="random kill points per backend per seed "
                    "(0 = exhaustive: every op)")
    ap.add_argument("--backend", choices=["memory", "sqlite", "both"],
                    default="both")
    ap.add_argument("--sigkill", action="store_true",
                    help="also run the real-SIGKILL subprocess rounds")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--datadir", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.child:
        return _child(args.datadir, args.slots)

    from lighthouse_tpu.testing.crash_drill import (
        MemoryBackend, SqliteBackend, count_store_ops, kill_point_drill)

    fx = _fixture(args.slots)
    failures = 0
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as tmp:
        backends = {"memory": [MemoryBackend()],
                    "sqlite": [SqliteBackend(tmp)],
                    "both": [MemoryBackend(), SqliteBackend(tmp)]}[
                        args.backend]
        for seed in range(args.seed0, args.seed0 + args.seeds):
            for backend in backends:
                points = None
                if args.sample:
                    total = count_store_ops(fx, backend)
                    rng = random.Random(seed * 1000 + args.sample)
                    points = sorted(rng.sample(
                        range(total), min(args.sample, total)))
                rep = kill_point_drill(fx, backend, points, seed=seed)
                rep["seed"] = seed
                print(json.dumps(rep), flush=True)
                failures += len(rep["failures"])
        if args.sigkill:
            for seed in range(args.seed0, args.seed0 + args.seeds):
                rep = _sigkill_round(args.slots, seed)
                print(json.dumps({"sigkill": rep}), flush=True)
                failures += len(rep["divergences"])
                if rep["child_rc"] is not None and rep["child_rc"] >= 0:
                    # Child exited cleanly before the kill landed — the
                    # round degenerates to a clean-restart check (still
                    # compared above), note it.
                    print(json.dumps({"note": "child finished before "
                                      "SIGKILL landed", "seed": seed}),
                          flush=True)
    print(json.dumps({
        "metric": "crash_recovery_drill",
        "slots": args.slots,
        "seeds": args.seeds,
        "failures": failures,
        "total_s": round(time.perf_counter() - t0, 1),
        "ok": failures == 0,
    }))
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
