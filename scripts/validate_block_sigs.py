#!/usr/bin/env python
"""Differential validation of the overlapped block-signature pipeline
(ISSUE 14): the asynchronously-dispatched batch must be VERDICT-
IDENTICAL to the trailing synchronous verify on every block shape.

    JAX_PLATFORMS=cpu python scripts/validate_block_sigs.py \
        --atts 4 --seeds 3 [--device] [--warmup] \
        [--trace trace.json --modeled-rate 1964.9]

Per seed, a real-signed MINIMAL-preset block (committee attestations +
sync aggregate) is run through ``process_block`` with the overlap knob
ON and OFF, in four variants — valid, tampered nth-attestation
signature, tampered randao reveal, empty-ops — plus a NO_VERIFICATION
control (the tampered block must pass under both paths: no phantom
dispatch).  Outcomes compare as ("ok", post-state root) /
("err", error class); **any divergence exits 1**.

``--device`` keeps the configured BLS backend (the TPU path; otherwise
the python host oracle verifies, real pairings).  ``--warmup``
pre-compiles the K-bucketed dispatch shapes a block batch produces into
``.jax_cache`` (minutes per shape cold on CPU — run once after kernel
changes).  Compile-cache flags: the cache only replays for processes
with matching XLA flags (see tests/conftest.py).

``--trace FILE`` additionally drives one overlapped import with slot
tracing enabled and a MODELED device (a sleep at ``--modeled-rate``
sets/s, default the r5 measured flagship 1964.9 — the sleep releases
the GIL, so the overlap is real), writes the Chrome trace-event JSON
(open in Perfetto: the ``sig_dispatch`` span precedes the deferred
participation/rewards apply, ``sig_join`` trails the post-state root),
and prints the join-wait / device-verify split.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

FLAGSHIP_RATE_SETS_PER_S = 1964.9  # measured r5 flagship (BENCH r5)


def _build_fixture(n_validators: int, n_atts: int):
    from lighthouse_tpu.testing.harness import StateHarness
    from lighthouse_tpu.types.presets import MINIMAL

    h = StateHarness(n_validators=n_validators, preset=MINIMAL)
    for _ in range(3):
        h.apply_block(h.build_block())
    sb = h.build_block()
    atts = list(sb.message.body.attestations)[:max(1, n_atts)]
    if len(list(sb.message.body.attestations)) != len(atts):
        sb = h.build_block(attestations=atts)
    return h, h.state.copy(), sb


def _resign(h, block):
    from lighthouse_tpu.state_transition import interop_secret_key
    from lighthouse_tpu.state_transition.helpers import (
        compute_signing_root, get_domain)
    from lighthouse_tpu.types.chain_spec import Domain

    epoch = int(block.slot) // h.preset.SLOTS_PER_EPOCH
    domain = get_domain(h.state, Domain.BEACON_PROPOSER, epoch, h.preset)
    sig = interop_secret_key(int(block.proposer_index)).sign(
        compute_signing_root(block, domain)).serialize()
    return h.T.signed_block_cls(
        h.fork_at(int(block.slot)))(message=block, signature=sig)


def _run(h, pre, sb, strategy=None, dispatcher=None):
    from lighthouse_tpu.state_transition import SignatureStrategy
    from lighthouse_tpu.state_transition.per_block import (
        BlockProcessingError, process_block)
    from lighthouse_tpu.state_transition.per_slot import process_slots

    if strategy is None:
        strategy = SignatureStrategy.VERIFY_BULK
    state = pre.copy()
    state = process_slots(state, int(sb.message.slot), h.preset, h.spec,
                          h.T)
    try:
        acc = process_block(state, sb, h.fork_at(int(sb.message.slot)),
                            h.preset, h.spec, h.T, strategy=strategy,
                            sig_dispatcher=dispatcher,
                            defer_sig_join=True)
        root = state.tree_hash_root()  # the import-path overlap window
        if acc is not None:
            acc.finish()
    except BlockProcessingError as e:
        return ("err", type(e).__name__)
    return ("ok", root.hex())


def _variants(h, sb, rng):
    from lighthouse_tpu.state_transition import interop_secret_key

    out = [("valid", sb)]
    atts = list(sb.message.body.attestations)
    if atts:
        n = rng.randrange(len(atts))
        block = sb.message.copy()
        block.body.attestations[n].signature = interop_secret_key(0).sign(
            b"tampered-%d" % n).serialize()
        out.append((f"tampered_att_{n}", _resign(h, block)))
    block = sb.message.copy()
    block.body.randao_reveal = interop_secret_key(
        int(block.proposer_index)).sign(b"wrong-epoch").serialize()
    out.append(("tampered_randao", _resign(h, block)))
    out.append(("empty_ops",
                h.build_block(attestations=[], sync_participation=0.0)))
    return out


def _with_knob(value: str):
    os.environ["LIGHTHOUSE_TPU_OVERLAP_BLOCK_SIGS"] = value


def _warmup(h, pre, sb) -> None:
    """Pre-compile the K-bucketed dispatch shapes of this block batch
    (one overlapped run on the CONFIGURED backend — its jit programs
    persist into .jax_cache)."""
    t0 = time.perf_counter()
    _with_knob("1")
    out = _run(h, pre, sb)
    print(f"warmup: overlapped dispatch ran ({out[0]}) in "
          f"{time.perf_counter() - t0:.1f}s — shapes persisted")


def _trace_run(h, pre, sb, out_path: str, rate: float) -> dict:
    from lighthouse_tpu.common.tracing import TRACER
    from lighthouse_tpu.common import tracing
    from lighthouse_tpu.state_transition.sig_dispatch import (
        BlockSigDispatcher)

    def modeled_device(sets):
        time.sleep(len(sets) / rate)   # releases the GIL — real overlap
        return True

    disp = BlockSigDispatcher(device_fn=modeled_device,
                              host_fn=modeled_device,
                              name="block_sigs_modeled")
    _with_knob("1")
    _run(h, pre, sb, dispatcher=disp)  # warm the dispatcher/envelope
    was_enabled = TRACER.enabled
    try:
        if not was_enabled:
            TRACER.reset()
        TRACER.enable()
        slot = int(sb.message.slot)
        TRACER.set_slot(slot)
        # An import-shaped enclosing span: the per-phase stage children
        # and the sig spans assemble into ONE slot trace, like the real
        # chain import path.
        with TRACER.span("block_import", cat="block_import", slot=slot):
            verdict = _run(h, pre, sb, dispatcher=disp)
        chrome = TRACER.chrome_trace(slot)
    finally:
        if was_enabled:
            TRACER.enable()
        else:
            TRACER.disable()
            TRACER.reset()
    split = tracing.stage_split("block_sigs")
    block_split = tracing.stage_split("block")
    stats = {
        "verdict": verdict[0],
        "sets": split.get("sets"),
        "deduped": split.get("deduped"),
        "path": split.get("path"),
        "device_verify_ms": split.get("device_verify_ms"),
        "join_wait_ms": split.get("join_wait_ms"),
        "overlap_efficiency": split.get("overlap_efficiency"),
        "dispatched_before_apply": ("sig_dispatch_ms" in block_split
                                    and "deferred_apply_ms" in block_split),
        "modeled_rate_sets_per_s": rate,
    }
    if out_path and chrome is not None:
        with open(out_path, "w") as f:
            json.dump(chrome, f)
        print(f"chrome trace written to {out_path} "
              f"({len(chrome['traceEvents'])} events) — open in Perfetto")
    return stats


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--atts", type=int, default=4)
    ap.add_argument("--validators", type=int, default=32)
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--device", action="store_true",
                    help="keep the configured BLS backend (device path); "
                         "default forces the python host oracle")
    ap.add_argument("--warmup", action="store_true",
                    help="pre-compile the block batch's K-bucketed "
                         "dispatch shapes before validating")
    ap.add_argument("--trace", type=str, default=None,
                    help="write a Chrome trace of one modeled-device "
                         "overlapped import here")
    ap.add_argument("--modeled-rate", type=float,
                    default=FLAGSHIP_RATE_SETS_PER_S,
                    help="modeled device verify rate (sets/s) for "
                         "--trace")
    args = ap.parse_args()

    import random

    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.state_transition import SignatureStrategy

    if not args.device:
        bls.set_backend("python")

    failures = 0
    prev = os.environ.pop("LIGHTHOUSE_TPU_OVERLAP_BLOCK_SIGS", None)
    try:
        h, pre, sb = _build_fixture(args.validators, args.atts)
        if args.warmup:
            _warmup(h, pre, sb)
        for seed in range(args.seeds):
            rng = random.Random(0xB10C + seed)
            for name, variant in _variants(h, sb, rng):
                _with_knob("1")
                got_overlap = _run(h, pre, variant)
                _with_knob("0")
                got_sync = _run(h, pre, variant)
                agree = got_overlap == got_sync
                print(f"seed {seed} {name:<18} overlap={got_overlap} "
                      f"sync={got_sync} {'OK' if agree else 'DIVERGED'}")
                if not agree:
                    failures += 1
                if name.startswith("tampered"):
                    for mode in ("1", "0"):
                        _with_knob(mode)
                        ctl = _run(h, pre, variant,
                                   strategy=SignatureStrategy.
                                   NO_VERIFICATION)
                        if ctl[0] != "ok":
                            print(f"  NO_VERIFICATION control broke "
                                  f"(knob={mode}): {ctl}")
                            failures += 1
        if args.trace:
            stats = _trace_run(h, pre, sb, args.trace, args.modeled_rate)
            print("modeled-device overlap: "
                  + json.dumps(stats, default=str))
    finally:
        if prev is None:
            os.environ.pop("LIGHTHOUSE_TPU_OVERLAP_BLOCK_SIGS", None)
        else:
            os.environ["LIGHTHOUSE_TPU_OVERLAP_BLOCK_SIGS"] = prev

    if failures:
        print(f"FAIL: {failures} divergence(s)", file=sys.stderr)
        return 1
    print("all variants verdict-identical (overlapped == synchronous)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
