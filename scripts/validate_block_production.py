"""Differential replay: device greedy-pack vs the host CELF oracle.

Randomized CSR pools (overlapping / disjoint committees, duplicate
aggregates, tie-heavy reward weights, empty and singleton candidates)
are packed by BOTH engines — the fixed-shape device rounds program
(:func:`lighthouse_tpu.op_pool.device_pack.greedy_pack_device`) and the
host lazy-greedy oracle (:func:`lighthouse_tpu.op_pool.max_cover.
greedy_pack`) — and the SELECTION ORDER is compared exactly: CELF's
(max marginal weight, earliest index) choice must be bit-identical to
the device argmax round for round.  Exit 1 on the first divergence with
the full pool shape + both selections — the ``validate_transition.py``
idiom for the block-production packing layer.

``--device`` forces the jitted pack engine (the program a real TPU
runs, here on the host backend); default exercises the numpy rounds
engine.  ``--warmup`` pre-compiles every pad bucket the trial plan will
hit, so reported device timings are dispatch-only (the production
steady state — buckets compile once, pool growth re-uses them).

Usage:
    python scripts/validate_block_production.py --ops 20 --atts 2000
    python scripts/validate_block_production.py --seeds 0,1,2 --device --warmup
"""

import sys; sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))  # noqa: E402

import argparse
import os
import time

import numpy as np


def random_pool(rng: np.random.Generator, n_cands: int,
                n_validators: int):
    """One randomized pool in CSR form, biased toward the adversarial
    corners: duplicate candidates (identical committees+bits), fully
    overlapping committees, disjoint committees, empty and singleton
    segments, and tie-heavy weights (few distinct balances, so argmax
    order is load-bearing)."""
    segments = []
    pool_committee = rng.choice(n_validators,
                                min(n_validators, 256), replace=False)
    for _ in range(n_cands):
        kind = rng.integers(0, 10)
        if kind == 0 and segments:                # exact duplicate
            segments.append(segments[rng.integers(0, len(segments))])
        elif kind == 1:                           # empty candidate
            segments.append(np.empty(0, np.int64))
        elif kind == 2:                           # singleton
            segments.append(rng.choice(n_validators, 1).astype(np.int64))
        elif kind <= 6:                           # overlapping draw
            size = int(rng.integers(1, 33))
            segments.append(np.sort(rng.choice(
                pool_committee, min(size, pool_committee.size),
                replace=False)).astype(np.int64))
        else:                                     # disjoint-ish draw
            size = int(rng.integers(1, 33))
            segments.append(rng.choice(
                n_validators, size, replace=False).astype(np.int64))
    offsets = np.zeros(len(segments) + 1, np.int64)
    np.cumsum([s.size for s in segments], out=offsets[1:])
    flat_e = (np.concatenate(segments) if segments
              else np.empty(0, np.int64))
    # Tie-heavy weights: 3 distinct effective balances.
    balances = rng.choice(
        np.array([31, 32, 2048], np.int64) * 10**9, n_validators)
    flat_w = balances[flat_e]
    return flat_e, flat_w, offsets


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ops", type=int, default=20,
                    help="randomized pools per seed (default 20)")
    ap.add_argument("--atts", type=int, default=2000,
                    help="candidate aggregates per pool (default 2000)")
    ap.add_argument("--seeds", default="0,1,2",
                    help="comma-separated RNG seeds (default 0,1,2)")
    ap.add_argument("--device", action="store_true",
                    help="force the jitted pack engine "
                         "(LIGHTHOUSE_TPU_PACK_JIT=1)")
    ap.add_argument("--warmup", action="store_true",
                    help="pre-compile every pad bucket the plan hits "
                         "before the checked runs")
    ap.add_argument("--validators", type=int, default=4096,
                    help="registry size (default 4096)")
    ap.add_argument("--limit", type=int, default=128,
                    help="MAX_ATTESTATIONS rounds (default 128)")
    args = ap.parse_args(argv)

    from lighthouse_tpu.op_pool.device_pack import greedy_pack_device
    from lighthouse_tpu.op_pool.max_cover import greedy_pack

    engine = "jit" if args.device else "numpy"
    seeds = [int(s) for s in args.seeds.split(",") if s != ""]
    if args.warmup and engine == "jit":
        # One throwaway pack per seed-plan shape: the pad buckets are
        # shape-keyed, so a dry run on a same-sized pool compiles every
        # kernel the checked runs will dispatch.
        rng = np.random.default_rng(10**9)
        flat_e, flat_w, offsets = random_pool(rng, args.atts,
                                              args.validators)
        t0 = time.time()
        greedy_pack_device(flat_e, flat_w, offsets, args.validators,
                           args.limit, engine=engine)
        print(f"warmup: bucket compile {time.time() - t0:.2f} s",
              flush=True)

    failures = 0
    t_dev = t_host = 0.0
    for seed in seeds:
        rng = np.random.default_rng(seed)
        for trial in range(args.ops):
            # Sweep the pool size across pad buckets (growth must not
            # change selections, only which kernel serves them).
            n_cands = max(0, int(args.atts * (trial + 1) / args.ops))
            flat_e, flat_w, offsets = random_pool(rng, n_cands,
                                                  args.validators)
            t0 = time.time()
            dev = greedy_pack_device(flat_e, flat_w, offsets,
                                     args.validators, args.limit,
                                     engine=engine)
            t_dev += time.time() - t0
            t0 = time.time()
            host, _, _ = greedy_pack(flat_e, flat_w, offsets,
                                     args.validators, args.limit)
            t_host += time.time() - t0
            if list(dev) != list(host):
                failures += 1
                print(f"MISMATCH seed={seed} trial={trial} "
                      f"cands={n_cands} entries={flat_e.size}",
                      flush=True)
                print(f"  device ({engine}): {list(dev)[:24]}...")
                print(f"  host CELF oracle:  {list(host)[:24]}...")
                for r, (a, b) in enumerate(zip(dev, host)):
                    if a != b:
                        print(f"  first divergent round {r}: "
                              f"device chose {a}, host chose {b}")
                        break
        print(f"seed {seed}: {args.ops} pools OK "
              f"(engine={engine})", flush=True)

    n_trials = len(seeds) * args.ops
    print(f"{n_trials} pools x {args.atts} max cands: "
          f"device({engine}) {t_dev:.2f} s, host CELF {t_host:.2f} s, "
          f"failures={failures}")
    if failures:
        print(f"FAIL: {failures} divergent packs", file=sys.stderr)
        return 1
    print("OK: device pack bit-identical to the host CELF oracle")
    return 0


if __name__ == "__main__":
    sys.exit(main())
