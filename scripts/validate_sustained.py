#!/usr/bin/env python
"""Sustained mainnet-cadence SLO drill — the exit-code contract.

Drives ``testing/sustained_load.run_sustained`` (block per slot +
subnet attestation stream + committee aggregates through the REAL
gossip → processor → streaming-verify → fork-choice → op-pool
pipeline) for ``--minutes`` of wall clock at ``--slot-s`` compressed
slots, with the SLO engine as the continuous scoreboard, and exits 1
on any violated invariant:

- any valid-message loss (a gossiped attester not registered, or the
  service's ``verified != submitted`` / ``rejected`` / ``shed`` ≠ 0)
- a slot whose end-of-slot drain timed out (verdicts still in flight —
  box overload, reported distinctly from loss: such a slot's loss
  check cannot certify either way, so the run is not green)
- a declared objective with no computed attainment (a dead feed)
- a proposer-lane block production that missed the slot/3 deadline, or
  a device-vs-host pack divergence on a live pool (the differential
  oracle riding the drill traffic)
- a warm-slot device-transfer budget violation (the device ledger's
  per-slot per-subsystem byte deltas against ``WARM_SLOT_BUDGET`` —
  a full-column host round-trip inside a measured slot fails the run)
- an UNEXPLAINED SLO violation: without ``--faults`` the health state
  must never leave ``healthy``; with ``--faults`` (a device outage
  injected for a slot window) the state must walk degraded → healthy
  and every burned objective must be attributable to the outage
- with ``--faults``: the injector must actually have fired and the
  breaker must have re-closed

The full scoreboard JSON (per-objective attainment/burn/p50/p99,
health-transition log, shed/fallback counts, per-slot health, trace
summaries) is written to ``--out`` — the artifact perf PRs cite.

Usage:
    python scripts/validate_sustained.py --minutes 1 --slot-s 1.0
    python scripts/validate_sustained.py --minutes 1 --faults
    python scripts/validate_sustained.py --realtime --minutes 5
    python scripts/validate_sustained.py --rate 2  # ~2x validator set

``--rate`` scales the validator set (message counts scale with the
committee structure); ``--realtime`` uses the spec slot cadence
instead of compressed slots.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--minutes", type=float, default=1.0,
                    help="wall-clock drill duration (default 1.0)")
    ap.add_argument("--slot-s", type=float, default=1.0,
                    help="compressed slot seconds (default 1.0)")
    ap.add_argument("--realtime", action="store_true",
                    help="use the spec slot cadence (MINIMAL: 6 s)")
    ap.add_argument("--rate", type=float, default=1.0,
                    help="validator-set scale factor (message rate "
                         "scales with committees; default 1.0 = 64)")
    ap.add_argument("--faults", action="store_true",
                    help="inject a device outage for ~15%% of the run "
                         "and require attributed degraded→healthy")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="sustained_slo.json",
                    help="scoreboard artifact path")
    args = ap.parse_args(argv)

    from lighthouse_tpu.testing.sustained_load import run_sustained

    slot_s = 6.0 if args.realtime else args.slot_s
    slots = max(8, int(args.minutes * 60.0 / slot_s))
    n_validators = max(16, int(64 * args.rate))
    outage = None
    if args.faults:
        start = max(2, slots // 4)
        outage = (start, start + max(2, int(slots * 0.15)))

    print(f"sustained drill: {slots} slots x {slot_s}s "
          f"({slots * slot_s:.0f}s wall), {n_validators} validators"
          + (f", outage slots {outage}" if outage else ""), flush=True)

    board = run_sustained(
        slots=slots, slot_s=slot_s, n_validators=n_validators,
        faults_outage_slots=outage, seed=args.seed)

    with open(args.out, "w") as fh:
        json.dump(board, fh, indent=1)

    failures = []
    if board["loss"]["drain_timeouts"]:
        # Distinct from loss: verdicts were still in flight when the
        # slot drain expired — slowness, not dropped messages.  The
        # per-slot loss check was skipped for these slots, so the run
        # cannot certify them either way.
        failures.append(
            f"slot drain timed out (box overload, not loss) at slots "
            f"{board['loss']['drain_timeouts']}")
    if not board["loss"]["zero_loss"]:
        if board["loss"]["missing_observed"] == 0 \
                and board["loss"]["drain_timeouts"]:
            pass  # counter mismatch already attributed to the drain
            #       timeout above — verdicts in flight, not loss
        else:
            failures.append(
                f"valid-message loss: "
                f"{board['loss']['missing_observed']} "
                f"attesters unregistered, rejected="
                f"{board['messages']['rejected']}, "
                f"shed={board['messages']['shed']}, verified="
                f"{board['messages']['verified']}/"
                f"{board['messages']['submitted']}")
    if not board["attainment_complete"]:
        dead = [k for k, v in board["attainment"].items() if v is None]
        failures.append(f"objectives with no attainment (dead feed?): "
                        f"{dead}")
    proof = board.get("proof", {})
    if proof.get("consumers", 0) > 0:
        # The proof-consumer fleet must actually have exercised the
        # serving plane (a silent fleet would leave the proof_serve
        # objective windowless) and every request must have been served.
        if proof.get("consumer_requests", 0) == 0:
            failures.append("proof-consumer fleet made no requests")
        if proof.get("consumer_errors", 0):
            failures.append(
                f"proof-consumer errors: {proof['consumer_errors']} of "
                f"{proof['consumer_requests'] + proof['consumer_errors']}"
                f" requests failed")
    if not board["device_budget"]["ok"]:
        # Warm-slot transfer budget (device ledger): a subsystem moved
        # more bytes in a measured slot than residency allows — the hot
        # path went host-roundtrip-shaped.
        viol = [f"{r['subsystem']}/{r['direction']}: "
                f"{r['worst_slot_bytes']} B > {r['budget_bytes']} B "
                f"(slot {r['worst_slot']})"
                for r in board["device_budget"]["violations"]]
        failures.append("warm-slot transfer budget violated: "
                        + "; ".join(viol))
    production = board["production"]
    if production["produced"] == 0:
        failures.append("proposer lane produced no blocks")
    if production["errors"]:
        failures.append(
            f"block production raised: {production['errors'][:4]}")
    if production["deadline_misses"]:
        # The proposer forfeits a proposal that misses the slot/3
        # broadcast deadline — a miss is a hard failure, not a latency
        # statistic.
        failures.append(
            f"block production missed the {production['deadline_ms']} ms"
            f" deadline at slots {production['deadline_misses']} "
            f"(p99 {production['p99_ms']} ms)")
    if production["pack_divergence"]:
        # The device greedy-pack and the host CELF oracle disagreed on
        # a live pool — a correctness bug, never acceptable.
        failures.append(
            f"device/host pack divergence at slots "
            f"{production['pack_divergence']}")
    transitions = board["health"]["transitions"]
    if not args.faults:
        if transitions or board["health"]["state"] != "healthy":
            failures.append(
                f"unexplained SLO violation: transitions={transitions}, "
                f"final state={board['health']['state']}")
    else:
        attr = board["fault_attribution"]
        if attr["injected"] == 0:
            failures.append("fault drill injected nothing")
        if not attr["went_degraded"]:
            failures.append("outage never degraded the node "
                            "(objectives blind to the fault)")
        if not attr["recovered_healthy"]:
            failures.append(
                f"node did not recover: final state "
                f"{board['health']['state']}, breaker "
                f"{board['breaker']['state']}")
        if not attr["attributed"]:
            failures.append(
                f"violation NOT attributed to the outage: burned "
                f"{attr['burned_objectives']}")
        if board["breaker"]["state"] != "closed":
            failures.append(
                f"breaker still {board['breaker']['state']}")

    summary = {
        "slots": board["config"]["slots"],
        "wall_s": board["wall_s"],
        "rate_atts_per_s": board["rate_atts_per_s"],
        "messages": board["messages"]["submitted"],
        "zero_loss": board["loss"]["zero_loss"],
        "attainment": board["attainment"],
        "health": board["health"]["state"],
        "transitions": [(t["from"], t["to"], t["reasons"])
                        for t in transitions],
        "host_fallbacks": board["host_fallbacks"],
        "production": production,
        "proof": board.get("proof"),
        "device_budget_ok": board["device_budget"]["ok"],
        "device_budget_attainment": board["device_budget"]["attainment"],
        "artifact": args.out,
        "failures": failures,
    }
    print(json.dumps(summary, indent=1))
    if failures:
        print("FAIL:\n  " + "\n  ".join(failures), file=sys.stderr)
        return 1
    print("OK: sustained drill green (zero loss, attainment complete, "
          + ("attributed outage recovered)" if args.faults
             else "no violations)"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
