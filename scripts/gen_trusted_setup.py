"""Regenerate the framework's insecure KZG trusted setup and the pinned
test vectors.

    python scripts/gen_trusted_setup.py --width 4          # setup JSON
    python scripts/gen_trusted_setup.py --width 4 --vectors

Provenance: the setup is powers-of-tau for the PUBLIC
``trusted_setup.INSECURE_TAU`` (sha256 of a fixed tag) — forgeable by
construction, structurally identical to a ceremony transcript.  The
``--width 4`` output is what is embedded as
``trusted_setup.EMBEDDED_MINIMAL_JSON`` (test_kzg pins the equality);
``--vectors`` prints the (blob, commitment, proof, z, y) tuple pinned in
``tests/test_kzg.py``.
"""

import sys, os  # noqa: E401
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import hashlib


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--width", type=int, default=4)
    ap.add_argument("--vectors", action="store_true",
                    help="print the pinned test-vector tuple instead")
    args = ap.parse_args()

    from lighthouse_tpu.kzg import fr, kzg as K
    from lighthouse_tpu.kzg.trusted_setup import (
        INSECURE_TAU, dump_trusted_setup, generate_insecure_setup)

    setup = generate_insecure_setup(args.width)
    if not args.vectors:
        print(dump_trusted_setup(setup))
        print(f"# tau = sha256('lighthouse-tpu insecure kzg tau') mod r "
              f"= {hex(INSECURE_TAU)}", file=sys.stderr)
        return

    evals = [int.from_bytes(hashlib.sha256(
        b"lighthouse-tpu kzg vector %d" % i).digest(), "big")
        % fr.BLS_MODULUS for i in range(args.width)]
    blob = K.polynomial_to_blob(evals)
    cm = K.blob_to_kzg_commitment(blob, setup)
    pf = K.compute_blob_kzg_proof(blob, cm, setup)
    z = K.compute_challenge(blob, cm, args.width)
    y = fr.evaluate_polynomial_in_evaluation_form(evals, z, setup.roots)
    print("BLOB =", blob.hex())
    print("COMMITMENT =", cm.hex())
    print("PROOF =", pf.hex())
    print("Z =", hex(z))
    print("Y =", hex(y))


if __name__ == "__main__":
    main()
