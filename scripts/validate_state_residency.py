"""Differential driver: device-resident BeaconState vs the host oracle.

Builds twin states of ``--validators N``, materializes one
(:func:`~lighthouse_tpu.types.device_state.materialize_state` — HBM
becomes the source of truth), applies ``--mutations M`` randomized rounds
of scatter mutations / appends / copies to BOTH, and asserts the
device-resident ``hash_tree_root`` is byte-identical to the host
incremental root after every round — printing per-round warm-root
timings and the bytes-pushed-per-root residency accounting.  Exit 1 on
the first mismatch (the ``validate_transition.py`` idiom, one layer
down).

``--warmup`` pre-compiles the dirty-propagation programs (leaf scatter →
level propagation, the registry-mirror scatter, and the full-level
rebuild bodies) at the widths the chosen ``--validators`` implies, so a
fresh node's — or the test suite's — first warm root is a persistent
compile-cache hit instead of a cold XLA build.

Compile-cache note (mirrors ``tests/conftest.py``): cache entries do NOT
transfer between processes with different XLA flags.  To warm the same
``.jax_cache`` the test suite reads, run with

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/validate_state_residency.py --warmup ...

(this script sets ``jax_compilation_cache_dir`` to the repo's
``.jax_cache`` itself, like conftest).  With ``--device`` the attached
backend is kept instead (real-TPU residency, Pallas hash kernels).

Usage:
    python scripts/validate_state_residency.py --validators 256 --mutations 32
    python scripts/validate_state_residency.py --validators 4096 --warmup
    python scripts/validate_state_residency.py --device --validators 65536
"""

import sys; sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))  # noqa: E402

import argparse
import os
import time

import numpy as np


def _configure_jax(device: bool) -> None:
    import jax
    if not device:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)


def _mk_state(n: int, seed: int):
    from lighthouse_tpu.types.chain_spec import ForkName
    from lighthouse_tpu.types.factory import spec_types
    from lighthouse_tpu.types.presets import MAINNET
    from lighthouse_tpu.types.validators import ValidatorRegistry

    rng = np.random.default_rng(seed)
    T = spec_types(MAINNET)
    state = T.state_cls(ForkName.CAPELLA)()
    reg = ValidatorRegistry(n)
    reg._n = n
    reg.init_columns(
        pubkey=rng.integers(0, 256, (n, 48), dtype=np.uint8),
        withdrawal_credentials=rng.integers(0, 256, (n, 32), dtype=np.uint8),
        effective_balance=(rng.integers(0, 33, n) * 10 ** 9).astype(
            np.uint64),
        slashed=rng.random(n) < 0.05)
    state.validators = reg
    state.balances = rng.integers(0, 40 * 10 ** 9, n).astype(np.uint64)
    state.previous_epoch_participation = rng.integers(0, 8, n).astype(
        np.uint8)
    state.current_epoch_participation = rng.integers(0, 8, n).astype(np.uint8)
    state.inactivity_scores = rng.integers(0, 100, n).astype(np.uint64)
    return state


def _mutate_round(rng: np.random.Generator, state, k: int) -> None:
    """One randomized mutation round: k scatter writes across the hot
    columns, plus occasional set/append (grow) and row rewrites."""
    from lighthouse_tpu.types.device_state import store_column
    from lighthouse_tpu.types.validators import Validator

    n = state.balances.shape[0]
    idx = np.unique(rng.integers(0, n, max(k, 1)))
    state.balances[idx] = rng.integers(0, 1 << 40, idx.size).astype(
        np.uint64)
    reg = state.validators
    ridx = np.unique(rng.integers(0, len(reg), max(k // 2, 1)))
    state.validators.wcol("effective_balance")[ridx] = (
        rng.integers(0, 33, ridx.size) * 10 ** 9).astype(np.uint64)
    i = int(rng.integers(0, n))
    state.inactivity_scores[i] = np.uint64(rng.integers(0, 1000))
    state.current_epoch_participation[i] |= np.uint8(2)
    if rng.random() < 0.3:  # exact-touched store (the transition-pass seam)
        bal = np.asarray(state.balances, dtype=np.uint64).copy()
        t = np.unique(rng.integers(0, n, 3))
        bal[t] = bal[t] // np.uint64(2)
        store_column(state, "balances", bal, touched=t)
    if rng.random() < 0.2:  # append + grow
        vseed = int(rng.integers(0, 1 << 30))
        vr = np.random.default_rng(vseed)
        reg.append(Validator(
            pubkey=vr.integers(0, 256, 48, dtype=np.uint8).tobytes(),
            withdrawal_credentials=vr.integers(
                0, 256, 32, dtype=np.uint8).tobytes(),
            effective_balance=32 * 10 ** 9, slashed=False,
            activation_eligibility_epoch=0, activation_epoch=0,
            exit_epoch=2 ** 64 - 1, withdrawable_epoch=2 ** 64 - 1))
        state.balances = np.concatenate(
            [np.asarray(state.balances, dtype=np.uint64),
             np.array([32 * 10 ** 9], dtype=np.uint64)])


# The residency subsystems (device-ledger attribution; ISSUE 15 —
# residency is read through the LEDGER snapshot, not
# ops.device_tree.residency_snapshot()).  One shared definition with
# the legacy view.


def _subs():
    from lighthouse_tpu.ops.device_tree import (
        LEGACY_RESIDENCY_SUBSYSTEMS)
    return LEGACY_RESIDENCY_SUBSYSTEMS


def _ledger_snapshot():
    from lighthouse_tpu.common.device_ledger import LEDGER
    return LEDGER.snapshot()["subsystems"]


def _pushed(snap) -> int:
    return sum(snap[s]["h2d_bytes"] for s in _subs())


def validate(n: int, mutations: int, seed: int, copy_every: int) -> int:
    from lighthouse_tpu.common import tracing
    from lighthouse_tpu.types.device_state import materialize_state

    host = _mk_state(n, seed)
    dev = _mk_state(n, seed)
    base = _ledger_snapshot()
    t0 = time.perf_counter()
    if not materialize_state(dev):
        print("materialize_state declined (LIGHTHOUSE_TPU_DEVICE_STATE=0?)")
        return 1
    mat = tracing.stage_split("materialize")
    print(f"materialize: {mat.get('materialize_ms')} ms, "
          f"{mat.get('bytes_pushed')} bytes pushed "
          f"(one-time)", flush=True)
    host.tree_hash_root()

    failures = 0
    for m in range(mutations):
        round_seed = seed * 100003 + m
        k = int(np.random.default_rng(round_seed).integers(1, 64))
        for s in (host, dev):
            _mutate_round(np.random.default_rng(round_seed), s, k)
        if copy_every and m % copy_every == copy_every - 1:
            # COW: continue on clones; the originals must keep their root.
            r_host, r_dev = host.tree_hash_root(), dev.tree_hash_root()
            host2, dev2 = host.copy(), dev.copy()
            _mutate_round(np.random.default_rng(round_seed ^ 1), host2, 4)
            _mutate_round(np.random.default_rng(round_seed ^ 1), dev2, 4)
            if (host.tree_hash_root(), dev.tree_hash_root()) != \
                    (r_host, r_dev):
                print(f"round {m}: COW LEAK into parent")
                failures += 1
            host, dev = host2, dev2
        before = _pushed(_ledger_snapshot())
        t0 = time.perf_counter()
        r_dev = dev.tree_hash_root()
        dev_ms = (time.perf_counter() - t0) * 1e3
        pushed = _pushed(_ledger_snapshot()) - before
        t0 = time.perf_counter()
        r_host = host.tree_hash_root()
        host_ms = (time.perf_counter() - t0) * 1e3
        status = "OK" if r_dev == r_host else "MISMATCH"
        print(f"round {m}: {status}  device {dev_ms:.1f} ms "
              f"({pushed} B pushed) vs host {host_ms:.1f} ms", flush=True)
        if r_dev != r_host:
            failures += 1
            break
    snap = _ledger_snapshot()

    def tot(key: str) -> int:
        return sum(snap[s][key] - base[s][key] for s in _subs())

    print(f"totals: {tot('h2d_bytes')} B pushed, "
          f"{tot('d2h_bytes')} B pulled, {tot('scatters')} scatters, "
          f"{tot('rebuilds')} rebuilds, "
          f"{tot('materializes')} materializes")
    print("per-subsystem ledger:")
    for s in _subs():
        row = snap[s]
        print(f"  {s:16s} h2d={row['h2d_bytes'] - base[s]['h2d_bytes']} B "
              f"d2h={row['d2h_bytes'] - base[s]['d2h_bytes']} B "
              f"resident={row['resident_bytes']} B "
              f"high_water={row['hbm_high_water_bytes']} B")
    return failures


def warmup(n: int) -> None:
    """Pre-compile the dirty-propagation / rebuild programs for an
    ``n``-validator state into the persistent compile cache: the generic
    leaf-scatter tree program at the packed-column widths, and the
    registry mirror's fused scatter + rebuild at the registry width —
    driven through a real materialized state so the traced shapes match
    what ``hash_tree_root`` dispatches (a shape warmed any other way can
    still cold-compile under a differently-configured process; see the
    compile-cache note in the module docstring)."""
    from lighthouse_tpu.ops.device_tree import warmup_scatter
    from lighthouse_tpu.ops.merkle import _next_pow2
    from lighthouse_tpu.types.device_state import materialize_state

    t0 = time.perf_counter()
    w = _next_pow2(max(n, 1))
    programs = warmup_scatter(max(w // 4, 8))  # u64-packed column width
    state = _mk_state(n, seed=0)
    materialize_state(state)
    state.tree_hash_root()
    for k in (1, 8, 64):
        idx = np.arange(min(k, n), dtype=np.int64)
        state.validators.wcol("effective_balance")[idx] = np.uint64(7 + k)
        state.balances[idx] = np.uint64(9 + k)
        state.tree_hash_root()
        programs += 2
    print(f"warmup: ~{programs} programs driven in "
          f"{time.perf_counter() - t0:.1f} s (persistent cache: .jax_cache)")


def main() -> None:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--validators", type=int, default=256)
    ap.add_argument("--mutations", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--copy-every", type=int, default=5,
                    help="interleave a copy-on-write fork every K rounds "
                         "(0 disables)")
    ap.add_argument("--device", action="store_true",
                    help="keep the attached backend (real-TPU residency) "
                         "instead of pinning jax to CPU")
    ap.add_argument("--warmup", action="store_true",
                    help="pre-compile the dirty-propagation programs for "
                         "this width into .jax_cache, then exit")
    args = ap.parse_args()
    _configure_jax(args.device)
    if args.warmup:
        warmup(args.validators)
        return
    failures = validate(args.validators, args.mutations, args.seed,
                        args.copy_every)
    print("RESULT:", "PASS" if failures == 0 else f"{failures} FAILURES")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
