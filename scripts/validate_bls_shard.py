"""Differential driver for the mesh-sharded flagship BLS verify.

Mirrors ``validate_pairing_kernels.py``: runs the sharded
``verify_signature_sets`` over an N-device virtual CPU mesh against the
pure-python host oracle (valid batch, tampered set, uneven remainder),
plus the MXU band-product bit-exactness check and (optionally) the fused
Miller+fold kernel differential.

Modes:

    python scripts/validate_bls_shard.py --sets 64 --devices 8
        Differential run at the given shape.

    python scripts/validate_bls_shard.py --warmup
        Compile-cache warmup hook: compiles every sharded/shared-key
        program the QUICK test tier and the multichip dry run use
        (16-set/8-dev, 4-set/1-dev, 8-set shared-key, 64-set/8-dev
        flagship), so tier-1 wall time replays executables from
        ``.jax_cache`` instead of paying minutes of XLA-CPU compile
        per shape.

    python scripts/validate_bls_shard.py --fused
        Adds the fused Miller+fold vs unfused kernel differential
        (compiles a 256-lane Pallas Miller shape — minutes, cold).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_N_DEV = "8"
if "--devices" in sys.argv:
    _N_DEV = sys.argv[sys.argv.index("--devices") + 1]
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_N_DEV}").strip()

import numpy as np  # noqa: E402
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from lighthouse_tpu.common.compile_cache import enable as _cache_enable  # noqa: E402

_cache_enable(os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache"))

import jax.numpy as jnp  # noqa: E402

from lighthouse_tpu.crypto import bls  # noqa: E402
from lighthouse_tpu.crypto.fields import R  # noqa: E402
from lighthouse_tpu.parallel.mesh import make_mesh  # noqa: E402
from lighthouse_tpu.parallel.bls_shard import (  # noqa: E402
    sharded_verify_signature_sets)

print("devices:", jax.devices(), flush=True)


def mk_sets(n, kps, tag=b"shard-smoke", key0=0x3000):
    sk_ints = [key0 + 5 * i for i in range(n * kps)]
    sks = [bls.SecretKey(v) for v in sk_ints]
    pks = [k.public_key() for k in sks]
    out = []
    for i in range(n):
        lo, hi = i * kps, (i + 1) * kps
        m = tag + b"-%02d" % i
        agg = bls.SecretKey(sum(sk_ints[lo:hi]) % R).sign(m)
        out.append(bls.SignatureSet(agg, list(pks[lo:hi]), m))
    return out


def tamper(sets, i, j):
    bad = list(sets)
    bad[i] = bls.SignatureSet(sets[i].signature, sets[j].signing_keys,
                              sets[i].message)
    return bad


def differential(n_sets, n_devices, kps=2, tag=b"shard-smoke", key0=0x3000):
    from lighthouse_tpu.parallel.bls_shard import _next_pow2
    mesh = make_mesh(jax.devices()[:n_devices])
    sets = mk_sets(n_sets, kps, tag=tag, key0=key0)
    host = bls._BACKENDS["python"]
    # The uneven case only runs when dropping a set keeps the padded
    # shape (same compiled program — this is a masking test, not an
    # excuse to compile another Miller shape).
    uneven = sets[:-1] if (
        n_sets > 1 and _next_pow2(n_sets - 1) == _next_pow2(n_sets)) else sets
    cases = [("valid", sets, True)]
    if n_sets >= 2:  # the key-swap tamper needs two distinct-key sets
        cases.append(
            ("tampered", tamper(sets, n_sets // 3, n_sets // 3 + 1), False))
    cases.append(("uneven", uneven, True))
    for name, batch, want in cases:
        t0 = time.time()
        dev = sharded_verify_signature_sets(batch, mesh)
        t_dev = time.time() - t0
        t0 = time.time()
        oracle = host.verify_signature_sets(batch)
        t_host = time.time() - t0
        assert dev == oracle == want, (
            f"{name}: sharded={dev} host={oracle} want={want}")
        print(f"{name} ({len(batch)} sets / {n_devices} dev): "
              f"sharded={dev} ({t_dev:.1f}s) == host ({t_host:.1f}s)",
              flush=True)
    print(f"sharded flagship == host oracle over {n_devices} devices OK",
          flush=True)


def check_mxu_band():
    from lighthouse_tpu.crypto import limb_field as LF
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.integers(0, 2**16, (64, LF.LIMBS)).astype(np.uint32))
    b = jnp.asarray(rng.integers(0, 2**16, (64, LF.LIMBS)).astype(np.uint32))
    for ncols in (LF.LIMBS, 2 * LF.LIMBS):
        assert (np.asarray(LF._band_columns(a, b, ncols))
                == np.asarray(LF._band_columns_mxu(a, b, ncols))).all()
    print("MXU band product bit-exact vs VPU", flush=True)


def check_fused_miller_fold():
    if jax.default_backend() != "tpu":
        print("fused miller+fold differential SKIPPED: pallas kernels "
              "need a real TPU (CPU pallas_call is interpret-only)",
              flush=True)
        return
    from lighthouse_tpu.crypto import pairing_kernel as PK
    rng = np.random.default_rng(3)
    M = 2 * PK.LANE_BLOCK
    g1 = jnp.asarray(rng.integers(0, 2**16, (64, M)).astype(np.uint32))
    g2 = jnp.asarray(rng.integers(0, 2**16, (128, M)).astype(np.uint32))
    mask = np.zeros((1, M), np.int32)
    mask[0, :7] = 1
    mask = jnp.asarray(mask)
    t0 = time.time()
    f = PK.miller_kernel_call(g1, g2)
    want = np.asarray(PK.product_chunks_kernel_call(f, mask))
    got = np.asarray(PK.miller_fold_kernel_call(g1, g2, mask))
    assert (got == want).all()
    print(f"fused miller+fold == unfused ({time.time() - t0:.1f}s)",
          flush=True)


def shared_key_check(n_msgs=8, kps=6):
    os.environ["LIGHTHOUSE_TPU_HOST_FASTPATH_MAX"] = "0"
    from lighthouse_tpu.crypto import tpu_backend as TB  # noqa: F401
    sk_ints = [0x7000 + 3 * i for i in range(kps)]
    pks = [bls.SecretKey(v).public_key() for v in sk_ints]
    fsum = sum(sk_ints) % R
    msgs = [b"sync-comm-%02d" % i for i in range(n_msgs)]
    fsets = [bls.SignatureSet(bls.SecretKey(fsum).sign(m), list(pks), m)
             for m in msgs]
    tpu = bls._BACKENDS["tpu"]
    assert tpu.verify_signature_sets(fsets) is True
    # Tamper the SIGNATURE (all sets share the same keys, so a key swap
    # would be a no-op): set 1 carries set 2's signature.
    bad = list(fsets)
    bad[1] = bls.SignatureSet(fsets[2].signature, fsets[1].signing_keys,
                              fsets[1].message)
    assert tpu.verify_signature_sets(bad) is False
    print(f"shared-key collapsed path OK ({n_msgs} sets × {kps} keys)",
          flush=True)


if __name__ == "__main__":
    if "--warmup" in sys.argv:
        # The quick-suite programs + the dry-run flagship shape.
        t0 = time.time()
        differential(16, min(8, len(jax.devices())))
        differential(3, 1, kps=1, tag=b"shard-d1", key0=0x5000)
        shared_key_check()
        differential(64, min(8, len(jax.devices())))
        print(f"warmup complete in {time.time() - t0:.0f}s "
              "(executables persisted to .jax_cache)", flush=True)
        sys.exit(0)
    n_sets = int(sys.argv[sys.argv.index("--sets") + 1]) \
        if "--sets" in sys.argv else 16
    check_mxu_band()
    differential(n_sets, int(_N_DEV))
    shared_key_check()
    if "--fused" in sys.argv:
        check_fused_miller_fold()
