"""Measure axon-tunnel roundtrip latency vs true device kernel cost."""
import os
import sys
import time
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
from __graft_entry__ import _enable_compile_cache
_enable_compile_cache()

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto import pairing_kernel as PK

# --- pure roundtrip: tiny compute, full sync --------------------------------
x = jnp.zeros(8, jnp.uint32)
np.asarray(x + 1)
for _ in range(3):
    t0 = time.perf_counter()
    np.asarray(x + 1)
    print(f"tiny roundtrip: {(time.perf_counter() - t0) * 1e3:8.2f} ms")

# --- transfer bandwidth -----------------------------------------------------
big = np.zeros((96, 128), np.uint32)
for n in (1, 10):
    t0 = time.perf_counter()
    ds = [jnp.asarray(big) for _ in range(n)]
    jax.block_until_ready(ds)
    print(f"h2d {n}x 49KB: {(time.perf_counter() - t0) * 1e3:8.2f} ms")

# --- per-kernel device cost: queue N, sync once -----------------------------
S = PK.PREP_S
rng = np.random.default_rng(0)
pk = jnp.asarray(rng.integers(0, 2**16, (96, S), np.uint32).astype(np.uint32))
kmask = jnp.ones((1, S), jnp.int32)
lo = jnp.ones((1, S), jnp.uint32)
hi = jnp.zeros((1, S), jnp.uint32)
g2 = jnp.asarray(rng.integers(0, 2**16, (128, 2 * S)).astype(np.uint32))
lm = jnp.ones((1, 2 * S), jnp.int32)

g1_aff, fl = PK.prepare_kernel_call(pk, kmask, lo, hi, K=1)
f = PK.miller_kernel_call(g1_aff, g2)
prod = PK.product_kernel_call(f, lm)
jax.block_until_ready(prod)

N = 10
t0 = time.perf_counter()
outs = [PK.prepare_kernel_call(pk, kmask, lo, hi, K=1)[0] for _ in range(N)]
jax.block_until_ready(outs)
print(f"prepare x{N}: {(time.perf_counter() - t0) * 1e3 / N:8.2f} ms/call")

t0 = time.perf_counter()
outs = [PK.miller_kernel_call(g1_aff, g2) for _ in range(N)]
jax.block_until_ready(outs)
print(f"miller(256) x{N}: {(time.perf_counter() - t0) * 1e3 / N:8.2f} ms/call")

t0 = time.perf_counter()
outs = [PK.product_kernel_call(f, lm) for _ in range(N)]
jax.block_until_ready(outs)
print(f"product x{N}: {(time.perf_counter() - t0) * 1e3 / N:8.2f} ms/call")

# --- chained without sync: full pipeline queued then one sync ---------------
t0 = time.perf_counter()
for _ in range(N):
    a, _fl = PK.prepare_kernel_call(pk, kmask, lo, hi, K=1)
    ff = PK.miller_kernel_call(a, g2)
    pr = PK.product_kernel_call(ff, lm)
jax.block_until_ready(pr)
print(f"chain x{N}: {(time.perf_counter() - t0) * 1e3 / N:8.2f} ms/chunk")
