// Native BLS12-381 multi-pairing — the host fast path of the runtime.
//
// Role: the latency tier of BLS verification (single gossip-block proposer
// checks, small batches) where the TPU's fixed dispatch latency dominates,
// and the fast host oracle for tests.  The batch path stays on the TPU
// (lighthouse_tpu/crypto/pairing_kernel.py); this is the native analogue of
// the reference's blst host calls (/root/reference/crypto/bls/src/impls/
// blst.rs:36-119) — portable C++ (uint64 Montgomery + __int128), no asm.
//
// The math mirrors the repo's RFC-anchored python oracle
// (lighthouse_tpu/crypto/{fields,pairing}.py) and the device kernel's
// formulation (limb_pairing.py):
//   - tower Fq2 = Fq[u]/(u²+1), Fq6 = Fq2[v]/(v³-ξ), ξ = 1+u,
//     Fq12 = Fq6[w]/(w²-v)
//   - Miller loop over |x| = 0xd201000000010000 (MSB-first, leading bit
//     implicit), lines as (A + B·v + C·v·w) with the w³·(2YZ²) scaling
//     killed by the final exponentiation; f conjugated at the end (x<0)
//   - final exponentiation CUBED via the Hayashida–Hayasaka–Teruya ladder
//     3·(p⁴−p²+1)/r = (u−1)²·(u+p)·(u²+p²−1) + 3  — identical for the
//     only consumer, the == 1 check (GT has prime order r ≠ 3)
//
// Contract: callers pass AFFINE, ON-CURVE, non-infinity points in standard
// (non-Montgomery) little-endian 6×u64 limbs; subgroup/validity checks
// happen at deserialization on the python side.  Constants come from the
// generated header (scripts/gen_native_consts.py).

#include <cstdint>
#include <cstring>

#include "bls381_consts.h"

typedef unsigned __int128 u128;

// --------------------------------------------------------------------------
// Fp: 6×u64 little-endian, Montgomery form (R = 2^384)
// --------------------------------------------------------------------------

struct Fp { uint64_t l[6]; };

static inline void fp_zero(Fp &a) { std::memset(a.l, 0, sizeof a.l); }

static inline bool fp_is_zero(const Fp &a) {
    uint64_t v = 0;
    for (int i = 0; i < 6; i++) v |= a.l[i];
    return v == 0;
}

static inline bool fp_eq(const Fp &a, const Fp &b) {
    uint64_t v = 0;
    for (int i = 0; i < 6; i++) v |= a.l[i] ^ b.l[i];
    return v == 0;
}

// a += b with carry out
static inline uint64_t add6(uint64_t *a, const uint64_t *b) {
    u128 c = 0;
    for (int i = 0; i < 6; i++) {
        c += (u128)a[i] + b[i];
        a[i] = (uint64_t)c;
        c >>= 64;
    }
    return (uint64_t)c;
}

// a -= b with borrow out
static inline uint64_t sub6(uint64_t *a, const uint64_t *b) {
    u128 br = 0;
    for (int i = 0; i < 6; i++) {
        u128 d = (u128)a[i] - b[i] - br;
        a[i] = (uint64_t)d;
        br = (d >> 64) ? 1 : 0;
    }
    return (uint64_t)br;
}

static inline bool geq6(const uint64_t *a, const uint64_t *b) {
    for (int i = 5; i >= 0; i--) {
        if (a[i] != b[i]) return a[i] > b[i];
    }
    return true;
}

static inline void fp_add(Fp &r, const Fp &a, const Fp &b) {
    r = a;
    uint64_t c = add6(r.l, b.l);
    if (c || geq6(r.l, FP_P)) sub6(r.l, FP_P);
}

static inline void fp_sub(Fp &r, const Fp &a, const Fp &b) {
    r = a;
    if (sub6(r.l, b.l)) add6(r.l, FP_P);
}

static inline void fp_neg(Fp &r, const Fp &a) {
    // Alias-safe (callers write fp_neg(y, y)): build P − a in a
    // temporary — overwriting r first would corrupt an aliased input.
    if (fp_is_zero(a)) { r = a; return; }
    Fp t;
    for (int i = 0; i < 6; i++) t.l[i] = FP_P[i];
    sub6(t.l, a.l);
    r = t;
}

static inline void fp_dbl(Fp &r, const Fp &a) { fp_add(r, a, a); }

// CIOS Montgomery multiplication.
static void fp_mul(Fp &r, const Fp &a, const Fp &b) {
    uint64_t t[8] = {0};
    for (int i = 0; i < 6; i++) {
        u128 c = 0;
        uint64_t ai = a.l[i];
        for (int j = 0; j < 6; j++) {
            c += (u128)t[j] + (u128)ai * b.l[j];
            t[j] = (uint64_t)c;
            c >>= 64;
        }
        c += t[6];
        t[6] = (uint64_t)c;
        t[7] = (uint64_t)(c >> 64);

        uint64_t m = t[0] * FP_INV;
        c = (u128)t[0] + (u128)m * FP_P[0];
        c >>= 64;
        for (int j = 1; j < 6; j++) {
            c += (u128)t[j] + (u128)m * FP_P[j];
            t[j - 1] = (uint64_t)c;
            c >>= 64;
        }
        c += t[6];
        t[5] = (uint64_t)c;
        t[6] = t[7] + (uint64_t)(c >> 64);
        t[7] = 0;
    }
    for (int i = 0; i < 6; i++) r.l[i] = t[i];
    if (t[6] || geq6(r.l, FP_P)) sub6(r.l, FP_P);
}

static inline void fp_sqr(Fp &r, const Fp &a) { fp_mul(r, a, a); }

static void fp_from_limbs(Fp &r, const uint64_t *in) {  // standard -> Mont
    Fp t, r2;
    std::memcpy(t.l, in, 48);
    std::memcpy(r2.l, FP_R2, 48);
    fp_mul(r, t, r2);
}

static const Fp *fp_one() { return (const Fp *)FP_ONE_MONT; }

// --------------------------------------------------------------------------
// Fq2 = Fq[u]/(u²+1)
// --------------------------------------------------------------------------

struct Fp2 { Fp c0, c1; };

static inline void fp2_add(Fp2 &r, const Fp2 &a, const Fp2 &b) {
    fp_add(r.c0, a.c0, b.c0); fp_add(r.c1, a.c1, b.c1);
}
static inline void fp2_sub(Fp2 &r, const Fp2 &a, const Fp2 &b) {
    fp_sub(r.c0, a.c0, b.c0); fp_sub(r.c1, a.c1, b.c1);
}
static inline void fp2_neg(Fp2 &r, const Fp2 &a) {
    fp_neg(r.c0, a.c0); fp_neg(r.c1, a.c1);
}
static inline void fp2_conj(Fp2 &r, const Fp2 &a) {
    r.c0 = a.c0; fp_neg(r.c1, a.c1);
}
static inline void fp2_dbl(Fp2 &r, const Fp2 &a) { fp2_add(r, a, a); }

static void fp2_mul(Fp2 &r, const Fp2 &a, const Fp2 &b) {
    // Karatsuba: (a0b0 - a1b1) + ((a0+a1)(b0+b1) - a0b0 - a1b1) u
    Fp t0, t1, s0, s1, m;
    fp_mul(t0, a.c0, b.c0);
    fp_mul(t1, a.c1, b.c1);
    fp_add(s0, a.c0, a.c1);
    fp_add(s1, b.c0, b.c1);
    fp_mul(m, s0, s1);
    fp_sub(r.c0, t0, t1);
    fp_sub(m, m, t0);
    fp_sub(r.c1, m, t1);
}

static void fp2_sqr(Fp2 &r, const Fp2 &a) {
    // (a0+a1)(a0-a1) + (2 a0 a1) u
    Fp s, d, m;
    fp_add(s, a.c0, a.c1);
    fp_sub(d, a.c0, a.c1);
    fp_mul(m, a.c0, a.c1);
    fp_mul(r.c0, s, d);
    fp_dbl(r.c1, m);
}

static void fp2_mul_fp(Fp2 &r, const Fp2 &a, const Fp &s) {
    fp_mul(r.c0, a.c0, s); fp_mul(r.c1, a.c1, s);
}

// ξ·a with ξ = 1 + u:  (a0 - a1) + (a0 + a1) u
static inline void fp2_mul_xi(Fp2 &r, const Fp2 &a) {
    Fp t0, t1;
    fp_sub(t0, a.c0, a.c1);
    fp_add(t1, a.c0, a.c1);
    r.c0 = t0; r.c1 = t1;
}

static void fp_inv(Fp &r, const Fp &a);  // fwd

static void fp2_inv(Fp2 &r, const Fp2 &a) {
    // (a0 - a1 u) / (a0² + a1²)
    Fp d, t0, t1;
    fp_sqr(t0, a.c0);
    fp_sqr(t1, a.c1);
    fp_add(d, t0, t1);
    fp_inv(d, d);
    fp_mul(r.c0, a.c0, d);
    fp_mul(t0, a.c1, d);
    fp_neg(r.c1, t0);
}

static inline bool fp2_is_zero(const Fp2 &a) {
    return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
static inline bool fp2_eq(const Fp2 &a, const Fp2 &b) {
    return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}

// Fermat inversion a^(p-2); MSB-first scan of p-2.  Used O(1) per call.
static void fp_inv(Fp &r, const Fp &a) {
    uint64_t e[6];
    std::memcpy(e, FP_P, 48);
    e[0] -= 2;  // p is odd, no borrow
    Fp acc = *fp_one();
    bool started = false;
    for (int i = 5; i >= 0; i--) {
        for (int b = 63; b >= 0; b--) {
            if (started) fp_sqr(acc, acc);
            if ((e[i] >> b) & 1) {
                if (started) fp_mul(acc, acc, a);
                else { acc = a; started = true; }
            }
        }
    }
    r = acc;
}

// --------------------------------------------------------------------------
// Fq6 = Fq2[v]/(v³ - ξ)
// --------------------------------------------------------------------------

struct Fp6 { Fp2 c0, c1, c2; };

static inline void fp6_add(Fp6 &r, const Fp6 &a, const Fp6 &b) {
    fp2_add(r.c0, a.c0, b.c0); fp2_add(r.c1, a.c1, b.c1);
    fp2_add(r.c2, a.c2, b.c2);
}
static inline void fp6_sub(Fp6 &r, const Fp6 &a, const Fp6 &b) {
    fp2_sub(r.c0, a.c0, b.c0); fp2_sub(r.c1, a.c1, b.c1);
    fp2_sub(r.c2, a.c2, b.c2);
}
static inline void fp6_neg(Fp6 &r, const Fp6 &a) {
    fp2_neg(r.c0, a.c0); fp2_neg(r.c1, a.c1); fp2_neg(r.c2, a.c2);
}

static void fp6_mul(Fp6 &r, const Fp6 &a, const Fp6 &b) {
    // Toom/Karatsuba (6 Fq2 muls):
    // c0 = a0b0 + ξ((a1+a2)(b1+b2) - a1b1 - a2b2)
    // c1 = (a0+a1)(b0+b1) - a0b0 - a1b1 + ξ a2b2
    // c2 = (a0+a2)(b0+b2) - a0b0 - a2b2 + a1b1
    Fp2 v0, v1, v2, t0, t1, t2, x;
    fp2_mul(v0, a.c0, b.c0);
    fp2_mul(v1, a.c1, b.c1);
    fp2_mul(v2, a.c2, b.c2);

    fp2_add(t0, a.c1, a.c2);
    fp2_add(t1, b.c1, b.c2);
    fp2_mul(t2, t0, t1);
    fp2_sub(t2, t2, v1);
    fp2_sub(t2, t2, v2);
    fp2_mul_xi(x, t2);
    Fp2 c0; fp2_add(c0, v0, x);

    fp2_add(t0, a.c0, a.c1);
    fp2_add(t1, b.c0, b.c1);
    fp2_mul(t2, t0, t1);
    fp2_sub(t2, t2, v0);
    fp2_sub(t2, t2, v1);
    fp2_mul_xi(x, v2);
    Fp2 c1; fp2_add(c1, t2, x);

    fp2_add(t0, a.c0, a.c2);
    fp2_add(t1, b.c0, b.c2);
    fp2_mul(t2, t0, t1);
    fp2_sub(t2, t2, v0);
    fp2_sub(t2, t2, v2);
    Fp2 c2; fp2_add(c2, t2, v1);

    r.c0 = c0; r.c1 = c1; r.c2 = c2;
}

static void fp6_sqr(Fp6 &r, const Fp6 &a) { fp6_mul(r, a, a); }

// v·a = (ξ a2, a0, a1)
static void fp6_mul_by_v(Fp6 &r, const Fp6 &a) {
    Fp2 t;
    fp2_mul_xi(t, a.c2);
    Fp2 a0 = a.c0, a1 = a.c1;
    r.c0 = t; r.c1 = a0; r.c2 = a1;
}

static void fp6_inv(Fp6 &r, const Fp6 &a) {
    // Standard: A = a0² - ξ a1 a2, B = ξ a2² - a0 a1, C = a1² - a0 a2,
    // F = a0 A + ξ(a2 B + a1 C);  r = (A, B, C)/F.
    Fp2 A, B, C, t, x, F2;
    fp2_sqr(t, a.c0);
    fp2_mul(x, a.c1, a.c2);
    fp2_mul_xi(x, x);
    fp2_sub(A, t, x);

    fp2_sqr(t, a.c2);
    fp2_mul_xi(t, t);
    fp2_mul(x, a.c0, a.c1);
    fp2_sub(B, t, x);

    fp2_sqr(t, a.c1);
    fp2_mul(x, a.c0, a.c2);
    fp2_sub(C, t, x);

    fp2_mul(t, a.c2, B);
    fp2_mul(x, a.c1, C);
    fp2_add(t, t, x);
    fp2_mul_xi(t, t);
    fp2_mul(x, a.c0, A);
    fp2_add(F2, x, t);

    fp2_inv(F2, F2);
    fp2_mul(r.c0, A, F2);
    fp2_mul(r.c1, B, F2);
    fp2_mul(r.c2, C, F2);
}

// --------------------------------------------------------------------------
// Fq12 = Fq6[w]/(w² - v)
// --------------------------------------------------------------------------

struct Fp12 { Fp6 c0, c1; };

static void fp12_one(Fp12 &r) {
    std::memset(&r, 0, sizeof r);
    r.c0.c0.c0 = *fp_one();
}

static void fp12_mul(Fp12 &r, const Fp12 &a, const Fp12 &b) {
    // Karatsuba: (a0b0 + v a1b1) + ((a0+a1)(b0+b1) - a0b0 - a1b1) w
    Fp6 t0, t1, s0, s1, m, x;
    fp6_mul(t0, a.c0, b.c0);
    fp6_mul(t1, a.c1, b.c1);
    fp6_add(s0, a.c0, a.c1);
    fp6_add(s1, b.c0, b.c1);
    fp6_mul(m, s0, s1);
    fp6_sub(m, m, t0);
    fp6_sub(m, m, t1);
    fp6_mul_by_v(x, t1);
    fp6_add(r.c0, t0, x);
    r.c1 = m;
}

static void fp12_sqr(Fp12 &r, const Fp12 &a) {
    // Complex squaring over Fq6[w], w² = v:
    //   c0' = (c0 + c1)(c0 + v·c1) − c0c1 − v·c0c1,  c1' = 2 c0c1
    Fp6 t0, t1, m, x;
    fp6_add(t0, a.c0, a.c1);
    fp6_mul_by_v(x, a.c1);
    fp6_add(t1, a.c0, x);
    fp6_mul(m, a.c0, a.c1);
    fp6_mul(t0, t0, t1);
    fp6_sub(t0, t0, m);
    fp6_mul_by_v(x, m);
    fp6_sub(r.c0, t0, x);
    fp6_add(r.c1, m, m);
}

// Granger–Scott cyclotomic squaring, for elements of the cyclotomic
// subgroup (post-easy-part only).  With a = (g0 + g1 v + g2 v²) +
// (h0 + h1 v + h2 v²) w and w² = v, the Fq4 subalgebra pairs are
// (g0, h1), (h0, g2), (g1, h2); per pair an Fq4 squaring
//   A = x² + ξ y²,  B = 2xy
// then the cyclotomic recombination (validated against the python
// fields oracle on cyclotomic elements in tests):
//   g0' = 3(g0² + ξh1²) − 2g0     h1' = 3·(2 g0 h1) + 2h1
//   g1' = 3(h0² + ξg2²) − 2g1     h2' = 3·(2 g2 h0) + 2h2
//   g2' = 3(g1² + ξh2²) − 2g2     h0' = 3·ξ·(2 g1 h2) + 2h0
static void fp12_cyclo_sqr(Fp12 &r, const Fp12 &a) {
    const Fp2 &g0 = a.c0.c0, &g1 = a.c0.c1, &g2 = a.c0.c2;
    const Fp2 &h0 = a.c1.c0, &h1 = a.c1.c1, &h2 = a.c1.c2;
    Fp2 t0, t1, s;

    Fp2 A0, B0;                             // pair (g0, h1)
    fp2_sqr(t0, g0);
    fp2_sqr(t1, h1);
    fp2_mul_xi(s, t1);
    fp2_add(A0, t0, s);                     // g0² + ξh1²
    fp2_add(s, g0, h1);
    fp2_sqr(s, s);
    fp2_sub(s, s, t0);
    fp2_sub(B0, s, t1);                     // 2 g0 h1

    Fp2 A1, B1;                             // pair (h0, g2)
    fp2_sqr(t0, h0);
    fp2_sqr(t1, g2);
    fp2_mul_xi(s, t1);
    fp2_add(A1, t0, s);                     // h0² + ξg2²
    fp2_add(s, h0, g2);
    fp2_sqr(s, s);
    fp2_sub(s, s, t0);
    fp2_sub(B1, s, t1);                     // 2 g2 h0

    Fp2 A2, B2;                             // pair (g1, h2)
    fp2_sqr(t0, g1);
    fp2_sqr(t1, h2);
    fp2_mul_xi(s, t1);
    fp2_add(A2, t0, s);                     // g1² + ξh2²
    fp2_add(s, g1, h2);
    fp2_sqr(s, s);
    fp2_sub(s, s, t0);
    fp2_sub(B2, s, t1);                     // 2 g1 h2

    Fp12 o;
    fp2_sub(t0, A0, g0); fp2_dbl(t0, t0); fp2_add(o.c0.c0, t0, A0);
    fp2_sub(t0, A1, g1); fp2_dbl(t0, t0); fp2_add(o.c0.c1, t0, A1);
    fp2_sub(t0, A2, g2); fp2_dbl(t0, t0); fp2_add(o.c0.c2, t0, A2);
    fp2_mul_xi(t1, B2);
    fp2_add(t0, t1, h0); fp2_dbl(t0, t0); fp2_add(o.c1.c0, t0, t1);
    fp2_add(t0, B0, h1); fp2_dbl(t0, t0); fp2_add(o.c1.c1, t0, B0);
    fp2_add(t0, B1, h2); fp2_dbl(t0, t0); fp2_add(o.c1.c2, t0, B1);
    r = o;
}

static inline void fp12_conj(Fp12 &r, const Fp12 &a) {
    r.c0 = a.c0; fp6_neg(r.c1, a.c1);
}

static void fp12_inv(Fp12 &r, const Fp12 &a) {
    // (c0 - c1 w) / (c0² - v c1²)
    Fp6 t0, t1, d;
    fp6_sqr(t0, a.c0);
    fp6_sqr(t1, a.c1);
    fp6_mul_by_v(t1, t1);
    fp6_sub(d, t0, t1);
    fp6_inv(d, d);
    fp6_mul(r.c0, a.c0, d);
    fp6_mul(t0, a.c1, d);
    fp6_neg(r.c1, t0);
}

static bool fp12_is_one(const Fp12 &a) {
    if (!fp_eq(a.c0.c0.c0, *fp_one())) return false;
    const Fp *z = &a.c0.c0.c1;
    // remaining 11 Fp coefficients must be zero
    for (int i = 1; i < 12; i++) {
        if (!fp_is_zero(((const Fp *)&a)[i])) return false;
    }
    (void)z;
    return true;
}

// Frobenius^n (n = 1..3): fq6 coeff i -> conj^n(a_i)·XI_3[n]^i;
// fq12 w-part additionally ·XI_6[n].
static void fp2_frob(Fp2 &r, const Fp2 &a, int n) {
    if (n & 1) fp2_conj(r, a); else r = a;
}

static void fp6_frob(Fp6 &r, const Fp6 &a, int n) {
    Fp2 t;
    fp2_frob(r.c0, a.c0, n);
    fp2_frob(t, a.c1, n);
    fp2_mul(r.c1, t, *(const Fp2 *)FROB_XI_3[n]);
    fp2_frob(t, a.c2, n);
    fp2_mul(r.c2, t, *(const Fp2 *)FROB_XI_3_SQ[n]);
}

static void fp12_frob(Fp12 &r, const Fp12 &a, int n) {
    fp6_frob(r.c0, a.c0, n);
    Fp6 t;
    fp6_frob(t, a.c1, n);
    const Fp2 *g = (const Fp2 *)FROB_XI_6[n];
    fp2_mul(r.c1.c0, t.c0, *g);
    fp2_mul(r.c1.c1, t.c1, *g);
    fp2_mul(r.c1.c2, t.c2, *g);
}

// --------------------------------------------------------------------------
// Miller loop: G2 homogeneous projective, lines as (A + B·v + C·v·w)
// --------------------------------------------------------------------------

struct G1Aff { Fp x, y; };
struct G2Aff { Fp2 x, y; };
struct G2Proj { Fp2 X, Y, Z; };

static const uint64_t X_ABS = 0xd201000000010000ULL;  // |BLS x|; x < 0

// fq6 × sparse (A + B·v): (a0A + ξa2B) + (a0B + a1A)v + (a1B + a2A)v²,
// Karatsuba on the first two coefficients — 5 fq2 muls.
static void fp6_mul_by_ab(Fp6 &r, const Fp6 &a, const Fp2 &A, const Fp2 &B) {
    Fp2 m1, m2, m3, m4, m5, s, t;
    fp2_mul(m1, a.c0, A);
    fp2_mul(m2, a.c1, B);
    fp2_add(s, a.c0, a.c1);
    fp2_add(t, A, B);
    fp2_mul(m3, s, t);
    fp2_sub(m3, m3, m1);
    fp2_sub(m3, m3, m2);                    // a0B + a1A
    fp2_mul(m4, a.c2, A);
    fp2_mul(m5, a.c2, B);
    fp2_mul_xi(t, m5);
    fp2_add(r.c0, m1, t);
    r.c1 = m3;
    fp2_add(r.c2, m2, m4);
}

// fq6 × (C·v): ξa2C + a0C·v + a1C·v² — 3 fq2 muls.
static void fp6_mul_by_cv(Fp6 &r, const Fp6 &a, const Fp2 &C) {
    Fp2 t0, t1, t2;
    fp2_mul(t0, a.c0, C);
    fp2_mul(t1, a.c1, C);
    fp2_mul(t2, a.c2, C);
    fp2_mul_xi(r.c0, t2);
    r.c1 = t0;
    r.c2 = t1;
}

// Sparse mul by a line (A + B·v + C·v·w) — 13 fq2 muls vs 18 generic.
static void fp12_mul_by_line(Fp12 &f, const Fp2 &A, const Fp2 &B,
                             const Fp2 &C) {
    // l = l0 + l1·w with l0 = (A, B, 0), l1 = (0, C, 0):
    // f' = (f0·l0 + v·(f1·l1)) + ((f0+f1)·(l0+l1) − f0·l0 − f1·l1)·w
    // and l0 + l1 = (A, B+C, 0).
    Fp6 t0, t1, s0, m, x;
    Fp2 bc;
    fp6_mul_by_ab(t0, f.c0, A, B);
    fp6_mul_by_cv(t1, f.c1, C);
    fp6_add(s0, f.c0, f.c1);
    fp2_add(bc, B, C);
    fp6_mul_by_ab(m, s0, A, bc);
    fp6_sub(m, m, t0);
    fp6_sub(m, m, t1);
    fp6_mul_by_v(x, t1);
    fp6_add(f.c0, t0, x);
    f.c1 = m;
}

// Doubling step: line l_{T,T}(P)·w³·(2YZ²) and T ← 2T.
//   A = 3X³ − 2Y²Z, B = −3X²Z·xP, C = 2YZ²·yP
static void dbl_step(G2Proj &T, const G1Aff &P, Fp2 &A, Fp2 &B, Fp2 &C) {
    Fp2 XX, YY, ZZ, X3, Y2Z, X2Z, YZ2, t;
    fp2_sqr(XX, T.X);
    fp2_sqr(YY, T.Y);
    fp2_sqr(ZZ, T.Z);
    fp2_mul(X3, XX, T.X);          // X³
    fp2_mul(Y2Z, YY, T.Z);         // Y²Z
    fp2_mul(X2Z, XX, T.Z);         // X²Z
    fp2_mul(YZ2, T.Y, ZZ);         // YZ²

    // A = 3X³ − 2Y²Z
    fp2_dbl(t, X3); fp2_add(t, t, X3);
    Fp2 u; fp2_dbl(u, Y2Z);
    fp2_sub(A, t, u);
    // B = −3X²Z·xP
    fp2_dbl(t, X2Z); fp2_add(t, t, X2Z);
    fp2_mul_fp(t, t, P.x);
    fp2_neg(B, t);
    // C = 2YZ²·yP
    fp2_dbl(t, YZ2);
    fp2_mul_fp(C, t, P.y);

    // T ← 2T (homogeneous projective doubling, a = 0):
    //   W = 3X², S = YZ, Bb = XYS, H = W² − 8Bb,
    //   X' = 2HS, Y' = W(4Bb − H) − 8Y²S², Z' = 8S³
    Fp2 W, S, Bb, H, t2;
    fp2_dbl(W, XX); fp2_add(W, W, XX);
    fp2_mul(S, T.Y, T.Z);
    fp2_mul(t, T.X, T.Y);
    fp2_mul(Bb, t, S);
    fp2_sqr(H, W);
    fp2_dbl(t, Bb); fp2_dbl(t, t); fp2_dbl(t, t);   // 8Bb
    fp2_sub(H, H, t);
    fp2_mul(t, H, S);
    fp2_dbl(T.X, t);                                 // X' = 2HS
    fp2_dbl(t, Bb); fp2_dbl(t, t);                   // 4Bb
    fp2_sub(t, t, H);
    fp2_mul(t, W, t);                                // W(4Bb − H)
    fp2_mul(t2, YY, S);
    fp2_mul(t2, t2, S);                              // Y²S²
    fp2_dbl(t2, t2); fp2_dbl(t2, t2); fp2_dbl(t2, t2);  // 8Y²S²
    fp2_sub(T.Y, t, t2);
    fp2_sqr(t, S);
    fp2_mul(t, t, S);                                // S³
    fp2_dbl(t, t); fp2_dbl(t, t); fp2_dbl(T.Z, t);   // Z' = 8S³
}

// Addition step: chord l_{T,Q}(P)·w³·D and T ← T + Q (Q affine).
//   N = y₂Z − Y, D = x₂Z − X; A = N·x₂ − y₂·D, B = −N·xP, C = D·yP
static void add_step(G2Proj &T, const G2Aff &Q, const G1Aff &P,
                     Fp2 &A, Fp2 &B, Fp2 &C) {
    Fp2 N, D, t, u;
    fp2_mul(t, Q.y, T.Z);
    fp2_sub(N, t, T.Y);
    fp2_mul(t, Q.x, T.Z);
    fp2_sub(D, t, T.X);

    fp2_mul(t, N, Q.x);
    fp2_mul(u, Q.y, D);
    fp2_sub(A, t, u);
    fp2_mul_fp(t, N, P.x);
    fp2_neg(B, t);
    fp2_mul_fp(C, D, P.y);

    // T ← T + Q (mixed homogeneous projective add; T ≠ ±Q inside the
    // Miller loop for prime-order inputs):
    //   U = N, V = D, VV = V², VVV = V³, R = VV·X,
    //   Aa = U²Z − VVV − 2R, X' = V·Aa, Y' = U(R − Aa) − VVV·Y, Z' = VVV·Z
    Fp2 VV, VVV, Rr, Aa, t2;
    fp2_sqr(VV, D);
    fp2_mul(VVV, VV, D);
    fp2_mul(Rr, VV, T.X);
    fp2_sqr(t, N);
    fp2_mul(t, t, T.Z);
    fp2_sub(t, t, VVV);
    fp2_dbl(t2, Rr);
    fp2_sub(Aa, t, t2);
    fp2_mul(T.X, D, Aa);
    fp2_sub(t, Rr, Aa);
    fp2_mul(t, N, t);
    fp2_mul(t2, VVV, T.Y);
    fp2_sub(T.Y, t, t2);
    fp2_mul(T.Z, VVV, T.Z);
}

// f ← f_{|x|,Q}(P) accumulated INTO f (callers chain pairs), conjugation
// applied by the caller once at the end.
static void miller_loop_acc(Fp12 &f, const G1Aff &P, const G2Aff &Q) {
    G2Proj T;
    T.X = Q.x; T.Y = Q.y;
    std::memset(&T.Z, 0, sizeof T.Z);
    T.Z.c0 = *fp_one();
    Fp12 g;
    fp12_one(g);
    Fp2 A, B, C;
    // MSB-first over |x| with the leading 1 implicit.
    for (int i = 62; i >= 0; i--) {
        fp12_sqr(g, g);
        dbl_step(T, P, A, B, C);
        fp12_mul_by_line(g, A, B, C);
        if ((X_ABS >> i) & 1) {
            add_step(T, Q, P, A, B, C);
            fp12_mul_by_line(g, A, B, C);
        }
    }
    fp12_mul(f, f, g);
}

// --------------------------------------------------------------------------
// Final exponentiation (cubed): HHT x-ladder — mirrors
// pairing.final_exponentiation_cubed / limb_pairing.
// --------------------------------------------------------------------------

static void pow_x_abs(Fp12 &r, const Fp12 &g) {
    // g^|x|, square-and-multiply MSB-first (|x| = 0xd201000000010000).
    // Inputs are cyclotomic (post-easy-part), so the squarings use the
    // Granger–Scott formulas (~3× cheaper than generic).
    Fp12 acc = g;
    for (int i = 62; i >= 0; i--) {
        fp12_cyclo_sqr(acc, acc);
        if ((X_ABS >> i) & 1) fp12_mul(acc, acc, g);
    }
    r = acc;
}

static void pow_u(Fp12 &r, const Fp12 &g) {  // g^u, u = -|x|; cyclotomic g
    Fp12 t;
    pow_x_abs(t, g);
    fp12_conj(r, t);
}

static void final_exp_cubed(Fp12 &r, const Fp12 &f) {
    Fp12 f1, m, m1, k2, k3, k4, t, u;
    // easy part: f^(q^6-1) then ^(q^2+1)
    fp12_conj(t, f);
    fp12_inv(u, f);
    fp12_mul(f1, t, u);
    fp12_frob(t, f1, 2);
    fp12_mul(m, t, f1);
    // hard part ladder
    pow_u(t, m); fp12_conj(u, m); fp12_mul(m1, t, u);
    pow_u(t, m1); fp12_conj(u, m1); fp12_mul(k2, t, u);
    pow_u(t, k2); fp12_frob(u, k2, 1); fp12_mul(k3, t, u);
    pow_u(t, k3); pow_u(t, t);
    fp12_frob(u, k3, 2); fp12_mul(t, t, u);
    fp12_conj(u, k3); fp12_mul(k4, t, u);
    fp12_sqr(t, m); fp12_mul(t, t, m);
    fp12_mul(r, k4, t);
}

// --------------------------------------------------------------------------
// G2 jacobian arithmetic over Fp2 (x = X/Z², y = Y/Z³; infinity Z = 0) —
// the curve half of hash-to-curve and scalar multiplication.
// --------------------------------------------------------------------------

struct G2Jac { Fp2 X, Y, Z; };

static inline bool fp2j_is_inf(const G2Jac &p) { return fp2_is_zero(p.Z); }

static void g2j_dbl(G2Jac &r, const G2Jac &p) {
    if (fp2j_is_inf(p)) { r = p; return; }
    Fp2 A, B, Cc, D, E, F2, X3, Y3, Z3, t;
    fp2_sqr(A, p.X);
    fp2_sqr(B, p.Y);
    fp2_sqr(Cc, B);
    fp2_add(t, p.X, B);
    fp2_sqr(t, t);
    fp2_sub(t, t, A);
    fp2_sub(t, t, Cc);
    fp2_dbl(D, t);                  // 2((X+Y²)² − X² − Y⁴)
    fp2_dbl(E, A); fp2_add(E, E, A);  // 3X²
    fp2_sqr(F2, E);
    fp2_sub(X3, F2, D);
    fp2_sub(X3, X3, D);
    fp2_sub(t, D, X3);
    fp2_mul(Y3, E, t);
    fp2_dbl(t, Cc); fp2_dbl(t, t); fp2_dbl(t, t);  // 8Y⁴
    fp2_sub(Y3, Y3, t);
    fp2_mul(t, p.Y, p.Z);
    fp2_dbl(Z3, t);
    r.X = X3; r.Y = Y3; r.Z = Z3;
}

static void g2j_add(G2Jac &r, const G2Jac &p, const G2Jac &q) {
    if (fp2j_is_inf(p)) { r = q; return; }
    if (fp2j_is_inf(q)) { r = p; return; }
    Fp2 Z1Z1, Z2Z2, U1, U2, S1, S2, H, Rr, t;
    fp2_sqr(Z1Z1, p.Z);
    fp2_sqr(Z2Z2, q.Z);
    fp2_mul(U1, p.X, Z2Z2);
    fp2_mul(U2, q.X, Z1Z1);
    fp2_mul(t, p.Y, q.Z);
    fp2_mul(S1, t, Z2Z2);
    fp2_mul(t, q.Y, p.Z);
    fp2_mul(S2, t, Z1Z1);
    fp2_sub(H, U2, U1);
    fp2_sub(Rr, S2, S1);
    if (fp2_is_zero(H)) {
        if (fp2_is_zero(Rr)) { g2j_dbl(r, p); return; }
        std::memset(&r, 0, sizeof r);      // P + (−P) = O
        r.X.c0 = *fp_one(); r.Y.c0 = *fp_one();
        fp_zero(r.Z.c0); fp_zero(r.Z.c1);
        return;
    }
    Fp2 HH, HHH, V, X3, Y3, Z3;
    fp2_sqr(HH, H);
    fp2_mul(HHH, HH, H);
    fp2_mul(V, U1, HH);
    fp2_sqr(X3, Rr);
    fp2_sub(X3, X3, HHH);
    fp2_sub(X3, X3, V);
    fp2_sub(X3, X3, V);
    fp2_sub(t, V, X3);
    fp2_mul(Y3, Rr, t);
    fp2_mul(t, S1, HHH);
    fp2_sub(Y3, Y3, t);
    fp2_mul(t, p.Z, q.Z);
    fp2_mul(Z3, t, H);
    r.X = X3; r.Y = Y3; r.Z = Z3;
}

static inline void g2j_neg(G2Jac &r, const G2Jac &p) {
    r.X = p.X; fp2_neg(r.Y, p.Y); r.Z = p.Z;
}

static void g2j_from_affine(G2Jac &r, const Fp2 &x, const Fp2 &y) {
    r.X = x; r.Y = y;
    r.Z.c0 = *fp_one(); fp_zero(r.Z.c1);
}

static bool g2j_to_affine(Fp2 &x, Fp2 &y, const G2Jac &p) {
    if (fp2j_is_inf(p)) return false;
    Fp2 zi, zi2, zi3;
    fp2_inv(zi, p.Z);
    fp2_sqr(zi2, zi);
    fp2_mul(zi3, zi2, zi);
    fp2_mul(x, p.X, zi2);
    fp2_mul(y, p.Y, zi3);
    return true;
}

// [|x|]P for the BLS parameter (64-bit MSB ladder).
static void g2j_mul_xabs(G2Jac &r, const G2Jac &p) {
    G2Jac acc = p;
    for (int i = 62; i >= 0; i--) {
        g2j_dbl(acc, acc);
        if ((X_ABS >> i) & 1) g2j_add(acc, acc, p);
    }
    r = acc;
}

// Generic scalar mul, scalar as 4 LE u64 limbs (256-bit ladder).
static void g2j_mul_scalar(G2Jac &r, const G2Jac &p, const uint64_t *s) {
    G2Jac acc;
    std::memset(&acc, 0, sizeof acc);
    acc.X.c0 = *fp_one(); acc.Y.c0 = *fp_one();
    bool started = false;
    for (int i = 3; i >= 0; i--) {
        for (int b = 63; b >= 0; b--) {
            if (started) g2j_dbl(acc, acc);
            if ((s[i] >> b) & 1) {
                if (started) g2j_add(acc, acc, p);
                else { acc = p; started = true; }
            }
        }
    }
    if (!started) { std::memset(&acc.Z, 0, sizeof acc.Z); }
    r = acc;
}

// --------------------------------------------------------------------------
// Hash-to-curve (curve half): SSWU → 3-isogeny → Budroni–Pintore cofactor
// — mirrors the RFC-anchored host oracle (lighthouse_tpu/crypto/
// hash_to_curve.py), constants from the generated header.
// --------------------------------------------------------------------------

static inline const Fp2 *c2(const uint64_t arr[2][6]) {
    return (const Fp2 *)arr;
}

// ω-candidate square root: (is_qr, root) with root² = α or Z·α
// (the branchless 8-candidate scheme; host oracle `sqrt_or_z_times`).
static bool fp2_sqrt_or_z(Fp2 &root, const Fp2 &alpha) {
    // c = α^((p²+7)/16) via the 761-bit header exponent.
    Fp2 c;
    c.c0 = *fp_one(); fp_zero(c.c1);
    bool started = false;
    for (int i = 11; i >= 0; i--) {
        for (int b = 63; b >= 0; b--) {
            if (started) fp2_sqr(c, c);
            if ((H2C_E16_EXP[i] >> b) & 1) {
                if (started) fp2_mul(c, c, alpha);
                else { c = alpha; started = true; }
            }
        }
    }
    Fp2 cand, sq;
    for (int k = 0; k < 4; k++) {
        fp2_mul(cand, c, *c2(H2C_E8_INV_POWS[k]));
        fp2_sqr(sq, cand);
        if (fp2_eq(sq, alpha)) { root = cand; return true; }
    }
    Fp2 za;
    fp2_mul(za, *c2(H2C_Z_SSWU), alpha);
    for (int k = 0; k < 4; k++) {
        fp2_mul(cand, c, *c2(H2C_T_KS[k]));
        fp2_sqr(sq, cand);
        if (fp2_eq(sq, za)) { root = cand; return false; }
    }
    // unreachable for α ≠ 0 (some 8th root of unity matches)
    root = c;
    return false;
}

static int fp2_sgn0(const Fp2 &a) {
    // RFC 9380 sgn0 for Fq2: sign of c0, or of c1 when c0 == 0 —
    // computed on the STANDARD (non-Montgomery) representative.
    Fp one_std, s0, s1;
    std::memset(&one_std, 0, sizeof one_std);
    one_std.l[0] = 1;
    fp_mul(s0, a.c0, one_std);
    fp_mul(s1, a.c1, one_std);
    int sign_0 = (int)(s0.l[0] & 1);
    bool zero_0 = fp_is_zero(s0);
    int sign_1 = (int)(s1.l[0] & 1);
    return sign_0 | (zero_0 ? sign_1 : 0);
}

// g(x) = x³ + A'x + B' on the SSWU twist curve.
static void gx_twist(Fp2 &r, const Fp2 &x) {
    Fp2 t;
    fp2_sqr(t, x);
    fp2_mul(t, t, x);
    Fp2 ax;
    fp2_mul(ax, *c2(H2C_A_TWIST), x);
    fp2_add(t, t, ax);
    fp2_add(r, t, *c2(H2C_B_TWIST));
}

// Simplified SWU onto E' (non-constant-time; hashes public messages).
static void sswu_map(Fp2 &x, Fp2 &y, const Fp2 &t) {
    Fp2 tv1, tv2, x1, gx1;
    fp2_sqr(tv1, t);
    fp2_mul(tv1, *c2(H2C_Z_SSWU), tv1);        // Z t²
    fp2_sqr(tv2, tv1);
    fp2_add(tv2, tv2, tv1);                     // Z²t⁴ + Zt²
    if (fp2_is_zero(tv2)) {
        Fp2 za;
        fp2_mul(za, *c2(H2C_Z_SSWU), *c2(H2C_A_TWIST));
        fp2_inv(za, za);
        fp2_mul(x1, *c2(H2C_B_TWIST), za);      // B / (Z·A)
    } else {
        Fp2 inv, nb, ia;
        fp2_inv(ia, *c2(H2C_A_TWIST));
        fp2_neg(nb, *c2(H2C_B_TWIST));
        fp2_mul(nb, nb, ia);                    // −B/A
        fp2_inv(inv, tv2);
        Fp2 onep;
        onep.c0 = *fp_one(); fp_zero(onep.c1);
        fp2_add(inv, inv, onep);                // 1 + 1/tv2
        fp2_mul(x1, nb, inv);
    }
    gx_twist(gx1, x1);
    Fp2 root;
    if (fp2_sqrt_or_z(root, gx1)) {
        x = x1; y = root;
    } else {
        // x2 = Zt²·x1 and g(x2) = (Zt²)³·g(x1) = Z²t⁶ · (Z·g(x1));
        // sqrt_or_z returned root² = Z·g(x1), so y2 = Z·t³·root — three
        // Fq2 muls instead of a second 761-bit exponentiation (this
        // branch runs for ~half of hash-derived inputs).
        fp2_mul(x, tv1, x1);
        Fp2 t3;
        fp2_sqr(t3, t);
        fp2_mul(t3, t3, t);                 // t³
        fp2_mul(y, t3, root);
        fp2_mul(y, *c2(H2C_Z_SSWU), y);     // Z·t³·root
    }
    if (fp2_sgn0(t) != fp2_sgn0(y)) fp2_neg(y, y);
}

static void poly_eval(Fp2 &r, const uint64_t coeffs[][2][6], int n,
                      const Fp2 &x) {
    std::memset(&r, 0, sizeof r);
    for (int i = n - 1; i >= 0; i--) {
        Fp2 t;
        fp2_mul(t, r, x);
        fp2_add(r, t, *c2(coeffs[i]));
    }
}

// 3-isogeny E' -> E; returns false for infinity (vanishing denominator).
static bool iso_map(Fp2 &xo, Fp2 &yo, const Fp2 &x, const Fp2 &y) {
    Fp2 xn, xd, yn, yd;
    poly_eval(xn, H2C_ISO_X_NUM, 4, x);
    poly_eval(xd, H2C_ISO_X_DEN, 3, x);
    poly_eval(yn, H2C_ISO_Y_NUM, 4, x);
    poly_eval(yd, H2C_ISO_Y_DEN, 4, x);
    if (fp2_is_zero(xd) || fp2_is_zero(yd)) return false;
    Fp2 inv;
    fp2_inv(inv, xd);
    fp2_mul(xo, xn, inv);
    fp2_inv(inv, yd);
    fp2_mul(yo, yn, inv);
    fp2_mul(yo, yo, y);
    return true;
}

// ψ(x, y) = (cx·conj(x), cy·conj(y)) on jacobian coords: conj applies
// coordinate-wise and the multipliers adjust (Z conj as well).
static void g2j_psi(G2Jac &r, const G2Jac &p) {
    Fp2 x, y;
    if (!g2j_to_affine(x, y, p)) { r = p; return; }
    Fp2 cx, cy;
    fp2_conj(x, x);
    fp2_conj(y, y);
    fp2_mul(cx, *c2(H2C_PSI_CX), x);
    fp2_mul(cy, *c2(H2C_PSI_CY), y);
    g2j_from_affine(r, cx, cy);
}

// Budroni–Pintore: h_eff·P = ([x²]P − [x]P − P) + ψ([x]P − P) + ψ²([2]P)
static void clear_cofactor(G2Jac &r, const G2Jac &p) {
    G2Jac t1, t2, acc, tmp, np, nt1;
    g2j_mul_xabs(t1, p);
    g2j_neg(t1, t1);               // [x]P (x < 0)
    g2j_mul_xabs(t2, t1);
    g2j_neg(t2, t2);               // [x²]P
    g2j_neg(nt1, t1);
    g2j_neg(np, p);
    g2j_add(acc, t2, nt1);
    g2j_add(acc, acc, np);
    g2j_add(tmp, t1, np);
    g2j_psi(tmp, tmp);
    g2j_add(acc, acc, tmp);
    g2j_add(tmp, p, p);
    g2j_psi(tmp, tmp);
    g2j_psi(tmp, tmp);
    g2j_add(r, acc, tmp);
}

// --------------------------------------------------------------------------
// G1 aggregation (jacobian): pubkey sums for fast_aggregate_verify and
// the shared-keygroup dedup in the tpu backend's batch marshalling.
// --------------------------------------------------------------------------

struct G1Jac { Fp X, Y, Z; };  // x = X/Z², y = Y/Z³; infinity: Z = 0

static void g1j_dbl(G1Jac &r, const G1Jac &p) {
    if (fp_is_zero(p.Z)) { r = p; return; }
    Fp A, B, Cc, D, t;
    fp_sqr(A, p.X);                 // X²
    fp_sqr(B, p.Y);                 // Y²
    fp_sqr(Cc, B);                  // Y⁴
    fp_add(t, p.X, B);
    fp_sqr(t, t);
    fp_sub(t, t, A);
    fp_sub(t, t, Cc);
    fp_dbl(D, t);                   // D = 2((X+Y²)² − X² − Y⁴)
    Fp E;
    fp_dbl(E, A); fp_add(E, E, A);  // 3X²
    Fp F2;
    fp_sqr(F2, E);
    Fp X3;
    fp_sub(X3, F2, D);
    fp_sub(X3, X3, D);              // E² − 2D
    Fp Y3;
    fp_sub(t, D, X3);
    fp_mul(Y3, E, t);
    Fp c8;
    fp_dbl(c8, Cc); fp_dbl(c8, c8); fp_dbl(c8, c8);  // 8Y⁴
    fp_sub(Y3, Y3, c8);
    Fp Z3;
    fp_mul(t, p.Y, p.Z);
    fp_dbl(Z3, t);
    r.X = X3; r.Y = Y3; r.Z = Z3;
}

// Mixed add: q affine (never infinity — callers filter).
static void g1j_add_aff(G1Jac &r, const G1Jac &p, const Fp &qx,
                        const Fp &qy) {
    if (fp_is_zero(p.Z)) {
        r.X = qx; r.Y = qy; r.Z = *fp_one();
        return;
    }
    Fp Z2, U2, S2, H, Rr, t;
    fp_sqr(Z2, p.Z);
    fp_mul(U2, qx, Z2);
    fp_mul(t, qy, Z2);
    fp_mul(S2, t, p.Z);
    fp_sub(H, U2, p.X);
    fp_sub(Rr, S2, p.Y);
    if (fp_is_zero(H)) {
        if (fp_is_zero(Rr)) { g1j_dbl(r, p); return; }
        r.X = *fp_one(); r.Y = *fp_one();  // P + (−P) = O (Z = 0)
        fp_zero(r.Z);
        return;
    }
    Fp HH, HHH, V;
    fp_sqr(HH, H);
    fp_mul(HHH, HH, H);
    fp_mul(V, p.X, HH);
    Fp X3;
    fp_sqr(X3, Rr);
    fp_sub(X3, X3, HHH);
    fp_sub(X3, X3, V);
    fp_sub(X3, X3, V);
    Fp Y3;
    fp_sub(t, V, X3);
    fp_mul(Y3, Rr, t);
    fp_mul(t, p.Y, HHH);
    fp_sub(Y3, Y3, t);
    Fp Z3;
    fp_mul(Z3, p.Z, H);
    r.X = X3; r.Y = Y3; r.Z = Z3;
}

// --------------------------------------------------------------------------
// C API
// --------------------------------------------------------------------------

extern "C" {

// n pairs; g1: n×12 u64 (x,y | 6 LE limbs each, standard form);
// g2: n×24 u64 (x.c0, x.c1, y.c0, y.c1).  Returns 1 iff
// prod_i e(P_i, Q_i) == 1.  Points must be affine, on-curve,
// non-infinity (validated python-side).
int bls381_multi_pairing_is_one(const uint64_t *g1, const uint64_t *g2,
                                uint64_t n) {
    Fp12 f;
    fp12_one(f);
    for (uint64_t i = 0; i < n; i++) {
        G1Aff P;
        fp_from_limbs(P.x, g1 + i * 12);
        fp_from_limbs(P.y, g1 + i * 12 + 6);
        G2Aff Q;
        fp_from_limbs(Q.x.c0, g2 + i * 24);
        fp_from_limbs(Q.x.c1, g2 + i * 24 + 6);
        fp_from_limbs(Q.y.c0, g2 + i * 24 + 12);
        fp_from_limbs(Q.y.c1, g2 + i * 24 + 18);
        miller_loop_acc(f, P, Q);
    }
    Fp12 fc, out;
    fp12_conj(fc, f);           // x < 0
    final_exp_cubed(out, fc);
    return fp12_is_one(out) ? 1 : 0;
}

// Raw product of Miller loops + cubed final exp, for oracle cross-checks:
// writes the 12 Fq coefficients (standard form, 6 LE limbs each, the
// (c0|c1)(a0,a1,a2)(fp0,fp1) nesting) to out[144].
void bls381_multi_pairing_gt(const uint64_t *g1, const uint64_t *g2,
                             uint64_t n, uint64_t *out) {
    Fp12 f;
    fp12_one(f);
    for (uint64_t i = 0; i < n; i++) {
        G1Aff P;
        fp_from_limbs(P.x, g1 + i * 12);
        fp_from_limbs(P.y, g1 + i * 12 + 6);
        G2Aff Q;
        fp_from_limbs(Q.x.c0, g2 + i * 24);
        fp_from_limbs(Q.x.c1, g2 + i * 24 + 6);
        fp_from_limbs(Q.y.c0, g2 + i * 24 + 12);
        fp_from_limbs(Q.y.c1, g2 + i * 24 + 18);
        miller_loop_acc(f, P, Q);
    }
    Fp12 fc, res;
    fp12_conj(fc, f);
    final_exp_cubed(res, fc);
    // Montgomery -> standard: multiply by 1 (mont_mul with literal 1).
    Fp one_std;
    std::memset(&one_std, 0, sizeof one_std);
    one_std.l[0] = 1;
    const Fp *coeffs = (const Fp *)&res;
    for (int i = 0; i < 12; i++) {
        Fp s;
        fp_mul(s, coeffs[i], one_std);
        std::memcpy(out + i * 6, s.l, 48);
    }
}

// Hash-to-curve curve half: u = (u0.c0, u0.c1, u1.c0, u1.c1) as 4×6 LE
// limbs (standard form); writes the affine G2 point (x.c0, x.c1, y.c0,
// y.c1) to out[24].  Returns 1 (the output is never infinity for
// hash-derived u with overwhelming probability; 0 on the pathological
// infinity case).
int bls381_hash_to_g2_u(const uint64_t *u, uint64_t *out) {
    Fp2 u0, u1;
    fp_from_limbs(u0.c0, u);
    fp_from_limbs(u0.c1, u + 6);
    fp_from_limbs(u1.c0, u + 12);
    fp_from_limbs(u1.c1, u + 18);

    G2Jac q0, q1, acc;
    std::memset(&q0, 0, sizeof q0);
    std::memset(&q1, 0, sizeof q1);
    Fp2 x, y, xi, yi;
    sswu_map(x, y, u0);
    if (iso_map(xi, yi, x, y)) g2j_from_affine(q0, xi, yi);
    sswu_map(x, y, u1);
    if (iso_map(xi, yi, x, y)) g2j_from_affine(q1, xi, yi);
    g2j_add(acc, q0, q1);
    clear_cofactor(acc, acc);

    Fp2 xa, ya;
    if (!g2j_to_affine(xa, ya, acc)) return 0;
    Fp one_std, t;
    std::memset(&one_std, 0, sizeof one_std);
    one_std.l[0] = 1;
    fp_mul(t, xa.c0, one_std); std::memcpy(out, t.l, 48);
    fp_mul(t, xa.c1, one_std); std::memcpy(out + 6, t.l, 48);
    fp_mul(t, ya.c0, one_std); std::memcpy(out + 12, t.l, 48);
    fp_mul(t, ya.c1, one_std); std::memcpy(out + 18, t.l, 48);
    return 1;
}

// [s]P for affine G2 P (24 u64) and 256-bit scalar s (4 LE u64); writes
// the affine product.  Returns 0 if the result is infinity.
int bls381_g2_mul(const uint64_t *p, const uint64_t *scalar,
                  uint64_t *out) {
    Fp2 x, y;
    fp_from_limbs(x.c0, p);
    fp_from_limbs(x.c1, p + 6);
    fp_from_limbs(y.c0, p + 12);
    fp_from_limbs(y.c1, p + 18);
    G2Jac j, r;
    g2j_from_affine(j, x, y);
    g2j_mul_scalar(r, j, scalar);
    Fp2 xa, ya;
    if (!g2j_to_affine(xa, ya, r)) return 0;
    Fp one_std, t;
    std::memset(&one_std, 0, sizeof one_std);
    one_std.l[0] = 1;
    fp_mul(t, xa.c0, one_std); std::memcpy(out, t.l, 48);
    fp_mul(t, xa.c1, one_std); std::memcpy(out + 6, t.l, 48);
    fp_mul(t, ya.c0, one_std); std::memcpy(out + 12, t.l, 48);
    fp_mul(t, ya.c1, one_std); std::memcpy(out + 18, t.l, 48);
    return 1;
}

// Sum n affine G1 points (12 u64 each, standard form, non-infinity —
// callers filter identities).  Writes the affine sum to out[12]; returns
// 1 on a finite sum, 0 if the sum is the identity (out untouched).
int bls381_g1_aggregate(const uint64_t *pts, uint64_t n, uint64_t *out) {
    G1Jac acc;
    fp_zero(acc.X); fp_zero(acc.Y); fp_zero(acc.Z);
    for (uint64_t i = 0; i < n; i++) {
        Fp qx, qy;
        fp_from_limbs(qx, pts + i * 12);
        fp_from_limbs(qy, pts + i * 12 + 6);
        g1j_add_aff(acc, acc, qx, qy);
    }
    if (fp_is_zero(acc.Z)) return 0;
    // to affine: x = X/Z², y = Y/Z³; then Montgomery -> standard.
    Fp zi, zi2, zi3, ax, ay, one_std;
    fp_inv(zi, acc.Z);
    fp_sqr(zi2, zi);
    fp_mul(zi3, zi2, zi);
    fp_mul(ax, acc.X, zi2);
    fp_mul(ay, acc.Y, zi3);
    std::memset(&one_std, 0, sizeof one_std);
    one_std.l[0] = 1;
    fp_mul(ax, ax, one_std);
    fp_mul(ay, ay, one_std);
    std::memcpy(out, ax.l, 48);
    std::memcpy(out + 6, ay.l, 48);
    return 1;
}

}  // extern "C"
