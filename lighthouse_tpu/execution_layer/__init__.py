"""Execution layer: engine-API seam + in-process mock EL.

Counterpart of ``beacon_node/execution_layer``
(``/root/reference/beacon_node/execution_layer/src/``): the ``Engine``
abstraction (newPayload / forkchoiceUpdated / getPayload), a
primary-with-fallback engine list, and the hermetic
``MockExecutionLayer``/``ExecutionBlockGenerator`` the whole test suite
runs against (``execution_layer/src/test_utils/`` — a hash-linked payload
chain with validity-injection hooks).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Dict, List, Optional


class PayloadStatus(str, Enum):
    """engine_newPayload statuses (`engine_api.rs` PayloadStatusV1)."""
    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"


class EngineError(RuntimeError):
    pass


class Engine:
    """One execution engine endpoint (the JSON-RPC transport seam)."""

    def new_payload(self, payload) -> PayloadStatus:
        raise NotImplementedError

    def forkchoice_updated(self, head_hash: bytes, safe_hash: bytes,
                           finalized_hash: bytes,
                           payload_attributes=None) -> Optional[bytes]:
        raise NotImplementedError

    def get_payload(self, payload_id: bytes):
        raise NotImplementedError


class ExecutionLayer:
    """Primary/fallback engine routing (`engines.rs` state machine)."""

    def __init__(self, engines: List[Engine]):
        if not engines:
            raise EngineError("at least one engine required")
        self.engines = list(engines)

    def _first_up(self, fn: Callable):
        last: Optional[Exception] = None
        for engine in self.engines:
            try:
                return fn(engine)
            except EngineError as e:
                last = e
        raise EngineError(f"all engines failed: {last}")

    def notify_new_payload(self, payload) -> PayloadStatus:
        return self._first_up(lambda e: e.new_payload(payload))

    def notify_forkchoice_updated(self, head: bytes, safe: bytes,
                                  finalized: bytes,
                                  payload_attributes=None):
        return self._first_up(lambda e: e.forkchoice_updated(
            head, safe, finalized, payload_attributes))

    def get_payload(self, payload_id: bytes):
        return self._first_up(lambda e: e.get_payload(payload_id))

    def payload_verifier(self):
        """The `per_block.process_execution_payload` hook: payload →
        bool (the `payload_notifier` of `block_verification.rs:1335`)."""
        def verify(payload) -> bool:
            return self.notify_new_payload(payload) == PayloadStatus.VALID
        return verify


@dataclass
class _MockBlock:
    block_hash: bytes
    parent_hash: bytes
    block_number: int
    timestamp: int


class ExecutionBlockGenerator:
    """Hash-linked execution chain (`test_utils/execution_block_generator.rs`)."""

    def __init__(self, terminal_block_hash: bytes = b"\x42" * 32):
        genesis = _MockBlock(terminal_block_hash, b"\x00" * 32, 0, 0)
        self.blocks: Dict[bytes, _MockBlock] = {genesis.block_hash: genesis}
        self.head = genesis.block_hash

    def insert(self, parent_hash: bytes, block_number: int,
               timestamp: int) -> bytes:
        h = hashlib.sha256(parent_hash + block_number.to_bytes(8, "little")
                           ).digest()
        self.blocks[h] = _MockBlock(h, parent_hash, block_number, timestamp)
        return h


class MockExecutionLayer(Engine):
    """In-process fake engine (`test_utils/mod.rs` MockExecutionLayer):
    validates payload linkage against the generator chain; test hooks can
    force any status (`test_utils/hook.rs`)."""

    def __init__(self):
        self.generator = ExecutionBlockGenerator()
        self.status_hook: Optional[Callable] = None
        self.payloads_seen: List[bytes] = []
        self._pending: Dict[bytes, dict] = {}

    def new_payload(self, payload) -> PayloadStatus:
        block_hash = bytes(payload.block_hash)
        self.payloads_seen.append(block_hash)
        if self.status_hook is not None:
            forced = self.status_hook(payload)
            if forced is not None:
                return forced
        parent = bytes(payload.parent_hash)
        if parent not in self.generator.blocks:
            return PayloadStatus.SYNCING
        self.generator.blocks[block_hash] = _MockBlock(
            block_hash, parent, int(payload.block_number),
            int(payload.timestamp))
        return PayloadStatus.VALID

    def forkchoice_updated(self, head_hash, safe_hash, finalized_hash,
                           payload_attributes=None):
        if head_hash not in self.generator.blocks:
            return None
        self.generator.head = head_hash
        if payload_attributes is not None:
            pid = hashlib.sha256(head_hash + b"pid").digest()[:8]
            self._pending[pid] = {"parent": head_hash,
                                  "attrs": payload_attributes}
            return pid
        return None

    def get_payload(self, payload_id: bytes):
        if payload_id not in self._pending:
            raise EngineError("unknown payload id")
        return self._pending.pop(payload_id)
