"""External block-builder client — the builder-API side of
``/root/reference/beacon_node/execution_layer/src/lib.rs`` (the
``BuilderBid`` flow) and Lighthouse's ``eth2::BuilderHttpClient``.

Flow (builder-specs): the VC registers validators with the builder;
at proposal time the BN asks ``GET /eth/v1/builder/header/{slot}/
{parent_hash}/{pubkey}`` for a ``SignedBuilderBid`` carrying an
ExecutionPayloadHeader + value; the proposer signs a BLINDED block over
that header; ``POST /eth/v1/builder/blinded_blocks`` reveals the full
payload.  Falling back to the local engine when the builder misbehaves is
the caller's job (`execution_layer/src/lib.rs` get_payload local/builder
race) — here we implement the transport + bid verification.
"""

from __future__ import annotations

import http.client
import json
from typing import Optional
from urllib.parse import urlparse

from . import EngineError
from .engine_api import json_to_payload_fields, payload_to_json


class BuilderError(EngineError):
    pass


class BuilderHttpClient:
    def __init__(self, url: str, timeout: float = 3.0):
        self.url = url.rstrip("/")
        self._parsed = urlparse(self.url)
        self.timeout = timeout

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> tuple[int, bytes]:
        conn = http.client.HTTPConnection(
            self._parsed.hostname or "127.0.0.1",
            self._parsed.port or 18550, timeout=self.timeout)
        try:
            conn.request(method, path,
                         None if body is None else json.dumps(body),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, resp.read()
        except (OSError, http.client.HTTPException) as e:
            raise BuilderError(f"builder transport failure: {e}")
        finally:
            conn.close()

    # -- builder-specs routes ------------------------------------------------

    def register_validators(self, registrations: list[dict]) -> None:
        """`POST /eth/v1/builder/validators` — signed validator
        registrations (fee recipient + gas limit per key)."""
        status, _ = self._request(
            "POST", "/eth/v1/builder/validators", registrations)
        if status != 200:
            raise BuilderError(f"register_validators: HTTP {status}")

    def get_header(self, slot: int, parent_hash: bytes,
                   pubkey: bytes) -> Optional[dict]:
        """`GET /eth/v1/builder/header/...` → bid dict with
        ``header`` (payload-header JSON), ``value`` (wei int), ``pubkey``.
        None when the builder has no bid (204)."""
        status, data = self._request(
            "GET", f"/eth/v1/builder/header/{slot}/0x{parent_hash.hex()}"
                   f"/0x{pubkey.hex()}")
        if status == 204:
            return None
        if status != 200:
            raise BuilderError(f"get_header: HTTP {status}")
        msg = json.loads(data)["data"]["message"]
        return {"header": msg["header"],
                "value": int(msg["value"]),
                "pubkey": msg["pubkey"]}

    def submit_blinded_block(self, signed_blinded_json: dict) -> dict:
        """`POST /eth/v1/builder/blinded_blocks` → the unblinded
        ExecutionPayload field dict."""
        status, data = self._request(
            "POST", "/eth/v1/builder/blinded_blocks", signed_blinded_json)
        if status != 200:
            raise BuilderError(f"submit_blinded_block: HTTP {status}")
        return json_to_payload_fields(json.loads(data)["data"])
