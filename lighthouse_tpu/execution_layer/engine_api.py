"""Engine-API JSON-RPC transport with JWT auth.

Counterpart of ``/root/reference/beacon_node/execution_layer/src/engine_api/
http.rs`` (method names, per-method timeouts, capability exchange) and
``engine_api/auth.rs`` (HS256 JWT with an ``iat`` claim per the
execution-apis authentication spec).  The transport is stdlib
``http.client`` — one persistent connection per engine, re-opened on
failure — so the beacon node can drive a real execution client (geth,
nethermind, ...) with no third-party dependencies.

Serialization follows the execution-apis JSON conventions: camelCase
field names, ``0x``-prefixed hex for both QUANTITY (minimal-length) and
DATA (fixed-length) values.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client
import json
import random
import time
from typing import Any, List, Optional
from urllib.parse import urlparse

from . import Engine, EngineError, PayloadStatus
from ..common.backoff import backoff_delay
from ..common.metrics import REGISTRY

# Method names + timeouts (`engine_api/http.rs:30-50`).
ETH_SYNCING = "eth_syncing"
ENGINE_NEW_PAYLOAD_V1 = "engine_newPayloadV1"
ENGINE_NEW_PAYLOAD_V2 = "engine_newPayloadV2"
ENGINE_GET_PAYLOAD_V1 = "engine_getPayloadV1"
ENGINE_GET_PAYLOAD_V2 = "engine_getPayloadV2"
ENGINE_FORKCHOICE_UPDATED_V1 = "engine_forkchoiceUpdatedV1"
ENGINE_FORKCHOICE_UPDATED_V2 = "engine_forkchoiceUpdatedV2"
ENGINE_EXCHANGE_CAPABILITIES = "engine_exchangeCapabilities"

TIMEOUTS = {
    ETH_SYNCING: 1.0,
    ENGINE_NEW_PAYLOAD_V1: 8.0,
    ENGINE_NEW_PAYLOAD_V2: 8.0,
    ENGINE_GET_PAYLOAD_V1: 2.0,
    ENGINE_GET_PAYLOAD_V2: 2.0,
    ENGINE_FORKCHOICE_UPDATED_V1: 8.0,
    ENGINE_FORKCHOICE_UPDATED_V2: 8.0,
    ENGINE_EXCHANGE_CAPABILITIES: 1.0,
}

LIGHTHOUSE_CAPABILITIES = [
    ENGINE_NEW_PAYLOAD_V1, ENGINE_NEW_PAYLOAD_V2,
    ENGINE_GET_PAYLOAD_V1, ENGINE_GET_PAYLOAD_V2,
    ENGINE_FORKCHOICE_UPDATED_V1, ENGINE_FORKCHOICE_UPDATED_V2,
]


# ---------------------------------------------------------------------------
# JWT (auth.rs; execution-apis authentication.md)
# ---------------------------------------------------------------------------


def _b64url(data: bytes) -> bytes:
    return base64.urlsafe_b64encode(data).rstrip(b"=")


class JwtAuth:
    """HS256 token minting over a 32-byte shared secret (`auth.rs:100-126`).

    A fresh token is minted per request with ``iat`` = now — engines
    reject tokens older than 60 s, so caching would only save a μs HMAC.
    """

    def __init__(self, secret: bytes):
        if len(secret) != 32:
            raise EngineError(f"jwt secret must be 32 bytes, got {len(secret)}")
        self.secret = secret

    @classmethod
    def from_hex_file(cls, path: str) -> "JwtAuth":
        with open(path) as f:
            text = f.read().strip()
        return cls(bytes.fromhex(text[2:] if text.startswith("0x") else text))

    def token(self, now: Optional[int] = None) -> str:
        header = _b64url(json.dumps(
            {"typ": "JWT", "alg": "HS256"}, separators=(",", ":")).encode())
        claims = _b64url(json.dumps(
            {"iat": int(now if now is not None else time.time())},
            separators=(",", ":")).encode())
        signing_input = header + b"." + claims
        sig = _b64url(hmac.new(self.secret, signing_input,
                               hashlib.sha256).digest())
        return (signing_input + b"." + sig).decode()


# ---------------------------------------------------------------------------
# JSON <-> payload types (json_structures.rs)
# ---------------------------------------------------------------------------


def _q(v: int) -> str:
    """QUANTITY: minimal big-endian hex."""
    return hex(int(v))


def _d(v) -> str:
    """DATA: fixed-length hex."""
    return "0x" + bytes(v).hex()


def payload_to_json(payload) -> dict:
    """ExecutionPayload container → engine-API JSON (ExecutionPayloadV1/V2)."""
    out = {
        "parentHash": _d(payload.parent_hash),
        "feeRecipient": _d(payload.fee_recipient),
        "stateRoot": _d(payload.state_root),
        "receiptsRoot": _d(payload.receipts_root),
        "logsBloom": _d(payload.logs_bloom),
        "prevRandao": _d(payload.prev_randao),
        "blockNumber": _q(payload.block_number),
        "gasLimit": _q(payload.gas_limit),
        "gasUsed": _q(payload.gas_used),
        "timestamp": _q(payload.timestamp),
        "extraData": _d(payload.extra_data),
        "baseFeePerGas": _q(payload.base_fee_per_gas),
        "blockHash": _d(payload.block_hash),
        "transactions": [_d(tx) for tx in payload.transactions],
    }
    if hasattr(payload, "withdrawals"):
        out["withdrawals"] = [{
            "index": _q(w.index),
            "validatorIndex": _q(w.validator_index),
            "address": _d(w.address),
            "amount": _q(w.amount),
        } for w in payload.withdrawals]
    return out


def json_to_payload_fields(obj: dict) -> dict:
    """Engine-API JSON → kwargs for the ExecutionPayload container."""
    fields = {
        "parent_hash": bytes.fromhex(obj["parentHash"][2:]),
        "fee_recipient": bytes.fromhex(obj["feeRecipient"][2:]),
        "state_root": bytes.fromhex(obj["stateRoot"][2:]),
        "receipts_root": bytes.fromhex(obj["receiptsRoot"][2:]),
        "logs_bloom": bytes.fromhex(obj["logsBloom"][2:]),
        "prev_randao": bytes.fromhex(obj["prevRandao"][2:]),
        "block_number": int(obj["blockNumber"], 16),
        "gas_limit": int(obj["gasLimit"], 16),
        "gas_used": int(obj["gasUsed"], 16),
        "timestamp": int(obj["timestamp"], 16),
        "extra_data": bytes.fromhex(obj["extraData"][2:]),
        "base_fee_per_gas": int(obj["baseFeePerGas"], 16),
        "block_hash": bytes.fromhex(obj["blockHash"][2:]),
        "transactions": [bytes.fromhex(tx[2:])
                         for tx in obj["transactions"]],
    }
    if "withdrawals" in obj:
        fields["withdrawals"] = [{
            "index": int(w["index"], 16),
            "validator_index": int(w["validatorIndex"], 16),
            "address": bytes.fromhex(w["address"][2:]),
            "amount": int(w["amount"], 16),
        } for w in obj["withdrawals"]]
    return fields


# ---------------------------------------------------------------------------
# The transport
# ---------------------------------------------------------------------------


class HttpJsonRpcEngine(Engine):
    """One execution engine over authenticated JSON-RPC (`http.rs`
    HttpJsonRpc + `engines.rs` Engine).  Thread-compatible: callers
    serialize through the ExecutionLayer's first-up routing."""

    # Transport-failure retry policy: a flaky engine connection (restart,
    # LB blip, slow disk stall) should cost backoff, not an immediate
    # missed payload — the same backoff+jitter discipline as the device
    # resilience envelope.  Only TRANSPORT failures and 5xx responses
    # retry; JSON-RPC application errors are the engine's answer and
    # surface immediately.
    RETRIES = 3
    BACKOFF_BASE_S = 0.05
    BACKOFF_MAX_S = 1.0

    def __init__(self, url: str, jwt: JwtAuth, *,
                 retries: Optional[int] = None, sleep=time.sleep,
                 rng: Optional[random.Random] = None):
        self.url = url
        self.jwt = jwt
        self._parsed = urlparse(url)
        self._conn: Optional[http.client.HTTPConnection] = None
        self._id = 0
        self.capabilities: Optional[List[str]] = None
        self.retries = self.RETRIES if retries is None else int(retries)
        self._sleep = sleep
        self._rng = rng or random.Random()
        self.retry_counts: dict = {}  # method → retries performed
        self._m_retries = REGISTRY.counter(
            "engine_api_retries_total", "engine-API transport retries")
        self._m_failures = REGISTRY.counter(
            "engine_api_transport_failures_total",
            "engine-API calls failed after all retries")

    # -- wire ---------------------------------------------------------------

    def _connect(self, timeout: float) -> http.client.HTTPConnection:
        host = self._parsed.hostname or "127.0.0.1"
        port = self._parsed.port or 8551
        return http.client.HTTPConnection(host, port, timeout=timeout)

    def _backoff(self, attempt: int) -> None:
        self._sleep(backoff_delay(attempt, base_s=self.BACKOFF_BASE_S,
                                  max_s=self.BACKOFF_MAX_S, rng=self._rng))

    def _note_retry(self, method: str, attempt: int, attempts: int,
                    err_msg: str) -> None:
        """Account one transient failure: raise on the final attempt,
        otherwise count the retry and back off."""
        if attempt == attempts - 1:
            self._m_failures.inc()
            raise EngineError(err_msg)
        self.retry_counts[method] = self.retry_counts.get(method, 0) + 1
        self._m_retries.inc()
        self._backoff(attempt)

    def rpc(self, method: str, params: list) -> Any:
        self._id += 1
        body = json.dumps({"jsonrpc": "2.0", "id": self._id,
                           "method": method, "params": params})
        headers = {
            "Content-Type": "application/json",
            "Authorization": "Bearer " + self.jwt.token(),
        }
        timeout = TIMEOUTS.get(method, 8.0)
        attempts = self.retries + 1
        attempt = 0
        while True:
            conn = self._conn
            reused = conn is not None
            if conn is None:
                conn = self._connect(timeout)
            try:
                conn.request("POST", self._parsed.path or "/", body, headers)
                resp = conn.getresponse()
                data = resp.read()
                self._conn = conn
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                self._conn = None
                if reused:
                    # Dead keep-alive after an idle gap is routine (the
                    # engine reaped the connection): reconnect
                    # immediately — no backoff, no retry metric, and no
                    # attempt consumed (the seed's "one silent
                    # reconnect"; works even with retries=0).  At most
                    # once per call: self._conn is now None, so the
                    # retried iteration cannot be `reused` again.
                    continue
                self._note_retry(method, attempt, attempts,
                                 f"{method}: transport failure after "
                                 f"{attempts} attempts: {e}")
                attempt += 1
                continue
            if resp.status >= 500:  # engine-side transient (proxy 502s...)
                self._conn = None
                conn.close()
                self._note_retry(method, attempt, attempts,
                                 f"{method}: HTTP {resp.status} after "
                                 f"{attempts} attempts")
                attempt += 1
                continue
            break
        if resp.status != 200:
            raise EngineError(f"{method}: HTTP {resp.status}")
        try:
            obj = json.loads(data)
        except ValueError as e:
            raise EngineError(f"{method}: bad JSON from engine: {e}")
        if obj.get("error") is not None:
            err = obj["error"]
            raise EngineError(
                f"{method}: engine error {err.get('code')}: "
                f"{err.get('message')}")
        return obj.get("result")

    # -- Engine interface ---------------------------------------------------

    def exchange_capabilities(self) -> List[str]:
        caps = self.rpc(ENGINE_EXCHANGE_CAPABILITIES,
                        [LIGHTHOUSE_CAPABILITIES])
        self.capabilities = list(caps or [])
        return self.capabilities

    def new_payload(self, payload) -> PayloadStatus:
        method = (ENGINE_NEW_PAYLOAD_V2 if hasattr(payload, "withdrawals")
                  else ENGINE_NEW_PAYLOAD_V1)
        result = self.rpc(method, [payload_to_json(payload)])
        try:
            return PayloadStatus(result["status"])
        except (TypeError, KeyError, ValueError):
            raise EngineError(f"{method}: malformed status: {result!r}")

    def forkchoice_updated(self, head_hash: bytes, safe_hash: bytes,
                           finalized_hash: bytes,
                           payload_attributes=None) -> Optional[bytes]:
        fc_state = {"headBlockHash": _d(head_hash),
                    "safeBlockHash": _d(safe_hash),
                    "finalizedBlockHash": _d(finalized_hash)}
        attrs = None
        if payload_attributes is not None:
            attrs = {
                "timestamp": _q(payload_attributes["timestamp"]),
                "prevRandao": _d(payload_attributes["prev_randao"]),
                "suggestedFeeRecipient": _d(
                    payload_attributes["suggested_fee_recipient"]),
            }
            if "withdrawals" in payload_attributes:  # capella: V2 attrs
                attrs["withdrawals"] = [{
                    "index": _q(w["index"]),
                    "validatorIndex": _q(w["validator_index"]),
                    "address": _d(w["address"]),
                    "amount": _q(w["amount"]),
                } for w in payload_attributes["withdrawals"]]
        method = (ENGINE_FORKCHOICE_UPDATED_V2
                  if attrs is not None and "withdrawals" in attrs
                  else ENGINE_FORKCHOICE_UPDATED_V1)
        result = self.rpc(method, [fc_state, attrs])
        status = (result or {}).get("payloadStatus", {}).get("status")
        if status == PayloadStatus.INVALID.value:
            raise EngineError(f"{method}: INVALID forkchoice state")
        pid = (result or {}).get("payloadId")
        return bytes.fromhex(pid[2:]) if pid else None

    def get_payload(self, payload_id: bytes):
        # V2 responses wrap the payload with a block value; V1 is bare.
        try:
            result = self.rpc(ENGINE_GET_PAYLOAD_V2, [_d(payload_id)])
            if result and "executionPayload" in result:
                return json_to_payload_fields(result["executionPayload"])
        except EngineError:
            result = self.rpc(ENGINE_GET_PAYLOAD_V1, [_d(payload_id)])
        return json_to_payload_fields(result)

    def is_syncing(self) -> bool:
        return bool(self.rpc(ETH_SYNCING, []))
