"""Device-resident slasher span planes — SURVEY §7's designated second
TPU workload (VERDICT r4 #9).

The reference updates chunked min/max-target arrays per validator-chunk ×
epoch-chunk in LMDB (``/root/reference/slasher/src/array.rs:106-116``).
The TPU redesign keeps the WHOLE span plane HBM-resident as two
``(n_validators, history)`` uint16 ring buffers and turns an ingest batch
into ONE fused dispatch:

- attestations are grouped host-side by their (source, target) pair — in
  steady state a slot's batch has a handful of distinct pairs (one per
  recent target), each with the union of its attesters;
- each group becomes a full-plane masked min/max sweep: the candidate
  value at (v, e) is an arithmetic ramp ``t − e`` over the epoch axis,
  gated by a per-validator membership mask and a per-column range mask —
  pure VPU work at HBM bandwidth, no scatters (a gather/scatter of
  |live|×|cols| indices would serialise on TPU; the dense sweep is the
  shape XLA tiles well);
- the G groups run under ``lax.scan`` inside one jit — one device
  roundtrip per ingest batch, G statically padded (pow-2 bucket like the
  BLS pipeline's set counts);
- surround DETECTION needs only the two columns at the new attestation's
  source: those are gathered in the same dispatch and returned (a
  (G, n) slice), so the host touches per-offence evidence only.

Memory: n=2^20, H=1024 → 2 GiB/plane in HBM (v5e has 16 GiB); the ring
layout bounds the epoch axis and `history` bounds total footprint — the
host Slasher's numpy planes stay the ground truth (cross-checked in
tests/test_slasher.py).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

_NO_MIN = np.uint16(0xFFFF)
_NO_MAX = np.uint16(0)

# Static pow-2 bucket sizes for group counts, so recompiles are bounded
# (same discipline as the BLS pipeline's set-count buckets).
_MAX_GROUPS = 16

from ..ops.merkle import _next_pow2  # noqa: E402 (shared helper)


@partial(jax.jit, donate_argnums=(0, 1))
def _ingest_kernel(min_plane, max_plane, masks_packed, sources, targets,
                   live, group_idx):
    """One fused ingest: scan G groups of full-plane masked sweeps.

    min_plane/max_plane: (n, H) uint16 ring buffers (column = epoch % H)
    masks_packed: (G, n/8) uint8 — BIT-PACKED group membership (the
        tunnel is bandwidth-bound; the packed form is 8× smaller and is
        unpacked on device)
    sources: (G,) int32, targets: (G,) int32 (absolute epochs; −1 = pad)
    live:    (G,) bool — group is real
    group_idx: (G, W) int32 — each group's member validator indices,
        zero-padded; the surround gathers return ONLY these positions
        (pulling full (n,) columns back dwarfed the sweep at registry
        scale)

    Returns updated planes + (G, W) pre-update min/max gathers at each
    group's source column.
    """
    n, H = min_plane.shape
    cols = jnp.arange(H, dtype=jnp.int32)  # column index = epoch % H

    def body(planes, group):
        mn, mx = planes
        packed, s, t, ok, gidx = group
        # unpack bits (bitorder='little' matches np.packbits host-side)
        mask = ((packed[:, None] >> jnp.arange(8, dtype=jnp.uint8))
                & 1).astype(bool).reshape(-1)[:n]
        # Mirror the host sweeps exactly (slasher/__init__.py):
        #   min: e ∈ [max(s−H+1, 0), s)  → min_span[e%H] = min(., t−e)
        #   max: e ∈ (s, t)              → max_span[e%H] = max(., t−e)
        # Each column c has at most one representative epoch in a
        # length-≤H range [lo, hi): e(c) = lo + ((c − lo) mod H).
        lo1 = jnp.maximum(s - H + 1, 0)
        e1 = lo1 + ((cols - lo1) % H)          # (H,) candidate epochs
        min_cols = e1 < s                      # range [lo1, s)
        v1 = jnp.clip(t - e1, 0, 0xFFFE).astype(jnp.uint16)
        lo2 = s + 1
        e2 = lo2 + ((cols - lo2) % H)
        max_cols = e2 < t                      # range (s, t)
        v2 = jnp.clip(t - e2, 0, 0xFFFE).astype(jnp.uint16)

        m2 = (mask & ok)[:, None]              # (n, 1)
        mn_new = jnp.where(m2 & min_cols[None, :],
                           jnp.minimum(mn, v1[None, :]), mn)
        mx_new = jnp.where(m2 & max_cols[None, :],
                           jnp.maximum(mx, v2[None, :]), mx)
        # pre-update gathers at the source column, at the group's own
        # member indices only
        sc = (s % H).astype(jnp.int32)
        col_min = lax.dynamic_index_in_dim(mn, sc, axis=1,
                                           keepdims=False)
        col_max = lax.dynamic_index_in_dim(mx, sc, axis=1,
                                           keepdims=False)
        return (mn_new, mx_new), (col_min[gidx], col_max[gidx])

    (mn, mx), (g_min, g_max) = lax.scan(
        body, (min_plane, max_plane),
        (masks_packed, sources, targets, live, group_idx))
    return mn, mx, g_min, g_max


class DeviceSpanPlane:
    """HBM-resident min/max span planes with fused batched ingest."""

    def __init__(self, n_validators: int, history: int = 1024):
        from ..common.device_ledger import LEDGER
        from ..parallel.mesh import mesh_place
        self.n = n_validators
        self.history = history
        # Device-side fills placed on the process mesh — the validator
        # axis shards over ``batch`` when it divides, so each chip holds
        # ``2nH/d`` bytes of plane; zero H2D either way.
        self.min_plane = mesh_place(
            "slasher_planes",
            jnp.full((n_validators, history), _NO_MIN, jnp.uint16))
        self.max_plane = mesh_place(
            "slasher_planes",
            jnp.full((n_validators, history), _NO_MAX, jnp.uint16))
        # (the GC finalizer releases the residency with the plane object)
        self._res = LEDGER.track(
            self, "slasher",
            int(self.min_plane.nbytes) + int(self.max_plane.nbytes))

    @staticmethod
    def group(atts: Sequence[Tuple[int, int, np.ndarray]]
              ) -> List[Tuple[int, int, np.ndarray]]:
        """Group (source, target, indices) attestations by (s, t),
        unioning attester indices — the host-side half of the ingest."""
        by_st: Dict[Tuple[int, int], List[np.ndarray]] = {}
        for s, t, idx in atts:
            by_st.setdefault((s, t), []).append(np.asarray(idx))
        return [(s, t, np.unique(np.concatenate(parts)))
                for (s, t), parts in sorted(by_st.items())]

    def ingest(self, groups: Sequence[Tuple[int, int, np.ndarray]]):  # device-io: slasher
        """Apply grouped updates in fused dispatches of ≤ _MAX_GROUPS.

        Returns one dict (s, t) → (min gather, max gather) at the
        source column, ALIGNED WITH the group's (sorted, unique) member
        index array — positional, not validator-indexed.

        Contract: exact equality with the host Slasher's numpy sweeps
        holds for t − s ≤ min(history, 0xFFFE) — beyond that the ring
        cannot represent the max-sweep range uniquely (and the reference
        saturates spans at the u16 bound anyway, `array.rs` MAX_SPAN
        encoding); such groups are rejected here rather than silently
        diverging.
        """
        for s, t, _ in groups:
            if t - s > min(self.history, 0xFFFE):
                raise ValueError(
                    f"span distance {t - s} exceeds the history window "
                    f"{self.history}; clamp upstream")
        from ..common.device_ledger import LEDGER
        from ..parallel.mesh import mesh_gather, mesh_put
        pre: Dict[Tuple[int, int], Tuple[np.ndarray, np.ndarray]] = {}
        for at in range(0, len(groups), _MAX_GROUPS):
            chunk = groups[at:at + _MAX_GROUPS]
            G = _next_pow2(len(chunk))
            W = _next_pow2(max(len(idx) for _s, _t, idx in chunk))
            masks = np.zeros((G, self.n), bool)
            sources = np.full(G, -1, np.int32)
            targets = np.full(G, -1, np.int32)
            live = np.zeros(G, bool)
            gidx = np.zeros((G, W), np.int32)
            for i, (s, t, idx) in enumerate(chunk):
                masks[i, idx] = True
                sources[i] = s
                targets[i] = t
                live[i] = True
                gidx[i, :len(idx)] = idx
            packed = np.packbits(masks, axis=1, bitorder="little")
            t0 = time.perf_counter()
            self.min_plane, self.max_plane, g_min, g_max = _ingest_kernel(  # device-io: slasher
                self.min_plane, self.max_plane,
                mesh_put("slasher_groups", packed, subsystem="slasher"),
                mesh_put("slasher_groups", sources, subsystem="slasher"),
                mesh_put("slasher_groups", targets, subsystem="slasher"),
                mesh_put("slasher_groups", live, subsystem="slasher"),
                mesh_put("slasher_groups", gidx, subsystem="slasher"))
            g_min = mesh_gather(g_min, subsystem="slasher")
            g_max = mesh_gather(g_max, subsystem="slasher")
            LEDGER.note_dispatch("slasher",
                                 (time.perf_counter() - t0) * 1e3)
            for i, (s, t, idx) in enumerate(chunk):
                pre[(s, t)] = (g_min[i, :len(idx)], g_max[i, :len(idx)])
        return pre

    def to_host(self) -> Tuple[np.ndarray, np.ndarray]:
        from ..parallel.mesh import mesh_gather
        mn = mesh_gather(self.min_plane, subsystem="slasher",
                         name="slasher_planes")
        mx = mesh_gather(self.max_plane, subsystem="slasher",
                         name="slasher_planes")
        return mn, mx


def bench_device_span_update(n_validators: int, history: int,
                             atts: Sequence) -> dict:
    """Device column of :func:`..bench_span_update` — same attestation
    batch through the fused plane kernel; reports the ingest time with
    the result synced (one dispatch per ≤16 groups)."""
    triples = [(int(a.data.source.epoch), int(a.data.target.epoch),
                np.asarray([int(i) for i in a.attesting_indices]))
               for a in atts]
    plane = DeviceSpanPlane(n_validators, history)
    groups = plane.group(triples)
    plane.ingest(groups)  # warm the compile
    del plane  # free before the timed plane (2× planes would double peak)
    plane2 = DeviceSpanPlane(n_validators, history)
    t0 = time.perf_counter()
    plane2.ingest(groups)
    jax.block_until_ready((plane2.min_plane, plane2.max_plane))
    ms = (time.perf_counter() - t0) * 1e3
    return {
        "slasher_device_update_1m_ms": round(ms, 1),
        "slasher_device_groups": len(groups),
    }
