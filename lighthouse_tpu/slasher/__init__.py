"""Slasher: attester/proposer slashing detection —
``slasher`` (``/root/reference/slasher/src/``).

The reference implements the Phase-0 "minimal span" design as chunked
min/max-target arrays in LMDB/MDBX, updated per validator-chunk×epoch-chunk
grid (``array.rs:106-116,486,573``).  Columnar redesign: the WHOLE span
plane is two numpy arrays (validators × history window) and every ingest is
a broadcast range-min/max over the epoch axis — the per-chunk loops become
single vector ops (and, at registry scale, a device dispatch).

Detection rules (``lib.rs:33-49`` AttesterSlashingStatus):

- double vote: same (validator, target epoch), different attestation data;
- surround: ``max_span[v][s] > t − s`` ⇒ an earlier attestation surrounds
  the new one; ``min_span[v][s] < t − s`` ⇒ the new one surrounds an
  earlier one.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..store.kv import DBColumn, KeyValueStore, MemoryStore

_NO_SPAN_MIN = np.uint16(0xFFFF)
_NO_SPAN_MAX = np.uint16(0)


@dataclass
class AttesterRecord:
    """Indexed attestation summary kept for slashing construction."""
    source: int
    target: int
    data_root: bytes
    indexed: object  # the original IndexedAttestation-like object


@dataclass
class Slashing:
    """A detected offence: the two conflicting attestations."""
    kind: str  # "double" | "surrounds" | "surrounded"
    validator_index: int
    attestation_1: object
    attestation_2: object


class Slasher:
    """Whole-plane min/max-span slasher.

    ``engine="numpy"`` (default) keeps the span planes as host arrays;
    ``engine="device"`` keeps them HBM-resident and drains each queue as
    grouped fused dispatches (:mod:`.device_spans` — SURVEY §7's second
    TPU workload), with doubles/evidence handled identically host-side.
    Both engines are cross-checked in tests/test_slasher.py."""

    def __init__(self, n_validators: int, history_length: int = 4096,
                 kv: Optional[KeyValueStore] = None,
                 engine: str = "numpy"):
        self.history = history_length
        self.n = n_validators
        self.engine = engine
        # Spans store (target − e) distances, clamped to u16 like the
        # reference chunks (`array.rs` MIN_SPAN/MAX_SPAN encodings).
        if engine == "device":
            from .device_spans import DeviceSpanPlane
            self.device_plane = DeviceSpanPlane(n_validators,
                                                history=history_length)
            self.min_span = None
            self.max_span = None
        else:
            self.device_plane = None
            self.min_span = np.full((n_validators, history_length),
                                    _NO_SPAN_MIN, np.uint16)
            self.max_span = np.full((n_validators, history_length),
                                    _NO_SPAN_MAX, np.uint16)
        # (validator, target) → AttesterRecord for double votes + evidence.
        self.by_target: Dict[Tuple[int, int], AttesterRecord] = {}
        # validator → [(source, target)] of WIDE votes (t − s beyond the
        # span-plane encoding).  The device engine keeps these out of the
        # plane but must still honour them in surround detection — the
        # evidence dict is the ground truth the plane only accelerates.
        self._wide: Dict[int, List[Tuple[int, int]]] = {}
        self.kv = kv or MemoryStore()
        self.queue: List[object] = []

    # -- ingest --------------------------------------------------------------

    def accept_attestation(self, indexed) -> None:
        """Batch ingest queue (`attestation_queue.rs`)."""
        self.queue.append(indexed)

    def process_queued(self, current_epoch: int) -> List[Slashing]:
        """Drain the queue — one vectorized span update per attestation
        (numpy engine), or grouped fused device dispatches with the
        surround gathers coming back from the same dispatch (device
        engine)."""
        if self.engine == "device":
            return self._process_queued_device(current_epoch)
        out: List[Slashing] = []
        for indexed in self.queue:
            out.extend(self._process_one(indexed, current_epoch))
        self.queue = []
        return out

    def _process_queued_device(self, current_epoch: int) -> List[Slashing]:
        out: List[Slashing] = []
        live_atts = []
        for indexed in self.queue:
            data = indexed.data
            s = int(data.source.epoch)
            t = int(data.target.epoch)
            if t < s or t > current_epoch or \
                    current_epoch - t >= self.history:
                continue
            # Wide-source attestations (t − s beyond the span-plane
            # encoding) are excluded from the PLANE ingest only: the
            # by-target double-vote pass below must still see them — the
            # numpy engine detects doubles for such attestations, and
            # skipping them here let a crafted wide vote evade detection
            # on engine='device' (ADVICE r5).
            wide = t - s > min(self.history, 0xFFFE)
            data_root = data.tree_hash_root()
            idx = np.asarray([int(i) for i in indexed.attesting_indices],
                             dtype=np.int64)
            idx = idx[idx < self.n]
            # Doubles first, recording IMMEDIATELY so later atts in the
            # SAME batch see earlier ones (matches the numpy engine's
            # sequential semantics).
            live = []
            rec = AttesterRecord(s, t, data_root, indexed)
            for v in idx:
                prev = self.by_target.get((int(v), t))
                if prev is not None and prev.data_root != data_root:
                    out.append(Slashing("double", int(v), prev.indexed,
                                        indexed))
                else:
                    live.append(int(v))
                    self.by_target[(int(v), t)] = rec
            if not live:
                continue
            if wide:
                # Wide votes bypass the plane entirely; surround checks
                # run on the evidence dict directly (ground truth — the
                # plane gathers are only its accelerator).  Wide votes
                # are adversarial rarities, so the O(dict) scan is off
                # the hot path.
                for v in live:
                    self._wide.setdefault(v, []).append((s, t))
                    prior = self._find_surrounding(v, s, t)
                    if prior is not None:
                        out.append(Slashing("surrounds", v,
                                            prior.indexed, indexed))
                    prior = self._find_surrounded(v, s, t)
                    if prior is not None:
                        out.append(Slashing("surrounded", v, indexed,
                                            prior.indexed))
            else:
                live_atts.append((s, t, np.asarray(live, np.int64),
                                  indexed, data_root))
        self.queue = []
        if not live_atts:
            return out
        groups = self.device_plane.group(
            [(s, t, idx) for s, t, idx, _a, _r in live_atts])
        group_members = {(s, t): idx for s, t, idx in groups}
        pre = self.device_plane.ingest(groups)
        for s, t, live, indexed, data_root in live_atts:
            gm_vals, gx_vals = pre[(s, t)]
            members = group_members[(s, t)]  # sorted unique indices
            # positional lookup: this att's validators within the group
            pos = np.searchsorted(members, live)
            g_min = gm_vals[pos]
            g_max = gx_vals[pos]
            dist = t - s
            # Pre-batch plane gathers can't see SAME-batch attestations
            # (ingest is one fused dispatch); fold those in by a pairwise
            # group sweep — G is a handful per batch, so this is cheap
            # (the numpy engine gets this for free by updating spans
            # sequentially).
            surrounds = g_max.astype(np.int64) > dist
            surrounded = g_min.astype(np.int64) < dist
            batch_sur = np.zeros(live.shape, bool)
            batch_subd = np.zeros(live.shape, bool)
            for s2, t2, live2, _a2, _r2 in live_atts:
                if s2 < s and t2 > t:
                    batch_sur |= np.isin(live, live2)
                if s2 > s and t2 < t:
                    batch_subd |= np.isin(live, live2)
            surrounds |= batch_sur
            surrounded |= batch_subd
            # Wide votes never touched the plane; fold their spans in
            # from the side index (empty in the non-adversarial case).
            if self._wide:
                for j in range(live.shape[0]):
                    spans = self._wide.get(int(live[j]))
                    if not spans:
                        continue
                    surrounds[j] |= any(s2 < s and t2 > t
                                        for s2, t2 in spans)
                    surrounded[j] |= any(s2 > s and t2 < t
                                         for s2, t2 in spans)
            for v in live[surrounds]:
                prior = self._find_surrounding(int(v), s, t)
                if prior is not None:
                    out.append(Slashing("surrounds", int(v),
                                        prior.indexed, indexed))
            for v in live[surrounded]:
                prior = self._find_surrounded(int(v), s, t)
                if prior is not None:
                    out.append(Slashing("surrounded", int(v), indexed,
                                        prior.indexed))
        return out

    def _process_one(self, indexed, current_epoch: int) -> List[Slashing]:
        data = indexed.data
        s = int(data.source.epoch)
        t = int(data.target.epoch)
        if t < s or t > current_epoch or current_epoch - t >= self.history:
            return []
        data_root = data.tree_hash_root()
        idx = np.asarray([int(i) for i in indexed.attesting_indices],
                         dtype=np.int64)
        idx = idx[idx < self.n]
        out: List[Slashing] = []

        # Double votes (per validator; dict lookups, small).
        live = []
        for v in idx:
            rec = self.by_target.get((int(v), t))
            if rec is not None and rec.data_root != data_root:
                out.append(Slashing("double", int(v), rec.indexed, indexed))
            else:
                live.append(int(v))
        live = np.asarray(live, dtype=np.int64)
        if live.size == 0:
            return out

        dist = t - s
        se = s % self.history
        # Surround checks — one gather per plane (`array.rs` chunk reads).
        surrounds = self.max_span[live, se].astype(np.int64) > dist
        surrounded = self.min_span[live, se].astype(np.int64) < dist
        for v in live[surrounds]:
            prior = self._find_surrounding(int(v), s, t)
            if prior is not None:
                out.append(Slashing("surrounds", int(v), prior.indexed,
                                    indexed))
        for v in live[surrounded]:
            prior = self._find_surrounded(int(v), s, t)
            if prior is not None:
                out.append(Slashing("surrounded", int(v), indexed,
                                    prior.indexed))

        # Span plane updates — broadcast range ops over the epoch axis
        # (`array.rs:486,573` update_* loops as single vector ops):
        # min_span[v][e] = min(., t−e) for e in [t−history+1, s);
        # max_span[v][e] = max(., t−e) for e in (s, t).
        lo = max(s - self.history + 1, 0)
        if s > lo:
            es = np.arange(lo, s)
            cols = es % self.history
            vals = np.minimum(t - es, 0xFFFE).astype(np.uint16)
            plane = self.min_span[live[:, None], cols[None, :]]
            self.min_span[live[:, None], cols[None, :]] = \
                np.minimum(plane, vals[None, :])
        if t > s + 1:
            es = np.arange(s + 1, t)
            cols = es % self.history
            # Saturate at the u16 bound (reference MAX_SPAN encoding) —
            # an unclamped cast would wrap for adversarial t − s > 65535.
            vals = np.minimum(t - es, 0xFFFE).astype(np.uint16)
            plane = self.max_span[live[:, None], cols[None, :]]
            self.max_span[live[:, None], cols[None, :]] = \
                np.maximum(plane, vals[None, :])

        rec = AttesterRecord(s, t, data_root, indexed)
        for v in live:
            self.by_target[(int(v), t)] = rec
        return out

    def _find_surrounding(self, v: int, s: int, t: int):
        """Locate an attestation (s' < s, t' > t) for evidence."""
        best = None
        for (vi, target), rec in self.by_target.items():
            if vi == v and rec.source < s and target > t:
                if best is None or target < best.target:
                    best = rec
        return best

    def _find_surrounded(self, v: int, s: int, t: int):
        best = None
        for (vi, target), rec in self.by_target.items():
            if vi == v and rec.source > s and target < t:
                if best is None or target > best.target:
                    best = rec
        return best

    # -- blocks (proposer equivocation) --------------------------------------

    def accept_block_header(self, signed_header) -> Optional[Slashing]:
        """`block_queue.rs` + proposer double-proposal detection."""
        h = signed_header.message
        key = struct.pack("<QQ", int(h.proposer_index), int(h.slot))
        root = h.tree_hash_root()
        prev = self.kv.get(DBColumn.BeaconMeta, b"hdr" + key)
        if prev is None:
            self.kv.put(DBColumn.BeaconMeta, b"hdr" + key,
                        root + signed_header.encode())
            return None
        if prev[:32] == root:
            return None
        return Slashing("double_proposal", int(h.proposer_index),
                        prev[32:], signed_header)

    # -- maintenance ---------------------------------------------------------

    def grow(self, n_validators: int) -> None:
        if n_validators <= self.n:
            return
        extra = n_validators - self.n
        self.min_span = np.concatenate(
            [self.min_span, np.full((extra, self.history), _NO_SPAN_MIN,
                                    np.uint16)])
        self.max_span = np.concatenate(
            [self.max_span, np.full((extra, self.history), _NO_SPAN_MAX,
                                    np.uint16)])
        self.n = n_validators

    def prune(self, current_epoch: int) -> None:
        horizon = current_epoch - self.history
        self.by_target = {k: v for k, v in self.by_target.items()
                          if k[1] > horizon}
        if self._wide:
            self._wide = {
                v: kept for v, spans in self._wide.items()
                if (kept := [st for st in spans if st[1] > horizon])}


def bench_span_update(n_validators: int = 1 << 20, n_atts: int = 1024,
                      history: int = 1024, per_att: int = 256,
                      seed: int = 0) -> dict:
    """VERDICT r4 #9: span min/max ingest at registry scale — the
    ``array.rs:106-116`` update grid workload.  ``n_atts`` aggregates of
    ``per_att`` attesters each over a ``n_validators``-validator registry,
    drained in one ``process_queued`` batch (numpy whole-plane path; the
    device plane is benchmarked alongside when available)."""
    import time as _time

    rng = np.random.default_rng(seed)
    cur = history - 2

    class _Data:
        __slots__ = ("source", "target", "_root")

        def __init__(self, s, t, salt):
            self.source = type("E", (), {"epoch": s})()
            self.target = type("E", (), {"epoch": t})()
            self._root = struct.pack("<QQQ", s, t, salt) + b"\0" * 8

        def tree_hash_root(self):
            return self._root

    class _Indexed:
        __slots__ = ("data", "attesting_indices")

        def __init__(self, data, idx):
            self.data = data
            self.attesting_indices = idx

    # Disjoint validator pools per attestation (each validator attests at
    # most once) so NO slashings fire inside the timed region — the metric
    # measures the span-plane update grid (`array.rs:106-116`), not the
    # Python evidence-scan path (which only runs on actual offences).
    if n_atts * per_att > n_validators:
        raise ValueError("need n_atts*per_att <= n_validators for a "
                         "collision-free schedule")
    pools = rng.permutation(n_validators)[:n_atts * per_att]
    pools = pools.reshape(n_atts, per_att)
    atts = []
    for i in range(n_atts):
        t = cur - (i % 2)
        s = t - 1 - (i % 3)
        atts.append(_Indexed(_Data(s, t, i), pools[i].tolist()))

    slasher = Slasher(n_validators, history_length=history)
    for a in atts:
        slasher.accept_attestation(a)
    t0 = _time.perf_counter()
    slashings = slasher.process_queued(cur)
    numpy_ms = (_time.perf_counter() - t0) * 1e3
    if slashings:
        raise RuntimeError("collision-free schedule produced slashings")

    out = {
        "slasher_update_1m_ms": round(numpy_ms, 1),
        "slasher_atts": n_atts,
        "slasher_attesters_per_att": per_att,
        "slasher_history": history,
    }
    del slasher  # free the numpy planes before the device allocation
    try:
        from .device_spans import bench_device_span_update
        out.update(bench_device_span_update(
            n_validators=n_validators, history=history, atts=atts))
    except Exception as e:  # device column must not lose the numpy row
        out["slasher_device_error"] = f"{type(e).__name__}: {e}"
    return out
