"""Phase0 (PendingAttestation-based) epoch processing —
``per_epoch_processing/base``
(``/root/reference/consensus/state_processing/src/per_epoch_processing/base/``).

Pre-altair, participation is reconstructed each epoch from the stored
``PendingAttestation`` lists: matching source/target/head sets resolve
through historical committees, then the four base-reward components
(source, target, head, inclusion delay) and the inactivity leak apply.
Participation resolves into boolean masks over the whole registry so the
reward math is column arithmetic like the altair path.
"""

from __future__ import annotations

import numpy as np

from ..types.chain_spec import ForkName
from .committees import get_beacon_committee
from math import isqrt

from .helpers import (
    current_epoch,
    get_block_root,
    get_block_root_at_slot,
    get_total_active_balance,
    previous_epoch,
)
from .per_epoch import (
    EpochSummary,
    eligible_validator_mask,
    weigh_justification_and_finalization,
)

BASE_REWARDS_PER_EPOCH = 4


def _attestation_masks(state, attestations, preset):
    """(source_mask, min_delay, min_proposer) over the registry for a
    pending-attestation list: which unslashed validators attested, their
    minimum inclusion delay and that attestation's proposer."""
    n = len(state.validators)
    mask = np.zeros(n, dtype=bool)
    min_delay = np.full(n, np.iinfo(np.int64).max, dtype=np.int64)
    min_proposer = np.full(n, -1, dtype=np.int64)
    for att in attestations:
        committee = np.asarray(get_beacon_committee(
            state, int(att.data.slot), int(att.data.index), preset))
        bits = np.asarray(att.aggregation_bits, dtype=bool)[:len(committee)]
        idx = committee[bits]
        mask[idx] = True
        delay = int(att.inclusion_delay)
        better = delay < min_delay[idx]
        min_delay[idx[better]] = delay
        min_proposer[idx[better]] = int(att.proposer_index)
    mask &= ~np.asarray(state.validators.col("slashed"))
    return mask, min_delay, min_proposer


def _matching_attestations(state, epoch: int, preset):
    cur = current_epoch(state, preset)
    atts = (state.current_epoch_attestations if epoch == cur
            else state.previous_epoch_attestations)
    source = list(atts)
    boundary = get_block_root(state, epoch, preset)
    target = [a for a in source if bytes(a.data.target.root) == boundary]
    head = [a for a in target
            if bytes(a.data.beacon_block_root)
            == get_block_root_at_slot(state, int(a.data.slot), preset)]
    return source, target, head


def _finality_delay(state, preset) -> int:
    return previous_epoch(state, preset) - int(
        state.finalized_checkpoint.epoch)


def _in_leak(state, preset) -> bool:
    return _finality_delay(state, preset) \
        > preset.MIN_EPOCHS_TO_INACTIVITY_PENALTY


def process_justification_and_finalization_phase0(
        state, preset, T, summary: EpochSummary) -> None:
    """Target balances from PendingAttestations (`base/justification...`)."""
    from ..types.chain_spec import GENESIS_EPOCH

    cur = current_epoch(state, preset)
    if cur <= GENESIS_EPOCH + 1:
        return
    prev = previous_epoch(state, preset)
    balances = state.validators.col("effective_balance")
    _, prev_t, _ = _matching_attestations(state, prev, preset)
    prev_mask, _, _ = _attestation_masks(state, prev_t, preset)
    _, cur_t, _ = _matching_attestations(state, cur, preset)
    cur_mask, _, _ = _attestation_masks(state, cur_t, preset)
    total = get_total_active_balance(state, preset)
    prev_bal = max(int(balances[prev_mask].sum()),
                   preset.EFFECTIVE_BALANCE_INCREMENT)
    cur_bal = max(int(balances[cur_mask].sum()),
                  preset.EFFECTIVE_BALANCE_INCREMENT)
    summary.total_active_balance = total
    summary.previous_target_balance = prev_bal
    summary.current_target_balance = cur_bal
    weigh_justification_and_finalization(state, total, prev_bal, cur_bal,
                                         preset, T)


def attestation_deltas_phase0(state, preset, spec):
    """Per-component attestation deltas — the EF `rewards` runner's
    decomposition of `get_attestation_deltas`
    (`base/rewards_and_penalties.rs`): a dict of component name →
    (rewards, penalties) int64 arrays for source / target / head /
    inclusion_delay / inactivity_penalty.  Applying the summed
    components is exactly :func:`process_rewards_and_penalties_phase0`.
    """
    n = len(state.validators)
    balances = np.asarray(state.validators.col("effective_balance"),
                          dtype=np.int64)
    total = get_total_active_balance(state, preset)
    sqrt_total = isqrt(total)
    base_reward = (balances * preset.BASE_REWARD_FACTOR // sqrt_total
                   // BASE_REWARDS_PER_EPOCH)
    eligible = eligible_validator_mask(state, preset)
    prev = previous_epoch(state, preset)
    src_atts, tgt_atts, head_atts = _matching_attestations(
        state, prev, preset)
    src_mask, min_delay, min_prop = _attestation_masks(state, src_atts,
                                                       preset)
    tgt_mask, _, _ = _attestation_masks(state, tgt_atts, preset)
    head_mask, _, _ = _attestation_masks(state, head_atts, preset)

    incr = preset.EFFECTIVE_BALANCE_INCREMENT
    total_incr = total // incr
    in_leak = _in_leak(state, preset)

    out = {}
    for name, mask in (("source", src_mask), ("target", tgt_mask),
                       ("head", head_mask)):
        rewards = np.zeros(n, dtype=np.int64)
        penalties = np.zeros(n, dtype=np.int64)
        att_incr = int(balances[mask].sum()) // incr
        hit = eligible & mask
        miss = eligible & ~mask
        if in_leak:
            # Optimal performance cancels to neutral during a leak.
            rewards[hit] += base_reward[hit]
        else:
            rewards[hit] += base_reward[hit] * att_incr // total_incr
        penalties[miss] += base_reward[miss]
        out[name] = (rewards, penalties)

    # Inclusion delay: proposer cut + delay-decayed attester reward.
    proposer_reward = base_reward // preset.PROPOSER_REWARD_QUOTIENT
    rewards = np.zeros(n, dtype=np.int64)
    src_idx = np.nonzero(src_mask)[0]
    for i in src_idx:
        rewards[min_prop[i]] += int(proposer_reward[i])
        max_att = int(base_reward[i]) - int(proposer_reward[i])
        rewards[i] += max_att // int(min_delay[i])
    out["inclusion_delay"] = (rewards, np.zeros(n, dtype=np.int64))

    penalties = np.zeros(n, dtype=np.int64)
    if in_leak:
        delay = _finality_delay(state, preset)
        el = np.nonzero(eligible)[0]
        penalties[el] += (BASE_REWARDS_PER_EPOCH * base_reward[el]
                          - proposer_reward[el])
        lazy = eligible & ~tgt_mask
        penalties[lazy] += (balances[lazy] * delay
                            // preset.INACTIVITY_PENALTY_QUOTIENT)
    out["inactivity_penalty"] = (np.zeros(n, dtype=np.int64), penalties)
    return out


def process_rewards_and_penalties_phase0(state, preset, spec,
                                         summary: EpochSummary) -> None:
    """`get_attestation_deltas` (`base/rewards_and_penalties.rs`), as
    column arithmetic over the participation masks."""
    from ..types.chain_spec import GENESIS_EPOCH

    if current_epoch(state, preset) == GENESIS_EPOCH:
        return
    n = len(state.validators)
    deltas = attestation_deltas_phase0(state, preset, spec)
    rewards = np.zeros(n, dtype=np.int64)
    penalties = np.zeros(n, dtype=np.int64)
    for r, p in deltas.values():
        rewards += r
        penalties += p
    bal = np.asarray(state.balances, dtype=np.int64)
    state.balances[:] = np.maximum(bal + rewards - penalties, 0).astype(
        np.uint64)


def process_participation_record_updates(state) -> None:
    """Rotate the pending-attestation lists (`base/` record updates)."""
    state.previous_epoch_attestations = list(
        state.current_epoch_attestations)
    state.current_epoch_attestations = []
