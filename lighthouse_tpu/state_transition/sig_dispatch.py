"""Overlapped block-signature dispatch — the device batch rides under
the host transition instead of trailing it.

``process_block(strategy=VERIFY_BULK)`` used to pay its bulk
``verify_signature_sets`` call as a trailing synchronous step: the whole
transition ran, THEN the batch went to the device and the import waited
out the pairing latency end-to-end.  The committee-consensus study
(arXiv:2302.00418) shows verification throughput under per-slot
committee load — not peak batch size — decides liveness, and the IoT
pairing-processor paper (arXiv:2201.07496) wins by keeping its wide
multiplier saturated instead of idle between dispatches; both argue for
hiding the pairing latency under the transition, which is what this
module does:

- :meth:`BlockSigDispatcher.submit` takes the block's accumulated
  signature sets as soon as the op-accumulation phase has built them
  (before the participation scatters / proposer rewards / sync-aggregate
  balance work / payload header build), drops exact-duplicate sets
  (:func:`~lighthouse_tpu.crypto.bls.dedup_signature_sets`), and
  dispatches verification on a worker thread;
- the device route goes through the mesh-sharded path
  (:func:`~lighthouse_tpu.parallel.bls_shard.
  bucketed_verify_signature_sets` — sets grouped by padded signer count
  K exactly like the verification service's ingress buckets) when a
  multi-chip mesh is attached, and is wrapped in the PR-7 global BLS
  :class:`~lighthouse_tpu.beacon_chain.verification_service.
  ResilienceEnvelope` (via
  :func:`~lighthouse_tpu.beacon_chain.verification_service.
  block_sig_dispatch`), so a tripped device degrades the block batch to
  the host oracle through the SAME breaker every other non-streamed
  verify uses — zero new failure modes;
- :meth:`BlockSigBatch.join` delivers the verdict at
  ``SigAccumulator.finish()`` — on the import pipeline
  (``block_verification.ExecutedBlock``) that is AFTER the post-state
  root hash, so device pairing time hides behind host transition +
  hashing compute and only the remainder (``join_wait_ms``) lands on
  the critical path.

The python/fake backends ARE the host path: they dispatch directly on
the worker thread with no envelope (wrapping them would add
retry/deadline semantics to logic-test verifies — the same rule as
``verification_service._global_dispatch``).  With the fake backend the
whole machinery (dedup, async submit, deferred applies, join) still
runs, which is how the quick tier drives it without compiling any
pairing-shaped program.

Stats of the most recent completed batch land in
:data:`LAST_SIG_DISPATCH` (stage source ``"block_sigs"`` — bench and
the validation script read it through ``tracing.stage_split``):
``sets`` / ``deduped`` / ``path`` / ``device_verify_ms`` /
``join_wait_ms`` / ``overlap_efficiency`` (= 1 − join_wait /
device_verify) / ``overlapped``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

from ..common.tracing import TRACER

# Stats of the most recent block-signature verdict (overlapped OR the
# synchronous oracle path) — read via tracing.stage_split("block_sigs").
LAST_SIG_DISPATCH: dict = {}


def overlap_enabled() -> bool:
    """Overlapped dispatch knob: on unless
    ``LIGHTHOUSE_TPU_OVERLAP_BLOCK_SIGS=0`` (the trailing synchronous
    verify is the differential oracle)."""
    from ..common.knobs import knob_bool
    return knob_bool("LIGHTHOUSE_TPU_OVERLAP_BLOCK_SIGS")


def _shard_route() -> bool:
    """Route the device dispatch through the mesh-sharded BLS path?
    ``LIGHTHOUSE_TPU_BLOCK_SIG_SHARD`` forces; auto = TPU backend on a
    multi-chip mesh (a 1-device mesh would only add shard_map overhead
    over the fused single-chip pipeline)."""
    from ..common.knobs import knob_tribool
    forced = knob_tribool("LIGHTHOUSE_TPU_BLOCK_SIG_SHARD")
    if forced is not None:
        return forced
    import jax
    return jax.default_backend() == "tpu" and jax.device_count() > 1


def _device_verify(sets) -> bool:
    """The device leg handed to the resilience envelope: sharded
    K-bucketed dispatch over the mesh when routed, else the TPU
    backend's fused single-chip pipeline."""
    from ..crypto import bls
    if _shard_route():
        from ..parallel.bls_shard import bucketed_verify_signature_sets
        from ..parallel.mesh import make_mesh
        return bucketed_verify_signature_sets(sets, make_mesh())
    return bls._BACKENDS["tpu"].verify_signature_sets(sets)


class BlockSigBatch:
    """The in-flight verdict of one block's signature batch."""

    __slots__ = ("_done", "_verdict", "_error", "stats", "slot")

    def __init__(self, stats: dict, slot: Optional[int] = None):
        self._done = threading.Event()
        self._verdict = False
        self._error: Optional[BaseException] = None
        self.stats = stats
        self.slot = slot

    def done(self) -> bool:
        return self._done.is_set()

    def _complete(self, verdict: bool = False,
                  error: Optional[BaseException] = None) -> None:
        self._verdict = bool(verdict)
        self._error = error
        self._done.set()

    def join(self) -> bool:
        """Block until the verdict is in; publish the join-wait /
        overlap stats.  A verifier-side exception (one that escaped the
        envelope, i.e. a data error or a host-oracle failure) re-raises
        here, on the importing thread."""
        t0 = time.perf_counter()
        with TRACER.span("sig_join", cat="state_transition",
                         slot=self.slot) as sp:
            self._done.wait()
            wait_ms = (time.perf_counter() - t0) * 1e3
            self.stats["join_wait_ms"] = round(wait_ms, 3)
            dv = self.stats.get("device_verify_ms") or 0.0
            self.stats["overlap_efficiency"] = (
                None if dv <= 0.0
                else round(max(0.0, 1.0 - wait_ms / dv), 4))
            LAST_SIG_DISPATCH.clear()
            LAST_SIG_DISPATCH.update(self.stats)
            sp.set(join_wait_ms=self.stats["join_wait_ms"],
                   path=self.stats.get("path"),
                   verdict=self._error is None and self._verdict)
        if self._error is not None:
            raise self._error
        return self._verdict


class BlockSigDispatcher:
    """Asynchronous verifier for one block's accumulated signature sets.

    The default (module-singleton) instance routes by backend: tpu →
    sharded/fused device dispatch under the global BLS envelope,
    python/fake → direct host verify on the worker thread.  Tests and
    bench inject ``device_fn``/``host_fn`` (+ an optional pre-built
    envelope) to drive fault drills and modeled-latency devices through
    the REAL submit/join machinery."""

    def __init__(self, device_fn: Optional[Callable] = None,
                 host_fn: Optional[Callable] = None,
                 envelope=None, name: str = "block_sigs"):
        self._device_fn = device_fn
        self._host_fn = host_fn
        self._envelope = envelope
        self.name = name

    def submit(self, sets: List[object],
               slot: Optional[int] = None) -> BlockSigBatch:
        """Dedup + launch verification of ``sets`` on a worker thread;
        returns immediately with the joinable batch."""
        from ..crypto import bls
        with TRACER.span("sig_dispatch", cat="state_transition",
                         slot=slot) as sp:
            deduped, dropped = bls.dedup_signature_sets(sets)
            stats = {"sets": len(sets), "deduped": dropped,
                     "overlapped": True}
            sp.set(sets=len(sets), deduped=dropped)
            batch = BlockSigBatch(stats, slot=slot)
            ctx = TRACER.ctx() if TRACER.enabled else None
            threading.Thread(target=self._run, args=(deduped, batch, ctx),
                             name="block-sig-verify", daemon=True).start()
        return batch

    # -- worker side ---------------------------------------------------------

    def _run(self, sets, batch: BlockSigBatch, ctx) -> None:
        t0 = time.perf_counter()
        try:
            # cat stays "state_transition": this is the signature leg of
            # the block transition (the "verification_service" category
            # is reserved for the streamed gossip pipeline — a DIRECT
            # import must not fabricate that stage in its trace).
            with TRACER.span("sig_device_verify",
                             cat="state_transition", parent=ctx,
                             sets=len(sets)) as sp:
                ok, path = self._verify(sets)
                sp.set(path=path, verdict=bool(ok))
        except BaseException as e:  # noqa: BLE001 — re-raised at join
            batch.stats["device_verify_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 3)
            batch.stats["path"] = "error"
            batch._complete(False, e)
            return
        batch.stats["device_verify_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 3)
        batch.stats["path"] = path
        batch._complete(ok)

    def _verify(self, sets) -> Tuple[bool, str]:
        from ..crypto import bls
        if self._device_fn is not None:
            env = self._ensure_envelope()
            host = (self._host_fn
                    or bls._BACKENDS["python"].verify_signature_sets)
            ok, path = env.call(self._device_fn, host, (sets,))
            return bool(ok), path
        backend = bls.get_backend()
        if getattr(backend, "name", "") != "tpu":
            # Direct host-backend verify: deliberately NOT a ledger
            # dispatch — the ledger answers "what ran on the device",
            # and a python/fake verify never touched one (same rule as
            # the envelope's host-fallback path).
            return (bool(backend.verify_signature_sets(sets)),
                    getattr(backend, "name", "host"))
        from ..beacon_chain.verification_service import block_sig_dispatch
        return block_sig_dispatch(_device_verify, sets)

    def _ensure_envelope(self):
        if self._envelope is None:
            from ..beacon_chain.verification_service import (
                ResilienceEnvelope)
            self._envelope = ResilienceEnvelope(self.name, retries=1)
        return self._envelope


_DEFAULT = BlockSigDispatcher()


def get_dispatcher() -> BlockSigDispatcher:
    return _DEFAULT


def record_sync_verify(n_sets: int, deduped: int,
                       verify_ms: float) -> None:
    """Publish the SYNCHRONOUS (non-overlapped) verify's stats so the
    ``block_sigs`` stage source always reflects the most recent block —
    a sync verify IS its own join wait (overlap efficiency 0).  The
    sync path verifies UN-deduped (it is the differential oracle for
    dedup too), so ``deduped`` is 0 there."""
    LAST_SIG_DISPATCH.clear()
    LAST_SIG_DISPATCH.update(
        sets=n_sets, deduped=deduped, path="sync",
        device_verify_ms=round(verify_ms, 3),
        join_wait_ms=round(verify_ms, 3),
        overlap_efficiency=0.0, overlapped=False)
