"""Fork upgrades: phase0→altair→bellatrix→capella state migrations.

Counterpart of ``/root/reference/consensus/state_processing/src/upgrade/
{altair,merge,capella}.rs``.  Each upgrade re-homes the state into the next
fork's class, carrying fields per the spec's ``upgrade_to_*`` functions.
"""

from __future__ import annotations

import numpy as np

from ..types.chain_spec import ForkName


def upgrade_state(state, epoch: int, preset, spec, T):
    """Apply any upgrade scheduled exactly at ``epoch``."""
    fork_now = spec.fork_name_at_epoch(epoch)
    current = T.fork_of_state(state)
    while current < fork_now:
        nxt = spec.next_fork(current)
        state = _UPGRADES[nxt](state, epoch, preset, spec, T)
        current = nxt
    return state


def _carry_common(old, new, T) -> None:
    for name in type(old).FIELDS:
        if name in type(new).FIELDS and name in (
                set(type(old).FIELDS) & set(type(new).FIELDS)):
            if name == "latest_execution_payload_header":
                continue  # per-fork type; handled by the upgrade fn
            setattr(new, name, getattr(old, name))


def upgrade_to_altair(state, epoch, preset, spec, T):
    from .per_epoch import get_next_sync_committee
    new = T.BeaconStateAltair()
    _carry_common(state, new, T)
    new.fork = T.Fork(previous_version=state.fork.current_version,
                      current_version=spec.altair_fork_version,
                      epoch=epoch)
    n = len(state.validators)
    new.previous_epoch_participation = np.zeros(n, dtype=np.uint8)
    new.current_epoch_participation = np.zeros(n, dtype=np.uint8)
    new.inactivity_scores = np.zeros(n, dtype=np.uint64)
    # NOTE: the spec translates phase0 pending attestations into
    # participation flags; chains here start at altair+ so the pending lists
    # are empty (phase0 epoch processing is likewise not implemented).
    sync = get_next_sync_committee(new, preset, T)
    new.current_sync_committee = sync
    new.next_sync_committee = get_next_sync_committee(new, preset, T)
    return new


def upgrade_to_bellatrix(state, epoch, preset, spec, T):
    new = T.BeaconStateBellatrix()
    _carry_common(state, new, T)
    new.fork = T.Fork(previous_version=state.fork.current_version,
                      current_version=spec.bellatrix_fork_version,
                      epoch=epoch)
    new.latest_execution_payload_header = T.ExecutionPayloadHeaderBellatrix()
    return new


def upgrade_to_capella(state, epoch, preset, spec, T):
    new = T.BeaconStateCapella()
    _carry_common(state, new, T)
    new.fork = T.Fork(previous_version=state.fork.current_version,
                      current_version=spec.capella_fork_version,
                      epoch=epoch)
    old_h = state.latest_execution_payload_header
    new.latest_execution_payload_header = T.ExecutionPayloadHeaderCapella(
        **{f: getattr(old_h, f) for f in type(old_h).FIELDS},
        withdrawals_root=b"\x00" * 32)
    new.next_withdrawal_index = 0
    new.next_withdrawal_validator_index = 0
    new.historical_summaries = []
    return new


def upgrade_to_deneb(state, epoch, preset, spec, T):
    new = T.BeaconStateDeneb()
    _carry_common(state, new, T)
    new.fork = T.Fork(previous_version=state.fork.current_version,
                      current_version=spec.deneb_fork_version,
                      epoch=epoch)
    old_h = state.latest_execution_payload_header
    new.latest_execution_payload_header = T.ExecutionPayloadHeaderDeneb(
        **{f: getattr(old_h, f) for f in type(old_h).FIELDS},
        blob_gas_used=0, excess_blob_gas=0)
    return new


_UPGRADES = {
    ForkName.ALTAIR: upgrade_to_altair,
    ForkName.BELLATRIX: upgrade_to_bellatrix,
    ForkName.CAPELLA: upgrade_to_capella,
    ForkName.DENEB: upgrade_to_deneb,
}
