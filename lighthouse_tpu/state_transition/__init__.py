"""Pure state-transition layer.

Counterpart of ``/root/reference/consensus/state_processing`` — spec
``per_slot`` / ``per_epoch`` / ``per_block`` functions over the SoA state,
with signature sets accumulated for batched (device-dispatchable) BLS
verification.
"""

from .helpers import (
    compute_domain,
    compute_epoch_at_slot,
    compute_signing_root,
    compute_start_slot_at_epoch,
    current_epoch,
    get_active_validator_indices,
)
from .per_block import (
    BlockProcessingError,
    SignatureStrategy,
    process_block,
)
from .batch_replay import (
    EpochReplayer,
    WindowBlockInvalid,
    WindowError,
    WindowRootMismatch,
    WindowSignaturesInvalid,
    batch_replay_enabled,
    known_roots_fn,
    replay_states,
)
from .per_epoch import process_epoch
from .per_slot import (
    SlotProcessingError,
    process_slot,
    process_slots,
    state_transition,
)
from .committees import (
    get_attesting_indices,
    get_beacon_committee,
    get_beacon_proposer_index,
)
from .genesis import interop_genesis_state, interop_keypairs, interop_secret_key

__all__ = [
    "BlockProcessingError", "SignatureStrategy", "SlotProcessingError",
    "process_block", "process_epoch", "process_slot", "process_slots",
    "state_transition", "get_attesting_indices", "get_beacon_committee",
    "get_beacon_proposer_index", "interop_genesis_state", "interop_keypairs",
    "interop_secret_key", "compute_domain", "compute_epoch_at_slot",
    "compute_signing_root", "compute_start_slot_at_epoch", "current_epoch",
    "get_active_validator_indices",
    "EpochReplayer", "WindowBlockInvalid", "WindowError",
    "WindowRootMismatch", "WindowSignaturesInvalid",
    "batch_replay_enabled", "known_roots_fn", "replay_states",
]
