"""Genesis state construction — interop/deterministic path.

Counterpart of ``/root/reference/beacon_node/genesis/src/interop.rs`` and
the deterministic keypairs of ``common/eth2_interop_keypairs`` (used by
every reference test via ``beacon_chain/src/test_utils.rs:53,310-316``).
Keys follow the standard interop rule:
``privkey_i = int(sha256(uint32_le(i)).digest(), 'little') % r``.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache

import numpy as np

from ..crypto import bls as B
from ..crypto import fields as F
from ..types.chain_spec import FAR_FUTURE_EPOCH, ForkName, GENESIS_EPOCH
from ..types.validators import Validator, ValidatorRegistry

ETH1_BLOCK_HASH = b"\x42" * 32


@lru_cache(maxsize=None)
def interop_secret_key(index: int) -> B.SecretKey:
    h = hashlib.sha256(index.to_bytes(32, "little")).digest()
    return B.SecretKey(int.from_bytes(h, "little") % F.R)


@lru_cache(maxsize=None)
def interop_pubkey(index: int) -> bytes:
    return interop_secret_key(index).public_key().serialize()


def interop_keypairs(n: int) -> list[tuple[B.SecretKey, bytes]]:
    return [(interop_secret_key(i), interop_pubkey(i)) for i in range(n)]


def bls_withdrawal_credentials(pubkey: bytes) -> bytes:
    return b"\x00" + hashlib.sha256(pubkey).digest()[1:]


def interop_genesis_state(n_validators: int, genesis_time: int, preset, spec,
                          T, fork: ForkName = ForkName.CAPELLA):
    """Build a fully-active genesis state directly at ``fork`` (the
    reference builds deposits then replays them; for the hermetic harness we
    construct the registry directly, like ``interop.rs`` fast-path)."""
    from .per_epoch import get_next_sync_committee

    reg = ValidatorRegistry(n_validators)
    reg._n = n_validators
    pubs = np.zeros((n_validators, 48), dtype=np.uint8)
    creds = np.zeros((n_validators, 32), dtype=np.uint8)
    for i in range(n_validators):
        pk = interop_pubkey(i)
        pubs[i] = np.frombuffer(pk, dtype=np.uint8)
        creds[i] = np.frombuffer(bls_withdrawal_credentials(pk),
                                 dtype=np.uint8)
    reg.init_columns(
        pubkey=pubs,
        withdrawal_credentials=creds,
        effective_balance=np.full(n_validators, preset.MAX_EFFECTIVE_BALANCE,
                                  dtype=np.uint64),
        activation_eligibility_epoch=np.full(n_validators, GENESIS_EPOCH,
                                             dtype=np.uint64),
        activation_epoch=np.full(n_validators, GENESIS_EPOCH, dtype=np.uint64),
        exit_epoch=np.full(n_validators, FAR_FUTURE_EPOCH, dtype=np.uint64),
        withdrawable_epoch=np.full(n_validators, FAR_FUTURE_EPOCH,
                                   dtype=np.uint64))

    scls = T.state_cls(fork)
    state = scls()
    state.genesis_time = genesis_time
    state.fork = T.Fork(
        previous_version=spec.fork_version(fork),
        current_version=spec.fork_version(fork),
        epoch=GENESIS_EPOCH)
    state.validators = reg
    state.balances = np.full(n_validators, preset.MAX_EFFECTIVE_BALANCE,
                             dtype=np.uint64)
    for i in range(preset.EPOCHS_PER_HISTORICAL_VECTOR):
        state.randao_mixes.set(i, ETH1_BLOCK_HASH)
    state.eth1_data = T.Eth1Data(
        deposit_root=b"\x00" * 32,
        deposit_count=n_validators,
        block_hash=ETH1_BLOCK_HASH)
    state.eth1_deposit_index = n_validators

    body_root = T.body_cls(fork)().tree_hash_root()
    state.latest_block_header = T.BeaconBlockHeader(body_root=body_root)

    state.genesis_validators_root = type(state).FIELDS[
        "validators"].hash_tree_root(reg)

    if fork >= ForkName.ALTAIR:
        state.previous_epoch_participation = np.zeros(n_validators,
                                                      dtype=np.uint8)
        state.current_epoch_participation = np.zeros(n_validators,
                                                     dtype=np.uint8)
        state.inactivity_scores = np.zeros(n_validators, dtype=np.uint64)
        sync = get_next_sync_committee(state, preset, T)
        state.current_sync_committee = sync
        state.next_sync_committee = get_next_sync_committee(state, preset, T)

    if fork >= ForkName.BELLATRIX:
        # Post-merge genesis: a synthetic terminal execution header so the
        # payload chain links up (mock-EL style, ``interop`` + test_utils).
        header_cls = type(state).FIELDS["latest_execution_payload_header"]
        state.latest_execution_payload_header = header_cls(
            block_hash=ETH1_BLOCK_HASH,
            timestamp=genesis_time,
            prev_randao=ETH1_BLOCK_HASH,
        )
    return state
