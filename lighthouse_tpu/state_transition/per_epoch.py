"""Epoch processing (altair through capella), vectorized over SoA columns.

Counterpart of ``/root/reference/consensus/state_processing/src/
per_epoch_processing/{altair,capella}/`` and the shared steps in
``per_epoch_processing/*``.  Where the reference precomputes a
``ParticipationCache`` then loops validators (with rayon), every step here
is whole-column numpy arithmetic — the registry IS the batch.  The returned
:class:`EpochSummary` plays the role of ``epoch_processing_summary.rs``
(metrics/validator-monitor input).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from ..types.chain_spec import (
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    Domain,
    ForkName,
)
from .helpers import (
    compute_activation_exit_epoch,
    current_epoch,
    get_block_root,
    get_randao_mix,
    get_total_active_balance,
    has_flag,
    is_active_at,
    previous_epoch,
)
from .mutations import initiate_validator_exit, proportional_slashing_multiplier


@dataclass
class EpochSummary:
    """Per-epoch numbers for metrics/monitoring
    (``epoch_processing_summary.rs`` analogue)."""
    total_active_balance: int = 0
    previous_target_balance: int = 0
    current_target_balance: int = 0
    activated: int = 0
    ejected: int = 0
    rewards: np.ndarray | None = None
    penalties: np.ndarray | None = None


def base_reward_per_increment(total_active_balance: int, preset) -> int:
    return (preset.EFFECTIVE_BALANCE_INCREMENT * preset.BASE_REWARD_FACTOR
            // math.isqrt(total_active_balance))


def base_rewards_column(state, total_active_balance: int, preset) -> np.ndarray:
    """Vectorized spec ``get_base_reward`` for all validators."""
    per_inc = base_reward_per_increment(total_active_balance, preset)
    increments = state.validators.col("effective_balance") // np.uint64(
        preset.EFFECTIVE_BALANCE_INCREMENT)
    return increments * np.uint64(per_inc)


def eligible_validator_mask(state, preset) -> np.ndarray:
    """``get_eligible_validator_indices`` as a mask."""
    reg = state.validators
    prev = previous_epoch(state, preset)
    return (is_active_at(reg, prev)
            | (reg.col("slashed")
               & (prev + 1 < reg.col("withdrawable_epoch"))))


def unslashed_participating_mask(state, flag_index: int, epoch: int,
                                 preset) -> np.ndarray:
    """``get_unslashed_participating_indices`` as a mask."""
    if epoch == current_epoch(state, preset):
        participation = state.current_epoch_participation
    elif epoch == previous_epoch(state, preset):
        participation = state.previous_epoch_participation
    else:
        raise ValueError("epoch out of participation range")
    n = len(state.validators)
    part = np.zeros(n, dtype=np.uint8)
    part[:participation.shape[0]] = participation
    return (is_active_at(state.validators, epoch)
            & has_flag(part, flag_index)
            & ~state.validators.col("slashed"))


def _participating_balance(state, mask: np.ndarray, preset) -> int:
    bal = int(state.validators.col("effective_balance")[mask].sum())
    return max(bal, preset.EFFECTIVE_BALANCE_INCREMENT)


def is_in_inactivity_leak(state, preset) -> bool:
    finality_delay = (previous_epoch(state, preset)
                      - state.finalized_checkpoint.epoch)
    return finality_delay > preset.MIN_EPOCHS_TO_INACTIVITY_PENALTY


# ---------------------------------------------------------------------------
# Steps
# ---------------------------------------------------------------------------

def process_justification_and_finalization(state, preset, T,
                                           summary: EpochSummary) -> None:
    cur = current_epoch(state, preset)
    if cur <= GENESIS_EPOCH + 1:
        return
    prev = previous_epoch(state, preset)
    prev_target = _participating_balance(
        state, unslashed_participating_mask(
            state, TIMELY_TARGET_FLAG_INDEX, prev, preset), preset)
    cur_target = _participating_balance(
        state, unslashed_participating_mask(
            state, TIMELY_TARGET_FLAG_INDEX, cur, preset), preset)
    total = get_total_active_balance(state, preset)
    summary.total_active_balance = total
    summary.previous_target_balance = prev_target
    summary.current_target_balance = cur_target
    weigh_justification_and_finalization(state, total, prev_target,
                                         cur_target, preset, T)


def weigh_justification_and_finalization(state, total, prev_target, cur_target,
                                         preset, T) -> None:
    cur = current_epoch(state, preset)
    prev = previous_epoch(state, preset)
    old_prev_justified = state.previous_justified_checkpoint
    old_cur_justified = state.current_justified_checkpoint

    state.previous_justified_checkpoint = state.current_justified_checkpoint
    bits = state.justification_bits
    bits[1:] = bits[:-1].copy()
    bits[0] = False
    if prev_target * 3 >= total * 2:
        state.current_justified_checkpoint = T.Checkpoint(
            epoch=prev, root=get_block_root(state, prev, preset))
        bits[1] = True
    if cur_target * 3 >= total * 2:
        state.current_justified_checkpoint = T.Checkpoint(
            epoch=cur, root=get_block_root(state, cur, preset))
        bits[0] = True

    # Finalization (the four 2nd/234th-bit rules).
    if bits[1:4].all() and old_prev_justified.epoch + 3 == cur:
        state.finalized_checkpoint = old_prev_justified
    if bits[1:3].all() and old_prev_justified.epoch + 2 == cur:
        state.finalized_checkpoint = old_prev_justified
    if bits[0:3].all() and old_cur_justified.epoch + 2 == cur:
        state.finalized_checkpoint = old_cur_justified
    if bits[0:2].all() and old_cur_justified.epoch + 1 == cur:
        state.finalized_checkpoint = old_cur_justified


def process_inactivity_updates(state, preset, spec) -> None:
    if current_epoch(state, preset) == GENESIS_EPOCH:
        return
    eligible = eligible_validator_mask(state, preset)
    target = unslashed_participating_mask(
        state, TIMELY_TARGET_FLAG_INDEX, previous_epoch(state, preset), preset)
    scores = _full_column(state.inactivity_scores, len(state.validators),
                          np.uint64)
    # participating: score -= min(1, score); else score += bias
    dec = np.minimum(np.uint64(1), scores)
    scores = np.where(eligible & target, scores - dec, scores)
    scores = np.where(eligible & ~target,
                      scores + np.uint64(spec.inactivity_score_bias), scores)
    if not is_in_inactivity_leak(state, preset):
        rec = np.minimum(np.uint64(spec.inactivity_score_recovery_rate), scores)
        scores = np.where(eligible, scores - rec, scores)
    from ..types.device_state import store_column
    store_column(state, "inactivity_scores", scores,
                 touched=np.flatnonzero(eligible))


def _full_column(arr, n: int, dtype) -> np.ndarray:
    out = np.zeros(n, dtype=dtype)
    out[:arr.shape[0]] = arr
    return out


def inactivity_penalty_quotient(fork: ForkName, preset) -> int:
    if fork >= ForkName.BELLATRIX:
        return preset.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX
    if fork >= ForkName.ALTAIR:
        return preset.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
    return preset.INACTIVITY_PENALTY_QUOTIENT


def flag_deltas(state, fork: ForkName, preset, spec):
    """Per-component deltas — the EF `rewards` runner's decomposition of
    altair+ `get_flag_index_deltas` + `get_inactivity_penalty_deltas`
    (`altair/rewards_and_penalties.rs`): component name → (rewards,
    penalties) uint64 arrays for source / target / head /
    inactivity_penalty."""
    n = len(state.validators)
    prev = previous_epoch(state, preset)
    total = get_total_active_balance(state, preset)
    eligible = eligible_validator_mask(state, preset)
    base = base_rewards_column(state, total, preset)
    active_increments = total // preset.EFFECTIVE_BALANCE_INCREMENT
    in_leak = is_in_inactivity_leak(state, preset)

    out = {}
    names = ("source", "target", "head")
    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        rewards = np.zeros(n, dtype=np.uint64)
        penalties = np.zeros(n, dtype=np.uint64)
        participating = unslashed_participating_mask(
            state, flag_index, prev, preset)
        unslashed_increments = (
            _participating_balance(state, participating, preset)
            // preset.EFFECTIVE_BALANCE_INCREMENT)
        if not in_leak:
            reward_num = base * np.uint64(weight) * np.uint64(unslashed_increments)
            rewards += np.where(
                eligible & participating,
                reward_num // np.uint64(active_increments * WEIGHT_DENOMINATOR),
                np.uint64(0))
        if flag_index != TIMELY_HEAD_FLAG_INDEX:
            penalties += np.where(
                eligible & ~participating,
                base * np.uint64(weight) // np.uint64(WEIGHT_DENOMINATOR),
                np.uint64(0))
        out[names[flag_index]] = (rewards, penalties)

    # Inactivity penalties (altair formula).
    target = unslashed_participating_mask(
        state, TIMELY_TARGET_FLAG_INDEX, prev, preset)
    scores = _full_column(state.inactivity_scores, n, np.uint64)
    quotient = (spec.inactivity_score_bias
                * inactivity_penalty_quotient(fork, preset))
    inact = (state.validators.col("effective_balance") * scores
             // np.uint64(quotient))
    out["inactivity_penalty"] = (
        np.zeros(n, dtype=np.uint64),
        np.where(eligible & ~target, inact, np.uint64(0)))
    return out


def process_rewards_and_penalties(state, fork: ForkName, preset, spec,
                                  summary: EpochSummary) -> None:
    if current_epoch(state, preset) == GENESIS_EPOCH:
        return
    n = len(state.validators)
    deltas = flag_deltas(state, fork, preset, spec)
    rewards = np.zeros(n, dtype=np.uint64)
    penalties = np.zeros(n, dtype=np.uint64)
    for r, p in deltas.values():
        rewards += r
        penalties += p

    summary.rewards, summary.penalties = rewards, penalties
    bal = _full_column(state.balances, n, np.uint64)
    bal = bal + rewards
    bal = np.where(bal >= penalties, bal - penalties, np.uint64(0))
    from ..types.device_state import store_column
    store_column(state, "balances", bal,
                 touched=np.flatnonzero((rewards != 0) | (penalties != 0)))


def process_registry_updates(state, preset, spec,
                             summary: EpochSummary) -> None:
    reg = state.validators
    cur = current_epoch(state, preset)

    # Eligibility for the activation queue.
    eligible = ((reg.col("activation_eligibility_epoch")
                 == np.uint64(FAR_FUTURE_EPOCH))
                & (reg.col("effective_balance")
                   == np.uint64(preset.MAX_EFFECTIVE_BALANCE)))
    reg.wcol("activation_eligibility_epoch")[eligible] = cur + 1

    # Ejections — sequential: each consumes exit churn.
    eject = (is_active_at(reg, cur)
             & (reg.col("effective_balance")
                <= np.uint64(spec.ejection_balance)))
    for idx in np.flatnonzero(eject):
        initiate_validator_exit(state, int(idx), preset, spec)
        summary.ejected += 1

    # Activation queue: ordered by (eligibility epoch, index), churn-limited.
    queue_mask = ((reg.col("activation_eligibility_epoch")
                   <= np.uint64(state.finalized_checkpoint.epoch))
                  & (reg.col("activation_epoch")
                     == np.uint64(FAR_FUTURE_EPOCH)))
    queue = np.flatnonzero(queue_mask)
    order = np.argsort(
        reg.col("activation_eligibility_epoch")[queue], kind="stable")
    queue = queue[order]
    from .helpers import get_validator_churn_limit
    churn = get_validator_churn_limit(state, preset, spec)
    dequeued = queue[:churn]
    reg.wcol("activation_epoch")[dequeued] = compute_activation_exit_epoch(
        cur, preset.MAX_SEED_LOOKAHEAD)
    summary.activated += len(dequeued)


def process_slashings(state, fork: ForkName, preset) -> None:
    cur = current_epoch(state, preset)
    total = get_total_active_balance(state, preset)
    adjusted = min(
        int(state.slashings.sum()) * proportional_slashing_multiplier(fork, preset),
        total)
    reg = state.validators
    inc = preset.EFFECTIVE_BALANCE_INCREMENT
    mask = (reg.col("slashed")
            & (cur + preset.EPOCHS_PER_SLASHINGS_VECTOR // 2
               == reg.col("withdrawable_epoch")))
    if not mask.any():
        return
    # Per-spec integer order: (eff // inc * adjusted) // total * inc.
    # increments ≤ 32 and adjusted ≤ total_balance, so the product fits u64.
    increments = reg.col("effective_balance") // np.uint64(inc)
    penalties = (increments * np.uint64(adjusted)
                 // np.uint64(total) * np.uint64(inc))
    n = len(reg)
    bal = _full_column(state.balances, n, np.uint64)
    pen = np.where(mask, penalties, np.uint64(0))
    from ..types.device_state import store_column
    store_column(state, "balances",
                 np.where(bal >= pen, bal - pen, np.uint64(0)),
                 touched=np.flatnonzero(mask))


def process_eth1_data_reset(state, preset) -> None:
    next_epoch = current_epoch(state, preset) + 1
    if next_epoch % preset.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
        state.eth1_data_votes = []


def process_effective_balance_updates(state, preset) -> None:
    reg = state.validators
    n = len(reg)
    bal = _full_column(state.balances, n, np.uint64)
    eff = reg.col("effective_balance")
    inc = np.uint64(preset.EFFECTIVE_BALANCE_INCREMENT)
    hysteresis_inc = inc // np.uint64(preset.HYSTERESIS_QUOTIENT)
    downward = hysteresis_inc * np.uint64(preset.HYSTERESIS_DOWNWARD_MULTIPLIER)
    upward = hysteresis_inc * np.uint64(preset.HYSTERESIS_UPWARD_MULTIPLIER)
    update = (bal + downward < eff) | (eff + upward < bal)
    new_eff = np.minimum(bal - bal % inc,
                         np.uint64(preset.MAX_EFFECTIVE_BALANCE))
    reg.wcol("effective_balance")[update] = new_eff[update]


def process_slashings_reset(state, preset) -> None:
    next_epoch = current_epoch(state, preset) + 1
    state.slashings[next_epoch % preset.EPOCHS_PER_SLASHINGS_VECTOR] = 0


def process_randao_mixes_reset(state, preset) -> None:
    cur = current_epoch(state, preset)
    next_epoch = cur + 1
    state.randao_mixes.set(next_epoch % preset.EPOCHS_PER_HISTORICAL_VECTOR,
                           get_randao_mix(state, cur, preset))


def process_historical_update(state, fork: ForkName, preset, T) -> None:
    """historical_roots (pre-capella) / historical_summaries (capella+)."""
    next_epoch = current_epoch(state, preset) + 1
    if next_epoch % (preset.SLOTS_PER_HISTORICAL_ROOT
                     // preset.SLOTS_PER_EPOCH) != 0:
        return
    if fork >= ForkName.CAPELLA:
        state.historical_summaries = state.historical_summaries + [
            T.HistoricalSummary(
                block_summary_root=type(state).FIELDS["block_roots"]
                .hash_tree_root(state.block_roots),
                state_summary_root=type(state).FIELDS["state_roots"]
                .hash_tree_root(state.state_roots),
            )]
    else:
        batch = T.HistoricalBatch(block_roots=state.block_roots,
                                  state_roots=state.state_roots)
        state.historical_roots = state.historical_roots.append_root(
            batch.tree_hash_root())


def process_participation_flag_updates(state) -> None:
    n = len(state.validators)
    state.previous_epoch_participation = _full_column(
        state.current_epoch_participation, n, np.uint8)
    state.current_epoch_participation = np.zeros(n, dtype=np.uint8)


def get_next_sync_committee_indices(state, preset) -> list[int]:
    """Spec sampling: shuffled candidates + effective-balance acceptance,
    vectorized in committee-sized chunks (the scalar per-candidate loop
    cost ``2 * SHUFFLE_ROUND_COUNT`` hashes per candidate)."""
    epoch = current_epoch(state, preset) + 1
    from .helpers import get_active_validator_indices, get_seed
    from .shuffle import sample_committee_candidates
    active = get_active_validator_indices(state.validators, epoch)
    seed = get_seed(state, epoch, Domain.SYNC_COMMITTEE, preset)
    eff = state.validators.col("effective_balance")
    return sample_committee_candidates(
        eff, active.astype(np.int64), seed, preset.SHUFFLE_ROUND_COUNT,
        preset.MAX_EFFECTIVE_BALANCE, needed=preset.SYNC_COMMITTEE_SIZE)


def get_next_sync_committee(state, preset, T):
    from ..crypto import bls as B
    from ..crypto import curve as C
    indices = get_next_sync_committee_indices(state, preset)
    pubkeys = [state.validators.col("pubkey")[i].tobytes() for i in indices]
    agg = None
    for pk in pubkeys:
        agg = C.g1_add(agg, C.g1_decompress(pk))
    return T.SyncCommittee(pubkeys=pubkeys, aggregate_pubkey=C.g1_compress(agg))


def process_sync_committee_updates(state, preset, T) -> None:
    next_epoch = current_epoch(state, preset) + 1
    if next_epoch % preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0:
        state.current_sync_committee = state.next_sync_committee
        state.next_sync_committee = get_next_sync_committee(state, preset, T)


# ---------------------------------------------------------------------------
# Single-pass epoch processing
# ---------------------------------------------------------------------------

#: Stage timings (ms) of the most recent single-pass epoch transition —
#: bench.py's ``epoch_transition_ms`` decomposition.
LAST_EPOCH_TIMINGS: dict = {}


def _single_pass_enabled() -> bool:
    """Fused-epoch knob: on unless ``LIGHTHOUSE_TPU_SINGLE_PASS_EPOCH=0``
    (the stepwise path is the differential oracle)."""
    from ..common.knobs import knob_bool
    return knob_bool("LIGHTHOUSE_TPU_SINGLE_PASS_EPOCH")


def _epoch_device_enabled() -> bool:
    """``LIGHTHOUSE_TPU_EPOCH_DEVICE=1`` routes the fused rewards/inactivity
    sweep through the jitted device kernel (per_epoch_device)."""
    from ..common.knobs import knob_bool
    return knob_bool("LIGHTHOUSE_TPU_EPOCH_DEVICE")


@dataclass
class EpochContext:
    """Everything the altair+ epoch steps re-derive from the registry,
    computed once — Lighthouse's single-pass ``EpochProcessingCache`` idea:
    each column is read one time and every mask is shared."""
    prev: int
    cur: int
    active_prev: np.ndarray
    active_cur: np.ndarray
    eligible: np.ndarray
    not_slashed: np.ndarray
    prev_part: np.ndarray
    cur_part: np.ndarray
    unslashed_prev: tuple          # per participation flag, previous epoch
    target_cur: np.ndarray
    eff: np.ndarray
    total_active_balance: int
    base: np.ndarray


def build_epoch_context(state, preset) -> EpochContext:
    reg = state.validators
    n = len(reg)
    cur = current_epoch(state, preset)
    prev = previous_epoch(state, preset)
    act = reg.col("activation_epoch")
    ext = reg.col("exit_epoch")
    wd = reg.col("withdrawable_epoch")
    slashed = reg.col("slashed")
    eff = reg.col("effective_balance")
    active_prev = (act <= prev) & (prev < ext)
    active_cur = (act <= cur) & (cur < ext)
    eligible = active_prev | (slashed & (prev + 1 < wd))
    not_slashed = ~slashed
    prev_part = _full_column(state.previous_epoch_participation, n, np.uint8)
    cur_part = _full_column(state.current_epoch_participation, n, np.uint8)
    unslashed_prev = tuple(
        active_prev & ((prev_part & np.uint8(1 << f)) != 0) & not_slashed
        for f in range(len(PARTICIPATION_FLAG_WEIGHTS)))
    target_cur = (active_cur
                  & ((cur_part & np.uint8(1 << TIMELY_TARGET_FLAG_INDEX)) != 0)
                  & not_slashed)
    total = max(int(eff[active_cur].sum()),
                preset.EFFECTIVE_BALANCE_INCREMENT)
    per_inc = base_reward_per_increment(total, preset)
    base = (eff // np.uint64(preset.EFFECTIVE_BALANCE_INCREMENT)
            ) * np.uint64(per_inc)
    return EpochContext(
        prev=prev, cur=cur, active_prev=active_prev, active_cur=active_cur,
        eligible=eligible, not_slashed=not_slashed, prev_part=prev_part,
        cur_part=cur_part, unslashed_prev=unslashed_prev,
        target_cur=target_cur, eff=eff, total_active_balance=total,
        base=base)


def _participating_balance_from(eff: np.ndarray, mask: np.ndarray,
                                preset) -> int:
    return max(int(eff[mask].sum()), preset.EFFECTIVE_BALANCE_INCREMENT)


def _fused_inactivity_and_rewards(state, fork: ForkName, preset, spec,
                                  ctx: EpochContext, summary: EpochSummary,
                                  timings: dict) -> None:
    """``process_inactivity_updates`` + ``process_rewards_and_penalties`` as
    one columnar sweep over the shared context.  Bit-identical to the
    sequential steps (incl. u64 wrap/floor-division semantics): the score
    update runs first in-register, and the inactivity penalty reads the NEW
    scores, exactly as the stepwise order does."""
    import time
    n = len(state.validators)
    if ctx.cur == GENESIS_EPOCH:
        return
    in_leak = is_in_inactivity_leak(state, preset)
    target_prev = ctx.unslashed_prev[TIMELY_TARGET_FLAG_INDEX]

    if _epoch_device_enabled():
        from . import per_epoch_device as PED
        if PED.fused_sweep(state, fork, preset, spec, ctx, summary,
                           in_leak, timings):
            return

    t0 = time.perf_counter()
    scores = _full_column(state.inactivity_scores, n, np.uint64)
    dec = np.minimum(np.uint64(1), scores)
    scores = np.where(ctx.eligible & target_prev, scores - dec, scores)
    scores = np.where(ctx.eligible & ~target_prev,
                      scores + np.uint64(spec.inactivity_score_bias), scores)
    if not in_leak:
        rec = np.minimum(np.uint64(spec.inactivity_score_recovery_rate),
                         scores)
        scores = np.where(ctx.eligible, scores - rec, scores)
    from ..types.device_state import store_column
    store_column(state, "inactivity_scores", scores,
                 touched=np.flatnonzero(ctx.eligible))
    timings["inactivity_ms"] = (time.perf_counter() - t0) * 1e3

    t0 = time.perf_counter()
    rewards = np.zeros(n, dtype=np.uint64)
    penalties = np.zeros(n, dtype=np.uint64)
    active_increments = (ctx.total_active_balance
                         // preset.EFFECTIVE_BALANCE_INCREMENT)
    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        participating = ctx.unslashed_prev[flag_index]
        unslashed_increments = (
            _participating_balance_from(ctx.eff, participating, preset)
            // preset.EFFECTIVE_BALANCE_INCREMENT)
        if not in_leak:
            reward_num = (ctx.base * np.uint64(weight)
                          * np.uint64(unslashed_increments))
            rewards += np.where(
                ctx.eligible & participating,
                reward_num
                // np.uint64(active_increments * WEIGHT_DENOMINATOR),
                np.uint64(0))
        if flag_index != TIMELY_HEAD_FLAG_INDEX:
            penalties += np.where(
                ctx.eligible & ~participating,
                ctx.base * np.uint64(weight) // np.uint64(WEIGHT_DENOMINATOR),
                np.uint64(0))
    quotient = (spec.inactivity_score_bias
                * inactivity_penalty_quotient(fork, preset))
    inact = ctx.eff * scores // np.uint64(quotient)
    penalties += np.where(ctx.eligible & ~target_prev, inact, np.uint64(0))

    summary.rewards, summary.penalties = rewards, penalties
    bal = _full_column(state.balances, n, np.uint64)
    bal = bal + rewards
    bal = np.where(bal >= penalties, bal - penalties, np.uint64(0))
    store_column(state, "balances", bal,
                 touched=np.flatnonzero((rewards != 0) | (penalties != 0)))
    timings["rewards_ms"] = (time.perf_counter() - t0) * 1e3


def process_epoch_single_pass(state, fork: ForkName, preset, spec,
                              T) -> EpochSummary:
    """Altair+ epoch transition as a single columnar sweep: one
    :class:`EpochContext` build feeds justification, inactivity, and
    rewards; the remaining steps are already one-column passes.  Stage
    timings land in :data:`LAST_EPOCH_TIMINGS`."""
    import time
    summary = EpochSummary()
    timings: dict = {}
    t_all = time.perf_counter()

    t0 = time.perf_counter()
    ctx = build_epoch_context(state, preset)
    timings["context_ms"] = (time.perf_counter() - t0) * 1e3

    t0 = time.perf_counter()
    if ctx.cur > GENESIS_EPOCH + 1:
        prev_target = _participating_balance_from(
            ctx.eff, ctx.unslashed_prev[TIMELY_TARGET_FLAG_INDEX], preset)
        cur_target = _participating_balance_from(ctx.eff, ctx.target_cur,
                                                 preset)
        summary.total_active_balance = ctx.total_active_balance
        summary.previous_target_balance = prev_target
        summary.current_target_balance = cur_target
        weigh_justification_and_finalization(
            state, ctx.total_active_balance, prev_target, cur_target,
            preset, T)
    timings["justification_ms"] = (time.perf_counter() - t0) * 1e3

    _fused_inactivity_and_rewards(state, fork, preset, spec, ctx, summary,
                                  timings)

    t0 = time.perf_counter()
    process_registry_updates(state, preset, spec, summary)
    timings["registry_ms"] = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    process_slashings(state, fork, preset)
    timings["slashings_ms"] = (time.perf_counter() - t0) * 1e3
    process_eth1_data_reset(state, preset)
    t0 = time.perf_counter()
    process_effective_balance_updates(state, preset)
    timings["effective_balance_ms"] = (time.perf_counter() - t0) * 1e3
    process_slashings_reset(state, preset)
    process_randao_mixes_reset(state, preset)
    process_historical_update(state, fork, preset, T)
    process_participation_flag_updates(state)
    t0 = time.perf_counter()
    process_sync_committee_updates(state, preset, T)
    timings["shuffle_ms"] = (time.perf_counter() - t0) * 1e3

    timings["total_ms"] = (time.perf_counter() - t_all) * 1e3
    LAST_EPOCH_TIMINGS.clear()
    LAST_EPOCH_TIMINGS.update(timings)
    # Stage adapter: the epoch decomposition bench.py reads becomes
    # child spans of the enclosing epoch-transition span.
    from ..common.tracing import TRACER
    TRACER.record_stages("epoch", cat="state_transition")
    return summary


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def process_epoch_phase0(state, preset, spec, T) -> EpochSummary:
    """Phase0 epoch transition (`per_epoch_processing/base/`): the
    PendingAttestation-driven steps, then the shared tail."""
    from . import per_epoch_phase0 as P0

    summary = EpochSummary()
    P0.process_justification_and_finalization_phase0(state, preset, T,
                                                     summary)
    P0.process_rewards_and_penalties_phase0(state, preset, spec, summary)
    process_registry_updates(state, preset, spec, summary)
    process_slashings(state, ForkName.PHASE0, preset)
    process_eth1_data_reset(state, preset)
    process_effective_balance_updates(state, preset)
    process_slashings_reset(state, preset)
    process_randao_mixes_reset(state, preset)
    process_historical_update(state, ForkName.PHASE0, preset, T)
    P0.process_participation_record_updates(state)
    return summary


def process_epoch_stepwise(state, fork: ForkName, preset, spec,
                           T) -> EpochSummary:
    """Altair+ epoch transition, one step at a time, step order per
    ``per_epoch_processing/altair.rs:process_epoch`` — the differential
    oracle for :func:`process_epoch_single_pass`."""
    summary = EpochSummary()
    process_justification_and_finalization(state, preset, T, summary)
    process_inactivity_updates(state, preset, spec)
    process_rewards_and_penalties(state, fork, preset, spec, summary)
    process_registry_updates(state, preset, spec, summary)
    process_slashings(state, fork, preset)
    process_eth1_data_reset(state, preset)
    process_effective_balance_updates(state, preset)
    process_slashings_reset(state, preset)
    process_randao_mixes_reset(state, preset)
    process_historical_update(state, fork, preset, T)
    process_participation_flag_updates(state)
    process_sync_committee_updates(state, preset, T)
    return summary


def process_epoch(state, fork: ForkName, preset, spec, T) -> EpochSummary:
    """Altair+ epoch transition: the fused single-pass sweep by default,
    the stepwise oracle under ``LIGHTHOUSE_TPU_SINGLE_PASS_EPOCH=0``."""
    if fork == ForkName.PHASE0:
        return process_epoch_phase0(state, preset, spec, T)
    if not _single_pass_enabled():
        return process_epoch_stepwise(state, fork, preset, spec, T)
    return process_epoch_single_pass(state, fork, preset, spec, T)
