"""Swap-or-not shuffle, vectorized over the whole index list.

The reference ships both a per-index ``compute_shuffled_index`` and a
~250x-faster whole-list ``shuffle_list``
(``/root/reference/consensus/swap_or_not_shuffle/src/``).  Here the
whole-list form IS the per-index form applied to the vector of all indices
at once with numpy: per round, one pivot hash plus ``ceil(n/256)`` source
hashes cover every index, and the swap becomes a vectorized select.  This
keeps the semantics line-for-line equal to ``compute_shuffled_index``
(trivially auditable) while shuffling ~1M indices in tens of milliseconds.
"""

from __future__ import annotations

import hashlib

import numpy as np


def _sha(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def compute_shuffled_index(index: int, count: int, seed: bytes,
                           rounds: int) -> int:
    """Spec ``compute_shuffled_index`` (scalar ground truth)."""
    assert 0 <= index < count
    for r in range(rounds):
        pivot = int.from_bytes(_sha(seed + bytes([r]))[:8], "little") % count
        flip = (pivot + count - index) % count
        position = max(index, flip)
        source = _sha(seed + bytes([r]) + (position // 256).to_bytes(4, "little"))
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) & 1:
            index = flip
    return index


def shuffled_positions(count: int, seed: bytes, rounds: int) -> np.ndarray:
    """``perm`` with ``perm[i] = compute_shuffled_index(i, count, seed)`` for
    all ``i``, vectorized."""
    if count == 0:
        return np.zeros(0, dtype=np.uint64)
    # uint32 lanes when indices fit (any realistic registry): the round
    # loop is memory-bandwidth bound, and half-width lanes halve it.  The
    # pivot sum pivot + n - idx lies in [pivot + 1, pivot + n] < 2^32 for
    # count ≤ 2^31, so the arithmetic stays exact.
    dt = np.uint32 if count <= (1 << 31) else np.uint64
    idx = np.arange(count, dtype=dt)
    n = dt(count)
    n_blocks = (count + 255) // 256
    for r in range(rounds):
        rb = bytes([r])
        pivot = int.from_bytes(_sha(seed + rb)[:8], "little") % count
        # (pivot + n - idx) % n without the modulo: pivot + n < 2^32 is a
        # scalar, and one masked subtract replaces the division that
        # dominated the 2^20 shuffle.
        flip = dt(pivot + count) - idx
        np.subtract(flip, n, out=flip, where=flip >= n)
        position = np.maximum(idx, flip)
        # One 32-byte source block covers 256 positions.
        sources = b"".join(
            _sha(seed + rb + b.to_bytes(4, "little")) for b in range(n_blocks))
        source_bytes = np.frombuffer(sources, dtype=np.uint8)
        byte = source_bytes[position >> dt(3)]
        bit = (byte >> (position & dt(7)).astype(np.uint8)) & 1
        idx = np.where(bit.astype(bool), flip, idx)
    return idx.astype(np.uint64)


def shuffle_list(values: np.ndarray, seed: bytes, rounds: int) -> np.ndarray:
    """Shuffled copy: ``out[compute_shuffled_index(i)] = values[i]``.

    This matches the spec orientation used by ``compute_committee``:
    ``committee[i] = indices[compute_shuffled_index(i, ...)]`` reads from the
    *unshuffled* list at shuffled positions, i.e. ``values[perm]``.
    """
    perm = shuffled_positions(len(values), seed, rounds)
    return np.asarray(values)[perm.astype(np.int64)]


def shuffled_index_batch(indices: np.ndarray, count: int, seed: bytes,
                         rounds: int) -> np.ndarray:
    """``compute_shuffled_index`` for an arbitrary SUBSET of indices at once.

    Per round: one shared pivot hash plus one source hash per DISTINCT
    256-position block the subset's positions land in — for a k-candidate
    sample that is ``rounds * (1 + distinct_blocks)`` hashes instead of the
    scalar loop's ``rounds * 2 * k`` (and the numpy select replaces the
    per-index Python).  Bit-identical to the scalar form by construction.
    """
    idx = np.asarray(indices, dtype=np.uint64).copy()
    if idx.size == 0:
        return idx
    n = np.uint64(count)
    for r in range(rounds):
        rb = bytes([r])
        pivot = np.uint64(int.from_bytes(_sha(seed + rb)[:8], "little") % count)
        flip = pivot + n - idx
        flip -= n * (flip >= n)
        position = np.maximum(idx, flip)
        blocks = (position >> np.uint64(8)).astype(np.int64)
        uniq, inv = np.unique(blocks, return_inverse=True)
        src = b"".join(_sha(seed + rb + int(b).to_bytes(4, "little"))
                       for b in uniq)
        source_bytes = np.frombuffer(src, dtype=np.uint8)
        byte = source_bytes[inv * 32
                            + ((position >> np.uint64(3))
                               & np.uint64(31)).astype(np.int64)]
        bit = (byte >> (position & np.uint64(7)).astype(np.uint8)) & 1
        idx = np.where(bit.astype(bool), flip, idx)
    return idx


def candidate_random_bytes(seed: bytes, candidate_ids: np.ndarray) -> np.ndarray:
    """Spec candidate-sampling randomness, vectorized: byte ``i % 32`` of
    ``sha(seed + uint64(i // 32))`` for each candidate counter ``i`` — one
    hash per distinct 32-candidate window."""
    ids = np.asarray(candidate_ids, dtype=np.int64)
    windows = ids // 32
    uniq, inv = np.unique(windows, return_inverse=True)
    digests = b"".join(_sha(seed + int(w).to_bytes(8, "little"))
                       for w in uniq)
    pool = np.frombuffer(digests, dtype=np.uint8)
    return pool[inv * 32 + (ids % 32)]


def sample_committee_candidates(effective_balances: np.ndarray,
                                indices: np.ndarray, seed: bytes, rounds: int,
                                max_effective_balance: int, needed: int,
                                chunk: int | None = None) -> list[int]:
    """Shuffled-order candidate sampling with effective-balance acceptance,
    vectorized in chunks — the shared core of ``compute_proposer_index`` and
    sync-committee selection (both walk the same candidate sequence; only
    ``needed`` differs).  Returns the first ``needed`` accepted validator
    indices, in acceptance order."""
    assert len(indices) > 0
    total = len(indices)
    indices = np.asarray(indices, dtype=np.int64)
    if chunk is None:
        chunk = max(8, min(512, 2 * needed))
    out: list[int] = []
    i = 0
    while len(out) < needed:
        ids = np.arange(i, i + chunk, dtype=np.int64)
        shuffled = shuffled_index_batch(
            (ids % total).astype(np.uint64), total, seed, rounds)
        cands = indices[shuffled.astype(np.int64)]
        rand = candidate_random_bytes(seed, ids).astype(np.int64)
        eff = effective_balances[cands]
        if int(eff.max(initial=0)) < (1 << 55):
            ok = eff.astype(np.int64) * 255 >= max_effective_balance * rand
        else:  # un-spec-ably large balances: exact Python-int compare
            ok = np.array([int(e) * 255 >= max_effective_balance * int(rb)
                           for e, rb in zip(eff, rand)], dtype=bool)
        accepted = cands[ok]
        out.extend(int(c) for c in accepted[:needed - len(out)])
        i += chunk
    return out


def compute_proposer_index(effective_balances: np.ndarray,
                           indices: np.ndarray, seed: bytes, rounds: int,
                           max_effective_balance: int) -> int:
    """Spec ``compute_proposer_index``: shuffled-order candidate sampling with
    effective-balance acceptance (``state_processing`` helper semantics)."""
    return sample_committee_candidates(
        effective_balances, indices, seed, rounds, max_effective_balance,
        needed=1, chunk=8)[0]
