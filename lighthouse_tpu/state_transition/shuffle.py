"""Swap-or-not shuffle, vectorized over the whole index list.

The reference ships both a per-index ``compute_shuffled_index`` and a
~250x-faster whole-list ``shuffle_list``
(``/root/reference/consensus/swap_or_not_shuffle/src/``).  Here the
whole-list form IS the per-index form applied to the vector of all indices
at once with numpy: per round, one pivot hash plus ``ceil(n/256)`` source
hashes cover every index, and the swap becomes a vectorized select.  This
keeps the semantics line-for-line equal to ``compute_shuffled_index``
(trivially auditable) while shuffling ~1M indices in tens of milliseconds.
"""

from __future__ import annotations

import hashlib

import numpy as np


def _sha(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def compute_shuffled_index(index: int, count: int, seed: bytes,
                           rounds: int) -> int:
    """Spec ``compute_shuffled_index`` (scalar ground truth)."""
    assert 0 <= index < count
    for r in range(rounds):
        pivot = int.from_bytes(_sha(seed + bytes([r]))[:8], "little") % count
        flip = (pivot + count - index) % count
        position = max(index, flip)
        source = _sha(seed + bytes([r]) + (position // 256).to_bytes(4, "little"))
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) & 1:
            index = flip
    return index


def shuffled_positions(count: int, seed: bytes, rounds: int) -> np.ndarray:
    """``perm`` with ``perm[i] = compute_shuffled_index(i, count, seed)`` for
    all ``i``, vectorized."""
    if count == 0:
        return np.zeros(0, dtype=np.uint64)
    idx = np.arange(count, dtype=np.uint64)
    n = np.uint64(count)
    n_blocks = (count + 255) // 256
    for r in range(rounds):
        rb = bytes([r])
        pivot = np.uint64(
            int.from_bytes(_sha(seed + rb)[:8], "little") % count)
        flip = (pivot + n - idx) % n
        position = np.maximum(idx, flip)
        # One 32-byte source block covers 256 positions.
        sources = b"".join(
            _sha(seed + rb + b.to_bytes(4, "little")) for b in range(n_blocks))
        source_bytes = np.frombuffer(sources, dtype=np.uint8)
        byte = source_bytes[(position // np.uint64(8)).astype(np.int64)]
        bit = (byte >> (position % np.uint64(8)).astype(np.uint8)) & 1
        idx = np.where(bit.astype(bool), flip, idx)
    return idx


def shuffle_list(values: np.ndarray, seed: bytes, rounds: int) -> np.ndarray:
    """Shuffled copy: ``out[compute_shuffled_index(i)] = values[i]``.

    This matches the spec orientation used by ``compute_committee``:
    ``committee[i] = indices[compute_shuffled_index(i, ...)]`` reads from the
    *unshuffled* list at shuffled positions, i.e. ``values[perm]``.
    """
    perm = shuffled_positions(len(values), seed, rounds)
    return np.asarray(values)[perm.astype(np.int64)]


def compute_proposer_index(effective_balances: np.ndarray,
                           indices: np.ndarray, seed: bytes, rounds: int,
                           max_effective_balance: int) -> int:
    """Spec ``compute_proposer_index``: shuffled-order candidate sampling with
    effective-balance acceptance (``state_processing`` helper semantics)."""
    assert len(indices) > 0
    total = len(indices)
    i = 0
    while True:
        cand = indices[compute_shuffled_index(i % total, total, seed, rounds)]
        random_byte = _sha(seed + (i // 32).to_bytes(8, "little"))[i % 32]
        eff = int(effective_balances[cand])
        if eff * 255 >= max_effective_balance * random_byte:
            return int(cand)
        i += 1
