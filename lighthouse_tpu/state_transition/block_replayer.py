"""Block-range replay with hooks — the ``BlockReplayer`` pattern
(``/root/reference/consensus/state_processing/src/block_replayer.rs:86-305``).

Re-applies a range of blocks to a base state for state reconstruction
(store replay from ``HotStateSummary``/restore points), analytics, and the
profiling CLI.  Signature verification defaults OFF (replayed blocks were
already verified on import) and state-root computation is skipped wherever
a known root can be supplied (``state_root_fn`` — the store feeds roots it
already has on disk), matching the reference's ``state_root_iter``.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from .per_block import SignatureStrategy, process_block
from .per_slot import process_slots


class BlockReplayer:
    """Builder-style replayer: configure, then :meth:`apply_blocks`."""

    def __init__(self, state, preset, spec, T,
                 strategy: SignatureStrategy = SignatureStrategy.NO_VERIFICATION,
                 state_root_fn: Optional[Callable[[int], Optional[bytes]]] = None):
        self.state = state
        self.preset = preset
        self.spec = spec
        self.T = T
        self.strategy = strategy
        self.state_root_fn = state_root_fn
        self.pre_block_hook: Optional[Callable] = None
        self.post_block_hook: Optional[Callable] = None
        self.pre_slot_hook: Optional[Callable] = None

    def apply_blocks(self, blocks: Iterable, target_slot: Optional[int] = None):
        """Apply ``blocks`` in order (advancing slots between them), then
        optionally advance to ``target_slot``.  Returns the final state."""
        state = self.state
        for signed in blocks:
            block = signed.message
            if int(block.slot) <= int(state.slot):
                raise ValueError(
                    f"replay block slot {int(block.slot)} not after state "
                    f"slot {int(state.slot)}")
            if self.pre_slot_hook is not None:
                self.pre_slot_hook(state)
            state = process_slots(state, int(block.slot), self.preset,
                                  self.spec, self.T,
                                  state_root_fn=self.state_root_fn)
            if self.pre_block_hook is not None:
                self.pre_block_hook(state, signed)
            fork = self.spec.fork_name_at_epoch(
                int(state.slot) // self.preset.SLOTS_PER_EPOCH)
            process_block(state, signed, fork, self.preset, self.spec,
                          self.T, strategy=self.strategy)
            if self.post_block_hook is not None:
                self.post_block_hook(state, signed)
        if target_slot is not None and target_slot > int(state.slot):
            state = process_slots(state, target_slot, self.preset, self.spec,
                                  self.T, state_root_fn=self.state_root_fn)
        self.state = state
        return state
