"""Block processing: header → withdrawals/payload → randao → eth1 data →
operations → sync aggregate.

Counterpart of ``/root/reference/consensus/state_processing/src/
per_block_processing.rs:95-181`` and ``per_block_processing/
{process_operations,verify_*}.rs``.  Signature handling mirrors
``BlockSignatureStrategy`` (``per_block_processing.rs:49-58``): the caller
picks no-verification / individual / bulk; bulk accumulates every set and
verifies once via the BLS backend (one batched device dispatch).
"""

from __future__ import annotations

import enum
import math
from functools import lru_cache

import numpy as np

from ..common.safe_arith import safe_add, safe_div, safe_mul, safe_sub
from ..crypto import bls as B
from ..types.chain_spec import (
    FAR_FUTURE_EPOCH,
    PARTICIPATION_FLAG_WEIGHTS,
    PROPOSER_WEIGHT,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    BLS_WITHDRAWAL_PREFIX,
    ETH1_ADDRESS_WITHDRAWAL_PREFIX,
    Domain,
    ForkName,
)
from . import signature_sets as sigs
from .committees import (
    get_attesting_indices,
    get_beacon_proposer_index,
    get_committee_count_per_slot,
)
from .helpers import (
    compute_epoch_at_slot,
    current_epoch,
    decrease_balance,
    get_block_root,
    get_block_root_at_slot,
    get_randao_mix,
    get_total_active_balance,
    increase_balance,
    previous_epoch,
    sha,
)
from .mutations import initiate_validator_exit, slash_validator
from .per_epoch import base_rewards_column, _full_column


class BlockProcessingError(ValueError):
    pass


class InvalidSignaturesError(BlockProcessingError):
    """A block's signature verification failed — the TYPED classification
    boundary ``block_verification.py`` maps to ``InvalidSignatures``.
    Raised only by :class:`SigAccumulator` on an actual cryptographic
    verdict (bulk batch False, or an individually-verified set False); a
    non-signature ``ValueError`` whose message merely mentions
    "signature" must NOT classify as a signature failure (the old
    string-matching classifier did exactly that)."""


# Wall-time decomposition of the most recent :func:`process_block` call
# (plus the attestation sub-phases from the batched path) — the
# profiling groundwork for the <150 ms per-block target (VERDICT r5
# item 7).  Host perf_counter spans; the state-transition path is
# synchronous numpy, so spans == cost.  Surfaced by bench.py as the
# ``block_transition_ms`` phase split.
LAST_BLOCK_TIMINGS: dict = {}


def _phase(name: str, t0: float) -> float:
    import time
    t1 = time.perf_counter()
    LAST_BLOCK_TIMINGS[name] = round(
        LAST_BLOCK_TIMINGS.get(name, 0.0) + (t1 - t0) * 1e3, 3)
    return t1


class SignatureStrategy(enum.Enum):
    """``BlockSignatureStrategy`` (``per_block_processing.rs:49-58``).

    ``BATCH_DEFERRED`` extends the reference set for the epoch-batched
    replay engine (:mod:`.batch_replay`): every set is accumulated like
    ``VERIFY_BULK`` but the accumulator never verifies or dispatches on
    its own — the WINDOW owner harvests ``acc.sets`` across many blocks
    and delivers one sharded verdict that gates commit of the whole
    window."""
    NO_VERIFICATION = "no_verification"
    VERIFY_INDIVIDUAL = "verify_individual"
    VERIFY_BULK = "verify_bulk"
    VERIFY_RANDAO = "verify_randao"
    BATCH_DEFERRED = "batch_deferred"


class SigAccumulator:
    """Collects signature sets; verifies at the end (bulk) or immediately
    (individual) — the ``BlockSignatureVerifier`` accumulation pattern
    (``block_signature_verifier.rs:74-214``).

    Under ``VERIFY_BULK`` the batch can additionally be **dispatched
    early** (:meth:`dispatch`): verification then runs asynchronously on
    a worker thread (:mod:`.sig_dispatch`) while the caller finishes the
    transition, and :meth:`finish` JOINS the verdict instead of paying
    the verify serially."""

    def __init__(self, strategy: SignatureStrategy):
        self.strategy = strategy
        self.sets: list[B.SignatureSet] = []
        self._batch = None          # in-flight async verdict
        self._finished = False

    @property
    def wants_sets(self) -> bool:
        """False under NO_VERIFICATION: callers on the batched path skip
        building (and pubkey-decompressing) sets that would be dropped."""
        return self.strategy != SignatureStrategy.NO_VERIFICATION

    def add(self, sset: B.SignatureSet | None) -> None:
        if sset is None:
            return
        if self.strategy == SignatureStrategy.NO_VERIFICATION:
            return
        if self.strategy == SignatureStrategy.VERIFY_INDIVIDUAL:
            if not B.verify_signature_sets([sset]):
                raise InvalidSignaturesError("invalid signature")
            return
        if self._batch is not None:
            raise BlockProcessingError(
                "signature set added after the batch dispatched")
        self.sets.append(sset)

    def dispatch(self, dispatcher=None, slot: int | None = None) -> None:
        """Early asynchronous dispatch of the accumulated batch
        (``VERIFY_BULK`` only; no-op otherwise).  Safe to call once all
        of the block's sets are accumulated — further :meth:`add` calls
        raise."""
        if self.strategy != SignatureStrategy.VERIFY_BULK \
                or not self.sets or self._batch is not None:
            return
        from .sig_dispatch import get_dispatcher
        self._batch = (dispatcher or get_dispatcher()).submit(
            self.sets, slot=slot)

    def finish(self) -> None:
        """Deliver the batch verdict: join the async dispatch when one
        is in flight, else verify synchronously (the oracle path).
        Idempotent — the deferred-join import pipeline may reach it
        twice."""
        if self.strategy != SignatureStrategy.VERIFY_BULK or self._finished:
            return
        self._finished = True
        if self._batch is not None:
            batch, self._batch = self._batch, None
            if not batch.join():
                raise InvalidSignaturesError(
                    "bulk signature verification failed")
            return
        if self.sets:
            import time
            from . import sig_dispatch as SD
            # The synchronous path verifies the sets UN-deduped: it is
            # the knob-off differential oracle, so the one
            # verdict-affecting transform the overlapped path adds
            # (dedup_signature_sets) must stay visible to the
            # overlap-vs-sync differential suite.
            t0 = time.perf_counter()
            ok = B.verify_signature_sets(self.sets)
            SD.record_sync_verify(len(self.sets), 0,
                                  (time.perf_counter() - t0) * 1e3)
            if not ok:
                raise InvalidSignaturesError(
                    "bulk signature verification failed")


def process_block(state, signed_block, fork: ForkName, preset, spec, T,
                  strategy: SignatureStrategy = SignatureStrategy.VERIFY_BULK,
                  pubkey_cache: sigs.PubkeyCache | None = None,
                  verify_block_root: bytes | None = None,
                  payload_verifier=None, sig_dispatcher=None,
                  defer_sig_join: bool = False):
    """Apply ``signed_block.message`` to ``state`` (already slot-advanced).

    Under ``VERIFY_BULK`` with ``LIGHTHOUSE_TPU_OVERLAP_BLOCK_SIGS`` on
    (the default) the pipeline is OVERLAPPED: every signature set is
    built during the op-accumulation phase, the batch dispatches
    asynchronously (:mod:`.sig_dispatch`) before the
    participation-scatter / proposer-reward / sync-aggregate-balance /
    payload-header work, that work runs while the device verifies, and
    the verdict joins at ``acc.finish()``.  Mutation ORDER differs from
    the spec walk only in commuting ways (the deferred scatters touch
    columns no later op reads; payload-header construction has no reader
    before the post-state root) — the knob-off path is the differential
    oracle.

    ``defer_sig_join=True`` skips the final join and returns the
    :class:`SigAccumulator`: the import pipeline
    (``block_verification.ExecutedBlock``) calls ``acc.finish()`` after
    the post-state-root hash so the device batch also hides behind
    hashing.  Returns ``None`` otherwise.
    """
    import time

    if pubkey_cache is None:
        pubkey_cache = sigs.PubkeyCache()
    acc = SigAccumulator(strategy)
    block = signed_block.message
    from .sig_dispatch import overlap_enabled
    overlap = (strategy == SignatureStrategy.VERIFY_BULK
               and overlap_enabled())
    deferred: list | None = [] if overlap else None

    LAST_BLOCK_TIMINGS.clear()
    t0 = time.perf_counter()
    if strategy in (SignatureStrategy.VERIFY_INDIVIDUAL,
                    SignatureStrategy.VERIFY_BULK,
                    SignatureStrategy.BATCH_DEFERRED):
        acc.add(sigs.block_proposal_signature_set(
            state, signed_block, pubkey_cache, preset,
            block_root=verify_block_root))

    process_block_header(state, block, preset, T, deferred=deferred)
    t0 = _phase("header_ms", t0)
    if fork >= ForkName.BELLATRIX and is_execution_enabled(state, block.body):
        # Pre-merge-transition blocks carry the default payload and skip both
        # steps (``per_block_processing.rs`` is_execution_enabled gate).
        if fork >= ForkName.CAPELLA:
            process_withdrawals(state, block.body.execution_payload, preset, T)
        process_execution_payload(state, block.body, fork, preset, spec, T,
                                  payload_verifier, deferred=deferred)
    t0 = _phase("payload_ms", t0)
    process_randao(state, block, preset, acc, pubkey_cache,
                   verify=strategy != SignatureStrategy.NO_VERIFICATION)
    process_eth1_data(state, block.body.eth1_data, preset)
    t0 = _phase("randao_eth1_ms", t0)
    process_operations(state, block.body, fork, preset, spec, T, acc,
                       pubkey_cache, deferred=deferred)
    t0 = _phase("operations_ms", t0)
    if fork >= ForkName.ALTAIR:
        process_sync_aggregate(state, block.body.sync_aggregate, preset, spec,
                               T, acc, pubkey_cache=pubkey_cache,
                               deferred=deferred)
    t0 = _phase("sync_aggregate_ms", t0)
    if overlap:
        # EARLY dispatch: every signature set is accumulated; the batch
        # verifies on a worker thread while the deferred heavy host work
        # (participation scatters, proposer rewards, sync-aggregate
        # balances, payload header build) runs below.
        acc.dispatch(dispatcher=sig_dispatcher, slot=int(block.slot))
        t0 = _phase("sig_dispatch_ms", t0)
        for fn in deferred:
            fn()
        t0 = _phase("deferred_apply_ms", t0)
    from ..common.tracing import TRACER
    if defer_sig_join:
        # Stage adapter (common/tracing): the SAME dict bench.py reads
        # as `block_phase_split` becomes child spans of the enclosing
        # state-transition span — one source, two surfaces.
        TRACER.record_stages("block", cat="state_transition")
        return acc
    acc.finish()
    _phase("signature_verify_ms", t0)
    TRACER.record_stages("block", cat="state_transition")
    return None


def process_block_header(state, block, preset, T, deferred=None) -> None:
    if block.slot != state.slot:
        raise BlockProcessingError(
            f"block slot {block.slot} != state slot {state.slot}")
    if block.slot <= state.latest_block_header.slot:
        raise BlockProcessingError("block not newer than latest header")
    if block.proposer_index != get_beacon_proposer_index(state, preset):
        raise BlockProcessingError("incorrect proposer index")
    if block.parent_root != state.latest_block_header.tree_hash_root():
        raise BlockProcessingError("parent root mismatch")

    def commit() -> None:
        state.latest_block_header = T.BeaconBlockHeader(
            slot=block.slot,
            proposer_index=block.proposer_index,
            parent_root=block.parent_root,
            state_root=b"\x00" * 32,
            body_root=block.body.tree_hash_root(),
        )

    if deferred is None:
        commit()
    else:
        # The header WRITE — dominated by the body tree-hash — has no
        # reader before the post-state root (every in-block root lookup
        # reads state.block_roots, already rolled by process_slots), so
        # the overlapped pipeline parks it past the signature dispatch
        # point.  The checks above (and the slashed-proposer check
        # below) stay in spec position.
        deferred.append(commit)
    if bool(state.validators.col("slashed")[block.proposer_index]):
        raise BlockProcessingError("proposer is slashed")


def process_randao(state, block, preset, acc, pubkey_cache,
                   verify: bool = True) -> None:
    if verify:
        acc.add(sigs.randao_signature_set(state, block, pubkey_cache, preset))
    epoch = current_epoch(state, preset)
    mix = bytes(a ^ b for a, b in zip(
        get_randao_mix(state, epoch, preset), sha(block.body.randao_reveal)))
    state.randao_mixes.set(epoch % preset.EPOCHS_PER_HISTORICAL_VECTOR, mix)


def process_eth1_data(state, eth1_data, preset) -> None:
    state.eth1_data_votes = list(state.eth1_data_votes) + [eth1_data]
    votes_needed = preset.EPOCHS_PER_ETH1_VOTING_PERIOD * preset.SLOTS_PER_EPOCH
    if sum(1 for v in state.eth1_data_votes if v == eth1_data) * 2 > votes_needed:
        state.eth1_data = eth1_data


# ---------------------------------------------------------------------------
# Operations
# ---------------------------------------------------------------------------

def _batched_atts_enabled() -> bool:
    """Vectorized attestation processing knob: on unless
    ``LIGHTHOUSE_TPU_BATCHED_ATTS=0`` (the scalar spec path is the
    differential oracle — see README "State transition")."""
    from ..common.knobs import knob_bool
    return knob_bool("LIGHTHOUSE_TPU_BATCHED_ATTS")


def process_operations(state, body, fork, preset, spec, T, acc,
                       pubkey_cache, deferred=None) -> None:
    expected_deposits = min(
        preset.MAX_DEPOSITS,
        state.eth1_data.deposit_count - state.eth1_deposit_index)
    if len(body.deposits) != expected_deposits:
        raise BlockProcessingError(
            f"expected {expected_deposits} deposits, block has "
            f"{len(body.deposits)}")
    for op in body.proposer_slashings:
        process_proposer_slashing(state, op, fork, preset, spec, acc,
                                  pubkey_cache)
    for op in body.attester_slashings:
        process_attester_slashing(state, op, fork, preset, spec, acc,
                                  pubkey_cache)
    atts = list(body.attestations)
    if fork != ForkName.PHASE0 and len(atts) > 1 and _batched_atts_enabled():
        process_attestations_batched(state, atts, fork, preset, spec, T, acc,
                                     pubkey_cache, deferred=deferred)
    else:
        for op in atts:
            process_attestation(state, op, fork, preset, spec, T, acc,
                                pubkey_cache)
    for op in body.deposits:
        process_deposit(state, op, preset, spec, T)
    for op in body.voluntary_exits:
        process_voluntary_exit(state, op, fork, preset, spec, acc,
                               pubkey_cache)
    if fork >= ForkName.CAPELLA:
        for op in body.bls_to_execution_changes:
            process_bls_to_execution_change(state, op, spec, acc)


def process_proposer_slashing(state, slashing, fork, preset, spec, acc,
                              pubkey_cache) -> None:
    h1, h2 = slashing.signed_header_1.message, slashing.signed_header_2.message
    if h1.slot != h2.slot:
        raise BlockProcessingError("proposer slashing: slot mismatch")
    if h1.proposer_index != h2.proposer_index:
        raise BlockProcessingError("proposer slashing: proposer mismatch")
    if h1 == h2:
        raise BlockProcessingError("proposer slashing: identical headers")
    idx = h1.proposer_index
    epoch = current_epoch(state, preset)
    from .helpers import is_slashable_at
    if not bool(is_slashable_at(state.validators, epoch)[idx]):
        raise BlockProcessingError("proposer not slashable")
    for sh in (slashing.signed_header_1, slashing.signed_header_2):
        acc.add(sigs.block_header_signature_set(state, sh, pubkey_cache,
                                                preset))
    slash_validator(state, idx, fork, preset, spec)


def is_valid_indexed_attestation(state, indexed, preset, acc,
                                 pubkey_cache) -> None:
    indices = list(indexed.attesting_indices)
    if not indices:
        raise BlockProcessingError("indexed attestation: empty indices")
    if indices != sorted(set(indices)):
        raise BlockProcessingError("indexed attestation: not sorted/unique")
    if max(indices) >= len(state.validators):
        raise BlockProcessingError("indexed attestation: unknown validator")
    acc.add(sigs.indexed_attestation_signature_set(
        state, indices, indexed.signature, indexed.data, pubkey_cache,
        preset))


def is_slashable_attestation_data(d1, d2) -> bool:
    double = d1 != d2 and d1.target.epoch == d2.target.epoch
    surround = (d1.source.epoch < d2.source.epoch
                and d2.target.epoch < d1.target.epoch)
    return double or surround


def process_attester_slashing(state, slashing, fork, preset, spec, acc,
                              pubkey_cache) -> None:
    a1, a2 = slashing.attestation_1, slashing.attestation_2
    if not is_slashable_attestation_data(a1.data, a2.data):
        raise BlockProcessingError("attestations not slashable")
    is_valid_indexed_attestation(state, a1, preset, acc, pubkey_cache)
    is_valid_indexed_attestation(state, a2, preset, acc, pubkey_cache)
    from .helpers import is_slashable_at
    epoch = current_epoch(state, preset)
    slashable = is_slashable_at(state.validators, epoch)
    common = sorted(set(map(int, a1.attesting_indices))
                    & set(map(int, a2.attesting_indices)))
    slashed_any = False
    for idx in common:
        if bool(slashable[idx]):
            slash_validator(state, idx, fork, preset, spec)
            slashed_any = True
    if not slashed_any:
        raise BlockProcessingError("no slashable indices")


def get_attestation_participation_flag_indices(state, data, inclusion_delay,
                                               preset) -> list[int]:
    """Spec altair helper: which timeliness flags this attestation earns."""
    if data.target.epoch == current_epoch(state, preset):
        justified = state.current_justified_checkpoint
    else:
        justified = state.previous_justified_checkpoint
    if data.source != justified:
        raise BlockProcessingError("attestation source != justified checkpoint")
    is_matching_target = data.target.root == get_block_root(
        state, data.target.epoch, preset)
    is_matching_head = (is_matching_target and data.beacon_block_root
                        == get_block_root_at_slot(state, data.slot, preset))
    flags = []
    if inclusion_delay <= math.isqrt(preset.SLOTS_PER_EPOCH):
        flags.append(TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target and inclusion_delay <= preset.SLOTS_PER_EPOCH:
        flags.append(TIMELY_TARGET_FLAG_INDEX)
    if is_matching_head and inclusion_delay == preset.MIN_ATTESTATION_INCLUSION_DELAY:
        flags.append(TIMELY_HEAD_FLAG_INDEX)
    return flags


def _check_attestation_data(state, data, cur: int, prev: int, preset) -> None:
    """Shared per-attestation data validation (scalar and batched paths
    raise the same errors in the same order)."""
    if data.target.epoch not in (prev, cur):
        raise BlockProcessingError("attestation target epoch out of range")
    if data.target.epoch != compute_epoch_at_slot(data.slot,
                                                  preset.SLOTS_PER_EPOCH):
        raise BlockProcessingError("target epoch != epoch of slot")
    if not (data.slot + preset.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot
            <= data.slot + preset.SLOTS_PER_EPOCH):
        raise BlockProcessingError("attestation outside inclusion window")
    if data.index >= get_committee_count_per_slot(state, data.target.epoch,
                                                  preset):
        raise BlockProcessingError("committee index out of range")


def process_attestation(state, attestation, fork, preset, spec, T, acc,
                        pubkey_cache) -> None:
    data = attestation.data
    cur, prev = current_epoch(state, preset), previous_epoch(state, preset)
    _check_attestation_data(state, data, cur, prev, preset)

    indices = get_attesting_indices(state, data, attestation.aggregation_bits,
                                    preset)
    acc.add(sigs.indexed_attestation_signature_set(
        state, indices, attestation.signature, data, pubkey_cache, preset))

    if fork == ForkName.PHASE0:
        # Phase0 records a PendingAttestation; rewards happen per-epoch
        # (``per_block_processing/process_operations.rs`` base arm).
        if data.target.epoch == cur:
            justified = state.current_justified_checkpoint
            pending_list = state.current_epoch_attestations
        else:
            justified = state.previous_justified_checkpoint
            pending_list = state.previous_epoch_attestations
        if data.source != justified:
            raise BlockProcessingError(
                "attestation source != justified checkpoint")
        pending_list.append(T.PendingAttestation(
            aggregation_bits=attestation.aggregation_bits,
            data=data,
            inclusion_delay=state.slot - data.slot,
            proposer_index=get_beacon_proposer_index(state, preset)))
        return

    inclusion_delay = state.slot - data.slot
    flags = get_attestation_participation_flag_indices(
        state, data, inclusion_delay, preset)

    if data.target.epoch == cur:
        participation = state.current_epoch_participation
    else:
        participation = state.previous_epoch_participation
    n = len(state.validators)
    participation = _full_column(participation, n, np.uint8)

    total = get_total_active_balance(state, preset)
    base = base_rewards_column(state, total, preset)
    idx = indices.astype(np.int64)
    proposer_reward_numerator = 0
    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        if flag_index not in flags:
            continue
        bit = np.uint8(1 << flag_index)
        fresh = (participation[idx] & bit) == 0
        participation[idx] |= bit
        # `safe_arith` discipline at the spec seam: the per-flag numerator
        # is u64 math in the reference; the per-validator base rewards are
        # summed exactly in python ints first (no u64 wrap possible there).
        proposer_reward_numerator = safe_add(
            proposer_reward_numerator,
            safe_mul(int(base[idx[fresh]].sum()), weight))

    from ..types.device_state import store_column
    if data.target.epoch == cur:
        store_column(state, "current_epoch_participation", participation,
                     touched=np.unique(idx))
    else:
        store_column(state, "previous_epoch_participation", participation,
                     touched=np.unique(idx))

    proposer_reward_denominator = safe_div(
        safe_mul(safe_sub(WEIGHT_DENOMINATOR, PROPOSER_WEIGHT),
                 WEIGHT_DENOMINATOR), PROPOSER_WEIGHT)
    proposer_reward = safe_div(proposer_reward_numerator,
                               proposer_reward_denominator)
    increase_balance(state, get_beacon_proposer_index(state, preset),
                     proposer_reward)


def process_attestations_batched(state, attestations, fork, preset, spec, T,
                                 acc, pubkey_cache, deferred=None) -> None:
    """All of a block's attestations in ONE columnar pass (altair+).

    The scalar path walks one attestation and one participant at a time;
    here per-attestation *data* validation stays scalar (cheap, identical
    errors) while the per-participant work — freshness tests, participation
    flag sets, proposer-reward numerators — becomes vectorized compares and
    scatter-ORs over the concatenated attesting-index column, grouped by
    (participation epoch, flag).  Freshness ordering across attestations in
    the block is preserved exactly: within each (epoch, flag) group, only a
    validator's FIRST occurrence (in block order) can be fresh, and
    pre-block freshness comes from the unmodified participation column.
    Per-attestation integer division of the proposer numerator is kept
    (sum-then-divide would round differently).  The scalar
    :func:`process_attestation` is the differential oracle
    (``LIGHTHOUSE_TPU_BATCHED_ATTS=0``).

    Signature sets build in a SECOND pass after validation: committee
    pubkeys materialize through one ``PubkeyCache.get_many`` sweep and
    signing roots/domains are shared across attestations that reuse the
    same ``AttestationData`` — the cheap-upfront build the overlapped
    dispatch needs.  With ``deferred`` (the overlapped pipeline) the
    participation/reward application is parked there and runs AFTER the
    batch dispatches; it re-reads the participation columns at apply
    time, so interleaving with deposits (which extend the columns) is
    value-identical to the spec walk.
    """
    cur, prev = current_epoch(state, preset), previous_epoch(state, preset)
    n = len(state.validators)
    total = get_total_active_balance(state, preset)
    base_u64 = base_rewards_column(state, total, preset)
    # int64 numerator accumulation needs headroom for n participants ×
    # the SUM of flag weights (one attestation can earn all three flags
    # per fresh validator); un-spec-ably large effective balances
    # (hand-crafted states) take the exact Python-int scalar path.
    if int(base_u64.max(initial=0)) * sum(PARTICIPATION_FLAG_WEIGHTS) \
            * max(n, 1) >= 1 << 62:
        for op in attestations:
            process_attestation(state, op, fork, preset, spec, T, acc,
                                pubkey_cache)
        return
    base = base_u64.astype(np.int64)

    import time
    t0 = time.perf_counter()
    idx_parts: list[np.ndarray] = []
    counts = np.empty(len(attestations), dtype=np.int64)
    flag_bits = np.empty(len(attestations), dtype=np.uint8)
    is_cur = np.empty(len(attestations), dtype=bool)
    for a, attestation in enumerate(attestations):
        data = attestation.data
        _check_attestation_data(state, data, cur, prev, preset)
        indices = get_attesting_indices(
            state, data, attestation.aggregation_bits, preset)
        flags = get_attestation_participation_flag_indices(
            state, data, state.slot - data.slot, preset)
        idx_parts.append(indices.astype(np.int64))
        counts[a] = indices.shape[0]
        flag_bits[a] = sum(1 << f for f in flags)
        is_cur[a] = data.target.epoch == cur

    idx = np.concatenate(idx_parts) if idx_parts else np.zeros(0, np.int64)
    if acc.wants_sets:
        # Batched set build: ONE get_many sweep decompress-and-caches
        # every distinct attester pubkey (no per-index Python dict hops
        # inside the per-attestation builders), and the signing-root
        # memo shares domain/root work across same-data attestations.
        roots = sigs.AttestationSigningRoots(state, preset)
        pubkey_cache.get_many(state.validators, np.unique(idx))
        for a, attestation in enumerate(attestations):
            acc.add(sigs.indexed_attestation_signature_set(
                state, idx_parts[a], attestation.signature,
                attestation.data, pubkey_cache, preset, msg_cache=roots))
    t0 = _phase("atts_committee_resolution_ms", t0)

    seg = np.repeat(np.arange(len(attestations)), counts)
    flags_flat = np.repeat(flag_bits, counts)
    is_cur_flat = np.repeat(is_cur, counts)

    def apply() -> None:
        import time
        t0 = time.perf_counter()
        # Re-read length + columns at APPLY time: under the overlapped
        # pipeline deposits may have appended validators since the
        # gather; scatters only touch pre-existing indices, so the
        # result is value-identical to the spec interleaving.
        n_apply = len(state.validators)
        cur_part = _full_column(state.current_epoch_participation, n_apply,
                                np.uint8)
        prev_part = _full_column(state.previous_epoch_participation,
                                 n_apply, np.uint8)
        numerators = np.zeros(len(attestations), dtype=np.int64)
        for epoch_is_cur, part in ((True, cur_part), (False, prev_part)):
            epoch_sel = is_cur_flat == epoch_is_cur
            for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
                bit = np.uint8(1 << flag_index)
                pos = np.flatnonzero(epoch_sel & ((flags_flat & bit) != 0))
                if pos.size == 0:
                    continue
                sub = idx[pos]
                pre_fresh = (part[sub] & bit) == 0
                # First block-order occurrence per validator within this
                # group.
                _, first = np.unique(sub, return_index=True)
                first_occurrence = np.zeros(sub.shape[0], dtype=bool)
                first_occurrence[first] = True
                fresh = pos[pre_fresh & first_occurrence]
                np.add.at(numerators, seg[fresh], base[idx[fresh]] * weight)
                part[sub] |= bit

        # Write back only the columns the block touched (the scalar path
        # only expands/reassigns the column of each attestation's target
        # epoch).  On a device-resident state the columnar update lands
        # as a device scatter of exactly the attested indices instead of
        # a full re-stage.
        from ..types.device_state import store_column
        if is_cur.any():
            store_column(state, "current_epoch_participation", cur_part,
                         touched=np.unique(idx[is_cur_flat]))
        if not is_cur.all():
            store_column(state, "previous_epoch_participation", prev_part,
                         touched=np.unique(idx[~is_cur_flat]))
        t0 = _phase("atts_participation_update_ms", t0)

        proposer_reward_denominator = safe_div(
            safe_mul(safe_sub(WEIGHT_DENOMINATOR, PROPOSER_WEIGHT),
                     WEIGHT_DENOMINATOR), PROPOSER_WEIGHT)
        proposer_reward = sum(
            safe_div(int(num), proposer_reward_denominator)
            for num in numerators)
        increase_balance(state, get_beacon_proposer_index(state, preset),
                         proposer_reward)
        _phase("atts_proposer_reward_ms", t0)

    if deferred is None:
        apply()
    else:
        deferred.append(apply)


def is_valid_merkle_branch(leaf: bytes, branch, depth: int, index: int,
                           root: bytes) -> bool:
    value = leaf
    for i in range(depth):
        if (index >> i) & 1:
            value = sha(branch[i] + value)
        else:
            value = sha(value + branch[i])
    return value == root


def process_deposit(state, deposit, preset, spec, T) -> None:
    leaf = deposit.data.tree_hash_root()
    if not is_valid_merkle_branch(
            leaf, deposit.proof, preset.DEPOSIT_CONTRACT_TREE_DEPTH + 1,
            state.eth1_deposit_index, state.eth1_data.deposit_root):
        raise BlockProcessingError("invalid deposit merkle proof")
    state.eth1_deposit_index += 1
    apply_deposit(state, deposit.data, preset, spec, T)


def apply_deposit(state, data, preset, spec, T) -> None:
    cache = _state_pubkey_cache(state)
    index = cache.index_of(state.validators, data.pubkey)
    if index is not None:
        increase_balance(state, index, data.amount)
        return
    # New validator: verify the deposit signature; invalid => skip silently
    # (spec behaviour — bad deposits burn the ETH).
    sset = sigs.deposit_signature_set(data, T, spec.genesis_fork_version)
    try:
        if not B.verify_signature_sets([sset]):
            return
    except B.BlsError:
        return
    from ..types.validators import Validator
    amount = data.amount
    eff = min(safe_sub(amount, amount % preset.EFFECTIVE_BALANCE_INCREMENT),
              preset.MAX_EFFECTIVE_BALANCE)
    state.validators.append(Validator(
        pubkey=data.pubkey,
        withdrawal_credentials=data.withdrawal_credentials,
        effective_balance=eff,
        slashed=False,
        activation_eligibility_epoch=FAR_FUTURE_EPOCH,
        activation_epoch=FAR_FUTURE_EPOCH,
        exit_epoch=FAR_FUTURE_EPOCH,
        withdrawable_epoch=FAR_FUTURE_EPOCH,
    ))
    state.balances = np.concatenate(
        [np.asarray(state.balances, dtype=np.uint64),
         np.array([amount], dtype=np.uint64)])
    if hasattr(state, "previous_epoch_participation"):
        n = len(state.validators)
        state.previous_epoch_participation = _full_column(
            state.previous_epoch_participation, n, np.uint8)
        state.current_epoch_participation = _full_column(
            state.current_epoch_participation, n, np.uint8)
        state.inactivity_scores = _full_column(
            state.inactivity_scores, n, np.uint64)


def process_voluntary_exit(state, signed_exit, fork, preset, spec, acc,
                           pubkey_cache) -> None:
    exit = signed_exit.message
    idx = exit.validator_index
    reg = state.validators
    epoch = current_epoch(state, preset)
    if idx >= len(reg):
        raise BlockProcessingError("exit: unknown validator")
    from .helpers import is_active_at
    if not bool(is_active_at(reg, epoch)[idx]):
        raise BlockProcessingError("exit: validator not active")
    if int(reg.col("exit_epoch")[idx]) != FAR_FUTURE_EPOCH:
        raise BlockProcessingError("exit: already exiting")
    if epoch < exit.epoch:
        raise BlockProcessingError("exit: not yet valid")
    if epoch < safe_add(int(reg.col("activation_epoch")[idx]),
                        spec.shard_committee_period):
        raise BlockProcessingError("exit: validator too young")
    acc.add(sigs.voluntary_exit_signature_set(state, signed_exit,
                                              pubkey_cache, preset))
    initiate_validator_exit(state, idx, preset, spec)


def process_bls_to_execution_change(state, signed_change, spec, acc) -> None:
    change = signed_change.message
    idx = change.validator_index
    if idx >= len(state.validators):
        raise BlockProcessingError("bls change: unknown validator")
    creds = state.validators.col("withdrawal_credentials")[idx].tobytes()
    if creds[:1] != BLS_WITHDRAWAL_PREFIX:
        raise BlockProcessingError("bls change: not BLS credentials")
    if creds[1:] != sha(change.from_bls_pubkey)[1:]:
        raise BlockProcessingError("bls change: pubkey hash mismatch")
    acc.add(sigs.bls_to_execution_change_signature_set(
        state, signed_change, spec.genesis_fork_version, None))
    new = (ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11
           + change.to_execution_address)
    state.validators.wcol("withdrawal_credentials")[idx] = np.frombuffer(
        new, dtype=np.uint8)


# ---------------------------------------------------------------------------
# Sync aggregate
# ---------------------------------------------------------------------------

def process_sync_aggregate(state, aggregate, preset, spec, T, acc,
                           pubkey_cache=None, deferred=None) -> None:
    """Sync-aggregate processing, split at the signature-set boundary:
    the set (and its validity rules — non-infinity-with-empty-bits)
    builds up front so the overlapped pipeline can dispatch it with the
    block batch; the balance application parks on ``deferred`` (running
    after dispatch, before the join) or executes inline (spec order)."""
    def block_root_fn(slot):
        return get_block_root_at_slot(state, slot, preset)

    acc.add(sigs.sync_aggregate_signature_set(
        state, aggregate, state.slot, block_root_fn, preset,
        pubkey_cache=pubkey_cache))

    def apply() -> None:
        _apply_sync_aggregate_balances(state, aggregate, preset, spec)

    if deferred is None:
        apply()
    else:
        deferred.append(apply)


def _apply_sync_aggregate_balances(state, aggregate, preset, spec) -> None:
    total = get_total_active_balance(state, preset)
    from .per_epoch import base_reward_per_increment
    per_inc = base_reward_per_increment(total, preset)
    # Spec u64 math end-to-end (`safe_arith` seam): any overflow is an
    # invalid operation, never a wrapped reward.
    total_increments = safe_div(total, preset.EFFECTIVE_BALANCE_INCREMENT)
    total_base_rewards = safe_mul(per_inc, total_increments)
    max_participant_rewards = safe_div(
        safe_div(safe_mul(total_base_rewards, 2), WEIGHT_DENOMINATOR),
        preset.SLOTS_PER_EPOCH)
    participant_reward = safe_div(max_participant_rewards,
                                  preset.SYNC_COMMITTEE_SIZE)
    proposer_reward = safe_div(
        safe_mul(participant_reward, PROPOSER_WEIGHT),
        safe_sub(WEIGHT_DENOMINATOR, PROPOSER_WEIGHT))

    proposer = get_beacon_proposer_index(state, preset)
    bits = np.asarray(aggregate.sync_committee_bits, dtype=bool)
    reg = state.validators
    members = np.empty(len(state.current_sync_committee.pubkeys),
                       dtype=np.int64)
    for i, pk in enumerate(state.current_sync_committee.pubkeys):
        idx = reg.pubkey_index(bytes(pk))
        if idx is None:
            raise BlockProcessingError("sync committee pubkey not in registry")
        members[i] = idx

    # One scatter pass instead of 512 scalar balance ops.  The scalar loop's
    # only order-sensitivity is decrease-saturation at ~zero balances (a
    # validator can appear multiple times in the committee, mixing + and −);
    # when any involved balance could saturate, or the totals strain u64,
    # fall back to the exact sequential loop.
    n_bal = state.balances.shape[0]
    bal = np.asarray(state.balances, dtype=np.uint64)
    n_participants = int(bits.sum())
    safe = (participant_reward < 1 << 44
            and proposer_reward < 1 << 44
            and proposer < n_bal
            and int(members.max(initial=0)) < n_bal
            and int(bal.max(initial=0)) < 1 << 62)
    if safe:
        inc_cnt = np.bincount(members[bits], minlength=n_bal).astype(np.int64)
        dec_cnt = np.bincount(members[~bits], minlength=n_bal).astype(np.int64)
        need = dec_cnt * participant_reward
        safe = bool(np.all(bal.astype(np.int64) >= need))
    if safe:
        delta = (inc_cnt - dec_cnt) * participant_reward
        delta[proposer] += n_participants * proposer_reward
        from ..types.device_state import store_column
        store_column(state, "balances",
                     (bal.astype(np.int64) + delta).astype(np.uint64),
                     touched=np.flatnonzero(delta != 0))
    else:
        for i in range(members.shape[0]):
            idx = int(members[i])
            if bits[i]:
                increase_balance(state, idx, participant_reward)
                increase_balance(state, proposer, proposer_reward)
            else:
                decrease_balance(state, idx, participant_reward)


# ---------------------------------------------------------------------------
# Execution payload + withdrawals (bellatrix / capella)
# ---------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _default_header_root(header_cls: type) -> bytes:
    return header_cls().tree_hash_root()


def is_merge_transition_complete(state) -> bool:
    header = state.latest_execution_payload_header
    return _default_header_root(type(header)) != header.tree_hash_root()


def is_merge_transition_block(state, body) -> bool:
    payload = body.execution_payload
    return (not is_merge_transition_complete(state)
            and payload != type(payload)())


def is_execution_enabled(state, body) -> bool:
    complete = is_merge_transition_complete(state)
    return complete or body.execution_payload != type(body.execution_payload)()


def compute_timestamp_at_slot(state, spec, preset) -> int:
    return state.genesis_time + state.slot * spec.seconds_per_slot


def process_execution_payload(state, body, fork, preset, spec, T,
                              payload_verifier=None, deferred=None) -> None:
    payload = body.execution_payload
    if fork >= ForkName.DENEB and len(body.blob_kzg_commitments) > \
            preset.MAX_BLOBS_PER_BLOCK:
        raise BlockProcessingError("too many blob commitments")
    if is_merge_transition_complete(state):
        if payload.parent_hash != state.latest_execution_payload_header.block_hash:
            raise BlockProcessingError("payload parent hash mismatch")
    if payload.prev_randao != get_randao_mix(
            state, current_epoch(state, preset), preset):
        raise BlockProcessingError("payload prev_randao mismatch")
    if payload.timestamp != compute_timestamp_at_slot(state, spec, preset):
        raise BlockProcessingError("payload timestamp mismatch")
    if payload_verifier is not None:
        payload_verifier(payload)  # engine-API newPayload seam

    def commit() -> None:
        header_cls = type(state).FIELDS["latest_execution_payload_header"]
        tx_list_t = type(payload).FIELDS["transactions"]
        kw = dict(
            parent_hash=payload.parent_hash,
            fee_recipient=payload.fee_recipient,
            state_root=payload.state_root,
            receipts_root=payload.receipts_root,
            logs_bloom=payload.logs_bloom,
            prev_randao=payload.prev_randao,
            block_number=payload.block_number,
            gas_limit=payload.gas_limit,
            gas_used=payload.gas_used,
            timestamp=payload.timestamp,
            extra_data=payload.extra_data,
            base_fee_per_gas=payload.base_fee_per_gas,
            block_hash=payload.block_hash,
            transactions_root=tx_list_t.hash_tree_root(payload.transactions),
        )
        if fork >= ForkName.CAPELLA:
            wd_list_t = type(payload).FIELDS["withdrawals"]
            kw["withdrawals_root"] = wd_list_t.hash_tree_root(
                payload.withdrawals)
        if fork >= ForkName.DENEB:
            kw["blob_gas_used"] = payload.blob_gas_used
            kw["excess_blob_gas"] = payload.excess_blob_gas
        state.latest_execution_payload_header = header_cls(**kw)

    if deferred is None:
        commit()
    else:
        # The expensive half — transactions/withdrawals list hashing +
        # header construction — has no reader before the post-state
        # root, so the overlapped pipeline parks it past the signature
        # dispatch point.  The VALIDATION above stays in spec position
        # (the prev_randao check must see the pre-randao mix).
        deferred.append(commit)


def get_expected_withdrawals_scalar(state, preset) -> list:
    """Capella withdrawal sweep (spec ``get_expected_withdrawals``) — the
    scalar per-validator oracle for the vectorized sweep below."""
    epoch = current_epoch(state, preset)
    withdrawal_index = state.next_withdrawal_index
    validator_index = state.next_withdrawal_validator_index
    reg = state.validators
    n = len(reg)
    withdrawals = []
    creds = reg.col("withdrawal_credentials")
    for _ in range(min(n, preset.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)):
        if len(withdrawals) == preset.MAX_WITHDRAWALS_PER_PAYLOAD:
            break
        balance = int(state.balances[validator_index]) \
            if validator_index < state.balances.shape[0] else 0
        cred = creds[validator_index].tobytes()
        has_eth1 = cred[:1] == ETH1_ADDRESS_WITHDRAWAL_PREFIX
        wd_epoch = int(reg.col("withdrawable_epoch")[validator_index])
        eff = int(reg.col("effective_balance")[validator_index])
        if has_eth1 and wd_epoch <= epoch and balance > 0:
            withdrawals.append((withdrawal_index, validator_index,
                                cred[12:], balance))
            withdrawal_index += 1
        elif (has_eth1 and eff == preset.MAX_EFFECTIVE_BALANCE
              and balance > preset.MAX_EFFECTIVE_BALANCE):
            withdrawals.append((withdrawal_index, validator_index, cred[12:],
                                safe_sub(balance,
                                         preset.MAX_EFFECTIVE_BALANCE)))
            withdrawal_index += 1
        validator_index = (validator_index + 1) % n
    return withdrawals


def get_expected_withdrawals(state, preset) -> list:
    """Vectorized withdrawal sweep: eligibility for every swept validator in
    a handful of column compares, then the first
    ``MAX_WITHDRAWALS_PER_PAYLOAD`` hits in sweep order.  Bit-identical to
    :func:`get_expected_withdrawals_scalar` (asserted in tests)."""
    epoch = current_epoch(state, preset)
    reg = state.validators
    n = len(reg)
    if n == 0:
        return []
    sweep = min(n, preset.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
    order = ((state.next_withdrawal_validator_index
              + np.arange(sweep, dtype=np.int64)) % n)
    bal_col = np.asarray(state.balances, dtype=np.uint64)
    balance = np.where(order < bal_col.shape[0],
                       bal_col[np.minimum(order, bal_col.shape[0] - 1)]
                       if bal_col.shape[0] else np.uint64(0),
                       np.uint64(0))
    creds = reg.col("withdrawal_credentials")[order]
    has_eth1 = creds[:, 0] == ETH1_ADDRESS_WITHDRAWAL_PREFIX[0]
    wd_epoch = reg.col("withdrawable_epoch")[order]
    eff = reg.col("effective_balance")[order]
    max_eb = np.uint64(preset.MAX_EFFECTIVE_BALANCE)
    full = has_eth1 & (wd_epoch <= np.uint64(epoch)) & (balance > 0)
    partial = has_eth1 & (eff == max_eb) & (balance > max_eb)
    hits = np.flatnonzero(full | partial)[:preset.MAX_WITHDRAWALS_PER_PAYLOAD]
    withdrawals = []
    wi = state.next_withdrawal_index
    for k, t in enumerate(hits):
        amount = int(balance[t]) if full[t] \
            else safe_sub(int(balance[t]), preset.MAX_EFFECTIVE_BALANCE)
        withdrawals.append((safe_add(wi, k), int(order[t]),
                            creds[t, 12:].tobytes(), amount))
    return withdrawals


def process_withdrawals(state, payload, preset, T) -> None:
    expected = get_expected_withdrawals(state, preset)
    got = [(w.index, w.validator_index, w.address, w.amount)
           for w in payload.withdrawals]
    if got != expected:
        raise BlockProcessingError("withdrawals mismatch")
    for (_, vidx, _, amount) in expected:
        decrease_balance(state, vidx, amount)
    if expected:
        state.next_withdrawal_index = safe_add(expected[-1][0], 1)
    n = len(state.validators)
    if len(expected) == preset.MAX_WITHDRAWALS_PER_PAYLOAD:
        state.next_withdrawal_validator_index = \
            (expected[-1][1] + 1) % n
    else:
        state.next_withdrawal_validator_index = (
            state.next_withdrawal_validator_index
            + preset.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP) % n


def _state_pubkey_cache(state) -> sigs.PubkeyCache:
    cache = getattr(state, "_pubkey_cache", None)
    if cache is None:
        cache = sigs.PubkeyCache()
        state._pubkey_cache = cache
    return cache
