"""Jitted device variant of the fused epoch sweep.

One XLA program computes the inactivity-score update, the per-flag
rewards/penalties, the inactivity penalty, and the balance application for
every validator — the device-side twin of
:func:`per_epoch._fused_inactivity_and_rewards`, enabled with
``LIGHTHOUSE_TPU_EPOCH_DEVICE=1``.

Exactness: the sweep is u64 arithmetic with spec wrap/floor semantics, and
this process runs without global ``jax_enable_x64`` (the crypto kernels are
explicit-dtype 32-bit limb code).  The kernel therefore traces AND executes
inside ``jax.experimental.enable_x64()``, where jnp uint64 matches numpy
uint64 bit-for-bit (asserted against the numpy sweep in tests).  Compiles
land in the persistent compile cache (``common/compile_cache``) like every
other kernel; :func:`warmup` pre-lowers a given registry size so the first
real epoch of a fresh node is a cache hit.
"""

from __future__ import annotations

import numpy as np

from ..types.chain_spec import (
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
)

_KERNEL = None
_WARNED = False


def _get_kernel():
    """Build (once) the jitted fused sweep.  Returns None when JAX or the
    x64 context is unavailable — callers fall back to the numpy sweep."""
    global _KERNEL
    if _KERNEL is not None:
        return _KERNEL
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import enable_x64
    except Exception:  # pragma: no cover - jax always present in-tree
        return None

    def sweep(act, ext, wd, slashed, eff, prev_part, scores, balances,
              prev, bias, recovery, in_leak, per_inc, increment,
              active_increments, quotient):
        u64 = jnp.uint64
        one = u64(1)
        active_prev = (act <= prev) & (prev < ext)
        eligible = active_prev | (slashed & (prev + one < wd))
        not_slashed = ~slashed
        flags = [(prev_part & jnp.uint8(1 << f)) != 0
                 for f in range(len(PARTICIPATION_FLAG_WEIGHTS))]
        unslashed = [active_prev & fl & not_slashed for fl in flags]
        target = unslashed[TIMELY_TARGET_FLAG_INDEX]

        # inactivity scores (process_inactivity_updates order)
        dec = jnp.minimum(one, scores)
        scores = jnp.where(eligible & target, scores - dec, scores)
        scores = jnp.where(eligible & ~target, scores + bias, scores)
        rec = jnp.minimum(recovery, scores)
        scores = jnp.where(~in_leak & eligible, scores - rec, scores)

        base = (eff // increment) * per_inc
        n = eff.shape[0]
        rewards = jnp.zeros(n, dtype=u64)
        penalties = jnp.zeros(n, dtype=u64)
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            participating = unslashed[flag_index]
            part_bal = jnp.maximum(
                jnp.where(participating, eff, u64(0)).sum(dtype=u64),
                increment)
            unslashed_increments = part_bal // increment
            reward_num = base * u64(weight) * unslashed_increments
            flag_rewards = jnp.where(
                eligible & participating,
                reward_num // (active_increments * u64(WEIGHT_DENOMINATOR)),
                u64(0))
            rewards = jnp.where(in_leak, rewards, rewards + flag_rewards)
            if flag_index != TIMELY_HEAD_FLAG_INDEX:
                penalties += jnp.where(
                    eligible & ~participating,
                    base * u64(weight) // u64(WEIGHT_DENOMINATOR),
                    u64(0))
        inact = eff * scores // quotient
        penalties += jnp.where(eligible & ~target, inact, u64(0))

        balances = balances + rewards
        balances = jnp.where(balances >= penalties, balances - penalties,
                             u64(0))
        return scores, rewards, penalties, balances

    jitted = jax.jit(sweep)

    def call(*args):
        with enable_x64():
            return jitted(*args)

    _KERNEL = call
    return _KERNEL


def fused_sweep(state, fork, preset, spec, ctx, summary, in_leak: bool,
                timings: dict) -> bool:
    """Run the device sweep; True on success (state/summary updated),
    False to make the caller fall back to the numpy sweep."""
    import time
    from .per_epoch import (_full_column, base_reward_per_increment,
                            inactivity_penalty_quotient)

    kernel = _get_kernel()
    if kernel is None:
        return False
    n = len(state.validators)
    reg = state.validators
    u64 = np.uint64
    t0 = time.perf_counter()
    try:
        scores, rewards, penalties, balances = kernel(
            reg.col("activation_epoch"), reg.col("exit_epoch"),
            reg.col("withdrawable_epoch"), reg.col("slashed"),
            ctx.eff, ctx.prev_part,
            _full_column(state.inactivity_scores, n, np.uint64),
            _full_column(state.balances, n, np.uint64),
            u64(ctx.prev), u64(spec.inactivity_score_bias),
            u64(spec.inactivity_score_recovery_rate), bool(in_leak),
            u64(base_reward_per_increment(ctx.total_active_balance, preset)),
            u64(preset.EFFECTIVE_BALANCE_INCREMENT),
            u64(ctx.total_active_balance
                // preset.EFFECTIVE_BALANCE_INCREMENT),
            u64(spec.inactivity_score_bias
                * inactivity_penalty_quotient(fork, preset)))
    except Exception:
        global _WARNED
        if not _WARNED:  # surface the degradation once, then fall back
            _WARNED = True
            import logging
            logging.getLogger(__name__).warning(
                "device epoch sweep failed; falling back to numpy",
                exc_info=True)
        return False
    # summary columns are consumed by host passes either way; the state
    # columns are ADOPTED on a device-resident state (the jax outputs
    # become the columns — no pull, the next root re-reduces in HBM).
    from ..types.device_state import store_column
    summary.rewards = np.asarray(rewards, dtype=np.uint64)
    summary.penalties = np.asarray(penalties, dtype=np.uint64)
    store_column(state, "inactivity_scores", scores)
    store_column(state, "balances", balances)
    ms = (time.perf_counter() - t0) * 1e3
    timings["inactivity_ms"] = 0.0
    timings["rewards_ms"] = ms
    timings["device"] = True
    return True


def warmup(n: int) -> bool:
    """Pre-compile the sweep for an ``n``-validator registry (abstract
    shapes only); with the persistent compile cache enabled the artifact
    lands on disk for future processes."""
    kernel = _get_kernel()
    if kernel is None:
        return False
    z64 = np.zeros(n, dtype=np.uint64)
    z8 = np.zeros(n, dtype=np.uint8)
    zb = np.zeros(n, dtype=bool)
    u64 = np.uint64
    kernel(z64, z64, z64, zb, z64, z8, z64, z64,
           u64(0), u64(4), u64(16), False, u64(1), u64(10 ** 9),
           u64(1), u64(1 << 26))
    return True
