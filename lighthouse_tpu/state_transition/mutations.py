"""Registry mutations: exit initiation and slashing.

Counterpart of ``/root/reference/consensus/state_processing/src/common/
{initiate_validator_exit,slash_validator}.rs``.  These are inherently
sequential (each exit consumes churn), so they stay scalar; everything bulk
remains in the vectorized epoch steps.
"""

from __future__ import annotations

import numpy as np

from ..common.safe_arith import safe_add, safe_div, safe_mul, safe_sub
from ..types.chain_spec import (
    FAR_FUTURE_EPOCH,
    ForkName,
    PROPOSER_WEIGHT,
    WEIGHT_DENOMINATOR,
)
from .helpers import (
    compute_activation_exit_epoch,
    current_epoch,
    decrease_balance,
    get_validator_churn_limit,
    increase_balance,
)


def initiate_validator_exit(state, index: int, preset, spec) -> None:
    """Queue a validator exit behind the churn limit."""
    reg = state.validators
    if int(reg.col("exit_epoch")[index]) != FAR_FUTURE_EPOCH:
        return
    exit_epochs = reg.col("exit_epoch")
    pending = exit_epochs[exit_epochs != np.uint64(FAR_FUTURE_EPOCH)]
    exit_queue_epoch = max(
        int(pending.max()) if pending.size else 0,
        compute_activation_exit_epoch(current_epoch(state, preset),
                                      preset.MAX_SEED_LOOKAHEAD))
    exit_queue_churn = int((pending == np.uint64(exit_queue_epoch)).sum())
    if exit_queue_churn >= get_validator_churn_limit(state, preset, spec):
        exit_queue_epoch = safe_add(exit_queue_epoch, 1)
    reg.wcol("exit_epoch")[index] = exit_queue_epoch
    # `safe_add` discipline: an epoch sum past u64 is an INVALID
    # operation, not a wrapped uint64 in the column.
    reg.wcol("withdrawable_epoch")[index] = safe_add(
        exit_queue_epoch, spec.min_validator_withdrawability_delay)


def min_slashing_penalty_quotient(fork: ForkName, preset) -> int:
    if fork >= ForkName.BELLATRIX:
        return preset.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX
    if fork >= ForkName.ALTAIR:
        return preset.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR
    return preset.MIN_SLASHING_PENALTY_QUOTIENT


def proportional_slashing_multiplier(fork: ForkName, preset) -> int:
    if fork >= ForkName.BELLATRIX:
        return preset.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX
    if fork >= ForkName.ALTAIR:
        return preset.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR
    return preset.PROPORTIONAL_SLASHING_MULTIPLIER


def slash_validator(state, slashed_index: int, fork: ForkName, preset, spec,
                    whistleblower_index: int | None = None,
                    proposer_index: int | None = None) -> None:
    """Spec ``slash_validator``: exit + mark slashed + penalty + rewards."""
    from .committees import get_beacon_proposer_index

    epoch = current_epoch(state, preset)
    initiate_validator_exit(state, slashed_index, preset, spec)
    reg = state.validators
    reg.wcol("slashed")[slashed_index] = True
    reg.wcol("withdrawable_epoch")[slashed_index] = max(
        int(reg.col("withdrawable_epoch")[slashed_index]),
        safe_add(epoch, preset.EPOCHS_PER_SLASHINGS_VECTOR))
    eff = int(reg.col("effective_balance")[slashed_index])
    slot = epoch % preset.EPOCHS_PER_SLASHINGS_VECTOR
    state.slashings[slot] = np.uint64(
        safe_add(int(state.slashings[slot]), eff))
    decrease_balance(state, slashed_index,
                     safe_div(eff, min_slashing_penalty_quotient(fork,
                                                                 preset)))

    if proposer_index is None:
        proposer_index = get_beacon_proposer_index(state, preset)
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    whistleblower_reward = safe_div(eff,
                                    preset.WHISTLEBLOWER_REWARD_QUOTIENT)
    if fork >= ForkName.ALTAIR:
        proposer_reward = safe_div(
            safe_mul(whistleblower_reward, PROPOSER_WEIGHT),
            WEIGHT_DENOMINATOR)
    else:
        proposer_reward = safe_div(whistleblower_reward,
                                   preset.PROPOSER_REWARD_QUOTIENT)
    increase_balance(state, proposer_index, proposer_reward)
    increase_balance(state, whistleblower_index,
                     safe_sub(whistleblower_reward, proposer_reward))
