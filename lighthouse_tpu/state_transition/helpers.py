"""Spec accessor/predicate helpers over the SoA state.

Counterpart of the misc helpers scattered through
``/root/reference/consensus/state_processing/src/common/`` and
``consensus/types/src/beacon_state.rs`` accessor methods.  Everything that
touches the validator registry is vectorized over the SoA columns.
"""

from __future__ import annotations

import hashlib

import numpy as np

from ..common.safe_arith import safe_add, safe_mul
from ..ssz import Container, Bytes4, Bytes32
from ..types.chain_spec import (
    Domain,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
)


def sha(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


# -- epoch / slot math -------------------------------------------------------

def compute_epoch_at_slot(slot: int, slots_per_epoch: int) -> int:
    return slot // slots_per_epoch


def compute_start_slot_at_epoch(epoch: int, slots_per_epoch: int) -> int:
    return safe_mul(epoch, slots_per_epoch)


def compute_activation_exit_epoch(epoch: int, max_seed_lookahead: int = 4) -> int:
    return safe_add(safe_add(epoch, 1), max_seed_lookahead)


def current_epoch(state, preset) -> int:
    return compute_epoch_at_slot(state.slot, preset.SLOTS_PER_EPOCH)


def previous_epoch(state, preset) -> int:
    cur = current_epoch(state, preset)
    return cur - 1 if cur > GENESIS_EPOCH else GENESIS_EPOCH


# -- registry predicates (vectorized) ---------------------------------------

def is_active_at(registry, epoch: int) -> np.ndarray:
    """Boolean mask of validators active at ``epoch``."""
    return ((registry.col("activation_epoch") <= epoch)
            & (epoch < registry.col("exit_epoch")))


def get_active_validator_indices(registry, epoch: int) -> np.ndarray:
    return np.flatnonzero(is_active_at(registry, epoch)).astype(np.uint64)


def is_eligible_for_activation_queue(registry) -> np.ndarray:
    raise NotImplementedError("use mask form in per_epoch")


def is_slashable_at(registry, epoch: int) -> np.ndarray:
    """Mask: active-ish and not slashed (``is_slashable_validator``)."""
    return (~registry.col("slashed")
            & (registry.col("activation_epoch") <= epoch)
            & (epoch < registry.col("withdrawable_epoch")))


def get_total_balance(registry, indices: np.ndarray,
                      effective_balance_increment: int) -> int:
    """Sum of effective balances, floored at one increment
    (spec ``get_total_balance``)."""
    total = int(registry.col("effective_balance")[indices.astype(np.int64)].sum())
    return max(total, effective_balance_increment)


def get_total_active_balance(state, preset) -> int:
    idx = get_active_validator_indices(state.validators,
                                       current_epoch(state, preset))
    return get_total_balance(state.validators, idx,
                             preset.EFFECTIVE_BALANCE_INCREMENT)


# -- balances ---------------------------------------------------------------

def increase_balance(state, index: int, delta: int) -> None:
    """``safe_add`` discipline (`safe_arith`): a u64 overflow here is an
    INVALID operation, not a wrapped numpy value silently entering the
    balance column."""
    from ..common.safe_arith import safe_add
    state.balances[index] = np.uint64(
        safe_add(int(state.balances[index]), delta))


def decrease_balance(state, index: int, delta: int) -> None:
    """``saturating_sub`` per spec (balances clamp at zero)."""
    from ..common.safe_arith import saturating_sub
    state.balances[index] = np.uint64(
        saturating_sub(int(state.balances[index]), delta))


# -- roots / mixes / seeds ---------------------------------------------------

def get_block_root_at_slot(state, slot: int, preset) -> bytes:
    if not slot < state.slot <= slot + preset.SLOTS_PER_HISTORICAL_ROOT:
        raise ValueError(f"slot {slot} out of block-roots range at "
                         f"state slot {state.slot}")
    return state.block_roots.get(slot % preset.SLOTS_PER_HISTORICAL_ROOT)


def get_block_root(state, epoch: int, preset) -> bytes:
    return get_block_root_at_slot(
        state, compute_start_slot_at_epoch(epoch, preset.SLOTS_PER_EPOCH),
        preset)


def get_randao_mix(state, epoch: int, preset) -> bytes:
    return state.randao_mixes.get(epoch % preset.EPOCHS_PER_HISTORICAL_VECTOR)


def get_seed(state, epoch: int, domain_type: Domain, preset) -> bytes:
    mix = get_randao_mix(
        state,
        epoch + preset.EPOCHS_PER_HISTORICAL_VECTOR - preset.MIN_SEED_LOOKAHEAD - 1,
        preset)
    return sha(domain_type.value + epoch.to_bytes(8, "little") + mix)


# -- domains / signing roots -------------------------------------------------

class _ForkData(Container):
    current_version: Bytes4
    genesis_validators_root: Bytes32


class _SigningData(Container):
    object_root: Bytes32
    domain: Bytes32


def compute_fork_data_root(current_version: bytes,
                           genesis_validators_root: bytes) -> bytes:
    return _ForkData(
        current_version=current_version,
        genesis_validators_root=genesis_validators_root).tree_hash_root()


def compute_fork_digest(current_version: bytes,
                        genesis_validators_root: bytes) -> bytes:
    return compute_fork_data_root(current_version, genesis_validators_root)[:4]


def compute_domain(domain_type: Domain, fork_version: bytes = bytes(4),
                   genesis_validators_root: bytes = bytes(32)) -> bytes:
    fork_data_root = compute_fork_data_root(fork_version,
                                            genesis_validators_root)
    return domain_type.value + fork_data_root[:28]


def get_domain(state, domain_type: Domain, epoch: int | None, preset) -> bytes:
    """``BeaconState::get_domain`` (``types/src/beacon_state.rs``)."""
    if epoch is None:
        epoch = current_epoch(state, preset)
    fork_version = (state.fork.previous_version if epoch < state.fork.epoch
                    else state.fork.current_version)
    return compute_domain(domain_type, fork_version,
                          state.genesis_validators_root)


def compute_signing_root(obj, domain: bytes) -> bytes:
    root = obj if isinstance(obj, bytes) else obj.tree_hash_root()
    return _SigningData(object_root=root, domain=domain).tree_hash_root()


# -- churn -------------------------------------------------------------------

def get_validator_churn_limit(state, preset, spec) -> int:
    active = int(is_active_at(state.validators,
                              current_epoch(state, preset)).sum())
    return max(spec.min_per_epoch_churn_limit,
               active // spec.churn_limit_quotient)


# -- participation flags -----------------------------------------------------

def has_flag(flags: np.ndarray | int, flag_index: int):
    bit = 1 << flag_index
    if isinstance(flags, np.ndarray):
        return (flags & np.uint8(bit)) != 0
    return (flags & bit) != 0


def add_flag(flags, flag_index: int):
    if isinstance(flags, np.ndarray):
        return flags | np.uint8(1 << flag_index)
    return flags | (1 << flag_index)
