"""Committee cache: one whole-epoch shuffle serving every lookup.

Counterpart of the reference's ``CommitteeCache``
(``/root/reference/consensus/types/src/beacon_state/committee_cache.rs``):
the active-index list is shuffled ONCE per (state, epoch) with the
vectorized swap-or-not shuffle, and every ``get_beacon_committee`` call is a
slice of the cached permutation — the same ~250x trick the reference credits
its ``shuffle_list`` with (``swap_or_not_shuffle/src/compute_shuffled_index.rs:11``).
Caches attach to the state object lazily and are dropped by ``copy()``
(fresh states recompute, mirroring ``BeaconState``'s non-SSZ cache fields).
"""

from __future__ import annotations

import numpy as np

from ..common.metrics import REGISTRY
from ..types.chain_spec import Domain
from .helpers import (
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
    current_epoch,
    get_active_validator_indices,
    get_seed,
    sha,
)
from .shuffle import compute_proposer_index, shuffled_positions


class CommitteeCache:
    """Shuffling for one epoch: ``shuffled[i] = active[perm[i]]``."""

    def __init__(self, state, epoch: int, preset):
        self.epoch = epoch
        self.active = get_active_validator_indices(state.validators, epoch)
        self.seed = get_seed(state, epoch, Domain.BEACON_ATTESTER, preset)
        perm = shuffled_positions(len(self.active), self.seed,
                                  preset.SHUFFLE_ROUND_COUNT)
        self.shuffled = self.active[perm.astype(np.int64)]
        self.committees_per_slot = committees_per_slot_count(
            len(self.active), preset)
        self.slots_per_epoch = preset.SLOTS_PER_EPOCH

    def committee(self, slot: int, index: int) -> np.ndarray:
        """``get_beacon_committee`` slice (spec ``compute_committee``)."""
        count = self.committees_per_slot * self.slots_per_epoch
        i = (slot % self.slots_per_epoch) * self.committees_per_slot + index
        n = len(self.shuffled)
        start = n * i // count
        end = n * (i + 1) // count
        return self.shuffled[start:end]

    def committees_at_slot(self, slot: int) -> list[np.ndarray]:
        return [self.committee(slot, i)
                for i in range(self.committees_per_slot)]


def committees_per_slot_count(active_count: int, preset) -> int:
    return max(1, min(
        preset.MAX_COMMITTEES_PER_SLOT,
        active_count // preset.SLOTS_PER_EPOCH // preset.TARGET_COMMITTEE_SIZE))


# Shuffle-cache observability: every whole-epoch shuffle costs a full
# active-set permutation, and until now the cache was blind — a
# hit-rate collapse (state copies dropping caches, committee churn)
# was invisible.  Bounded cardinality: one family, two outcomes.
_SHUFFLE_CACHE_REQS = REGISTRY.counter(
    "shuffle_cache_requests_total",
    "whole-epoch committee shuffle cache lookups",
    labelnames=("outcome",))


def get_committee_cache(state, epoch: int, preset) -> CommitteeCache:
    """Relative-epoch cache (previous/current/next), attached to the state
    like the reference's ``committee_caches`` field
    (``types/src/beacon_state.rs:338`` area)."""
    caches = getattr(state, "_committee_caches", None)
    if caches is None:
        caches = {}
        state._committee_caches = caches
    cache = caches.get(epoch)
    if cache is None:
        cur = current_epoch(state, preset)
        if not cur - 1 <= epoch <= cur + 1:
            raise ValueError(
                f"committee cache only covers epochs {cur - 1}..{cur + 1}, "
                f"requested {epoch}")
        _SHUFFLE_CACHE_REQS.labels("miss").inc()
        cache = CommitteeCache(state, epoch, preset)
        caches[epoch] = cache
    else:
        _SHUFFLE_CACHE_REQS.labels("hit").inc()
    return cache


def get_beacon_committee(state, slot: int, index: int, preset) -> np.ndarray:
    epoch = compute_epoch_at_slot(slot, preset.SLOTS_PER_EPOCH)
    return get_committee_cache(state, epoch, preset).committee(slot, index)


def get_committee_count_per_slot(state, epoch: int, preset) -> int:
    return get_committee_cache(state, epoch, preset).committees_per_slot


def get_beacon_proposer_index(state, preset, slot: int | None = None) -> int:
    """Spec ``get_beacon_proposer_index`` (per-slot seed + balance-weighted
    sampling).  Memoized per (slot) like ``ConsensusContext``
    (``state_processing/src/consensus_context.rs:12-49``)."""
    if slot is None:
        slot = state.slot
    memo = getattr(state, "_proposer_memo", None)
    if memo is None:
        memo = {}
        state._proposer_memo = memo
    if slot in memo:
        return memo[slot]
    epoch = compute_epoch_at_slot(slot, preset.SLOTS_PER_EPOCH)
    seed = sha(get_seed(state, epoch, Domain.BEACON_PROPOSER, preset)
               + int(slot).to_bytes(8, "little"))
    indices = get_active_validator_indices(state.validators, epoch)
    proposer = compute_proposer_index(
        state.validators.col("effective_balance"), indices, seed,
        preset.SHUFFLE_ROUND_COUNT, preset.MAX_EFFECTIVE_BALANCE)
    memo[slot] = proposer
    return proposer


def get_attesting_indices(state, data, aggregation_bits, preset) -> np.ndarray:
    """Committee members whose aggregation bit is set
    (``state_processing/src/common/get_attesting_indices.rs``)."""
    committee = get_beacon_committee(state, data.slot, data.index, preset)
    bits = np.asarray(aggregation_bits, dtype=bool)
    if bits.shape[0] != len(committee):
        raise ValueError("aggregation bitlist length != committee size")
    return committee[bits]


def compute_subnet_for_attestation(state, att_data, preset) -> int:
    """Gossip subnet of an unaggregated attestation
    (spec `compute_subnet_for_attestation`; the reference's
    `lighthouse_network` subnet_id) — committee offset within the epoch
    modulo the 64 attestation subnets."""
    slot = int(att_data.slot)
    committees_per_slot = get_committee_count_per_slot(
        state, slot // preset.SLOTS_PER_EPOCH, preset)
    slots_since_epoch_start = slot % preset.SLOTS_PER_EPOCH
    committees_since_epoch_start = committees_per_slot * slots_since_epoch_start
    return int((committees_since_epoch_start + int(att_data.index))
               % preset.ATTESTATION_SUBNET_COUNT)
