"""Epoch-batched replay: a WINDOW of already-downloaded blocks applied
as one device-program-shaped unit instead of N serial imports.

The serial :class:`~.block_replayer.BlockReplayer` applies one block at a
time through the full import path — per-block signature dispatch,
per-slot state-root hashing — so catching up from months behind runs at
host rate while the sharded BLS path and the device-resident columns sit
idle.  :class:`EpochReplayer` fuses three things across the window
(Lighthouse ``block_replayer.rs`` generalized to a batch):

1. **Signatures** — every block runs under
   ``SignatureStrategy.BATCH_DEFERRED``: the per-block
   :class:`~.per_block.SigAccumulator` collects its sets without
   verifying, the window owner concatenates them and dispatches ONE
   batch through :mod:`.sig_dispatch` (mesh-sharded ``parallel/bls_shard``
   on a TPU backend).  The verdict gates commit of the WHOLE window; on
   ``False`` the per-block set slices are re-verified serially to name
   the exact offending block (:class:`WindowSignaturesInvalid`).
2. **State roots** — per-slot ``tree_hash_root`` collapses to known
   roots: the caller's ``state_root_fn`` (store-fed) where present, else
   the blocks' own claimed ``state_root``s; ONE root is computed at the
   window boundary and checked against the final block's claim.  On
   mismatch the serial :class:`BlockReplayer` oracle re-runs from the
   saved pre-state with full hashing to bisect the offending block
   (:class:`WindowRootMismatch`).
3. **Scatters** — the participation/balance/inactivity column writes of
   the whole window land on the device-resident state
   (``types/device_state.py`` coalesces dirty indices across blocks; the
   epoch sweep at window-internal boundaries is the existing single-pass
   path), so the window compiles to a handful of device programs.

Timings for the last window land in :data:`LAST_REPLAY_TIMINGS`
(``collect_ms`` / ``apply_ms`` / ``root_ms`` / ``verify_ms``), surfaced
via ``tracing.stage_split("replay")`` and a ``replay`` device-ledger
family with a per-window transfer budget
(:data:`~..common.device_ledger.REPLAY_WINDOW_BUDGET`).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..common.knobs import knob_tribool
from ..crypto import bls as B
from .block_replayer import BlockReplayer
from .per_block import (
    BlockProcessingError,
    SignatureStrategy,
    process_block,
)
from .per_slot import process_slots

# Windows shorter than this stay serial under the "auto" knob setting:
# one dispatch + one boundary root amortize over too few blocks to beat
# the plain path.
MIN_BATCH_WINDOW = 4

# Wall-time decomposition of the most recent replay window plus the
# cumulative window counters — read via tracing.stage_split("replay").
# ``*_ms`` keys become child spans of the enclosing span; ``path`` is
# "batched" / "serial" / "fell_back".
LAST_REPLAY_TIMINGS: dict = {}

# Cumulative across windows (merged into the stage dict on publish):
# the simulator's partition-heal scenario asserts batched_windows > 0
# to prove the healed node actually caught up through this path.
_COUNTERS = {"batched_windows": 0, "serial_windows": 0, "fallbacks": 0}


def batch_replay_enabled(n_blocks: Optional[int] = None) -> bool:
    """Resolve the ``LIGHTHOUSE_TPU_BATCH_REPLAY`` tribool: forced
    on/off wins; auto batches windows of >= :data:`MIN_BATCH_WINDOW`."""
    forced = knob_tribool("LIGHTHOUSE_TPU_BATCH_REPLAY")
    if forced is not None:
        return forced
    return n_blocks is None or n_blocks >= MIN_BATCH_WINDOW


def known_roots_fn(blocks: Sequence) -> Callable[[int], Optional[bytes]]:
    """``state_root_fn`` from a block window's CLAIMED state roots: the
    post-state at a block's slot has exactly that block's
    ``message.state_root`` (empty slots return None and fall back to
    hashing).  Safe for already-imported chains (the claim was checked
    at import); untrusted windows are caught by the boundary-root check
    + serial bisect."""
    roots = {int(b.message.slot): bytes(b.message.state_root)
             for b in blocks}
    return lambda slot: roots.get(int(slot))


class WindowError(BlockProcessingError):
    """Batched-window failure naming the offending block where known."""

    def __init__(self, msg: str, *, block_root: Optional[bytes] = None,
                 slot: Optional[int] = None):
        super().__init__(msg)
        self.block_root = block_root
        self.slot = slot


class WindowSignaturesInvalid(WindowError):
    """The window batch verdict was False; bisect named the block."""


class WindowRootMismatch(WindowError):
    """Boundary root disagreed with the final claim; the serial oracle
    named the block whose claimed state_root is wrong."""


class WindowBlockInvalid(WindowError):
    """A block failed the state transition itself (bad proposer, bad
    operation, …) while applying the window."""


def _set_bytes(sets: Sequence[B.SignatureSet]) -> int:
    # Marshalled device footprint: 32 B message + 96 B signature +
    # 48 B per signing key (compressed points; decompression happens
    # on-device in the sharded path).
    return sum(32 + 96 + 48 * len(s.signing_keys) for s in sets)


def _publish(timings: dict) -> None:
    from ..common.tracing import TRACER
    LAST_REPLAY_TIMINGS.clear()
    LAST_REPLAY_TIMINGS.update(timings)
    LAST_REPLAY_TIMINGS.update(_COUNTERS)
    TRACER.record_stages("replay", cat="state_transition")


class EpochReplayer:
    """Builder-style batched replayer: configure, then
    :meth:`apply_window`.

    ``verify_signatures=True`` collects every block's sets and verifies
    them as ONE batch whose verdict gates the whole window; off, the
    window replays trusted blocks (store rebuild) with no signature
    work.  ``state_root_fn`` supplies store-known roots; the blocks' own
    claimed roots fill the gaps.  ``post_block_hook(state, signed)``
    fires after each block's transition (callers snapshot per-block
    post-states for import) — note the hook runs BEFORE the window
    verdict; consumers must not commit snapshots until
    :meth:`apply_window` returns.
    """

    def __init__(self, state, preset, spec, T, *,
                 verify_signatures: bool = False,
                 state_root_fn: Optional[Callable[[int], Optional[bytes]]] = None,
                 pubkey_cache=None,
                 sig_dispatcher=None,
                 boundary_root_check: bool = True,
                 fallback: bool = True):
        self.state = state
        self.preset = preset
        self.spec = spec
        self.T = T
        self.verify_signatures = verify_signatures
        self.state_root_fn = state_root_fn
        self.pubkey_cache = pubkey_cache
        self.sig_dispatcher = sig_dispatcher
        self.boundary_root_check = boundary_root_check
        self.fallback = fallback
        self.post_block_hook: Optional[Callable] = None

    # -- internals ----------------------------------------------------

    def _root_fn(self, blocks: Sequence) -> Callable[[int], Optional[bytes]]:
        known = known_roots_fn(blocks)
        caller = self.state_root_fn
        if caller is None:
            return known
        return lambda slot: caller(slot) or known(slot)

    def _apply(self, state, blocks: Sequence, root_fn, strategy,
               sets: Optional[List[B.SignatureSet]],
               slices: Optional[List[Tuple[int, int, int, int]]]):
        """The fused forward pass.  Mutates ``state`` through the window;
        harvests each block's signature sets into ``sets`` with per-block
        ``(index, slot, start, end)`` slices for the bisect path."""
        for i, signed in enumerate(blocks):
            slot = int(signed.message.slot)
            if slot <= int(state.slot):
                raise ValueError(
                    f"window block slot {slot} not after state slot "
                    f"{int(state.slot)}")
            state = process_slots(state, slot, self.preset, self.spec,
                                  self.T, state_root_fn=root_fn)
            fork = self.spec.fork_name_at_epoch(
                slot // self.preset.SLOTS_PER_EPOCH)
            try:
                acc = process_block(
                    state, signed, fork, self.preset, self.spec, self.T,
                    strategy=strategy, pubkey_cache=self.pubkey_cache,
                    defer_sig_join=True)
            except WindowError:
                raise
            except (BlockProcessingError, ValueError) as e:
                raise WindowBlockInvalid(
                    f"block at slot {slot} failed the window transition: "
                    f"{e}", slot=slot,
                    block_root=bytes(signed.message.tree_hash_root()),
                ) from e
            if sets is not None and acc is not None and acc.sets:
                start = len(sets)
                sets.extend(acc.sets)
                slices.append((i, slot, start, len(sets)))
            if self.post_block_hook is not None:
                self.post_block_hook(state, signed)
        return state

    def _bisect_signatures(self, blocks, sets, slices) -> None:
        """Batch verdict was False: re-verify per-block slices serially
        to name the offender (the differential tests pin exactness)."""
        for i, slot, start, end in slices:
            if not B.verify_signature_sets(sets[start:end]):
                raise WindowSignaturesInvalid(
                    f"window signature batch invalid: block at slot "
                    f"{slot} (index {i}) fails",
                    slot=slot,
                    block_root=bytes(blocks[i].message.tree_hash_root()))
        # Every slice passes individually yet the batch failed — a
        # backend inconsistency, not a nameable block.  Still reject.
        raise WindowSignaturesInvalid(
            "window signature batch invalid (no single block names the "
            "failure)")

    def _bisect_roots(self, pre_state, blocks, target_slot):
        """Boundary root mismatched: replay serially from the saved
        pre-state with FULL hashing, checking each block's claimed
        state_root, to name the first lying block.  If every claim
        matches the serial computation, the batched path itself diverged
        — the serial state is authoritative (``path="fell_back"``)."""
        _COUNTERS["fallbacks"] += 1
        state = pre_state
        rep = BlockReplayer(state, self.preset, self.spec, self.T,
                            strategy=SignatureStrategy.NO_VERIFICATION)
        for signed in blocks:
            rep.apply_blocks([signed])
            computed = bytes(rep.state.tree_hash_root())
            claimed = bytes(signed.message.state_root)
            if computed != claimed:
                raise WindowRootMismatch(
                    f"block at slot {int(signed.message.slot)} claims "
                    f"state root {claimed.hex()[:16]}… but the serial "
                    f"oracle computes {computed.hex()[:16]}…",
                    slot=int(signed.message.slot),
                    block_root=bytes(signed.message.tree_hash_root()))
        if target_slot is not None and target_slot > int(rep.state.slot):
            rep.apply_blocks([], target_slot=target_slot)
        return rep.state

    # -- public -------------------------------------------------------

    def apply_window(self, blocks: Sequence, target_slot: Optional[int] = None):
        """Apply ``blocks`` (slot-ascending, parent-linked) as one
        window, then optionally advance to ``target_slot``.  Returns the
        final state only after the window verdict (signatures + boundary
        root) passes — a failed window raises a typed
        :class:`WindowError` and commits nothing."""
        blocks = list(blocks)
        if not blocks:
            if target_slot is not None and target_slot > int(self.state.slot):
                self.state = process_slots(
                    self.state, target_slot, self.preset, self.spec,
                    self.T, state_root_fn=self.state_root_fn)
            return self.state

        verify = self.verify_signatures
        # The saved pre-state feeds the serial root-bisect oracle; the
        # boundary check is the only consumer.
        pre_state = (self.state.copy()
                     if self.boundary_root_check and self.fallback else None)
        root_fn = self._root_fn(blocks)
        strategy = (SignatureStrategy.BATCH_DEFERRED if verify
                    else SignatureStrategy.NO_VERIFICATION)
        sets: Optional[List[B.SignatureSet]] = [] if verify else None
        slices: Optional[List[Tuple[int, int, int, int]]] = \
            [] if verify else None

        t0 = time.perf_counter()
        state = self._apply(self.state, blocks, root_fn, strategy,
                            sets, slices)
        t1 = time.perf_counter()

        # ONE window-wide dispatch: the batch verifies on a worker
        # thread (mesh-sharded on a TPU backend) while the boundary root
        # hashes below.
        batch = None
        if verify and sets:
            from .sig_dispatch import get_dispatcher
            dispatcher = self.sig_dispatcher or get_dispatcher()
            batch = dispatcher.submit(sets, slot=int(blocks[-1].message.slot))
        t2 = time.perf_counter()

        # ONE computed root at the boundary (vs one per block serially),
        # checked against the final block's claim.
        boundary_ok = True
        if self.boundary_root_check:
            boundary_ok = (bytes(state.tree_hash_root())
                           == bytes(blocks[-1].message.state_root))
        t3 = time.perf_counter()

        verdict = True
        if batch is not None:
            try:
                verdict = batch.join()
            except Exception as e:
                raise WindowSignaturesInvalid(
                    f"window signature dispatch failed: {e}") from e
        t4 = time.perf_counter()

        timings = {
            "apply_ms": round((t1 - t0) * 1e3, 3),
            "collect_ms": round((t2 - t1) * 1e3, 3),
            "root_ms": round((t3 - t2) * 1e3, 3),
            "verify_ms": round((t4 - t3) * 1e3, 3),
            "blocks": len(blocks),
            "sets": len(sets) if sets else 0,
            "path": "batched",
        }
        if batch is not None:
            h2d = _set_bytes(sets)
            from ..common.device_ledger import (LEDGER,
                                                REPLAY_WINDOW_BUDGET)
            LEDGER.note_dispatch("replay", timings["verify_ms"])
            timings["window_h2d_bytes"] = h2d
            timings["budget_ok"] = h2d <= REPLAY_WINDOW_BUDGET["h2d_bytes"]

        if not verdict:
            _publish(dict(timings, path="rejected"))
            self._bisect_signatures(blocks, sets, slices)

        if not boundary_ok:
            if pre_state is None:
                _publish(dict(timings, path="rejected"))
                raise WindowRootMismatch(
                    "window boundary state root mismatch (fallback "
                    "disabled)",
                    slot=int(blocks[-1].message.slot),
                    block_root=bytes(blocks[-1].message.tree_hash_root()))
            # Serial oracle from the saved pre-state: names the lying
            # block, or supersedes the batched state if every claim
            # checks out (a batched-path divergence).
            state = self._bisect_roots(pre_state, blocks, target_slot)
            _COUNTERS["batched_windows"] += 1
            _publish(dict(timings, path="fell_back"))
            self.state = state
            return state

        if target_slot is not None and target_slot > int(state.slot):
            state = process_slots(state, target_slot, self.preset,
                                  self.spec, self.T, state_root_fn=root_fn)

        _COUNTERS["batched_windows"] += 1
        _publish(timings)
        self.state = state
        return state


def replay_states(base_state, pairs: Sequence[Tuple[bytes, object]],
                  preset, spec, T, *,
                  state_root_fn=None) -> Dict[bytes, object]:
    """Batched trusted replay of a parent-linked run of stored blocks:
    returns ``{block_root: post_state copy}`` for every block in
    ``pairs`` (``(root, signed_block)`` slot-ascending).  The recovery
    rebuild uses this to prime per-block states in ONE window instead of
    one O(summary-replay) store fetch per block.  Mutates (a copy of)
    ``base_state``; no signature work, no boundary check — the blocks
    were committed by a prior import."""
    out: Dict[bytes, object] = {}
    roots = [r for r, _ in pairs]
    rep = EpochReplayer(base_state.copy(), preset, spec, T,
                        verify_signatures=False,
                        state_root_fn=state_root_fn,
                        boundary_root_check=False)
    idx = {"i": 0}

    def hook(state, signed) -> None:
        out[roots[idx["i"]]] = state.copy()
        idx["i"] += 1

    rep.post_block_hook = hook
    rep.apply_window([b for _, b in pairs])
    return out


def note_serial_window() -> None:
    """Consumers on the knob-off / short-window serial path record the
    window here so the batched-vs-serial split stays visible in the
    stage counters."""
    _COUNTERS["serial_windows"] += 1
    LAST_REPLAY_TIMINGS.update(_COUNTERS)
