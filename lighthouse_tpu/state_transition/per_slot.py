"""Slot processing and the whole-state transition entry points.

Counterpart of ``/root/reference/consensus/state_processing/src/
per_slot_processing.rs`` and the ``state_transition`` composition: cache the
state root, roll the block/state root vectors, run epoch processing at
boundaries, apply fork upgrades at activation epochs.
"""

from __future__ import annotations

from ..common.tracing import TRACER
from ..types.chain_spec import ForkName
from .per_block import SignatureStrategy, process_block
from .per_epoch import process_epoch
from .upgrade import upgrade_state


class SlotProcessingError(ValueError):
    pass


def process_slot(state, preset, known_root: bytes | None = None) -> bytes:
    """One ``process_slot``: record state root, backfill header state root,
    record block root.  Returns the cached state root.

    ``known_root`` short-circuits the state-root computation when the
    caller already has it (store replay via ``BlockReplayer`` —
    ``block_replayer.rs`` ``state_root_iter``)."""
    state_root = known_root if known_root is not None \
        else state.tree_hash_root()
    state.state_roots.set(state.slot % preset.SLOTS_PER_HISTORICAL_ROOT,
                          state_root)
    if state.latest_block_header.state_root == b"\x00" * 32:
        state.latest_block_header.state_root = state_root
    block_root = state.latest_block_header.tree_hash_root()
    state.block_roots.set(state.slot % preset.SLOTS_PER_HISTORICAL_ROOT,
                          block_root)
    return state_root


def process_slots(state, target_slot: int, preset, spec, T,
                  state_root_fn=None):
    """Advance ``state`` to ``target_slot`` (epoch processing + fork
    upgrades on the way).  Returns the (possibly upgraded) state — upgrades
    change the state's class, mirroring ``per_slot_processing``'s
    ``Option<EpochProcessingSummary>`` + upgrade handling.

    ``state_root_fn(slot) -> bytes | None`` supplies known state roots to
    skip hashing during replay."""
    if target_slot < state.slot:
        raise SlotProcessingError(
            f"cannot rewind state from {state.slot} to {target_slot}")
    while state.slot < target_slot:
        known = state_root_fn(int(state.slot)) if state_root_fn else None
        process_slot(state, preset, known_root=known)
        if (state.slot + 1) % preset.SLOTS_PER_EPOCH == 0:
            fork = spec.fork_name_at_epoch(
                state.slot // preset.SLOTS_PER_EPOCH)
            with TRACER.span("epoch_transition", cat="state_transition",
                             epoch=int(state.slot)
                             // preset.SLOTS_PER_EPOCH + 1):
                process_epoch(state, fork, preset, spec, T)
        state.slot += 1
        if state.slot % preset.SLOTS_PER_EPOCH == 0:
            epoch = state.slot // preset.SLOTS_PER_EPOCH
            state = upgrade_state(state, epoch, preset, spec, T)
    return state


def state_transition(state, signed_block, preset, spec, T,
                     strategy: SignatureStrategy = SignatureStrategy.VERIFY_BULK,
                     validate_state_root: bool = True,
                     pubkey_cache=None, payload_verifier=None):
    """Full spec ``state_transition``: slots → block → state-root check.
    Returns the post-state (upgraded class if a fork activated)."""
    block = signed_block.message
    state = process_slots(state, block.slot, preset, spec, T)
    fork = spec.fork_name_at_epoch(state.slot // preset.SLOTS_PER_EPOCH)
    process_block(state, signed_block, fork, preset, spec, T,
                  strategy=strategy, pubkey_cache=pubkey_cache,
                  payload_verifier=payload_verifier)
    if validate_state_root:
        root = state.tree_hash_root()
        if root != block.state_root:
            raise SlotProcessingError(
                f"post-state root {root.hex()} != block.state_root "
                f"{block.state_root.hex()}")
    return state
