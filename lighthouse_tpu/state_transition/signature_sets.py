"""Signature-set builders for every consensus message kind.

Counterpart of ``/root/reference/consensus/state_processing/src/
per_block_processing/signature_sets.rs:74-599``.  Each builder returns a
:class:`~lighthouse_tpu.crypto.bls.SignatureSet` {aggregate signature,
signing keys, message}; the verifier batches them into ONE
random-linear-combination multi-pairing — the funnel that makes per-slot
crypto a single device launch.
"""

from __future__ import annotations

import numpy as np

from ..crypto.bls import PublicKey, Signature, SignatureSet
from ..types.chain_spec import Domain
from .committees import get_attesting_indices, get_beacon_proposer_index
from .helpers import (
    compute_epoch_at_slot,
    compute_signing_root,
    get_domain,
)


class SignatureSetError(ValueError):
    pass


class PubkeyCache:
    """Decompressed, subgroup-checked pubkeys by validator index — the
    ``ValidatorPubkeyCache`` seam
    (``beacon_node/beacon_chain/src/validator_pubkey_cache.rs:18-161``)."""

    def __init__(self):
        self._by_index: dict[int, PublicKey] = {}
        self._index_by_pubkey: dict[bytes, int] = {}

    def get(self, registry, index: int) -> PublicKey:
        pk = self._by_index.get(index)
        if pk is None:
            raw = registry.col("pubkey")[index].tobytes()
            pk = PublicKey.deserialize(raw)
            self._by_index[index] = pk
            self._index_by_pubkey[raw] = index
        return pk

    def get_many(self, registry, indices) -> list[PublicKey]:
        """Batched decompress-and-cache: the committee-sized builders'
        replacement for per-index :meth:`get` loops (one pubkey-column
        gather + one dict sweep instead of a Python attribute/method hop
        per index — committee-sized per-index loops were measurable
        block time).  Returns the keys in ``indices`` order."""
        by_index = self._by_index
        missing = {int(i) for i in indices if int(i) not in by_index}
        if missing:
            col = registry.col("pubkey")
            for i in missing:
                raw = col[i].tobytes()
                pk = PublicKey.deserialize(raw)
                by_index[i] = pk
                self._index_by_pubkey[raw] = i
        return [by_index[int(i)] for i in indices]

    def get_many_bytes(self, registry, raws) -> list[PublicKey]:
        """Batched lookup by compressed ENCODING (the sync-committee
        shape: the state stores committee pubkeys as bytes, possibly
        with duplicates, possibly — in hand-crafted states — not in the
        registry at all).  Registry members route through the index
        cache; foreign keys fall back to direct deserialization."""
        out = []
        for raw in raws:
            raw = bytes(raw)
            idx = self._index_by_pubkey.get(raw)
            if idx is None:
                idx = registry.pubkey_index(raw)
                if idx is not None:
                    self._index_by_pubkey[raw] = idx
            if idx is None:
                out.append(PublicKey.deserialize(raw))
                continue
            pk = self._by_index.get(idx)
            if pk is None:
                pk = self._by_index[idx] = PublicKey.deserialize(raw)
            out.append(pk)
        return out

    def index_of(self, registry, pubkey: bytes) -> int | None:
        idx = self._index_by_pubkey.get(pubkey)
        if idx is not None:
            return idx
        # Registry-resident reverse map (one lazy build per registry
        # lineage — a fresh per-state column scan per lookup made
        # sync-aggregate processing ~40% of block time).
        idx = registry.pubkey_index(pubkey)
        if idx is None:
            return None
        self._index_by_pubkey[pubkey] = idx
        return idx


class AttestationSigningRoots:
    """Per-block memo of attestation signing material: the
    ``BEACON_ATTESTER`` domain per target epoch (a block spans at most
    two) and the signing root per ``AttestationData`` VALUE — duplicate
    committee aggregates in one block share the data, and every
    signing-root recompute is ~7 SHA rounds of SSZ hashing the memo
    skips."""

    def __init__(self, state, preset):
        self._state = state
        self._preset = preset
        self._domains: dict[int, bytes] = {}
        self._messages: dict[tuple, bytes] = {}

    def domain(self, epoch: int) -> bytes:
        d = self._domains.get(epoch)
        if d is None:
            d = self._domains[epoch] = get_domain(
                self._state, Domain.BEACON_ATTESTER, epoch, self._preset)
        return d

    def message(self, data) -> bytes:
        key = (int(data.slot), int(data.index),
               bytes(data.beacon_block_root),
               int(data.source.epoch), bytes(data.source.root),
               int(data.target.epoch), bytes(data.target.root))
        m = self._messages.get(key)
        if m is None:
            m = self._messages[key] = compute_signing_root(
                data, self.domain(int(data.target.epoch)))
        return m


def block_proposal_signature_set(state, signed_block, pubkey_cache, preset,
                                 block_root: bytes | None = None) -> SignatureSet:
    block = signed_block.message
    proposer = block.proposer_index
    if proposer != get_beacon_proposer_index(state, preset, slot=block.slot):
        raise SignatureSetError(f"wrong proposer index {proposer}")
    domain = get_domain(state, Domain.BEACON_PROPOSER,
                        compute_epoch_at_slot(block.slot,
                                              preset.SLOTS_PER_EPOCH), preset)
    root = block_root if block_root is not None else block.tree_hash_root()
    return SignatureSet(
        signature=Signature.deserialize(signed_block.signature),
        signing_keys=[pubkey_cache.get(state.validators, proposer)],
        message=compute_signing_root(root, domain))


def randao_signature_set(state, block, pubkey_cache, preset) -> SignatureSet:
    epoch = compute_epoch_at_slot(block.slot, preset.SLOTS_PER_EPOCH)
    domain = get_domain(state, Domain.RANDAO, epoch, preset)
    from ..ssz import uint64 as _u64
    return SignatureSet(
        signature=Signature.deserialize(block.body.randao_reveal),
        signing_keys=[pubkey_cache.get(state.validators, block.proposer_index)],
        message=compute_signing_root(_u64.hash_tree_root(epoch), domain))


def block_header_signature_set(state, signed_header, pubkey_cache,
                               preset) -> SignatureSet:
    header = signed_header.message
    domain = get_domain(state, Domain.BEACON_PROPOSER,
                        compute_epoch_at_slot(header.slot,
                                              preset.SLOTS_PER_EPOCH), preset)
    return SignatureSet(
        signature=Signature.deserialize(signed_header.signature),
        signing_keys=[pubkey_cache.get(state.validators,
                                       header.proposer_index)],
        message=compute_signing_root(header, domain))


def indexed_attestation_signature_set(state, indices, signature_bytes, data,
                                      pubkey_cache, preset,
                                      msg_cache: AttestationSigningRoots
                                      | None = None) -> SignatureSet:
    if msg_cache is not None:
        message = msg_cache.message(data)
    else:
        domain = get_domain(state, Domain.BEACON_ATTESTER, data.target.epoch,
                            preset)
        message = compute_signing_root(data, domain)
    keys = pubkey_cache.get_many(state.validators, indices)
    return SignatureSet(
        signature=Signature.deserialize(signature_bytes),
        signing_keys=keys,
        message=message)


def attestation_signature_set(state, attestation, pubkey_cache,
                              preset) -> SignatureSet:
    indices = get_attesting_indices(
        state, attestation.data, attestation.aggregation_bits, preset)
    return indexed_attestation_signature_set(
        state, indices, attestation.signature, attestation.data,
        pubkey_cache, preset)


def voluntary_exit_signature_set(state, signed_exit, pubkey_cache,
                                 preset) -> SignatureSet:
    exit = signed_exit.message
    domain = get_domain(state, Domain.VOLUNTARY_EXIT, exit.epoch, preset)
    return SignatureSet(
        signature=Signature.deserialize(signed_exit.signature),
        signing_keys=[pubkey_cache.get(state.validators,
                                       exit.validator_index)],
        message=compute_signing_root(exit, domain))


def sync_aggregate_signature_set(state, sync_aggregate, slot: int,
                                 block_root_fn, preset,
                                 pubkey_cache: PubkeyCache | None = None,
                                 ) -> SignatureSet | None:
    """Signature over the previous slot's block root by the participating
    sync-committee subset.  ``block_root_fn(slot)`` supplies the root
    (``sync_committee_verification``-style).  Returns None when no bits are
    set and the signature is infinity (valid empty aggregate).

    With a ``pubkey_cache`` the committee subset materializes through
    one :meth:`PubkeyCache.get_many_bytes` sweep instead of a per-bit
    deserialize loop."""
    bits = np.asarray(sync_aggregate.sync_committee_bits, dtype=bool)
    sig = Signature.deserialize(sync_aggregate.sync_committee_signature)
    if not bits.any():
        if sig.point is None:
            return None
        raise SignatureSetError("non-infinity signature with empty bits")
    previous_slot = max(slot, 1) - 1
    domain = get_domain(state, Domain.SYNC_COMMITTEE,
                        compute_epoch_at_slot(previous_slot,
                                              preset.SLOTS_PER_EPOCH), preset)
    committee = state.current_sync_committee.pubkeys
    sel = np.flatnonzero(bits)
    if pubkey_cache is not None:
        pubkeys = pubkey_cache.get_many_bytes(
            state.validators, [committee[i] for i in sel])
    else:
        pubkeys = [PublicKey.deserialize(committee[i]) for i in sel]
    return SignatureSet(
        signature=sig,
        signing_keys=pubkeys,
        message=compute_signing_root(block_root_fn(previous_slot), domain))


def selection_proof_signature_set(state, slot: int, aggregator_index: int,
                                  selection_proof: bytes, pubkey_cache,
                                  preset) -> SignatureSet:
    """Aggregator slot-selection proof: BLS over the slot
    (``signature_sets.rs`` aggregate selection-proof arm)."""
    from ..ssz import uint64 as _u64
    domain = get_domain(state, Domain.SELECTION_PROOF,
                        compute_epoch_at_slot(slot, preset.SLOTS_PER_EPOCH),
                        preset)
    return SignatureSet(
        signature=Signature.deserialize(selection_proof),
        signing_keys=[pubkey_cache.get(state.validators, aggregator_index)],
        message=compute_signing_root(_u64.hash_tree_root(slot), domain))


def aggregate_and_proof_signature_set(state, signed_aggregate, pubkey_cache,
                                      preset) -> SignatureSet:
    """The aggregator's signature over the AggregateAndProof container
    (``signature_sets.rs`` signed_aggregate arm)."""
    msg = signed_aggregate.message
    slot = int(msg.aggregate.data.slot)
    domain = get_domain(state, Domain.AGGREGATE_AND_PROOF,
                        compute_epoch_at_slot(slot, preset.SLOTS_PER_EPOCH),
                        preset)
    return SignatureSet(
        signature=Signature.deserialize(signed_aggregate.signature),
        signing_keys=[pubkey_cache.get(state.validators,
                                       int(msg.aggregator_index))],
        message=compute_signing_root(msg, domain))


def sync_committee_message_signature_set(state, message, pubkey_cache,
                                         preset) -> SignatureSet:
    """A single sync-committee member's vote over a beacon block root
    (``signature_sets.rs`` sync_committee_message arm)."""
    domain = get_domain(state, Domain.SYNC_COMMITTEE,
                        compute_epoch_at_slot(int(message.slot),
                                              preset.SLOTS_PER_EPOCH),
                        preset)
    return SignatureSet(
        signature=Signature.deserialize(message.signature),
        signing_keys=[pubkey_cache.get(state.validators,
                                       int(message.validator_index))],
        message=compute_signing_root(
            bytes(message.beacon_block_root), domain))


def sync_selection_proof_signature_set(state, contribution_and_proof,
                                       pubkey_cache, preset, T) -> SignatureSet:
    """Sync-subcommittee aggregator selection proof over
    SyncAggregatorSelectionData (``signature_sets.rs``
    sync-selection-proof arm)."""
    c = contribution_and_proof.contribution
    slot = int(c.slot)
    data = T.SyncAggregatorSelectionData(
        slot=slot, subcommittee_index=int(c.subcommittee_index))
    domain = get_domain(state, Domain.SYNC_COMMITTEE_SELECTION_PROOF,
                        compute_epoch_at_slot(slot, preset.SLOTS_PER_EPOCH),
                        preset)
    return SignatureSet(
        signature=Signature.deserialize(
            contribution_and_proof.selection_proof),
        signing_keys=[pubkey_cache.get(
            state.validators, int(contribution_and_proof.aggregator_index))],
        message=compute_signing_root(data, domain))


def contribution_and_proof_signature_set(state, signed_contribution,
                                         pubkey_cache, preset) -> SignatureSet:
    """The sync aggregator's signature over ContributionAndProof
    (``signature_sets.rs`` signed_contribution_and_proof arm)."""
    msg = signed_contribution.message
    slot = int(msg.contribution.slot)
    domain = get_domain(state, Domain.CONTRIBUTION_AND_PROOF,
                        compute_epoch_at_slot(slot, preset.SLOTS_PER_EPOCH),
                        preset)
    return SignatureSet(
        signature=Signature.deserialize(signed_contribution.signature),
        signing_keys=[pubkey_cache.get(state.validators,
                                       int(msg.aggregator_index))],
        message=compute_signing_root(msg, domain))


def bls_to_execution_change_signature_set(state, signed_change,
                                          genesis_fork_version: bytes,
                                          preset) -> SignatureSet:
    """Signed with the GENESIS fork version regardless of current fork
    (capella spec; ``signature_sets.rs`` bls_execution_change arm)."""
    from .helpers import compute_domain
    change = signed_change.message
    domain = compute_domain(Domain.BLS_TO_EXECUTION_CHANGE,
                            genesis_fork_version,
                            state.genesis_validators_root)
    return SignatureSet(
        signature=Signature.deserialize(signed_change.signature),
        signing_keys=[PublicKey.deserialize(change.from_bls_pubkey)],
        message=compute_signing_root(change, domain))


def deposit_signature_set(deposit_data, T,
                          genesis_fork_version: bytes = bytes(4)) -> SignatureSet:
    """Deposits sign over DepositMessage with the genesis fork version and an
    EMPTY genesis_validators_root (spec ``is_valid_deposit_signature``)."""
    from .helpers import compute_domain
    msg = T.DepositMessage(
        pubkey=deposit_data.pubkey,
        withdrawal_credentials=deposit_data.withdrawal_credentials,
        amount=deposit_data.amount)
    domain = compute_domain(Domain.DEPOSIT, genesis_fork_version)
    return SignatureSet(
        signature=Signature.deserialize(deposit_data.signature),
        signing_keys=[PublicKey.deserialize(deposit_data.pubkey)],
        message=compute_signing_root(msg, domain))
