"""Ordered stepwise schema migrations — the role of the reference's
``beacon_node/store/src/metadata.rs`` + ``schema_change.rs``: the
on-disk schema version gates ``HotColdDB`` open, and an out-of-date
store walks ``v(n) → v(n+1)`` steps until it reaches the current
version.  Each step commits in bounded batches (idempotent per row)
with its version bump folded into the LAST batch, so a crash
mid-migration resumes exactly where it left off: the version is
unchanged until the step fully lands, and re-running skips the rows an
interrupted attempt already converted.

Shipped migrations:

- **v1 → v2** (crash-safe store PR): every value row outside
  ``BeaconMeta`` gains the CRC32 checksum frame
  (:func:`..kv.frame_value`), and the ``StoreJournal`` column comes into
  existence (vacuously — v1 stores have no pending import window, the
  old code persisted fork choice only at shutdown).  ``BeaconMeta``
  stays raw: the ``schema`` key must be readable before any framing
  decision, and the slasher parks counters there under its own keys.
"""

from __future__ import annotations

import struct
from typing import Callable, Dict, List

from .kv import DBColumn, KeyValueStore, frame_value

SCHEMA_VERSION = 2

# Columns whose values carry the checksum frame from v2 on.  BeaconMeta
# is deliberately absent (see module docstring); Quarantine holds rows
# exactly as they were found (possibly corrupt — that is the point).
FRAMED_COLUMNS = (
    DBColumn.BeaconBlock, DBColumn.ColdBlock,
    DBColumn.BeaconState, DBColumn.ColdState,
    DBColumn.BeaconStateSummary, DBColumn.BeaconRestorePoint,
    DBColumn.BlobSidecar, DBColumn.StoreJournal,
    DBColumn.OpPool, DBColumn.ForkChoice, DBColumn.BeaconChain,
    DBColumn.PubkeyCache,
)


class MigrationError(ValueError):
    pass


# Rows per commit during a migration step: bounds peak memory and
# transaction size to O(batch) instead of O(store) on a large datadir
# (the cold tier holds every full finalized state).  Steps must be
# IDEMPOTENT per row so a crash between batches resumes cleanly — the
# version bump rides only in the final batch.
MIGRATION_BATCH_ROWS = 512


def _already_framed(value: bytes) -> bool:
    from .kv import unframe_value, ChecksumError
    try:
        unframe_value(value)
        return True
    except ChecksumError:
        return False


def _v1_to_v2(kv: KeyValueStore):
    """Yield op batches wrapping every value row in the checksum frame.
    Idempotent: rows already carrying a valid frame (a crash-interrupted
    earlier attempt) are skipped, so re-running after a mid-migration
    death frames only the remainder."""
    batch: List[tuple] = []
    for col in FRAMED_COLUMNS:
        for key, value in list(kv.iter_column(col)):
            value = bytes(value)
            if _already_framed(value):
                continue
            batch.append(("put", col, bytes(key), frame_value(value)))
            if len(batch) >= MIGRATION_BATCH_ROWS:
                yield batch
                batch = []
    yield batch


_STEPS: Dict[int, Callable] = {
    1: _v1_to_v2,
}


def migrate_schema(kv: KeyValueStore, from_version: int,
                   to_version: int = SCHEMA_VERSION) -> List[int]:
    """Walk the store from ``from_version`` up to ``to_version``.
    Returns the list of step start-versions applied.  Raises
    :class:`MigrationError` when a step is missing (a store too old or
    too new for this build) — the caller surfaces that as a refusal to
    open, never a silent partial read.

    Each step commits in bounded batches with the version bump folded
    into the LAST batch: a crash mid-step leaves the version unchanged
    and the step re-runs idempotently; a crash after the final commit
    has the bump and never re-runs."""
    if from_version > to_version:
        raise MigrationError(
            f"store schema v{from_version} is newer than this build's "
            f"v{to_version} — refusing to downgrade")
    applied: List[int] = []
    for v in range(from_version, to_version):
        step = _STEPS.get(v)
        if step is None:
            raise MigrationError(
                f"no migration path from schema v{v} to v{v + 1}")
        pending: List[tuple] = []
        for batch in step(kv):
            if pending:
                kv.do_atomically(pending)
            pending = list(batch)
        pending.append(("put", DBColumn.BeaconMeta, b"schema",
                        struct.pack("<Q", v + 1)))
        kv.do_atomically(pending)
        applied.append(v)
    return applied
