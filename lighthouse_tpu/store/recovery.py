"""Startup reconciliation — the restart path's self-healing pass
(the roles of the reference's ``fork_revert.rs`` head reconstruction and
``hot_cold_store`` consistency checks, extended with checksum-driven
quarantine).

A node that died mid-import restarts from whatever subset of its atomic
batches committed.  Because every import is ONE batch (block + state/
summary + sidecars + journal entry) and fork choice persists at every
finalization, the damage surface is small and enumerable, and this pass
walks it in order:

1. **verify** — every framed row's CRC is checked; failing rows move to
   the ``Quarantine`` column (kept for post-mortem, invisible to normal
   reads) instead of being silently decoded.
2. **walk** — every block root in the persisted fork-choice snapshot
   must still load from the block columns; a miss means the snapshot
   depends on data that no longer exists → :class:`StoreCorruption`
   with an actionable message.
3. **replay** — journal entries (and any hot blocks the snapshot
   missed) newer than the snapshot re-import into fork choice in slot
   order, bringing the in-memory head back to exactly where the crashed
   process was.
4. **de-orphan** — partial imports (a journaled block whose state was
   quarantined, a block whose parent never made it) are quarantined so
   they cannot shadow a future re-import of the same root.

When the fork-choice blob itself is missing or corrupt the chain falls
back to a **full rebuild**: a fresh genesis-anchored fork choice replays
every stored block (cold then hot) in slot order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from .kv import ChecksumError, DBColumn, unframe_value
from .hot_cold import HotColdDB, StoreCorruption, StoreError

# Boot-time CRC scan scope: the hot tier, the persisted singletons and
# the journal — everything stages 2-4 will dereference.  The COLD tier
# (full finalized history, O(chain length)) is deliberately absent:
# cold rows are verified lazily at read time (`_get_value` raises
# StoreCorruption), and walking them here would make every restart
# O(total history) — exactly the downtime this PR exists to bound.
BOOT_SCAN_COLUMNS = (
    DBColumn.BeaconBlock, DBColumn.BeaconState,
    DBColumn.BeaconStateSummary, DBColumn.BeaconRestorePoint,
    DBColumn.BlobSidecar, DBColumn.StoreJournal,
    DBColumn.OpPool, DBColumn.ForkChoice, DBColumn.BeaconChain,
    DBColumn.PubkeyCache,
)


@dataclass
class QuarantinedRow:
    column: DBColumn
    key: bytes
    reason: str


@dataclass
class RecoveryReport:
    """What the reconciliation pass found and did."""
    quarantined: List[QuarantinedRow] = field(default_factory=list)
    orphans_removed: List[bytes] = field(default_factory=list)
    replayed: List[bytes] = field(default_factory=list)
    skipped_stale: int = 0
    rebuilt_fork_choice: bool = False
    notes: List[str] = field(default_factory=list)

    def summary(self) -> dict:
        return {
            "quarantined": len(self.quarantined),
            "orphans_removed": len(self.orphans_removed),
            "replayed_blocks": len(self.replayed),
            "skipped_stale": self.skipped_stale,
            "rebuilt_fork_choice": self.rebuilt_fork_choice,
            "notes": list(self.notes),
        }


def _quarantine_key(column: DBColumn, key: bytes) -> bytes:
    return column.value.encode() + b":" + bytes(key)


def verify_and_quarantine(store: HotColdDB) -> RecoveryReport:
    """Stage 1: CRC-walk the boot-relevant columns (hot tier +
    singletons + journal — see :data:`BOOT_SCAN_COLUMNS`); move failing
    rows into ``Quarantine`` (one atomic batch).  After this pass,
    normal reads see corrupt rows as *absent*, so later stages reason
    about missing data only.  Cold-tier rows keep their lazy read-time
    CRC check instead of a boot walk."""
    report = RecoveryReport()
    ops: List[tuple] = []
    for col in BOOT_SCAN_COLUMNS:
        for key, data in list(store.kv.iter_column(col)):
            try:
                unframe_value(data)
            except ChecksumError as e:
                ops.append(("put", DBColumn.Quarantine,
                            _quarantine_key(col, key), bytes(data)))
                ops.append(("delete", col, bytes(key), None))
                report.quarantined.append(
                    QuarantinedRow(col, bytes(key), str(e)))
    if ops:
        store.kv.do_atomically(ops)
    return report


def _orphan_ops(store: HotColdDB, block_root: bytes,
                state_root: Optional[bytes]) -> List[tuple]:
    """Quarantine a partial import: block, its journal entry, its
    summary/state rows and any sidecars move out of the live columns so
    a later re-import of the same root starts clean."""
    ops: List[tuple] = []
    for col in (DBColumn.BeaconBlock, DBColumn.StoreJournal):
        data = store.kv.get(col, block_root)
        if data is not None:
            ops.append(("put", DBColumn.Quarantine,
                        _quarantine_key(col, block_root), data))
        ops.append(("delete", col, bytes(block_root), None))
    if state_root:
        for col in (DBColumn.BeaconState, DBColumn.BeaconStateSummary):
            data = store.kv.get(col, state_root)
            if data is not None:
                ops.append(("put", DBColumn.Quarantine,
                            _quarantine_key(col, state_root), data))
                ops.append(("delete", col, bytes(state_root), None))
    for index in range(store.preset.MAX_BLOBS_PER_BLOCK):
        key = bytes(block_root) + bytes([index])
        data = store.kv.get(DBColumn.BlobSidecar, key)
        if data is not None:
            ops.append(("put", DBColumn.Quarantine,
                        _quarantine_key(DBColumn.BlobSidecar, key), data))
            ops.append(("delete", DBColumn.BlobSidecar, key, None))
    return ops


def _pending_blocks(store: HotColdDB, known: set,
                    include_cold: bool) -> List[Tuple[int, bytes]]:
    """(slot, root) of every stored block NOT in ``known``, slot-
    ascending: the journal entries plus — belt-and-braces, and the only
    source on a just-migrated v1 store or a rebuild — a scan of the
    block columns themselves."""
    pending: dict[bytes, int] = {}
    for entry in store.journal_entries():
        if entry.block_root not in known:
            pending[entry.block_root] = entry.slot
    cols = (DBColumn.ColdBlock, DBColumn.BeaconBlock) if include_cold \
        else (DBColumn.BeaconBlock,)
    for col in cols:
        for key, _data in list(store.kv.iter_column(col)):
            root = bytes(key)
            if root in known or root in pending:
                continue
            block = store.get_block(root)
            if block is None:
                continue  # quarantined between scan and read
            pending[root] = int(block.message.slot)
    return sorted(((slot, root) for root, slot in pending.items()),
                  key=lambda t: (t[0], t[1]))


def _segment_replay_cache(store: HotColdDB, chain,
                          pending: List[Tuple[int, bytes]]) -> dict:
    """Batched rebuild acceleration: group the pending blocks into
    parent-linked segments and prime ``{block_root: post_state}`` for
    each multi-block segment with ONE :func:`replay_states` window from
    the segment's base state, instead of one ``store.get_state`` —
    potentially an O(epoch) summary replay EACH — per block.

    Purely a cache: the reconcile loop's orphan decisions still key off
    the store's own rows (a computed state never resurrects a partial
    import whose state row is gone), and any segment whose base state
    won't load simply falls back to the per-block path."""
    from ..state_transition.batch_replay import (batch_replay_enabled,
                                                 replay_states)
    if not pending or not batch_replay_enabled(len(pending)):
        return {}
    blocks: dict[bytes, object] = {}
    for _slot, root in pending:
        b = store.get_block(root)
        if b is not None:
            blocks[root] = b
    # Greedy parent-linking in slot order; a fork's second child starts
    # its own segment (its base state comes from the cache when the
    # sibling's segment already computed it).
    segments: List[Tuple[bytes, List[Tuple[bytes, object]]]] = []
    tips: dict[bytes, tuple] = {}
    for _slot, root in pending:
        b = blocks.get(root)
        if b is None:
            continue
        parent = bytes(b.message.parent_root)
        seg = tips.pop(parent, None)
        if seg is None:
            seg = (parent, [])
            segments.append(seg)
        seg[1].append((root, b))
        tips[root] = seg
    cache: dict = {}
    for base_root, pairs in segments:
        if len(pairs) < 2:
            continue
        base_state = cache.get(base_root)
        if base_state is None:
            try:
                if bytes(base_root) == bytes(chain.genesis_block_root):
                    base_state = store.get_state(
                        bytes(chain.genesis_state_root))
                else:
                    base_block = store.get_block(base_root)
                    if base_block is None:
                        continue
                    base_state = store.get_state(
                        bytes(base_block.message.state_root))
            except (StoreCorruption, StoreError):
                base_state = None
        if base_state is None:
            continue
        try:
            cache.update(replay_states(base_state, pairs, store.preset,
                                       store.spec, store.T))
        except Exception:
            # Segment won't replay (e.g. a slot gap the stored chain
            # can't bridge) — the per-block loop handles its blocks.
            continue
    return cache


def _state_row_present(store: HotColdDB, state_root: bytes) -> bool:
    """Does the store hold ANY row for this state root (full, cold or
    summary)?  The exact set :meth:`HotColdDB.get_state` consults — the
    orphan rule stays keyed to store contents even when a replay cache
    can synthesize the state."""
    return any(store._get_value(col, state_root) is not None
               for col in (DBColumn.BeaconState, DBColumn.ColdState,
                           DBColumn.BeaconStateSummary))


def reconcile(store: HotColdDB, chain, report: RecoveryReport,
              *, genesis_root: bytes) -> RecoveryReport:
    """Stages 2-4 against a constructed chain (its ``fork_choice`` is
    the decoded snapshot, or a fresh genesis anchor on a rebuild)."""
    fc = chain.fork_choice

    # Stage 2: the snapshot's nodes must be backed by loadable blocks.
    # A CRC-verified raw read suffices (stage 1 already quarantined
    # corrupt rows) — no need to SSZ-decode every block per boot.
    for root in list(fc.proto.indices):
        if bytes(root) == bytes(genesis_root):
            continue
        if store._get_value(DBColumn.BeaconBlock, root) is None and \
                store._get_value(DBColumn.ColdBlock, root) is None:
            raise StoreCorruption(
                "fork-choice snapshot references a block the store no "
                "longer holds (quarantined or lost) — restore the datadir "
                "from a backup or resync from a checkpoint",
                DBColumn.BeaconBlock, root)

    # Historical floor: blocks at or below the fork-choice anchor's slot
    # can never be orphaned partial imports — they are checkpoint-sync
    # BACKFILL (stored below the anchor, parents deliberately outside
    # fork choice) or pre-finalization fork debris below the split.
    try:
        anchor_slot = fc.block_slot(genesis_root)
    except Exception:
        anchor_slot = 0
    floor = max(int(anchor_slot), int(store.split_slot))

    # Stage 3+4: replay the post-snapshot window, de-orphaning partial
    # imports as they surface.
    known = set(bytes(r) for r in fc.proto.indices)
    orphan_ops: List[tuple] = []
    pending = _pending_blocks(store, known, report.rebuilt_fork_choice)
    # Cold-then-hot rebuild at device rate: prime the per-block states
    # with one batched window per parent-linked segment (the per-block
    # ``get_state`` below degenerates to an O(epoch) summary replay per
    # non-boundary block).
    replay_cache = _segment_replay_cache(store, chain, pending)
    if replay_cache:
        report.notes.append(
            f"batched replay primed {len(replay_cache)} rebuild states")
    for slot, root in pending:
        block = store.get_block(root)
        if block is None:
            # Journal entry whose block row was quarantined.
            orphan_ops += _orphan_ops(store, root, None)
            report.orphans_removed.append(root)
            continue
        parent = bytes(block.message.parent_root)
        if parent not in fc.proto.indices:
            if slot <= floor:
                report.skipped_stale += 1
                continue
            orphan_ops += _orphan_ops(
                store, root, bytes(block.message.state_root))
            report.orphans_removed.append(root)
            continue
        state = None
        if root in replay_cache and \
                _state_row_present(store, bytes(block.message.state_root)):
            state = replay_cache[root]
        if state is None:
            try:
                state = store.get_state(bytes(block.message.state_root))
            except (StoreCorruption, StoreError):
                state = None
        if state is None:
            orphan_ops += _orphan_ops(
                store, root, bytes(block.message.state_root))
            report.orphans_removed.append(root)
            continue
        chain._replay_imported_block(block, root, state)
        known.add(root)
        report.replayed.append(root)
    if orphan_ops:
        store.kv.do_atomically(orphan_ops)
    return report
