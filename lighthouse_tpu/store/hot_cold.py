"""Two-tier beacon database — ``HotColdDB``
(``/root/reference/beacon_node/store/src/hot_cold_store.rs:48``).

Hot tier: every block; full states at epoch boundaries; a
``HotStateSummary`` {slot, latest_block_root, epoch_boundary_state_root}
for every other state, reconstructed by replaying blocks from the boundary
state (``hot_cold_store.rs:587`` + ``state_processing``'s BlockReplayer).

Cold tier (freezer): on finalization, blocks and periodic restore-point
states (every ``slots_per_restore_point``) migrate to cold columns and the
hot tier is pruned up to the split slot (``migrate.rs`` role, here a
synchronous call).  States between restore points replay from the previous
restore point.

All state/block values are SSZ, tagged with a 1-byte fork id so the right
per-fork container class decodes them.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Iterable, List, Optional, Tuple

from ..types.chain_spec import ForkName
from ..state_transition.block_replayer import BlockReplayer
from .kv import DBColumn, KeyValueStore, MemoryStore

_FORK_IDS = {f: i for i, f in enumerate(ForkName)}
_FORK_BY_ID = {i: f for f, i in _FORK_IDS.items()}

SCHEMA_VERSION = 1


class StoreError(ValueError):
    pass


@dataclass
class HotStateSummary:
    """`HotStateSummary` (`hot_cold_store.rs` StoreItem)."""
    slot: int
    latest_block_root: bytes
    epoch_boundary_state_root: bytes

    def encode(self) -> bytes:
        return struct.pack("<Q", self.slot) + self.latest_block_root \
            + self.epoch_boundary_state_root

    @classmethod
    def decode(cls, data: bytes) -> "HotStateSummary":
        if len(data) != 8 + 32 + 32:
            raise StoreError("bad hot state summary encoding")
        return cls(struct.unpack("<Q", data[:8])[0], data[8:40], data[40:72])


class HotColdDB:
    """The chain's persistence root object."""

    def __init__(self, kv: KeyValueStore, preset, spec, T,
                 slots_per_restore_point: int | None = None):
        self.kv = kv
        self.preset = preset
        self.spec = spec
        self.T = T
        self.sprp = slots_per_restore_point or (
            2 * preset.SLOTS_PER_EPOCH)
        self.split_slot = 0
        self._load_meta()

    @classmethod
    def memory(cls, preset, spec, T) -> "HotColdDB":
        return cls(MemoryStore(), preset, spec, T)

    # -- metadata ------------------------------------------------------------

    def _load_meta(self) -> None:
        v = self.kv.get(DBColumn.BeaconMeta, b"schema")
        if v is None:
            self.kv.put(DBColumn.BeaconMeta, b"schema",
                        struct.pack("<Q", SCHEMA_VERSION))
        elif struct.unpack("<Q", v)[0] != SCHEMA_VERSION:
            raise StoreError(
                f"schema version {struct.unpack('<Q', v)[0]} needs migration")
        sp = self.kv.get(DBColumn.BeaconMeta, b"split")
        if sp is not None:
            self.split_slot = struct.unpack("<Q", sp)[0]

    def _store_meta(self) -> None:
        self.kv.put(DBColumn.BeaconMeta, b"split",
                    struct.pack("<Q", self.split_slot))

    # -- blocks --------------------------------------------------------------

    def put_block(self, block_root: bytes, signed_block) -> None:
        fork = self.T.fork_of_block(signed_block)
        self.kv.put(DBColumn.BeaconBlock, block_root,
                    bytes([_FORK_IDS[fork]]) + signed_block.encode())

    def get_block(self, block_root: bytes):
        for col in (DBColumn.BeaconBlock, DBColumn.ColdBlock):
            data = self.kv.get(col, block_root)
            if data is not None:
                fork = _FORK_BY_ID[data[0]]
                return self.T.signed_block_cls(fork).deserialize(data[1:])
        return None

    # -- blob sidecars (Deneb data availability) -----------------------------

    def put_blob_sidecar(self, block_root: bytes, index: int,
                         sidecar) -> None:
        """Keyed block_root ‖ index (`hot_cold_store.rs` put_blobs; this
        stores sidecars individually so by-root requests for a subset
        avoid decoding the full 6-blob bundle)."""
        self.kv.put(DBColumn.BlobSidecar,
                    bytes(block_root) + bytes([index]),
                    type(sidecar).serialize(sidecar))

    def get_blob_sidecar(self, block_root: bytes, index: int):
        data = self.kv.get(DBColumn.BlobSidecar,
                           bytes(block_root) + bytes([index]))
        if data is None:
            return None
        return self.T.BlobSidecar.deserialize(data)

    def get_blob_sidecars(self, block_root: bytes) -> List:
        """All stored sidecars for a block, ascending index."""
        out = []
        for index in range(self.preset.MAX_BLOBS_PER_BLOCK):
            sc = self.get_blob_sidecar(block_root, index)
            if sc is not None:
                out.append(sc)
        return out

    # -- states --------------------------------------------------------------

    def put_state(self, state_root: bytes, state,
                  latest_block_root: bytes) -> None:
        """Full state at epoch boundaries, summary otherwise
        (`store_hot_state`, `hot_cold_store.rs:560-610`)."""
        slot = int(state.slot)
        if slot % self.preset.SLOTS_PER_EPOCH == 0:
            self._put_full_state(DBColumn.BeaconState, state_root, state)
            return
        boundary_slot = (slot // self.preset.SLOTS_PER_EPOCH
                         * self.preset.SLOTS_PER_EPOCH)
        boundary_root = bytes(state.state_roots.get(
            boundary_slot % self.preset.SLOTS_PER_HISTORICAL_ROOT))
        if self.kv.get(DBColumn.BeaconState, boundary_root) is None:
            # The epoch boundary was a skipped slot (no block → no stored
            # post-state there): a summary would be unloadable, so store
            # this state fully instead (self-contained).
            self._put_full_state(DBColumn.BeaconState, state_root, state)
            return
        summary = HotStateSummary(slot, latest_block_root, boundary_root)
        self.kv.put(DBColumn.BeaconStateSummary, state_root,
                    summary.encode())

    def _put_full_state(self, col: DBColumn, state_root: bytes, state) -> None:
        fork = self.T.fork_of_state(state)
        self.kv.put(col, state_root, bytes([_FORK_IDS[fork]]) + state.encode())

    def _get_full_state(self, col: DBColumn, state_root: bytes):
        data = self.kv.get(col, state_root)
        if data is None:
            return None
        fork = _FORK_BY_ID[data[0]]
        return self.T.state_cls(fork).deserialize(data[1:])

    def get_state(self, state_root: bytes):
        """Full state, summary-replay, or restore-point replay
        (`load_hot_state` / `load_cold_state`)."""
        state = self._get_full_state(DBColumn.BeaconState, state_root)
        if state is not None:
            return state
        state = self._get_full_state(DBColumn.ColdState, state_root)
        if state is not None:
            return state
        summary_data = self.kv.get(DBColumn.BeaconStateSummary, state_root)
        if summary_data is not None:
            return self._replay_from_summary(
                HotStateSummary.decode(summary_data))
        return None

    def _block_chain_to(self, latest_block_root: bytes,
                        after_slot: int) -> List:
        """Blocks (ascending) strictly after ``after_slot`` ending at
        ``latest_block_root``, following parent pointers."""
        blocks = []
        root = latest_block_root
        while True:
            block = self.get_block(root)
            if block is None or int(block.message.slot) <= after_slot:
                break
            blocks.append(block)
            root = bytes(block.message.parent_root)
        blocks.reverse()
        return blocks

    def _replay_from_summary(self, summary: HotStateSummary):
        base = self._get_full_state(DBColumn.BeaconState,
                                    summary.epoch_boundary_state_root)
        if base is None:
            # Boundary state may have migrated to the freezer.
            base = self._get_full_state(DBColumn.ColdState,
                                        summary.epoch_boundary_state_root)
        if base is None:
            raise StoreError("missing epoch boundary state for summary")
        blocks = self._block_chain_to(summary.latest_block_root,
                                      int(base.slot))
        replayer = BlockReplayer(base, self.preset, self.spec, self.T)
        return replayer.apply_blocks(blocks, target_slot=summary.slot)

    # -- finalization migration (hot → cold) ---------------------------------

    def migrate_to_cold(self, finalized_slot: int,
                        finalized_block_root: bytes) -> None:
        """Move finalized blocks to the freezer, keep restore-point states,
        prune hot summaries/states below the split
        (`migrate.rs` + `hot_cold_store.rs` migrate_database)."""
        if finalized_slot <= self.split_slot:
            return
        # Blocks along the finalized chain → cold.
        chain = self._block_chain_to(finalized_block_root, -1)
        ops = []
        for signed in chain:
            if int(signed.message.slot) >= finalized_slot:
                continue
            root = signed.message.tree_hash_root()
            data = self.kv.get(DBColumn.BeaconBlock, root)
            if data is not None:
                ops.append(("put", DBColumn.ColdBlock, root, data))
                ops.append(("delete", DBColumn.BeaconBlock, root, None))
        # Hot full states below the split move to the freezer wholesale
        # (denser than the reference's sparse restore points + replay, but
        # every previously-stored state stays loadable — the summaries are
        # kept, and their boundary lookups fall through to the cold tier).
        for state_root, data in list(self.kv.iter_column(DBColumn.BeaconState)):
            state_slot = self._peek_state_slot(data)
            if state_slot < finalized_slot:
                ops.append(("put", DBColumn.ColdState, state_root, data))
                if state_slot % self.sprp == 0:
                    ops.append(("put", DBColumn.BeaconRestorePoint,
                                struct.pack("<Q", state_slot), state_root))
                ops.append(("delete", DBColumn.BeaconState, state_root, None))
        self.kv.do_atomically(ops)
        self.split_slot = finalized_slot
        self._store_meta()

    def _peek_state_slot(self, data: bytes) -> int:
        # BeaconState SSZ layout: genesis_time (8) + genesis_validators_root
        # (32) + slot (8) — fixed offsets for every fork.
        return struct.unpack("<Q", data[1 + 40:1 + 48])[0]

    # -- persisted singletons (fork choice, op pool, chain) ------------------

    def put_item(self, column: DBColumn, key: bytes, value: bytes) -> None:
        self.kv.put(column, key, value)

    def get_item(self, column: DBColumn, key: bytes) -> Optional[bytes]:
        return self.kv.get(column, key)
