"""Two-tier beacon database — ``HotColdDB``
(``/root/reference/beacon_node/store/src/hot_cold_store.rs:48``).

Hot tier: every block; full states at epoch boundaries; a
``HotStateSummary`` {slot, latest_block_root, epoch_boundary_state_root}
for every other state, reconstructed by replaying blocks from the boundary
state (``hot_cold_store.rs:587`` + ``state_processing``'s BlockReplayer).

Cold tier (freezer): on finalization, blocks and periodic restore-point
states (every ``slots_per_restore_point``) migrate to cold columns and the
hot tier is pruned up to the split slot (``migrate.rs`` role, here a
synchronous call).  States between restore points replay from the previous
restore point.

All state/block values are SSZ, tagged with a 1-byte fork id so the right
per-fork container class decodes them.  From schema v2 every value row
outside ``BeaconMeta`` additionally carries a CRC32 checksum frame
(:mod:`.kv`), so a torn or bit-rotted row surfaces as
:class:`StoreCorruption` instead of decoding into a wrong object.

Crash consistency: writers assemble **op lists** (``block_put_ops`` /
``state_put_ops`` / ``blob_put_ops`` / ``journal_put_op``) that the chain
commits as ONE ``do_atomically`` batch per imported block, together with
a ``StoreJournal`` entry (block_root → slot ‖ parent_root) that bounds
the restart replay window (:mod:`.recovery`).
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass
from typing import Iterable, List, Optional, Tuple

from ..common import tracing
from ..types.chain_spec import ForkName
from ..state_transition.batch_replay import known_roots_fn
from ..state_transition.block_replayer import BlockReplayer
from .kv import (
    ChecksumError,
    DBColumn,
    KeyValueStore,
    MemoryStore,
    frame_value,
    unframe_value,
)
from .migrations import MigrationError, SCHEMA_VERSION

_FORK_IDS = {f: i for i, f in enumerate(ForkName)}
_FORK_BY_ID = {i: f for f, i in _FORK_IDS.items()}

# Stage dict for the `store` tracing source: the last atomic commit's
# timing/op-count, read by `tracing.record_stages("store")` inside the
# chain's `store_put` span and by any bench row that wants it.
LAST_STORE_TIMINGS: dict = {}

tracing.register_stage_source("store", lambda: LAST_STORE_TIMINGS)


class StoreError(ValueError):
    pass


class StoreCorruption(StoreError):
    """A row failed its integrity check, or a row the persisted chain
    depends on is missing.  ``column``/``key`` locate the damage; the
    message is actionable (what recovery tried, what the operator can
    do)."""

    def __init__(self, message: str, column: Optional[DBColumn] = None,
                 key: Optional[bytes] = None):
        where = ""
        if column is not None:
            where = f" [column={column.value}" + (
                f" key={bytes(key).hex()[:16]}…]" if key is not None else "]")
        super().__init__(message + where)
        self.column = column
        self.key = key


@dataclass
class HotStateSummary:
    """`HotStateSummary` (`hot_cold_store.rs` StoreItem)."""
    slot: int
    latest_block_root: bytes
    epoch_boundary_state_root: bytes

    def encode(self) -> bytes:
        return struct.pack("<Q", self.slot) + self.latest_block_root \
            + self.epoch_boundary_state_root

    @classmethod
    def decode(cls, data: bytes) -> "HotStateSummary":
        if len(data) != 8 + 32 + 32:
            raise StoreError("bad hot state summary encoding")
        return cls(struct.unpack("<Q", data[:8])[0], data[8:40], data[40:72])


@dataclass
class JournalEntry:
    """One import-batch journal record (`StoreJournal` column): enough
    to order a restart replay without decoding the block."""
    block_root: bytes
    slot: int
    parent_root: bytes

    def encode(self) -> bytes:
        return struct.pack("<Q", self.slot) + self.parent_root

    @classmethod
    def decode(cls, block_root: bytes, data: bytes) -> "JournalEntry":
        if len(data) != 8 + 32:
            raise StoreError("bad journal entry encoding")
        return cls(bytes(block_root), struct.unpack("<Q", data[:8])[0],
                   data[8:40])


class HotColdDB:
    """The chain's persistence root object."""

    def __init__(self, kv: KeyValueStore, preset, spec, T,
                 slots_per_restore_point: int | None = None):
        self.kv = kv
        self.preset = preset
        self.spec = spec
        self.T = T
        self.sprp = slots_per_restore_point or (
            2 * preset.SLOTS_PER_EPOCH)
        self.split_slot = 0
        self._load_meta()

    @classmethod
    def memory(cls, preset, spec, T) -> "HotColdDB":
        return cls(MemoryStore(), preset, spec, T)

    # -- framed value plumbing -----------------------------------------------

    def _get_value(self, column: DBColumn, key: bytes) -> Optional[bytes]:
        """Read + verify one framed row.  Raises :class:`StoreCorruption`
        on a failed check — callers that can *recover* from corruption
        (the startup reconciliation pass) catch it; hot-path callers must
        not decode garbage."""
        data = self.kv.get(column, key)
        if data is None:
            return None
        try:
            return unframe_value(data)
        except ChecksumError as e:
            raise StoreCorruption(
                f"corrupt row: {e}; run startup recovery "
                "(BeaconChain.from_store) to quarantine it, or restore the "
                "datadir from a checkpoint", column, key) from e

    def _put_op(self, column: DBColumn, key: bytes,
                value: bytes) -> tuple:
        return ("put", column, bytes(key), frame_value(value))

    def do_atomically(self, ops: List[tuple]) -> None:
        """Commit one batch through the KV layer, recording the commit
        timing/op count in :data:`LAST_STORE_TIMINGS` (the ``store``
        tracing stage source)."""
        t0 = time.perf_counter()
        self.kv.do_atomically(ops)
        LAST_STORE_TIMINGS.clear()
        LAST_STORE_TIMINGS.update({
            "commit_ms": (time.perf_counter() - t0) * 1e3,
            "ops": len(ops),
        })

    # -- metadata ------------------------------------------------------------

    def _load_meta(self) -> None:
        v = self.kv.get(DBColumn.BeaconMeta, b"schema")
        if v is None:
            self.kv.put(DBColumn.BeaconMeta, b"schema",
                        struct.pack("<Q", SCHEMA_VERSION))
            self.schema_migrated_from: Optional[int] = None
        else:
            ver = struct.unpack("<Q", v)[0]
            if ver != SCHEMA_VERSION:
                from .migrations import migrate_schema
                try:
                    applied = migrate_schema(self.kv, ver, SCHEMA_VERSION)
                except MigrationError as e:
                    raise StoreError(str(e)) from e
                self.schema_migrated_from = ver if applied else None
            else:
                self.schema_migrated_from = None
        sp = self.kv.get(DBColumn.BeaconMeta, b"split")
        if sp is not None:
            if len(sp) != 8:
                raise StoreCorruption(
                    "split meta is not a u64 — the store metadata is "
                    "damaged; restore the datadir from a checkpoint",
                    DBColumn.BeaconMeta, b"split")
            self.split_slot = struct.unpack("<Q", sp)[0]

    def _store_meta(self) -> None:
        self.kv.put(DBColumn.BeaconMeta, b"split",
                    struct.pack("<Q", self.split_slot))

    def _split_meta_op(self, split_slot: int) -> tuple:
        """The split write as a batch op — folded into the freezer
        migration's atomic batch so a crash can never strand the split
        behind (or ahead of) the moved rows."""
        return ("put", DBColumn.BeaconMeta, b"split",
                struct.pack("<Q", split_slot))

    # -- blocks --------------------------------------------------------------

    def block_put_ops(self, block_root: bytes, signed_block) -> List[tuple]:
        fork = self.T.fork_of_block(signed_block)
        return [self._put_op(
            DBColumn.BeaconBlock, block_root,
            bytes([_FORK_IDS[fork]]) + signed_block.encode())]

    def put_block(self, block_root: bytes, signed_block) -> None:
        self.do_atomically(self.block_put_ops(block_root, signed_block))

    def get_block(self, block_root: bytes):
        for col in (DBColumn.BeaconBlock, DBColumn.ColdBlock):
            data = self._get_value(col, block_root)
            if data is not None:
                fork = _FORK_BY_ID[data[0]]
                return self.T.signed_block_cls(fork).deserialize(data[1:])
        return None

    # -- blob sidecars (Deneb data availability) -----------------------------

    def blob_put_ops(self, block_root: bytes, index: int,
                     sidecar) -> List[tuple]:
        """Keyed block_root ‖ index (`hot_cold_store.rs` put_blobs; this
        stores sidecars individually so by-root requests for a subset
        avoid decoding the full 6-blob bundle)."""
        return [self._put_op(DBColumn.BlobSidecar,
                             bytes(block_root) + bytes([index]),
                             type(sidecar).serialize(sidecar))]

    def put_blob_sidecar(self, block_root: bytes, index: int,
                         sidecar) -> None:
        self.do_atomically(self.blob_put_ops(block_root, index, sidecar))

    def get_blob_sidecar(self, block_root: bytes, index: int):
        data = self._get_value(DBColumn.BlobSidecar,
                               bytes(block_root) + bytes([index]))
        if data is None:
            return None
        return self.T.BlobSidecar.deserialize(data)

    def get_blob_sidecars(self, block_root: bytes) -> List:
        """All stored sidecars for a block, ascending index."""
        out = []
        for index in range(self.preset.MAX_BLOBS_PER_BLOCK):
            sc = self.get_blob_sidecar(block_root, index)
            if sc is not None:
                out.append(sc)
        return out

    # -- import journal ------------------------------------------------------

    def journal_put_op(self, block_root: bytes, slot: int,
                       parent_root: bytes) -> tuple:
        """The import batch's journal record: after the last fork-choice
        snapshot, these entries are exactly the blocks a restart must
        replay (`fork_revert.rs` / reconstruct-head role)."""
        return self._put_op(
            DBColumn.StoreJournal, block_root,
            JournalEntry(bytes(block_root), int(slot),
                         bytes(parent_root)).encode())

    def journal_entries(self) -> List[JournalEntry]:
        """Decode every journal row, slot-ascending.  Corrupt entries
        surface as :class:`StoreCorruption` (recovery quarantines them
        first)."""
        out = []
        for key, data in list(self.kv.iter_column(DBColumn.StoreJournal)):
            try:
                value = unframe_value(data)
            except ChecksumError as e:
                raise StoreCorruption(f"corrupt journal entry: {e}",
                                      DBColumn.StoreJournal, key) from e
            out.append(JournalEntry.decode(key, value))
        out.sort(key=lambda j: (j.slot, j.block_root))
        return out

    def journal_clear_ops(self) -> List[tuple]:
        """Delete ops for every journal row — folded into the atomic
        fork-choice persist batch, so the journal always holds exactly
        the imports since the LAST durable snapshot."""
        return [("delete", DBColumn.StoreJournal, bytes(key), None)
                for key, _ in list(self.kv.iter_column(
                    DBColumn.StoreJournal))]

    # -- states --------------------------------------------------------------

    def state_put_ops(self, state_root: bytes, state,
                      latest_block_root: bytes) -> List[tuple]:
        """Full state at epoch boundaries, summary otherwise
        (`store_hot_state`, `hot_cold_store.rs:560-610`)."""
        slot = int(state.slot)
        if slot % self.preset.SLOTS_PER_EPOCH == 0:
            return self._full_state_ops(DBColumn.BeaconState, state_root,
                                        state)
        boundary_slot = (slot // self.preset.SLOTS_PER_EPOCH
                         * self.preset.SLOTS_PER_EPOCH)
        boundary_root = bytes(state.state_roots.get(
            boundary_slot % self.preset.SLOTS_PER_HISTORICAL_ROOT))
        if self.kv.get(DBColumn.BeaconState, boundary_root) is None:
            # The epoch boundary was a skipped slot (no block → no stored
            # post-state there): a summary would be unloadable, so store
            # this state fully instead (self-contained).
            return self._full_state_ops(DBColumn.BeaconState, state_root,
                                        state)
        summary = HotStateSummary(slot, latest_block_root, boundary_root)
        return [self._put_op(DBColumn.BeaconStateSummary, state_root,
                             summary.encode())]

    def put_state(self, state_root: bytes, state,
                  latest_block_root: bytes) -> None:
        self.do_atomically(self.state_put_ops(state_root, state,
                                              latest_block_root))

    def _full_state_ops(self, col: DBColumn, state_root: bytes,
                        state) -> List[tuple]:
        fork = self.T.fork_of_state(state)
        return [self._put_op(col, state_root,
                             bytes([_FORK_IDS[fork]]) + state.encode())]

    def _get_full_state(self, col: DBColumn, state_root: bytes):
        data = self._get_value(col, state_root)
        if data is None:
            return None
        fork = _FORK_BY_ID[data[0]]
        return self.T.state_cls(fork).deserialize(data[1:])

    def get_state(self, state_root: bytes):
        """Full state, summary-replay, or restore-point replay
        (`load_hot_state` / `load_cold_state`)."""
        state = self._get_full_state(DBColumn.BeaconState, state_root)
        if state is not None:
            return state
        state = self._get_full_state(DBColumn.ColdState, state_root)
        if state is not None:
            return state
        summary_data = self._get_value(DBColumn.BeaconStateSummary,
                                       state_root)
        if summary_data is not None:
            return self._replay_from_summary(
                HotStateSummary.decode(summary_data))
        return None

    def _block_chain_to(self, latest_block_root: bytes,
                        after_slot: int) -> List:
        """Blocks (ascending) strictly after ``after_slot`` ending at
        ``latest_block_root``, following parent pointers."""
        return [b for _, b in self._block_chain_roots_to(
            latest_block_root, after_slot)]

    def _block_chain_roots_to(self, latest_block_root: bytes,
                              after_slot: int) -> List[Tuple[bytes, object]]:
        """(root, block) pairs, ascending — the root is the KV key the
        walk fetched the block under, so callers never re-derive it via
        ``tree_hash_root()``."""
        chain = []
        root = latest_block_root
        while True:
            block = self.get_block(root)
            if block is None or int(block.message.slot) <= after_slot:
                break
            chain.append((bytes(root), block))
            root = bytes(block.message.parent_root)
        chain.reverse()
        return chain

    def _replay_from_summary(self, summary: HotStateSummary):
        base = self._get_full_state(DBColumn.BeaconState,
                                    summary.epoch_boundary_state_root)
        if base is None:
            # Boundary state may have migrated to the freezer.
            base = self._get_full_state(DBColumn.ColdState,
                                        summary.epoch_boundary_state_root)
        if base is None:
            raise StoreError("missing epoch boundary state for summary")
        blocks = self._block_chain_to(summary.latest_block_root,
                                      int(base.slot))
        # Known roots: the stored chain's blocks already carry their
        # (import-verified) post-state roots, so the replay skips every
        # per-slot tree hash except at empty slots past the last block
        # (`block_replayer.rs` state_root_iter).
        replayer = BlockReplayer(base, self.preset, self.spec, self.T,
                                 state_root_fn=known_roots_fn(blocks))
        return replayer.apply_blocks(blocks, target_slot=summary.slot)

    # -- finalization migration (hot → cold) ---------------------------------

    def migrate_to_cold(self, finalized_slot: int,
                        finalized_block_root: bytes) -> None:
        """Move finalized blocks to the freezer, keep restore-point states,
        prune hot summaries/states below the split
        (`migrate.rs` + `hot_cold_store.rs` migrate_database).

        ONE atomic batch, split meta included: a crash anywhere inside the
        migration leaves either the old store or the new one, never a
        half-moved freezer with a stale (or advanced) split."""
        if finalized_slot <= self.split_slot:
            return
        # Blocks along the finalized chain → cold, keyed by the root the
        # chain walk already fetched them under (no tree_hash_root()).
        ops = []
        for root, signed in self._block_chain_roots_to(
                finalized_block_root, -1):
            if int(signed.message.slot) >= finalized_slot:
                continue
            data = self.kv.get(DBColumn.BeaconBlock, root)
            if data is not None:
                ops.append(("put", DBColumn.ColdBlock, root, data))
                ops.append(("delete", DBColumn.BeaconBlock, root, None))
        # Hot full states below the split move to the freezer wholesale
        # (denser than the reference's sparse restore points + replay, but
        # every previously-stored state stays loadable — the summaries are
        # kept, and their boundary lookups fall through to the cold tier).
        for state_root, data in list(self.kv.iter_column(DBColumn.BeaconState)):
            state_slot = self._peek_state_slot(data)
            if state_slot < finalized_slot:
                ops.append(("put", DBColumn.ColdState, state_root, data))
                if state_slot % self.sprp == 0:
                    ops.append(self._put_op(DBColumn.BeaconRestorePoint,
                                            struct.pack("<Q", state_slot),
                                            state_root))
                ops.append(("delete", DBColumn.BeaconState, state_root, None))
        ops.append(self._split_meta_op(finalized_slot))
        self.do_atomically(ops)
        self.split_slot = finalized_slot

    def _peek_state_slot(self, data: bytes) -> int:
        # BeaconState SSZ layout: genesis_time (8) + genesis_validators_root
        # (32) + slot (8) — fixed offsets for every fork.  ``data`` is the
        # raw (framed) row from iter_column; verify + strip first.
        try:
            value = unframe_value(data)
        except ChecksumError as e:
            raise StoreCorruption(f"corrupt state row: {e}",
                                  DBColumn.BeaconState) from e
        return struct.unpack("<Q", value[1 + 40:1 + 48])[0]

    # -- persisted singletons (fork choice, op pool, chain) ------------------

    def item_put_op(self, column: DBColumn, key: bytes,
                    value: bytes) -> tuple:
        """Framed put op for a persisted singleton — callers fold it into
        their own atomic batches (the chain's ``persist()``)."""
        return self._put_op(column, key, value)

    def put_item(self, column: DBColumn, key: bytes, value: bytes) -> None:
        self.do_atomically([self._put_op(column, key, value)])

    def get_item(self, column: DBColumn, key: bytes) -> Optional[bytes]:
        return self._get_value(column, key)
