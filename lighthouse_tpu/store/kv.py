"""Column-oriented key-value store seam.

``KeyValueStore``/``ItemStore`` traits of the reference
(``/root/reference/beacon_node/store/src/lib.rs:169-210`` DBColumn,
``leveldb_store.rs``, ``memory_store.rs``), with two backends:

- :class:`MemoryStore` — dict-backed, for tests and ephemeral harnesses
  (the reference's ``MemoryStore``);
- :class:`SqliteStore` — embedded on-disk engine (the reference links
  LevelDB/C++; SQLite is the embedded native store available here), with
  WAL journaling and batched atomic writes.
"""

from __future__ import annotations

import sqlite3
import threading
from enum import Enum
from typing import Iterable, Optional, Sequence, Tuple


class DBColumn(str, Enum):
    """`DBColumn` (`store/src/lib.rs:169`) — the subset in use."""
    BeaconMeta = "bma"
    BeaconBlock = "blk"
    BeaconState = "ste"
    BeaconStateSummary = "bss"
    BeaconChain = "bch"
    OpPool = "opo"
    ForkChoice = "frk"
    PubkeyCache = "pkc"
    BeaconRestorePoint = "brp"
    ColdBlock = "cbk"
    ColdState = "cst"
    BlobSidecar = "blb"


class KeyValueStore:
    """Abstract column KV API (get/put/delete/iter + atomic batches)."""

    def get(self, column: DBColumn, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, column: DBColumn, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, column: DBColumn, key: bytes) -> None:
        raise NotImplementedError

    def do_atomically(self, ops: Sequence[Tuple[str, DBColumn, bytes,
                                                Optional[bytes]]]) -> None:
        """ops: ("put", col, key, value) | ("delete", col, key, None)."""
        raise NotImplementedError

    def iter_column(self, column: DBColumn) -> Iterable[Tuple[bytes, bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryStore(KeyValueStore):
    def __init__(self):
        self._data: dict[tuple[str, bytes], bytes] = {}
        self._lock = threading.Lock()

    def get(self, column, key):
        return self._data.get((column.value, bytes(key)))

    def put(self, column, key, value):
        with self._lock:
            self._data[(column.value, bytes(key))] = bytes(value)

    def delete(self, column, key):
        with self._lock:
            self._data.pop((column.value, bytes(key)), None)

    def do_atomically(self, ops):
        with self._lock:
            for op, col, key, value in ops:
                if op == "put":
                    self._data[(col.value, bytes(key))] = bytes(value)
                elif op == "delete":
                    self._data.pop((col.value, bytes(key)), None)
                else:
                    raise ValueError(op)

    def iter_column(self, column):
        with self._lock:
            items = [(k[1], v) for k, v in self._data.items()
                     if k[0] == column.value]
        return iter(items)


class SqliteStore(KeyValueStore):
    """One table per database: (column, key) → value, WAL mode."""

    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv ("
                "col TEXT NOT NULL, key BLOB NOT NULL, value BLOB NOT NULL, "
                "PRIMARY KEY (col, key)) WITHOUT ROWID")
            self._conn.commit()

    def get(self, column, key):
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE col=? AND key=?",
                (column.value, bytes(key))).fetchone()
        return None if row is None else row[0]

    def put(self, column, key, value):
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (col, key, value) VALUES (?,?,?)",
                (column.value, bytes(key), bytes(value)))
            self._conn.commit()

    def delete(self, column, key):
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE col=? AND key=?",
                               (column.value, bytes(key)))
            self._conn.commit()

    def do_atomically(self, ops):
        with self._lock:
            try:
                for op, col, key, value in ops:
                    if op == "put":
                        self._conn.execute(
                            "INSERT OR REPLACE INTO kv (col, key, value) "
                            "VALUES (?,?,?)", (col.value, bytes(key),
                                               bytes(value)))
                    elif op == "delete":
                        self._conn.execute(
                            "DELETE FROM kv WHERE col=? AND key=?",
                            (col.value, bytes(key)))
                    else:
                        raise ValueError(op)
                self._conn.commit()
            except Exception:
                self._conn.rollback()
                raise

    def iter_column(self, column):
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, value FROM kv WHERE col=?",
                (column.value,)).fetchall()
        return iter(rows)

    def close(self):
        with self._lock:
            self._conn.close()
