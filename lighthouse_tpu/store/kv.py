"""Column-oriented key-value store seam.

``KeyValueStore``/``ItemStore`` traits of the reference
(``/root/reference/beacon_node/store/src/lib.rs:169-210`` DBColumn,
``leveldb_store.rs``, ``memory_store.rs``), with two backends:

- :class:`MemoryStore` — dict-backed, for tests and ephemeral harnesses
  (the reference's ``MemoryStore``);
- :class:`SqliteStore` — embedded on-disk engine (the reference links
  LevelDB/C++; SQLite is the embedded native store available here), with
  WAL journaling, batched atomic writes and a durability knob
  (``PRAGMA synchronous`` via ``LIGHTHOUSE_TPU_STORE_SYNC``).

The checksum frame (:func:`frame_value` / :func:`unframe_value`) lives
here so the hot/cold DB, the schema migrations and the recovery scan all
share one encoding: a torn or bit-rotted row must be *detected* at read
time, never silently decoded into a wrong state.
"""

from __future__ import annotations

import sqlite3
import struct
import threading
import zlib
from enum import Enum
from typing import Iterable, Optional, Sequence, Tuple


class DBColumn(str, Enum):
    """`DBColumn` (`store/src/lib.rs:169`) — the subset in use."""
    BeaconMeta = "bma"
    BeaconBlock = "blk"
    BeaconState = "ste"
    BeaconStateSummary = "bss"
    BeaconChain = "bch"
    OpPool = "opo"
    ForkChoice = "frk"
    PubkeyCache = "pkc"
    BeaconRestorePoint = "brp"
    ColdBlock = "cbk"
    ColdState = "cst"
    BlobSidecar = "blb"
    # Crash-consistency additions (schema v2): the per-import journal
    # whose entries bound the restart replay window, and the quarantine
    # column recovery moves checksum-failing rows into (kept for
    # post-mortem instead of deleted).
    StoreJournal = "jnl"
    Quarantine = "qtn"


# -- checksum frame (schema v2) ----------------------------------------------

CHECKSUM_MAGIC = 0xC5
_FRAME_HDR = 5  # magic byte + crc32


class ChecksumError(ValueError):
    """A framed value failed its integrity check (torn write / bit rot)."""


def frame_value(value: bytes) -> bytes:
    """``magic ‖ crc32(value) ‖ value`` — the schema-v2 on-disk frame."""
    value = bytes(value)
    return (bytes([CHECKSUM_MAGIC])
            + struct.pack("<I", zlib.crc32(value) & 0xFFFFFFFF) + value)


def unframe_value(data: bytes) -> bytes:
    """Verify and strip a frame; raises :class:`ChecksumError` on a bad
    magic byte, short row, or CRC mismatch."""
    if len(data) < _FRAME_HDR or data[0] != CHECKSUM_MAGIC:
        raise ChecksumError("missing checksum frame")
    (want,) = struct.unpack_from("<I", data, 1)
    value = bytes(data[_FRAME_HDR:])
    got = zlib.crc32(value) & 0xFFFFFFFF
    if got != want:
        raise ChecksumError(
            f"checksum mismatch: stored {want:#010x} != computed {got:#010x}")
    return value


class KeyValueStore:
    """Abstract column KV API (get/put/delete/iter + atomic batches)."""

    def get(self, column: DBColumn, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, column: DBColumn, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, column: DBColumn, key: bytes) -> None:
        raise NotImplementedError

    def do_atomically(self, ops: Sequence[Tuple[str, DBColumn, bytes,
                                                Optional[bytes]]]) -> None:
        """ops: ("put", col, key, value) | ("delete", col, key, None)."""
        raise NotImplementedError

    def iter_column(self, column: DBColumn) -> Iterable[Tuple[bytes, bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryStore(KeyValueStore):
    def __init__(self):
        self._data: dict[tuple[str, bytes], bytes] = {}
        self._lock = threading.Lock()

    def get(self, column, key):
        return self._data.get((column.value, bytes(key)))

    def put(self, column, key, value):
        with self._lock:
            self._data[(column.value, bytes(key))] = bytes(value)

    def delete(self, column, key):
        with self._lock:
            self._data.pop((column.value, bytes(key)), None)

    def do_atomically(self, ops):
        with self._lock:
            for op, col, key, value in ops:
                if op == "put":
                    self._data[(col.value, bytes(key))] = bytes(value)
                elif op == "delete":
                    self._data.pop((col.value, bytes(key)), None)
                else:
                    raise ValueError(op)

    def iter_column(self, column):
        with self._lock:
            items = [(k[1], v) for k, v in self._data.items()
                     if k[0] == column.value]
        return iter(items)


# PRAGMA synchronous levels accepted by the durability knob.  WAL +
# NORMAL is the crash-safe default for *process* death (a committed
# transaction is always intact — exactly the SIGKILL drill's model);
# FULL/EXTRA additionally survive OS crash / power loss at an fsync-per-
# commit cost; OFF trades all durability for speed (ephemeral harnesses).
_SYNC_LEVELS = {"off": "OFF", "normal": "NORMAL", "full": "FULL",
                "extra": "EXTRA"}


class SqliteStore(KeyValueStore):
    """One table per database: (column, key) → value, WAL mode.

    ``sync`` (or env ``LIGHTHOUSE_TPU_STORE_SYNC``) selects the
    ``PRAGMA synchronous`` level — see :data:`_SYNC_LEVELS`.
    """

    def __init__(self, path: str, sync: Optional[str] = None):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        from ..common.knobs import knob_choice
        sync = sync.lower() if sync \
            else knob_choice("LIGHTHOUSE_TPU_STORE_SYNC")
        if sync not in _SYNC_LEVELS:
            raise ValueError(
                f"LIGHTHOUSE_TPU_STORE_SYNC={sync!r}: expected one of "
                f"{sorted(_SYNC_LEVELS)}")
        self.sync = sync
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(f"PRAGMA synchronous={_SYNC_LEVELS[sync]}")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv ("
                "col TEXT NOT NULL, key BLOB NOT NULL, value BLOB NOT NULL, "
                "PRIMARY KEY (col, key)) WITHOUT ROWID")
            self._conn.commit()

    def get(self, column, key):
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE col=? AND key=?",
                (column.value, bytes(key))).fetchone()
        return None if row is None else row[0]

    def put(self, column, key, value):
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (col, key, value) VALUES (?,?,?)",
                (column.value, bytes(key), bytes(value)))
            self._conn.commit()

    def delete(self, column, key):
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE col=? AND key=?",
                               (column.value, bytes(key)))
            self._conn.commit()

    def do_atomically(self, ops):
        with self._lock:
            try:
                for op, col, key, value in ops:
                    if op == "put":
                        self._conn.execute(
                            "INSERT OR REPLACE INTO kv (col, key, value) "
                            "VALUES (?,?,?)", (col.value, bytes(key),
                                               bytes(value)))
                    elif op == "delete":
                        self._conn.execute(
                            "DELETE FROM kv WHERE col=? AND key=?",
                            (col.value, bytes(key)))
                    else:
                        raise ValueError(op)
                self._conn.commit()
            except Exception:
                self._conn.rollback()
                raise

    def iter_column(self, column):
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, value FROM kv WHERE col=?",
                (column.value,)).fetchall()
        return iter(rows)

    def close(self):
        with self._lock:
            self._conn.close()
