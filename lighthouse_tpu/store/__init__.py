"""Persistent storage: KV abstraction + hot/cold beacon DB.

Counterpart of ``beacon_node/store``
(``/root/reference/beacon_node/store/src/``): a column-oriented
``KeyValueStore`` seam with in-memory and SQLite backends (the reference
uses LevelDB via FFI — SQLite is this build's embedded native engine), and
``HotColdDB`` with epoch-boundary full states + ``HotStateSummary`` replay
between them.
"""

from .kv import DBColumn, KeyValueStore, MemoryStore, SqliteStore
from .hot_cold import HotColdDB, HotStateSummary, StoreError

__all__ = [
    "DBColumn", "KeyValueStore", "MemoryStore", "SqliteStore",
    "HotColdDB", "HotStateSummary", "StoreError",
]
