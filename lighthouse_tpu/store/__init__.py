"""Persistent storage: KV abstraction + hot/cold beacon DB.

Counterpart of ``beacon_node/store``
(``/root/reference/beacon_node/store/src/``): a column-oriented
``KeyValueStore`` seam with in-memory and SQLite backends (the reference
uses LevelDB via FFI — SQLite is this build's embedded native engine), and
``HotColdDB`` with epoch-boundary full states + ``HotStateSummary`` replay
between them.  Crash consistency rides on three seams: checksum-framed
values (:mod:`.kv`), stepwise schema migrations (:mod:`.migrations`) and
the startup reconciliation pass (:mod:`.recovery`).
"""

from .kv import (
    ChecksumError,
    DBColumn,
    KeyValueStore,
    MemoryStore,
    SqliteStore,
    frame_value,
    unframe_value,
)
from .migrations import SCHEMA_VERSION, MigrationError, migrate_schema
from .hot_cold import (
    HotColdDB,
    HotStateSummary,
    JournalEntry,
    StoreCorruption,
    StoreError,
)
from .recovery import (
    RecoveryReport,
    reconcile,
    verify_and_quarantine,
)

__all__ = [
    "ChecksumError", "DBColumn", "KeyValueStore", "MemoryStore",
    "SqliteStore", "frame_value", "unframe_value",
    "SCHEMA_VERSION", "MigrationError", "migrate_schema",
    "HotColdDB", "HotStateSummary", "JournalEntry", "StoreCorruption",
    "StoreError",
    "RecoveryReport", "reconcile", "verify_and_quarantine",
]
