"""Chain-segment processing seam shared by the catch-up consumers
(range sync, checkpoint backfill continuation, any future batch
importer).

``process_chain_segment`` is the ONE entry point that decides between
the epoch-batched replay engine (:mod:`..state_transition.batch_replay`)
and the serial per-block import oracle, classifies failures into
retryable (bad peer / missing data) vs deterministic (bad BLOCK — the
chain itself is invalid, rotating peers cannot help), and commits
nothing unless the whole segment's verdict passes.

Mirrors the reference's ``beacon_chain::process_chain_segment`` /
``ChainSegmentResult`` split (``beacon_chain/src/chain_segment.rs``):
the caller (``network/range_sync.py``) maps OK → batch processed,
RETRY → rotate peer and re-download, FATAL → fail the whole syncing
chain immediately.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

from ..beacon_chain.block_verification import ExecutedBlock
from ..beacon_chain.errors import (
    BlobsUnavailable,
    BlockError,
    BlockIsAlreadyKnown,
    IncorrectProposer,
    InvalidBlock,
    InvalidSignatures,
    ParentUnknown,
    ProposalSignatureInvalid,
    StateRootMismatch,
)
from ..state_transition.batch_replay import (
    EpochReplayer,
    WindowBlockInvalid,
    WindowRootMismatch,
    WindowSignaturesInvalid,
    batch_replay_enabled,
    note_serial_window,
)

__all__ = ["Outcome", "SegmentResult", "process_chain_segment"]

# Deterministic rejections: the BLOCK is bad under consensus rules, so
# any honest peer would serve the same bytes — retrying against a new
# peer burns attempts without changing the verdict.
_DETERMINISTIC = (InvalidBlock, InvalidSignatures, StateRootMismatch,
                  IncorrectProposer, ProposalSignatureInvalid)


class Outcome(enum.Enum):
    OK = "ok"          # segment fully imported
    RETRY = "retry"    # transient / peer-attributable — re-download
    FATAL = "fatal"    # deterministic bad block — fail the chain


@dataclass
class SegmentResult:
    outcome: Outcome
    imported: int = 0
    error: Optional[BaseException] = None
    # Set when a block's blobs are missing: the caller fetches sidecars
    # for THIS block and re-calls (already-imported blocks are skipped
    # on the retry).
    needs_blobs: Optional[object] = None
    batched: bool = False


def _serial_segment(chain, blocks) -> SegmentResult:
    """The per-block oracle: the exact pre-batching import loop, with
    deterministic rejections classified FATAL instead of burning peer
    retries."""
    imported = 0
    for b in blocks:
        try:
            chain.per_slot_task(int(b.message.slot))
            chain.process_block(b)
            imported += 1
        except BlockIsAlreadyKnown:
            continue
        except BlobsUnavailable as e:
            return SegmentResult(Outcome.RETRY, imported, error=e,
                                 needs_blobs=b)
        except _DETERMINISTIC as e:
            return SegmentResult(Outcome.FATAL, imported, error=e)
        except Exception as e:
            return SegmentResult(Outcome.RETRY, imported, error=e)
    note_serial_window()
    return SegmentResult(Outcome.OK, imported)


def _linked(pairs) -> bool:
    for (pr, prev), (_, nxt) in zip(pairs, pairs[1:]):
        if bytes(nxt.message.parent_root) != pr:
            return False
    return True


def process_chain_segment(chain, blocks) -> SegmentResult:
    """Import a slot-ascending run of blocks into ``chain``.

    Batched path (knob auto/on, window long enough, parent-linked):
    apply the whole window through :class:`EpochReplayer` on a copy of
    the parent state — ONE sharded signature batch, known state roots,
    ONE boundary root — and only on a passing verdict commit every
    block through the chain's atomic import (fork choice, store batch,
    attester caches, head recompute).  A failed window commits NOTHING.
    Serial path otherwise (the differential oracle)."""
    blocks = list(blocks)
    if not blocks:
        return SegmentResult(Outcome.OK, 0)

    # Drop already-known blocks (overlapping batch boundaries re-serve
    # the anchor block) — roots are needed for import anyway.
    fresh = []
    for b in blocks:
        root = bytes(b.message.tree_hash_root())
        if not chain.fork_choice.contains_block(root):
            fresh.append((root, b))
    if not fresh:
        return SegmentResult(Outcome.OK, 0)

    if not (batch_replay_enabled(len(fresh)) and _linked(fresh)):
        return _serial_segment(chain, [b for _, b in fresh])

    parent_root = bytes(fresh[0][1].message.parent_root)
    if not chain.fork_choice.contains_block(parent_root):
        return SegmentResult(
            Outcome.RETRY, 0,
            error=ParentUnknown(
                f"segment parent {parent_root.hex()[:16]} unknown"))

    # Availability gate BEFORE any state work: a missing sidecar aborts
    # the window cheaply and names the block to fetch.
    for root, b in fresh:
        try:
            chain.data_availability.check_availability(b, root)
        except BlobsUnavailable as e:
            return SegmentResult(Outcome.RETRY, 0, error=e, needs_blobs=b)

    try:
        # Own copy: the replayer mutates it, and the store/snapshot
        # caches may hand back a shared object.
        pre_state = chain.state_at_block_root(parent_root).copy()
    except Exception as e:
        return SegmentResult(Outcome.RETRY, 0, error=e)

    snapshots: list = []
    rep = EpochReplayer(pre_state, chain.preset, chain.spec, chain.T,
                        verify_signatures=True,
                        pubkey_cache=chain.pubkey_cache)
    rep.post_block_hook = lambda state, signed: snapshots.append(
        state.copy())
    try:
        rep.apply_window([b for _, b in fresh])
    except WindowSignaturesInvalid as e:
        return SegmentResult(Outcome.FATAL, 0,
                             error=InvalidSignatures(str(e)), batched=True)
    except WindowRootMismatch as e:
        return SegmentResult(Outcome.FATAL, 0,
                             error=StateRootMismatch(str(e)), batched=True)
    except WindowBlockInvalid as e:
        return SegmentResult(Outcome.FATAL, 0,
                             error=InvalidBlock(str(e)), batched=True)
    except BlockError as e:
        out = Outcome.FATAL if isinstance(e, _DETERMINISTIC) \
            else Outcome.RETRY
        return SegmentResult(out, 0, error=e, batched=True)
    except Exception as e:
        return SegmentResult(Outcome.RETRY, 0, error=e, batched=True)

    # Window verdict passed — commit every block through the atomic
    # import path (store batch + fork choice + caches + head).
    imported = 0
    for (root, b), state in zip(fresh, snapshots):
        slot = int(b.message.slot)
        chain.per_slot_task(slot)
        try:
            chain.observed_block_producers.observe(
                slot, int(b.message.proposer_index), root)
        except Exception:
            pass  # dedup bookkeeping must not fail a verified window
        ex = ExecutedBlock(signed_block=b, block_root=root,
                           post_state=state)
        try:
            chain._import_block(ex, is_timely=False)
        except BlockIsAlreadyKnown:
            continue
        except _DETERMINISTIC as e:
            return SegmentResult(Outcome.FATAL, imported, error=e,
                                 batched=True)
        except Exception as e:
            return SegmentResult(Outcome.RETRY, imported, error=e,
                                 batched=True)
        imported += 1
    return SegmentResult(Outcome.OK, imported, batched=True)
