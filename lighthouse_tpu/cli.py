"""The ``lighthouse-tpu`` command-line tool.

One binary, subcommands — mirroring the reference's CLI tree
(``/root/reference/lighthouse/src/main.rs:315-319``: ``beacon_node``,
``validator_client``, ``account_manager``, ``database_manager``) plus the
``lcli`` developer tools (``transition-blocks``/``skip-slots`` per-phase
profilers, ``lcli/src/transition_blocks.rs:229,308-396``).

Run as ``python -m lighthouse_tpu.cli <subcommand> ...``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--preset", choices=["minimal", "mainnet"],
                   default="minimal")
    p.add_argument("--validators", type=int, default=64)
    p.add_argument("--spec-config", default="",
                   help="chain spec config.yaml (overrides the preset's "
                        "built-in spec)")
    p.add_argument("--dump-config", default="",
                   help="write the effective chain spec YAML to PATH and "
                        "exit (`clap_utils` --dump-config)")
    p.add_argument("--compile-cache", default="", metavar="DIR",
                   help="enable JAX's persistent compilation cache at DIR "
                        "(default: <repo>/.jax_cache; 'off' disables) so a "
                        "restarted node never re-pays the cold XLA compile "
                        "of the device pipelines")


def _maybe_enable_compile_cache(args) -> None:
    flag = getattr(args, "compile_cache", "")
    if flag == "off":
        return
    from .common.compile_cache import enable

    enable(flag or None)


def _effective_spec(args):
    from .types.chain_spec import ChainSpec

    if getattr(args, "spec_config", ""):
        return ChainSpec.from_yaml(open(args.spec_config).read())
    return None  # harness default for the preset


def _setup(args):
    from .crypto import bls
    from .testing.harness import StateHarness
    from .types.presets import MAINNET, MINIMAL

    _maybe_enable_compile_cache(args)
    bls.set_backend(args.backend if hasattr(args, "backend") else "fake")
    preset = MINIMAL if args.preset == "minimal" else MAINNET
    spec = _effective_spec(args)
    kwargs = {}
    if spec is not None:
        # The genesis state's fork follows the LOADED spec's schedule —
        # building (say) a Capella state under a config whose forks sit at
        # far-future would split the state shape from the transition code.
        kwargs["fork"] = spec.fork_name_at_epoch(0)
    return StateHarness(n_validators=args.validators, preset=preset,
                        spec=spec, **kwargs)


def cmd_transition_blocks(args) -> int:
    """Per-phase block-application profiler (`lcli transition-blocks`)."""
    from .state_transition import SignatureStrategy
    from .state_transition.per_block import process_block
    from .state_transition.per_slot import process_slots

    h = _setup(args)
    h.extend_chain(args.warmup_blocks)
    signed = h.build_block()
    pre_state = h.state
    fork = h.fork_at(int(signed.message.slot))
    strategy = (SignatureStrategy.VERIFY_BULK if args.backend != "fake"
                else SignatureStrategy.NO_VERIFICATION)

    phases = {"slot_advance": [], "block_processing": [], "state_root": []}
    for _ in range(args.runs):
        state = pre_state.copy()
        t0 = time.perf_counter()
        state = process_slots(state, int(signed.message.slot), h.preset,
                              h.spec, h.T)
        t1 = time.perf_counter()
        process_block(state, signed, fork, h.preset, h.spec, h.T,
                      strategy=strategy)
        t2 = time.perf_counter()
        state.tree_hash_root()
        t3 = time.perf_counter()
        phases["slot_advance"].append((t1 - t0) * 1e3)
        phases["block_processing"].append((t2 - t1) * 1e3)
        phases["state_root"].append((t3 - t2) * 1e3)

    out = {name: {"min_ms": round(min(v), 3),
                  "mean_ms": round(sum(v) / len(v), 3)}
           for name, v in phases.items()}
    out["runs"] = args.runs
    out["attestations_in_block"] = len(signed.message.body.attestations)
    print(json.dumps(out, indent=2))
    return 0


def cmd_skip_slots(args) -> int:
    """`lcli skip-slots`: cost of empty-slot advance (epoch boundaries)."""
    from .state_transition.per_slot import process_slots

    h = _setup(args)
    state = h.state
    t0 = time.perf_counter()
    process_slots(state.copy(), int(state.slot) + args.slots, h.preset,
                  h.spec, h.T)
    dt = (time.perf_counter() - t0) * 1e3
    print(json.dumps({"slots": args.slots, "total_ms": round(dt, 3),
                      "ms_per_slot": round(dt / args.slots, 3)}))
    return 0


def _load_identity(datadir: str) -> bytes:
    """Load (or mint + persist) the node's static X25519 identity key —
    the reference persists its libp2p keypair at ``<datadir>/beacon/
    network/key`` so the node id survives restarts; same deal here.
    Without a datadir the identity is ephemeral."""
    import os
    import secrets as pysecrets

    path = os.path.join(datadir, "node_key")
    if os.path.exists(path):
        try:
            with open(path) as f:
                key = bytes.fromhex(f.read().strip())
            if len(key) == 32:
                return key
        except ValueError:
            pass
        # Truncated/corrupt key file (e.g. a crash mid-write before the
        # atomic-rename scheme below existed): the identity is already
        # lost — mint a new one instead of bricking startup forever.
        print(f"warning: corrupt identity key at {path}; regenerating "
              f"(node id will change)")
    key = pysecrets.token_bytes(32)
    os.makedirs(datadir, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(key.hex())
    os.chmod(tmp, 0o600)
    os.replace(tmp, path)  # atomic: never a half-written identity
    return key


def cmd_beacon_node(args) -> int:
    """Run an interop beacon node + HTTP API (demo/devnet mode)."""
    from .api import HttpApiServer
    from .beacon_chain import BeaconChain
    from .common.slot_clock import SystemTimeSlotClock
    from .store import HotColdDB, SqliteStore
    from .validator_client import (
        InProcessBeaconNode, ValidatorClient, ValidatorStore)
    from .state_transition.genesis import interop_secret_key

    h = _setup(args)
    hdr = h.state.latest_block_header.copy()
    hdr.state_root = h.state.tree_hash_root()
    store = (HotColdDB(SqliteStore(args.datadir + "/beacon.sqlite"),
                       h.preset, h.spec, h.T) if args.datadir
             else HotColdDB.memory(h.preset, h.spec, h.T))
    # Resume from a previous run's persisted chain when the datadir holds
    # one (`ClientBuilder.build_beacon_chain` resume branch); otherwise
    # boot from interop genesis.
    chain = None
    if args.datadir:
        from .beacon_chain.errors import BlockError
        from .store import StoreCorruption
        try:
            chain = BeaconChain.resume(store=store, preset=h.preset,
                                       spec=h.spec, T=h.T)
            print(f"resumed chain at slot {chain.head.slot} "
                  f"head={chain.head.root.hex()[:12]}")
            rec = chain.last_recovery
            if rec is not None and (rec.quarantined or rec.replayed
                                    or rec.rebuilt_fork_choice):
                print(f"startup recovery: {rec.summary()}")
        except StoreCorruption:
            # Do NOT fall back to a fresh genesis chain here: the
            # BeaconChain constructor persists (overwriting the
            # fork-choice snapshot and clearing the journal), which
            # would destroy exactly the bytes the operator needs to
            # restore from.  Surface the actionable error instead.
            raise
        except BlockError:
            chain = None  # virgin datadir: no persisted chain yet
    if chain is None:
        chain = BeaconChain(store=store, genesis_state=h.state.copy(),
                            genesis_block_root=hdr.tree_hash_root(),
                            preset=h.preset, spec=h.spec, T=h.T)
    if args.validator_monitor_auto:
        from .beacon_chain.validator_monitor import ValidatorMonitor
        chain.validator_monitor = ValidatorMonitor(auto_register=True)
    # Wire networking: encrypted by default (`--insecure` keeps the
    # plaintext framing for debugging).  The identity key persists in
    # the datadir so scores/bans keyed on the node id survive restarts.
    net = None
    disco = None
    if args.listen_port is not None or args.boot_node:
        from .network.transport import WireNetwork

        static_key = _load_identity(args.datadir) if args.datadir else None
        net = WireNetwork(chain, name="bn",
                          port=args.listen_port or 0,
                          secure=not args.insecure,
                          static_key=static_key)
        mode = "plaintext (INSECURE)" if args.insecure else "noise-xx"
        print(f"wire transport up: tcp://127.0.0.1:{net.port} "
              f"[{mode}] node_id={net.node_id.hex()}")
        if args.boot_node:
            host, _, port_s = args.boot_node.rpartition(":")
            disco = net.discover(host or "127.0.0.1", int(port_s))
            print(f"discovery up: udp://127.0.0.1:{disco.udp_port} "
                  f"boot={args.boot_node}")
    api = HttpApiServer(chain, port=args.http_port)
    api.start()
    print(f"beacon node up: http://127.0.0.1:{api.port} "
          f"(validators={args.validators}, preset={args.preset})")
    vc = None
    km = None
    if args.with_validators:
        vstore = ValidatorStore()
        for i in range(args.validators):
            vstore.add_validator(interop_secret_key(i), index=i)
        vc = ValidatorClient(vstore, [InProcessBeaconNode(chain)], h.preset)
        if args.keymanager_port is not None:
            from .validator_client.keymanager import KeymanagerServer
            km = KeymanagerServer(
                vstore, port=args.keymanager_port,
                genesis_validators_root=bytes(
                    h.state.genesis_validators_root))
            km.start()
            print(f"keymanager API up: http://127.0.0.1:{km.port} "
                  f"token={km.token}")
    # Graceful-shutdown service (`environment`'s shutdown-signal task +
    # `beacon_chain` persist-on-drop): SIGTERM must reach the persist
    # path below, not kill the process mid-write.  Service threads run
    # under the TaskExecutor so shutdown signals, joins, and reports
    # stragglers (`common/task_executor` role).
    import signal

    from .common.task_executor import TaskExecutor

    executor = TaskExecutor()

    def _term(_sig, _frm):
        raise SystemExit(0)
    try:
        signal.signal(signal.SIGTERM, _term)
    except ValueError:
        pass  # non-main thread (embedded use) — rely on finally

    # 3/4-slot state-advance timer as a managed service thread
    # (`state_advance_timer.rs` spawn).
    def _advance_timer(stop):
        fired = -1
        consecutive_failures = 0
        while not stop.wait(0.1):
            try:
                s_now = clock.now()
                if clock.slot_progress() >= 0.75 and fired < s_now:
                    fired = s_now
                    chain.on_three_quarters_slot(s_now)
                consecutive_failures = 0
            except Exception as e:
                # transient failures are tolerated; a persistent one
                # surfaces through the executor's died-task report
                consecutive_failures += 1
                print(f"state-advance timer error: {e!r}")
                if consecutive_failures >= 3:
                    raise

    # Devnet clock: start at the next slot AFTER the (possibly resumed)
    # head — restarting at slot 0 against a resumed head would have the VC
    # proposing slot-1 blocks onto a later state.
    clock = SystemTimeSlotClock(
        genesis_time=int(time.time())
        - chain.head.slot * args.seconds_per_slot,
        seconds_per_slot=args.seconds_per_slot)
    last = chain.head.slot
    try:
        deadline = (time.time() + args.run_for) if args.run_for else None
        executor.spawn(_advance_timer, "state_advance_timer")
        while deadline is None or time.time() < deadline:
            slot = clock.now()
            if slot > last:
                last = slot
                chain.per_slot_task(slot)
                if vc is not None:
                    vc.on_slot(slot)
                print(f"slot {slot} head={chain.head.root.hex()[:12]} "
                      f"(slot {chain.head.slot})")
            time.sleep(0.1)
    except KeyboardInterrupt:
        pass
    finally:
        stragglers = executor.shutdown(timeout=3)
        if stragglers:
            print(f"warning: tasks did not stop: {stragglers}")
        if args.datadir:
            chain.persist()  # graceful-shutdown persistence
        if disco is not None:
            disco.close()
        if net is not None:
            net.close()
    if km is not None:
        km.stop()
    api.stop()
    return 0


def cmd_boot_node(args) -> int:
    """`boot_node`: run the standalone discovery registry."""
    from .network.discovery import BootNode

    boot = BootNode(port=args.port)
    print(f"boot node up: udp://127.0.0.1:{boot.port}")
    try:
        if args.run_for:
            time.sleep(args.run_for)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    boot.close()
    return 0


def cmd_account(args) -> int:
    """`account_manager`: create/import EIP-2335 keystores."""
    import getpass
    import os
    import secrets as pysecrets

    from .crypto import bls
    from .crypto.key_derivation import derive_path, validator_signing_path
    from .crypto.keystore import Keystore

    os.makedirs(args.dir, exist_ok=True)
    if args.account_cmd == "create":
        password = args.password or getpass.getpass("keystore password: ")
        seed = pysecrets.token_bytes(32)
        for i in range(args.count):
            sk_int = derive_path(seed, validator_signing_path(i))
            sk = bls.SecretKey(sk_int)
            ks = Keystore.encrypt(
                sk.serialize(), password,
                pubkey=sk.public_key().serialize(),
                path=validator_signing_path(i), scrypt_n=args.scrypt_n)
            out = os.path.join(args.dir, f"keystore-{i}.json")
            with open(out, "w") as f:
                f.write(ks.to_json())
            print(f"wrote {out} pubkey=0x{ks.pubkey[:16]}…")
        return 0
    if args.account_cmd == "list":
        for name in sorted(os.listdir(args.dir)):
            if name.endswith(".json"):
                with open(os.path.join(args.dir, name)) as f:
                    ks = Keystore.from_json(f.read())
                print(f"{name}: 0x{ks.pubkey} path={ks.path}")
        return 0
    print("unknown account command", file=sys.stderr)
    return 1


def cmd_warmup(args) -> int:
    """Pre-compile the device hot paths into the persistent cache
    (`--compile-cache`), so the next node process pays disk reads, not
    the ~17-minute cold XLA compile, on its first slot.  Off-TPU this is
    a no-op (the warmup API reports it)."""
    from .common.compile_cache import DEFAULT_BUCKETS, enable, warmup

    if args.compile_cache == "off":
        # A warmup that persists nothing is minutes of compile thrown
        # away the moment the process exits — refuse instead.
        print(json.dumps({"error": "warmup requires a persistent cache; "
                                   "drop --compile-cache off"}))
        return 2
    cache = enable(args.compile_cache or None)
    buckets = []
    for part in (args.shapes.split(",") if args.shapes else []):
        sets, _, keys = part.partition("x")
        buckets.append((int(sets), int(keys or 1)))
    out = warmup(buckets or DEFAULT_BUCKETS)
    out["cache_dir"] = cache
    print(json.dumps(out))
    return 0


def cmd_db(args) -> int:
    """`database_manager`: inspect a store."""
    from .store import DBColumn, SqliteStore

    kv = SqliteStore(args.path)
    out = {}
    for col in DBColumn:
        n = sum(1 for _ in kv.iter_column(col))
        if n:
            out[col.name] = n
    print(json.dumps(out, indent=2))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="lighthouse-tpu", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    bn = sub.add_parser("bn", help="run a beacon node (interop/devnet)")
    _add_common(bn)
    bn.add_argument("--backend", default="fake",
                    choices=["fake", "python", "tpu"])
    bn.add_argument("--http-port", type=int, default=5052)
    bn.add_argument("--seconds-per-slot", type=int, default=2)
    bn.add_argument("--with-validators", action="store_true")
    bn.add_argument("--keymanager-port", type=int, default=None,
                    help="serve the keymanager API (`--http` on the "
                         "reference VC; prints the bearer token)")
    bn.add_argument("--validator-monitor-auto", action="store_true",
                    help="track every observed validator "
                         "(`--validator-monitor-auto`)")
    bn.add_argument("--datadir", default="")
    bn.add_argument("--run-for", type=float, default=0,
                    help="seconds to run (0 = forever)")
    bn.add_argument("--listen-port", type=int, default=None,
                    help="TCP wire-transport listen port (0 = ephemeral; "
                         "omit to run without wire networking)")
    bn.add_argument("--boot-node", default="",
                    help="bootstrap UDP endpoint host:port (a boot-node "
                         "process or any node's discovery port)")
    bn.add_argument("--insecure", action="store_true",
                    help="disable the noise-xx encrypted transport and "
                         "speak legacy plaintext frames (debugging / "
                         "simulator escape hatch)")
    bn.set_defaults(fn=cmd_beacon_node)

    tb = sub.add_parser("transition-blocks",
                        help="per-phase block application profiler")
    _add_common(tb)
    tb.add_argument("--backend", default="fake",
                    choices=["fake", "python", "tpu"])
    tb.add_argument("--runs", type=int, default=5)
    tb.add_argument("--warmup-blocks", type=int, default=2)
    tb.set_defaults(fn=cmd_transition_blocks)

    ss = sub.add_parser("skip-slots", help="empty slot advance profiler")
    _add_common(ss)
    ss.add_argument("--backend", default="fake")
    ss.add_argument("--slots", type=int, default=8)
    ss.set_defaults(fn=cmd_skip_slots)

    ac = sub.add_parser("account", help="keystore management")
    ac.add_argument("account_cmd", choices=["create", "list"])
    ac.add_argument("--dir", default="validator_keys")
    ac.add_argument("--count", type=int, default=1)
    ac.add_argument("--password", default="")
    ac.add_argument("--scrypt-n", type=int, default=16384)
    ac.set_defaults(fn=cmd_account)

    db = sub.add_parser("db", help="database inspection")
    db.add_argument("path")
    db.set_defaults(fn=cmd_db)

    wu = sub.add_parser("warmup",
                        help="pre-compile the device hot paths into the "
                             "persistent compilation cache")
    wu.add_argument("--compile-cache", default="", metavar="DIR",
                    help="cache directory (default: <repo>/.jax_cache)")
    wu.add_argument("--shapes", default="",
                    help="comma-separated (sets)x(keys) buckets, e.g. "
                         "'256x16,256x1' (default: the slot-path buckets)")
    wu.set_defaults(fn=cmd_warmup)

    bnode = sub.add_parser("boot-node",
                           help="standalone discovery registry "
                                "(`boot_node` subcommand / discv5 role)")
    bnode.add_argument("--port", type=int, default=15000)
    bnode.add_argument("--run-for", type=float, default=0)
    bnode.set_defaults(fn=cmd_boot_node)

    args = ap.parse_args(argv)
    if getattr(args, "dump_config", ""):
        from .types.chain_spec import ChainSpec
        spec = _effective_spec(args) or (
            ChainSpec.minimal() if getattr(args, "preset", "") == "minimal"
            else ChainSpec.mainnet())
        with open(args.dump_config, "w") as f:
            f.write(spec.to_yaml())
        print(f"wrote effective chain spec to {args.dump_config}")
        return 0
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
