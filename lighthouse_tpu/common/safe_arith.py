"""Safe arithmetic — the role of ``consensus/safe_arith``
(``/root/reference/consensus/safe_arith/src/lib.rs``): spec math is
u64 with DEFINED overflow behavior (an overflowing block is INVALID,
not a wrapped number).

Python ints don't overflow, so the risk here is inverted: a negative
intermediate or an over-wide value silently flows into a numpy uint64
column and WRAPS there.  These helpers make the u64 bounds explicit at
the spec seams; `state_transition` uses them where the reference calls
``safe_add``/``safe_sub``/``safe_mul``.

Where it IS applied (every scalar spec seam — VERDICT r5 item 10):
balance credit/debit (`helpers.increase_balance`/`decrease_balance`),
epoch/slot products and exit-epoch sums (`helpers.compute_*`,
`mutations.initiate_validator_exit`), the whole slashing path
(`mutations.slash_validator`: slashings accumulator, penalty and
whistleblower/proposer reward chains), attestation proposer-reward
numerators and denominators, sync-aggregate reward derivation, deposit
effective-balance rounding, voluntary-exit eligibility epochs, and the
withdrawal sweep (both the scalar oracle and the vectorized fast path's
scalar emissions) in `per_block.py`.

Where it is NOT applied, and why: the vectorized epoch-processing
columns (`per_epoch.py`, `per_epoch_device.py`).  Those paths do their
arithmetic over whole uint64/int64 numpy columns where a per-element
python guard would deoptimize the single-pass sweep by orders of
magnitude; instead they bound inputs structurally — effective balances
are ≤ MAX_EFFECTIVE_BALANCE (32 ETH ≈ 2^35) and reward/penalty
numerators are products of ≤2^35 values with ≤2^6 weights over ≤2^40
validators, provably inside u64/i64 — and saturate explicitly
(`np.minimum`/`where` clamps) at the few seams (inactivity-score
decrement, balance deltas) where the spec saturates.  The scalar
stepwise oracle cross-checked against them in
`tests/test_vectorized_transition.py` routes through these helpers, so
a silent wrap in the vectorized path cannot survive the differential.
"""

from __future__ import annotations

U64_MAX = 2**64 - 1


class ArithError(OverflowError):
    """The reference's ``ArithError`` — consensus code treats it as
    'operation invalid', never as a crash."""


def safe_add(a: int, b: int) -> int:
    r = int(a) + int(b)
    if r > U64_MAX:
        raise ArithError(f"u64 add overflow: {a} + {b}")
    return r


def safe_sub(a: int, b: int) -> int:
    r = int(a) - int(b)
    if r < 0:
        raise ArithError(f"u64 sub underflow: {a} - {b}")
    return r


def safe_mul(a: int, b: int) -> int:
    r = int(a) * int(b)
    if r > U64_MAX:
        raise ArithError(f"u64 mul overflow: {a} * {b}")
    return r


def safe_div(a: int, b: int) -> int:
    if int(b) == 0:
        raise ArithError(f"division by zero: {a} / {b}")
    return int(a) // int(b)


def saturating_sub(a: int, b: int) -> int:
    """``saturating_sub`` — clamps at zero (balance decreases)."""
    return max(int(a) - int(b), 0)


def assert_u64(v: int, what: str = "value") -> int:
    v = int(v)
    if not 0 <= v <= U64_MAX:
        raise ArithError(f"{what} out of u64 range: {v}")
    return v
