"""Structured logging — ``common/logging``
(``/root/reference/common/logging/src/lib.rs:28,196,221``): slog-style
key=value records with the reference's aligned terminal format, a ring
buffer for SSE re-broadcast (the ``/lighthouse/logs`` stream), and a
capture logger for tests."""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

LEVELS = {"TRCE": 0, "DEBG": 1, "INFO": 2, "WARN": 3, "ERRO": 4, "CRIT": 5}


class Logger:
    """Key-value structured logger with slog-ish aligned output."""

    def __init__(self, name: str = "", level: str = "INFO",
                 stream=None, ring_size: int = 1024):
        self.name = name
        self.level = level
        self.stream = stream if stream is not None else sys.stderr
        self.ring: Deque[dict] = deque(maxlen=ring_size)
        self._lock = threading.Lock()
        self._subscribers: List = []

    def child(self, name: str) -> "Logger":
        out = Logger.__new__(Logger)
        out.__dict__.update(self.__dict__)
        out.name = f"{self.name}/{name}" if self.name else name
        return out

    def _log(self, level: str, msg: str, **kv) -> None:
        if LEVELS[level] < LEVELS[self.level]:
            return
        rec = {"ts": time.time(), "level": level, "module": self.name,
               "msg": msg, **kv}
        line = self.format(rec)
        with self._lock:
            self.ring.append(rec)
            if self.stream is not None:
                print(line, file=self.stream)
            for fn in self._subscribers:
                fn(rec)

    @staticmethod
    def format(rec: dict) -> str:
        ts = time.strftime("%b %d %H:%M:%S", time.localtime(rec["ts"]))
        kv = ", ".join(f"{k}: {v}" for k, v in rec.items()
                       if k not in ("ts", "level", "module", "msg"))
        mod = f" [{rec['module']}]" if rec["module"] else ""
        base = f"{ts} {rec['level']}{mod} {rec['msg']:<40}"
        return f"{base} {kv}" if kv else base

    def subscribe(self, fn) -> None:
        """SSE-rebroadcast hook (`logging/src/lib.rs` SSEDrain role)."""
        self._subscribers.append(fn)

    def trace(self, msg, **kv):
        self._log("TRCE", msg, **kv)

    def debug(self, msg, **kv):
        self._log("DEBG", msg, **kv)

    def info(self, msg, **kv):
        self._log("INFO", msg, **kv)

    def warn(self, msg, **kv):
        self._log("WARN", msg, **kv)

    def error(self, msg, **kv):
        self._log("ERRO", msg, **kv)

    def crit(self, msg, **kv):
        self._log("CRIT", msg, **kv)


def test_logger() -> Logger:
    """Capture-only logger (`test_logger`): records to the ring, no IO."""
    return Logger(level="TRCE", stream=None)
