"""Persistent XLA compilation cache + explicit hot-path warmup.

The first BLS batch in a fresh process pays the full XLA/Mosaic compile
of the fused pairing pipeline — ~17 minutes through the axon tunnel
(``batch_cold_ms`` ≈ 1,049,000 in BENCH_LATEST.json) — because nothing
wired up JAX's persistent compilation cache for the node entry points
(only bench.py and the test conftest did).  Two pieces fix that:

- :func:`enable` points JAX at a persistent on-disk cache (configurable
  directory; ``--compile-cache`` in the CLI, ``LH_TPU_JAX_CACHE`` in the
  environment).  Safe to call from any entry point, idempotent, and a
  graceful no-op on JAX builds without the feature.
- :func:`warmup` pre-compiles the bucketed ``(sets, keys)`` shapes of
  the fused BLS pipeline via ``jit.lower(...).compile()`` — abstract
  shapes only, no device data — so a restarted node (or one warming in
  the background at boot) never pays the cold compile in the slot path:
  with the cache enabled the compiles land on disk, and the first real
  verify of each bucket is a cache hit.  On CPU this is a graceful
  no-op: the Pallas programs only lower on TPU, and the scanned-XLA
  twins take minutes per shape on one core — warming them would cost
  more than it saves.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple

_state = {"dir": None, "monitoring": False}


# ---------------------------------------------------------------------------
# Compile-cache observability.  JAX emits monitoring events on every
# cache-eligible compile ('/jax/compilation_cache/compile_requests_use_
# cache') and on every persistent-cache hit ('/jax/compilation_cache/
# cache_hits'); a request without a hit is a miss — which on this box
# costs MINUTES per pairing-scale program.  The listener feeds a labeled
# counter family (`compile_cache_events_total{event="request"|"hit"}`)
# and a scrape-time collector derives the miss count, so a cold-cache
# node is visible on /metrics instead of just "mysteriously slow".
# ---------------------------------------------------------------------------

_EVENT_MAP = {
    "/jax/compilation_cache/compile_requests_use_cache": "request",
    "/jax/compilation_cache/cache_hits": "hit",
}


def _on_jax_event(event: str, **_kw) -> None:
    label = _EVENT_MAP.get(event)
    if label is None:
        return
    from .metrics import REGISTRY
    REGISTRY.counter(
        "compile_cache_events_total",
        "persistent XLA compile-cache activity",
        labelnames=("event",)).labels(label).inc()


def _collect_cache_misses() -> None:
    from .metrics import REGISTRY
    fam = REGISTRY.counter(
        "compile_cache_events_total",
        "persistent XLA compile-cache activity",
        labelnames=("event",))
    requests = fam.labels("request").value
    hits = fam.labels("hit").value
    REGISTRY.gauge(
        "compile_cache_misses",
        "cache-eligible compiles not served from the persistent "
        "cache").set(max(requests - hits, 0.0))


def install_monitoring() -> bool:
    """Register the jax monitoring listener (idempotent; a jax build
    without the monitoring API degrades to counters that stay 0).

    Called from :func:`enable` — the entry points that turn the
    persistent cache on are exactly the processes whose hit/miss
    traffic matters — NOT at module import: this module must stay
    cheap to import (``default_dir`` readers shouldn't pay the
    multi-second jax import)."""
    if _state["monitoring"]:
        return True
    try:
        from jax import monitoring as _mon  # public front
    except Exception:
        try:
            from jax._src import monitoring as _mon  # older builds
        except Exception:
            return False
    try:
        _mon.register_event_listener(_on_jax_event)
    except Exception:
        return False
    from .metrics import REGISTRY
    REGISTRY.register_collector(_collect_cache_misses)
    # The device ledger taps the SAME event stream for per-subsystem
    # compile attribution — one listener each (the ledger's install is
    # idempotent, so the two never double-register).
    from .device_ledger import LEDGER
    LEDGER._maybe_install_listener()
    _state["monitoring"] = True
    return True


def default_dir() -> str:
    """``LH_TPU_JAX_CACHE`` or ``<repo>/.jax_cache`` (the directory
    bench.py and the tests already share; the registry default IS the
    real repo-relative path)."""
    from .knobs import knob_str
    return knob_str("LH_TPU_JAX_CACHE")


def enable(cache_dir: Optional[str] = None,
           min_compile_time_secs: float = 2.0) -> Optional[str]:
    """Enable JAX's persistent compilation cache at ``cache_dir``.

    Returns the cache directory actually configured, or None when the
    running JAX has no persistent-cache support (ancient builds — run
    uncached rather than fail)."""
    import jax

    install_monitoring()  # hit/miss counters ride the cache lifecycle

    cache = os.path.abspath(cache_dir or default_dir())
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
    except Exception:
        return None
    try:
        # The cache object is lazily initialised ONCE per process; if a
        # compile already ran against another directory, the config
        # update alone is ignored — reset so the new dir takes effect.
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:
        pass  # private API drift: first-configured dir keeps winning
    _state["dir"] = cache
    return cache


def is_enabled() -> bool:
    return _state["dir"] is not None


def cache_dir() -> Optional[str]:
    return _state["dir"]


# The shape buckets a mainnet node hits in the slot path: the pipeline
# sub-batch of the 1024-set aggregate-attestation batch (16-key
# committees), the 256-set sync-committee shape (dedup collapses it to
# K=1), and the small head-of-slot batches.  (sets, keys) pairs; keys
# bucket to next-pow2(signer count) and sets to the C chunk count
# exactly like the dispatcher (which sub-batches at 256 sets, so larger
# batches reuse the 256-set executable).
DEFAULT_BUCKETS: Tuple[Tuple[int, int], ...] = (
    (256, 16), (256, 1), (8, 16), (8, 1),
)


def warmup(buckets: Sequence[Tuple[int, int]] = DEFAULT_BUCKETS,
           table_cols: int = 1 << 15) -> Dict[str, object]:
    """Pre-compile the fused BLS pipeline for each ``(sets, keys)``
    bucket, plus the shared finalize/verdict programs.

    Uses ``jit.lower(abstract shapes).compile()`` — no device inputs are
    materialised and nothing executes; with :func:`enable` active every
    compile is persisted, so the next process (or the next call in this
    one) hits the disk cache instead of XLA.  Returns a summary dict;
    ``{"skipped": "cpu"}`` off-TPU (see module docstring).
    """
    import jax

    if jax.default_backend() != "tpu":
        return {"skipped": "cpu", "compiled": []}

    import numpy as np

    from ..crypto import htc_kernel as HK
    from ..crypto import pairing_kernel as PK
    from ..crypto import tpu_backend as TB
    from ..ops.merkle import _next_pow2

    S = PK.PREP_S
    aval = jax.ShapeDtypeStruct
    compiled = []
    for sets, keys in buckets:
        K = _next_pow2(max(1, int(keys)))
        C = _next_pow2(max(1, -(-int(sets) // S)))
        args = (
            aval((64, table_cols), np.uint32),           # pubkey table
            aval((C * K * S,), np.int32),                # idx
            aval((1, C * K * S), np.int32),              # kmask
            aval((1, C * S), np.uint32),                 # lo
            aval((1, C * S), np.uint32),                 # hi
            aval((2 * HK.BLOCK_ROWS, C * 2 * S), np.uint32),  # u planes
            aval((128, C * S), np.uint32),               # sig cols
            aval((1, C * S), np.int32),                  # sigmask
            aval((1, C * S), np.int32),                  # setlive
        )
        TB.fused_pipeline_jit().lower(*args, K=K).compile()
        compiled.append({"sets": int(sets), "keys": int(keys),
                         "C": C, "K": K})
    # The shared tail: the finalize fold at the 1- and 4-dispatch group
    # widths + the scalar verdict combine (the donated twin — the
    # dispatcher's hot-path entry, so the persisted executable matches
    # its cache key).
    for m in (128, 512):
        PK.finalize_kernel_call_donated.lower(
            aval((384, m), np.uint32)).compile()
    for g in (1, 4):
        TB._combine_verdict.lower(
            aval((1, 1), np.int32), aval((g,), np.bool_)).compile()
    return {"cache_dir": cache_dir(), "compiled": compiled}
