"""The single typed accessor layer for every ``LIGHTHOUSE_TPU_*`` knob.

Before this module the tree had four truthiness dialects for its ~23
environment knobs — bare-truthy (``os.environ.get(name)``), ``!= "0"``,
``== "1"`` and ``not in ("0", "false", "")`` — which is how
``LIGHTHOUSE_TPU_NO_NATIVE=0`` came to *disable* the native backend.
Every knob is now declared ONCE in :data:`KNOBS` (name, type, default,
doc) and read ONLY through the typed accessors here:

- ``knob_bool``    — one truthiness convention: true ∈ {1, true, yes,
  on}, false ∈ {0, false, no, off}; empty means UNSET (the ``VAR=``
  shell idiom → the default); anything else is a :class:`KnobError`.
- ``knob_tribool`` — three-state for auto-detected features: unset /
  ``auto`` / ``""`` → None (probe the backend), else the bool sets.
- ``knob_int`` / ``knob_float`` — parsed with an actionable error on
  malformed values and clamped to the registry's [min, max] range.
- ``knob_str`` / ``knob_choice`` — the latter validated against the
  registry's choice set.

The ``knob-registry`` checker (:mod:`lighthouse_tpu.analysis`) enforces
that no code outside this module reads ``LIGHTHOUSE_TPU_*`` names from
``os.environ``, and that every literal knob name appearing anywhere in
the tree is declared here — a typo'd knob is a lint failure, not a
silently-ignored setting.  The README knob table is generated from this
registry (``scripts/lint.py --fix-readme``).

This module must stay import-cheap and dependency-free (stdlib only):
it is imported by ``common.tracing`` and the crypto hot paths.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple, Union


class KnobError(ValueError):
    """A malformed or undeclared knob.  Subclasses ``ValueError`` so
    call sites that historically raised/caught ValueError keep
    working."""


_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")
# An EMPTY value means "unset" (the `VAR= cmd` shell idiom), never
# false: knob_bool falls back to the default, knob_tribool to auto.


@dataclass(frozen=True)
class Knob:
    """One declared environment knob."""
    name: str
    type: str               # bool | tribool | int | float | str | choice
    default: object         # the REAL default the accessors return
    doc: str                # one line, rendered in the README table
    choices: Tuple[str, ...] = ()
    min_value: Optional[float] = None
    max_value: Optional[float] = None
    display_default: Optional[str] = None  # README rendering override
    #   (machine-dependent or multi-site defaults declare their
    #   human-readable form HERE, next to the knob — not in the
    #   renderer)


KNOBS: Dict[str, Knob] = {}


def _declare(name: str, type: str, default: object, doc: str,
             choices: Tuple[str, ...] = (),
             min_value: Optional[float] = None,
             max_value: Optional[float] = None,
             display_default: Optional[str] = None) -> None:
    KNOBS[name] = Knob(name, type, default, doc, choices,
                       min_value, max_value, display_default)


# ---------------------------------------------------------------------------
# The registry.  Every LIGHTHOUSE_TPU_* knob in the tree, plus the
# LH_TPU_JAX_CACHE compile-cache path.  Keep docs to one line — they
# render as the README knob table.
# ---------------------------------------------------------------------------

# -- crypto / BLS hot path --
_declare("LIGHTHOUSE_TPU_NO_NATIVE", "bool", False,
         "Disable the native C++ BLS library; verify via device/python "
         "fallbacks.")
_declare("LIGHTHOUSE_TPU_MXU", "tribool", "auto",
         "Route band products through the MXU matmul formulation "
         "(auto: on iff the backend is a real TPU).")
_declare("LIGHTHOUSE_TPU_PIPELINE_SETS", "int", 1024,
         "Sub-batch size of the staged BLS executor; 0 disables "
         "pipelining.", min_value=0)
_declare("LIGHTHOUSE_TPU_SHARED_MIN", "int", 8,
         "Batch size from which the collapsed shared-key verify path "
         "wins over the general path.", min_value=1)
_declare("LIGHTHOUSE_TPU_HOST_FASTPATH_MAX", "int", 4,
         "Batches up to this many sets verify on the host native "
         "pairing; 0 keeps everything on-device.", min_value=0)

# -- state transition --
_declare("LIGHTHOUSE_TPU_OVERLAP_BLOCK_SIGS", "bool", True,
         "Overlapped block import: dispatch the block's signature batch "
         "asynchronously before the participation/rewards phase (0 = "
         "trailing synchronous verify, the oracle).")
_declare("LIGHTHOUSE_TPU_BLOCK_SIG_SHARD", "tribool", "auto",
         "Route block signature batches through the mesh-sharded BLS "
         "path (auto: on iff the TPU backend runs on >1 device).")
_declare("LIGHTHOUSE_TPU_BATCHED_ATTS", "bool", True,
         "Columnar batched attestation processing (0 = scalar spec "
         "oracle).")
_declare("LIGHTHOUSE_TPU_SINGLE_PASS_EPOCH", "bool", True,
         "Fused single-pass epoch transition (0 = stepwise oracle).")
_declare("LIGHTHOUSE_TPU_EPOCH_DEVICE", "bool", False,
         "Route the fused epoch rewards/inactivity sweep to the "
         "device.")
_declare("LIGHTHOUSE_TPU_DEVICE_STATE", "bool", True,
         "Device-resident BeaconState: HBM is the hashing source of "
         "truth (0 = host incremental oracle).")
_declare("LIGHTHOUSE_TPU_BATCH_REPLAY", "tribool", "auto",
         "Epoch-batched replay for range sync / recovery / backfill: "
         "one window-wide signature batch, known state roots, one "
         "boundary root (auto: batch windows of >= 4 blocks; 0 = "
         "serial BlockReplayer oracle).")

# -- block production / op pool --
_declare("LIGHTHOUSE_TPU_DEVICE_PACK", "bool", True,
         "Fixed-shape device greedy-pack for attestation max-cover "
         "(0 = host CELF oracle).")
_declare("LIGHTHOUSE_TPU_PACK_JIT", "tribool", "auto",
         "Force the jitted pack engine on/off (auto: jit iff the "
         "backend is a real TPU; numpy rounds engine otherwise).")
_declare("LIGHTHOUSE_TPU_SPECULATIVE_PRODUCE", "bool", True,
         "Pre-advance the next slot's state on a COW share during the "
         "slot tail; production adopts it iff the head is unchanged "
         "(0 = advance serially at production time).")

# -- fork choice --
_declare("LIGHTHOUSE_TPU_DEVICE_FORKCHOICE", "bool", True,
         "Columnar device proto-array (0 = host walk oracle).")
_declare("LIGHTHOUSE_TPU_FORKCHOICE_JIT", "tribool", "auto",
         "Force the jitted fork-choice engine on/off (auto: jit iff "
         "the backend is a real TPU).")
_declare("LIGHTHOUSE_TPU_FORKCHOICE_JIT_MAX_DEPTH", "int", 512,
         "Tree depth past which the jit engine's per-level loop "
         "yields to the host walk.", min_value=1)

# -- merkle / device residency --
_declare("LIGHTHOUSE_TPU_MESH_DEVICES", "int", 0,
         "Axis size of the process-wide named mesh every device "
         "subsystem places residency on (parallel/mesh). 0 = auto: "
         "all local devices on a real TPU backend, 1 otherwise; N "
         "clamps to the local device count. 1 degenerates every "
         "sharded column/program to the single-device spelling.",
         min_value=0, display_default="0 (auto)")
_declare("LIGHTHOUSE_TPU_PUSH_CHUNK_ROWS", "int", 1 << 18,
         "H2D streaming chunk rows for big column pushes (leaf builds "
         "default 2^18, registry builds 2^17); <= 0 disables "
         "chunking.", display_default="2^18 / 2^17")

# -- KZG / Deneb --
_declare("LIGHTHOUSE_TPU_KZG_DEVICE", "tribool", "auto",
         "Force device KZG verification on/off (auto: device iff the "
         "backend is a real TPU).")

# -- store --
_declare("LIGHTHOUSE_TPU_STORE_SYNC", "choice", "normal",
         "SQLite PRAGMA synchronous level for the on-disk store.",
         choices=("off", "normal", "full", "extra"))

# -- streaming verification --
_declare("LIGHTHOUSE_TPU_RESILIENT", "bool", True,
         "Wrap the global BLS backend in the resilience envelope "
         "(deadline/retry/breaker/host fallback).")
_declare("LIGHTHOUSE_TPU_STREAM_SLO_MS", "float", 250.0,
         "Streaming verification per-message latency SLO driving "
         "adaptive micro-batching.", min_value=1.0)
_declare("LIGHTHOUSE_TPU_STREAM_MAX_BATCH", "int", 256,
         "Streaming verification bucket dispatch cap.", min_value=1)
_declare("LIGHTHOUSE_TPU_VERIFY_DEADLINE_MS", "float", 8000.0,
         "Device dispatch watchdog deadline; <= 0 disables the "
         "watchdog entirely.")
_declare("LIGHTHOUSE_TPU_BREAKER_N", "int", 5,
         "Consecutive device faults that trip the circuit breaker to "
         "host fallback.", min_value=1)

# -- proof serving --
_declare("LIGHTHOUSE_TPU_PROOF_DEVICE", "bool", True,
         "Serve Merkle proofs by device gather from the resident field "
         "tree (0 = host-walk oracle path).")
_declare("LIGHTHOUSE_TPU_PROOF_WINDOW_MS", "float", 2.0,
         "Proof-server micro-batching window: concurrent requests "
         "arriving within it coalesce into one device gather.",
         min_value=0.0)
_declare("LIGHTHOUSE_TPU_PROOF_MAX_BATCH", "int", 1024,
         "Distinct gindices that dispatch a proof batch early, before "
         "the window closes.", min_value=1)

# -- observability --
_declare("LIGHTHOUSE_TPU_TRACE", "bool", False,
         "Enable slot-scope tracing at import.")
_declare("LIGHTHOUSE_TPU_TRACE_RING", "int", 64,
         "Fully-assembled slot traces kept in the ring.", min_value=1)
_declare("LIGHTHOUSE_TPU_DEVICE_LEDGER", "bool", True,
         "Device ledger: per-subsystem HBM/transfer/compile accounting "
         "(0 freezes all counters — escape hatch only).")
_declare("LIGHTHOUSE_TPU_DEVICE_LEDGER_SLOTS", "int", 64,
         "Per-slot device-transfer delta entries kept in the ledger "
         "ring.", min_value=1)

# -- SLO engine / node health --
_declare("LIGHTHOUSE_TPU_SLO", "bool", True,
         "Evaluate the declarative SLO registry and publish node "
         "health (0 = engine constructed but never evaluated).")
_declare("LIGHTHOUSE_TPU_SLO_FAST_WINDOW_S", "float", 60.0,
         "Fast-burn rolling attainment window (SRE short window).",
         min_value=0.1)
_declare("LIGHTHOUSE_TPU_SLO_SLOW_WINDOW_S", "float", 360.0,
         "Slow-burn rolling attainment window (SRE long window).",
         min_value=0.1)
_declare("LIGHTHOUSE_TPU_SLO_BLOCK_IMPORT_MS", "float", 150.0,
         "block_import objective: p99 wall budget per block import.",
         min_value=1.0)
_declare("LIGHTHOUSE_TPU_SLO_SHED_PCT", "float", 0.1,
         "shed_rate objective: max percent of submitted messages shed.",
         min_value=0.0)
_declare("LIGHTHOUSE_TPU_SLO_FALLBACK_PCT", "float", 1.0,
         "host_fallback_rate objective: max percent of dispatches "
         "served by the host oracle.", min_value=0.0)
_declare("LIGHTHOUSE_TPU_SLO_PROOF_SERVE_MS", "float", 50.0,
         "proof_serve objective: p99 wall budget per served proof "
         "request.", min_value=1.0)
_declare("LIGHTHOUSE_TPU_SLO_HYSTERESIS", "int", 2,
         "Consecutive evaluations a new health state must hold before "
         "the node transitions.", min_value=1)

# -- toolchain --
# The registry default is the REAL repo-relative path (usable by any
# accessor call); the README renders it as "<repo>/.jax_cache".
_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
_declare("LH_TPU_JAX_CACHE", "str",
         os.path.join(_REPO_ROOT, ".jax_cache"),
         "Directory of the persistent XLA compilation cache "
         "(default: <repo>/.jax_cache).",
         display_default="<repo>/.jax_cache")


# ---------------------------------------------------------------------------
# Accessors
# ---------------------------------------------------------------------------

def _raw(name: str) -> Optional[str]:
    if name not in KNOBS:
        raise KnobError(
            f"undeclared knob {name!r}: every LIGHTHOUSE_TPU_* knob "
            f"must be declared in lighthouse_tpu/common/knobs.py")
    raw = os.environ.get(name)
    # Empty means UNSET for EVERY knob type (the `VAR= cmd` shell
    # idiom) — one rule, not a per-accessor quirk.
    if raw is not None and raw.strip() == "":
        return None
    return raw


def knob_bool(name: str, default: Optional[bool] = None) -> bool:
    """The ONE boolean convention.  Unset or empty → the registry
    default."""
    raw = _raw(name)
    if raw is None:
        return bool(KNOBS[name].default if default is None else default)
    v = raw.strip().lower()
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    raise KnobError(
        f"{name}={raw!r}: expected a boolean — one of "
        f"{_TRUE + _FALSE} (or unset for the default)")


def knob_tribool(name: str) -> Optional[bool]:
    """Three-state knob for auto-detected features: returns None when
    unset / ``auto`` / ``""`` (caller probes the backend), else the
    forced boolean."""
    raw = _raw(name)
    if raw is None:
        return None
    v = raw.strip().lower()
    if v == "auto":
        return None
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    raise KnobError(
        f"{name}={raw!r}: expected 'auto' or a boolean — one of "
        f"{_TRUE + _FALSE}")


def _clamp(name: str, value: float) -> float:
    k = KNOBS[name]
    clamped = value
    if k.min_value is not None and value < k.min_value:
        clamped = k.min_value
    if k.max_value is not None and value > k.max_value:
        clamped = k.max_value
    if clamped != value:
        # Clamping is never silent: the operator asked for a value the
        # registry range rejects — run with the boundary, but say so.
        import warnings
        warnings.warn(
            f"{name}={value} outside the registry range "
            f"[{k.min_value}, {k.max_value}] — clamped to {clamped}",
            stacklevel=3)
    return clamped


def knob_int(name: str, default: Optional[int] = None) -> int:
    """Integer knob, clamped to the registry range.  ``default``
    overrides the registry default for sites with a site-specific one
    (e.g. the two PUSH_CHUNK_ROWS builders)."""
    raw = _raw(name)
    if raw is None:
        return int(KNOBS[name].default if default is None else default)
    try:
        value = int(raw.strip())
    except ValueError:
        raise KnobError(
            f"{name}={raw!r}: expected an integer (default "
            f"{KNOBS[name].default if default is None else default}); "
            f"unset the variable to use the default") from None
    return int(_clamp(name, value))


def knob_float(name: str, default: Optional[float] = None) -> float:
    raw = _raw(name)
    if raw is None:
        return float(KNOBS[name].default if default is None else default)
    try:
        value = float(raw.strip())
    except ValueError:
        raise KnobError(
            f"{name}={raw!r}: expected a number (default "
            f"{KNOBS[name].default if default is None else default}); "
            f"unset the variable to use the default") from None
    return float(_clamp(name, value))


def knob_str(name: str, default: Optional[str] = None) -> str:
    raw = _raw(name)
    if raw is None:
        return str(KNOBS[name].default if default is None else default)
    return raw


def knob_choice(name: str, default: Optional[str] = None) -> str:
    """Validated against the registry's choice set (lower-cased) —
    including an explicitly passed ``default``, so a call-site typo
    cannot smuggle an out-of-set value past the contract."""
    k = KNOBS[name]
    raw = _raw(name)
    if raw is None:
        raw = str(k.default if default is None else default)
    v = raw.strip().lower()
    if v not in k.choices:
        raise KnobError(
            f"{name}={raw!r}: expected one of {sorted(k.choices)}")
    return v


# ---------------------------------------------------------------------------
# README table generation (consumed by scripts/lint.py and the
# readme-drift checker: generated table == committed README section).
# ---------------------------------------------------------------------------

def _default_repr(k: Knob) -> str:
    if k.display_default is not None:
        return k.display_default
    if k.type == "bool":
        return "on" if k.default else "off"
    return str(k.default)


def render_knob_table() -> str:
    """The README knob table, one row per registry entry."""
    rows = ["| Knob | Type | Default | Meaning |",
            "|---|---|---|---|"]
    for name in sorted(KNOBS):
        k = KNOBS[name]
        rows.append(f"| `{k.name}` | {k.type} | `{_default_repr(k)}` "
                    f"| {k.doc} |")
    return "\n".join(rows) + "\n"
