"""Device ledger — unified HBM/transfer/compile accounting, per subsystem.

Before this module, five device subsystems (BLS shard, DeviceTree /
registry mirror, packed-column cache, fork-choice vote columns, slasher
planes) each owned ad-hoc residency accounting: ``ops/device_tree.
RESIDENCY_STATS`` covered the tree/registry path only, the BLS pipeline
accounted zero transfer bytes, and nothing in the node could answer
"how many HBM bytes does each subsystem hold, what moved over PCIe this
slot, and what did we recompile?".  The ledger is ONE process-wide,
thread-safe accounting layer every device subsystem reports into,
attributed by the fixed :data:`SUBSYSTEMS` enum:

- **transfers** — H2D/D2H bytes + op counts (:meth:`DeviceLedger.
  note_transfer`).  ``ops/device_tree.note_push/note_pull`` route here
  with the *ambient* attribution (:meth:`DeviceLedger.attribute` — a
  thread-local context the materialize/scatter/pull seams set), so the
  legacy ``RESIDENCY_STATS`` surface becomes a ledger-backed view and
  every existing caller keeps working.
- **dispatches** — device dispatch counts + device-verify wall time,
  fed from the existing seams: the verification-service resilience
  envelopes (stream bls / kzg / global), ``sig_dispatch``'s direct
  host-backend path, and the sharded BLS entry points.
- **compiles** — per-program compile events from the jax monitoring
  listener PR 13 already taps for the cache counters
  (``/jax/compilation_cache/compile_requests_use_cache``), attributed
  by the ambient subsystem at compile time (``unattributed`` when a
  compile happens outside any seam — warmups, scripts).
- **HBM residency watermarks** — live resident bytes per subsystem with
  a high-water mark, maintained by :class:`ResidencyToken` handles the
  owning objects (DeviceTree, DeviceRegistryMirror, the fork-choice
  vote mirror, the slasher planes) update at materialize/share/drop
  seams; a dropped owner releases via ``weakref.finalize``.
- **per-shard transfers** — since the PR-20 mesh layer, every
  ``parallel/mesh`` placement seam additionally reports the bytes
  DELIVERED to each mesh shard (:meth:`DeviceLedger.
  note_shard_transfer`).  Shard rows answer "what landed on device i",
  so a replicated column counts its full size on EVERY shard (one host
  copy fans out over ICI) while a batch-sharded column counts 1/d per
  shard — the per-subsystem families above stay the host-wire totals.

Surfaces:

- ``/lighthouse/device`` — the HTTP scoreboard (JSON: per-subsystem
  bytes/ops/watermarks/compiles, plus the per-slot delta ring keyed to
  the slot numbers the trace ring uses; ``chain.per_slot_task`` calls
  :meth:`DeviceLedger.mark_slot` next to ``tracing.set_slot``).
- Prometheus families via ``register_collector``:
  ``device_transfer_bytes_total{subsystem,direction}``,
  ``device_transfer_ops_total{subsystem,direction}``,
  ``device_hbm_resident_bytes{subsystem}``,
  ``device_hbm_high_water_bytes{subsystem}``,
  ``device_dispatches_total{subsystem}``,
  ``device_verify_seconds_total{subsystem}``,
  ``device_compiles_total{subsystem}``.
- The ``device_ledger`` tracing stage source (``tracing.stage_split(
  "device_ledger")`` — the bench/scripts read surface), and per-slot
  transfer-delta attributes on block-import/verify spans via
  ``Tracer.record_residency``.
- The **warm-slot transfer budget** (:data:`WARM_SLOT_BUDGET`): a
  declarative per-subsystem per-slot byte budget — warm-path H2D is
  bounded by dirty fractions and signature batches, warm-path pulls are
  ≈ 0 outside the fork-choice weight/best-child/best-descendant reads
  and verdict bytes — checked by the sustained drill
  (:func:`evaluate_budget`, exported as an SLO-style attainment row),
  so "the hot path went host-roundtrip-shaped" is a failing check
  instead of a silent 2× regression.

Knobs: ``LIGHTHOUSE_TPU_DEVICE_LEDGER`` (0 freezes all accounting —
an escape hatch, not a supported mode: the residency view and the
budget check read zeros) and ``LIGHTHOUSE_TPU_DEVICE_LEDGER_SLOTS``
(per-slot delta ring length, default 64 like the trace ring).

This module must stay import-cheap (stdlib + common.metrics only): it
is imported by ``ops/device_tree`` and the crypto dispatch paths.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

# The fixed attribution enum.  Every device subsystem reports under one
# of these; the graftlint ``device-accounting`` checker validates seam
# annotations against this tuple.
SUBSYSTEMS: Tuple[str, ...] = (
    "bls",              # BLS verify pipeline (sharded + staged + stream)
    "device_tree",      # DeviceTree leaf/level planes (direct use)
    "registry_mirror",  # validator-registry HBM columns + record tree
    "packed_cache",     # packed-column device caches (balances, …)
    "fork_choice",      # proto-array vote/topology mirrors
    "slasher",          # min/max span planes
    "kzg",              # Deneb blob verification
    "staging",          # ChunkStager / cold-build streaming pushes
    "proof_engine",     # device Merkle-branch extraction / proof serving
    "op_pool",          # block-packing CSR columns + greedy-pack rounds
    "replay",           # epoch-batched replay windows (catch-up sync)
)

# Compile events that fire outside any attribution seam (conftest
# warmups, standalone scripts) land here — visible, never miscounted.
UNATTRIBUTED = "unattributed"

_TRANSFER_KEYS = ("h2d_bytes", "h2d_ops", "d2h_bytes", "d2h_ops")
# Per-slot delta keys: transfers + the materialize event count (the
# "cold slot" marker — a slot that materialized is start-up/re-stage
# traffic the HTTP budget view may exclude; the drill never does).
_SLOT_KEYS = _TRANSFER_KEYS + ("materializes",)
_COUNTER_KEYS = _TRANSFER_KEYS + (
    "dispatches", "device_ms", "compiles", "compile_hits",
    "scatters", "rebuilds", "materializes")

# ---------------------------------------------------------------------------
# Warm-slot transfer budget — bytes per subsystem per slot on the WARM
# path.  Semantics (README "Device ledger"): once a subsystem is
# materialized, its per-slot H2D is bounded by dirty fractions and the
# slot's signature/blob batches, and its D2H is bounded by verdict/root
# reads plus the fork-choice weight/bc/bd pulls — a full-column
# round-trip inside a warm slot means residency broke.  The sustained
# drill enforces this (exit 1 on violation); the numbers are deliberate
# ceilings, not targets.
# ---------------------------------------------------------------------------

MiB = 1 << 20

WARM_SLOT_BUDGET: Dict[str, Dict[str, int]] = {
    # Signature batches ARE warm traffic: ~50 KB marshalled per 16-key
    # set, a mainnet slot carries ≲ 2k sets.  Verdicts come back as
    # flags.
    "bls": {"h2d_bytes": 256 * MiB, "d2h_bytes": 1 * MiB},
    # Dirty leaf rows + indices only; a root is a 32-byte pull.
    "device_tree": {"h2d_bytes": 4 * MiB, "d2h_bytes": 1 * MiB},
    # Dirty raw records (121 B each, bucket-padded); 32 B down.
    "registry_mirror": {"h2d_bytes": 8 * MiB, "d2h_bytes": 1 * MiB},
    # Dirty chunk rows of the packed columns; adopted device results
    # push nothing.
    "packed_cache": {"h2d_bytes": 8 * MiB, "d2h_bytes": 1 * MiB},
    # Changed-vote scatters + occasional topology push up; the per-round
    # weight/best-child/best-descendant columns down are the ONE
    # legitimate warm-path pull (≤ ~16 B/node · 100k nodes).
    "fork_choice": {"h2d_bytes": 16 * MiB, "d2h_bytes": 32 * MiB},
    # Bit-packed membership masks (n/8 per group) + per-offence
    # evidence gathers down.
    "slasher": {"h2d_bytes": 64 * MiB, "d2h_bytes": 16 * MiB},
    # Blob polynomials up (128 KB/blob mainnet), verdict down.
    "kzg": {"h2d_bytes": 64 * MiB, "d2h_bytes": 1 * MiB},
    # Cold-build streaming belongs OUTSIDE warm slots: a ChunkStager
    # push mid-slot means a full re-stage leaked onto the hot path.
    "staging": {"h2d_bytes": 0, "d2h_bytes": 0},
    # Proof serving: branches are GATHERED from resident levels, never
    # re-hashed — H2D is one small field-root plane per new head state,
    # D2H is sibling rows (32 B each, bucket-padded).  A budget breach
    # means serving went re-stage-shaped instead of gather-shaped.
    "proof_engine": {"h2d_bytes": 2 * MiB, "d2h_bytes": 2 * MiB},
    # Block packing: the candidate CSR columns (element ids, weights,
    # segment ids, precomputed word/bit planes — ≈ 26 B/entry, a
    # backlogged mainnet pool is a few M entries) go up once per
    # produce; the selection vector coming down is rounds × 4 B.
    "op_pool": {"h2d_bytes": 256 * MiB, "d2h_bytes": 1 * MiB},
    # Catch-up replay belongs OUTSIDE warm slots: a node that is in
    # sync imports via the live pipeline (whose signature traffic is
    # the bls family).  Replay-attributed transfers inside a warm slot
    # mean a backfill/range-sync window leaked onto the hot path.
    "replay": {"h2d_bytes": 0, "d2h_bytes": 0},
}

# Per-WINDOW transfer budget for one epoch-batched replay window
# (state_transition/batch_replay.py): the window's signature sets
# marshalled up in one sharded dispatch (~50 KB per 16-key set; a
# 128-block window of full mainnet blocks is ≲ 2k sets), verdict flags
# down.  Evaluated per window by the replayer itself — replay runs at
# catch-up time, not per slot, so the warm-slot ring is the wrong
# denominator.
REPLAY_WINDOW_BUDGET: Dict[str, int] = {
    "h2d_bytes": 256 * MiB, "d2h_bytes": 1 * MiB,
}


class ResidencyToken:
    """Live-resident-bytes handle for one device-owning object.

    ``set(nbytes)`` moves this owner's contribution to ``nbytes``
    (delta-applied to the subsystem's live residency + high-water mark);
    ``release()`` drops it.  Owners register a ``weakref.finalize`` so
    garbage collection releases automatically — the drop seam of every
    subsystem that has no explicit close.
    """

    __slots__ = ("_ledger", "subsystem", "_bytes", "_released",
                 "__weakref__")

    def __init__(self, ledger: "DeviceLedger", subsystem: str):
        self._ledger = ledger
        self.subsystem = subsystem
        self._bytes = 0
        self._released = False
        ledger._tokens.add(self)

    def set(self, nbytes: int) -> None:
        if self._released:
            return
        nbytes = max(int(nbytes), 0)
        delta = nbytes - self._bytes
        self._bytes = nbytes
        if delta:
            self._ledger._adjust_resident(self.subsystem, delta)

    @property
    def nbytes(self) -> int:
        return self._bytes

    def release(self) -> None:
        """Idempotent drop (explicit close paths AND the GC finalizer)."""
        if self._released:
            return
        self._released = True
        if self._bytes:
            self._ledger._adjust_resident(self.subsystem, -self._bytes)
            self._bytes = 0


class DeviceLedger:
    """The process-wide accounting layer (singleton :data:`LEDGER`)."""

    def __init__(self):
        from .knobs import knob_bool, knob_int
        self.enabled = knob_bool("LIGHTHOUSE_TPU_DEVICE_LEDGER")
        self.max_slots = knob_int("LIGHTHOUSE_TPU_DEVICE_LEDGER_SLOTS")
        # Reentrant: ResidencyToken.release runs as a weakref.finalize
        # GC callback, and a collection can trigger inside any locked
        # section of the SAME thread (an allocation under the lock) —
        # release -> _adjust_resident must then re-enter, not deadlock.
        self._lock = threading.RLock()
        self._tls = threading.local()
        self._sub: Dict[str, Dict[str, float]] = {
            s: dict.fromkeys(_COUNTER_KEYS, 0) for s in SUBSYSTEMS
        }  # guarded-by: _lock
        self._sub[UNATTRIBUTED] = dict.fromkeys(_COUNTER_KEYS, 0)
        self._resident: Dict[str, int] = dict.fromkeys(SUBSYSTEMS, 0)
        self._high: Dict[str, int] = dict.fromkeys(SUBSYSTEMS, 0)
        # Per-shard delivered bytes: subsystem -> shard index ->
        # {h2d_bytes, d2h_bytes}.  Fed only by the parallel/mesh seams;
        # empty until the first mesh placement.  guarded-by: _lock
        self._shards: Dict[str, Dict[int, Dict[str, int]]] = {}
        # Per-slot delta ring: slot → {subsystem: {transfer-key deltas}}.
        self._slot_ring: "OrderedDict[int, dict]" = \
            OrderedDict()  # guarded-by: _lock
        self._last_slot: Optional[int] = None
        self._slot_base: Dict[str, Dict[str, float]] = {}
        self._listener_installed = False
        self._collector_registered = False
        # Live residency tokens (weak): reset() re-seeds resident bytes
        # from these so live device objects never under-report after a
        # bench/test reset.
        self._tokens: "weakref.WeakSet[ResidencyToken]" = weakref.WeakSet()

    # -- attribution context -------------------------------------------------

    @contextmanager
    def attribute(self, subsystem: str):
        """Thread-local attribution scope: ``note_push``/``note_pull``
        and compile events inside the ``with`` body charge
        ``subsystem``.  Nests (innermost wins); crosses no threads —
        background stagers take an explicit ``subsystem=`` instead."""
        assert subsystem in SUBSYSTEMS, subsystem
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(subsystem)
        try:
            yield
        finally:
            stack.pop()

    def ambient(self) -> Optional[str]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def _resolve(self, subsystem: Optional[str], default: str) -> str:
        if subsystem is not None:
            assert subsystem in SUBSYSTEMS, subsystem
            return subsystem
        return self.ambient() or default

    # -- recording -----------------------------------------------------------

    def note_transfer(self, direction: str, nbytes: int,
                      subsystem: Optional[str] = None,
                      ops: int = 1) -> None:
        """One H2D (``direction="h2d"``) or D2H (``"d2h"``) transfer of
        ``nbytes`` on behalf of ``subsystem`` (default: the ambient
        attribution, else ``device_tree`` — the pre-ledger owner of the
        residency stats)."""
        if not self.enabled:
            return
        sub = self._resolve(subsystem, "device_tree")
        with self._lock:
            row = self._sub[sub]
            row[f"{direction}_bytes"] += int(nbytes)
            row[f"{direction}_ops"] += int(ops)
        self._maybe_install_listener()

    def note_shard_transfer(self, direction: str,
                            per_shard: Dict[int, int],
                            subsystem: Optional[str] = None) -> None:
        """Per-shard DELIVERED bytes for one mesh placement/pull
        (``parallel/mesh`` seams only).  ``per_shard`` maps mesh shard
        index → bytes landing on (``"h2d"``) or read from (``"d2h"``)
        that shard.  A batch-sharded column delivers 1/d per shard, a
        replicated one its full size on every shard — so shard sums may
        legitimately exceed the host-wire totals in
        :meth:`note_transfer` (one host copy fans out over ICI)."""
        if not self.enabled or not per_shard:
            return
        sub = self._resolve(subsystem, "device_tree")
        key = f"{direction}_bytes"
        with self._lock:
            rows = self._shards.setdefault(sub, {})
            for shard, nbytes in per_shard.items():
                row = rows.setdefault(
                    int(shard), {"h2d_bytes": 0, "d2h_bytes": 0})
                row[key] += int(nbytes)

    def shard_totals(self) -> Dict[str, Dict[int, Dict[str, int]]]:
        """Per-subsystem per-shard delivered-byte totals (deep copy) —
        the mesh-slot bench / validate_mesh read surface."""
        with self._lock:
            return {s: {i: dict(row) for i, row in rows.items()}
                    for s, rows in self._shards.items()}

    def note_dispatch(self, subsystem: str, wall_ms: float,
                      count: int = 1) -> None:
        """One device dispatch (count) + its device-verify wall time.

        No-op inside a :meth:`suppress_dispatch` scope: the resilience
        envelope wraps device paths that ALSO self-account (the kzg
        pairing seam, the direct XLA verify) and records the dispatch
        itself on success — without suppression every enveloped call
        would count twice."""
        if not self.enabled or getattr(self._tls, "suppress", 0):
            return
        sub = self._resolve(subsystem, "bls")
        with self._lock:
            row = self._sub[sub]
            row["dispatches"] += int(count)
            row["device_ms"] += float(wall_ms)

    @contextmanager
    def suppress_dispatch(self):
        """Scope in which inner ``note_dispatch`` calls are no-ops —
        the OUTER accounting seam (the envelope) owns the dispatch.
        Thread-local; callers that hand the wrapped fn to another
        thread (the deadline watchdog pool) must wrap the FN, not the
        call site, so the flag travels with execution."""
        self._tls.suppress = getattr(self._tls, "suppress", 0) + 1
        try:
            yield
        finally:
            self._tls.suppress -= 1

    def note_compile(self, subsystem: Optional[str] = None,
                     count: int = 1, key: str = "compiles") -> None:
        """One per-program compile-request event (the jax monitoring
        listener calls this with the ambient attribution).  Both
        ``compiles`` (requests) and ``compile_hits`` (served from the
        persistent cache) are MONOTONIC — net recompiles are derived at
        read time, never decremented, so the Prometheus counters stay
        counters."""
        if not self.enabled:
            return
        assert key in ("compiles", "compile_hits"), key
        sub = subsystem if subsystem in SUBSYSTEMS \
            else (self.ambient() or UNATTRIBUTED)
        with self._lock:
            self._sub[sub][key] += int(count)

    def note_event(self, name: str,
                   subsystem: Optional[str] = None) -> None:
        """Residency protocol events (``scatters`` / ``rebuilds`` /
        ``materializes``) — the legacy RESIDENCY_STATS op counts, now
        attributed."""
        if not self.enabled:
            return
        assert name in ("scatters", "rebuilds", "materializes"), name
        sub = self._resolve(subsystem, "device_tree")
        with self._lock:
            self._sub[sub][name] += 1

    # -- residency watermarks ------------------------------------------------

    def residency(self, subsystem: str) -> ResidencyToken:
        assert subsystem in SUBSYSTEMS, subsystem
        return ResidencyToken(self, subsystem)

    def track(self, owner, subsystem: str, nbytes: int) -> ResidencyToken:
        """Token + GC drop seam in one call: ``owner`` going away
        releases the bytes (``weakref.finalize`` — no explicit close
        needed at knob-off de-materialization / mirror replacement)."""
        tok = self.residency(subsystem)
        tok.set(nbytes)
        weakref.finalize(owner, ResidencyToken.release, tok)
        return tok

    def _adjust_resident(self, subsystem: str, delta: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            cur = self._resident[subsystem] + int(delta)
            self._resident[subsystem] = max(cur, 0)
            if cur > self._high[subsystem]:
                self._high[subsystem] = cur

    # -- jax compile listener ------------------------------------------------

    _COMPILE_EVENT = "/jax/compilation_cache/compile_requests_use_cache"
    _CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

    def _maybe_install_listener(self) -> None:
        """Lazy one-shot: transfers imply jax is live, so install the
        monitoring listener at the first note (idempotent; a jax build
        without the API degrades to compiles staying 0).  NOT at import
        — this module is imported by processes that never touch jax."""
        if self._listener_installed:
            return
        import sys
        if "jax" not in sys.modules:
            return
        # Check-and-set under the lock: two threads noting concurrently
        # (a stager thread + the main thread) must not BOTH register —
        # a duplicate listener would double every compile count forever.
        with self._lock:
            if self._listener_installed:
                return
            self._listener_installed = True  # one attempt ever
        try:
            try:
                from jax import monitoring as _mon
            except Exception:
                from jax._src import monitoring as _mon  # older builds
            _mon.register_event_listener(self._on_jax_event)
        except Exception:
            pass

    def _on_jax_event(self, event: str, **_kw) -> None:
        # The request event fires for every cache-eligible compile, the
        # hit event for the ones served from the persistent cache; both
        # fire on the same thread inside one compile call, so the
        # ambient attribution matches.  Net recompiles (requests −
        # hits) are DERIVED at read time — decrementing a counter here
        # would break Prometheus monotonicity (a scrape between the two
        # events would read as a process restart).
        if event == self._COMPILE_EVENT:
            self.note_compile()
        elif event == self._CACHE_HIT_EVENT:
            self.note_compile(key="compile_hits")

    # -- per-slot delta ring -------------------------------------------------

    def mark_slot(self, slot: int) -> None:
        """Slot boundary: fold the transfer deltas since the previous
        mark into the ring under the PREVIOUS slot (the interval they
        belong to).  Idempotent per slot value — multiple nodes in one
        process ticking the same wall-clock slot mark once."""
        if not self.enabled:
            return
        slot = int(slot)
        with self._lock:
            if slot == self._last_slot:
                return
            if self._last_slot is not None:
                delta = self._delta_locked()
                if any(any(row.values()) for row in delta.values()):
                    self._slot_ring[self._last_slot] = delta
                    while len(self._slot_ring) > self.max_slots:
                        self._slot_ring.popitem(last=False)
                else:
                    # A quiet interval must also RETIRE a stale entry
                    # under the same key: drills restart slot numbering
                    # within one process, and a previous run's traffic
                    # surviving under this run's slot number would be
                    # evaluated against this run's budget.
                    self._slot_ring.pop(self._last_slot, None)
            self._slot_base = {
                s: {k: self._sub[s][k] for k in _SLOT_KEYS}
                for s in SUBSYSTEMS}
            self._last_slot = slot

    def _delta_locked(self) -> dict:  # lock-held: _lock
        out = {}
        for s in SUBSYSTEMS:
            base = self._slot_base.get(s, {})
            row = {k: int(self._sub[s][k] - base.get(k, 0))
                   for k in _SLOT_KEYS}
            out[s] = row
        return out

    def slot_deltas(self) -> List[dict]:
        """``[{"slot": s, "cold": bool, "subsystems": {name:
        {h2d/d2h bytes+ops, materializes}}}]`` for every closed slot
        still in the ring, oldest first — the /lighthouse/device
        per-slot view and the budget check's input.  ``cold`` marks a
        slot in which a materialization ran (start-up / re-stage
        traffic).  Only subsystems with nonzero activity appear."""
        with self._lock:
            return [{"slot": s,
                     "cold": any(row.get("materializes")
                                 for row in d.values()),
                     "subsystems": {n: dict(row)
                                    for n, row in d.items()
                                    if any(row.values())}}
                    for s, d in self._slot_ring.items()]

    def current_slot_delta(self) -> dict:
        """Transfer deltas of the OPEN slot (since the last mark)."""
        with self._lock:
            return self._delta_locked()

    def clear_slot_ring(self) -> None:
        """Drop every per-slot delta and the open-slot baseline —
        drivers that restart slot numbering (the sustained drill) call
        this at run start so another run's entries under the same slot
        numbers can never leak into their budget window.  Counters and
        watermarks are untouched."""
        with self._lock:
            self._slot_ring.clear()
            self._slot_base = {}
            self._last_slot = None

    # -- reads ---------------------------------------------------------------

    def snapshot(self) -> dict:
        """Consistent full-ledger copy: per-subsystem counters +
        residency watermarks (the /lighthouse/device body's core and
        the scripts' read surface)."""
        with self._lock:
            subs = {}
            for s in SUBSYSTEMS:
                row = {k: (round(v, 3) if k == "device_ms" else int(v))
                       for k, v in self._sub[s].items()}
                row["resident_bytes"] = self._resident[s]
                row["hbm_high_water_bytes"] = self._high[s]
                # derived: what actually RECOMPILED (requests − cache
                # hits) — the raw pair stays monotonic for Prometheus
                row["compiles_net"] = max(
                    row["compiles"] - row["compile_hits"], 0)
                subs[s] = row
            un = self._sub[UNATTRIBUTED]
            return {
                "enabled": self.enabled,
                "subsystems": subs,
                # String shard keys: this dict is the JSON body of
                # /lighthouse/device and int keys would not round-trip.
                "shards": {s: {str(i): dict(row)
                               for i, row in sorted(rows.items())}
                           for s, rows in self._shards.items()},
                "unattributed_compiles": max(
                    int(un["compiles"] - un["compile_hits"]), 0),
            }

    def subsystem_totals(self, subsystems: Tuple[str, ...]
                         ) -> Dict[str, float]:
        """Counter sums over a subsystem subset (the RESIDENCY_STATS
        view sums only its historical feeders)."""
        with self._lock:
            out = dict.fromkeys(_COUNTER_KEYS, 0.0)
            for s in subsystems:
                for k in _COUNTER_KEYS:
                    out[k] += self._sub[s][k]
            return out

    def transfer_totals(self) -> Dict[str, Tuple[int, int]]:
        """Cheap per-subsystem ``(h2d_bytes, d2h_bytes)`` read — the
        hot-path span-attribution snapshot (no rounding, no nested
        dict copies; the full :meth:`snapshot` is the HTTP/report
        surface)."""
        with self._lock:
            return {s: (int(self._sub[s]["h2d_bytes"]),
                        int(self._sub[s]["d2h_bytes"]))
                    for s in SUBSYSTEMS}

    def stage_dict(self) -> dict:
        """Flat per-subsystem totals for the ``device_ledger`` tracing
        stage source (``<subsystem>_<counter>`` keys; no ``*_ms`` keys —
        these are counters, not a phase decomposition, so the adapter
        attaches them as attributes rather than laying out spans)."""
        with self._lock:
            out = {}
            for s in SUBSYSTEMS:
                row = self._sub[s]
                for k in _TRANSFER_KEYS + ("dispatches", "compiles"):
                    v = int(row[k])
                    if v:
                        out[f"{s}_{k}"] = v
                if row["device_ms"]:
                    # key must NOT end in "_ms": record_stages lays
                    # *_ms keys out as phase spans, and this is a
                    # process-lifetime counter, not a decomposition
                    out[f"{s}_device_verify_ms_total"] = \
                        round(row["device_ms"], 3)
                if self._resident[s]:
                    out[f"{s}_resident_bytes"] = self._resident[s]
            return out

    def reset(self) -> None:
        """Zero every counter and the slot ring (bench rows and tests;
        a live node never resets — Prometheus counters must stay
        monotonic).  Residency is RE-SEEDED from the live tokens, not
        zeroed: device objects created before the reset still hold
        their HBM, and zeroing under them would make every later
        token delta land on a stale base (permanent under-report)."""
        with self._lock:
            for row in self._sub.values():
                for k in row:
                    row[k] = 0
            for s in SUBSYSTEMS:
                self._resident[s] = 0
                self._high[s] = 0
            self._shards.clear()
            self._slot_ring.clear()
            self._slot_base = {}
            self._last_slot = None
        for tok in list(self._tokens):
            if not tok._released and tok._bytes:
                self._adjust_resident(tok.subsystem, tok._bytes)

    # -- Prometheus ----------------------------------------------------------

    def register_metrics(self) -> None:
        """Register the scrape-time collector exporting the labeled
        families (idempotent; called at chain construction so a bare
        library import never touches the registry)."""
        if self._collector_registered:
            return
        self._collector_registered = True
        from .metrics import REGISTRY
        REGISTRY.register_collector(self._collect)

    @staticmethod
    def _set_child(family, labels: tuple, value: float) -> None:
        child = family.labels(*labels)
        with child._lock:
            child.value = float(value)

    def _collect(self) -> None:
        from .metrics import REGISTRY
        snap = self.snapshot()
        f_bytes = REGISTRY.counter(
            "device_transfer_bytes_total",
            "host<->device transfer bytes by subsystem",
            labelnames=("subsystem", "direction"))
        f_ops = REGISTRY.counter(
            "device_transfer_ops_total",
            "host<->device transfer operations by subsystem",
            labelnames=("subsystem", "direction"))
        f_res = REGISTRY.gauge(
            "device_hbm_resident_bytes",
            "live HBM-resident bytes by subsystem",
            labelnames=("subsystem",))
        f_high = REGISTRY.gauge(
            "device_hbm_high_water_bytes",
            "high-water HBM residency by subsystem",
            labelnames=("subsystem",))
        f_disp = REGISTRY.counter(
            "device_dispatches_total",
            "device dispatches by subsystem",
            labelnames=("subsystem",))
        f_verify = REGISTRY.counter(
            "device_verify_seconds_total",
            "device-verify wall time by subsystem",
            labelnames=("subsystem",))
        f_comp = REGISTRY.counter(
            "device_compiles_total",
            "per-program compile-request events by subsystem",
            labelnames=("subsystem",))
        f_hits = REGISTRY.counter(
            "device_compile_cache_hits_total",
            "compile requests served from the persistent cache",
            labelnames=("subsystem",))
        with self._lock:
            un_requests = int(self._sub[UNATTRIBUTED]["compiles"])
            un_hits = int(self._sub[UNATTRIBUTED]["compile_hits"])
        for s, row in snap["subsystems"].items():
            self._set_child(f_bytes, (s, "h2d"), row["h2d_bytes"])
            self._set_child(f_bytes, (s, "d2h"), row["d2h_bytes"])
            self._set_child(f_ops, (s, "h2d"), row["h2d_ops"])
            self._set_child(f_ops, (s, "d2h"), row["d2h_ops"])
            self._set_child(f_res, (s,), row["resident_bytes"])
            self._set_child(f_high, (s,), row["hbm_high_water_bytes"])
            self._set_child(f_disp, (s,), row["dispatches"])
            self._set_child(f_verify, (s,), row["device_ms"] / 1e3)
            # BOTH monotonic — net recompiles = requests − hits is a
            # query-time derivation, never a decremented counter.
            self._set_child(f_comp, (s,), row["compiles"])
            self._set_child(f_hits, (s,), row["compile_hits"])
        self._set_child(f_comp, (UNATTRIBUTED,), un_requests)
        self._set_child(f_hits, (UNATTRIBUTED,), un_hits)


# ---------------------------------------------------------------------------
# Warm-slot budget evaluation (the sustained drill's check)
# ---------------------------------------------------------------------------

def evaluate_budget(slot_deltas: List[dict],
                    budget: Optional[Dict[str, Dict[str, int]]] = None,
                    include_cold: bool = True) -> dict:
    """Check per-slot transfer deltas against the warm-slot budget.

    ``slot_deltas`` is :meth:`DeviceLedger.slot_deltas` output (possibly
    filtered to the measured slots).  Returns the SLO-style row: one
    entry per (subsystem, direction) with a declared budget —
    worst-slot bytes, violating slots, ok — plus ``attainment`` (the
    fraction of slot×budget cells inside budget) and the overall
    verdict ``ok``.  An empty window attains 1.0 vacuously (a fresh
    node is not in violation).

    ``include_cold=False`` skips slots in which a materialization ran
    (reported in ``cold_slots_skipped``, never silently) — the HTTP
    scoreboard's view, where a fresh node's start-up staging must not
    read as a warm-path violation.  The sustained drill keeps the
    default: its measured slots follow the warm-up, so a mid-run
    re-materialize is exactly the regression it must catch."""
    budget = WARM_SLOT_BUDGET if budget is None else budget
    cold_skipped = []
    if not include_cold:
        cold_skipped = [d["slot"] for d in slot_deltas if d.get("cold")]
        slot_deltas = [d for d in slot_deltas if not d.get("cold")]
    rows = []
    cells = 0
    ok_cells = 0
    for sub in sorted(budget):
        for direction in ("h2d", "d2h"):
            limit = budget[sub].get(f"{direction}_bytes")
            if limit is None:
                continue
            worst = 0
            worst_slot = None
            violations = []
            for entry in slot_deltas:
                used = entry["subsystems"].get(sub, {}).get(
                    f"{direction}_bytes", 0)
                cells += 1
                if used <= limit:
                    ok_cells += 1
                else:
                    violations.append(entry["slot"])
                if used > worst:
                    worst = used
                    worst_slot = entry["slot"]
            rows.append({
                "subsystem": sub, "direction": direction,
                "budget_bytes": limit, "worst_slot_bytes": worst,
                "worst_slot": worst_slot,
                "violations": violations,
                "ok": not violations,
            })
    return {
        "slots_checked": len(slot_deltas),
        "cold_slots_skipped": cold_skipped,
        "attainment": round(ok_cells / cells, 6) if cells else 1.0,
        "ok": all(r["ok"] for r in rows),
        "rows": rows,
    }


# The process ledger + module-level conveniences (the seam-call idiom
# mirrors tracing's TRACER).
LEDGER = DeviceLedger()

attribute = LEDGER.attribute
note_transfer = LEDGER.note_transfer
note_shard_transfer = LEDGER.note_shard_transfer
note_dispatch = LEDGER.note_dispatch
note_compile = LEDGER.note_compile
note_event = LEDGER.note_event
mark_slot = LEDGER.mark_slot
