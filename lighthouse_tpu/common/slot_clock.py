"""Slot clocks — ``common/slot_clock``
(``/root/reference/common/slot_clock/src/``): the ``SlotClock`` trait with
a wall-clock implementation and the manually-driven test clock every
harness uses (``TestingSlotClock``)."""

from __future__ import annotations

import time


class SlotClock:
    """Trait: genesis-anchored slot arithmetic."""

    def __init__(self, genesis_time: int, seconds_per_slot: int):
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot

    def now(self) -> int:
        raise NotImplementedError

    def slot_of(self, timestamp: float) -> int:
        if timestamp < self.genesis_time:
            return 0
        return int(timestamp - self.genesis_time) // self.seconds_per_slot

    def start_of(self, slot: int) -> int:
        return self.genesis_time + slot * self.seconds_per_slot

    def seconds_into_slot(self, timestamp: float) -> float:
        return (timestamp - self.genesis_time) % self.seconds_per_slot

    def slot_progress(self) -> float:
        """Fraction of the current slot elapsed, in [0, 1) — drives the
        3/4-slot state-advance timer (`state_advance_timer.rs:94-106`)."""
        raise NotImplementedError


class SystemTimeSlotClock(SlotClock):
    """`SystemTimeSlotClock` — wall clock."""

    def now(self) -> int:
        return self.slot_of(time.time())

    def duration_to_next_slot(self) -> float:
        t = time.time()
        return self.start_of(self.slot_of(t) + 1) - t

    def slot_progress(self) -> float:
        return self.seconds_into_slot(time.time()) / self.seconds_per_slot


class ManualSlotClock(SlotClock):
    """`ManualSlotClock`/`TestingSlotClock` — tests drive time."""

    def __init__(self, genesis_time: int = 0, seconds_per_slot: int = 12,
                 slot: int = 0):
        super().__init__(genesis_time, seconds_per_slot)
        self._slot = slot
        self._progress = 0.0

    def now(self) -> int:
        return self._slot

    def set_slot(self, slot: int) -> None:
        self._slot = slot
        self._progress = 0.0

    def set_progress(self, fraction: float) -> None:
        """Tests drive intra-slot time explicitly (e.g. 0.75 fires the
        state-advance timer in a cli-style loop)."""
        self._progress = fraction

    def slot_progress(self) -> float:
        return self._progress

    def advance(self, n: int = 1) -> int:
        self._slot += n
        self._progress = 0.0
        return self._slot

    def duration_to_next_slot(self) -> float:
        return 0.0
