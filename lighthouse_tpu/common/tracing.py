"""Slot-scope tracing — unified spans from gossip arrival to head.

The hot path's timings used to live in eight disconnected module-global
dicts (``LAST_BLOCK_TIMINGS``, ``LAST_EPOCH_TIMINGS``, ``LAST_COLD_
TIMINGS``, ``LAST_FAST_AGG_TIMINGS``, ``LAST_KZG_TIMINGS``,
``LAST_PUSH_STATS``, the fast-agg ``STAGE_TIMINGS`` profile and
``RESIDENCY_STATS``) that only ``bench.py`` knew how to read, and no
artifact showed one slot end-to-end.  This module is the one
instrument:

- :class:`Tracer` — a low-overhead, thread-safe span system.  Spans
  nest via a thread-local stack; :meth:`Tracer.ctx` captures a
  :class:`SpanContext` token that another thread adopts with
  ``span(..., parent=ctx)`` (the BeaconProcessor worker /
  verification-service pump-thread hops).  A **disabled** tracer is a
  no-op fast path: ``span()`` returns a shared singleton after one
  attribute check, and every call site that would compute arguments
  first guards on ``TRACER.enabled``.
- **Slot traces** — every completed span lands in the per-slot trace of
  its resolved slot (explicit argument > parent's slot > the ambient
  slot the chain sets from ``per_slot_task``).  A ring buffer keeps the
  last N fully-assembled slots (``LIGHTHOUSE_TPU_TRACE_RING``,
  default 64).
- **Chrome trace-event export** — :meth:`Tracer.chrome_trace` emits the
  ``{"traceEvents": [...]}`` JSON that opens directly in Perfetto /
  ``chrome://tracing`` (``ph:"X"`` duration events on real thread
  tracks, ``ph:"i"`` instants for gossip-arrival stamps and breaker
  transitions).
- **The stage adapter** — :func:`stage_split` snapshots any of the
  legacy stage dicts by name (ONE read surface: bench.py's
  ``block_phase_split`` / ``epoch`` / ``bls_stage_split`` rows read
  through it), and :func:`record_stages` converts the same dict into
  child spans of the current span, laid out back-to-back ending at the
  call instant — so the per-phase decomposition appears inside the slot
  trace instead of a parallel reporting channel.

Knobs:

====================================  ======================================
``LIGHTHOUSE_TPU_TRACE``              ``1`` enables tracing at import
``LIGHTHOUSE_TPU_TRACE_RING``         slot traces kept (default 64)
====================================  ======================================

Surfaced by ``/lighthouse/tracing/slots`` +
``/lighthouse/tracing/slot/{slot}[?format=chrome_trace]`` (HTTP API) and
``scripts/trace_slot.py`` (the CI-able completeness check).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

from .metrics import REGISTRY

# The per-slot pipeline stages a fully-assembled trace must cover —
# span categories, used by the completeness check (`scripts/
# trace_slot.py` exits 1 when one is missing).
PIPELINE_STAGES = (
    "gossip_arrival",          # network/: arrival stamps
    "verification_service",    # dispatch/envelope/breaker
    "block_import",            # gossip verify → import pipeline
    "state_transition",        # per-slot/per-block/per-epoch phases
    "fork_choice",             # on_block + deltas/apply/find_head
    "head",                    # head recompute / swap
)

# Spans kept per slot trace before truncation (a hostile gossip flood
# must not grow a slot's trace unboundedly).
MAX_SPANS_PER_SLOT = 8192


class SpanContext:
    """Cross-thread propagation token: enough to parent a span created
    on another thread under the capturing span (id + slot scope)."""

    __slots__ = ("span_id", "slot")

    def __init__(self, span_id: int, slot: int):
        self.span_id = span_id
        self.slot = slot


class _NoopSpan:
    """Shared no-op returned by a disabled tracer — zero allocation on
    the hot path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass

    def ctx(self) -> Optional[SpanContext]:
        return None


_NOOP = _NoopSpan()


class Span:
    """A live span (context manager).  Entering pushes it on the
    thread-local stack; exiting records it into its slot's trace."""

    __slots__ = ("_tracer", "name", "cat", "slot", "attrs", "span_id",
                 "parent_id", "t0", "_entered")

    def __init__(self, tracer: "Tracer", name: str, cat: str, slot: int,
                 parent_id: int, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.slot = slot
        self.parent_id = parent_id
        self.attrs = attrs
        self.span_id = next(tracer._ids)
        self.t0 = 0.0
        self._entered = False

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def ctx(self) -> SpanContext:
        return SpanContext(self.span_id, self.slot)

    def __enter__(self) -> "Span":
        self.t0 = time.perf_counter()
        self._tracer._stack().append(self)
        self._entered = True
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.perf_counter() - self.t0
        stack = self._tracer._stack()
        if self._entered and stack and stack[-1] is self:
            stack.pop()
        elif self._entered and self in stack:  # out-of-order exit
            stack.remove(self)
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self._tracer._record(self.slot, {
            "id": self.span_id, "parent": self.parent_id,
            "name": self.name, "cat": self.cat,
            "ts_us": round(self.t0 * 1e6, 1),
            "dur_us": round(dur * 1e6, 1),
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
            "attrs": self.attrs,
        })
        return False


class Tracer:
    """Process tracer.  One instance (:data:`TRACER`) serves the whole
    node; everything here is safe under concurrent span completion from
    gossip handlers, processor workers, pump threads and the HTTP API
    reading traces."""

    def __init__(self, max_slots: Optional[int] = None):
        from .knobs import knob_bool, knob_int
        self.enabled = knob_bool("LIGHTHOUSE_TPU_TRACE")
        ring = knob_int("LIGHTHOUSE_TPU_TRACE_RING")
        self.max_slots = max(1, max_slots if max_slots is not None else ring)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._ids = itertools.count(1)
        self._slots: "OrderedDict[int, dict]" = \
            OrderedDict()  # guarded-by: _lock
        self._ambient_slot = 0
        self.evicted_slots = 0
        self.dropped_stale = 0  # spans for slots older than the ring
        self._m_spans = None  # lazy labeled histogram family

    # -- lifecycle -----------------------------------------------------------

    def enable(self, ring: Optional[int] = None) -> None:
        if ring is not None:
            self.max_slots = max(1, int(ring))
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._slots.clear()
            self.evicted_slots = 0
            self.dropped_stale = 0

    # -- slot scope ----------------------------------------------------------

    def set_slot(self, slot: int) -> None:
        """Ambient slot: spans with no explicit/inherited slot attribute
        land in this slot's trace.  The chain's per-slot task calls this
        at every tick; an int store, cheap enough to run unconditionally."""
        self._ambient_slot = int(slot)

    def current_slot(self) -> int:
        return self._ambient_slot

    # -- span creation -------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def span(self, name: str, cat: str = "", slot: Optional[int] = None,
             parent: Optional[SpanContext] = None, **attrs):
        """Open a span.  ``parent`` (a :class:`SpanContext`) adopts a
        span captured on another thread; otherwise the parent is the
        thread's innermost open span."""
        if not self.enabled:
            return _NOOP
        stack = self._stack()
        top = stack[-1] if stack else None
        if parent is not None:
            # Context adoption is CAUSAL parenting, not temporal
            # nesting: the parent may have exited before this span
            # starts (submit → async dispatch).  Mark it so trace
            # consumers don't assume interval containment.
            parent_id = parent.span_id
            inherited = parent.slot
            attrs = {"adopted": True, **attrs}
        elif top is not None:
            parent_id = top.span_id
            inherited = top.slot
        else:
            parent_id = 0
            inherited = self._ambient_slot
        return Span(self, name, cat,
                    inherited if slot is None else int(slot),
                    parent_id, attrs)

    def instant(self, name: str, cat: str = "",
                slot: Optional[int] = None, **attrs) -> None:
        """Zero-duration marker (gossip arrival stamps, breaker
        transitions).  Callers computing arguments should guard on
        ``TRACER.enabled`` first."""
        if not self.enabled:
            return
        stack = self._stack()
        top = stack[-1] if stack else None
        self._record(
            (top.slot if top is not None else self._ambient_slot)
            if slot is None else int(slot),
            {"id": next(self._ids),
             "parent": top.span_id if top is not None else 0,
             "name": name, "cat": cat,
             "ts_us": round(time.perf_counter() * 1e6, 1),
             "dur_us": 0.0, "inst": True,
             "tid": threading.get_ident(),
             "thread": threading.current_thread().name,
             "attrs": attrs})

    def ctx(self) -> SpanContext:
        """Capture the current position for another thread (innermost
        open span, or the bare ambient slot)."""
        stack = self._stack()
        if stack:
            return stack[-1].ctx()
        return SpanContext(0, self._ambient_slot)

    # -- recording -----------------------------------------------------------

    def _record(self, slot: int, rec: dict) -> None:
        with self._lock:
            bucket = self._slots.get(slot)
            if bucket is None:
                if len(self._slots) >= self.max_slots \
                        and slot < min(self._slots):
                    # A straggler span for a slot already behind the
                    # ring (e.g. a late streamed verdict whose context
                    # points >ring slots back): drop it outright — a
                    # fresh bucket would just self-evict and churn.
                    self.dropped_stale += 1
                    return
                bucket = self._slots[slot] = {
                    "slot": slot, "spans": [], "truncated": 0,
                    # Aggregates maintained at record time so the slot
                    # summary never scans/copies span lists under the
                    # tracer lock (the lock every hot-path span exit
                    # takes).  "stats" adds per-category duration
                    # aggregates ([count, sum_us, max_us]) — the SLO
                    # engine's worst-offending-slot attribution reads
                    # these, never the span lists.
                    "t0": rec["ts_us"], "t1": 0.0, "cats": set(),
                    "stats": {}}
                while len(self._slots) > self.max_slots:
                    self._slots.pop(min(self._slots))
                    self.evicted_slots += 1
            # Record-time aggregates NEVER truncate (O(1) per span,
            # bounded per slot): a hostile-flood slot past the span cap
            # is exactly the slot the SLO worst-offender attribution
            # must still rank correctly — only span STORAGE is capped.
            bucket["t0"] = min(bucket["t0"], rec["ts_us"])
            bucket["t1"] = max(bucket["t1"],
                               rec["ts_us"] + rec["dur_us"])
            if rec["cat"]:
                bucket["cats"].add(rec["cat"])
                if not rec.get("inst"):
                    st = bucket["stats"].get(rec["cat"])
                    if st is None:
                        st = bucket["stats"][rec["cat"]] = [0, 0.0, 0.0]
                    st[0] += 1
                    st[1] += rec["dur_us"]
                    st[2] = max(st[2], rec["dur_us"])
            if len(bucket["spans"]) >= MAX_SPANS_PER_SLOT:
                # Only span STORAGE is capped: fall through so the
                # labeled histogram below keeps counting too — the
                # Prometheus family and slot_stats() must agree on a
                # flooded slot.
                bucket["truncated"] += 1
            else:
                bucket["spans"].append(rec)
        cat = rec.get("cat")
        if cat and not rec.get("inst"):
            if self._m_spans is None:
                self._m_spans = REGISTRY.histogram(
                    "tracing_span_seconds", "span duration by category",
                    labelnames=("cat",))
            self._m_spans.labels(cat).observe(rec["dur_us"] / 1e6)

    # -- stage-dict adapter --------------------------------------------------

    def stage_split(self, source: str) -> dict:
        """Snapshot one of the legacy stage dicts by name — the ONE read
        surface bench.py and the trace adapter share (see
        :data:`_STAGE_SOURCES` for the names)."""
        return dict(_STAGE_SOURCES[source]())

    def record_stages(self, source: str, cat: Optional[str] = None) -> None:
        """Convert ``source``'s stage dict into child spans of the
        current span.  The dicts carry durations, not start offsets, so
        children are laid out back-to-back ENDING at the call instant
        (they record sequential phase decompositions, so the layout is
        faithful).  Non-``*_ms`` keys become attributes on the parent."""
        if not self.enabled:
            return
        snap = self.stage_split(source)
        if not snap:
            return
        stack = self._stack()
        top = stack[-1] if stack else None
        parent_id = top.span_id if top is not None else 0
        slot = top.slot if top is not None else self._ambient_slot
        if cat is None:
            cat = top.cat if top is not None and top.cat else "stage"
        tid = threading.get_ident()
        tname = threading.current_thread().name
        # "total_ms" is the sum of the others (the dicts' convention) —
        # emitting it as a sibling would double the laid-out time.
        ms = [(k, float(v)) for k, v in snap.items()
              if k.endswith("_ms") and k != "total_ms"
              and isinstance(v, (int, float))]
        other = {k: v for k, v in snap.items() if not k.endswith("_ms")}
        now = time.perf_counter()
        t = now - sum(v for _, v in ms) / 1e3
        for k, v in ms:
            self._record(slot, {
                "id": next(self._ids), "parent": parent_id,
                "name": f"{source}:{k[:-3]}", "cat": cat,
                "ts_us": round(t * 1e6, 1),
                "dur_us": round(v * 1e3, 1),
                "tid": tid, "thread": tname,
                "attrs": {"source": source}})
            t += v / 1e3
        if other and top is not None:
            top.set(**{f"{source}_{k}": v for k, v in other.items()})

    # -- device residency attribution ---------------------------------------

    def residency_mark(self) -> Optional[dict]:
        """Snapshot ``RESIDENCY_STATS`` plus the device ledger's
        per-subsystem transfer totals for delta attribution (pair with
        :meth:`record_residency`)."""
        if not self.enabled:
            return None
        from ..ops.device_tree import residency_snapshot
        from .device_ledger import LEDGER
        mark = residency_snapshot()
        mark["_ledger"] = LEDGER.transfer_totals()
        return mark

    def record_residency(self, span, mark: Optional[dict]) -> None:
        """Attach the device push/pull byte deltas since ``mark`` to
        ``span`` — both the legacy flat ``residency_*`` totals and the
        ledger's per-subsystem ``dev_<subsystem>_<dir>_bytes`` split
        (the device-stage attribution of a transition)."""
        if mark is None or not self.enabled:
            return
        from ..ops.device_tree import residency_snapshot
        from .device_ledger import LEDGER
        ledger_mark = mark.pop("_ledger", {})
        after = residency_snapshot()
        delta = {f"residency_{k}": after[k] - mark[k]
                 for k in mark if after.get(k, 0) != mark[k]}
        for s, (h2d, d2h) in LEDGER.transfer_totals().items():
            b_h2d, b_d2h = ledger_mark.get(s, (0, 0))
            if h2d != b_h2d:
                delta[f"dev_{s}_h2d_bytes"] = h2d - b_h2d
            if d2h != b_d2h:
                delta[f"dev_{s}_d2h_bytes"] = d2h - b_d2h
        if delta:
            span.set(**delta)

    # -- export --------------------------------------------------------------

    def slots(self) -> List[int]:
        with self._lock:
            return sorted(self._slots)

    def slot_summaries(self) -> List[dict]:
        # Reads only the per-bucket aggregates maintained at record
        # time — O(ring) under the lock, never a span-list scan/copy.
        with self._lock:
            out = [{
                "slot": b["slot"],
                "spans": len(b["spans"]),
                "truncated": b["truncated"],
                "wall_ms": round(max(b["t1"] - b["t0"], 0.0) / 1e3, 3),
                "stages": sorted(b["cats"]),
            } for b in self._slots.values()]
        out.sort(key=lambda r: r["slot"])
        return out

    def slot_stats(self) -> List[dict]:
        """Per-slot per-category duration aggregates maintained at
        record time: ``[{"slot", "stats": {cat: {"count", "total_ms",
        "max_ms"}}}]`` — O(ring × cats) under the lock, never a span
        scan.  The SLO engine's worst-offender attribution."""
        with self._lock:
            out = [{
                "slot": b["slot"],
                "stats": {cat: {"count": st[0],
                                "total_ms": round(st[1] / 1e3, 3),
                                "max_ms": round(st[2] / 1e3, 3)}
                          for cat, st in b["stats"].items()},
            } for b in self._slots.values()]
        out.sort(key=lambda r: r["slot"])
        return out

    def slot_trace(self, slot: int) -> Optional[dict]:
        with self._lock:
            bucket = self._slots.get(int(slot))
            if bucket is None:
                return None
            spans = list(bucket["spans"])
            truncated = bucket["truncated"]
        spans.sort(key=lambda s: s["ts_us"])
        return {"slot": int(slot), "truncated": truncated,
                "missing_stages": self._missing(spans), "spans": spans}

    @staticmethod
    def _missing(spans: List[dict]) -> List[str]:
        present = {s["cat"] for s in spans}
        return [st for st in PIPELINE_STAGES if st not in present]

    def missing_stages(self, slot: int) -> List[str]:
        """Pipeline stages absent from ``slot``'s trace (empty = the
        trace covers gossip → head).  A slot never traced reports every
        stage missing."""
        trace = self.slot_trace(slot)
        if trace is None:
            return list(PIPELINE_STAGES)
        return trace["missing_stages"]

    def chrome_trace(self, slot: int) -> Optional[dict]:
        """Chrome trace-event JSON (Perfetto / chrome://tracing).  One
        pid (the node), real thread tracks, ``X`` duration events and
        ``i`` instants."""
        trace = self.slot_trace(slot)
        if trace is None:
            return None
        events: List[dict] = []
        threads: Dict[int, str] = {}
        for s in trace["spans"]:
            threads.setdefault(s["tid"], s["thread"])
        for tid, tname in sorted(threads.items()):
            events.append({"ph": "M", "pid": 0, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": tname}})
        for s in trace["spans"]:
            args = {"slot": trace["slot"], "span_id": s["id"],
                    "parent_id": s["parent"], **s["attrs"]}
            if s.get("inst"):
                events.append({"ph": "i", "pid": 0, "tid": s["tid"],
                               "name": s["name"], "cat": s["cat"] or "-",
                               "ts": s["ts_us"], "s": "t", "args": args})
            else:
                events.append({"ph": "X", "pid": 0, "tid": s["tid"],
                               "name": s["name"], "cat": s["cat"] or "-",
                               "ts": s["ts_us"], "dur": s["dur_us"],
                               "args": args})
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "metadata": {"slot": trace["slot"],
                             "truncated": trace["truncated"],
                             "tool": "lighthouse-tpu tracing"}}


# ---------------------------------------------------------------------------
# Stage-dict source registry (lazy imports: tracing must stay cheap to
# import and cycle-free — the sources import tracing, not vice versa).
# ---------------------------------------------------------------------------

def _src_block() -> dict:
    from ..state_transition.per_block import LAST_BLOCK_TIMINGS
    return LAST_BLOCK_TIMINGS


def _src_epoch() -> dict:
    from ..state_transition.per_epoch import LAST_EPOCH_TIMINGS
    return LAST_EPOCH_TIMINGS


def _src_cold_merkle() -> dict:
    from ..types.validators import LAST_COLD_TIMINGS
    return LAST_COLD_TIMINGS


def _src_leaf_push() -> dict:
    from ..ops.merkle_kernel import LAST_PUSH_STATS
    return LAST_PUSH_STATS


def _src_fast_agg() -> dict:
    from ..crypto.tpu_backend import LAST_FAST_AGG_TIMINGS
    return LAST_FAST_AGG_TIMINGS


def _src_kzg() -> dict:
    from ..kzg.device import LAST_KZG_TIMINGS
    return LAST_KZG_TIMINGS


def _src_bls_kernels() -> dict:
    from ..crypto.profiling import LAST_STAGE_PROFILE
    return LAST_STAGE_PROFILE


def _src_residency() -> dict:
    from ..ops.device_tree import RESIDENCY_STATS
    return RESIDENCY_STATS


def _src_pipeline() -> dict:
    from ..crypto.tpu_backend import LAST_PIPELINE_STATS
    return LAST_PIPELINE_STATS


def _src_materialize() -> dict:
    from ..types.device_state import LAST_MATERIALIZE_STATS
    return LAST_MATERIALIZE_STATS


def _src_block_sigs() -> dict:
    from ..state_transition.sig_dispatch import LAST_SIG_DISPATCH
    return LAST_SIG_DISPATCH


def _src_device_ledger() -> dict:
    from .device_ledger import LEDGER
    return LEDGER.stage_dict()


def _src_op_pool() -> dict:
    from ..op_pool.device_pack import LAST_PACK_STATS
    return LAST_PACK_STATS


def _src_replay() -> dict:
    from ..state_transition.batch_replay import LAST_REPLAY_TIMINGS
    return LAST_REPLAY_TIMINGS


_STAGE_SOURCES: Dict[str, Callable[[], dict]] = {
    "block": _src_block,
    "epoch": _src_epoch,
    "cold_merkle": _src_cold_merkle,
    "leaf_push": _src_leaf_push,
    "fast_agg": _src_fast_agg,
    "kzg": _src_kzg,
    "bls_kernels": _src_bls_kernels,
    "residency": _src_residency,
    "pipeline": _src_pipeline,
    "materialize": _src_materialize,
    "block_sigs": _src_block_sigs,
    "device_ledger": _src_device_ledger,
    "op_pool": _src_op_pool,
    "replay": _src_replay,
}


def register_stage_source(name: str, getter: Callable[[], dict]) -> None:
    """Extension point (tests, future subsystems): add a named stage
    dict to the adapter."""
    _STAGE_SOURCES[name] = getter


# The process tracer + module-level conveniences.
TRACER = Tracer()

span = TRACER.span
instant = TRACER.instant
set_slot = TRACER.set_slot
record_stages = TRACER.record_stages
stage_split = TRACER.stage_split
