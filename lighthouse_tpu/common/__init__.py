"""Cross-cutting commons (counterpart of ``common/*``): metrics registry,
structured logging, slot clocks."""

from .logging import Logger, test_logger
from .metrics import REGISTRY, Registry, start_timer
from .slot_clock import ManualSlotClock, SlotClock, SystemTimeSlotClock

__all__ = ["Logger", "test_logger", "REGISTRY", "Registry", "start_timer",
           "SlotClock", "SystemTimeSlotClock", "ManualSlotClock"]
