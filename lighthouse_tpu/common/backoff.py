"""Shared retry-backoff policy.

One implementation of exponential-backoff-with-jitter for every retry
loop in the process (the device resilience envelope, the engine-API
transport): ``min(base * 2^attempt, max)`` scaled by a uniform jitter in
``[0.5, 1.5)`` so concurrent retriers decorrelate instead of hammering
a recovering dependency in lockstep.
"""

from __future__ import annotations

import random


def backoff_delay(attempt: int, *, base_s: float, max_s: float,
                  rng: random.Random) -> float:
    """Delay before retry number ``attempt`` (0-based)."""
    return min(base_s * (2 ** attempt), max_s) * (0.5 + rng.random())
