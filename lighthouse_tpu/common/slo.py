"""SLO engine & node-health scoreboard — objectives over the pipeline's
record-time aggregates.

PR 9 gave the node per-slot traces and labeled metric families; nothing
turned them into *objectives* — "is the node healthy?  is the block
budget being met?  are we shedding?".  This module is that layer, the
observability counterpart of SRE burn-rate alerting:

- **Declarative registry** — an :class:`Objective` is a feed + a budget:
  ``gossip_to_verified p99 < slot/3``, ``block_import p99 < 150 ms``,
  ``shed_rate < 0.1%``, ``host_fallback_rate < 1%`` (the defaults;
  budgets knob-overridable, registry extensible via
  :meth:`SloEngine.add_objective`).
- **Record-time aggregates only** — feeds return cumulative histogram
  or counter states maintained where events happen (the verification
  service's per-message latency histogram, the chain's block-import
  histogram, shed/fallback counters).  Evaluation diffs those states
  between window snapshots: it never scans span lists or latency
  deques, so the evaluator costs nothing on the hot path (the bench
  ``trace_overhead`` bound holds with the engine enabled).
- **Multi-window rolling attainment** — every objective is evaluated
  over a fast-burn and a slow-burn window (SRE multi-window/multi-burn
  alerting): attainment = fraction of in-budget events in the window,
  error-budget burn = error_rate / error_budget.  An objective is
  *burning* only when BOTH windows burn ≥ the threshold — a transient
  spike (fast only) or an already-recovered incident (slow only) does
  not flip health.
- **Node health with hysteresis** — ``healthy | degraded(reasons) |
  unhealthy(reasons)`` from the burning objectives' severities; a new
  state must hold for N consecutive evaluations before the node
  transitions.  Transitions land in the slot trace
  (``health_transition`` instants, cat ``slo``), the transition log,
  and the ``node_health_state`` gauge.
- **Surfaces** — labeled Prometheus families (``slo_attainment``,
  ``slo_budget_burn`` keyed by objective × window), HTTP routes
  ``/lighthouse/slo`` (full per-objective detail + the worst offending
  slots' trace links) and ``/lighthouse/health`` (the operator's
  one-look answer; 503 when unhealthy).

Knobs: ``LIGHTHOUSE_TPU_SLO`` (master) plus the ``LIGHTHOUSE_TPU_SLO_``
family: fast/slow window seconds, block-import budget, shed/fallback
percents, hysteresis (see the README knob table).

``testing/sustained_load.py`` drives a mainnet-shape gossip stream
through the whole pipeline for minutes (compressed-time mode for tests)
with this engine as the scoreboard; ``scripts/validate_sustained.py``
is the exit-code contract and ``bench.py``'s ``sustained_slo`` row the
standing number.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, replace
from typing import Callable, Deque, Dict, List, Optional, Tuple

from .metrics import REGISTRY
from .tracing import TRACER

HEALTHY = "healthy"
DEGRADED = "degraded"
UNHEALTHY = "unhealthy"
_STATE_LEVEL = {HEALTHY: 0, DEGRADED: 1, UNHEALTHY: 2}


@dataclass(frozen=True)
class Objective:
    """One service-level objective.

    ``kind="latency"``: ``percentile`` of the feed's events must fall
    at or under ``budget`` seconds (attainment target = percentile).
    ``kind="ratio"``: the feed's bad/total rate must stay under
    ``budget`` (attainment target = 1 - budget).
    ``severity`` is the health state a sustained violation drives.
    ``trace_cat`` names the slot-trace category whose record-time
    per-slot stats attribute the worst offending slots."""
    name: str
    feed: str
    kind: str                       # "latency" | "ratio"
    budget: float                   # seconds (latency) | fraction (ratio)
    percentile: float = 0.99
    severity: str = DEGRADED
    trace_cat: Optional[str] = None
    description: str = ""


def default_objectives(slot_seconds: float = 12.0) -> Tuple[Objective, ...]:
    """The standing node objectives (budgets knob-overridable)."""
    from .knobs import knob_float
    return (
        Objective(
            "gossip_to_verified", feed="gossip_to_verified",
            kind="latency", budget=float(slot_seconds) / 3.0,
            percentile=0.99, severity=DEGRADED,
            trace_cat="verification_service",
            description="p99 gossip-arrival → verified latency within "
                        "a third of the slot"),
        Objective(
            "block_import", feed="block_import", kind="latency",
            budget=knob_float("LIGHTHOUSE_TPU_SLO_BLOCK_IMPORT_MS") / 1e3,
            percentile=0.99, severity=DEGRADED, trace_cat="block_import",
            description="p99 block-import wall within the per-block "
                        "budget"),
        Objective(
            "shed_rate", feed="shed_rate", kind="ratio",
            budget=knob_float("LIGHTHOUSE_TPU_SLO_SHED_PCT") / 100.0,
            severity=UNHEALTHY,
            description="messages shed under overload / messages "
                        "submitted"),
        Objective(
            "import_failure_rate", feed="import_failure_rate",
            kind="ratio", budget=0.05, severity=UNHEALTHY,
            description="block imports dying on INFRASTRUCTURE errors "
                        "(store/device) over successes + such failures "
                        "— peer-protocol rejections excluded from both "
                        "sides, so junk gossip can neither burn nor "
                        "dilute it; a latency-only objective would "
                        "read an import-dead node as healthy (empty "
                        "window)"),
        Objective(
            "host_fallback_rate", feed="host_fallback_rate", kind="ratio",
            budget=knob_float("LIGHTHOUSE_TPU_SLO_FALLBACK_PCT") / 100.0,
            severity=DEGRADED,
            description="dispatches served by the host oracle / total "
                        "dispatches"),
        Objective(
            "block_production_ms", feed="block_production",
            kind="latency", budget=float(slot_seconds) / 3.0,
            percentile=0.99, severity=DEGRADED,
            description="p99 end-to-end block production (adopt "
                        "pre-advanced state → device pack → assemble) "
                        "within a third of the slot — a proposer that "
                        "misses this window forfeits the proposal"),
        Objective(
            "proof_serve_ms", feed="proof_serve", kind="latency",
            budget=knob_float("LIGHTHOUSE_TPU_SLO_PROOF_SERVE_MS") / 1e3,
            percentile=0.99, severity=DEGRADED,
            description="p99 proof-request wall (light-client branches + "
                        "state proofs off the device proof engine) — the "
                        "serving plane must not stall behind imports"),
    )


# ---------------------------------------------------------------------------
# Histogram window math (pure functions — pinned against a hand-computed
# oracle in tests/test_slo.py).
# ---------------------------------------------------------------------------

def events_within(buckets: Tuple[float, ...], counts, budget: float
                  ) -> float:
    """Events with value ≤ ``budget`` from per-bucket ``counts``
    (``len(buckets) + 1`` entries, last = +Inf overflow), linearly
    interpolated within the straddling bucket.  Budgets beyond the last
    finite bound count the overflow bucket as OUT of budget
    (conservative: overflow values are unbounded)."""
    total = 0.0
    lo = 0.0
    for i, b in enumerate(buckets):
        if budget >= b:
            total += counts[i]
        else:
            if budget > lo:
                total += counts[i] * (budget - lo) / (b - lo)
            return total
        lo = b
    return total


def hist_quantile(buckets: Tuple[float, ...], counts, q: float
                  ) -> Optional[float]:
    """Interpolated quantile of a per-bucket histogram; ``None`` on an
    empty window.  A rank landing in the overflow bucket reports the
    last finite bound (a lower bound on the true quantile)."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    lo = 0.0
    for i, b in enumerate(buckets):
        c = counts[i]
        if c > 0 and cum + c >= rank:
            return lo + (b - lo) * max(rank - cum, 0.0) / c
        cum += c
        lo = b
    return lo


def _diff_state(cur, base):
    """Window delta of two cumulative feed states (clamped ≥ 0 so a
    counter reset degrades to an empty window, never negatives)."""
    if cur is None:
        return None
    if cur[0] == "hist":
        _tag, buckets, counts, total = cur
        if base is None or base[0] != "hist":
            return ("hist", buckets, counts, total)
        b_counts, b_total = base[2], base[3]
        d = tuple(max(0, c - b) for c, b in zip(counts, b_counts))
        return ("hist", buckets, d, max(0, total - b_total))
    if cur[0] == "ratio":
        _tag, bad, total = cur
        if base is None or base[0] != "ratio":
            return ("ratio", max(0, bad), max(0, total))
        return ("ratio", max(0, bad - base[1]), max(0, total - base[2]))
    return None


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

class SloEngine:
    """Continuous SLO evaluation + node health for one chain.

    Feeds are zero-argument callables returning a cumulative state —
    ``("hist", buckets, per_bucket_counts, total)`` or
    ``("ratio", bad, total)`` — or ``None`` when the source does not
    exist yet.  :meth:`evaluate` snapshots every feed, diffs against
    the snapshot at each window's edge, and derives attainment /
    burn / health.  Thread-safe; gauges are process-global families
    (one evaluating node per process owns them — the simulator's extra
    nodes overwrite labels, same contract as the validator monitor)."""

    MAX_SNAPS = 512  # hard bound independent of evaluation cadence

    def __init__(self, objectives: Optional[Tuple[Objective, ...]] = None,
                 *, clock=time.monotonic, enabled: Optional[bool] = None,
                 fast_window_s: Optional[float] = None,
                 slow_window_s: Optional[float] = None,
                 hysteresis: Optional[int] = None,
                 burn_threshold: float = 1.0,
                 min_bad_events: float = 2.0,
                 min_eval_interval_s: float = 1.0):
        from .knobs import knob_bool, knob_float, knob_int
        self.enabled = (knob_bool("LIGHTHOUSE_TPU_SLO")
                        if enabled is None else bool(enabled))
        self.fast_window_s = (
            knob_float("LIGHTHOUSE_TPU_SLO_FAST_WINDOW_S")
            if fast_window_s is None else float(fast_window_s))
        self.slow_window_s = (
            knob_float("LIGHTHOUSE_TPU_SLO_SLOW_WINDOW_S")
            if slow_window_s is None else float(slow_window_s))
        self.hysteresis = (knob_int("LIGHTHOUSE_TPU_SLO_HYSTERESIS")
                           if hysteresis is None else max(1, int(hysteresis)))
        self.burn_threshold = float(burn_threshold)
        # A single out-of-budget event can never flip health: with
        # p99-style targets over small windows, one scheduler stall
        # would otherwise read as burn ≫ 1 (1 bad of 24 events = 4×
        # budget).  Windows must hold at least this much bad mass.
        self.min_bad_events = float(min_bad_events)
        self.min_eval_interval_s = float(min_eval_interval_s)
        self._clock = clock
        self._objectives: Dict[str, Objective] = {
            o.name: o for o in (objectives if objectives is not None
                                else default_objectives())}
        self._feeds: Dict[str, Callable[[], object]] = {}
        self._lock = threading.Lock()
        # Whole-evaluation serialization: the timer tick and an HTTP
        # refresh can evaluate concurrently; the health state machine
        # (pending counts, transition log) assumes one stepper.
        self._eval_lock = threading.Lock()
        self._snaps: Deque[Tuple[float, dict]] = deque()  # guarded-by: _lock
        self.state = HEALTHY
        self.state_since = self._clock()
        self.transitions: Deque[dict] = deque(maxlen=64)
        self._pending_state: Optional[str] = None
        self._pending_n = 0
        self._current_reasons: List[str] = []
        self._last_report: Optional[dict] = None
        self._last_eval_t: Optional[float] = None
        self._g_att = REGISTRY.gauge(
            "slo_attainment", "windowed SLO attainment per objective",
            labelnames=("objective", "window"))
        self._g_burn = REGISTRY.gauge(
            "slo_budget_burn", "error-budget burn rate per objective",
            labelnames=("objective", "window"))
        self._g_health = REGISTRY.gauge(
            "node_health_state",
            "node health (0 healthy, 1 degraded, 2 unhealthy)")

    # -- registry ------------------------------------------------------------

    def register_feed(self, name: str, fn: Callable[[], object]) -> None:
        self._feeds[name] = fn

    def add_objective(self, objective: Objective) -> None:
        self._objectives[objective.name] = objective

    def set_budget(self, name: str, budget: float) -> None:
        """Override one objective's budget (the sustained driver scales
        gossip_to_verified to its compressed slot)."""
        self._objectives[name] = replace(self._objectives[name],
                                         budget=float(budget))

    def objectives(self) -> List[Objective]:
        return list(self._objectives.values())

    def configure(self, *, fast_window_s: Optional[float] = None,
                  slow_window_s: Optional[float] = None,
                  hysteresis: Optional[int] = None,
                  min_eval_interval_s: Optional[float] = None) -> None:
        if fast_window_s is not None:
            self.fast_window_s = float(fast_window_s)
        if slow_window_s is not None:
            self.slow_window_s = float(slow_window_s)
        if hysteresis is not None:
            self.hysteresis = max(1, int(hysteresis))
        if min_eval_interval_s is not None:
            self.min_eval_interval_s = float(min_eval_interval_s)

    # -- evaluation ----------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """Rate-limited :meth:`evaluate` — the per-slot-task hook (a
        harness looping per_slot_task hundreds of times per second must
        not snapshot every call).  The interval check-and-set runs
        under the evaluation lock: two concurrent tickers (timer thread
        + an HTTP scrape) must not both pass it, or the hysteresis
        counter steps faster than the configured cadence."""
        if not self.enabled:
            return None
        with self._eval_lock:
            now = self._clock() if now is None else now
            if self._last_eval_t is not None and \
                    now - self._last_eval_t < self.min_eval_interval_s:
                return None
            return self._evaluate_locked(now)

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One evaluation: snapshot feeds, window-diff, health step.
        Returns (and stores) the full report dict."""
        with self._eval_lock:
            return self._evaluate_locked(now)

    def _evaluate_locked(self, now: Optional[float]) -> dict:
        now = self._clock() if now is None else now
        self._last_eval_t = now
        if not self.enabled:
            return self.report()
        cur: dict = {}
        # list() snapshots: register_feed/add_objective are legal on a
        # live engine from another thread — iterating the dicts raw
        # would RuntimeError mid-tick on a concurrent registration.
        for name, fn in list(self._feeds.items()):
            try:
                cur[name] = fn()
            except Exception:  # noqa: BLE001 — a broken feed reads as
                cur[name] = None  # "no data", never kills the evaluator
        with self._lock:
            self._snaps.append((now, cur))
            horizon = now - self.slow_window_s
            # Keep ONE snapshot at/behind the slow edge as its baseline.
            while len(self._snaps) > 2 and self._snaps[1][0] <= horizon:
                self._snaps.popleft()
            while len(self._snaps) > self.MAX_SNAPS:
                self._snaps.popleft()
            snaps = list(self._snaps)
        # A capped deque whose oldest snapshot is younger than the slow
        # window means the cap — not startup — bounds the window: say
        # so instead of silently burning over a shorter span than the
        # operator configured (span_s on each window row carries the
        # actual coverage).
        slow_truncated = (len(snaps) >= self.MAX_SNAPS
                          and now - snaps[0][0] < self.slow_window_s)
        slot_stats = TRACER.slot_stats() if TRACER.enabled else []
        rows = []
        burning: List[Objective] = []
        for obj in list(self._objectives.values()):
            row = self._eval_objective(obj, cur.get(obj.feed), snaps, now,
                                       slot_stats)
            rows.append(row)
            if row["burning"]:
                burning.append(obj)
        reasons = [o.name for o in burning]
        candidate = HEALTHY
        for o in burning:
            if _STATE_LEVEL.get(o.severity, 1) > _STATE_LEVEL[candidate]:
                candidate = o.severity
        self._health_step(candidate, reasons, now)
        report = {
            "state": self.state,
            "since": round(self.state_since, 3),
            "reasons": (list(self._current_reasons)
                        if self.state != HEALTHY else []),
            "burning": reasons,
            "windows": {"fast_s": self.fast_window_s,
                        "slow_s": self.slow_window_s,
                        "slow_truncated_by_snapshot_cap": slow_truncated},
            "hysteresis": self.hysteresis,
            "objectives": rows,
            "transitions": list(self.transitions),
            "evaluated_at": round(now, 3),
            "enabled": self.enabled,
        }
        self._last_report = report
        return report

    def _baseline(self, snaps, now: float, window_s: float):
        """Newest snapshot at/behind the window edge (else the oldest —
        a short-lived process measures since start)."""
        edge = now - window_s
        base = snaps[0]
        for snap in snaps:
            if snap[0] <= edge:
                base = snap
            else:
                break
        return base

    def _eval_window(self, obj: Objective, cur_state, snaps, now,
                     window_s: float) -> dict:
        base_t, base = self._baseline(snaps, now, window_s)
        d = _diff_state(cur_state, base.get(obj.feed))
        out: dict = {"window_s": window_s,
                     "span_s": round(max(now - base_t, 0.0), 3),
                     "events": 0, "attainment": None, "burn": None}
        if d is None:
            return out
        if obj.kind == "latency" and d[0] == "hist":
            _tag, buckets, counts, total = d
            out["events"] = int(total)
            if buckets and obj.budget > buckets[-1]:
                # The feed cannot resolve a budget above its top finite
                # bound: overflow events are indistinguishable from
                # in-budget ones there, and counting them out-of-budget
                # (the normal conservative rule) would FALSELY burn an
                # objective whose every event meets the raised budget.
                out["note"] = (f"budget {obj.budget}s beyond histogram "
                               f"resolution ({buckets[-1]}s) — not "
                               f"measurable")
                return out
            # The overflow bucket is part of the event count even though
            # interpolation never credits it as in-budget.
            n = sum(counts)
            if n > 0:
                good = events_within(buckets, counts, obj.budget)
                att = min(good / n, 1.0)
                out["bad"] = round(n - good, 3)
                out["attainment"] = round(att, 6)
                err_budget = 1.0 - obj.percentile
                # 1e9 caps stand in for infinity: the JSON surfaces
                # must stay strict-parseable (Infinity is not JSON).
                out["burn"] = round((1.0 - att) / err_budget, 3) \
                    if err_budget > 0 else (0.0 if att >= 1.0 else 1e9)
                p50 = hist_quantile(buckets, counts, 0.50)
                p99 = hist_quantile(buckets, counts, 0.99)
                out["p50_ms"] = None if p50 is None else round(p50 * 1e3, 2)
                out["p99_ms"] = None if p99 is None else round(p99 * 1e3, 2)
        elif obj.kind == "ratio" and d[0] == "ratio":
            _tag, bad, total = d
            out["events"] = int(total)
            if total > 0:
                rate = bad / total
                out["bad"] = int(bad)
                out["rate"] = round(rate, 6)
                out["attainment"] = round(1.0 - rate, 6)
                if obj.budget > 0:
                    out["burn"] = round(rate / obj.budget, 3)
                else:
                    out["burn"] = 0.0 if rate == 0 else 1e9
        return out

    def _eval_objective(self, obj: Objective, cur_state, snaps, now,
                        slot_stats) -> dict:
        fast = self._eval_window(obj, cur_state, snaps, now,
                                 self.fast_window_s)
        slow = self._eval_window(obj, cur_state, snaps, now,
                                 self.slow_window_s)
        # SRE multi-window rule: page only when BOTH windows burn — the
        # fast window confirms it is happening NOW, the slow window that
        # it is material against the budget — and both hold at least
        # min_bad_events of bad mass (a lone straggler never pages).
        burning = (fast["burn"] is not None and slow["burn"] is not None
                   and fast["burn"] >= self.burn_threshold
                   and slow["burn"] >= self.burn_threshold
                   and fast.get("bad", 0.0) >= self.min_bad_events
                   and slow.get("bad", 0.0) >= self.min_bad_events)
        for label, win in (("fast", fast), ("slow", slow)):
            # An empty window exports the NEUTRAL values (no events =
            # no errors): skipping the write would leave an incident's
            # last burn value frozen on /metrics forever after traffic
            # stops, paging on an incident that ended.
            att = win["attainment"]
            burn = win["burn"]
            self._g_att.labels(obj.name, label).set(
                1.0 if att is None else att)
            self._g_burn.labels(obj.name, label).set(
                0.0 if burn is None else min(burn, 1e9))
        row = {
            "name": obj.name, "kind": obj.kind, "feed": obj.feed,
            "severity": obj.severity, "description": obj.description,
            "budget": obj.budget, "burning": burning,
            "fast": fast, "slow": slow,
        }
        if obj.kind == "latency":
            row["percentile"] = obj.percentile
            row["budget_ms"] = round(obj.budget * 1e3, 2)
        if obj.trace_cat and slot_stats:
            # Top-3 HEAVIEST slots by the category's max span — no
            # budget filter: the spans are stage costs, not the feed's
            # end-to-end latency (a queue-wait burn has ms-scale
            # dispatch spans), so a threshold would return [] exactly
            # when the operator needs somewhere to look.
            worst = []
            for s in slot_stats:
                st = s["stats"].get(obj.trace_cat)
                if st is not None:
                    worst.append({"slot": s["slot"],
                                  "max_ms": st["max_ms"],
                                  "trace": f"/lighthouse/tracing/slot/"
                                           f"{s['slot']}"})
            worst.sort(key=lambda w: -w["max_ms"])
            row["worst_slots"] = worst[:3]
        return row

    # -- health state machine ------------------------------------------------

    def _health_step(self, candidate: str, reasons: List[str],
                     now: float) -> None:
        """Hysteresis: a candidate state must hold ``hysteresis``
        consecutive evaluations before the node transitions (both
        directions — flapping feeds can neither degrade nor clear the
        node on one sample)."""
        if candidate == self.state:
            self._pending_state = None
            self._pending_n = 0
            if candidate != HEALTHY:
                self._current_reasons = reasons
            return
        if candidate == self._pending_state:
            self._pending_n += 1
        else:
            self._pending_state = candidate
            self._pending_n = 1
        if self._pending_n < self.hysteresis:
            return
        old = self.state
        self.state = candidate
        self.state_since = now
        self._current_reasons = reasons if candidate != HEALTHY else []
        self._pending_state = None
        self._pending_n = 0
        self.transitions.append({
            "t": round(now, 3), "from": old, "to": candidate,
            "reasons": list(reasons)})
        self._g_health.set(float(_STATE_LEVEL[candidate]))
        if TRACER.enabled:
            TRACER.instant("health_transition", cat="slo",
                           from_state=old, to_state=candidate,
                           reasons=",".join(reasons))

    # -- surfaces ------------------------------------------------------------

    def report(self, refresh: bool = False) -> dict:
        """Last evaluation (optionally refreshed) — the
        ``/lighthouse/slo`` body."""
        if refresh and self.enabled:
            return self.evaluate()
        if self._last_report is not None:
            return self._last_report
        return {
            "state": self.state, "since": round(self.state_since, 3),
            "reasons": [], "burning": [],
            "windows": {"fast_s": self.fast_window_s,
                        "slow_s": self.slow_window_s},
            "hysteresis": self.hysteresis,
            "objectives": [], "transitions": list(self.transitions),
            "evaluated_at": None,
            "enabled": self.enabled,
        }

    def health(self) -> dict:
        """The one-look answer — the ``/lighthouse/health`` body."""
        return {
            "state": self.state,
            "reasons": (list(self._current_reasons)
                        if self.state != HEALTHY else []),
            "since": round(self.state_since, 3),
            "enabled": self.enabled,
            "transitions": len(self.transitions),
        }


# ---------------------------------------------------------------------------
# Chain wiring — the default feeds, all record-time aggregates.
# ---------------------------------------------------------------------------

def wire_chain_feeds(engine: SloEngine, chain) -> None:
    """Attach the standard feeds for one chain.  Every feed reads a
    cumulative record-time aggregate owned by the source subsystem —
    the service's local latency histogram, the chain's import
    histogram, the service/envelope counters — never per-event lists.
    Feeds resolve ``chain.verification_service`` at call time (the
    network layer attaches it after chain construction)."""

    def gossip_to_verified():
        svc = chain.verification_service
        if svc is None:
            return None
        buckets, counts, total, _sum = svc.latency_snapshot()
        return ("hist", buckets, counts, total)

    def block_import():
        buckets, counts, total, _sum = chain._slo_import_hist.snapshot()
        return ("hist", buckets, counts, total)

    def shed_rate():
        svc = chain.verification_service
        if svc is None:
            return ("ratio", 0, 0)
        ctr = svc.slo_counters()
        return ("ratio", ctr.get("shed", 0), ctr.get("submitted", 0))

    def import_failure_rate():
        return ("ratio", chain._slo_import_failures,
                chain._slo_import_attempts)

    def host_fallback_rate():
        svc = chain.verification_service
        if svc is None:
            return ("ratio", 0, 0)
        bad = good = 0
        for env in (svc.envelope, svc.kzg_envelope):
            snap = env.snapshot()
            bad += snap.get("host_fallbacks", 0)
            good += snap.get("device_ok", 0)
        return ("ratio", bad, bad + good)

    def proof_serve():
        # Raw attribute, NOT the lazy property — a feed evaluation must
        # never construct the proof server; before the first proof
        # request the objective simply has no window.
        srv = getattr(chain, "_proof_server", None)
        if srv is None:
            return None
        buckets, counts, total, _sum = srv.latency_snapshot()
        return ("hist", buckets, counts, total)

    def block_production():
        buckets, counts, total, _sum = \
            chain._slo_production_hist.snapshot()
        return ("hist", buckets, counts, total)

    engine.register_feed("gossip_to_verified", gossip_to_verified)
    engine.register_feed("block_production", block_production)
    engine.register_feed("block_import", block_import)
    engine.register_feed("shed_rate", shed_rate)
    engine.register_feed("import_failure_rate", import_failure_rate)
    engine.register_feed("host_fallback_rate", host_fallback_rate)
    engine.register_feed("proof_serve", proof_serve)
