"""Process-global metrics registry — ``common/lighthouse_metrics``
(``/root/reference/common/lighthouse_metrics/src/lib.rs:2-37,69-137``):
counters, gauges and histograms created lazily by name, ``start_timer`` /
``stop_timer`` guards around hot sections, and Prometheus text encoding
(the scrape surface of ``beacon_node/http_metrics``).

Labeled families: pass ``labelnames=("kind", ...)`` at creation and call
``.labels("subnet_att")`` (or ``.labels(kind="subnet_att")``) for the
per-label-set child metric.  Exposition follows the Prometheus text
format: one ``# HELP``/``# TYPE`` header per family, label values
escaped (backslash, newline, double quote) and help text escaped
(backslash, newline) per the spec.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional, Tuple

_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0)


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, newline,
    double quote (in that order — escaping the escape char first)."""
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _escape_help(h: str) -> str:
    """HELP lines escape backslash and newline only."""
    return h.replace("\\", "\\\\").replace("\n", "\\n")


def _pairs_str(pairs: Tuple[Tuple[str, str], ...]) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
    return "{" + inner + "}"


class _LabeledFamily:
    """Shared ``labels()`` machinery: a metric created with
    ``labelnames`` acts as a family whose children carry the values."""

    def _init_family(self, labelnames) -> None:
        self.labelnames = tuple(labelnames)
        self._label_pairs: Tuple[Tuple[str, str], ...] = ()
        self._children: Dict[tuple, object] = {}

    def _resolve_values(self, values, kw) -> tuple:
        if kw:
            if values:
                raise ValueError("pass label values positionally OR by "
                                 "keyword, not both")
            if set(kw) != set(self.labelnames):
                raise ValueError(f"labels {sorted(kw)} != declared "
                                 f"{list(self.labelnames)}")
            return tuple(str(kw[k]) for k in self.labelnames)
        if len(values) != len(self.labelnames):
            raise ValueError(f"expected {len(self.labelnames)} label "
                             f"values, got {len(values)}")
        return tuple(str(v) for v in values)

    def labels(self, *values, **kw):
        if not self.labelnames:
            raise ValueError(f"metric {self.name} has no labels")
        vals = self._resolve_values(values, kw)
        with self._lock:
            child = self._children.get(vals)
            if child is None:
                child = self._make_child()
                child._label_pairs = tuple(zip(self.labelnames, vals))
                self._children[vals] = child
            return child

    def _sorted_children(self) -> list:
        with self._lock:
            return [c for _k, c in sorted(self._children.items())]

    def clear_children(self) -> None:
        """Drop every labeled child series (the family stays
        registered).  For callers that stop emitting per-label series —
        leaving the old children in place would export frozen stale
        values forever."""
        with self._lock:
            self._children.clear()


class Counter(_LabeledFamily):
    def __init__(self, name: str, help_: str, labelnames=()):
        self.name, self.help = name, help_
        self.value = 0.0
        self._lock = threading.Lock()
        self._init_family(labelnames)

    _TYPE = "counter"

    def _make_child(self) -> "Counter":
        return type(self)(self.name, self.help)

    def inc(self, by: float = 1.0) -> None:
        if self.labelnames:
            raise ValueError(f"labeled metric {self.name}: call "
                             ".labels(...) first")
        with self._lock:
            self.value += by

    def _header(self) -> str:
        return (f"# HELP {self.name} {_escape_help(self.help)}\n"
                f"# TYPE {self.name} {self._TYPE}\n")

    def _sample_lines(self) -> str:
        return f"{self.name}{_pairs_str(self._label_pairs)} {self.value}\n"

    def encode(self) -> str:
        if self.labelnames:
            return self._header() + "".join(
                c._sample_lines() for c in self._sorted_children())
        return self._header() + self._sample_lines()


class Gauge(Counter):
    _TYPE = "gauge"

    def set(self, v: float) -> None:
        if self.labelnames:
            raise ValueError(f"labeled metric {self.name}: call "
                             ".labels(...) first")
        with self._lock:
            self.value = v


class Histogram(_LabeledFamily):
    def __init__(self, name: str, help_: str,
                 buckets: Tuple[float, ...] = _DEFAULT_BUCKETS,
                 labelnames=()):
        self.name, self.help = name, help_
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.total = 0
        self._lock = threading.Lock()
        self._init_family(labelnames)

    def _make_child(self) -> "Histogram":
        return type(self)(self.name, self.help, buckets=self.buckets)

    def observe(self, v: float) -> None:
        if self.labelnames:
            raise ValueError(f"labeled metric {self.name}: call "
                             ".labels(...) first")
        # bisect_left finds the first bucket with bound >= v — identical
        # to the linear `v <= b` scan, O(log n) instead of O(n) per
        # observation on the hot verify/import paths; index len(buckets)
        # IS the +Inf overflow slot.
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.sum += v
            self.total += 1
            self.counts[i] += 1

    def start_timer(self) -> "HistogramTimer":
        return HistogramTimer(self)

    def snapshot(self) -> Tuple[Tuple[float, ...], Tuple[int, ...],
                                int, float]:
        """Consistent ``(buckets, per-bucket counts, total, sum)`` copy —
        the record-time aggregate the SLO engine diffs between window
        snapshots (never a per-observation list)."""
        with self._lock:
            return (self.buckets, tuple(self.counts), self.total,
                    self.sum)

    def _header(self) -> str:
        return (f"# HELP {self.name} {_escape_help(self.help)}\n"
                f"# TYPE {self.name} histogram\n")

    def _sample_lines(self) -> str:
        out = []
        cum = 0
        for b, c in zip(self.buckets, self.counts):
            cum += c
            out.append(f"{self.name}_bucket"
                       f"{_pairs_str(self._label_pairs + (('le', str(b)),))}"
                       f" {cum}")
        cum += self.counts[-1]
        out.append(f"{self.name}_bucket"
                   f"{_pairs_str(self._label_pairs + (('le', '+Inf'),))}"
                   f" {cum}")
        base = _pairs_str(self._label_pairs)
        out.append(f"{self.name}_sum{base} {self.sum}")
        out.append(f"{self.name}_count{base} {self.total}")
        return "\n".join(out) + "\n"

    def encode(self) -> str:
        if self.labelnames:
            return self._header() + "".join(
                c._sample_lines() for c in self._sorted_children())
        return self._header() + self._sample_lines()


class HistogramTimer:
    """`start_timer`/`stop_timer` guard; also a context manager."""

    def __init__(self, hist: Histogram):
        self.hist = hist
        self.t0 = time.perf_counter()
        self.stopped = False

    def stop(self) -> float:
        if not self.stopped:
            dt = time.perf_counter() - self.t0
            self.hist.observe(dt)
            self.stopped = True
            return dt
        return 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class Registry:
    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._collectors: List = []
        self._lock = threading.Lock()

    def register_collector(self, fn) -> None:
        """Scrape-time refresher: ``fn()`` runs before every
        :meth:`encode` so pull-model values (process RSS, fd count, GC
        stats) are current at scrape without a background thread."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def _get(self, cls, name: str, help_: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name} already registered as "
                                f"{type(m).__name__}")
            elif tuple(kw.get("labelnames", ())) != \
                    getattr(m, "labelnames", ()):
                raise TypeError(
                    f"metric {name} already registered with labels "
                    f"{list(getattr(m, 'labelnames', ()))}")
            return m

    def counter(self, name: str, help_: str = "", **kw) -> Counter:
        return self._get(Counter, name, help_, **kw)

    def gauge(self, name: str, help_: str = "", **kw) -> Gauge:
        return self._get(Gauge, name, help_, **kw)

    def histogram(self, name: str, help_: str = "", **kw) -> Histogram:
        return self._get(Histogram, name, help_, **kw)

    def encode(self) -> str:
        """Prometheus text exposition (the `/metrics` body)."""
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a broken collector must
                pass           # never take the whole scrape down
        with self._lock:
            metrics = sorted(self._metrics.items())
        return "".join(m.encode() for _, m in metrics)


# The process-global registry (`lighthouse_metrics` lazy_static).
REGISTRY = Registry()

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram


def start_timer(name: str, help_: str = "") -> HistogramTimer:
    return REGISTRY.histogram(name, help_).start_timer()


def observe(name: str, value: float, help_: str = "") -> None:
    """One-shot histogram observation — the stage-boundary hook the
    device pipeline uses (host-prep / transfer / compute / pull), where
    the section being timed spans threads and a timer guard can't."""
    REGISTRY.histogram(name, help_).observe(value)


# ---------------------------------------------------------------------------
# Process-level metrics (the classic node-observability gap): RSS, thread
# count, open fds, uptime and GC collections as standard gauges refreshed
# at scrape time.  Cardinality is bounded — plain gauges plus one labeled
# family with exactly the three GC generations.
# ---------------------------------------------------------------------------

_PROCESS_T0 = time.monotonic()


def _read_rss_bytes() -> Optional[int]:
    """VmRSS from /proc (linux); None elsewhere — the RSS gauge is
    then simply absent from the exposition (it is only created on the
    first successful read; same contract as process_open_fds)."""
    try:
        with open("/proc/self/status", "r") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        return None
    return None


def _collect_process_metrics() -> None:
    import gc
    import os

    rss = _read_rss_bytes()
    if rss is not None:
        REGISTRY.gauge("process_resident_memory_bytes",
                       "resident set size").set(float(rss))
    REGISTRY.gauge("process_threads",
                   "live python threads").set(
        float(threading.active_count()))
    try:
        n_fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        n_fds = None
    if n_fds is not None:
        REGISTRY.gauge("process_open_fds",
                       "open file descriptors").set(float(n_fds))
    REGISTRY.gauge("process_uptime_seconds",
                   "seconds since metrics import").set(
        time.monotonic() - _PROCESS_T0)
    g = REGISTRY.gauge("process_gc_collections",
                       "collector runs per GC generation",
                       labelnames=("generation",))
    for gen, stats in enumerate(gc.get_stats()):
        g.labels(str(gen)).set(float(stats.get("collections", 0)))


REGISTRY.register_collector(_collect_process_metrics)
