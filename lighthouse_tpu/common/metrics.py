"""Process-global metrics registry — ``common/lighthouse_metrics``
(``/root/reference/common/lighthouse_metrics/src/lib.rs:2-37,69-137``):
counters, gauges and histograms created lazily by name, ``start_timer`` /
``stop_timer`` guards around hot sections, and Prometheus text encoding
(the scrape surface of ``beacon_node/http_metrics``)."""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0)


class Counter:
    def __init__(self, name: str, help_: str):
        self.name, self.help = name, help_
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, by: float = 1.0) -> None:
        with self._lock:
            self.value += by

    def encode(self) -> str:
        return (f"# HELP {self.name} {self.help}\n"
                f"# TYPE {self.name} counter\n"
                f"{self.name} {self.value}\n")


class Gauge(Counter):
    def set(self, v: float) -> None:
        with self._lock:
            self.value = v

    def encode(self) -> str:
        return (f"# HELP {self.name} {self.help}\n"
                f"# TYPE {self.name} gauge\n"
                f"{self.name} {self.value}\n")


class Histogram:
    def __init__(self, name: str, help_: str,
                 buckets: Tuple[float, ...] = _DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.sum = 0.0
        self.total = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.sum += v
            self.total += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def start_timer(self) -> "HistogramTimer":
        return HistogramTimer(self)

    def encode(self) -> str:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        cum = 0
        for b, c in zip(self.buckets, self.counts):
            cum += c
            out.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
        cum += self.counts[-1]
        out.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        out.append(f"{self.name}_sum {self.sum}")
        out.append(f"{self.name}_count {self.total}")
        return "\n".join(out) + "\n"


class HistogramTimer:
    """`start_timer`/`stop_timer` guard; also a context manager."""

    def __init__(self, hist: Histogram):
        self.hist = hist
        self.t0 = time.perf_counter()
        self.stopped = False

    def stop(self) -> float:
        if not self.stopped:
            dt = time.perf_counter() - self.t0
            self.hist.observe(dt)
            self.stopped = True
            return dt
        return 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class Registry:
    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help_: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name} already registered as "
                                f"{type(m).__name__}")
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(Gauge, name, help_)

    def histogram(self, name: str, help_: str = "", **kw) -> Histogram:
        return self._get(Histogram, name, help_, **kw)

    def encode(self) -> str:
        """Prometheus text exposition (the `/metrics` body)."""
        with self._lock:
            return "".join(m.encode()
                           for _, m in sorted(self._metrics.items()))


# The process-global registry (`lighthouse_metrics` lazy_static).
REGISTRY = Registry()

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram


def start_timer(name: str, help_: str = "") -> HistogramTimer:
    return REGISTRY.histogram(name, help_).start_timer()


def observe(name: str, value: float, help_: str = "") -> None:
    """One-shot histogram observation — the stage-boundary hook the
    device pipeline uses (host-prep / transfer / compute / pull), where
    the section being timed spans threads and a timer guard can't."""
    REGISTRY.histogram(name, help_).observe(value)
