"""Task executor — the role of ``common/task_executor``
(``/root/reference/common/task_executor/src/lib.rs``): every long-lived
service thread registers here, so shutdown is one call that signals,
joins, and reports stragglers, and metrics expose what is running.

The reference wraps a tokio runtime handle + exit futures + a shutdown
channel; this build's runtime is OS threads, so the executor wraps
daemon threads with a shared shutdown :class:`threading.Event` and a
registry the metrics endpoint can read (``async_tasks_count`` role).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .metrics import REGISTRY


@dataclass
class _Task:
    name: str
    thread: threading.Thread
    critical: bool = False


class TaskExecutor:
    """Spawn/track/shutdown for service threads."""

    def __init__(self, log=None):
        self.log = log
        self.shutdown_signal = threading.Event()
        self._tasks: List[_Task] = []
        self._lock = threading.Lock()
        self._gauge = REGISTRY.gauge(
            "task_executor_tasks", "Live service threads")

    def spawn(self, fn: Callable[[threading.Event], None], name: str,
              critical: bool = False) -> threading.Thread:
        """Run ``fn(shutdown_event)`` on a named daemon thread.  The fn
        must poll/wait on the event and return when it fires.  A CRITICAL
        task dying triggers executor-wide shutdown (`task_executor`'s
        ``spawn_monitor`` semantics: losing the beacon processor is fatal,
        losing a metrics scraper is not)."""

        def runner():
            try:
                fn(self.shutdown_signal)
            except Exception:
                if self.log is not None:
                    self.log.warn("task died", task=name)
                if critical:
                    self.shutdown_signal.set()
            finally:
                with self._lock:
                    self._tasks[:] = [t for t in self._tasks
                                      if t.thread is not thread]
                self._gauge.set(len(self._tasks))

        thread = threading.Thread(target=runner, name=name, daemon=True)
        with self._lock:
            self._tasks.append(_Task(name=name, thread=thread,
                                     critical=critical))
        self._gauge.set(len(self._tasks))
        thread.start()
        return thread

    def running(self) -> List[str]:
        with self._lock:
            return [t.name for t in self._tasks if t.thread.is_alive()]

    def shutdown(self, timeout: float = 5.0) -> List[str]:
        """Signal + join; returns the names of stragglers that failed to
        stop within the timeout (logged, like the reference's exit
        timeout warnings)."""
        self.shutdown_signal.set()
        with self._lock:
            tasks = list(self._tasks)
        stragglers = []
        for t in tasks:
            t.thread.join(timeout=timeout)
            if t.thread.is_alive():
                stragglers.append(t.name)
                if self.log is not None:
                    self.log.warn("task did not stop", task=t.name)
        return stragglers
