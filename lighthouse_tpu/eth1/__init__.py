"""Eth1 bridge: deposit cache, block cache, eth1-data voting, genesis.

Counterpart of ``beacon_node/eth1`` (``/root/reference/beacon_node/eth1/
src/``) and ``beacon_node/genesis``: ingested deposit-contract logs feed a
Merkle deposit tree (proof source for blocks), an eth1 block cache backs
the in-range eth1_data vote, and :func:`genesis_from_deposits` builds the
full genesis state by replaying deposits
(``genesis/src/eth1_genesis_service.rs`` + ``state_processing/src/
genesis.rs``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..ops.merkle_proof import DepositTree
from ..types.chain_spec import ForkName, GENESIS_EPOCH


@dataclass
class Eth1Block:
    """`block_cache.rs` Eth1Block."""
    hash: bytes
    number: int
    timestamp: int
    deposit_root: bytes
    deposit_count: int


class DepositCache:
    """Ordered deposit logs + proof tree (`deposit_cache.rs`)."""

    def __init__(self, depth: int = 32):
        self.tree = DepositTree(depth)
        self.logs: List[object] = []  # DepositData in log order

    def insert_log(self, index: int, deposit_data) -> None:
        if index != len(self.logs):
            raise ValueError(f"non-contiguous deposit log {index}, "
                             f"expected {len(self.logs)}")
        self.logs.append(deposit_data)
        self.tree.push(deposit_data.tree_hash_root())

    def get_deposits(self, start: int, end: int, T) -> List:
        """Deposits [start, end) with proofs valid against the tree at
        ``end`` deposits (`deposit_cache.rs get_deposits`)."""
        if end > len(self.logs):
            raise ValueError("deposit range beyond known logs")
        sub = DepositTree(self.tree.tree.depth)
        for d in self.logs[:end]:
            sub.push(d.tree_hash_root())
        return [T.Deposit(proof=sub.proof(i), data=self.logs[i])
                for i in range(start, end)]

    def root_at(self, count: int) -> bytes:
        sub = DepositTree(self.tree.tree.depth)
        for d in self.logs[:count]:
            sub.push(d.tree_hash_root())
        return sub.root()


class BlockCache:
    def __init__(self):
        self.by_number: Dict[int, Eth1Block] = {}

    def insert(self, block: Eth1Block) -> None:
        self.by_number[block.number] = block

    def latest(self) -> Optional[Eth1Block]:
        if not self.by_number:
            return None
        return self.by_number[max(self.by_number)]


class Eth1Service:
    """Polling service role (`service.rs`): callers push logs/blocks; the
    chain asks for the eth1 vote."""

    def __init__(self, preset, spec):
        self.preset = preset
        self.spec = spec
        self.deposits = DepositCache(preset.DEPOSIT_CONTRACT_TREE_DEPTH)
        self.blocks = BlockCache()

    def eth1_data_for_vote(self, state, T):
        """`get_eth1_vote`: pick the latest in-range block's eth1 data
        (majority voting simplified to freshest-valid, like the reference's
        fallback when no majority exists)."""
        latest = self.blocks.latest()
        if latest is None or latest.deposit_count < int(
                state.eth1_data.deposit_count):
            return state.eth1_data
        return T.Eth1Data(deposit_root=latest.deposit_root,
                          deposit_count=latest.deposit_count,
                          block_hash=latest.hash)


def genesis_from_deposits(deposits: List, eth1_block_hash: bytes,
                          eth1_timestamp: int, preset, spec, T,
                          fork: ForkName = ForkName.PHASE0):
    """``initialize_beacon_state_from_eth1``
    (``state_processing/src/genesis.rs``): replay every deposit, activate
    validators with full effective balance, stamp genesis metadata.
    Returns None-equivalent validity via ``is_valid_genesis_state``
    semantics (caller checks validator count)."""
    from ..state_transition.genesis import interop_genesis_state
    from ..state_transition.per_block import apply_deposit
    from ..state_transition.upgrade import upgrade_state

    # Start from an empty-registry state skeleton at the fork.
    state = interop_genesis_state(0, 0, preset, spec, T, fork=fork)
    state.genesis_time = (eth1_timestamp + spec.genesis_delay)
    state.eth1_data = T.Eth1Data(
        deposit_root=b"\x00" * 32, deposit_count=len(deposits),
        block_hash=eth1_block_hash)
    for i in range(preset.EPOCHS_PER_HISTORICAL_VECTOR):
        state.randao_mixes.set(i, eth1_block_hash)

    # Apply deposits (signature-checked; invalid ones skip, per spec).
    for deposit in deposits:
        apply_deposit(state, deposit.data, preset, spec, T)
    state.eth1_deposit_index = len(deposits)

    # Activate genesis validators (`genesis.rs` activation loop) —
    # columnar: everyone at MAX_EFFECTIVE_BALANCE activates at genesis.
    reg = state.validators
    n = len(reg)
    if n:
        bal = np.asarray(state.balances[:n], dtype=np.uint64)
        eff = np.minimum(
            bal - bal % preset.EFFECTIVE_BALANCE_INCREMENT,
            preset.MAX_EFFECTIVE_BALANCE).astype(np.uint64)
        reg.wcol("effective_balance")[:] = eff
        genesis_active = eff >= preset.MAX_EFFECTIVE_BALANCE
        reg.wcol("activation_eligibility_epoch")[genesis_active] = \
            GENESIS_EPOCH
        reg.wcol("activation_epoch")[genesis_active] = GENESIS_EPOCH
    state.genesis_validators_root = type(state).FIELDS[
        "validators"].hash_tree_root(reg)
    return state


def is_valid_genesis_state(state, preset, spec) -> bool:
    """`is_valid_genesis_state` (`genesis.rs`)."""
    if int(state.genesis_time) < spec.min_genesis_time:
        return False
    from ..state_transition.helpers import is_active_at
    active = int(is_active_at(state.validators, GENESIS_EPOCH).sum())
    return active >= spec.min_genesis_active_validator_count
