"""Eth1 ingestion service — the polling loop of
``/root/reference/beacon_node/eth1/src/service.rs``: follow the eth1
chain head over JSON-RPC, fetch deposit-contract logs in bounded block
ranges, and feed the :class:`~..eth1.DepositCache` / ``BlockCache`` the
chain reads its eth1 vote and deposit proofs from.

The RPC seam is the same ``HttpJsonRpcEngine.rpc`` transport the engine
API uses (an eth1 node speaks plain JSON-RPC on the same endpoint);
tests drive the service against an in-process mock RPC server.

Polling model (service.rs `update` loop):

- `eth_blockNumber` → follow distance applied (the head minus
  ``eth1_follow_distance`` is the newest block considered stable);
- logs fetched with `eth_getLogs` over ``[next_fetch, stable]`` in
  chunks of ``MAX_LOG_RANGE`` blocks, decoded into DepositData and
  inserted in log-index order (gaps are an error: the deposit tree is
  append-only);
- block metadata (`eth_getBlockByNumber`) recorded into the BlockCache
  so `eth1_data_for_vote` has (root, count, hash) triples.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, List, Optional

from . import DepositCache, Eth1Block

MAX_LOG_RANGE = 1000

# keccak("DepositEvent(bytes,bytes,bytes,bytes,bytes)") — the deposit
# contract's single event topic (public constant).
DEPOSIT_EVENT_TOPIC = (
    "0x649bbc62d0e31342afea4e5cd82d4049e7e1ee912fc0889aa790803be39038c5")


@dataclass
class Eth1ServiceConfig:
    deposit_contract_address: str = "0x" + "00" * 20
    follow_distance: int = 8
    poll_interval_s: float = 1.0


def _decode_deposit_log(data_hex: str, T):
    """ABI-decode a DepositEvent's data blob into (DepositData, index).

    Layout: 5 dynamic byte fields (pubkey, withdrawal_credentials,
    amount, signature, index), each a 32-byte offset slot then
    length-prefixed data — the exact contract ABI the reference decodes
    (`eth1/src/deposit_log.rs`)."""
    raw = bytes.fromhex(data_hex[2:] if data_hex.startswith("0x")
                        else data_hex)

    def field(i: int) -> bytes:
        off = int.from_bytes(raw[32 * i:32 * i + 32], "big")
        ln = int.from_bytes(raw[off:off + 32], "big")
        return raw[off + 32:off + 32 + ln]

    pubkey = field(0)
    creds = field(1)
    amount = int.from_bytes(field(2), "little")
    signature = field(3)
    index = int.from_bytes(field(4), "little")
    data = T.DepositData(pubkey=pubkey, withdrawal_credentials=creds,
                         amount=amount, signature=signature)
    return data, index


class Eth1PollingService:
    """Drives an :class:`~..eth1.Eth1Service`'s caches from an RPC."""

    def __init__(self, eth1_service, rpc: Callable[[str, list], object],
                 T, config: Optional[Eth1ServiceConfig] = None):
        self.svc = eth1_service
        self.rpc = rpc
        self.T = T
        self.config = config or Eth1ServiceConfig()
        self.next_fetch_block = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.errors = 0

    # -- one polling round ---------------------------------------------------

    def update(self) -> int:
        """One `service.rs::update` round; returns logs ingested."""
        head = int(self.rpc("eth_blockNumber", []), 16)
        stable = head - self.config.follow_distance
        if stable < self.next_fetch_block:
            return 0
        ingested = 0
        while self.next_fetch_block <= stable:
            frm = self.next_fetch_block
            to = min(frm + MAX_LOG_RANGE - 1, stable)
            logs = self.rpc("eth_getLogs", [{
                "fromBlock": hex(frm), "toBlock": hex(to),
                "address": self.config.deposit_contract_address,
                "topics": [DEPOSIT_EVENT_TOPIC]}])
            # Decode the WHOLE chunk before inserting anything: a
            # mid-chunk failure after partial inserts would wedge the
            # append-only cache forever (the retried chunk re-presents
            # already-inserted indices).  Already-known indices are
            # skipped so a re-fetch after a crash is idempotent.
            decoded = [_decode_deposit_log(log["data"], self.T)
                       for log in logs]
            for data, index in decoded:
                if index < len(self.svc.deposits.logs):
                    continue
                self.svc.deposits.insert_log(index, data)
                ingested += 1
            self.next_fetch_block = to + 1
        # Record the stable block for eth1-data votes; the incrementally
        # maintained tree already holds the current root.
        blk = self.rpc("eth_getBlockByNumber", [hex(stable), False])
        if blk is not None:
            self.svc.blocks.insert(Eth1Block(
                hash=bytes.fromhex(blk["hash"][2:]),
                number=int(blk["number"], 16),
                timestamp=int(blk["timestamp"], 16),
                deposit_root=self.svc.deposits.tree.root(),
                deposit_count=len(self.svc.deposits.logs)))
        return ingested

    # -- service lifecycle ---------------------------------------------------

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.config.poll_interval_s):
                try:
                    self.update()
                except Exception:
                    self.errors += 1  # RPC flaps must not kill the loop

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
