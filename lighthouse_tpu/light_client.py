"""Light-client sync protocol: types, production, verification.

Counterpart of the reference's light-client surface
(``/root/reference/consensus/types/src/light_client_{bootstrap,update,
finality_update,optimistic_update}.rs`` and ``beacon_node/beacon_chain/src/
light_client_{finality,optimistic}_update_verification.rs``): bootstrap =
header + current sync committee + a Merkle branch into the state; updates
carry the attested/finalized headers, the next-sync-committee branch and
the sync aggregate that signed them.

Branches are computed from the state's container layout via
:func:`state_field_proof` — the per-field roots the incremental tree-hash
cache already maintains fold into a small tree whose siblings form the
proof (``merkle_proof.rs`` generalized-index idea over this build's
layout).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional

from .ops.merkle import ZERO_HASHES_BYTES


def _field_roots(state) -> List[bytes]:
    return [ftype.hash_tree_root(getattr(state, fname))
            for fname, ftype in type(state).FIELDS.items()]


def _tree_width(n: int) -> int:
    w = 1
    while w < n:
        w *= 2
    return w


def state_field_proof(state, field_name: str) -> tuple[List[bytes], int]:
    """(branch, field index) proving ``field_name``'s root against the
    state root."""
    names = list(type(state).FIELDS)
    idx = names.index(field_name)
    leaves = _field_roots(state)
    width = _tree_width(len(leaves))
    level = leaves + [ZERO_HASHES_BYTES[0]] * (width - len(leaves))
    branch: List[bytes] = []
    i = idx
    while len(level) > 1:
        branch.append(level[i ^ 1])
        level = [hashlib.sha256(level[j] + level[j + 1]).digest()
                 for j in range(0, len(level), 2)]
        i //= 2
    return branch, idx


def verify_field_proof(field_root: bytes, branch: List[bytes], index: int,
                       state_root: bytes) -> bool:
    node = field_root
    i = index
    for sib in branch:
        node = (hashlib.sha256(sib + node).digest() if i & 1
                else hashlib.sha256(node + sib).digest())
        i //= 2
    return node == state_root


@dataclass
class LightClientBootstrap:
    """`LightClientBootstrap` — served via RPC (`rpc/protocol.rs:178`)."""
    header: object                       # BeaconBlockHeader
    current_sync_committee: object
    current_sync_committee_branch: List[bytes]

    def verify(self, trusted_block_root: bytes, state, T) -> bool:
        if self.header.tree_hash_root() != trusted_block_root:
            return False
        names = list(type(state).FIELDS)
        idx = names.index("current_sync_committee")
        return verify_field_proof(
            self.current_sync_committee.tree_hash_root(),
            self.current_sync_committee_branch, idx,
            bytes(self.header.state_root))


@dataclass
class LightClientUpdate:
    """`LightClientUpdate` — sync-committee period advancement."""
    attested_header: object
    next_sync_committee: object
    next_sync_committee_branch: List[bytes]
    finalized_header: Optional[object]
    finality_branch: List[bytes]
    sync_aggregate: object
    signature_slot: int


@dataclass
class LightClientFinalityUpdate:
    """`LightClientFinalityUpdate` — gossip topic payload."""
    attested_header: object
    finalized_header: object
    finality_branch: List[bytes]
    sync_aggregate: object
    signature_slot: int


@dataclass
class LightClientOptimisticUpdate:
    attested_header: object
    sync_aggregate: object
    signature_slot: int


class LightClientServer:
    """Produces light-client artifacts from a chain
    (`beacon_chain/src/light_client_*` production paths)."""

    def __init__(self, chain):
        self.chain = chain

    def _header(self, state, block_root: Optional[bytes] = None):
        hdr = state.latest_block_header.copy()
        if bytes(hdr.state_root) == b"\x00" * 32:
            hdr.state_root = state.tree_hash_root()
        return hdr

    def bootstrap(self, block_root: Optional[bytes] = None
                  ) -> LightClientBootstrap:
        state = self.chain.head.state
        branch, _ = state_field_proof(state, "current_sync_committee")
        return LightClientBootstrap(
            header=self._header(state),
            current_sync_committee=state.current_sync_committee,
            current_sync_committee_branch=branch)

    def optimistic_update(self, sync_aggregate,
                          signature_slot: int) -> LightClientOptimisticUpdate:
        state = self.chain.head.state
        return LightClientOptimisticUpdate(
            attested_header=self._header(state),
            sync_aggregate=sync_aggregate, signature_slot=signature_slot)

    def finality_update(self, sync_aggregate,
                        signature_slot: int) -> LightClientFinalityUpdate:
        state = self.chain.head.state
        branch, _ = state_field_proof(state, "finalized_checkpoint")
        fin_root = bytes(state.finalized_checkpoint.root)
        fin_block = self.chain.store.get_block(fin_root)
        fin_header = (fin_block.message if fin_block is not None else None)
        return LightClientFinalityUpdate(
            attested_header=self._header(state),
            finalized_header=fin_header,
            finality_branch=branch,
            sync_aggregate=sync_aggregate, signature_slot=signature_slot)
