"""Light-client sync protocol: types, production, verification.

Counterpart of the reference's light-client surface
(``/root/reference/consensus/types/src/light_client_{bootstrap,update,
finality_update,optimistic_update}.rs`` and ``beacon_node/beacon_chain/src/
light_client_{finality,optimistic}_update_verification.rs``): bootstrap =
header + current sync committee + a Merkle branch into the state; updates
carry the attested/finalized headers, the next-sync-committee branch and
the sync aggregate that signed them.

Branches are computed from the state's container layout via
:func:`state_field_proof` — the per-field roots the incremental tree-hash
cache already maintains fold into a small tree whose siblings form the
proof (``merkle_proof.rs`` generalized-index idea over this build's
layout).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional

from .ops.merkle import ZERO_HASHES_BYTES


def _field_roots(state) -> List[bytes]:
    """Per-field roots in FIELDS order, from the incremental tree-hash
    cache's container-fold layer when the state carries one: a
    ``tree_hash_root()`` call refreshes the layer diff-only, so repeated
    proof requests against the same state stop re-hashing every field
    (the old path rebuilt the whole layer — a SyncCommittee rehash alone
    is ~1k hashes — per request)."""
    thc = state.__dict__.get("_thc") if hasattr(state, "__dict__") else None
    if thc is not None or hasattr(state, "tree_hash_root"):
        try:
            state.tree_hash_root()  # incremental; refreshes field_layer
            layer = state.__dict__["_thc"].field_layer
            if layer is not None:
                return list(layer)
        except (AttributeError, KeyError, TypeError):
            pass
    return [ftype.hash_tree_root(getattr(state, fname))
            for fname, ftype in type(state).FIELDS.items()]


def _tree_width(n: int) -> int:
    w = 1
    while w < n:
        w *= 2
    return w


def state_field_proof(state, field_name: str) -> tuple[List[bytes], int]:
    """(branch, field index) proving ``field_name``'s root against the
    state root."""
    names = list(type(state).FIELDS)
    idx = names.index(field_name)
    leaves = _field_roots(state)
    width = _tree_width(len(leaves))
    level = leaves + [ZERO_HASHES_BYTES[0]] * (width - len(leaves))
    branch: List[bytes] = []
    i = idx
    while len(level) > 1:
        branch.append(level[i ^ 1])
        level = [hashlib.sha256(level[j] + level[j + 1]).digest()
                 for j in range(0, len(level), 2)]
        i //= 2
    return branch, idx


def verify_field_proof(field_root: bytes, branch: List[bytes], index: int,
                       state_root: bytes) -> bool:
    node = field_root
    i = index
    for sib in branch:
        node = (hashlib.sha256(sib + node).digest() if i & 1
                else hashlib.sha256(node + sib).digest())
        i //= 2
    return node == state_root


@dataclass
class LightClientBootstrap:
    """`LightClientBootstrap` — served via RPC (`rpc/protocol.rs:178`)."""
    header: object                       # BeaconBlockHeader
    current_sync_committee: object
    current_sync_committee_branch: List[bytes]

    def verify(self, trusted_block_root: bytes, state, T) -> bool:
        if self.header.tree_hash_root() != trusted_block_root:
            return False
        names = list(type(state).FIELDS)
        idx = names.index("current_sync_committee")
        return verify_field_proof(
            self.current_sync_committee.tree_hash_root(),
            self.current_sync_committee_branch, idx,
            bytes(self.header.state_root))


@dataclass
class LightClientUpdate:
    """`LightClientUpdate` — sync-committee period advancement."""
    attested_header: object
    next_sync_committee: object
    next_sync_committee_branch: List[bytes]
    finalized_header: Optional[object]
    finality_branch: List[bytes]
    sync_aggregate: object
    signature_slot: int


@dataclass
class LightClientFinalityUpdate:
    """`LightClientFinalityUpdate` — gossip topic payload.  Carries the
    finalized checkpoint EPOCH explicitly: the checkpoint's epoch can
    exceed finalized_header.slot // SPE when the boundary slot is empty,
    and the client needs it to reconstruct the proven Checkpoint."""
    attested_header: object
    finalized_header: object
    finality_branch: List[bytes]
    sync_aggregate: object
    signature_slot: int
    finalized_checkpoint_epoch: int = 0


@dataclass
class LightClientOptimisticUpdate:
    attested_header: object
    sync_aggregate: object
    signature_slot: int


def _verify_aggregate_with_committee(committee, genesis_validators_root,
                                     preset, spec, attested_header,
                                     sync_aggregate, signature_slot: int,
                                     min_participants: int) -> bool:
    """Shared sync-aggregate check: the committee signed the attested
    header's root under the SYNC_COMMITTEE domain of signature_slot−1's
    fork (used by both the full-node gossip gate and the light-client
    store)."""
    import numpy as np

    from .crypto.bls import PublicKey, Signature, get_backend
    from .state_transition.helpers import (
        compute_domain, compute_signing_root)
    from .types.chain_spec import Domain

    try:
        bits = np.asarray(sync_aggregate.sync_committee_bits, dtype=bool)
        if int(bits.sum()) < min_participants:
            return False
        sig = Signature.deserialize(
            sync_aggregate.sync_committee_signature)
        prev = max(int(signature_slot), 1) - 1
        epoch = prev // preset.SLOTS_PER_EPOCH
        fork = spec.fork_name_at_epoch(epoch)
        domain = compute_domain(Domain.SYNC_COMMITTEE,
                                spec.fork_version(fork),
                                bytes(genesis_validators_root))
        keys = [PublicKey.deserialize(committee.pubkeys[i])
                for i in np.flatnonzero(bits)]
        msg = compute_signing_root(attested_header.tree_hash_root(),
                                   domain)
        return get_backend().verify(sig, keys, msg)
    except Exception:
        return False


def verify_update_sync_aggregate(chain, attested_header, sync_aggregate,
                                 signature_slot: int,
                                 min_participants: int = 1) -> bool:
    """Full-node verification of a gossiped LC update
    (`light_client_{finality,optimistic}_update_verification.rs`): the
    signing committee is chosen by the signature slot's SYNC-COMMITTEE
    PERIOD relative to the head's — current committee for the same
    period, next committee for head period + 1 (a lagging node must not
    reject updates signed just across the boundary)."""
    state = chain.head.state
    preset, spec = chain.preset, chain.spec
    epochs_per_period = preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    slots_per_period = epochs_per_period * preset.SLOTS_PER_EPOCH
    head_period = int(state.slot) // slots_per_period
    sig_period = max(int(signature_slot), 1) // slots_per_period
    if sig_period == head_period:
        committee = state.current_sync_committee
    elif sig_period == head_period + 1:
        committee = state.next_sync_committee
    else:
        return False
    return _verify_aggregate_with_committee(
        committee, state.genesis_validators_root, preset, spec,
        attested_header, sync_aggregate, signature_slot,
        min_participants)


class LightClientServer:
    """Produces light-client artifacts from a chain
    (`beacon_chain/src/light_client_*` production paths)."""

    def __init__(self, chain):
        self.chain = chain

    def _branch(self, state, field_name: str) -> List[bytes]:
        """Field branch via the chain's device proof engine (one batched
        gather over the resident field-root tree) with
        :func:`state_field_proof`'s host walk as the differential oracle
        — knob-off or any device failure falls back byte-identically."""
        from .common.knobs import knob_bool
        if self.chain is not None and \
                knob_bool("LIGHTHOUSE_TPU_PROOF_DEVICE"):
            try:
                branch, _ = self.chain.proof_server.field_branch(
                    state, field_name)
                return branch
            except Exception:
                pass
        branch, _ = state_field_proof(state, field_name)
        return branch

    def _header(self, state, block_root: Optional[bytes] = None):
        hdr = state.latest_block_header.copy()
        if bytes(hdr.state_root) == b"\x00" * 32:
            hdr.state_root = state.tree_hash_root()
        return hdr

    def _block_to_header(self, block_msg):
        """BeaconBlock -> BeaconBlockHeader (same hash_tree_root)."""
        T = self.chain.T
        return T.BeaconBlockHeader(
            slot=block_msg.slot, proposer_index=block_msg.proposer_index,
            parent_root=block_msg.parent_root,
            state_root=block_msg.state_root,
            body_root=block_msg.body.tree_hash_root())

    def bootstrap(self, block_root: Optional[bytes] = None
                  ) -> LightClientBootstrap:
        state = self.chain.head.state
        branch = self._branch(state, "current_sync_committee")
        return LightClientBootstrap(
            header=self._header(state),
            current_sync_committee=state.current_sync_committee,
            current_sync_committee_branch=branch)

    def optimistic_update(self, sync_aggregate,
                          signature_slot: int) -> LightClientOptimisticUpdate:
        state = self.chain.head.state
        return LightClientOptimisticUpdate(
            attested_header=self._header(state),
            sync_aggregate=sync_aggregate, signature_slot=signature_slot)

    def finality_update(self, sync_aggregate,
                        signature_slot: int) -> LightClientFinalityUpdate:
        state = self.chain.head.state
        branch = self._branch(state, "finalized_checkpoint")
        fin_root = bytes(state.finalized_checkpoint.root)
        fin_block = self.chain.store.get_block(fin_root)
        fin_header = (self._block_to_header(fin_block.message)
                      if fin_block is not None else None)
        return LightClientFinalityUpdate(
            attested_header=self._header(state),
            finalized_header=fin_header,
            finality_branch=branch,
            sync_aggregate=sync_aggregate, signature_slot=signature_slot,
            finalized_checkpoint_epoch=int(state.finalized_checkpoint.epoch))

    def update(self, sync_aggregate,
               signature_slot: int) -> LightClientUpdate:
        """Period-advancing `LightClientUpdate` built from the LIVE HEAD
        state.  Only sound when ``sync_aggregate`` actually signed the
        current head header (e.g. produced in the same slot); for
        serving, use the update :meth:`updates_for_block` cached at
        import time instead — pairing a cached aggregate with a later
        head header yields a signature no spec client accepts."""
        state = self.chain.head.state
        next_branch = self._branch(state, "next_sync_committee")
        fin_branch = self._branch(state, "finalized_checkpoint")
        fin_root = bytes(state.finalized_checkpoint.root)
        fin_block = self.chain.store.get_block(fin_root)
        return LightClientUpdate(
            attested_header=self._header(state),
            next_sync_committee=state.next_sync_committee,
            next_sync_committee_branch=next_branch,
            finalized_header=(self._block_to_header(fin_block.message)
                              if fin_block is not None else None),
            finality_branch=fin_branch,
            sync_aggregate=sync_aggregate, signature_slot=signature_slot)

    def updates_for_block(self, signed_block):
        """Artifacts triggered by an imported block carrying a live sync
        aggregate (`beacon_chain/src/light_client_server_cache.rs` role):
        the aggregate attests to the PARENT header, so every artifact —
        including the full period-advancing `LightClientUpdate` — is
        built from the parent header/state the committee actually
        signed.  (Rebuilding the period update from the live head at
        serve time, as the `/updates` route once did, paired the cached
        aggregate with a header it never signed — cryptographically
        inconsistent whenever the head had advanced, i.e. almost
        always.)  Returns (optimistic_update | None,
        finality_update | None, period_update | None)."""
        import numpy as np

        agg = getattr(signed_block.message.body, "sync_aggregate", None)
        if agg is None:
            return None, None, None
        bits = np.asarray(agg.sync_committee_bits, dtype=bool)
        if not bits.any():
            return None, None, None
        parent = self.chain.store.get_block(
            bytes(signed_block.message.parent_root))
        if parent is None:
            return None, None, None
        parent_state = self.chain.state_at_block_root(
            bytes(signed_block.message.parent_root))
        hdr = parent_state.latest_block_header.copy()
        hdr.state_root = bytes(parent.message.state_root)
        slot = int(signed_block.message.slot)
        opt = LightClientOptimisticUpdate(
            attested_header=hdr, sync_aggregate=agg, signature_slot=slot)
        fin_branch = self._branch(parent_state, "finalized_checkpoint")
        fin_root = bytes(parent_state.finalized_checkpoint.root)
        fin_block = self.chain.store.get_block(fin_root)
        fin_header = (self._block_to_header(fin_block.message)
                      if fin_block is not None else None)
        fin = None
        if fin_header is not None:
            fin = LightClientFinalityUpdate(
                attested_header=hdr,
                finalized_header=fin_header,
                finality_branch=fin_branch,
                sync_aggregate=agg, signature_slot=slot,
                finalized_checkpoint_epoch=int(
                    parent_state.finalized_checkpoint.epoch))
        next_branch = self._branch(parent_state, "next_sync_committee")
        period = LightClientUpdate(
            attested_header=hdr,
            next_sync_committee=parent_state.next_sync_committee,
            next_sync_committee_branch=next_branch,
            finalized_header=fin_header,
            finality_branch=fin_branch,
            sync_aggregate=agg, signature_slot=slot)
        return opt, fin, period


class LightClientStore:
    """The CLIENT side — a light client following the chain from a
    bootstrap using sync-committee-signed updates
    (`consensus/types/src/light_client_update.rs` verification rules +
    the spec's `process_light_client_update`, simplified to the
    single-period flow this framework's tests drive end-to-end)."""

    MIN_SYNC_PARTICIPANTS = 1

    def __init__(self, bootstrap: LightClientBootstrap,
                 trusted_block_root: bytes, state, T, preset, spec):
        if not bootstrap.verify(trusted_block_root, state, T):
            raise ValueError("bootstrap proof invalid for trusted root")
        self.finalized_header = bootstrap.header
        self.optimistic_header = bootstrap.header
        self.current_sync_committee = bootstrap.current_sync_committee
        self.T = T
        self.preset = preset
        self.spec = spec
        self._genesis_validators_root = bytes(state.genesis_validators_root)
        # precomputed proof index; holding the state itself would pin
        # ~100 MB at registry scale for one FIELDS lookup
        self._finalized_cp_index = list(type(state).FIELDS).index(
            "finalized_checkpoint")

    def _verify_sync_aggregate(self, attested_header, sync_aggregate,
                               signature_slot: int) -> bool:
        """The committee signed the attested header's root at
        signature_slot − 1's epoch domain (shared helper with the
        full-node gossip gate)."""
        return _verify_aggregate_with_committee(
            self.current_sync_committee, self._genesis_validators_root,
            self.preset, self.spec, attested_header, sync_aggregate,
            signature_slot, self.MIN_SYNC_PARTICIPANTS)

    def process_optimistic_update(
            self, update: LightClientOptimisticUpdate) -> bool:
        if int(update.attested_header.slot) <= \
                int(self.optimistic_header.slot):
            return False  # not newer
        if not self._verify_sync_aggregate(
                update.attested_header, update.sync_aggregate,
                update.signature_slot):
            return False
        self.optimistic_header = update.attested_header
        return True

    def process_finality_update(
            self, update: LightClientFinalityUpdate) -> bool:
        if update.finalized_header is None:
            return False
        if not self._verify_sync_aggregate(
                update.attested_header, update.sync_aggregate,
                update.signature_slot):
            return False
        # The finalized checkpoint proof anchors the finalized header to
        # the attested header's state.
        idx = self._finalized_cp_index
        fin_root = update.finalized_header.tree_hash_root()
        # finality_branch proves the Checkpoint container, whose root
        # commits to (epoch, root=finalized block root).
        cp = self.T.Checkpoint(
            epoch=int(update.finalized_checkpoint_epoch), root=fin_root)
        if not verify_field_proof(
                cp.tree_hash_root(), update.finality_branch, idx,
                bytes(update.attested_header.state_root)):
            return False
        if int(update.finalized_header.slot) > \
                int(self.finalized_header.slot):
            self.finalized_header = update.finalized_header
        if int(update.attested_header.slot) > \
                int(self.optimistic_header.slot):
            self.optimistic_header = update.attested_header
        return True
