"""graftlint core — the AST-walking lint framework.

The Rust reference gets lock discipline, exhaustive knob handling and
API-misuse detection from rustc + clippy for free; this port encodes
the same *repo-specific* invariants as AST checkers so review stops
re-learning them (the PR-7/PR-9 review logs are the motivation: six
passes each, every worst bug an instance of a statically checkable
shape).

Architecture
------------

- :class:`Finding` — one violation: ``path:line``, a message, a fix
  hint, and a **stable waiver key** (``checker:path:detail`` — no line
  numbers, so unrelated edits don't churn the baseline).
- :class:`Checker` — subclass per invariant, registered with
  :func:`register`.  Three phases: ``collect`` runs over EVERY file
  first (cross-file facts: which module defines which stage dict),
  then ``check`` per file, then ``finalize`` for whole-tree
  invariants.
- **Baseline** (``analysis/baseline.json``) — findings may be waived,
  but every waiver MUST carry a written justification; an empty
  justification is itself a lint failure.  Stale waivers (matching
  nothing) are reported so the baseline only ever shrinks.

Run via ``scripts/lint.py`` (exit 1 on any unwaived finding) or
in-process through :func:`run` — the quick test tier asserts zero
unwaived findings on the real tree, which is what makes every future
PR cheaper to review.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One invariant violation."""
    checker: str
    path: str          # repo-relative, '/'-separated
    line: int
    message: str
    hint: str = ""
    detail: str = ""   # stable key component; defaults to the message

    @property
    def key(self) -> str:
        """The baseline waiver key — deliberately line-free."""
        return f"{self.checker}:{self.path}:{self.detail or self.message}"

    def render(self) -> str:
        out = f"{self.path}:{self.line}: [{self.checker}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


# ---------------------------------------------------------------------------
# Checker registry
# ---------------------------------------------------------------------------


@dataclass
class Context:
    """Per-run shared state (cross-file facts land in ``shared``)."""
    root: str
    files: Sequence[str] = ()
    shared: dict = field(default_factory=dict)


class Checker:
    """Base class.  Subclass, set ``name``/``doc``, register."""

    name: str = ""
    doc: str = ""

    def collect(self, ctx: Context, path: str, tree: ast.AST,
                lines: Sequence[str]) -> None:
        """First pass over every file — gather cross-file facts."""

    def check(self, ctx: Context, path: str, tree: ast.AST,
              lines: Sequence[str]) -> Iterable[Finding]:
        """Second pass — per-file findings."""
        return ()

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        """After all files — whole-tree findings."""
        return ()


CHECKERS: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    assert cls.name and cls.name not in CHECKERS, cls
    CHECKERS[cls.name] = cls
    return cls


# ---------------------------------------------------------------------------
# File discovery + run loop
# ---------------------------------------------------------------------------

# What graftlint covers: the package, the scripts, and the bench
# driver.  tests/ is deliberately excluded — fixtures there CONTAIN
# the forbidden shapes on purpose, and the env save/restore idiom
# (read a knob to restore it in teardown) is legitimate test plumbing.
DEFAULT_TARGETS: Tuple[str, ...] = ("lighthouse_tpu", "scripts", "bench.py")


def lint_files(root: str,
               targets: Sequence[str] = DEFAULT_TARGETS) -> List[str]:
    """Repo-relative ``.py`` paths under ``targets``, sorted."""
    out: List[str] = []
    for target in targets:
        full = os.path.join(root, target)
        if os.path.isfile(full):
            if target.endswith(".py"):
                out.append(target.replace(os.sep, "/"))
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    out.append(rel.replace(os.sep, "/"))
    return sorted(set(out))


def _parse(root: str, rel: str):
    with open(os.path.join(root, rel), "r", encoding="utf-8") as fh:
        source = fh.read()
    return ast.parse(source, filename=rel), source.splitlines()


def run(root: str, files: Optional[Sequence[str]] = None,
        checker_names: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run ``checker_names`` (default: all registered) over ``files``
    (default: the standard lint set).  Returns findings sorted by
    location.  ``collect`` always runs over the FULL lint set so
    cross-file invariants hold even under ``--changed``."""
    from . import checkers as _  # noqa: F401 — registration side effect

    all_files = lint_files(root)
    check_files = list(files) if files is not None else all_files
    names = list(checker_names) if checker_names is not None \
        else sorted(CHECKERS)
    active = [CHECKERS[n]() for n in names]

    findings: List[Finding] = []
    parsed: Dict[str, tuple] = {}
    for rel in all_files:
        try:
            parsed[rel] = _parse(root, rel)
        except SyntaxError as exc:
            findings.append(Finding(
                "parse", rel, int(exc.lineno or 0),
                f"file does not parse: {exc.msg}",
                detail="syntax-error"))

    ctx = Context(root=root, files=all_files)
    for rel in all_files:
        if rel in parsed:
            tree, lines = parsed[rel]
            for c in active:
                c.collect(ctx, rel, tree, lines)
    for rel in check_files:
        if rel in parsed:
            tree, lines = parsed[rel]
            for c in active:
                findings.extend(c.check(ctx, rel, tree, lines))
    for c in active:
        findings.extend(c.finalize(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.checker, f.message))
    return findings


# ---------------------------------------------------------------------------
# Baseline (waivers)
# ---------------------------------------------------------------------------

BASELINE_PATH = os.path.join("lighthouse_tpu", "analysis", "baseline.json")


class BaselineError(ValueError):
    pass


def load_baseline(root: str) -> Dict[str, str]:
    """``{waiver key: justification}``.  Missing file → empty.  A
    waiver without a non-empty justification string is an error — the
    baseline is a ledger of *argued* exceptions, not a mute list."""
    path = os.path.join(root, BASELINE_PATH)
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    waivers = data.get("waivers")
    if not isinstance(waivers, list):
        raise BaselineError(f"{BASELINE_PATH}: expected a 'waivers' list")
    out: Dict[str, str] = {}
    for i, w in enumerate(waivers):
        key = w.get("key") if isinstance(w, dict) else None
        just = w.get("justification") if isinstance(w, dict) else None
        if not key or not isinstance(key, str):
            raise BaselineError(
                f"{BASELINE_PATH}: waiver #{i} has no 'key'")
        if not just or not isinstance(just, str) or not just.strip():
            raise BaselineError(
                f"{BASELINE_PATH}: waiver {key!r} has no written "
                f"justification — every waiver must argue why the "
                f"finding is acceptable")
        if key in out:
            raise BaselineError(
                f"{BASELINE_PATH}: duplicate waiver {key!r}")
        out[key] = just
    return out


def write_baseline(root: str, findings: Sequence[Finding],
                   keep: Optional[Dict[str, str]] = None) -> int:
    """Regenerate the baseline from ``findings``, preserving existing
    justifications; new entries get an EMPTY justification that
    :func:`load_baseline` will REJECT until a human writes the
    argument.  Returns the number of entries written."""
    keep = keep or {}
    entries = []
    for f in sorted({f.key: f for f in findings}.values(),
                    key=lambda f: f.key):
        entries.append({
            "key": f.key,
            "justification": keep.get(
                f.key, ""),  # empty → load_baseline refuses
            "message": f.message,
        })
    path = os.path.join(root, BASELINE_PATH)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({
            "_doc": "graftlint waiver baseline. Every entry MUST carry "
                    "a non-empty justification; scripts/lint.py "
                    "--baseline regenerates keys but never invents "
                    "arguments.",
            "waivers": entries,
        }, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return len(entries)


def apply_baseline(findings: Sequence[Finding], baseline: Dict[str, str]):
    """Split into ``(unwaived, waived, stale_keys)``."""
    keys = {f.key for f in findings}
    unwaived = [f for f in findings if f.key not in baseline]
    waived = [f for f in findings if f.key in baseline]
    stale = sorted(k for k in baseline if k not in keys)
    return unwaived, waived, stale


# ---------------------------------------------------------------------------
# Small shared AST helpers (used by several checkers)
# ---------------------------------------------------------------------------


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
