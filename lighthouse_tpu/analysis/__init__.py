"""graftlint — repo-native static analysis.

The AST lint pass that encodes the invariants this repo kept
re-learning in review: knob reads through the typed registry, lock
discipline on annotated state, JAX x64/shard_map/import-time hygiene,
framed-column store writes, and the one-stage-data-surface rule.  See
:mod:`.core` for the framework and ``scripts/lint.py`` for the CLI.
"""

from .core import (  # noqa: F401
    BASELINE_PATH,
    BaselineError,
    CHECKERS,
    Checker,
    Context,
    Finding,
    apply_baseline,
    lint_files,
    load_baseline,
    run,
    write_baseline,
)
