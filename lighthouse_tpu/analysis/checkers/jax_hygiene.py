"""jax-hygiene — the three JAX idioms this repo has re-learned in review.

1. **x64 is scoped, never global.**  ``jax.config.update("jax_enable_
   x64", ...)`` flips dtype semantics for EVERY jitted program in the
   process — the crypto kernels are traced under 32-bit semantics and
   silently produce wrong limbs afterwards.  The proven spelling is
   the scoped context manager ``with jax.experimental.enable_x64():``
   (see ``fork_choice/device_proto_array.py`` throughout).

2. **One shard_map spelling.**  This container's jax (0.4.37) only has
   ``jax.experimental.shard_map.shard_map`` with ``check_rep`` — the
   top-level ``jax.shard_map`` and the ``check_vma`` kwarg exist only
   in newer jax.  The proven portable spelling is the experimental
   import + an explicit ``check_rep=False`` (``parallel/bls_shard.py``
   ``sharded_g1_sum``, validated on single-chip AND the multichip
   dryrun).

3. **No ``jnp.`` computation at import time.**  A module-level
   ``jnp.arange(...)`` materializes a device buffer (and may initialize
   the backend) the moment the module imports — import order starts
   deciding device state, and CPU-only test processes pay for buffers
   they never use.  Module constants stay numpy; convert at trace time.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Checker, Context, Finding, dotted, register, str_const


def _root(chain: str) -> str:
    return chain.split(".", 1)[0]


@register
class JaxHygieneChecker(Checker):
    name = "jax-hygiene"
    doc = ("enable_x64 only as a scoped context manager; shard_map "
           "only via jax.experimental.shard_map with check_rep=False; "
           "no jnp. computation at module import time")

    def check(self, ctx: Context, path: str, tree: ast.AST,
              lines) -> Iterable[Finding]:
        out: List[Finding] = []
        self._scan(tree, path, out, depth=0, func="module")
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and \
                    node.module in ("jax", "jax.sharding") and \
                    any(a.name == "shard_map" for a in node.names):
                out.append(Finding(
                    self.name, path, node.lineno,
                    f"shard_map imported from {node.module!r} — only "
                    f"jax.experimental.shard_map exists across the "
                    f"jax versions this repo runs on",
                    hint="from jax.experimental.shard_map import "
                         "shard_map",
                    detail="shard-map-import"))
        return out

    def _scan(self, node: ast.AST, path: str, out: List[Finding],
              depth: int, func: str) -> None:
        """depth counts enclosing function bodies (0 = import time)."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                self._scan(d, path, out, depth, func)
            for default in node.args.defaults + \
                    [d for d in node.args.kw_defaults if d is not None]:
                self._scan(default, path, out, depth, func)
            for child in node.body:
                self._scan(child, path, out, depth + 1, node.name)
            return
        if isinstance(node, ast.Lambda):
            self._scan(node.body, path, out, depth + 1, func)
            return
        if isinstance(node, ast.Call):
            self._call(node, path, out, depth, func)
        for child in ast.iter_child_nodes(node):
            self._scan(child, path, out, depth, func)

    def _call(self, node: ast.Call, path: str, out: List[Finding],
              depth: int, func: str) -> None:
        chain = dotted(node.func) or ""

        if chain.endswith("config.update") and node.args:
            key = str_const(node.args[0]) or ""
            if "enable_x64" in key:
                out.append(Finding(
                    self.name, path, node.lineno,
                    "global jax_enable_x64 via config.update — flips "
                    "dtype semantics for every jitted program in the "
                    "process (the crypto kernels trace under 32-bit "
                    "semantics)",
                    hint="use the scoped form: "
                         "'with jax.experimental.enable_x64():'",
                    detail=f"enable-x64-config:{func}"))

        if chain == "jax.shard_map" or \
                (chain.endswith(".shard_map")
                 and _root(chain) == "jax"
                 and "experimental" not in chain):
            out.append(Finding(
                self.name, path, node.lineno,
                f"{chain}(...) — the top-level shard_map only exists "
                f"in newer jax",
                hint="from jax.experimental.shard_map import "
                     "shard_map",
                detail=f"shard-map-spelling:{func}"))
        elif chain == "shard_map" or chain.endswith(".shard_map"):
            # elif: a wrong-spelling call is ONE defect — reporting
            # the missing check_rep too would mint a second waiver key
            # that goes stale the moment the import is fixed.
            kw = {k.arg: k.value for k in node.keywords}
            ok = isinstance(kw.get("check_rep"), ast.Constant) and \
                kw["check_rep"].value is False
            if not ok:
                out.append(Finding(
                    self.name, path, node.lineno,
                    "shard_map call without check_rep=False — the one "
                    "spelling proven on this container's jax 0.4.37 "
                    "AND the multichip dryrun (check_vma / implicit "
                    "rep-checking are version-specific)",
                    hint="pass check_rep=False explicitly (mirror "
                         "parallel/bls_shard.sharded_g1_sum)",
                    detail=f"shard-map-check-rep:{func}"))

        if depth == 0 and (_root(chain) == "jnp"
                           or chain.startswith("jax.numpy.")):
            out.append(Finding(
                self.name, path, node.lineno,
                f"{chain}(...) at module import time — materializes "
                f"device buffers / initializes the backend on import",
                hint="keep module constants numpy and convert at "
                     "trace time, or build lazily inside the function",
                detail=f"module-jnp:{chain}"))
