"""mesh-residency — persistent device state placed through the one mesh.

PR 20 made :mod:`lighthouse_tpu.parallel.mesh` the single residency
layer: every long-lived device column is registered there
(``register_column``) and placed/refreshed/pulled through the
``mesh_put`` / ``mesh_place`` / ``mesh_gather`` seams, which pin the
column's PartitionSpec on the process mesh and settle wire + per-shard
bytes into the device ledger.  A raw ``jax.device_put`` inside a
persistent-residency module re-creates exactly the drift this layer
removed: an array living outside the registry, invisible to the
per-shard ledger, replicated when its family says sharded.

Two lexical rules:

1. ``jax.device_put(...)`` (any ``*.device_put`` spelling) inside the
   PERSISTENT-RESIDENCY modules (:data:`PERSISTENT_MODULES`) — the five
   subsystems whose arrays outlive a dispatch (resident tree, registry
   mirror, packed cache, fork-choice mirrors, slasher planes).  Staging
   pipelines (``parallel/pipeline.py``) and per-dispatch scratch
   elsewhere stay out of scope: their transfers are transient and
   ledger-annotated under the device-accounting checker.
2. ``Mesh(...)`` construction anywhere in ``lighthouse_tpu/`` outside
   ``parallel/mesh.py`` — ad-hoc meshes fork the axis namespace; the
   process mesh (``get_mesh``/``make_mesh``) is the one spelling.

Findings are baseline-waivable with justification, like every checker.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Checker, Context, Finding, dotted, register

PACKAGE = "lighthouse_tpu/"
MESH_MODULE = "lighthouse_tpu/parallel/mesh.py"

# The five subsystems whose device arrays persist across dispatches —
# their placements must route through parallel/mesh.
PERSISTENT_MODULES = frozenset({
    "lighthouse_tpu/ops/device_tree.py",
    "lighthouse_tpu/types/device_state.py",
    "lighthouse_tpu/types/validators.py",
    "lighthouse_tpu/fork_choice/device_proto_array.py",
    "lighthouse_tpu/slasher/device_spans.py",
})


@register
class MeshResidencyChecker(Checker):
    name = "mesh-residency"
    doc = ("raw jax.device_put of long-lived state outside parallel/mesh, "
           "or an ad-hoc jax.sharding.Mesh outside parallel/mesh.py")

    def check(self, ctx: Context, path: str, tree: ast.AST,
              lines) -> Iterable[Finding]:
        if not path.startswith(PACKAGE) or path == MESH_MODULE:
            return []
        out: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted(node.func) or ""
            if path in PERSISTENT_MODULES and (
                    chain == "jax.device_put"
                    or chain.endswith(".device_put")):
                out.append(Finding(
                    self.name, path, node.lineno,
                    "raw device_put in a persistent-residency module — "
                    "the array bypasses the mesh column registry and "
                    "per-shard ledger accounting",
                    hint="place it via parallel.mesh.mesh_put/mesh_place "
                         "under a registered column family",
                    detail="raw-device-put"))
            elif chain == "Mesh" or chain.endswith(".Mesh"):
                out.append(Finding(
                    self.name, path, node.lineno,
                    "ad-hoc Mesh construction outside parallel/mesh.py "
                    "— forks the process mesh / axis namespace",
                    hint="use parallel.mesh.get_mesh() (knob-sized) or "
                         "make_mesh(devices) from parallel/mesh.py",
                    detail="adhoc-mesh"))
        return out
