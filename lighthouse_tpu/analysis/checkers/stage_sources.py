"""stage-source — ``LAST_*`` stage dicts are read through tracing only.

PR 9 made ``common/tracing.py`` the ONE stage-data surface: bench rows
and trace children both read the legacy per-module ``LAST_*`` stage
dicts via ``tracing.stage_split(name)``, so the two never drift (the
pre-PR-9 failure: a bench row read a module dict directly, a later
refactor renamed a key, traces kept the old name, and the bench's
"stage split" silently stopped matching the trace's).  Two invariants:

1. **No direct foreign reads.**  Importing a ``LAST_*`` name from
   another module, or reading ``module.LAST_*``, outside the defining
   module and ``common/tracing.py``, is a finding — read
   ``tracing.stage_split("<source>")``.

2. **Every stage dict is registered.**  A module-level ``LAST_* = {}``
   dict must be reachable through the adapter: either wired into
   tracing's ``_STAGE_SOURCES`` table or self-registered via
   ``tracing.register_stage_source(...)`` at module import (the
   ``store/hot_cold.py`` idiom).  An unregistered stage dict is
   invisible to traces and resurrects the direct-read temptation.

The defining module itself may mutate its dict freely (bare-name
access) — ownership stays local; only the READ surface is unified.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set

from ..core import Checker, Context, Finding, register

LAST_RE = re.compile(r"LAST_[A-Z0-9_]+")
TRACING_MODULE = "lighthouse_tpu/common/tracing.py"


def _is_dict_value(node: ast.AST) -> bool:
    """A stage-dict definition is a dict LITERAL (or dict()/
    OrderedDict() call) — not any LAST_-named constant (regexes,
    tuples)."""
    if isinstance(node, ast.Dict):
        return True
    return isinstance(node, ast.Call) and \
        isinstance(node.func, ast.Name) and \
        node.func.id in ("dict", "OrderedDict")


@register
class StageSourceChecker(Checker):
    name = "stage-source"
    doc = ("LAST_* stage dicts are read only via tracing.stage_split "
           "and must be registered as stage sources")

    def collect(self, ctx: Context, path: str, tree: ast.AST,
                lines) -> None:
        shared = ctx.shared.setdefault("stage", {
            "defs": {},             # name -> (path, line)
            "self_registered": set(),  # LAST_* names referenced inside
                                       # a register_stage_source call
            "tracing_names": set()
        })
        for node in tree.body if isinstance(tree, ast.Module) else []:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and LAST_RE.fullmatch(t.id) \
                        and _is_dict_value(node.value):
                    shared["defs"].setdefault(t.id, (path, node.lineno))
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                fn = node.func
                attr = fn.attr if isinstance(fn, ast.Attribute) else \
                    (fn.id if isinstance(fn, ast.Name) else "")
                if attr == "register_stage_source":
                    # Per-DICT exemption: only the LAST_* names the
                    # call's getter actually references count as
                    # registered (a file-granular exemption would hide
                    # a second, unregistered dict in the same module).
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Name) and \
                                LAST_RE.fullmatch(sub.id):
                            shared["self_registered"].add(sub.id)
        if path == TRACING_MODULE:
            for node in ast.walk(tree):
                if isinstance(node, ast.Name) and \
                        LAST_RE.fullmatch(node.id):
                    shared["tracing_names"].add(node.id)
                if isinstance(node, ast.ImportFrom):
                    for a in node.names:
                        if LAST_RE.fullmatch(a.name):
                            shared["tracing_names"].add(a.name)

    def check(self, ctx: Context, path: str, tree: ast.AST,
              lines) -> Iterable[Finding]:
        if path == TRACING_MODULE:
            return []
        out: List[Finding] = []
        own: Set[str] = {
            t.id
            for node in (tree.body if isinstance(tree, ast.Module)
                         else [])
            if isinstance(node, (ast.Assign, ast.AnnAssign))
            for t in (node.targets if isinstance(node, ast.Assign)
                      else [node.target])
            if isinstance(t, ast.Name) and LAST_RE.fullmatch(t.id)
        }
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if LAST_RE.fullmatch(a.name):
                        out.append(Finding(
                            self.name, path, node.lineno,
                            f"direct import of stage dict {a.name} "
                            f"from {node.module!r} — stage data is "
                            f"read through the tracing adapter",
                            hint="use tracing.stage_split("
                                 "'<source name>') — one read surface "
                                 "for bench rows and trace children",
                            detail=f"import:{a.name}"))
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load) and \
                    LAST_RE.fullmatch(node.attr) and \
                    node.attr not in own:
                out.append(Finding(
                    self.name, path, node.lineno,
                    f"direct module-attribute read of stage dict "
                    f".{node.attr} — stage data is read through the "
                    f"tracing adapter",
                    hint="use tracing.stage_split('<source name>')",
                    detail=f"attr:{node.attr}"))
        return out

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        shared = ctx.shared.get("stage", {})
        defs: Dict[str, tuple] = shared.get("defs", {})
        self_registered = shared.get("self_registered", set())
        tracing_names = shared.get("tracing_names", set())
        out: List[Finding] = []
        for name, (path, line) in sorted(defs.items()):
            if name in tracing_names or name in self_registered:
                continue
            out.append(Finding(
                self.name, path, line,
                f"stage dict {name} is not registered as a tracing "
                f"stage source — invisible to slot traces and to "
                f"stage_split readers",
                hint="tracing.register_stage_source('<name>', lambda: "
                     f"{name}) at module import, or wire a getter "
                     "into tracing._STAGE_SOURCES",
                detail=f"unregistered:{name}"))
        return out
