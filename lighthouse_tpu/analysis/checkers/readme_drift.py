"""readme-drift — the README knob table matches the registry.

The "Static analysis & knobs" README section carries a table of every
``LIGHTHOUSE_TPU_*`` knob, generated from ``common/knobs.py``'s
registry between ``<!-- knobs:begin -->`` / ``<!-- knobs:end -->``
markers.  Docs that drift from the registry are worse than no docs —
this checker fails the lint until ``scripts/lint.py --fix-readme``
re-renders the committed section.
"""

from __future__ import annotations

import os
import re
from typing import Iterable, List

from ..core import Checker, Context, Finding, register

BEGIN = "<!-- knobs:begin -->"
END = "<!-- knobs:end -->"
SECTION_RE = re.compile(re.escape(BEGIN) + r"\n(.*?)" + re.escape(END),
                        re.S)


def committed_table(readme_text: str):
    m = SECTION_RE.search(readme_text)
    return m.group(1) if m else None


def replace_table(readme_text: str, table: str) -> str:
    # lambda replacement: the table is literal text, not a re template
    # (a backslash in a knob doc must not be parsed as an escape).
    return SECTION_RE.sub(lambda m: BEGIN + "\n" + table + END,
                          readme_text)


@register
class ReadmeDriftChecker(Checker):
    name = "readme-drift"
    doc = ("the README knob table between the knobs:begin/end markers "
           "equals the table generated from the knobs registry")

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        from ...common.knobs import render_knob_table
        out: List[Finding] = []
        path = os.path.join(ctx.root, "README.md")
        if not os.path.exists(path):
            return out
        with open(path, "r", encoding="utf-8") as fh:
            text = fh.read()
        committed = committed_table(text)
        if committed is None:
            out.append(Finding(
                self.name, "README.md", 1,
                f"README has no generated knob table ({BEGIN} … {END} "
                f"markers missing)",
                hint="run scripts/lint.py --fix-readme",
                detail="markers-missing"))
        elif committed != render_knob_table():
            out.append(Finding(
                self.name, "README.md", 1,
                "README knob table drifted from the common/knobs.py "
                "registry",
                hint="run scripts/lint.py --fix-readme",
                detail="table-drift"))
        return out
