"""store-write — no raw KV writes into framed columns outside store/.

Since schema v2 every value row outside ``BeaconMeta`` carries a CRC32
checksum frame (``store/kv.py``): a raw ``kv.put(DBColumn.X, ...)``
from outside the store layer writes an UNFRAMED value that reads back
as :class:`StoreCorruption` — a latent time bomb that only detonates
on the next restart's recovery scan (the PR-10 review shape).  Writers
outside ``lighthouse_tpu/store/`` must go through the ``HotColdDB`` op
builders (``block_put_ops`` / ``state_put_ops`` / ``blob_put_ops`` /
``item_put_op`` / ``journal_put_op``) committed via ``do_atomically``,
which frame values and keep the one-batch-per-import crash contract.

``DBColumn.BeaconMeta`` is exempt: it is deliberately unframed (the
schema-version gate must be readable by ANY schema, and the slasher's
counter rows live there).

Lexical, literal-first-arg only: ``kv.put(col_var, ...)`` with a
variable column is not caught — pass the DBColumn literally (the
repo's idiom everywhere) so the checker can see it.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Checker, Context, Finding, dotted, register

STORE_PACKAGE = "lighthouse_tpu/store/"
UNFRAMED = ("BeaconMeta",)


@register
class StoreWriteChecker(Checker):
    name = "store-write"
    doc = ("raw kv.put/kv.delete with a framed DBColumn outside "
           "lighthouse_tpu/store/ — use the HotColdDB op builders")

    def check(self, ctx: Context, path: str, tree: ast.AST,
              lines) -> Iterable[Finding]:
        if path.startswith(STORE_PACKAGE):
            return []
        out: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute) or \
                    node.func.attr not in ("put", "delete"):
                continue
            if not node.args:
                continue
            col = node.args[0]
            chain = dotted(col) or ""
            if not (chain == "DBColumn" or chain.startswith("DBColumn.")
                    or ".DBColumn." in chain):
                continue
            col_name = chain.rsplit(".", 1)[-1]
            if col_name in UNFRAMED:
                continue
            out.append(Finding(
                self.name, path, node.lineno,
                f"raw kv.{node.func.attr}(DBColumn.{col_name}, ...) "
                f"outside lighthouse_tpu/store/ — schema-v2 rows in "
                f"this column are CRC-framed; an unframed write reads "
                f"back as StoreCorruption",
                hint="build ops with the HotColdDB builders "
                     "(block_put_ops/state_put_ops/blob_put_ops/"
                     "item_put_op) and commit via do_atomically",
                detail=f"DBColumn.{col_name}.{node.func.attr}"))
        return out
