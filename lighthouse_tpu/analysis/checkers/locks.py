"""lock-discipline — ``# guarded-by: <lock>`` annotations are enforced.

The convention (the Rust reference gets this from ``Mutex<T>``'s type):
a class declares which attributes a lock guards by trailing a
``# guarded-by: _lock`` comment on the attribute's assignment
(typically in ``__init__``).  Every OTHER method touching a guarded
attribute must do so lexically inside ``with self._lock`` — the PR-7
bug shape (peek-then-observe dedup: check under no lock, mutate under
no lock, two pump threads both win) becomes a finding instead of a
sixth review pass.

Escape hatches, both explicit in source:

- ``__init__`` is exempt (construction happens-before sharing).
- a method whose ``def`` line carries ``# lock-held: _lock`` asserts
  its callers hold the lock (private helpers called under the lock).

Lexical only, by design: aliasing (``d = self._by_epoch`` then
mutating ``d`` outside the lock) is NOT caught — keep guarded state
access direct.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Sequence, Set

from ..core import Checker, Context, Finding, register

GUARD_RE = re.compile(r"#\s*guarded-by:\s*(\w+)")
HELD_RE = re.compile(r"#\s*lock-held:\s*(\w+)")


def _stmt_lines(node: ast.stmt, lines: Sequence[str]) -> str:
    end = getattr(node, "end_lineno", node.lineno)
    return "\n".join(lines[node.lineno - 1:end])


def _self_attr(node: ast.AST):
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


@register
class LockDisciplineChecker(Checker):
    name = "lock-discipline"
    doc = ("attributes annotated '# guarded-by: <lock>' may only be "
           "touched inside 'with self.<lock>' (or in methods marked "
           "'# lock-held: <lock>')")

    def check(self, ctx: Context, path: str, tree: ast.AST,
              lines) -> Iterable[Finding]:
        out: List[Finding] = []
        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            guarded = self._guarded_attrs(cls, lines)
            if not guarded:
                continue
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name == "__init__":
                    continue
                held: Set[str] = set(
                    HELD_RE.findall(lines[fn.lineno - 1]))
                self._walk(fn, held, guarded, cls.name, fn.name,
                           path, out)
        return out

    def _guarded_attrs(self, cls: ast.ClassDef,
                       lines) -> Dict[str, str]:
        """attr → lock name, from annotated assignments anywhere in
        the class body."""
        guarded: Dict[str, str] = {}
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            m = GUARD_RE.search(_stmt_lines(node, lines))
            if not m:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr:
                    guarded[attr] = m.group(1)
        return guarded

    def _walk(self, node: ast.AST, held: Set[str],
              guarded: Dict[str, str], cls_name: str, fn_name: str,
              path: str, out: List[Finding]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set(held)
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr:
                    acquired.add(attr)
                self._walk(item.context_expr, held, guarded,
                           cls_name, fn_name, path, out)
            for child in node.body:
                self._walk(child, acquired, guarded, cls_name,
                           fn_name, path, out)
            return
        attr = _self_attr(node)
        if attr is not None and attr in guarded \
                and guarded[attr] not in held:
            out.append(Finding(
                self.name, path, node.lineno,
                f"{cls_name}.{fn_name} touches self.{attr} "
                f"(guarded-by {guarded[attr]}) outside "
                f"'with self.{guarded[attr]}'",
                hint=f"wrap the access in 'with self.{guarded[attr]}:'"
                     f" or mark the method '# lock-held: "
                     f"{guarded[attr]}' if every caller holds it",
                detail=f"{cls_name}.{fn_name}.{attr}"))
            return  # one finding per access site; still walk siblings
        for child in ast.iter_child_nodes(node):
            self._walk(child, held, guarded, cls_name, fn_name,
                       path, out)
