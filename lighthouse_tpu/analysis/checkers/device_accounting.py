"""device-accounting — device I/O only inside ledger-annotated seams.

ISSUE 15 made :mod:`lighthouse_tpu.common.device_ledger` the ONE
accounting layer for host↔device traffic: every transfer is attributed
to a subsystem (``LEDGER.note_transfer`` / the ambient
``LEDGER.attribute`` context / an executor's ``subsystem=`` parameter).
A raw ``jax.device_put`` added outside those seams moves bytes the
ledger never sees — the warm-slot budget check and the per-slot
scoreboard silently under-report, which is exactly the "accounting
drifts from reality" failure the ledger exists to prevent.

A device-I/O call site must therefore carry a **seam annotation**: a
``# device-io: <subsystem>`` comment on the call's own line or on the
``def`` line of an enclosing function, with ``<subsystem>`` one of the
:data:`~lighthouse_tpu.common.device_ledger.SUBSYSTEMS` enum (the
annotation marks a REVIEWED seam whose bytes are accounted nearby — or
argued negligible, e.g. 32-byte root reads).  Unannotated sites are
findings, baseline-waivable with justification like every other
checker.

What counts as device I/O (lexical, like ``store-write``):

1. ``jax.device_put(...)`` / ``jax.device_get(...)`` anywhere in
   ``lighthouse_tpu/`` — the explicit transfer primitives.
2. ``jnp.asarray(...)`` inside the DEVICE SUBSYSTEM modules
   (:data:`DEVICE_MODULES`) — there, asarray IS the H2D staging call.
   Crypto/kernel modules are exempt: their ``jnp.asarray`` sites are
   trace-time constant material inside jit bodies, not runtime
   transfers (their real transfers are implicit jit-argument staging,
   accounted explicitly at the dispatch seams).
3. ``np.asarray(<device-suggestive>)`` / ``np.array(<device-
   suggestive>)`` anywhere — the D2H pull idioms — where the
   argument's name chain looks device-resident: a segment ending in
   ``_dev`` or ``_plane``, or equal to ``levels``.  A pull of a
   plainly-named local is NOT caught (lexical checker, documented
   limitation; the in-tree pull seams use the covered names).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional

from ...common.device_ledger import SUBSYSTEMS
from ..core import Checker, Context, Finding, dotted, register

PACKAGE = "lighthouse_tpu/"

# Modules whose jnp.asarray calls are runtime H2D staging (the device
# subsystems themselves), not trace-time constants.
DEVICE_MODULES = frozenset({
    "lighthouse_tpu/ops/device_tree.py",
    "lighthouse_tpu/ops/proof_engine.py",
    "lighthouse_tpu/ops/merkle_kernel.py",
    "lighthouse_tpu/types/device_state.py",
    "lighthouse_tpu/types/validators.py",
    "lighthouse_tpu/fork_choice/device_proto_array.py",
    "lighthouse_tpu/op_pool/device_pack.py",
    "lighthouse_tpu/slasher/device_spans.py",
    "lighthouse_tpu/parallel/pipeline.py",
    "lighthouse_tpu/kzg/device.py",
})

# The mesh residency layer (PR 20) IS the accounting seam: mesh_put /
# mesh_place / mesh_gather settle every transfer into the ledger with
# dynamic attribution (explicit subsystem= > ambient > column default),
# so its internal device_put/asarray sites cannot carry one static
# annotation.  The mesh-residency checker guards the inverse property —
# that persistent state OUTSIDE this module goes through it.
SEAM_MODULES = frozenset({"lighthouse_tpu/parallel/mesh.py"})

ANNOTATION_RE = re.compile(r"#\s*device-io:\s*([a-z_]+)")

_DEV_SEGMENT = re.compile(r"(_dev|_plane)$|^levels$")


def _annotation(line: str) -> Optional[str]:
    m = ANNOTATION_RE.search(line)
    return m.group(1) if m else None


def _unwrap(node: ast.AST) -> ast.AST:
    """Peel subscripts/calls so ``self.levels[-1]`` resolves to its
    base chain."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return node


def _device_suggestive(node: ast.AST) -> bool:
    chain = dotted(_unwrap(node))
    if not chain:
        return False
    return any(_DEV_SEGMENT.search(seg) for seg in chain.split("."))


@register
class DeviceAccountingChecker(Checker):
    name = "device-accounting"
    doc = ("raw jax.device_put / jnp.asarray / np.asarray(device_array) "
           "device I/O outside a '# device-io: <subsystem>' annotated "
           "ledger seam")

    def check(self, ctx: Context, path: str, tree: ast.AST,
              lines) -> Iterable[Finding]:
        if not path.startswith(PACKAGE) or path in SEAM_MODULES:
            return []
        out: List[Finding] = []
        self._walk(tree, path, lines, out, def_stack=[])
        return out

    def _walk(self, node: ast.AST, path: str, lines,
              out: List[Finding], def_stack: List[int]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            def_stack = def_stack + [node.lineno]
        elif isinstance(node, ast.Call):
            self._call(node, path, lines, out, def_stack)
        for child in ast.iter_child_nodes(node):
            self._walk(child, path, lines, out, def_stack)

    def _seam(self, lineno: int, lines,
              def_stack: List[int]) -> Optional[str]:
        """The governing annotation: the call's own line, else the
        nearest enclosing ``def`` line (first line of the signature)."""
        for ln in [lineno] + list(reversed(def_stack)):
            if 0 < ln <= len(lines):
                sub = _annotation(lines[ln - 1])
                if sub is not None:
                    return sub
        return None

    def _call(self, node: ast.Call, path: str, lines,
              out: List[Finding], def_stack: List[int]) -> None:
        chain = dotted(node.func) or ""
        kind = None
        if chain in ("jax.device_put", "jax.device_get") or \
                chain.endswith(".device_put") or \
                chain.endswith(".device_get"):
            kind = chain.rsplit(".", 1)[-1]
        elif chain in ("jnp.asarray", "jax.numpy.asarray") \
                and path in DEVICE_MODULES:
            kind = "jnp.asarray"
        elif chain in ("np.asarray", "numpy.asarray",
                       "np.array", "numpy.array") and node.args \
                and _device_suggestive(node.args[0]):
            kind = "np.asarray(device_array)"
        if kind is None:
            return
        sub = self._seam(node.lineno, lines, def_stack)
        if sub is None:
            out.append(Finding(
                self.name, path, node.lineno,
                f"raw {kind} device I/O outside an annotated ledger "
                f"seam — bytes the device ledger never sees",
                hint="account the transfer (LEDGER.note_transfer / an "
                     "executor subsystem=) and mark the seam with "
                     "'# device-io: <subsystem>' on the call or its "
                     "enclosing def",
                detail=f"unannotated:{kind}"))
        elif sub not in SUBSYSTEMS:
            out.append(Finding(
                self.name, path, node.lineno,
                f"device-io annotation names unknown subsystem "
                f"{sub!r} (enum: {', '.join(SUBSYSTEMS)})",
                hint="use a device_ledger.SUBSYSTEMS member",
                detail=f"bad-subsystem:{sub}"))
