"""knob-registry — every knob read goes through ``common/knobs.py``.

Two invariants:

1. **No raw env reads.**  Inside ``lighthouse_tpu/`` ANY
   ``os.environ`` / ``os.getenv`` read is a finding (the package had
   four truthiness dialects across ~23 knobs before the registry; the
   ``LIGHTHOUSE_TPU_NO_NATIVE=0``-disables-native bug is what bare
   truthiness buys).  In ``scripts/`` and ``bench.py`` only reads of
   literal ``LIGHTHOUSE_TPU_*`` names are findings — those trees own
   legitimate non-knob env vars (``BENCH_*``, ``XLA_FLAGS``).
   Env *writes* (``os.environ[k] = v``, ``.pop``, ``del``) stay legal
   everywhere: the validation scripts flip knobs on purpose.

2. **No undeclared knob names.**  Every literal ``LIGHTHOUSE_TPU_*``
   string anywhere in the lint set must be declared in
   :data:`lighthouse_tpu.common.knobs.KNOBS` — a typo'd knob is a lint
   failure, not a silently-ignored setting.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from ..core import Checker, Context, Finding, dotted, register, str_const

KNOB_NAME_RE = re.compile(r"LIGHTHOUSE_TPU_[A-Z0-9][A-Z0-9_]*[A-Z0-9]")

# The one module allowed to touch os.environ for knobs.
ACCESSOR_MODULE = "lighthouse_tpu/common/knobs.py"


def _is_env_read(node: ast.AST):
    """Returns (is_read, name_arg_node) for env-reading expressions."""
    if isinstance(node, ast.Call):
        chain = dotted(node.func)
        if chain in ("os.environ.get", "environ.get", "os.getenv",
                     "getenv", "os.environ.setdefault",
                     "environ.setdefault"):
            return True, (node.args[0] if node.args else None)
    if isinstance(node, ast.Subscript) and \
            isinstance(node.ctx, ast.Load) and \
            dotted(node.value) in ("os.environ", "environ"):
        return True, node.slice
    if isinstance(node, ast.Compare) and len(node.ops) == 1 and \
            isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
            dotted(node.comparators[0]) in ("os.environ", "environ"):
        return True, node.left
    return False, None


@register
class KnobRegistryChecker(Checker):
    name = "knob-registry"
    doc = ("LIGHTHOUSE_TPU_* knobs are read only through "
           "common/knobs.py typed accessors and must be declared "
           "in its registry")

    def _declared(self):
        from ...common.knobs import KNOBS
        return KNOBS

    def check(self, ctx: Context, path: str, tree: ast.AST,
              lines) -> Iterable[Finding]:
        if path == ACCESSOR_MODULE:
            return []
        in_package = path.startswith("lighthouse_tpu/")
        declared = self._declared()
        out: List[Finding] = []

        for node in ast.walk(tree):
            is_read, name_node = _is_env_read(node)
            if is_read:
                name = str_const(name_node) if name_node is not None \
                    else None
                if in_package:
                    what = f"of {name!r} " if name else ""
                    out.append(Finding(
                        self.name, path, node.lineno,
                        f"raw os.environ read {what}inside "
                        f"lighthouse_tpu/ — all env reads go through "
                        f"common/knobs.py",
                        hint="use knob_bool/knob_int/knob_float/"
                             "knob_str/knob_choice (declare the knob "
                             "in KNOBS if it is new)",
                        detail=f"env-read:{name or 'dynamic'}"))
                elif name and KNOB_NAME_RE.fullmatch(name):
                    out.append(Finding(
                        self.name, path, node.lineno,
                        f"raw os.environ read of knob {name!r} — "
                        f"knob reads go through common/knobs.py",
                        hint="use the typed accessor matching the "
                             "knob's registry type",
                        detail=f"env-read:{name}"))

        # Undeclared (typo'd) knob names in ANY string literal.
        seen = set()
        for node in ast.walk(tree):
            s = str_const(node)
            if s is None:
                continue
            for name in KNOB_NAME_RE.findall(s):
                if name not in declared and name not in seen:
                    seen.add(name)
                    out.append(Finding(
                        self.name, path, node.lineno,
                        f"undeclared knob name {name!r} — not in the "
                        f"common/knobs.py registry (typo, or a knob "
                        f"that was never declared)",
                        hint="declare it in KNOBS with type/default/"
                             "doc, or fix the spelling",
                        detail=f"undeclared:{name}"))
        return out
