"""graftlint checkers — importing this package registers them all."""

from . import device_accounting  # noqa: F401
from . import jax_hygiene    # noqa: F401
from . import knob_registry  # noqa: F401
from . import locks          # noqa: F401
from . import mesh_residency  # noqa: F401
from . import readme_drift   # noqa: F401
from . import stage_sources  # noqa: F401
from . import store_writes   # noqa: F401
