"""Batched Fr (BLS12-381 scalar field) arithmetic in 16-bit limbs.

The scalar-field sibling of :mod:`lighthouse_tpu.crypto.limb_field` (the
same VPU-shaped layout: little-endian 16-bit limbs in uint32 lanes,
Montgomery residues, lazy < 2N values, batched over leading axes) sized
for the 255-bit modulus: 17 limbs, R = 2^272 ≈ 2^17·N.  The headroom is
smaller than the base field's 2^35 but the same bounds go through:
mont_mul's output (T + mN)/R < 4N²/R + N < 2N because 4N/R < 2^-15.

Consumed by the barycentric blob-evaluation kernel
(:func:`.device.eval_blobs`); the pure-int helpers in :mod:`.fr` are the
semantics oracle.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .fr import BLS_MODULUS as N_INT

LIMB_BITS = 16
LIMBS = 17
MASK = np.uint32(0xFFFF)
R_BITS = LIMB_BITS * LIMBS          # 272
R_INT = 1 << R_BITS
R_MOD_N = R_INT % N_INT
RINV_INT = pow(R_INT, -1, N_INT)
NPRIME_INT = (-pow(N_INT, -1, R_INT)) % R_INT

# MSB-first exponent bits for the Fermat inversion ladder a^(N-2).
N_MINUS_2_BITS = np.array([int(b) for b in bin(N_INT - 2)[2:]],
                          dtype=np.int32)


def int_to_limbs(x: int) -> np.ndarray:
    if not 0 <= x < R_INT:
        raise ValueError("value out of limb range")
    return np.array([(x >> (LIMB_BITS * i)) & 0xFFFF for i in range(LIMBS)],
                    dtype=np.uint32)


def limbs_to_int(limbs: np.ndarray) -> int:
    limbs = np.asarray(limbs, dtype=np.uint64)
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(limbs))


N_LIMBS = int_to_limbs(N_INT)
N2_LIMBS = int_to_limbs(2 * N_INT)
_NPRIME_LIMBS = int_to_limbs(NPRIME_INT)


def to_mont(x: int) -> np.ndarray:
    return int_to_limbs((x % N_INT) * R_MOD_N % N_INT)


def from_mont(limbs: np.ndarray) -> int:
    return limbs_to_int(limbs) * RINV_INT % N_INT


def to_mont_array(xs) -> np.ndarray:
    """Nested sequence/array of python ints → (..., 17) Montgomery limbs."""
    arr = np.asarray(xs, dtype=object)
    flat = [to_mont(int(x)) for x in arr.reshape(-1)]
    out = np.stack(flat) if flat else np.zeros((0, LIMBS), np.uint32)
    return out.reshape(arr.shape + (LIMBS,))


def from_mont_array(limbs: np.ndarray) -> np.ndarray:
    arr = np.asarray(limbs)
    flat = arr.reshape(-1, LIMBS)
    out = np.empty(flat.shape[0], dtype=object)
    for i in range(flat.shape[0]):
        out[i] = from_mont(flat[i])
    return out.reshape(arr.shape[:-1])


ZERO = np.zeros(LIMBS, dtype=np.uint32)
ONE_MONT = to_mont(1)


# ---------------------------------------------------------------------------
# Device ops (batched over leading dims; limb axis = -1) — the exact
# structure of limb_field with Fr constants; see that module for the
# bound-by-bound reasoning.
# ---------------------------------------------------------------------------

def _carry_u32(x: jnp.ndarray) -> jnp.ndarray:
    out = []
    carry = jnp.zeros_like(x[..., 0])
    for i in range(LIMBS):
        v = x[..., i] + carry
        out.append(v & MASK)
        carry = v >> np.uint32(LIMB_BITS)
    return jnp.stack(out, axis=-1)


def _carry_i32(x: jnp.ndarray) -> jnp.ndarray:
    out = []
    carry = jnp.zeros_like(x[..., 0])
    for i in range(LIMBS):
        v = x[..., i] + carry
        out.append(v & jnp.int32(0xFFFF))
        carry = v >> 16
    return jnp.stack(out, axis=-1).astype(jnp.uint32)


def _cond_sub(x: jnp.ndarray, k_limbs: np.ndarray) -> jnp.ndarray:
    d = x.astype(jnp.int32) - jnp.asarray(k_limbs, jnp.int32)
    out = []
    carry = jnp.zeros_like(d[..., 0])
    for i in range(LIMBS):
        v = d[..., i] + carry
        out.append(v & jnp.int32(0xFFFF))
        carry = v >> 16
    d_norm = jnp.stack(out, axis=-1).astype(jnp.uint32)
    return jnp.where((carry == 0)[..., None], d_norm, x)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _cond_sub(_carry_u32(a + b), N2_LIMBS)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    d = a.astype(jnp.int32) + jnp.asarray(N2_LIMBS, jnp.int32) \
        - b.astype(jnp.int32)
    return _cond_sub(_carry_i32(d), N2_LIMBS)


def _band_columns(a: jnp.ndarray, b: jnp.ndarray, ncols: int) -> jnp.ndarray:
    prod = a[..., :, None] * b[..., None, :]
    lo = prod & MASK
    hi = prod >> np.uint32(LIMB_BITS)
    nd = lo.ndim - 2
    parts = []
    for i in range(LIMBS):
        width = min(LIMBS, ncols - i)
        if width > 0:
            parts.append(jnp.pad(lo[..., i, :width],
                                 [(0, 0)] * nd + [(i, ncols - i - width)]))
        width = min(LIMBS, ncols - i - 1)
        if width > 0:
            parts.append(jnp.pad(hi[..., i, :width],
                                 [(0, 0)] * nd + [(i + 1,
                                                   ncols - i - 1 - width)]))
    return jnp.sum(jnp.stack(parts), axis=0)


def _carry_cols(t: jnp.ndarray, ncols: int, keep_carry: bool) -> jnp.ndarray:
    out = []
    carry = jnp.zeros_like(t[..., 0])
    for i in range(ncols):
        v = t[..., i] + carry
        out.append(v & MASK)
        carry = v >> np.uint32(LIMB_BITS)
    if keep_carry:
        out.append(carry)
    return jnp.stack(out, axis=-1)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Batched a·b·R⁻¹ mod N; normalized < 2N in, < 2N out."""
    t = _band_columns(a, b, 2 * LIMBS)
    t_low = _carry_cols(t[..., :LIMBS], LIMBS, keep_carry=False)
    m = _carry_cols(_band_columns(t_low, jnp.asarray(_NPRIME_LIMBS), LIMBS),
                    LIMBS, keep_carry=False)
    u = _band_columns(m, jnp.asarray(N_LIMBS), 2 * LIMBS)
    s = _carry_cols(t + u, 2 * LIMBS, keep_carry=True)
    return s[..., LIMBS:2 * LIMBS]


def select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(mask[..., None], a, b)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """Exact zero test for lazy values < 4N."""
    out = None
    for k in range(4):
        eq = jnp.all(a == jnp.asarray(int_to_limbs(k * N_INT)), axis=-1)
        out = eq if out is None else (out | eq)
    return out


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """Batched Fermat inversion a^(N-2) (scanned ladder); inv(0) = 0."""
    one = jnp.broadcast_to(jnp.asarray(ONE_MONT), a.shape)

    def body(acc, bit):
        acc = mont_mul(acc, acc)
        return select(bit.astype(bool), mont_mul(acc, a), acc), None

    acc, _ = jax.lax.scan(body, one, jnp.asarray(N_MINUS_2_BITS))
    return acc
