"""Host arithmetic over Fr, the BLS12-381 scalar field — the KZG
"polynomial side" of consensus-specs ``polynomial-commitments.md``.

Fr is also the subgroup order r the pairing code already carries
(:data:`lighthouse_tpu.crypto.fields.R`), so the modulus is imported, not
re-stated.  Everything here is exact python ints: the host oracle for the
device barycentric kernel (:mod:`.device`) and the reference semantics for
challenges, roots of unity and field (de)serialization.
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence

from ..crypto.fields import R as BLS_MODULUS

BYTES_PER_FIELD_ELEMENT = 32

# Generator of Fr's multiplicative group (consensus-specs
# PRIMITIVE_ROOT_OF_UNITY); 7 generates because r - 1 = 2^32 · odd and
# 7^((r-1)/2) == -1.
PRIMITIVE_ROOT_OF_UNITY = 7


class FrError(ValueError):
    pass


def bytes_to_bls_field(b: bytes) -> int:
    """Big-endian 32 bytes → canonical Fr element; non-canonical (≥ r)
    encodings are rejected (spec ``bytes_to_bls_field``)."""
    if len(b) != BYTES_PER_FIELD_ELEMENT:
        raise FrError("field element must be 32 bytes")
    v = int.from_bytes(b, "big")
    if v >= BLS_MODULUS:
        raise FrError("non-canonical field element")
    return v


def bls_field_to_bytes(x: int) -> bytes:
    return (x % BLS_MODULUS).to_bytes(BYTES_PER_FIELD_ELEMENT, "big")


def hash_to_bls_field(data: bytes) -> int:
    """SHA-256 → Fr by modular reduction (spec ``hash_to_bls_field``; the
    ~2^-126 bias is part of the spec's Fiat-Shamir definition)."""
    return int.from_bytes(hashlib.sha256(data).digest(), "big") % BLS_MODULUS


def compute_powers(x: int, n: int) -> List[int]:
    """[1, x, x², …, x^(n-1)] mod r (spec ``compute_powers``)."""
    out, acc = [], 1
    for _ in range(n):
        out.append(acc)
        acc = acc * x % BLS_MODULUS
    return out


def _bit_reversal_permutation(seq: Sequence[int]) -> List[int]:
    """Reorder a power-of-two sequence by bit-reversed index (spec
    ``bit_reversal_permutation``) — the order blob evaluations live in."""
    n = len(seq)
    if n & (n - 1):
        raise FrError("length must be a power of two")
    bits = n.bit_length() - 1
    return [seq[int(format(i, f"0{bits}b")[::-1], 2) if bits else 0]
            for i in range(n)]


def compute_roots_of_unity(width: int) -> List[int]:
    """The ``width`` roots of x^width = 1, in BIT-REVERSAL order — blob
    element i is the polynomial's evaluation at ``roots[i]``."""
    if width & (width - 1) or width == 0:
        raise FrError("width must be a power of two")
    if (BLS_MODULUS - 1) % width:
        raise FrError("width does not divide r - 1")
    omega = pow(PRIMITIVE_ROOT_OF_UNITY, (BLS_MODULUS - 1) // width,
                BLS_MODULUS)
    roots, acc = [], 1
    for _ in range(width):
        roots.append(acc)
        acc = acc * omega % BLS_MODULUS
    return _bit_reversal_permutation(roots)


def evaluate_polynomial_in_evaluation_form(evals: Sequence[int], z: int,
                                           roots: Sequence[int]) -> int:
    """Barycentric evaluation p(z) from evaluations over the roots-of-unity
    domain (spec ``evaluate_polynomial_in_evaluation_form``):

        p(z) = (z^W - 1)/W · Σ_i  f_i · ω_i / (z - ω_i)

    with the in-domain special case p(ω_i) = f_i.  This is the exact host
    oracle the device kernel (:func:`.device.eval_blobs`) is checked
    against.
    """
    width = len(evals)
    if width != len(roots):
        raise FrError("evaluations/domain length mismatch")
    z %= BLS_MODULUS
    for f, w in zip(evals, roots):
        if z == w:
            return f % BLS_MODULUS
    inv_width = pow(width, BLS_MODULUS - 2, BLS_MODULUS)
    acc = 0
    for f, w in zip(evals, roots):
        acc += f * w % BLS_MODULUS * pow(z - w, BLS_MODULUS - 2, BLS_MODULUS)
    factor = (pow(z, width, BLS_MODULUS) - 1) * inv_width
    return acc * factor % BLS_MODULUS
