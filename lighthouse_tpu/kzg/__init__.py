"""EIP-4844 / Deneb KZG polynomial commitments.

The data-availability crypto of the Deneb fork (consensus-specs
``polynomial-commitments.md``; the reference consumes it through
``crypto/kzg`` wrapping c-kzg-4844): blobs are polynomials in evaluation
form over the BLS12-381 scalar field Fr, commitments/proofs are G1 points
under a powers-of-tau trusted setup, and verification is two pairings per
blob — the same pairing family the batched BLS backend already runs on
TPU, which is why ``verify_blob_kzg_proof_batch`` reduces to lanes of the
:mod:`..crypto.limb_pairing` Miller loop.

Layer map:

- :mod:`.fr`            host Fr arithmetic, roots of unity, Fiat-Shamir.
- :mod:`.fr_limb`       device Fr in 16-bit Montgomery limbs (VPU-shaped).
- :mod:`.trusted_setup` setup loader + embedded minimal-width setup.
- :mod:`.kzg`           host commit/prove/verify (the semantics oracle).
- :mod:`.device`        batched barycentric eval + fused pairing check.
- :mod:`.inclusion`     BlobSidecar commitment inclusion proofs.
"""

from .fr import (
    BLS_MODULUS,
    BYTES_PER_FIELD_ELEMENT,
    bytes_to_bls_field,
    bls_field_to_bytes,
    compute_roots_of_unity,
    evaluate_polynomial_in_evaluation_form,
)
from .trusted_setup import TrustedSetup, load_trusted_setup
from .kzg import (
    KzgError,
    blob_to_kzg_commitment,
    blob_to_polynomial,
    compute_blob_kzg_proof,
    compute_challenge,
    validate_blob,
    verify_blob_kzg_proof,
    verify_blob_kzg_proof_batch,
)
from .inclusion import (
    blob_sidecar_inclusion_proof,
    verify_blob_sidecar_inclusion_proof,
)

__all__ = [
    "BLS_MODULUS", "BYTES_PER_FIELD_ELEMENT", "bytes_to_bls_field",
    "bls_field_to_bytes", "compute_roots_of_unity",
    "evaluate_polynomial_in_evaluation_form", "TrustedSetup",
    "load_trusted_setup", "KzgError", "blob_to_kzg_commitment",
    "blob_to_polynomial", "compute_blob_kzg_proof", "compute_challenge",
    "validate_blob", "verify_blob_kzg_proof",
    "verify_blob_kzg_proof_batch", "blob_sidecar_inclusion_proof",
    "verify_blob_sidecar_inclusion_proof",
]
