"""Host KZG commit/prove/verify — the semantics oracle for the device path.

Follows consensus-specs ``polynomial-commitments.md`` (Deneb): blobs are
W·32 bytes of big-endian canonical Fr elements (the polynomial in
evaluation form over the bit-reversal roots-of-unity domain), commitments
and proofs are compressed G1.  Verification is the pairing identity

    e(C - [y]·G1, [1]·G2) == e(Q, [tau - z]·G2)

checked as a 2-pairing product through
:func:`lighthouse_tpu.crypto.pairing.multi_pairing_is_one` (which routes
to the native C++ pairing when built — the ``crypto/native.py``-style host
fast path).  The batch form draws Fiat-Shamir powers r^i and folds every
blob into ONE pairing product; :mod:`.device` runs the same reduction as
lanes of the TPU Miller-loop kernel.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..crypto import curve as C
from ..crypto import pairing as HP
from .fr import (
    BLS_MODULUS,
    BYTES_PER_FIELD_ELEMENT,
    bls_field_to_bytes,
    bytes_to_bls_field,
    compute_powers,
    evaluate_polynomial_in_evaluation_form,
    hash_to_bls_field,
)
from .trusted_setup import TrustedSetup

# Fiat-Shamir domain separators (spec constants).
FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
RANDOM_CHALLENGE_KZG_BATCH_DOMAIN = b"RCKZGBATCH___V1_"

G1_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 47


class KzgError(ValueError):
    pass


# -- blob plumbing -----------------------------------------------------------

def validate_blob(blob: bytes, width: int) -> None:
    """Every 32-byte chunk must be a canonical Fr element (spec
    ``validate_blob`` via bytes_to_bls_field's range check)."""
    if len(blob) != width * BYTES_PER_FIELD_ELEMENT:
        raise KzgError(f"blob must be {width * 32} bytes, got {len(blob)}")
    for i in range(width):
        v = int.from_bytes(blob[32 * i:32 * (i + 1)], "big")
        if v >= BLS_MODULUS:
            raise KzgError(f"blob element {i} is non-canonical")


def blob_to_polynomial(blob: bytes, width: int) -> List[int]:
    validate_blob(blob, width)
    return [int.from_bytes(blob[32 * i:32 * (i + 1)], "big")
            for i in range(width)]


def polynomial_to_blob(evals: Sequence[int]) -> bytes:
    return b"".join(bls_field_to_bytes(int(v)) for v in evals)


def bytes_to_kzg_commitment(data: bytes):
    """48-byte compressed G1 → affine point with SUBGROUP check (spec
    ``bytes_to_kzg_commitment`` / ``validate_kzg_g1``); identity allowed
    (the commitment to the zero polynomial)."""
    if len(data) != 48:
        raise KzgError("commitment/proof must be 48 bytes")
    try:
        p = C.g1_decompress(bytes(data))
    except ValueError as e:
        raise KzgError(f"bad G1 encoding: {e}") from None
    if p is not None and not C.g1_subgroup_check(p):
        raise KzgError("G1 point not in the r-order subgroup")
    return p


bytes_to_kzg_proof = bytes_to_kzg_commitment


# -- commit / prove (Lagrange MSM; width-sized, host) ------------------------

def _g1_lincomb(points, scalars) -> Optional[Tuple[int, int]]:
    acc = None
    for p, s in zip(points, scalars):
        s %= BLS_MODULUS
        if s == 0 or p is None:
            continue
        acc = C.g1_add(acc, C.g1_mul(p, s))
    return acc


def blob_to_kzg_commitment(blob: bytes, setup: TrustedSetup) -> bytes:
    """[p(tau)]·G1 as Σ f_i·[L_i(tau)]G1 (spec ``blob_to_kzg_commitment``).

    On an insecure setup (known tau) the MSM collapses to ONE scalar-mul
    via p(tau) — same point, width-independent cost; bench/tests use this
    to build mainnet-width fixtures without a 4096-point MSM per blob.
    """
    evals = blob_to_polynomial(blob, setup.width)
    if setup.tau is not None:
        p_tau = evaluate_polynomial_in_evaluation_form(
            evals, setup.tau, setup.roots)
        return C.g1_compress(None if p_tau == 0
                             else C.g1_mul(C.G1_GEN, p_tau))
    if not setup.g1_lagrange:
        raise KzgError("setup has no G1 Lagrange points (verify-only)")
    return C.g1_compress(_g1_lincomb(setup.g1_lagrange, evals))


def compute_challenge(blob: bytes, commitment: bytes, width: int) -> int:
    """Fiat-Shamir evaluation point z (spec ``compute_challenge``)."""
    # Length fields use KZG_ENDIANNESS = big (spec constant) — matching
    # c-kzg-4844 transcripts byte-for-byte.
    data = (FIAT_SHAMIR_PROTOCOL_DOMAIN
            + width.to_bytes(16, "big")
            + blob + bytes(commitment))
    return hash_to_bls_field(data)


def compute_blob_kzg_proof(blob: bytes, commitment: bytes,
                           setup: TrustedSetup) -> bytes:
    """Proof for the blob's own Fiat-Shamir challenge (spec
    ``compute_blob_kzg_proof``): Q = [q(tau)]·G1 with
    q(X) = (p(X) - y)/(X - z) built in evaluation form.

    Insecure-setup fast path: q(tau) = (p(tau) - y)/(tau - z) directly.
    """
    width = setup.width
    evals = blob_to_polynomial(blob, width)
    z = compute_challenge(blob, commitment, width)
    roots = setup.roots
    y = evaluate_polynomial_in_evaluation_form(evals, z, roots)
    if setup.tau is not None:
        q_tau = (evaluate_polynomial_in_evaluation_form(
            evals, setup.tau, roots) - y) \
            * pow(setup.tau - z, BLS_MODULUS - 2, BLS_MODULUS) % BLS_MODULUS
        return C.g1_compress(None if q_tau == 0
                             else C.g1_mul(C.G1_GEN, q_tau))
    if not setup.g1_lagrange:
        raise KzgError("setup has no G1 Lagrange points (verify-only)")
    if z in roots:
        raise KzgError("challenge landed in the domain")  # ~2^-250
    # q_i = (f_i - y)/(ω_i - z) plus no correction terms since z ∉ domain.
    q = [(f - y) * pow(w - z, BLS_MODULUS - 2, BLS_MODULUS) % BLS_MODULUS
         for f, w in zip(evals, roots)]
    return C.g1_compress(_g1_lincomb(setup.g1_lagrange, q))


# -- verify ------------------------------------------------------------------

def _proof_pairs(commitment_pt, z: int, y: int, proof_pt, setup, r: int = 1):
    """The two pairing pairs for one (C, z, y, Q) claim, with the G2 sides
    FIXED (G2 and X2) so batch lanes share them:

        e(r·(C - y·G1 + z·Q), -G2) · e(r·Q, X2) == 1

    — the z term moved from G2 to G1 by bilinearity; ``r`` is the batch
    RLC power (1 for a single verify)."""
    x2 = setup.g2_monomial[1]
    a = commitment_pt
    if y % BLS_MODULUS:
        a = C.g1_add(a, C.g1_neg(C.g1_mul(C.G1_GEN, y)))
    if proof_pt is not None and z % BLS_MODULUS:
        a = C.g1_add(a, C.g1_mul(proof_pt, z))
    if r != 1:
        a = None if a is None else C.g1_mul(a, r)
    b = None if proof_pt is None else C.g1_mul(proof_pt, r % BLS_MODULUS)
    return [(a, C.g2_neg(C.G2_GEN)), (b, x2)]


def verify_kzg_proof_impl(commitment_pt, z: int, y: int, proof_pt,
                          setup: TrustedSetup) -> bool:
    return HP.multi_pairing_is_one(
        _proof_pairs(commitment_pt, z, y, proof_pt, setup))


def verify_blob_kzg_proof(blob: bytes, commitment: bytes, proof: bytes,
                          setup: TrustedSetup) -> bool:
    """Spec ``verify_blob_kzg_proof``.  Malformed inputs raise
    :class:`KzgError`; a well-formed-but-wrong proof returns False."""
    width = setup.width
    evals = blob_to_polynomial(blob, width)
    cpt = bytes_to_kzg_commitment(commitment)
    qpt = bytes_to_kzg_proof(proof)
    z = compute_challenge(blob, commitment, width)
    y = evaluate_polynomial_in_evaluation_form(evals, z, setup.roots)
    return verify_kzg_proof_impl(cpt, z, y, qpt, setup)


def _batch_challenges(blobs, commitments, setup):
    """Per-blob (z_i, y_i) plus the RLC powers r^i (spec
    ``verify_blob_kzg_proof_batch`` Fiat-Shamir)."""
    width = setup.width
    zs, ys = [], []
    for blob, commitment in zip(blobs, commitments):
        evals = blob_to_polynomial(blob, width)
        z = compute_challenge(blob, commitment, width)
        zs.append(z)
        ys.append(evaluate_polynomial_in_evaluation_form(
            evals, z, setup.roots))
    return zs, ys


def _rlc_powers(commitments, zs, ys, proofs, width: int) -> List[int]:
    data = (RANDOM_CHALLENGE_KZG_BATCH_DOMAIN
            + width.to_bytes(8, "big")
            + len(commitments).to_bytes(8, "big"))
    for c, z, y, q in zip(commitments, zs, ys, proofs):
        data += bytes(c) + bls_field_to_bytes(z) + bls_field_to_bytes(y) \
            + bytes(q)
    return compute_powers(hash_to_bls_field(data), len(commitments))


def verify_blob_kzg_proof_batch_host(blobs, commitments, proofs,
                                     setup: TrustedSetup) -> bool:
    """Host batch verify: RLC-fold every claim into ONE 2-pairing check
    (the spec's ``verify_kzg_proof_batch`` shape — G1 MSM on the host,
    two pairings total), via the native pairing when built."""
    if not (len(blobs) == len(commitments) == len(proofs)):
        raise KzgError("batch length mismatch")
    if not blobs:
        return True
    cpts = [bytes_to_kzg_commitment(c) for c in commitments]
    qpts = [bytes_to_kzg_proof(q) for q in proofs]
    zs, ys = _batch_challenges(blobs, commitments, setup)
    rs = _rlc_powers(commitments, zs, ys, proofs, setup.width)
    pairs = []
    for cpt, z, y, qpt, r in zip(cpts, zs, ys, qpts, rs):
        pairs.extend(_proof_pairs(cpt, z, y, qpt, setup, r=r))
    # Fold the shared-G2 lanes: Σ lanes with -G2, Σ lanes with X2.
    a = b = None
    for (pa, _), (pb, _) in zip(pairs[0::2], pairs[1::2]):
        a = C.g1_add(a, pa)
        b = C.g1_add(b, pb)
    return HP.multi_pairing_is_one(
        [(a, C.g2_neg(C.G2_GEN)), (b, setup.g2_monomial[1])])


def verify_blob_kzg_proof_batch(blobs, commitments, proofs,
                                setup: TrustedSetup,
                                use_device: Optional[bool] = None) -> bool:
    """The framework entry point: device-batched when a TPU backend is
    live (lanes of the :mod:`..crypto.limb_pairing` Miller loop +
    the :mod:`.device` barycentric kernel), host RLC fold otherwise.
    ``use_device`` forces the choice (tests cross-check both)."""
    from . import device as D
    if use_device is None:
        use_device = D.device_default()
    if use_device:
        return D.verify_blob_kzg_proof_batch_device(
            blobs, commitments, proofs, setup)
    return verify_blob_kzg_proof_batch_host(blobs, commitments, proofs,
                                            setup)
