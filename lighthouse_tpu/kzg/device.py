"""Device-batched KZG verification: barycentric blob evaluation as a
VPU-shaped Fr kernel + the pairing equation reduced through the existing
TPU Miller loop.

Two device programs:

1. :func:`eval_blobs` — p_i(z_i) for B blobs at once.  The barycentric sum
       p(z) = (z^W - 1)/W · Σ_j f_j·ω_j/(z - ω_j)
   is elementwise Fr work over a (B, W) grid: one batched Fermat-ladder
   inversion (the only sequential part, a 255-step ``lax.scan`` shared by
   every lane), two batched ``mont_mul`` passes, and a log₂W tree-sum —
   exactly the many-independent-lanes shape the 16-bit-limb representation
   was built for (:mod:`.fr_limb`, same layout as the base-field
   ``limb_field``).  In-domain challenges (z = ω_j) resolve through the
   masked select, not a host branch.

2. :func:`verify_blob_kzg_proof_batch_device` — every blob contributes two
   pairing lanes with FIXED G2 sides,

       e(r_i·(C_i - y_i·G1 + z_i·Q_i), -G2) · e(r_i·Q_i, X2)  == 1  (∏ i)

   padded to a power of two and fused through
   :func:`lighthouse_tpu.crypto.limb_pairing.multi_pairing_is_one`: B
   blobs cost 2B batched Miller lanes and ONE shared final exponentiation
   — the same product-of-pairings amortization the BLS backend uses.  The
   host's role is only the per-lane scalar muls (4 G1 muls/blob) and the
   Fiat-Shamir transcript.

Stage timings land in :data:`LAST_KZG_TIMINGS` and the metrics registry
(``kzg_*`` histograms) for the bench row.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..common.metrics import observe
from ..crypto import curve as C
from ..crypto import limb_field as LF
from ..crypto import limb_pairing as LP
from . import fr_limb as FL
from .fr import BLS_MODULUS
from .trusted_setup import TrustedSetup

# Stage decomposition of the last batch verify (bench.py reads this, the
# LAST_COLD_TIMINGS idiom).
LAST_KZG_TIMINGS: Dict[str, float] = {}


def reset_stage_timings() -> None:
    """Clear the stage dict — the mutation surface OTHER modules use
    (e.g. the availability gate before a host-path verify, so stale
    device stages can't attach to a host span)."""
    LAST_KZG_TIMINGS.clear()


def device_default() -> bool:
    """Route batches to the device only on a real TPU backend — on CPU the
    Miller-scan compile dwarfs the work (same policy as the BLS
    backend's ``_use_pallas``).  LIGHTHOUSE_TPU_KZG_DEVICE=1/0 forces."""
    from ..common.knobs import knob_tribool
    forced = knob_tribool("LIGHTHOUSE_TPU_KZG_DEVICE")
    if forced is not None:
        return forced
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Barycentric evaluation kernel
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnums=(3,))
def _eval_kernel(f: jnp.ndarray, z: jnp.ndarray, roots: jnp.ndarray,  # device-io: kzg
                 width: int) -> jnp.ndarray:
    """f: (B, W, 17) Montgomery evals; z: (B, 17); roots: (W, 17).
    Returns (B, 17) Montgomery p_i(z_i)."""
    d = FL.sub(z[:, None, :], roots[None, :, :])           # (B, W, 17)
    hit = FL.is_zero(d)                                    # (B, W)
    dinv = FL.inv(d)                                       # inv(0) = 0
    terms = FL.mont_mul(FL.mont_mul(f, roots[None]), dinv)
    # Modular tree-sum over W (add() keeps the lazy < 2N invariant).
    acc = terms
    n = width
    while n > 1:
        n //= 2
        acc = FL.add(acc[:, :n, :], acc[:, n:2 * n, :])
    acc = acc[:, 0, :]
    # (z^W - 1)/W via log2(W) squarings.
    zw = z
    for _ in range(width.bit_length() - 1):
        zw = FL.mont_mul(zw, zw)
    w_inv = jnp.asarray(FL.to_mont(
        pow(width, BLS_MODULUS - 2, BLS_MODULUS)))
    factor = FL.mont_mul(FL.sub(zw, jnp.asarray(FL.ONE_MONT)), w_inv)
    out = FL.mont_mul(acc, factor)
    # In-domain challenge: p(ω_j) = f_j (the hit lane's evaluation; at
    # most one root can match, so a masked tree-sum selects it).
    fhit = FL.select(hit, f, jnp.zeros_like(f))
    n = width
    while n > 1:
        n //= 2
        fhit = FL.add(fhit[:, :n, :], fhit[:, n:2 * n, :])
    return FL.select(jnp.any(hit, axis=1), fhit[:, 0, :], out)


_ROOTS_CACHE: Dict[int, np.ndarray] = {}


def _roots_limbs(setup: TrustedSetup) -> np.ndarray:
    limbs = _ROOTS_CACHE.get(setup.width)
    if limbs is None:
        limbs = FL.to_mont_array(setup.roots)
        _ROOTS_CACHE[setup.width] = limbs
    return limbs


def eval_blobs(polys, zs, setup: TrustedSetup) -> list:  # device-io: kzg
    """Batched p_i(z_i) for B polynomials (lists of Fr ints) at B points.
    Host↔device conversion at the edges, ints in and out."""
    B = len(polys)
    if B == 0:
        return []
    from ..common.device_ledger import LEDGER
    f = FL.to_mont_array(polys)                    # (B, W, 17)
    z = FL.to_mont_array(zs)                       # (B, 17)
    roots = _roots_limbs(setup)
    # roots re-upload every call too (jnp.asarray of a host array) —
    # leaving them out would under-report kzg H2D by a (W, 17) plane.
    LEDGER.note_transfer("h2d", f.nbytes + z.nbytes + roots.nbytes,
                         subsystem="kzg")
    out = _eval_kernel(jnp.asarray(f), jnp.asarray(z),  # device-io: kzg
                       jnp.asarray(roots), setup.width)
    host = np.asarray(out)  # device-io: kzg
    LEDGER.note_transfer("d2h", host.nbytes, subsystem="kzg")
    return [int(v) for v in FL.from_mont_array(host)]


# ---------------------------------------------------------------------------
# Fused batch verification
# ---------------------------------------------------------------------------

def _g1_proj_limbs(points) -> np.ndarray:
    """Affine host points → (B, 3, 26) Montgomery projective lanes
    (identity → Z = 0, which the pairing masks to 1)."""
    out = np.zeros((len(points), 3, LF.LIMBS), np.uint32)
    for i, p in enumerate(points):
        if p is None:
            continue
        out[i, 0] = LF.to_mont(p[0])
        out[i, 1] = LF.to_mont(p[1])
        out[i, 2] = LF.to_mont(1)
    return out


def _g2_proj_limbs(points) -> np.ndarray:
    out = np.zeros((len(points), 3, 2, LF.LIMBS), np.uint32)
    for i, p in enumerate(points):
        if p is None:
            continue
        (x0, x1), (y0, y1) = p
        out[i, 0, 0] = LF.to_mont(x0)
        out[i, 0, 1] = LF.to_mont(x1)
        out[i, 1, 0] = LF.to_mont(y0)
        out[i, 1, 1] = LF.to_mont(y1)
        out[i, 2, 0] = LF.to_mont(1)
    return out


def verify_blob_kzg_proof_batch_device(blobs, commitments, proofs,  # device-io: kzg
                                       setup: TrustedSetup) -> bool:
    """B blobs → one device round-trip: eval kernel for the y_i, then 2B
    Miller lanes + shared final exponentiation.  Same accept/reject set as
    :func:`.kzg.verify_blob_kzg_proof_batch_host` (cross-checked in tests
    and ``scripts/validate_pairing_kernels.py --kzg``)."""
    from . import kzg as K
    if not (len(blobs) == len(commitments) == len(proofs)):
        raise K.KzgError("batch length mismatch")
    if not blobs:
        return True
    t0 = time.perf_counter()
    cpts = [K.bytes_to_kzg_commitment(c) for c in commitments]
    qpts = [K.bytes_to_kzg_proof(q) for q in proofs]
    polys = [K.blob_to_polynomial(b, setup.width) for b in blobs]
    zs = [K.compute_challenge(b, c, setup.width)
          for b, c in zip(blobs, commitments)]
    t_chal = time.perf_counter()
    ys = eval_blobs(polys, zs, setup)
    t_eval = time.perf_counter()
    rs = K._rlc_powers(commitments, zs, ys, proofs, setup.width)
    # Per-blob lanes with fixed G2 sides — the SAME per-claim group math
    # as the host fold (one source of truth for accept/reject parity).
    g1a, g1b = [], []
    for cpt, z, y, qpt, r in zip(cpts, zs, ys, qpts, rs):
        (a, _neg_g2), (b, _x2) = K._proof_pairs(cpt, z, y, qpt, setup, r=r)
        g1a.append(a)
        g1b.append(b)
    B = len(blobs)
    lanes = 1
    while lanes < 2 * B:
        lanes *= 2
    g1_lanes = np.zeros((lanes, 3, LF.LIMBS), np.uint32)
    g2_lanes = np.zeros((lanes, 3, 2, LF.LIMBS), np.uint32)
    mask = np.zeros(lanes, bool)
    g1_lanes[0:2 * B:2] = _g1_proj_limbs(g1a)
    g1_lanes[1:2 * B:2] = _g1_proj_limbs(g1b)
    neg_g2 = _g2_proj_limbs([C.g2_neg(C.G2_GEN)])[0]
    x2 = _g2_proj_limbs([setup.g2_monomial[1]])[0]
    g2_lanes[0:2 * B:2] = neg_g2
    g2_lanes[1:2 * B:2] = x2
    mask[:2 * B] = True
    t_prep = time.perf_counter()
    from ..common.device_ledger import LEDGER
    LEDGER.note_transfer(
        "h2d", g1_lanes.nbytes + g2_lanes.nbytes + mask.nbytes,
        subsystem="kzg")
    ok = bool(np.asarray(LP.multi_pairing_is_one(  # device-io: kzg
        jnp.asarray(g1_lanes), jnp.asarray(g2_lanes), jnp.asarray(mask))))
    t_pair = time.perf_counter()
    LEDGER.note_transfer("d2h", 1, subsystem="kzg")
    LEDGER.note_dispatch("kzg", (t_pair - t_prep) * 1e3)
    reset_stage_timings()
    LAST_KZG_TIMINGS.update({
        "blobs": B,
        "lanes": lanes,
        "challenge_ms": round((t_chal - t0) * 1e3, 2),
        "eval_ms": round((t_eval - t_chal) * 1e3, 2),
        "lane_prep_ms": round((t_prep - t_eval) * 1e3, 2),
        "pairing_ms": round((t_pair - t_prep) * 1e3, 2),
    })
    observe("kzg_eval_seconds", t_eval - t_chal)
    observe("kzg_lane_prep_seconds", t_prep - t_eval)
    observe("kzg_pairing_seconds", t_pair - t_prep)
    return ok
