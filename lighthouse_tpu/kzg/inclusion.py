"""BlobSidecar commitment inclusion proofs (Deneb p2p spec).

A sidecar proves its ``kzg_commitment`` sits at ``index`` inside the
block body the sidecar's ``signed_block_header`` names — the Merkle branch
from the commitment's leaf up to ``body_root``
(``verify_blob_sidecar_inclusion_proof``, deneb/p2p-interface.md).  The
branch has three segments, bottom-up:

1. ``log2(MAX_BLOB_COMMITMENTS_PER_BLOCK)`` siblings inside the
   commitments list's data tree (leaf = hash_tree_root of the Bytes48);
2. the list's length chunk (SSZ ``mix_in_length`` sibling);
3. ``ceil(log2(#body fields))`` siblings in the body's field tree.

For the Deneb body (12 fields → depth 4) this reproduces the spec depths
exactly: mainnet 12 + 1 + 4 = 17, minimal 4 + 1 + 4 = 9.
"""

from __future__ import annotations

import hashlib
from typing import List

from ..ops.merkle import ZERO_HASHES_BYTES


def _hash(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def _subtree_branch(leaves: List[bytes], depth: int,
                    index: int) -> List[bytes]:
    """Branch for ``leaves[index]`` in a zero-padded tree of ``depth``
    levels (virtual padding nodes at level l are ZERO_HASHES[l])."""
    branch = []
    level = list(leaves)
    idx = index
    for d in range(depth):
        sib = idx ^ 1
        branch.append(level[sib] if sib < len(level)
                      else ZERO_HASHES_BYTES[d])
        nxt = []
        for i in range(0, len(level), 2):
            left = level[i]
            right = level[i + 1] if i + 1 < len(level) \
                else ZERO_HASHES_BYTES[d]
            nxt.append(_hash(left, right))
        level = nxt or [ZERO_HASHES_BYTES[d + 1]]
        idx >>= 1
    return branch


def _body_field_roots(body) -> List[bytes]:
    cls = type(body)
    return [ftype.hash_tree_root(getattr(body, fname))
            for fname, ftype in cls.FIELDS.items()]


def _field_tree_depth(n_fields: int) -> int:
    d = 0
    while (1 << d) < n_fields:
        d += 1
    return d


def _commitment_leaf(commitment: bytes) -> bytes:
    """hash_tree_root of a Bytes48: two 32-byte chunks hashed."""
    c = bytes(commitment)
    return _hash(c[:32], c[32:] + b"\x00" * 16)


def blob_sidecar_inclusion_proof(body, index: int, preset) -> List[bytes]:
    """Build the branch for ``body.blob_kzg_commitments[index]``."""
    limit = preset.MAX_BLOB_COMMITMENTS_PER_BLOCK
    list_depth = _field_tree_depth(limit)
    commitments = list(body.blob_kzg_commitments)
    if not 0 <= index < len(commitments):
        raise IndexError("blob index outside the block's commitments")
    leaves = [_commitment_leaf(c) for c in commitments]
    branch = _subtree_branch(leaves, list_depth, index)
    branch.append(len(commitments).to_bytes(32, "little"))  # length chunk
    field_roots = _body_field_roots(body)
    field_idx = list(type(body).FIELDS).index("blob_kzg_commitments")
    branch.extend(_subtree_branch(field_roots,
                                  _field_tree_depth(len(field_roots)),
                                  field_idx))
    return branch


def verify_blob_sidecar_inclusion_proof(sidecar, preset) -> bool:
    """Fold the sidecar's branch from its commitment leaf up to the header
    body_root (spec ``verify_blob_sidecar_inclusion_proof``)."""
    limit = preset.MAX_BLOB_COMMITMENTS_PER_BLOCK
    list_depth = _field_tree_depth(limit)
    # 12 Deneb body fields; the commitments list is field index 11.
    field_idx, field_depth = 11, 4
    branch = [bytes(b) for b in sidecar.kzg_commitment_inclusion_proof]
    if len(branch) != list_depth + 1 + field_depth:
        return False
    # Bottom-up direction bits: blob index inside the list tree, then 0
    # (the data root is the LEFT child of the length mix-in), then the
    # field index inside the body tree.
    bits = [(int(sidecar.index) >> d) & 1 for d in range(list_depth)]
    bits.append(0)
    bits.extend((field_idx >> d) & 1 for d in range(field_depth))
    node = _commitment_leaf(sidecar.kzg_commitment)
    for bit, sib in zip(bits, branch):
        node = _hash(sib, node) if bit else _hash(node, sib)
    return node == bytes(sidecar.signed_block_header.message.body_root)
