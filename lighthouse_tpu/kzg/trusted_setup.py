"""KZG trusted setup: loader, embedded minimal setup, insecure generator.

A setup for blob width W is the ceremony output in Lagrange form:

- ``g1_lagrange[i] = [L_i(tau)]·G1`` for the Lagrange basis over the
  bit-reversal-ordered roots-of-unity domain (what commit/prove consume);
- ``g2_monomial = ([1]·G2, [tau]·G2)`` (what the verifier consumes — the
  verifier never touches the G1 side, so verification works without ever
  materializing the Lagrange points).

This environment has no network access to the real ceremony transcript
(``trusted_setup_4096.json``), so setups here are **derived from a fixed,
public tau** via :func:`generate_insecure_setup` — cryptographically
worthless for production (anyone knowing tau can forge proofs) but
structurally identical, which is what the framework needs: the verifier
code path is byte-for-byte the one a real ceremony file would drive
through :func:`load_trusted_setup`, and ``scripts/gen_trusted_setup.py``
regenerates/prints any width.  The minimal-preset setup (width 4) is
embedded below as hex so loading it exercises the real parser.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

from ..crypto import curve as C
from .fr import BLS_MODULUS, compute_roots_of_unity


class SetupError(ValueError):
    pass


# Fixed public tau for insecure (test/bench) setups: nothing-up-the-sleeve
# derivation, mirroring the interop secret-key convention.
INSECURE_TAU = int.from_bytes(
    __import__("hashlib").sha256(b"lighthouse-tpu insecure kzg tau").digest(),
    "big") % BLS_MODULUS


@dataclass
class TrustedSetup:
    """Parsed setup for one blob width.

    ``tau`` is present ONLY on insecure locally-generated setups (it lets
    tests/bench compute commitments with one scalar-mul instead of a
    width-sized MSM); a ceremony file loaded from disk has ``tau=None``
    and everything still works — just slower to commit with.
    """
    width: int
    g1_lagrange: List[Tuple[int, int]]          # affine G1, no identity
    g2_monomial: Tuple[object, object]          # ([1]G2, [tau]G2) affine
    tau: Optional[int] = None
    roots: List[int] = field(default_factory=list)

    def __post_init__(self):
        if not self.roots:
            self.roots = compute_roots_of_unity(self.width)


def generate_insecure_setup(width: int,
                            tau: int = INSECURE_TAU) -> TrustedSetup:
    """Powers-of-tau from a KNOWN tau (insecure by construction).

    Lagrange G1 points come from evaluating each basis polynomial at tau:
    ``L_i(tau) = (tau^W - 1)·ω_i / (W·(tau - ω_i))`` — one G1 scalar-mul
    per point, no FFT needed.
    """
    roots = compute_roots_of_unity(width)
    if tau % BLS_MODULUS in roots:
        raise SetupError("degenerate tau (lies in the evaluation domain)")
    zh = (pow(tau, width, BLS_MODULUS) - 1) % BLS_MODULUS  # tau^W - 1
    w_inv = pow(width, BLS_MODULUS - 2, BLS_MODULUS)
    g1 = []
    for w in roots:
        li = zh * w % BLS_MODULUS * w_inv % BLS_MODULUS \
            * pow(tau - w, BLS_MODULUS - 2, BLS_MODULUS) % BLS_MODULUS
        g1.append(C.g1_mul(C.G1_GEN, li))
    g2 = (C.G2_GEN, C.g2_mul(C.G2_GEN, tau))
    return TrustedSetup(width=width, g1_lagrange=g1, g2_monomial=g2,
                        tau=tau, roots=roots)


def verification_setup(width: int, tau: int = INSECURE_TAU) -> TrustedSetup:
    """Verifier-only setup: G2 points + roots, NO Lagrange G1 table.

    Verification never reads ``g1_lagrange``, so chains that only verify
    (the availability gate) skip the width-sized G1 generation entirely —
    this is what :class:`~..beacon_chain.data_availability
    .DataAvailabilityChecker` builds lazily.
    """
    return TrustedSetup(width=width, g1_lagrange=[],
                        g2_monomial=(C.G2_GEN, C.g2_mul(C.G2_GEN, tau)),
                        tau=tau)


def dump_trusted_setup(setup: TrustedSetup) -> str:
    """Serialize in the c-kzg-4844 JSON layout (``trusted_setup.json``)."""
    return json.dumps({
        "g1_lagrange": ["0x" + C.g1_compress(p).hex()
                        for p in setup.g1_lagrange],
        "g2_monomial": ["0x" + C.g2_compress(p).hex()
                        for p in setup.g2_monomial],
    }, indent=1)


def load_trusted_setup(source) -> TrustedSetup:
    """Parse a c-kzg-4844-style JSON setup (dict, JSON text, or path).

    Every point is decompressed AND subgroup-checked — a malformed or
    out-of-subgroup setup point would silently break the binding property,
    so it is a hard load-time error, not a verify-time surprise.
    """
    if isinstance(source, str):
        if source.lstrip().startswith("{"):
            raw = json.loads(source)
        else:
            with open(source) as f:
                raw = json.load(f)
    else:
        raw = dict(source)
    try:
        g1_hex = raw["g1_lagrange"]
        g2_hex = raw["g2_monomial"]
    except KeyError as e:
        raise SetupError(f"setup missing field {e}") from None
    if len(g2_hex) < 2:
        raise SetupError("setup needs [1]G2 and [tau]G2")
    width = len(g1_hex)
    if width == 0 or width & (width - 1):
        raise SetupError("g1_lagrange length must be a power of two")

    def _unhex(s: str) -> bytes:
        return bytes.fromhex(s[2:] if s.startswith("0x") else s)

    g1 = []
    for s in g1_hex:
        p = C.g1_decompress(_unhex(s))
        if p is None or not C.g1_subgroup_check(p):
            raise SetupError("G1 setup point fails subgroup check")
        g1.append(p)
    g2 = []
    for s in g2_hex[:2]:
        p = C.g2_decompress(_unhex(s))
        if p is None or not C.g2_subgroup_check(p):
            raise SetupError("G2 setup point fails subgroup check")
        g2.append(p)
    if g2[0] != C.G2_GEN:
        raise SetupError("g2_monomial[0] must be the G2 generator")
    return TrustedSetup(width=width, g1_lagrange=g1,
                        g2_monomial=(g2[0], g2[1]))


# ---------------------------------------------------------------------------
# Embedded minimal-preset setup (width 4), generated from INSECURE_TAU by
# scripts/gen_trusted_setup.py --width 4 — kept as JSON hex so loading it
# round-trips the real parser.  Regenerate with the script if the tau
# derivation or width changes; test_kzg pins the equality.
# ---------------------------------------------------------------------------

EMBEDDED_MINIMAL_JSON = """{
 "g1_lagrange": [
  "0x9621bb0d38c7ff042c8c291679fa5bc071e5336e3d45402b538d1a33a9761cbbd6531cad029faf0ef249345e670c311c",
  "0xa69a507e4931d6863761bce20c3b0654273ed30c361a70b6f6bfdfffc2d5b01149a4697f58538cadd558994c210132ed",
  "0x922092e132540848e2cda5f95641b4ddf4ea8e6fd512f50c80df4fbc544fb1f2b08f1e3aebdc6da28dcd29b1db3539ac",
  "0xa86554cbecdc0c30a88f8e895f5af0293ce41e06d3ee485ae1751d5110c07c2a2a041d25baa011dc7a5a68abe94e3192"
 ],
 "g2_monomial": [
  "0x93e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc7f5049334cf11213945d57e5ac7d055d042b7e024aa2b2f08f0a91260805272dc51051c6e47ad4fa403b02b4510b647ae3d1770bac0326a805bbefd48056c8c121bdb8",
  "0xab4d4e98e57ed98a1016bc1426322471c951026ee32c9521e8a042c794880ad4423b1d608fe216e2b5746989c6a36e4806ebb6238a1eecead93692332eb81b6d496b5f8977b9d9a0e898db6c6f4c381e5cd6552d12c5c1dddba08700b125a6d9"
 ]
}"""


@lru_cache(maxsize=8)
def embedded_setup(width: int) -> TrustedSetup:
    """The framework's canonical insecure setup for ``width``: parsed from
    the embedded JSON when one is checked in for that width (exercising
    the real loader; test_kzg pins the JSON against regeneration from
    INSECURE_TAU), generated from INSECURE_TAU otherwise."""
    if width == 4:
        setup = load_trusted_setup(EMBEDDED_MINIMAL_JSON)
        setup.tau = INSECURE_TAU
        return setup
    return generate_insecure_setup(width)
