"""Pallas TPU kernel for the big SSZ Merkle reductions.

This is the production hot path for registry-scale ``hash_tree_root`` — the
workload the reference parallelises with rayon over 4096-validator arenas
(``/root/reference/consensus/types/src/beacon_state/tree_hash_cache.rs:25-33,
535-556``) and we map onto the VPU as a single fused kernel.

Why a kernel at all: the pure-XLA reduction (:mod:`lighthouse_tpu.ops.merkle`)
rolls the 64 SHA-256 rounds with ``lax.scan``, which materialises the carry to
HBM every round — the whole reduction is HBM-bound (~90 ms on-device for 2^21
leaves).  Here the full 64 rounds ×2 compressions ×``chunk_log2`` tree levels
are unrolled inside one Pallas program, so a chunk's entire sub-tree reduces
in VMEM/registers with exactly one HBM read of the leaves and one 32-byte
write per chunk root (~6 ms on-device for the same tree — ~3 ns/hash,
~13x a single SHA-NI core's ~40 ns/hash).

Layout: digests live as 8 *word planes* — ``planes[w][i]`` = word ``w`` of
digest ``i`` — so every SHA op is a full-width elementwise vector op with the
digest index on the vector lanes (the structure-of-arrays twin of the
registry's SoA columns).

Pairing trick: Mosaic has no strided (de-interleave) lane access, so a level
cannot pair lanes ``(2i, 2i+1)``.  Instead each chunk's leaves are stored in
**bit-reversed order**, which turns the standard adjacent-pairs tree into the
*halves* tree: level ``m`` pairs lane ``i`` with lane ``i + m/2`` — two
contiguous slices, zero shuffles.  Chunks themselves stay in natural order
(a contiguous chunk is exactly an SSZ sub-tree), so only the cheap
within-chunk permutation (one device gather, ~1 ms at 2^21) is ever applied,
and the cross-chunk tail pairs naturally via :func:`..ops.merkle.merkleize`.
"""

from __future__ import annotations

import time
from functools import lru_cache, partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .sha256 import _IV, _K, _PAD64_KW, _rotr

U32 = np.uint32

# Default chunk: 2^15 leaves = 1 MiB of VMEM per input block; 15 unrolled
# levels keep the kernel within Mosaic's scoped-VMEM budget (2^16 overflows).
CHUNK_LOG2 = 15


def compress_data_block(state, block16):
    """One SHA-256 compression, fully unrolled, message schedule computed
    on the fly in a rolling 16-word window (keeps ≤24 live vectors — the
    upfront 64-entry schedule blows VMEM at wide lanes).

    ``state``: 8-sequence of same-shaped u32 arrays; ``block16``: 16-sequence.
    """
    a, b, c, d, e, f, g, h = state
    w = list(block16)
    for i in range(64):
        if i < 16:
            wi = w[i]
        else:
            x15, x2 = w[(i - 15) % 16], w[(i - 2) % 16]
            s0 = _rotr(x15, 7) ^ _rotr(x15, 18) ^ (x15 >> U32(3))
            s1 = _rotr(x2, 17) ^ _rotr(x2, 19) ^ (x2 >> U32(10))
            wi = w[i % 16] + s0 + w[(i - 7) % 16] + s1
            w[i % 16] = wi
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + wi + U32(_K[i])
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + s0 + maj
    return tuple(x + y for x, y in zip(state, (a, b, c, d, e, f, g, h)))


def compress_const_block(state, kw):
    """Compression against a constant block whose W+K schedule is
    precomputed (``kw``: 64 scalars) — the fixed padding block of a 64-byte
    message."""
    a, b, c, d, e, f, g, h = state
    for i in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kw[i]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + s0 + maj
    return tuple(x + y for x, y in zip(state, (a, b, c, d, e, f, g, h)))


_PAD64_KW_SCALARS = [U32(v) for v in _PAD64_KW]


def hash64_planes(left, right):
    """``hash32_concat`` over word planes: 8+8 same-shaped u32 arrays in,
    8 out.  Semantics match :func:`..ops.sha256.hash64`."""
    block = list(left) + list(right)
    shp = left[0].shape
    iv = tuple(jnp.full(shp, v, dtype=jnp.uint32) for v in _IV)
    mid = compress_data_block(iv, block)
    return list(compress_const_block(mid, _PAD64_KW_SCALARS))


def _halves_reduce(planes, levels: int):
    """The shared reduction body: ``levels`` rounds of halves pairing over
    2-D ``(1, m)`` word planes in bit-reversed leaf order.

    Used verbatim inside the Pallas kernel AND by the pure-XLA reference
    path, so CPU tests exercise the exact arithmetic the kernel compiles.
    """
    m = planes[0].shape[1]
    for _ in range(levels):
        m //= 2
        left = [p[:, :m] for p in planes]
        right = [p[:, m:] for p in planes]
        planes = hash64_planes(left, right)
    return planes


def _subtree_kernel(in_ref, out_ref, *, levels: int):
    """Reduce one chunk (bit-reversed leaf order) to its sub-tree root.

    ``in_ref``: ``(8, 2^levels)`` u32 word planes; ``out_ref``: ``(G, 8)``
    full output array — each grid cell writes its own row.
    """
    planes = _halves_reduce([in_ref[w:w + 1, :] for w in range(8)], levels)
    i = pl.program_id(0)
    out_ref[pl.ds(i, 1), :] = jnp.concatenate(planes, axis=1)


def chunk_roots(planes: jnp.ndarray, chunk_log2: int = CHUNK_LOG2,
                use_kernel: bool | None = None) -> jnp.ndarray:
    """Sub-tree roots of every ``2^chunk_log2``-leaf chunk.

    ``planes``: ``(8, n)`` u32 word planes, leaves bit-reversed *within* each
    chunk (see :func:`brev_indices`), chunks in natural order.  Returns
    ``(n / 2^chunk_log2, 8)`` u32 chunk roots (digests-major).

    ``use_kernel``: force the Pallas kernel (True) or the pure-XLA shared
    body (False); default picks the kernel off-CPU.  (Pallas interpret mode
    takes minutes to emulate one compression, so CPU tests run the shared
    body directly — same arithmetic, same pairing.)
    """
    n = planes.shape[1]
    c = 1 << chunk_log2
    if n % c or n < c:
        raise ValueError(f"{n} leaves not a multiple of chunk {c}")
    g = n // c
    if use_kernel is None:
        use_kernel = _use_pallas()
    if not use_kernel:
        grouped = planes.reshape(8, g, c)
        cols = _halves_reduce(
            [grouped[w] for w in range(8)], chunk_log2)  # 8 x (g, 1)
        return jnp.concatenate(cols, axis=1)  # (g, 8)
    return pl.pallas_call(
        partial(_subtree_kernel, levels=chunk_log2),
        grid=(g,),
        in_specs=[pl.BlockSpec((8, c), lambda i: (0, i),
                               memory_space=pltpu.VMEM)],
        # One resident output block; each cell stores one row.  (Per-cell
        # (8, 1) column blocks violate Mosaic's lane-divisibility rule and
        # dynamic column stores crash its vector_store lowering.)
        out_specs=pl.BlockSpec((g, 8), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((g, 8), jnp.uint32),
    )(planes)


def _hash64_pallas_kernel(l_ref, r_ref, o_ref):
    left = [l_ref[w:w + 1, :] for w in range(8)]
    right = [r_ref[w:w + 1, :] for w in range(8)]
    o_ref[:] = jnp.concatenate(hash64_planes(left, right), axis=0)


def hash64_pallas(left: jnp.ndarray, right: jnp.ndarray,
                  block_log2: int = 15) -> jnp.ndarray:
    """``hash64`` as a Pallas kernel over word planes: (n, 8) pairs →
    (n, 8) digests with the two compressions fully unrolled in VMEM (the
    XLA-scan ``hash64`` round-trips its 24-word working set through HBM
    every round — ~10× slower at registry widths)."""
    n = left.shape[0]
    b = 1 << block_log2
    if n % b:
        raise ValueError(f"lane count {n} not a multiple of {b}")
    g = n // b
    lp = left.T
    rp = right.T
    out = pl.pallas_call(
        _hash64_pallas_kernel,
        grid=(g,),
        in_specs=[pl.BlockSpec((8, b), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((8, b), lambda i: (0, i),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((8, b), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, n), jnp.uint32),
    )(lp, rp)
    return out.T


def _levels_body(leaves: jnp.ndarray, *, use_kernel: bool):
    """All tree levels over ``(w, 8)`` u32 leaves (w a power of two), as one
    traced program: Pallas hash64 for the wide levels, XLA for the tail.
    Returns ``(levels...)`` with ``levels[0] = leaves``, ``levels[-1]``
    the ``(1, 8)`` subtree root."""
    from .sha256 import hash64 as hash64_xla

    pb = 1 << 15

    def h64(a, b):
        if use_kernel and a.shape[0] >= pb and a.shape[0] % pb == 0:
            return hash64_pallas(a, b)
        return hash64_xla(a, b)

    levels = [leaves]
    cur = leaves
    while cur.shape[0] > 1:
        cur = h64(cur[0::2], cur[1::2])
        levels.append(cur)
    return tuple(levels)


_levels_device_jit = None

# H2D streaming granularity for big leaf pushes: 2^18 rows = 8 MiB per
# chunk at (rows, 8) u32.  Overridable (0 disables chunking) via
# LIGHTHOUSE_TPU_PUSH_CHUNK_ROWS.
PUSH_CHUNK_ROWS = 1 << 18

# Accumulated stats of chunked device builds since the last
# :func:`reset_push_stats` (a cold state root runs one build per big
# field, so totals are what bench.py surfaces as ``leaf_push_*``):
# wait_ms is the transfer time left on the critical path, overlap_ms
# the transfer time hidden behind compute.
LAST_PUSH_STATS: dict = {}


def reset_push_stats() -> None:
    LAST_PUSH_STATS.clear()


def _push_chunk_rows() -> int:
    """The env knob, rounded DOWN to a power of two so it always
    divides the (power-of-two) leaf widths — a non-divisor value must
    tune the pipeline, not silently disable it.  ≤ 0 disables."""
    from ..common.knobs import knob_int
    rows = knob_int("LIGHTHOUSE_TPU_PUSH_CHUNK_ROWS",
                    default=PUSH_CHUNK_ROWS)
    return 1 << (rows.bit_length() - 1) if rows > 0 else 0


def _get_levels_jit():
    global _levels_device_jit
    if _levels_device_jit is None:
        _levels_device_jit = jax.jit(_levels_body,
                                     static_argnames=("use_kernel",))
    return _levels_device_jit


def merkle_levels_device(leaves: np.ndarray, chunk_rows: int | None = None):
    """Compute every tree level of ``(w, 8)`` leaves on-device and return
    ``(root_words, device_levels)`` — the root pulled immediately
    (32 bytes), the levels left device-resident for the caller to pull
    lazily (the axon tunnel pulls ~11 MB/s; eager per-level pulls are
    what made the r3 cold state root take minutes).

    Wide builds stream the leaves in ``chunk_rows``-row column chunks
    through a background :class:`~lighthouse_tpu.parallel.pipeline.
    ChunkStager`: chunk i+1 transfers while chunk i's sub-tree levels
    already reduce on the device (the level-pull machinery in reverse),
    so the monolithic blocking push disappears from the critical path.
    The chunk sub-tree levels concat per level into the SAME full-tree
    levels the monolithic path produces, then the chunk roots reduce to
    the top — bit-identical output, tested off-device."""
    leaves = np.ascontiguousarray(leaves).astype(np.uint32, copy=False)
    w = leaves.shape[0]
    chunk = _push_chunk_rows() if chunk_rows is None else chunk_rows
    jit = _get_levels_jit()
    use_kernel = _use_pallas()
    if chunk <= 0 or w <= chunk or w % chunk:
        from ..common.device_ledger import LEDGER
        LEDGER.note_transfer("h2d", leaves.nbytes, subsystem="staging")
        dev = jax.device_put(leaves)  # device-io: staging
        levels = jit(dev, use_kernel=use_kernel)
        return np.asarray(levels[-1])[0], levels  # device-io: staging

    from ..parallel.pipeline import ChunkStager

    t0 = time.perf_counter()
    n_chunks = w // chunk
    stager = ChunkStager([leaves[i * chunk:(i + 1) * chunk]
                          for i in range(n_chunks)])
    parts = [jit(dev, use_kernel=use_kernel) for dev in stager]
    # Level l of the full tree is the in-order concat of the chunks'
    # level l (a contiguous chunk is exactly a sub-tree); above the
    # chunk roots the tail reduces as its own (tiny) levels program.
    levels = [jnp.concatenate([p[l] for p in parts], axis=0)
              for l in range(len(parts[0]))]
    tail = jit(levels[-1], use_kernel=use_kernel)
    levels.extend(tail[1:])
    root = np.asarray(levels[-1])[0]  # device-io: staging
    for key, add in (
            ("builds", 1), ("chunks", n_chunks),
            ("staging_fallbacks", stager.fallbacks),
            ("wait_ms", round(stager.wait_s * 1e3, 1)),
            ("overlap_ms", round(
                max(stager.transfer_s - stager.wait_s, 0.0) * 1e3, 1)),
            ("wall_ms", round((time.perf_counter() - t0) * 1e3, 1))):
        LAST_PUSH_STATS[key] = round(LAST_PUSH_STATS.get(key, 0) + add, 1)
    return root, tuple(levels)


@lru_cache(maxsize=8)
def brev_indices(chunk_log2: int) -> np.ndarray:
    """``(2^chunk_log2,) int32``: bit-reversal permutation of chunk slots.

    Self-inverse: ``x[brev] `` both applies and undoes the layout.
    """
    c = 1 << chunk_log2
    idx = np.arange(c, dtype=np.uint32)
    out = np.zeros(c, dtype=np.int32)
    for b in range(chunk_log2):
        out |= (((idx >> b) & 1) << (chunk_log2 - 1 - b)).astype(np.int32)
    return out


def _use_pallas() -> bool:
    """Real kernel only where Mosaic can lower it (TPU — the axon tunnel
    also reports ``tpu``); everything else takes the XLA/host paths."""
    return jax.default_backend() == "tpu"


def _chunk_roots_natural_impl(leaves: jnp.ndarray, chunk_log2: int,  # device-io: staging
                              use_kernel: bool) -> jnp.ndarray:
    n = leaves.shape[0]
    c = 1 << chunk_log2
    planes = leaves.T  # (8, n)
    brev = jnp.asarray(brev_indices(chunk_log2))
    planes = planes.reshape(8, n // c, c)[:, :, brev].reshape(8, n)
    return chunk_roots(planes, chunk_log2, use_kernel=use_kernel)  # (g, 8)


chunk_roots_natural = partial(jax.jit, static_argnames=(
    "chunk_log2", "use_kernel"))(_chunk_roots_natural_impl)
"""Jitted device pipeline: natural-order ``(n, 8)`` leaves → ``(g, 8)``
chunk sub-tree roots (transpose → within-chunk brev gather → kernel)."""


def merkle_root_chunked(leaves, depth: int,
                        chunk_log2: int = CHUNK_LOG2,
                        use_kernel: bool | None = None) -> np.ndarray:
    """Root of a depth-``depth`` padded tree over ``(n, 8)`` u32 leaves in
    natural order, ``n`` a power of two ≥ the chunk size.  Returns ``(8,)``
    u32 root words on the host.

    Split: the ``n → n/2^chunk_log2`` reduction (99.99% of the hashes) runs
    on-device in one dispatch; the remaining ~``log2(g) + depth - log2(n)``
    single-hash levels run on the host — a few dozen sequential 64-byte
    hashes cost microseconds on CPU but dominate dispatch-bound device time
    as a chain of one-element launches.  (On CPU the device part runs the
    shared body eagerly — XLA-CPU takes minutes to compile the ~1.5k-op
    unrolled compression chain that Mosaic handles in seconds.)
    """
    from .merkle import merkleize_auto

    n = leaves.shape[0]
    if n & (n - 1):
        raise ValueError("pad leaf count to a power of two first")
    c = 1 << chunk_log2
    if n < c:
        raise ValueError(f"use merkleize() below {c} leaves")
    if (n - 1).bit_length() > depth:
        raise ValueError(f"{n} leaves overflow a depth-{depth} tree")
    if use_kernel is None:
        use_kernel = _use_pallas()
    if use_kernel:
        roots = np.asarray(chunk_roots_natural(
            leaves, chunk_log2=chunk_log2, use_kernel=True))
    else:
        roots = np.asarray(_chunk_roots_natural_impl(  # device-io: staging
            jnp.asarray(leaves), chunk_log2, False))  # device-io: staging
    # Tail: a few dozen single-hash levels — host dispatch via merkleize_auto
    # (a chain of one-element device launches would be dispatch-bound).
    return merkleize_auto(roots, depth, base_level=chunk_log2)
