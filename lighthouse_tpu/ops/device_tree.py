"""Device-resident incremental Merkle trees — zero-push warm roots.

The host :class:`~lighthouse_tpu.ops.tree_cache.IncrementalMerkleCache`
stores every interior level in host numpy and either walks dirty paths with
hashlib or re-pushes the whole leaf set for a device rebuild.  That design
made the *cold* state root 9.2 s of which 5.1 s was one monolithic H2D push
(``state_root_cold_push_ms``) — the state lived on host and was re-staged
for every device pass.  Here the tree levels live in HBM as the source of
truth (the MTU tree-unit shape, arXiv:2507.16793: the whole hash-tree
reduction stays on the accelerator) and a warm root is

    H2D:  k dirty leaf rows (+ their int32 indices)       — bytes ∝ dirty
    one fused program: leaf scatter → per-level re-hash   — k·log n hashes
    D2H:  32 bytes of root

so the full-state push disappears from the warm path instead of merely
being overlapped.  Donation follows the
:class:`~lighthouse_tpu.parallel.pipeline.StagedExecutor` idiom: when a
tree owns its buffers exclusively the update program donates them (true
in-place HBM update); after :meth:`DeviceTree.share` (fork-choice
state-cache clones, ``BeaconState.copy``) the next update runs undonated —
XLA materialises fresh buffers for the mutator and the sibling keeps the
old ones untouched: copy-on-write without duplicating HBM at clone time.

Dirty-index batches are padded to power-of-two buckets so the number of
compiled program shapes stays logarithmic in the update size; padding
duplicates a real (index, row) pair, which is idempotent under both the
scatter and the re-hash.
"""

from __future__ import annotations

from collections.abc import Mapping

import numpy as np

from ..common.device_ledger import LEDGER
from .merkle import _next_pow2

# Byte accounting for the residency story (surfaced by bench.py as
# ``state_root_device_resident``): every host→device transfer made on
# behalf of device-resident state goes through note_push, every pull of a
# lazily-materialised host view through note_pull.  Since the device
# ledger landed these route into :data:`~lighthouse_tpu.common.
# device_ledger.LEDGER` with the caller's ambient subsystem attribution
# (``device_tree`` when no seam set one), and ``RESIDENCY_STATS`` is a
# ledger-backed VIEW summing exactly its historical feeders — the
# tree/registry/packed/fork-choice residency paths.  BLS/KZG/slasher/
# staging traffic (newly accounted) is visible only through the ledger,
# so every pre-ledger reader keeps its numbers.
# Public: the view's feeder set (bench.py and the residency scripts
# import this — ONE definition, not three drifting copies).
LEGACY_RESIDENCY_SUBSYSTEMS = ("device_tree", "registry_mirror",
                               "packed_cache", "fork_choice")
_LEGACY_SUBSYSTEMS = LEGACY_RESIDENCY_SUBSYSTEMS
_LEGACY_KEYS = {
    "bytes_pushed": "h2d_bytes",
    "bytes_pulled": "d2h_bytes",
    "scatters": "scatters",
    "rebuilds": "rebuilds",
    "materializes": "materializes",
}


class _ResidencyView(Mapping):
    """Read-only legacy view over the ledger (reset = re-base, so the
    ledger itself stays monotonic for Prometheus and the per-slot delta
    ring)."""

    def __init__(self):
        self._base: dict = {}

    def _totals(self) -> dict:
        return LEDGER.subsystem_totals(_LEGACY_SUBSYSTEMS)

    def rebase(self) -> None:
        t = self._totals()
        self._base = {k: t[lk] for k, lk in _LEGACY_KEYS.items()}

    def __getitem__(self, key: str) -> int:
        t = self._totals()[_LEGACY_KEYS[key]]
        return max(int(t - self._base.get(key, 0)), 0)

    def __iter__(self):
        return iter(_LEGACY_KEYS)

    def __len__(self) -> int:
        return len(_LEGACY_KEYS)

    def __repr__(self) -> str:
        return f"ResidencyView({dict(self)})"


RESIDENCY_STATS = _ResidencyView()


def reset_residency_stats() -> None:
    RESIDENCY_STATS.rebase()


def note_push(nbytes: int) -> None:
    LEDGER.note_transfer("h2d", nbytes)


def note_pull(nbytes: int) -> None:
    LEDGER.note_transfer("d2h", nbytes)


def residency_snapshot() -> dict:
    # One totals pass, not one per key (this runs on the traced block-
    # import path via Tracer.residency_mark/record_residency).
    t = RESIDENCY_STATS._totals()
    base = RESIDENCY_STATS._base
    return {k: max(int(t[lk] - base.get(k, 0)), 0)
            for k, lk in _LEGACY_KEYS.items()}


def _donation_works() -> bool:
    """Donate buffers only where XLA honors it (TPU); on CPU jax ignores
    donation with a warning per call — the undonated program is identical
    apart from the in-place aliasing."""
    import jax
    return jax.default_backend() == "tpu"


def _bucket(k: int) -> int:
    """Dirty-batch size bucket: power of two ≥ 8 bounds the number of
    compiled shapes to ~log(max batch) (the ``tree_dirty`` family's
    registered bucket floor)."""
    from ..parallel.mesh import bucket_rows
    return bucket_rows("tree_dirty", k)


def pad_bucket(idx: np.ndarray, rows: np.ndarray) -> tuple:
    """Pad ``(k,)`` indices / ``(k, …)`` rows to the bucket size by
    repeating the first entry — idempotent under scatter + re-hash."""
    k = idx.shape[0]
    b = _bucket(k)
    if k == b:
        return idx.astype(np.int32, copy=False), rows
    pidx = np.empty(b, dtype=np.int32)
    pidx[:k] = idx
    pidx[k:] = idx[0]
    prows = np.empty((b,) + rows.shape[1:], dtype=rows.dtype)
    prows[:k] = rows
    prows[k:] = rows[0]
    return pidx, prows


def scatter_propagate_body(levels, idx, rows):
    """The fused warm-root body: scatter ``rows`` into ``levels[0]`` at
    ``idx`` and re-hash exactly the touched ancestor path of every index
    up every level.  Duplicate indices (bucket padding) recompute the same
    parent with the same inputs — wasted lanes, never wrong bits.

    Shared verbatim by the packed-column trees and the registry mirror
    (which feeds record-mini-tree roots as ``rows``), so one compiled
    artifact per (bucket, width) covers both.
    """
    from .sha256 import hash64

    out = [levels[0].at[idx].set(rows)]
    cur = idx
    for lvl in range(1, len(levels)):
        cur = cur >> 1
        below = out[-1]
        h = hash64(below[2 * cur], below[2 * cur + 1])
        out.append(levels[lvl].at[cur].set(h))
    return tuple(out)


_scatter_jit = None
_scatter_jit_donated = None


def _get_scatter_jit(donate: bool):
    global _scatter_jit, _scatter_jit_donated
    import jax
    if donate:
        if _scatter_jit_donated is None:
            _scatter_jit_donated = jax.jit(scatter_propagate_body,
                                           donate_argnums=(0,))
        return _scatter_jit_donated
    if _scatter_jit is None:
        _scatter_jit = jax.jit(scatter_propagate_body)
    return _scatter_jit


def _levels_body(leaves, *, use_kernel: bool):
    """All levels over ``(w, 8)`` u32 leaves (w pow2) — the same body as
    :func:`..ops.merkle_kernel._levels_body`, re-exported here so the
    device-resident rebuild path has no import-order coupling with the
    Pallas module's jit singletons."""
    from .merkle_kernel import _levels_body as body
    return body(leaves, use_kernel=use_kernel)


_levels_jit = None


def _get_levels_jit():
    global _levels_jit
    import jax
    if _levels_jit is None:
        _levels_jit = jax.jit(_levels_body, static_argnames=("use_kernel",))
    return _levels_jit


def _use_kernel() -> bool:
    from .merkle_kernel import _use_pallas
    return _use_pallas()


def _build_levels(leaves_dev):
    """Every tree level from device-resident leaves: the sharded mesh
    program when the process mesh has >1 shard and the width divides it
    (leaf ranges sharded, top ``log2(ndev)`` levels past the shard
    boundary), else the 1-device fused body — bit-identical stacks."""
    from ..parallel import mesh as pmesh
    if pmesh.axis_size() > 1:
        from ..parallel.merkle_shard import sharded_tree_levels
        levels = sharded_tree_levels(
            leaves_dev, pmesh.get_mesh(), use_kernel=_use_kernel())
        if levels is not None:
            return levels
    return _get_levels_jit()(leaves_dev, use_kernel=_use_kernel())


class DeviceTree:
    """One padded Merkle tree whose every level lives on the device.

    ``levels[0]`` is the ``(w, 8)`` u32 leaf plane (w a power of two),
    ``levels[-1]`` the ``(1, 8)`` subtree root.  Zero-cap folding up to the
    SSZ limit and the length mixin stay host-side (≤ ~40 single hashes),
    exactly like the host cache.
    """

    __slots__ = ("levels", "shared", "_res", "__weakref__")

    def __init__(self, levels, shared: bool = False):
        self.levels = tuple(levels)
        self.shared = shared
        # Residency token created lazily at the first accounting seam:
        # a share() clone holds no token (the parent owns the shared
        # buffers) until its first mutation lands in fresh buffers.
        self._res = None

    def note_residency(self) -> None:
        """Update this tree's HBM-resident byte contribution under the
        ambient ledger attribution (creates the token + its GC drop
        seam on first call)."""
        total = sum(int(lv.nbytes) for lv in self.levels)
        if self._res is None:
            self._res = LEDGER.track(
                self, LEDGER.ambient() or "device_tree", total)
        else:
            self._res.set(total)

    # -- construction --------------------------------------------------------

    @classmethod
    def from_host_leaves(cls, leaves: np.ndarray) -> "DeviceTree":
        """One-time materialization: place the full (w, 8) leaf plane
        through the mesh seam (sharded over ``batch`` when the process
        mesh has >1 shard) and reduce every level on-device.  The ONLY
        full-width push this tree ever makes."""
        from ..parallel.mesh import mesh_put
        leaves = np.ascontiguousarray(leaves, dtype=np.uint32)
        assert leaves.shape[0] == _next_pow2(leaves.shape[0])
        LEDGER.note_event("materializes")
        dev = mesh_put("tree_leaves", leaves)
        tree = cls(_build_levels(dev))
        tree.note_residency()
        return tree

    @classmethod
    def from_device_leaves(cls, leaves) -> "DeviceTree":
        """Rebuild from leaves already resident in HBM — zero push."""
        LEDGER.note_event("rebuilds")
        tree = cls(_build_levels(leaves))
        tree.note_residency()
        return tree

    # -- queries -------------------------------------------------------------

    @property
    def width(self) -> int:
        return self.levels[0].shape[0]

    def root_words(self) -> np.ndarray:
        # 32-byte root read: reviewed seam, deliberately unaccounted.
        return np.asarray(self.levels[-1])[0]  # device-io: device_tree

    def pull_levels(self) -> list:
        """Host copies of every level (de-materialization / oracle)."""
        from ..parallel.mesh import mesh_gather
        return [mesh_gather(lv, name="tree_leaves")
                for lv in self.levels]

    # -- updates -------------------------------------------------------------

    def scatter(self, idx: np.ndarray, rows: np.ndarray) -> np.ndarray:
        """Warm update: ``rows`` (k, 8) u32 replace leaves at ``idx``
        (ascending, unique); returns the new subtree root words.  H2D is
        the bucket-padded (idx, rows) pair only (the replicated
        ``tree_dirty`` mesh family)."""
        if idx.size == 0:
            return self.root_words()
        from ..parallel.mesh import mesh_put
        pidx, prows = pad_bucket(np.asarray(idx),
                                 np.ascontiguousarray(rows, dtype=np.uint32))
        LEDGER.note_event("scatters")
        jit = _get_scatter_jit(_donation_works() and not self.shared)
        self.levels = jit(self.levels, mesh_put("tree_dirty", pidx),
                          mesh_put("tree_dirty", prows))
        self.shared = False  # the update produced buffers only we hold
        self.note_residency()
        return self.root_words()

    def scatter_device(self, idx_dev, rows_dev) -> np.ndarray:
        """Scatter with (idx, rows) already device-resident (registry
        mirror path) — zero push here; the caller accounted its own."""
        LEDGER.note_event("scatters")
        jit = _get_scatter_jit(_donation_works() and not self.shared)
        self.levels = jit(self.levels, idx_dev, rows_dev)
        self.shared = False
        self.note_residency()
        return self.root_words()

    def rebuild_device(self, leaves) -> np.ndarray:
        """Replace every level from device-resident leaves (dirty fraction
        past the walk/rebuild crossover, or width growth) — zero push."""
        LEDGER.note_event("rebuilds")
        self.levels = _build_levels(leaves)
        self.shared = False
        self.note_residency()
        return self.root_words()

    # -- copy-on-write -------------------------------------------------------

    def share(self) -> "DeviceTree":
        """COW clone: both trees reference the same HBM until either
        mutates (jax arrays are immutable; the next update simply skips
        donation and lands in fresh buffers)."""
        self.shared = True
        return DeviceTree(self.levels, shared=True)


def warmup_scatter(width: int, ks=(1, 8, 64), depth_only: bool = False) -> int:
    """Pre-compile the dirty-propagation program for a ``width``-leaf tree
    at the given dirty-batch bucket sizes (plus the full-levels rebuild
    body) so a fresh node's first warm root is a compile-cache hit.
    Returns the number of programs driven."""
    import jax

    w = _next_pow2(max(width, 1))
    leaves = np.zeros((w, 8), dtype=np.uint32)
    tree = DeviceTree.from_host_leaves(leaves)
    n = 1 if depth_only else 0
    done = set()
    for k in ks:
        b = _bucket(min(k, w))
        if b in done or b > w:
            continue
        done.add(b)
        idx = np.arange(b, dtype=np.int32) % w
        rows = np.zeros((b, 8), dtype=np.uint32)
        tree.scatter(np.unique(idx), rows[:np.unique(idx).shape[0]])
        n += 1
    jax.block_until_ready(tree.levels)
    return n + 1
