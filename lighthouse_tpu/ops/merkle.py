"""Device-side SSZ Merkleization: batched binary-tree SHA-256 reduction.

TPU counterpart of the reference's ``consensus/tree_hash`` streaming
``MerkleHasher`` (``/root/reference/consensus/tree_hash/src/merkle_hasher.rs``)
and the padded ``merkleize_padded``.  Where the reference folds one leaf at a
time through per-level SHA contexts to minimise allocation, a TPU wants the
opposite shape: the *whole level* hashed as one batched ``hash64`` launch,
level by level, with XLA fusing the 128 compression rounds across the lane
dimension.  Zero-subtree padding uses the same precomputed zero-hash table as
the reference (``/root/reference/crypto/eth2_hashing/src/lib.rs:205-217``,
``ZERO_HASHES`` to depth 48).

Leaves are ``(n, 8)`` uint32 arrays (32-byte chunks as big-endian words).
"""

from __future__ import annotations

import hashlib
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from .sha256 import hash64, bytes_to_words, words_to_bytes

MAX_TREE_DEPTH = 64

# ZERO_HASHES[i] = root of a depth-i tree of zero leaves.
_zh = [b"\x00" * 32]
for _ in range(MAX_TREE_DEPTH):
    _zh.append(hashlib.sha256(_zh[-1] + _zh[-1]).digest())
ZERO_HASHES = np.stack([bytes_to_words(h) for h in _zh])  # (65, 8) uint32
ZERO_HASHES_BYTES = list(_zh)


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


# Below this many nodes a batch is dispatched on the host: per-launch overhead
# (and per-shape XLA compiles) dwarf the hash work, mirroring how the
# reference only parallelises the big trees (``tree_hash_cache.rs:25-33``).
HOST_DISPATCH_THRESHOLD = 4096


def hash64_host_words(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    """Host hashlib counterpart of :func:`hash64` over ``(n, 8)`` u32 words.

    One interleaved buffer + one conversion pass: this sits on the per-slot
    incremental-root path (~150 calls/root), where the per-call numpy
    marshalling used to cost more than the hashing itself.
    """
    shape = left.shape
    l2 = left.reshape(-1, 8)
    r2 = right.reshape(-1, 8)
    n = l2.shape[0]
    buf = np.empty((n, 16), dtype=np.uint32)
    buf[:, :8] = l2
    buf[:, 8:] = r2
    msgs = buf.astype(">u4", copy=False).tobytes()
    out = bytearray(32 * n)
    sha256 = hashlib.sha256
    for i in range(n):
        out[32 * i:32 * i + 32] = sha256(msgs[64 * i:64 * i + 64]).digest()
    return (np.frombuffer(bytes(out), dtype=">u4").astype(np.uint32)
            .reshape(shape))


@partial(jax.jit, static_argnames=("depth", "base_level"))
def merkleize(leaves: jnp.ndarray, depth: int, base_level: int = 0) -> jnp.ndarray:
    """Root of a depth-``depth`` tree over ``leaves`` ``(n, 8)`` u32, n = 2^k ≤ 2^depth.

    The first ``ceil_log2(n)`` levels reduce the real leaves; remaining levels
    combine with the constant zero-hash of that level (the standard
    ``merkleize_padded`` trick — no materialised padding).

    ``base_level``: tree level the input nodes already sit at (0 = 32-byte
    chunks).  Non-zero when reducing subtree roots produced elsewhere — e.g.
    per-device partial roots in the sharded reduction
    (:mod:`lighthouse_tpu.parallel.merkle_shard`) — so that zero-subtree
    padding uses the correct ``ZERO_HASHES`` entries.  ``depth`` remains the
    *total* tree depth counted from level 0.
    """
    n = leaves.shape[0]
    assert n == _next_pow2(n), "pad leaf count to a power of two first"
    assert base_level + (n - 1).bit_length() <= depth, \
        f"{n} nodes at level {base_level} overflow a depth-{depth} tree"
    level = leaves
    lvl = base_level
    while level.shape[0] > 1:
        level = hash64(level[0::2], level[1::2])
        lvl += 1
    root = level[0]
    while lvl < depth:
        root = hash64(root, jnp.asarray(ZERO_HASHES[lvl]))
        lvl += 1
    return root


def merkleize_auto(leaves: np.ndarray, depth: int,
                   base_level: int = 0) -> np.ndarray:
    """:func:`merkleize` with host dispatch for small batches.

    Same contract (power-of-two ``(n, 8)`` u32 leaves, total tree ``depth``);
    returns an ``(8,)`` u32 root on whichever backend ran.
    """
    n = leaves.shape[0]
    assert n == _next_pow2(n), "pad leaf count to a power of two first"
    if n > HOST_DISPATCH_THRESHOLD:
        return np.asarray(merkleize(jnp.asarray(leaves), depth, base_level))
    level = np.asarray(leaves, dtype=np.uint32)
    lvl = base_level
    while level.shape[0] > 1:
        level = hash64_host_words(level[0::2], level[1::2])
        lvl += 1
    root = level[0]
    while lvl < depth:
        root = hash64_host_words(root[None], ZERO_HASHES[lvl][None])[0]
        lvl += 1
    return root


@jax.jit
def merkle_level(left_right: jnp.ndarray) -> jnp.ndarray:
    """One tree level: ``(n, 8)`` → ``(n/2, 8)`` (n even)."""
    return hash64(left_right[0::2], left_right[1::2])


@jax.jit
def mix_in_length(root: jnp.ndarray, length: jnp.ndarray) -> jnp.ndarray:
    """``hash(root || uint256_le(length))`` — SSZ list length mixin.

    Mirrors ``/root/reference/consensus/tree_hash/src/lib.rs:61-69``.
    ``length`` is a uint32 scalar (consensus list lengths fit; widen later via
    a (2,) lo/hi pair if a >4B-entry list ever appears).
    """
    # little-endian uint256: byte 0..3 = length LE -> big-endian word 0
    le = ((length & np.uint32(0xFF)) << np.uint32(24)) \
        | ((length >> np.uint32(8) & np.uint32(0xFF)) << np.uint32(16)) \
        | ((length >> np.uint32(16) & np.uint32(0xFF)) << np.uint32(8)) \
        | (length >> np.uint32(24))
    len_words = jnp.zeros(8, dtype=jnp.uint32).at[0].set(le)
    return hash64(root, len_words)


def subtree_then_zero_root(leaves: jnp.ndarray, depth: int,
                           length: jnp.ndarray | None = None) -> jnp.ndarray:
    """Root of a 2^depth-leaf tree where only a power-of-two prefix is real.

    This is the hot shape for the validator registry: ~1M real leaves inside a
    2^40-leaf SSZ list (``ValidatorRegistryLimit``,
    ``/root/reference/consensus/types/src/eth_spec.rs:267``).  Optionally mixes
    in the list length.
    """
    root = merkleize(leaves, depth)
    if length is not None:
        root = mix_in_length(root, jnp.asarray(length, dtype=jnp.uint32))
    return root


# ---------------------------------------------------------------------------
# Host-side reference (ground truth + cold paths)
# ---------------------------------------------------------------------------

def merkleize_host(chunks: list[bytes], limit: int | None = None) -> bytes:
    """Host merkleize per the SSZ spec (power-of-two zero padding up to limit)."""
    count = len(chunks)
    if limit is not None and count > limit:
        raise ValueError(f"{count} chunks exceeds limit {limit}")
    width = _next_pow2(count if limit is None else limit)
    depth = width.bit_length() - 1
    if count == 0:
        return ZERO_HASHES_BYTES[depth]
    level = list(chunks)
    for d in range(depth):
        if len(level) % 2 == 1:
            level.append(ZERO_HASHES_BYTES[d])
        level = [hashlib.sha256(level[i] + level[i + 1]).digest()
                 for i in range(0, len(level), 2)]
    return level[0]


def mix_in_length_host(root: bytes, length: int) -> bytes:
    return hashlib.sha256(root + length.to_bytes(32, "little")).digest()


def mix_in_selector_host(root: bytes, selector: int) -> bytes:
    """SSZ union selector mixin (``tree_hash/src/lib.rs:84-95``)."""
    return hashlib.sha256(root + selector.to_bytes(32, "little")).digest()
