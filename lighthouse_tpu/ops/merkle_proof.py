"""Merkle branch generation and verification.

Counterpart of ``/root/reference/consensus/merkle_proof/src/lib.rs``
(``MerkleTree``/``verify_merkle_proof``) — used for deposit-contract proofs
(``beacon_node/eth1/src/deposit_cache.rs``) and light-client branches.  Proof
*verification* is also inlined in block processing
(``per_block.is_valid_merkle_branch``); this module adds the generation side:
an incremental depth-``d`` tree over pushed leaves with zero-subtree padding.

Host-side by design: proofs are per-item cold paths (deposits arrive a few
per block); the batched device reductions in :mod:`lighthouse_tpu.ops.merkle`
cover the hot whole-tree roots.
"""

from __future__ import annotations

import hashlib

from .merkle import ZERO_HASHES_BYTES


def _hash(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


class MerkleTree:
    """Incremental fixed-depth binary Merkle tree with proof generation.

    Mirrors ``merkle_proof::MerkleTree`` semantics: a depth-``d`` tree whose
    leaves are pushed left-to-right, with all-zero subtrees padding the right.
    """

    def __init__(self, depth: int):
        if not 0 <= depth < len(ZERO_HASHES_BYTES):
            raise ValueError(f"unsupported depth {depth}")
        self.depth = depth
        self.leaves: list[bytes] = []

    def push_leaf(self, leaf: bytes) -> None:
        if len(leaf) != 32:
            raise ValueError("leaf must be 32 bytes")
        if len(self.leaves) >= (1 << self.depth):
            raise ValueError("tree is full")
        self.leaves.append(leaf)

    def _levels(self) -> list[list[bytes]]:
        """All levels bottom-up; level ``i`` holds the non-zero prefix."""
        levels = [list(self.leaves)]
        for d in range(self.depth):
            prev = levels[-1]
            if len(prev) % 2:
                prev = prev + [ZERO_HASHES_BYTES[d]]
            levels.append([_hash(prev[i], prev[i + 1])
                           for i in range(0, len(prev), 2)])
        return levels

    def root(self) -> bytes:
        if not self.leaves:
            return ZERO_HASHES_BYTES[self.depth]
        return self._levels()[self.depth][0]

    def proof(self, index: int) -> list[bytes]:
        """Sibling branch for leaf ``index``, bottom-up (length ``depth``)."""
        if not 0 <= index < (1 << self.depth):
            raise ValueError(f"index {index} out of range")
        levels = self._levels()
        branch = []
        for d in range(self.depth):
            sibling = (index >> d) ^ 1
            level = levels[d]
            branch.append(level[sibling] if sibling < len(level)
                          else ZERO_HASHES_BYTES[d])
        return branch


def verify_merkle_proof(leaf: bytes, branch: list[bytes], depth: int,
                        index: int, root: bytes) -> bool:
    """Spec ``is_valid_merkle_branch``."""
    value = leaf
    for i in range(depth):
        if (index >> i) & 1:
            value = _hash(branch[i], value)
        else:
            value = _hash(value, branch[i])
    return value == root


class DepositTree:
    """Deposit-contract tree: depth-32 ``MerkleTree`` whose root mixes in the
    deposit count, with proofs of length ``depth + 1`` (count as last node) —
    matching ``is_valid_merkle_branch(…, DEPOSIT_CONTRACT_TREE_DEPTH + 1, …)``
    in ``process_deposit`` and the eth1 ``deposit_cache`` layout."""

    def __init__(self, depth: int = 32):
        self.tree = MerkleTree(depth)

    def push(self, deposit_data_root: bytes) -> None:
        self.tree.push_leaf(deposit_data_root)

    @property
    def count(self) -> int:
        return len(self.tree.leaves)

    def root(self) -> bytes:
        return _hash(self.tree.root(), self.count.to_bytes(32, "little"))

    def proof(self, index: int) -> list[bytes]:
        return (self.tree.proof(index)
                + [self.count.to_bytes(32, "little")])
