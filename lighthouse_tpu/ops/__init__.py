"""Device kernels (JAX/XLA + Pallas) for the consensus hot path."""
