"""Incremental Merkleization — the ``cached_tree_hash`` counterpart.

The reference turns O(state) hashing into O(changes·log n) with per-field
arenas of interior nodes, dirty-leaf diffing and ``lift_dirty`` propagation
(``/root/reference/consensus/cached_tree_hash/src/cache.rs:60-147``,
``types/src/beacon_state/tree_hash_cache.rs:332``).  Same idea here, with
TPU-shaped dispatch:

- **Diff, don't track.**  Mutation sites never mark anything dirty; the
  cache keeps the previously-hashed leaves and diffs whole columns with one
  vectorized compare (numpy, ~ms at 1M leaves).  This is the reference's
  leaf-diff loop (``cache.rs:108-123``) as a single vector op, and it makes
  the cache correct under *any* mutation pattern.
- **Small diffs walk, big diffs rebuild.**  k dirty leaves recompute exactly
  their ⌈log n⌉ ancestor paths with host SHA (k·depth 64-byte hashes — µs
  for per-block churn).  Past a dirty fraction the whole tree re-reduces
  level-by-level instead (device ``hash64`` when a TPU is attached, else
  vectorized host hashing), which also refreshes every stored level.
- **Zero-cap folding.**  Only the occupied power-of-two subtree is stored;
  the (limit − subtree) levels fold against the precomputed zero-hash table
  at root time (≤ 40 host hashes), exactly like ``merkleize_padded``.

``HASH_COUNT`` counts 64-byte compressions actually performed — tests assert
the O(k·log n) bound with it.
"""

from __future__ import annotations

import numpy as np

from .merkle import (ZERO_HASHES, _next_pow2, hash64_host_words,
                     mix_in_length_host)
from .sha256 import words_to_bytes

# Instrumentation: number of 64-byte hash compressions performed by caches
# (host + device), for O(changes·log n) assertions in tests.
HASH_COUNT = [0]


def _h64_host(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    HASH_COUNT[0] += int(np.prod(left.shape[:-1], dtype=np.int64))
    return hash64_host_words(left, right)


def fold_zero_cap(root_words: np.ndarray, lvl: int, depth: int,
                  mixin_length: bool, length: int) -> bytes:
    """Fold a subtree root at level ``lvl`` against the zero-hash table up
    to ``depth`` + optional length mixin — a tight hashlib byte chain (the
    array-marshalling version cost ~60 µs per level; the registry pays 20
    of these per root at 2^20 inside a 2^40-limit list)."""
    import hashlib
    from .merkle import ZERO_HASHES_BYTES
    root = words_to_bytes(np.asarray(root_words, dtype=np.uint32))
    while lvl < depth:
        HASH_COUNT[0] += 1
        root = hashlib.sha256(root + ZERO_HASHES_BYTES[lvl]).digest()
        lvl += 1
    if mixin_length:
        HASH_COUNT[0] += 1
        root = mix_in_length_host(root, length)
    return root


# At/above this width a full (re)build runs on the attached TPU as ONE
# dispatch with a lazy level pull.  Dirty-path walks and small rebuilds stay
# on the host: through the axon tunnel a single device dispatch costs ~90 ms
# round-trip and pulls run ~11 MB/s, so eager per-level device hashing (the
# r3 design) LOSES to hashlib everywhere except the one-shot bulk build.
DEVICE_BUILD_THRESHOLD = 1 << 17
# Rebuild instead of walking when dirty leaves exceed this fraction.
REBUILD_FRACTION = 8  # dirty > width/8 → rebuild


def _tpu_attached() -> bool:
    try:
        from .merkle_kernel import _use_pallas
        return _use_pallas()
    except Exception:  # pragma: no cover
        return False


def start_level_pull(dev_levels) -> tuple:
    """Spawn a background thread pulling device tree levels to host numpy.

    Returns an opaque ``(thread, box)`` pending handle for
    :func:`join_level_pull`.  Non-daemon on purpose: a daemon thread still
    inside a jax device_get at interpreter shutdown aborts the process
    ("FATAL: exception not rethrown"); the interpreter joining a few MB of
    pull is the cheaper failure mode.
    """
    import threading
    import time

    from ..common.metrics import observe

    box: list = []

    def pull():
        t0 = time.perf_counter()
        try:
            got = [np.array(lv_dev) for lv_dev in dev_levels]  # device-io: staging
            box.append(got)
            # Explicit subsystem (background thread — the caller's
            # ambient attribution context is thread-local): the whole
            # tree coming D2H is the ledger's biggest pull path.
            from ..common.device_ledger import LEDGER
            LEDGER.note_transfer("d2h",
                                 sum(lv.nbytes for lv in got),
                                 subsystem="staging")
        except Exception as e:  # pragma: no cover - tunnel hiccup
            box.append(e)
        observe("merkle_level_pull_seconds", time.perf_counter() - t0)

    t = threading.Thread(target=pull, daemon=False)
    t.start()
    return (t, box)


def join_level_pull(pending) -> list | None:
    """Join a :func:`start_level_pull` handle; returns the host levels or
    None on pull failure (callers rebuild — correctness never depends on
    the cache)."""
    t, box = pending
    t.join()
    got = box[0] if box else None
    return got if isinstance(got, list) else None


class IncrementalMerkleCache:
    """Interior-node store for one padded Merkle tree (one SSZ field)."""

    def __init__(self, limit_chunks: int, mixin_length: bool):
        self.depth = max((int(limit_chunks) - 1).bit_length(), 0)
        self.mixin_length = mixin_length
        self.levels: list[np.ndarray] | None = None
        self._pending = None  # (thread, box) while a device build pulls back

    # -- internals -----------------------------------------------------------

    def _rebuild(self, leaves: np.ndarray) -> np.ndarray:
        """Recompute every stored level from ``leaves`` ((w, 8), w pow2);
        returns the subtree root words.  Big builds run on the device —
        the leaves stream up in column chunks overlapped with the
        earlier chunks' sub-tree reduction (``merkle_levels_device``'s
        ChunkStager path) — with the interior levels pulled by a
        background thread (the cache stays "pending" until they land)."""
        w = leaves.shape[0]
        if w >= DEVICE_BUILD_THRESHOLD and _tpu_attached():
            from .merkle_kernel import merkle_levels_device

            HASH_COUNT[0] += w - 1
            root, dev_levels = merkle_levels_device(leaves)
            self.levels = None
            self._pending = start_level_pull(dev_levels)
            return root
        levels = [leaves]
        cur = leaves
        while cur.shape[0] > 1:
            cur = _h64_host(cur[0::2], cur[1::2])
            levels.append(cur)
        self.levels = levels
        return levels[-1][0]

    def _finish_pending(self) -> None:
        got = join_level_pull(self._pending)
        self._pending = None
        if got is not None:
            self.levels = got
        # else: leave levels None — the next root_words() rebuilds.

    def _propagate(self, dirty: np.ndarray) -> None:
        """Recompute the ancestor paths of ``dirty`` leaf indices (host
        hashlib — k·log n 64-byte hashes, µs for per-block churn)."""
        idx = np.unique(dirty >> 1)
        for lvl in range(1, len(self.levels)):
            below = self.levels[lvl - 1]
            out = _h64_host(below[2 * idx], below[2 * idx + 1])
            self.levels[lvl][idx] = out
            idx = np.unique(idx >> 1)

    def _fold_and_mix(self, root: np.ndarray, lvl: int,
                      length: int) -> bytes:
        return fold_zero_cap(root, lvl, self.depth, self.mixin_length,
                             length)

    # -- API -----------------------------------------------------------------

    def root_words(self, leaves: np.ndarray, length: int | None = None) -> bytes:
        """Root over ``(k, 8)`` u32 chunk words (natural order), diffing
        against the cached copy.  Returns 32 bytes (with length mixin when
        configured)."""
        if self._pending is not None:
            self._finish_pending()
        k = leaves.shape[0]
        w = _next_pow2(max(k, 1))
        if leaves.dtype != np.uint32:
            leaves = leaves.astype(np.uint32)
        padded = np.zeros((w, 8), dtype=np.uint32)
        padded[:k] = leaves
        lvl_count = w.bit_length()  # len(levels) == log2(w) + 1
        if self.levels is None or self.levels[0].shape[0] != w:
            root = self._rebuild(padded)
        else:
            stored = self.levels[0]
            diff = np.nonzero((stored != padded).any(axis=1))[0]
            if diff.size > w // REBUILD_FRACTION:
                root = self._rebuild(padded)
            else:
                if diff.size:
                    stored[diff] = padded[diff]
                    self._propagate(diff)
                root = self.levels[-1][0]
        return self._fold_and_mix(root, lvl_count - 1,
                                  int(k if length is None else length))

    def update_rows(self, idx: np.ndarray, rows: np.ndarray,
                    count: int, length: int | None = None) -> bytes:
        """Sparse alternative to :meth:`root_words`: the caller diffed at
        the SOURCE level and supplies only the changed chunk rows
        (``idx`` ascending, ``rows`` (k, 8)).  ``count`` is the new total
        chunk count (must keep the same padded width)."""
        if self._pending is not None:
            self._finish_pending()
        if self.levels is None:
            raise ValueError("cold cache: call root_words first")
        w = self.levels[0].shape[0]
        if _next_pow2(max(count, 1)) != w:
            raise ValueError("width changed: use root_words")
        if idx.size:
            self.levels[0][idx] = rows
            self._propagate(idx)
        return self._fold_and_mix(self.levels[-1][0], len(self.levels) - 1,
                                  int(count if length is None else length))

    def copy(self) -> "IncrementalMerkleCache":
        if self._pending is not None:
            self._finish_pending()
        out = IncrementalMerkleCache.__new__(IncrementalMerkleCache)
        out.depth = self.depth
        out.mixin_length = self.mixin_length
        out.levels = (None if self.levels is None
                      else [lv.copy() for lv in self.levels])
        out._pending = None
        return out
