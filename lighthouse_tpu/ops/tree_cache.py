"""Incremental Merkleization — the ``cached_tree_hash`` counterpart.

The reference turns O(state) hashing into O(changes·log n) with per-field
arenas of interior nodes, dirty-leaf diffing and ``lift_dirty`` propagation
(``/root/reference/consensus/cached_tree_hash/src/cache.rs:60-147``,
``types/src/beacon_state/tree_hash_cache.rs:332``).  Same idea here, with
TPU-shaped dispatch:

- **Diff, don't track.**  Mutation sites never mark anything dirty; the
  cache keeps the previously-hashed leaves and diffs whole columns with one
  vectorized compare (numpy, ~ms at 1M leaves).  This is the reference's
  leaf-diff loop (``cache.rs:108-123``) as a single vector op, and it makes
  the cache correct under *any* mutation pattern.
- **Small diffs walk, big diffs rebuild.**  k dirty leaves recompute exactly
  their ⌈log n⌉ ancestor paths with host SHA (k·depth 64-byte hashes — µs
  for per-block churn).  Past a dirty fraction the whole tree re-reduces
  level-by-level instead (device ``hash64`` when a TPU is attached, else
  vectorized host hashing), which also refreshes every stored level.
- **Zero-cap folding.**  Only the occupied power-of-two subtree is stored;
  the (limit − subtree) levels fold against the precomputed zero-hash table
  at root time (≤ 40 host hashes), exactly like ``merkleize_padded``.

``HASH_COUNT`` counts 64-byte compressions actually performed — tests assert
the O(k·log n) bound with it.
"""

from __future__ import annotations

import numpy as np

from .merkle import (ZERO_HASHES, _next_pow2, hash64_host_words,
                     mix_in_length_host)
from .sha256 import hash64, words_to_bytes

# Instrumentation: number of 64-byte hash compressions performed by caches
# (host + device), for O(changes·log n) assertions in tests.
HASH_COUNT = [0]


def _h64_host(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    HASH_COUNT[0] += int(np.prod(left.shape[:-1], dtype=np.int64))
    return hash64_host_words(left, right)


# Above this many nodes a full level re-reduce goes to the device.
DEVICE_LEVEL_THRESHOLD = 1 << 14
# Rebuild instead of walking when dirty leaves exceed this fraction.
REBUILD_FRACTION = 8  # dirty > width/8 → rebuild


class IncrementalMerkleCache:
    """Interior-node store for one padded Merkle tree (one SSZ field)."""

    def __init__(self, limit_chunks: int, mixin_length: bool):
        self.depth = max((int(limit_chunks) - 1).bit_length(), 0)
        self.mixin_length = mixin_length
        self.levels: list[np.ndarray] | None = None

    # -- internals -----------------------------------------------------------

    def _rebuild(self, leaves: np.ndarray) -> None:
        """Recompute every stored level from ``leaves`` ((w, 8), w pow2)."""
        w = leaves.shape[0]
        levels = [leaves]
        use_device = False
        try:
            import jax
            use_device = (w >= DEVICE_LEVEL_THRESHOLD
                          and jax.default_backend() == "tpu")
        except Exception:
            pass
        cur = leaves
        if use_device:
            import jax.numpy as jnp
            dev = jnp.asarray(cur)
            while dev.shape[0] > 1:
                HASH_COUNT[0] += dev.shape[0] // 2
                dev = hash64(dev[0::2], dev[1::2])
                # np.array: device pulls are read-only views; levels must
                # stay writable for later dirty-path updates.
                levels.append(np.array(dev))
        else:
            while cur.shape[0] > 1:
                cur = _h64_host(cur[0::2], cur[1::2])
                levels.append(cur)
        self.levels = levels

    def _propagate(self, dirty: np.ndarray) -> None:
        """Recompute the ancestor paths of ``dirty`` leaf indices."""
        idx = np.unique(dirty >> 1)
        for lvl in range(1, len(self.levels)):
            below = self.levels[lvl - 1]
            big = idx.size >= DEVICE_LEVEL_THRESHOLD
            left = below[2 * idx]
            right = below[2 * idx + 1]
            if big:
                import jax.numpy as jnp
                HASH_COUNT[0] += idx.size
                out = np.array(hash64(jnp.asarray(left), jnp.asarray(right)))
            else:
                out = _h64_host(left, right)
            self.levels[lvl][idx] = out
            idx = np.unique(idx >> 1)

    # -- API -----------------------------------------------------------------

    def root_words(self, leaves: np.ndarray, length: int | None = None) -> bytes:
        """Root over ``(k, 8)`` u32 chunk words (natural order), diffing
        against the cached copy.  Returns 32 bytes (with length mixin when
        configured)."""
        k = leaves.shape[0]
        w = _next_pow2(max(k, 1))
        if leaves.dtype != np.uint32:
            leaves = leaves.astype(np.uint32)
        padded = np.zeros((w, 8), dtype=np.uint32)
        padded[:k] = leaves
        if self.levels is None or self.levels[0].shape[0] != w:
            self._rebuild(padded)
        else:
            stored = self.levels[0]
            diff = np.nonzero((stored != padded).any(axis=1))[0]
            if diff.size > w // REBUILD_FRACTION:
                self._rebuild(padded)
            elif diff.size:
                stored[diff] = padded[diff]
                self._propagate(diff)
        root = self.levels[-1][0]
        lvl = len(self.levels) - 1
        while lvl < self.depth:
            root = _h64_host(root[None], ZERO_HASHES[lvl][None])[0]
            lvl += 1
        root_bytes = words_to_bytes(root)
        if self.mixin_length:
            HASH_COUNT[0] += 1
            root_bytes = mix_in_length_host(
                root_bytes, int(k if length is None else length))
        return root_bytes

    def update_rows(self, idx: np.ndarray, rows: np.ndarray,
                    count: int, length: int | None = None) -> bytes:
        """Sparse alternative to :meth:`root_words`: the caller diffed at
        the SOURCE level and supplies only the changed chunk rows
        (``idx`` ascending, ``rows`` (k, 8)).  ``count`` is the new total
        chunk count (must keep the same padded width)."""
        if self.levels is None:
            raise ValueError("cold cache: call root_words first")
        w = self.levels[0].shape[0]
        if _next_pow2(max(count, 1)) != w:
            raise ValueError("width changed: use root_words")
        if idx.size:
            self.levels[0][idx] = rows
            self._propagate(idx)
        root = self.levels[-1][0]
        lvl = len(self.levels) - 1
        while lvl < self.depth:
            root = _h64_host(root[None], ZERO_HASHES[lvl][None])[0]
            lvl += 1
        root_bytes = words_to_bytes(root)
        if self.mixin_length:
            HASH_COUNT[0] += 1
            root_bytes = mix_in_length_host(
                root_bytes, int(count if length is None else length))
        return root_bytes

    def copy(self) -> "IncrementalMerkleCache":
        out = IncrementalMerkleCache.__new__(IncrementalMerkleCache)
        out.depth = self.depth
        out.mixin_length = self.mixin_length
        out.levels = (None if self.levels is None
                      else [lv.copy() for lv in self.levels])
        return out
