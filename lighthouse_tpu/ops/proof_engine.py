"""Device Merkle-proof engine — batched branch extraction, zero re-hashing.

:mod:`~lighthouse_tpu.ops.device_tree` keeps every tree level HBM-resident
for the per-slot root hot path, but until now the only thing it ever
emitted was the root: every light-client bootstrap / finality branch and
every state proof re-hashed subtrees on the host per request
(``light_client.state_field_proof``).  The MTU tree-unit shape
(arXiv:2507.16793) says the same resident structure should serve hashing
AND proof generation — a Merkle *branch* is not a computation over the
tree, it is a **read** of nodes the tree already holds.

So the engine never hashes.  Given one or many SSZ generalized indices it

1. maps every needed sibling gindex to its ``(level, index)`` coordinate
   in the :class:`~lighthouse_tpu.ops.device_tree.DeviceTree` layout
   (level ``j`` node ``i`` has gindex ``2^(depth-j) + i``),
2. deduplicates the union of sibling sets across the whole batch (shared
   upper-tree siblings are fetched once — this is what makes a
   1024-request batch a handful of rows, and is exactly the spec's
   multiproof ``get_helper_indices`` idea),
3. gathers the needed rows of each level in ONE jitted device program
   (a fixed-shape gather per level; index arrays are padded to
   power-of-two buckets like the scatter path, so compiled shapes stay
   logarithmic in batch size), and
4. pulls the gathered rows — the only D2H, 32 bytes per distinct node,
   accounted to the ``proof_engine`` ledger subsystem.

On top sits :class:`ProofServer`: the chain-facing serving layer that
builds (and LRU-caches) the head state's **field-root tree** from the
incremental tree-hash cache's field layer, micro-batches concurrent
requests (window knob ``LIGHTHOUSE_TPU_PROOF_WINDOW_MS``, early dispatch
at ``LIGHTHOUSE_TPU_PROOF_MAX_BATCH`` distinct gindices), coalesces
identical ``(state_root, gindex)`` requests, and serves both the
``/eth/v1/beacon/states/{state_id}/proof`` route and the re-homed
:class:`~lighthouse_tpu.light_client.LightClientServer` branches.  The
host hash-walk survives behind ``LIGHTHOUSE_TPU_PROOF_DEVICE=0`` as the
differential oracle (and the fallback when a device dispatch dies);
byte-equality of the two paths is pinned by tests/test_proof_engine.py.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..common.device_ledger import LEDGER
from ..common.metrics import Histogram
from .device_tree import DeviceTree, _bucket
from .merkle import ZERO_HASHES_BYTES, _next_pow2

# ---------------------------------------------------------------------------
# Generalized-index arithmetic (ssz/merkle-proofs spec helpers)
# ---------------------------------------------------------------------------


def branch_gindices(gindex: int) -> List[int]:
    """Sibling gindices proving ``gindex`` against the root, bottom-up
    (the spec's ``get_branch_indices`` without the trailing root)."""
    out = []
    g = int(gindex)
    while g > 1:
        out.append(g ^ 1)
        g >>= 1
    return out


def path_gindices(gindex: int) -> List[int]:
    """``gindex`` and every ancestor below the root."""
    out = []
    g = int(gindex)
    while g > 1:
        out.append(g)
        g >>= 1
    return out


def helper_gindices(gindices: Sequence[int]) -> List[int]:
    """The deduplicated multiproof helper set (spec
    ``get_helper_indices``): every sibling any branch needs that is not
    itself on (or derivable from) a proven path, sorted descending."""
    helpers: Set[int] = set()
    paths: Set[int] = set()
    for g in gindices:
        helpers.update(branch_gindices(g))
        paths.update(path_gindices(g))
    return sorted(helpers - paths, reverse=True)


def verify_merkle_multiproof(leaves: Sequence[bytes], proof: Sequence[bytes],
                             gindices: Sequence[int], root: bytes) -> bool:
    """Spec ``calculate_multi_merkle_root`` check: fold ``leaves`` at
    ``gindices`` with the helper ``proof`` nodes up to gindex 1."""
    helpers = helper_gindices(gindices)
    if len(leaves) != len(gindices) or len(proof) != len(helpers):
        return False
    objects: Dict[int, bytes] = dict(zip((int(g) for g in gindices), leaves))
    objects.update(zip(helpers, proof))
    keys = sorted(objects, reverse=True)
    pos = 0
    while pos < len(keys):
        k = keys[pos]
        if k in objects and k ^ 1 in objects and k >> 1 not in objects:
            objects[k >> 1] = hashlib.sha256(
                objects[k & ~1] + objects[k | 1]).digest()
            keys.append(k >> 1)
        pos += 1
    return objects.get(1) == root


def _validate_gindices(gindices: Sequence[int], depth: int) -> List[int]:
    """Malformed requests raise ``ValueError`` (the HTTP 400 contract):
    every gindex must address a node of a depth-``depth`` tree."""
    out = []
    for g in gindices:
        g = int(g)
        if g < 1 or g >= (1 << (depth + 1)):
            raise ValueError(
                f"gindex {g} outside a depth-{depth} tree (want 1 <= g "
                f"< {1 << (depth + 1)})")
        out.append(g)
    return out


# ---------------------------------------------------------------------------
# The device extraction core
# ---------------------------------------------------------------------------

_gather_jit = None


def _get_gather_jit():
    """One jitted multi-level gather: ``levels[j][idx_j]`` for every
    level with pending indices.  Retraces per (level-count, bucket)
    structure — bucket padding keeps that logarithmic."""
    global _gather_jit
    import jax

    if _gather_jit is None:
        def gather(levels, idxs):
            return tuple(lv[ix] for lv, ix in zip(levels, idxs))
        _gather_jit = jax.jit(gather)
    return _gather_jit


def _pad_indices(idx: np.ndarray) -> np.ndarray:
    """Bucket-pad a gather index vector by repeating the first entry —
    duplicate gathers read the same row twice, never wrong bits (the
    scatter path's ``pad_bucket`` idiom, index-only)."""
    k = idx.shape[0]
    b = _bucket(k)
    if k == b:
        return idx.astype(np.int32, copy=False)
    out = np.empty(b, dtype=np.int32)
    out[:k] = idx
    out[k:] = idx[0]
    return out


class DeviceProofEngine:
    """Branch extraction over one :class:`DeviceTree`'s resident levels.

    Pure gather — the engine contains no hash call.  Every node of the
    padded tree is resident (zero-padding subtrees are computed and
    stored at materialization), so any sibling a branch needs is a row
    read, byte-equal to the host ``ZERO_HASHES`` padding by
    construction.
    """

    def __init__(self, tree: DeviceTree):
        self.tree = tree
        self.depth = len(tree.levels) - 1

    def _coord(self, gindex: int) -> Tuple[int, int]:
        """gindex → (DeviceTree level, index within the level)."""
        d = gindex.bit_length() - 1
        return self.depth - d, gindex - (1 << d)

    def extract_nodes(self, gindices: Sequence[int]) -> Dict[int, bytes]:
        """The 32-byte nodes at ``gindices`` — one device program for
        the whole (deduplicated) set, one accounted D2H pull."""
        need = sorted({int(g) for g in gindices})
        if not need:
            return {}
        _validate_gindices(need, self.depth)
        per_level: Dict[int, List[int]] = {}
        for g in need:
            lv, ix = self._coord(g)
            per_level.setdefault(lv, []).append(ix)
        levels_used = sorted(per_level)
        idx_arrays = [np.asarray(per_level[lv], dtype=np.int32)
                      for lv in levels_used]
        with LEDGER.attribute("proof_engine"):
            import jax
            t0 = time.perf_counter()
            padded = [_pad_indices(a) for a in idx_arrays]
            LEDGER.note_transfer("h2d", sum(a.nbytes for a in padded),
                                 ops=len(padded))
            idx_dev = tuple(jax.device_put(a) for a in padded)  # device-io: proof_engine
            rows_dev = _get_gather_jit()(
                tuple(self.tree.levels[lv] for lv in levels_used), idx_dev)
            # The branch pull: the budget-relevant D2H of the serving
            # plane — 32 bytes per distinct node, bucket padding
            # included (it rides the same pull).
            host_rows = [np.asarray(row_dev)  # device-io: proof_engine
                         for row_dev in rows_dev]
            LEDGER.note_transfer("d2h", sum(r.nbytes for r in host_rows),
                                 ops=len(host_rows))
            LEDGER.note_dispatch(
                "proof_engine", (time.perf_counter() - t0) * 1e3)
        out: Dict[int, bytes] = {}
        for lv, idxs, rows in zip(levels_used,
                                  (per_level[l] for l in levels_used),
                                  host_rows):
            raw = rows.astype(">u4").tobytes()
            for j, ix in enumerate(idxs):
                g = (1 << (self.depth - lv)) + ix
                out[g] = raw[32 * j:32 * j + 32]
        return out

    def branches(self, gindices: Sequence[int]) -> Dict[int, List[bytes]]:
        """Single proofs for each requested gindex; the union of sibling
        sets is fetched in one program (shared uppers deduplicated)."""
        gs = _validate_gindices(gindices, self.depth)
        need: Set[int] = set()
        for g in gs:
            need.update(branch_gindices(g))
        nodes = self.extract_nodes(need)
        return {g: [nodes[s] for s in branch_gindices(g)] for g in gs}

    def multiproof(self, gindices: Sequence[int]
                   ) -> Tuple[List[bytes], List[bytes], List[int]]:
        """Deduplicated multiproof: ``(leaves, helpers, helper_gindices)``
        in the spec's descending helper order, verifiable with
        :func:`verify_merkle_multiproof`."""
        gs = _validate_gindices(gindices, self.depth)
        helpers = helper_gindices(gs)
        nodes = self.extract_nodes(list(gs) + helpers)
        return ([nodes[g] for g in gs], [nodes[h] for h in helpers],
                helpers)


# ---------------------------------------------------------------------------
# The serving layer
# ---------------------------------------------------------------------------


def _field_plane(field_roots: Sequence[bytes]) -> np.ndarray:
    """``(w, 8)`` u32 leaf plane over the state's field roots, zero-chunk
    padded to the container's power-of-two width (identical to the SSZ
    container fold's padding, so the tree root IS the state root)."""
    w = _next_pow2(max(len(field_roots), 1))
    rows = list(field_roots) + [ZERO_HASHES_BYTES[0]] * (w - len(field_roots))
    return (np.frombuffer(b"".join(rows), dtype=">u4")
            .astype(np.uint32).reshape(w, 8))


class _Batch:
    """One micro-batch window's pending gindex set for one state."""

    __slots__ = ("gindices", "done", "full", "nodes", "error")

    def __init__(self):
        self.gindices: Set[int] = set()
        self.done = threading.Event()
        self.full = threading.Event()
        self.nodes: Optional[Dict[int, bytes]] = None
        self.error: Optional[BaseException] = None


class ProofServer:
    """Micro-batching proof service over per-state field-root trees.

    Concurrent requests against the same state root that arrive within
    the batching window ride ONE device dispatch: the first requester
    becomes the window's leader (it waits out the window, then extracts
    the union gindex set); followers enqueue and block on the batch's
    completion event.  Identical gindices are coalesced by the set
    union — ``coalesced`` counts request-gindices that were already
    pending.  Field-root trees are cached per state root (small LRU;
    one ~1 KB H2D materialization per new head state).
    """

    def __init__(self, chain=None, window_ms: Optional[float] = None,
                 max_batch: Optional[int] = None, cache_states: int = 4):
        from ..common.knobs import knob_float, knob_int
        self.chain = chain
        self.window_s = (knob_float("LIGHTHOUSE_TPU_PROOF_WINDOW_MS")
                         if window_ms is None else float(window_ms)) / 1e3
        self.max_batch = (knob_int("LIGHTHOUSE_TPU_PROOF_MAX_BATCH")
                          if max_batch is None else int(max_batch))
        self._lock = threading.Lock()
        self._engines: "OrderedDict[bytes, DeviceProofEngine]" = \
            OrderedDict()
        self._building: Dict[bytes, threading.Event] = {}
        self._cache_states = cache_states
        self._batches: Dict[bytes, _Batch] = {}
        self.requests = 0
        self.coalesced = 0
        self.dispatches = 0
        self.gindices_dispatched = 0
        self.device_served = 0
        self.host_served = 0
        # Local (unregistered) latency histogram — the proof_serve_ms
        # SLO feed; bounds bracket the 50 ms budget.
        self._hist = Histogram(
            "proof_serve_seconds_local", "",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0))

    # -- feeds / panels ------------------------------------------------------

    def latency_snapshot(self):
        return self._hist.snapshot()

    def stats(self) -> dict:
        with self._lock:
            d = {
                "requests": self.requests,
                "coalesced": self.coalesced,
                "dispatches": self.dispatches,
                "gindices_dispatched": self.gindices_dispatched,
                "device_served": self.device_served,
                "host_served": self.host_served,
                "cached_state_trees": len(self._engines),
            }
        d["gindices_per_dispatch"] = (
            round(d["gindices_dispatched"] / d["dispatches"], 2)
            if d["dispatches"] else None)
        return d

    # -- state plumbing ------------------------------------------------------

    @staticmethod
    def _state_depth(state) -> int:
        return _next_pow2(max(len(type(state).FIELDS), 1)).bit_length() - 1

    @staticmethod
    def _field_roots(state) -> List[bytes]:
        from ..light_client import _field_roots
        return _field_roots(state)

    def _engine_for(self, state) -> Tuple[DeviceProofEngine, bytes]:
        root = bytes(state.tree_hash_root())
        while True:
            with self._lock:
                eng = self._engines.get(root)
                if eng is not None:
                    self._engines.move_to_end(root)
                    return eng, root
                # Per-root build dedup: concurrent first requests for
                # the same state root must pay ONE H2D materialization,
                # not one each (the losers' trees would be discarded by
                # the LRU insert but still billed to the ledger budget).
                ev = self._building.get(root)
                if ev is None:
                    ev = self._building[root] = threading.Event()
                    builder = True
                else:
                    builder = False
            if not builder:
                ev.wait()
                continue  # re-check the cache (or take over on failure)
            try:
                plane = _field_plane(self._field_roots(state))
                with LEDGER.attribute("proof_engine"):
                    tree = DeviceTree.from_host_leaves(plane)
                eng = DeviceProofEngine(tree)
                with self._lock:
                    self._engines[root] = eng
                    while len(self._engines) > self._cache_states:
                        self._engines.popitem(last=False)
                return eng, root
            finally:
                with self._lock:
                    del self._building[root]
                ev.set()

    # -- micro-batching ------------------------------------------------------

    def _batched_nodes(self, root: bytes, engine: DeviceProofEngine,
                       need: Set[int]) -> Dict[int, bytes]:
        with self._lock:
            batch = self._batches.get(root)
            leader = batch is None or batch.done.is_set()
            if leader:
                batch = _Batch()
                self._batches[root] = batch
            self.coalesced += len(need & batch.gindices)
            batch.gindices |= need
            if len(batch.gindices) >= self.max_batch:
                batch.full.set()
        if leader:
            if self.window_s > 0:
                batch.full.wait(self.window_s)
            with self._lock:
                if self._batches.get(root) is batch:
                    del self._batches[root]
                gs = sorted(batch.gindices)
            try:
                batch.nodes = engine.extract_nodes(gs)
                with self._lock:
                    self.dispatches += 1
                    self.gindices_dispatched += len(gs)
            except BaseException as e:  # noqa: BLE001 — relayed to waiters
                batch.error = e
            finally:
                batch.done.set()
        else:
            batch.done.wait(timeout=self.window_s * 10 + 15.0)
        if batch.error is not None:
            raise batch.error
        if batch.nodes is None:
            raise TimeoutError("proof batch dispatch timed out")
        return batch.nodes

    # -- node sourcing (device | host oracle) --------------------------------

    def _host_levels(self, state) -> List[List[bytes]]:
        """The differential-oracle walk: hashlib-fold the cached
        field-root layer (the ONLY hashing on the serving plane, and
        only behind the knob / fallback)."""
        roots = self._field_roots(state)
        w = _next_pow2(max(len(roots), 1))
        levels = [list(roots) + [ZERO_HASHES_BYTES[0]] * (w - len(roots))]
        while len(levels[-1]) > 1:
            lv = levels[-1]
            levels.append([hashlib.sha256(lv[i] + lv[i + 1]).digest()
                           for i in range(0, len(lv), 2)])
        return levels

    def _host_nodes(self, state, need: Set[int]) -> Dict[int, bytes]:
        levels = self._host_levels(state)
        depth = len(levels) - 1
        out = {}
        for g in need:
            d = g.bit_length() - 1
            out[g] = levels[depth - d][g - (1 << d)]
        return out

    def _serve(self, state, need: Set[int]) -> Dict[int, bytes]:
        from ..common.knobs import knob_bool
        if knob_bool("LIGHTHOUSE_TPU_PROOF_DEVICE"):
            try:
                engine, root = self._engine_for(state)
                nodes = self._batched_nodes(root, engine, need)
                with self._lock:
                    self.device_served += 1
                return nodes
            except ValueError:
                raise
            except Exception:
                # Device serving died mid-flight — the host oracle
                # carries the request (resilience-envelope idiom).
                pass
        nodes = self._host_nodes(state, need)
        with self._lock:
            self.host_served += 1
        return nodes

    # -- the public serving surface ------------------------------------------

    def state_proof(self, state, gindices: Sequence[int]
                    ) -> Dict[int, List[bytes]]:
        """Branches proving each gindex of the state's field-root tree
        against the state root.  Raises ``ValueError`` on a malformed
        gindex (the route's 400)."""
        t0 = time.perf_counter()
        try:
            gs = _validate_gindices(gindices, self._state_depth(state))
            with self._lock:
                self.requests += 1
            need: Set[int] = set()
            for g in gs:
                need.update(branch_gindices(g))
            nodes = self._serve(state, need)
            return {g: [nodes[s] for s in branch_gindices(g)] for g in gs}
        finally:
            self._hist.observe(time.perf_counter() - t0)

    def state_multiproof(self, state, gindices: Sequence[int]
                         ) -> Tuple[List[bytes], List[bytes], List[int]]:
        """Deduplicated multiproof over the state's field-root tree:
        ``(leaves, helpers, helper_gindices)``."""
        t0 = time.perf_counter()
        try:
            gs = _validate_gindices(gindices, self._state_depth(state))
            with self._lock:
                self.requests += 1
            helpers = helper_gindices(gs)
            nodes = self._serve(state, set(gs) | set(helpers))
            return ([nodes[g] for g in gs], [nodes[h] for h in helpers],
                    helpers)
        finally:
            self._hist.observe(time.perf_counter() - t0)

    def field_branch(self, state, field_name: str
                     ) -> Tuple[List[bytes], int]:
        """Device-extracted twin of
        :func:`~lighthouse_tpu.light_client.state_field_proof` —
        ``(branch, field index)`` for one state field."""
        names = list(type(state).FIELDS)
        idx = names.index(field_name)
        g = _next_pow2(max(len(names), 1)) + idx
        return self.state_proof(state, [g])[g], idx
