"""Batched SHA-256 for TPU, in pure jnp on uint32 lanes.

This is the device-side counterpart of the reference's ``crypto/eth2_hashing``
(``/root/reference/crypto/eth2_hashing/src/lib.rs:20-37`` — ``hash()``,
``hash_fixed()``, ``hash32_concat()``).  Where the reference dispatches to
CPU SHA-NI / ring assembly, we express the compression function over batched
``uint32`` lanes so XLA vectorises it across the VPU, with the batch dimension
carrying thousands of independent hashes (Merkle-tree nodes, signing roots).

Compiler notes: the 64 rounds and the message schedule are rolled up with
``lax.scan`` rather than unrolled in Python — a Merkle reduction chains
hundreds of compressions and an unrolled graph blows up XLA compile time;
the scan body is a handful of vector ops over the batch lane, which is the
shape the VPU wants anyway.

The dominant consensus op is the 64-byte two-child node hash
(``hash32_concat``).  SHA-256 of a 64-byte message is exactly two compression
calls: one over the data block and one over a *constant* padding block whose
message schedule is precomputed at import time (``_PAD64_KW``, with the round
constants already folded in).

All state is big-endian ``uint32`` words: a 32-byte digest is a ``(..., 8)``
uint32 array; a 64-byte block is ``(..., 16)``.
"""

from __future__ import annotations

import hashlib

import numpy as np
import jax
from jax import lax
import jax.numpy as jnp

# Round constants (FIPS 180-4).  Validated against hashlib in tests.
_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_IV = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)


def _schedule_np(block_words: np.ndarray) -> np.ndarray:
    """Host-side message schedule (python ints), for precomputing constants."""
    w = [int(x) for x in block_words]
    for i in range(16, 64):
        x15, x2 = w[i - 15], w[i - 2]
        s0 = ((x15 >> 7) | (x15 << 25)) ^ ((x15 >> 18) | (x15 << 14)) ^ (x15 >> 3)
        s1 = ((x2 >> 17) | (x2 << 15)) ^ ((x2 >> 19) | (x2 << 13)) ^ (x2 >> 10)
        w.append((w[i - 16] + (s0 & 0xFFFFFFFF) + w[i - 7] + (s1 & 0xFFFFFFFF)) & 0xFFFFFFFF)
    return np.array(w, dtype=np.uint32)


# Padding block for a 64-byte message: 0x80, zeros, then bit-length 512.
_PAD64_BLOCK = np.zeros(16, dtype=np.uint32)
_PAD64_BLOCK[0] = 0x80000000
_PAD64_BLOCK[15] = 512
# W+K folded together for the constant second block of hash64.
_PAD64_KW = ((_schedule_np(_PAD64_BLOCK).astype(np.uint64) + _K.astype(np.uint64))
             & 0xFFFFFFFF).astype(np.uint32)


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _rounds(state: jnp.ndarray, kw: jnp.ndarray) -> jnp.ndarray:
    """64 SHA-256 rounds via scan.  ``kw``: (64, ...) with W[i]+K[i] per round."""
    def step(carry, kwi):
        a, b, c, d, e, f, g, h = [carry[..., i] for i in range(8)]
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kwi
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return jnp.stack([t1 + t2, a, b, c, d + t1, e, f, g], axis=-1), None

    out, _ = lax.scan(step, state, kw)
    return state + out


def _expand_schedule(block: jnp.ndarray) -> jnp.ndarray:
    """Message schedule W[0..64) via scan over a rolling 16-word window.

    ``block``: (..., 16) uint32 → returns (64, ...) uint32 (round-major for
    feeding :func:`_rounds`).
    """
    def step(w, _):
        x15, x2 = w[..., 1], w[..., 14]
        s0 = _rotr(x15, 7) ^ _rotr(x15, 18) ^ (x15 >> np.uint32(3))
        s1 = _rotr(x2, 17) ^ _rotr(x2, 19) ^ (x2 >> np.uint32(10))
        nxt = w[..., 0] + s0 + w[..., 9] + s1
        return jnp.concatenate([w[..., 1:], nxt[..., None]], axis=-1), nxt

    _, rest = lax.scan(step, block, None, length=48)  # (48, ...)
    first = jnp.moveaxis(block, -1, 0)  # (16, ...)
    return jnp.concatenate([first, rest], axis=0)


def compress(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """One SHA-256 compression: ``state (..., 8)`` u32, ``block (..., 16)`` u32."""
    w = _expand_schedule(block)
    k = _K.reshape((64,) + (1,) * (state.ndim - 1))
    return _rounds(state, w + k)


def hash64(left: jnp.ndarray, right: jnp.ndarray) -> jnp.ndarray:
    """Batched ``hash32_concat``: SHA-256 of the 64-byte ``left || right``.

    ``left``/``right`` are ``(..., 8)`` uint32 digests; returns ``(..., 8)``.
    Mirrors ``/root/reference/crypto/eth2_hashing/src/lib.rs:31-37``.
    """
    block = jnp.concatenate([left, right], axis=-1)
    iv = jnp.broadcast_to(jnp.asarray(_IV), left.shape)
    mid = compress(iv, block)
    # Second block is the fixed padding block: W+K precomputed as constants.
    kw = jnp.broadcast_to(
        jnp.asarray(_PAD64_KW).reshape((64,) + (1,) * (left.ndim - 1)),
        (64,) + left.shape[:-1],
    )
    return _rounds(mid, kw)


def hash_blocks(data_words: jnp.ndarray) -> jnp.ndarray:
    """Batched SHA-256 over a statically-shaped byte payload.

    ``data_words``: ``(..., nblocks, 16)`` uint32 — already padded per FIPS
    180-4 (use :func:`pad_message_np` at trace time for the static layout).
    Returns ``(..., 8)`` digests.
    """
    n = data_words.shape[-2]
    state = jnp.broadcast_to(jnp.asarray(_IV), data_words.shape[:-2] + (8,))
    for i in range(n):
        state = compress(state, data_words[..., i, :])
    return state


def pad_message_np(length: int) -> tuple[int, np.ndarray, np.ndarray]:
    """Static padding layout for a ``length``-byte message.

    Returns ``(nblocks, tail_words, tail_mask)``: lay the message bytes into
    ``nblocks*16`` big-endian uint32 words, AND with ``tail_mask`` (keeps only
    real message bytes), then OR in ``tail_words`` (0x80 terminator + bit
    length).  Used for device-side hashing of fixed-size messages (e.g.
    ``expand_message_xmd`` blocks in hash-to-curve).
    """
    nblocks = (length + 8) // 64 + 1
    total = nblocks * 16
    tail = np.zeros(total, dtype=np.uint32)
    byte_i, bit_i = divmod(length, 4)
    tail[byte_i] = np.uint32(0x80000000) >> np.uint32(8 * bit_i)
    bitlen = length * 8
    tail[total - 2] = (bitlen >> 32) & 0xFFFFFFFF
    tail[total - 1] = bitlen & 0xFFFFFFFF
    mask = np.zeros(total, dtype=np.uint32)
    for i in range(total):
        nbytes = min(4, max(0, length - i * 4))
        if nbytes:
            mask[i] = np.uint32((0xFFFFFFFF << (8 * (4 - nbytes))) & 0xFFFFFFFF)
    return nblocks, tail, mask


# ---------------------------------------------------------------------------
# Host <-> device digest layout helpers
# ---------------------------------------------------------------------------

def bytes_to_words(data: bytes) -> np.ndarray:
    """Big-endian uint32 words from a byte string (len % 4 == 0)."""
    assert len(data) % 4 == 0
    return np.frombuffer(data, dtype=">u4").astype(np.uint32)


def words_to_bytes(words: np.ndarray) -> bytes:
    """Inverse of :func:`bytes_to_words`."""
    return np.asarray(words, dtype=np.uint32).astype(">u4").tobytes()


def sha256_host(data: bytes) -> bytes:
    """Host-side SHA-256 (hashlib); ground truth for tests and cold paths."""
    return hashlib.sha256(data).digest()
