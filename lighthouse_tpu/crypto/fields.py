"""BLS12-381 field towers in pure Python integers (host ground truth).

Tower (the standard one blst/milagro use, cf. the backends wrapped by
``/root/reference/crypto/bls/src/lib.rs:8-21``):

    Fq2  = Fq [u] / (u^2 + 1)
    Fq6  = Fq2[v] / (v^3 - xi),  xi = u + 1
    Fq12 = Fq6[w] / (w^2 - v)

Elements are immutable tuples of ints; all Frobenius constants are computed
at import from the tower structure (no memorised magic constants beyond the
curve parameters themselves).
"""

from __future__ import annotations

# Base field modulus and curve parameters (public BLS12-381 constants).
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# Subgroup order r (also the scalar field).
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter: the curve family's generator polynomial variable, x < 0.
BLS_X = -0xD201000000010000


# ---------------------------------------------------------------------------
# Fq — integers mod P
# ---------------------------------------------------------------------------

def fq_inv(a: int) -> int:
    return pow(a, P - 2, P)


def fq_sqrt(a: int) -> int | None:
    """Square root in Fq (P ≡ 3 mod 4), or None if not a QR."""
    a %= P
    r = pow(a, (P + 1) // 4, P)
    return r if r * r % P == a else None


def fq_sgn0(a: int) -> int:
    """RFC 9380 sgn0 for Fq: parity of the canonical representative."""
    return a % 2


# ---------------------------------------------------------------------------
# Fq2 — (c0, c1) = c0 + c1*u, u^2 = -1
# ---------------------------------------------------------------------------

Fq2 = tuple  # (int, int)

FQ2_ZERO = (0, 0)
FQ2_ONE = (1, 0)


def fq2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fq2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fq2_neg(a):
    return (-a[0] % P, -a[1] % P)


def fq2_mul(a, b):
    # Karatsuba: (a0+a1u)(b0+b1u) = a0b0 - a1b1 + ((a0+a1)(b0+b1) - a0b0 - a1b1)u
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def fq2_sqr(a):
    # (a0+a1u)^2 = (a0+a1)(a0-a1) + 2a0a1 u
    t0 = (a[0] + a[1]) * (a[0] - a[1])
    t1 = 2 * a[0] * a[1]
    return (t0 % P, t1 % P)


def fq2_muls(a, s: int):
    return (a[0] * s % P, a[1] * s % P)


def fq2_conj(a):
    return (a[0], -a[1] % P)


def fq2_inv(a):
    # 1/(a0+a1u) = conj(a)/(a0^2+a1^2)
    d = fq_inv((a[0] * a[0] + a[1] * a[1]) % P)
    return (a[0] * d % P, -a[1] * d % P)


def fq2_pow(a, e: int):
    out, base = FQ2_ONE, a
    while e:
        if e & 1:
            out = fq2_mul(out, base)
        base = fq2_sqr(base)
        e >>= 1
    return out


def fq2_sqrt(a):
    """Square root in Fq2 via the complex method (u^2 = -1), or None.

    For a = a0 + a1*u:  with n = a0^2 + a1^2 (the norm), a root exists iff
    sqrt(n) exists in Fq and one of (a0 ± sqrt(n))/2 is a QR.
    """
    a0, a1 = a[0] % P, a[1] % P
    if a1 == 0:
        r = fq_sqrt(a0)
        if r is not None:
            return (r, 0)
        # a0 is a non-residue: sqrt(a0) = sqrt(-a0)*u since u^2 = -1.
        r = fq_sqrt(-a0 % P)
        return None if r is None else (0, r)
    n = fq_sqrt((a0 * a0 + a1 * a1) % P)
    if n is None:
        return None
    inv2 = (P + 1) // 2
    for cand in ((a0 + n) * inv2 % P, (a0 - n) * inv2 % P):
        x0 = fq_sqrt(cand)
        if x0 is not None and x0 != 0:
            x1 = a1 * inv2 % P * fq_inv(x0) % P
            root = (x0, x1)
            if fq2_sqr(root) == (a0, a1):
                return root
    return None


def fq2_sgn0(a) -> int:
    """RFC 9380 sgn0 for Fq2 (little-endian over coefficients)."""
    s0 = a[0] % 2
    z0 = a[0] == 0
    s1 = a[1] % 2
    return s0 | (z0 & s1)


def fq2_is_zero(a) -> bool:
    return a[0] % P == 0 and a[1] % P == 0


# Fq6 non-residue xi = u + 1 (v^3 = xi).
XI = (1, 1)


# ---------------------------------------------------------------------------
# Fq6 — (c0, c1, c2) over Fq2, v^3 = XI
# ---------------------------------------------------------------------------

FQ6_ZERO = (FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE = (FQ2_ONE, FQ2_ZERO, FQ2_ZERO)


def _mul_by_xi(a):
    # (a0 + a1 u)(1 + u) = (a0 - a1) + (a0 + a1) u
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def fq6_add(a, b):
    return tuple(fq2_add(x, y) for x, y in zip(a, b))


def fq6_sub(a, b):
    return tuple(fq2_sub(x, y) for x, y in zip(a, b))


def fq6_neg(a):
    return tuple(fq2_neg(x) for x in a)


def fq6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0, t1, t2 = fq2_mul(a0, b0), fq2_mul(a1, b1), fq2_mul(a2, b2)
    c0 = fq2_add(t0, _mul_by_xi(fq2_sub(fq2_mul(fq2_add(a1, a2), fq2_add(b1, b2)),
                                        fq2_add(t1, t2))))
    c1 = fq2_add(fq2_sub(fq2_mul(fq2_add(a0, a1), fq2_add(b0, b1)), fq2_add(t0, t1)),
                 _mul_by_xi(t2))
    c2 = fq2_add(fq2_sub(fq2_mul(fq2_add(a0, a2), fq2_add(b0, b2)), fq2_add(t0, t2)), t1)
    return (c0, c1, c2)


def fq6_sqr(a):
    return fq6_mul(a, a)


def fq6_mul_by_v(a):
    # v * (a0 + a1 v + a2 v^2) = xi*a2 + a0 v + a1 v^2
    return (_mul_by_xi(a[2]), a[0], a[1])


def fq6_inv(a):
    a0, a1, a2 = a
    c0 = fq2_sub(fq2_sqr(a0), _mul_by_xi(fq2_mul(a1, a2)))
    c1 = fq2_sub(_mul_by_xi(fq2_sqr(a2)), fq2_mul(a0, a1))
    c2 = fq2_sub(fq2_sqr(a1), fq2_mul(a0, a2))
    t = fq2_inv(fq2_add(fq2_mul(a0, c0),
                        _mul_by_xi(fq2_add(fq2_mul(a2, c1), fq2_mul(a1, c2)))))
    return (fq2_mul(c0, t), fq2_mul(c1, t), fq2_mul(c2, t))


# ---------------------------------------------------------------------------
# Fq12 — (c0, c1) over Fq6, w^2 = v
# ---------------------------------------------------------------------------

FQ12_ONE = (FQ6_ONE, FQ6_ZERO)


def fq12_add(a, b):
    return (fq6_add(a[0], b[0]), fq6_add(a[1], b[1]))


def fq12_sub(a, b):
    return (fq6_sub(a[0], b[0]), fq6_sub(a[1], b[1]))


def fq12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fq6_mul(a0, b0)
    t1 = fq6_mul(a1, b1)
    c0 = fq6_add(t0, fq6_mul_by_v(t1))
    c1 = fq6_sub(fq6_mul(fq6_add(a0, a1), fq6_add(b0, b1)), fq6_add(t0, t1))
    return (c0, c1)


def fq12_sqr(a):
    return fq12_mul(a, a)


def fq12_inv(a):
    a0, a1 = a
    t = fq6_inv(fq6_sub(fq6_sqr(a0), fq6_mul_by_v(fq6_sqr(a1))))
    return (fq6_mul(a0, t), fq6_neg(fq6_mul(a1, t)))


def fq12_conj(a):
    """Conjugate over Fq6 (the w -> -w involution, = Frobenius^6)."""
    return (a[0], fq6_neg(a[1]))


def fq12_pow(a, e: int):
    if e < 0:
        return fq12_pow(fq12_conj(a), -e)  # valid for cyclotomic elements
    out, base = FQ12_ONE, a
    while e:
        if e & 1:
            out = fq12_mul(out, base)
        base = fq12_sqr(base)
        e >>= 1
    return out


# Frobenius constants, computed from the tower structure:
#   frob^n on Fq6 coefficients:  a_i -> conj^n(a_i) * XI^(i*(P^n-1)/3)
#   frob^n on the Fq12 w-part:   b1  -> b1' * XI^((P^n-1)/6)
_FROB_XI_3 = [fq2_pow(XI, (pow(P, n) - 1) // 3) for n in range(4)]
_FROB_XI_3_SQ = [fq2_sqr(c) for c in _FROB_XI_3]
_FROB_XI_6 = [fq2_pow(XI, (pow(P, n) - 1) // 6) for n in range(4)]


def _fq2_frob(a, n):
    return a if n % 2 == 0 else fq2_conj(a)


def _fq6_frob(a, n):
    return (_fq2_frob(a[0], n),
            fq2_mul(_fq2_frob(a[1], n), _FROB_XI_3[n]),
            fq2_mul(_fq2_frob(a[2], n), _FROB_XI_3_SQ[n]))


def fq12_frobenius(a, n: int = 1):
    """a^(P^n) for n in 1..3 (enough for the final exponentiation)."""
    assert 1 <= n <= 3
    c0 = _fq6_frob(a[0], n)
    c1 = _fq6_frob(a[1], n)
    gamma = _FROB_XI_6[n]
    c1 = tuple(fq2_mul(x, gamma) for x in c1)
    return (c0, c1)
