"""Batched BLS12-381 base-field arithmetic in 16-bit limbs for the TPU VPU.

This is the foundation of the ``tpu`` BLS backend — the device counterpart
of blst's assembly field arithmetic (the backend wrapped by
``/root/reference/crypto/bls/src/impls/blst.rs``).  A TPU has no 64-bit
integer multiplier, so a field element is 26 little-endian 16-bit limbs held
in ``uint32`` lanes (R = 2^416 > 4N), and every operation is elementwise /
batched over arbitrary leading dimensions — thousands of independent field
elements per vector op, which is exactly the shape batched signature
verification produces.

Representation invariants:

- Public values are **Montgomery residues** ``x·R mod N`` with *normalized*
  limbs (< 2^16) and value < 2N (lazy reduction — canonicalised only at the
  host boundary, where python ints take over).
- ``mont_mul`` is schoolbook column products (26×26 outer product, lo/hi
  split so every partial term fits uint32) + word-by-word Montgomery
  reduction, fully unrolled over the 26 limb positions (static slices; the
  batch dimension carries the parallelism, not the limb dimension).
- Sums/differences stay < 4N: with R = 2^416 ≈ 2^35·N there is enormous
  headroom, so no conditional subtractions exist anywhere on the device.

Host conversion helpers use exact python ints; the pure-python tower
(:mod:`..fields`) is the semantics oracle in tests.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from .fields import P as N_INT

LIMB_BITS = 16
LIMBS = 26
MASK = np.uint32(0xFFFF)
R_BITS = LIMB_BITS * LIMBS          # 416
R_INT = 1 << R_BITS
R_MOD_N = R_INT % N_INT
R2_MOD_N = (R_INT * R_INT) % N_INT
RINV_INT = pow(R_INT, -1, N_INT)
# -N^-1 mod 2^16 for the Montgomery word recurrence.
N0_INV = np.uint32((-pow(N_INT, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS))
# -N^-1 mod R for the full-width (single-shot) Montgomery reduction.
NPRIME_INT = (-pow(N_INT, -1, 1 << (LIMB_BITS * LIMBS))) % (1 << (LIMB_BITS * LIMBS))


def int_to_limbs(x: int) -> np.ndarray:
    """Python int (< R) → ``(26,)`` uint32 16-bit limbs, little-endian."""
    if not 0 <= x < R_INT:
        raise ValueError("value out of limb range")
    return np.array([(x >> (LIMB_BITS * i)) & 0xFFFF for i in range(LIMBS)],
                    dtype=np.uint32)


def limbs_to_int(limbs: np.ndarray) -> int:
    """``(..., 26)`` limbs → python int (no modular reduction)."""
    limbs = np.asarray(limbs, dtype=np.uint64)
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(limbs))


N_LIMBS = int_to_limbs(N_INT)
N2_LIMBS = int_to_limbs(2 * N_INT)
N4_LIMBS = int_to_limbs(4 * N_INT)


def to_mont(x: int) -> np.ndarray:
    """Canonical int → Montgomery-domain limbs."""
    return int_to_limbs((x % N_INT) * R_MOD_N % N_INT)


def from_mont(limbs: np.ndarray) -> int:
    """Montgomery-domain limbs (any lazy representative) → canonical int."""
    return limbs_to_int(limbs) * RINV_INT % N_INT


def to_mont_array(xs) -> np.ndarray:
    """Sequence/array of ints → ``(..., 26)`` Montgomery limbs."""
    flat = [to_mont(x) for x in np.asarray(xs, dtype=object).reshape(-1)]
    out = np.stack(flat) if flat else np.zeros((0, LIMBS), np.uint32)
    return out.reshape(np.asarray(xs, dtype=object).shape + (LIMBS,))


def from_mont_array(limbs: np.ndarray) -> np.ndarray:
    """``(..., 26)`` Montgomery limbs → object array of canonical ints."""
    arr = np.asarray(limbs)
    lead = arr.shape[:-1]
    flat = arr.reshape(-1, LIMBS)
    out = np.empty(flat.shape[0], dtype=object)
    for i in range(flat.shape[0]):
        out[i] = from_mont(flat[i])
    return out.reshape(lead)


ZERO = np.zeros(LIMBS, dtype=np.uint32)
ONE_MONT = to_mont(1)


# ---------------------------------------------------------------------------
# Device ops (pure jnp; batched over leading dims; limb axis = -1)
# ---------------------------------------------------------------------------

def _carry_u32(x: jnp.ndarray) -> jnp.ndarray:
    """Normalize uint32 limb values (< 2^32) to 16-bit limbs, unrolled
    carry chain.  The value must fit 26 limbs (guaranteed by the < 4N
    bound; R = 2^416 leaves 33+ spare bits)."""
    out = []
    carry = jnp.zeros_like(x[..., 0])
    for i in range(LIMBS):
        v = x[..., i] + carry
        out.append(v & MASK)
        carry = v >> np.uint32(LIMB_BITS)
    return jnp.stack(out, axis=-1)


def _carry_i32(x: jnp.ndarray) -> jnp.ndarray:
    """Signed carry normalization (int32 limb values, possibly negative;
    total value must be in [0, 2^416))."""
    out = []
    carry = jnp.zeros_like(x[..., 0])
    for i in range(LIMBS):
        v = x[..., i] + carry
        out.append(v & jnp.int32(0xFFFF))
        carry = v >> 16  # arithmetic shift: floor division by 2^16
    return jnp.stack(out, axis=-1).astype(jnp.uint32)


def _cond_sub(x: jnp.ndarray, k_limbs: np.ndarray) -> jnp.ndarray:
    """x - K if x ≥ K else x, branch-free (normalized limb input)."""
    d = x.astype(jnp.int32) - jnp.asarray(k_limbs, jnp.int32)
    out = []
    carry = jnp.zeros_like(d[..., 0])
    for i in range(LIMBS):
        v = d[..., i] + carry
        out.append(v & jnp.int32(0xFFFF))
        carry = v >> 16
    d_norm = jnp.stack(out, axis=-1).astype(jnp.uint32)
    no_borrow = carry == 0
    return jnp.where(no_borrow[..., None], d_norm, x)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b, conditionally reduced — inputs and output < 2N.

    The < 2N invariant everywhere makes bound reasoning trivial: every
    value this module hands out is safe for every other op.  The extra
    conditional-subtract carry pass is ~40 vector ops — noise next to a
    mont_mul, and the alternative (lazy growing bounds) silently corrupted
    curve formulas whose ×12 constants pushed intermediates past the
    subtraction slack."""
    return _cond_sub(_carry_u32(a + b), N2_LIMBS)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b mod N (inputs < 2N → a - b + 2N ∈ (0, 4N) → reduced < 2N)."""
    d = a.astype(jnp.int32) + jnp.asarray(N2_LIMBS, jnp.int32) - b.astype(jnp.int32)
    return _cond_sub(_carry_i32(d), N2_LIMBS)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    """2N - a ≡ -a (mod N); input < 2N → output ≤ 2N (2N ≡ 0 is a valid
    lazy zero and the next add/mul handles it)."""
    d = jnp.asarray(N2_LIMBS, jnp.int32) - a.astype(jnp.int32)
    return _carry_i32(d)


def muls(a: jnp.ndarray, s: int) -> jnp.ndarray:
    """a · s for a small int 0 ≤ s ≤ 16, reduced back below 2N."""
    if not 0 <= s <= 16:
        raise ValueError("small-scalar multiply supports 0..16")
    x = _carry_u32(a * np.uint32(s))     # < 32N
    x = _cond_sub(x, int_to_limbs(16 * N_INT))
    x = _cond_sub(x, int_to_limbs(8 * N_INT))
    x = _cond_sub(x, N4_LIMBS)
    return _cond_sub(x, N2_LIMBS)


def _band_columns(a: jnp.ndarray, b: jnp.ndarray, ncols: int) -> jnp.ndarray:
    """Column sums of the schoolbook product a·b: out[k] = Σ_{i+j=k} a_i·b_j
    (16-bit partial terms, lo at offset i+j, hi at i+j+1).  Expressed as
    static pads + one big sum — a wide, shallow graph XLA compiles orders of
    magnitude faster than an equivalent chain of slice-updates (which made
    the first version of this kernel take minutes to compile per scan).
    Column values < 52·2^16 < 2^23, comfortably inside uint32."""
    prod = a[..., :, None] * b[..., None, :]          # (..., 26, 26) < 2^32
    lo = prod & MASK
    hi = prod >> np.uint32(LIMB_BITS)
    nd = lo.ndim - 2
    parts = []
    for i in range(LIMBS):
        width = min(LIMBS, ncols - i)
        if width > 0:
            parts.append(jnp.pad(lo[..., i, :width],
                                 [(0, 0)] * nd + [(i, ncols - i - width)]))
        width = min(LIMBS, ncols - i - 1)
        if width > 0:
            parts.append(jnp.pad(hi[..., i, :width],
                                 [(0, 0)] * nd + [(i + 1, ncols - i - 1 - width)]))
    return jnp.sum(jnp.stack(parts), axis=0)


# ---------------------------------------------------------------------------
# MXU band products — the schoolbook column accumulation as ONE matmul
# ---------------------------------------------------------------------------
#
# The pad-and-sum tree above is pure VPU work: ~104 full-plane pads + adds
# per band product, and three band products per mont_mul.  Hardware pairing
# engines win by feeding the wide multiplier structured limb products
# ("A Low-Power BLS12-381 Pairing Crypto-Processor", arXiv:2201.07496);
# the TPU analogue is the MXU.  The accumulation
#     T[k] = Σ_{i+j=k} lo(a_i·b_j)  +  Σ_{i+j+1=k} hi(a_i·b_j)
# is a CONSTANT 0/1 contraction over the 2·26·26 = 1352 partial terms, so
# the whole band collapses to one (batch, 1352) × (1352, ncols) matmul
# against a fixed selection matrix.  Exactness: every partial term is
# < 2^16 (exact in f32) and every column accumulates ≤ 52 of them
# (< 2^22 < 2^24, the f32 integer-exact range), so the f32 MXU result is
# bit-exact — asserted against the VPU path in tests/test_bls_shard.py
# and scripts/validate_bls_shard.py.
#
# Default: on for the TPU backend, off elsewhere (the CPU "matmul" would
# just be a slower BLAS call); override with LIGHTHOUSE_TPU_MXU=0/1.

_MXU_FLAG: bool | None = None


def use_mxu() -> bool:
    """Whether band products route through the MXU matmul formulation."""
    global _MXU_FLAG
    if _MXU_FLAG is None:
        from ..common.knobs import knob_tribool
        forced = knob_tribool("LIGHTHOUSE_TPU_MXU")
        if forced is None:
            import jax
            _MXU_FLAG = jax.default_backend() == "tpu"
        else:
            _MXU_FLAG = forced
    return _MXU_FLAG


def band_sel_matrix(ncols: int) -> np.ndarray:
    """(2·26·26, ncols) f32 selection matrix: row i·26+j → column i+j
    (lo half), row 676+i·26+j → column i+j+1 (hi half)."""
    sel = np.zeros((2 * LIMBS * LIMBS, ncols), np.float32)
    for i in range(LIMBS):
        for j in range(LIMBS):
            if i + j < ncols:
                sel[i * LIMBS + j, i + j] = 1.0
            if i + j + 1 < ncols:
                sel[LIMBS * LIMBS + i * LIMBS + j, i + j + 1] = 1.0
    return sel


from functools import lru_cache as _lru_cache


@_lru_cache(maxsize=4)
def _band_sel_dev(ncols: int):
    return jnp.asarray(band_sel_matrix(ncols))


def _band_columns_mxu(a: jnp.ndarray, b: jnp.ndarray,
                      ncols: int) -> jnp.ndarray:
    """MXU twin of :func:`_band_columns` — identical column values."""
    import jax
    prod = a[..., :, None] * b[..., None, :]          # (..., 26, 26) < 2^32
    lead = prod.shape[:-2]
    lo = (prod & MASK).astype(jnp.float32).reshape(lead + (LIMBS * LIMBS,))
    hi = ((prod >> np.uint32(LIMB_BITS))
          .astype(jnp.float32).reshape(lead + (LIMBS * LIMBS,)))
    feat = jnp.concatenate([lo, hi], axis=-1)         # (..., 1352)
    t = jax.lax.dot_general(
        feat, _band_sel_dev(ncols),
        dimension_numbers=(((feat.ndim - 1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)
    return t.astype(jnp.uint32)


def _band(a: jnp.ndarray, b: jnp.ndarray, ncols: int) -> jnp.ndarray:
    return (_band_columns_mxu if use_mxu() else _band_columns)(a, b, ncols)


def _carry_cols(t: jnp.ndarray, ncols: int, keep_carry: bool) -> jnp.ndarray:
    """Normalize ``ncols`` uint32 columns (< 2^23) to 16-bit limbs; the final
    carry is appended iff ``keep_carry`` (else reduced mod 2^(16·ncols))."""
    out = []
    carry = jnp.zeros_like(t[..., 0])
    for i in range(ncols):
        v = t[..., i] + carry
        out.append(v & MASK)
        carry = v >> np.uint32(LIMB_BITS)
    if keep_carry:
        out.append(carry)
    return jnp.stack(out, axis=-1)


_NPRIME_LIMBS = int_to_limbs(NPRIME_INT)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Batched Montgomery product a·b·R^-1 mod N.

    Inputs: ``(..., 26)`` uint32, normalized limbs, values < 2N.
    Output: normalized limbs, value < 2N.

    Full-width reduction (one m = T·N' mod R, then (T + m·N)/R) instead of
    the textbook word-by-word recurrence: three band-products and three
    carry chains, no 26-step sequential slice-update dependency — the shape
    both the XLA compiler and the VPU prefer.  Bound: T < 4N², so
    (T + mN)/R < 4N²/R + N < 2N because R = 2^416 ≈ 2^35·N.
    """
    t = _band(a, b, 2 * LIMBS)                         # T columns
    t_low = _carry_cols(t[..., :LIMBS], LIMBS, keep_carry=False)
    m = _carry_cols(_band(t_low, jnp.asarray(_NPRIME_LIMBS), LIMBS),
                    LIMBS, keep_carry=False)           # m = T·N' mod R
    u = _band(m, jnp.asarray(N_LIMBS), 2 * LIMBS)
    s = _carry_cols(t + u, 2 * LIMBS, keep_carry=True)  # (T + mN), exact
    return s[..., LIMBS:2 * LIMBS]                      # / R  (low half ≡ 0)


def select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Elementwise ``mask ? a : b`` with mask broadcast over the limb axis."""
    return jnp.where(mask[..., None], a, b)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """Exact zero test (mod N) for lazy values < 8N: true iff the value is
    k·N for k < 8.  One mont_mul by R² would canonicalise, but comparing
    against the eight multiples directly is cheaper and branch-free."""
    out = None
    for k in range(8):
        eq = jnp.all(a == jnp.asarray(int_to_limbs(k * N_INT)), axis=-1)
        out = eq if out is None else (out | eq)
    return out
