"""Batched Fq2/Fq6/Fq12 tower arithmetic over the 16-bit-limb base field.

Tower structure matches the host oracle (:mod:`..fields`):
``Fq2 = Fq[u]/(u²+1)``, ``Fq6 = Fq2[v]/(v³-ξ)`` with ``ξ = u+1``,
``Fq12 = Fq6[w]/(w²-v)``.

Layout: an Fq2 element is ``(..., 2, 26)`` uint32 limbs; Fq6 is
``(..., 3, 2, 26)``; Fq12 is ``(..., 2, 3, 2, 26)`` — coefficient axes
mirror the host tuples, limbs innermost.

The TPU-shaped trick: every tower multiply *stacks* its schoolbook
sub-products along a new leading axis and recurses, so one ``fq12_mul``
lowers to exactly ONE batched :func:`..limb_field.mont_mul` call over
4·9·4 = 144 base-field products per element — the VPU sees a single wide
multiply instead of a tree of small ones.  Additions/negations are plain
limb ops and broadcast over every coefficient axis unchanged.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import limb_field as LF

# Re-exported limb ops work coefficient-wise on any (..., K, 26) stack.
add = LF.add
sub = LF.sub
neg = LF.neg
select = LF.select


# ---------------------------------------------------------------------------
# Fq2: (..., 2, 26)
# ---------------------------------------------------------------------------

def fq2_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook: (a0+a1u)(b0+b1u) = (a0b0 - a1b1) + (a0b1 + a1b0)u."""
    ai = a[..., (0, 1, 0, 1), :]
    bi = b[..., (0, 1, 1, 0), :]
    p = LF.mont_mul(ai, bi)  # (..., 4, 26)
    c0 = LF.sub(p[..., 0, :], p[..., 1, :])
    c1 = LF.add(p[..., 2, :], p[..., 3, :])
    return jnp.stack([c0, c1], axis=-2)


def fq2_sqr(a: jnp.ndarray) -> jnp.ndarray:
    return fq2_mul(a, a)


def fq2_conj(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack([a[..., 0, :], LF.neg(a[..., 1, :])], axis=-2)


def fq2_muls(a: jnp.ndarray, s: int) -> jnp.ndarray:
    return LF.muls(a, s)


def fq2_mul_by_xi(a: jnp.ndarray) -> jnp.ndarray:
    """ξ·(a0 + a1u) = (a0 - a1) + (a0 + a1)u  (ξ = 1 + u)."""
    c0 = LF.sub(a[..., 0, :], a[..., 1, :])
    c1 = LF.add(a[..., 0, :], a[..., 1, :])
    return jnp.stack([c0, c1], axis=-2)


def fq2_is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return LF.is_zero(a[..., 0, :]) & LF.is_zero(a[..., 1, :])


# ---------------------------------------------------------------------------
# Fq6: (..., 3, 2, 26)
# ---------------------------------------------------------------------------

def fq6_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook over Fq2 with v³ = ξ:

    c0 = a0b0 + ξ(a1b2 + a2b1)
    c1 = a0b1 + a1b0 + ξ(a2b2)
    c2 = a0b2 + a1b1 + a2b0
    """
    ai = a[..., (0, 1, 2, 0, 1, 2, 0, 1, 2), :, :]
    bi = b[..., (0, 2, 1, 1, 0, 2, 2, 1, 0), :, :]
    p = fq2_mul(ai, bi)  # (..., 9, 2, 26): [a0b0,a1b2,a2b1,a0b1,a1b0,a2b2,a0b2,a1b1,a2b0]
    c0 = LF.add(p[..., 0, :, :],
                fq2_mul_by_xi(LF.add(p[..., 1, :, :], p[..., 2, :, :])))
    c1 = LF.add(LF.add(p[..., 3, :, :], p[..., 4, :, :]),
                fq2_mul_by_xi(p[..., 5, :, :]))
    c2 = LF.add(LF.add(p[..., 6, :, :], p[..., 7, :, :]), p[..., 8, :, :])
    return jnp.stack([c0, c1, c2], axis=-3)


def fq6_mul_by_v(a: jnp.ndarray) -> jnp.ndarray:
    """v·(a0 + a1v + a2v²) = ξa2 + a0v + a1v²."""
    return jnp.stack([fq2_mul_by_xi(a[..., 2, :, :]),
                      a[..., 0, :, :], a[..., 1, :, :]], axis=-3)


# ---------------------------------------------------------------------------
# Fq12: (..., 2, 3, 2, 26)
# ---------------------------------------------------------------------------

def fq12_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a0 + a1w)(b0 + b1w) = (a0b0 + v·a1b1) + (a0b1 + a1b0)w."""
    ai = a[..., (0, 1, 0, 1), :, :, :]
    bi = b[..., (0, 1, 1, 0), :, :, :]
    p = fq6_mul(ai, bi)  # (..., 4, 3, 2, 26)
    c0 = LF.add(p[..., 0, :, :, :], fq6_mul_by_v(p[..., 1, :, :, :]))
    c1 = LF.add(p[..., 2, :, :, :], p[..., 3, :, :, :])
    return jnp.stack([c0, c1], axis=-4)


def fq12_sqr(a: jnp.ndarray) -> jnp.ndarray:
    return fq12_mul(a, a)


def fq12_conj(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack([a[..., 0, :, :, :],
                      LF.neg(a[..., 1, :, :, :])], axis=-4)


# ---------------------------------------------------------------------------
# Host conversions (exact ints; test/boundary only)
# ---------------------------------------------------------------------------

def fq2_to_limbs(x) -> np.ndarray:
    """Host Fq2 tuple (c0, c1) → (2, 26) Montgomery limbs."""
    return np.stack([LF.to_mont(x[0]), LF.to_mont(x[1])])


def fq2_from_limbs(a) -> tuple:
    a = np.asarray(a)
    return (LF.from_mont(a[..., 0, :]), LF.from_mont(a[..., 1, :]))


def fq6_to_limbs(x) -> np.ndarray:
    return np.stack([fq2_to_limbs(c) for c in x])


def fq6_from_limbs(a) -> tuple:
    a = np.asarray(a)
    return tuple(fq2_from_limbs(a[i]) for i in range(3))


def fq12_to_limbs(x) -> np.ndarray:
    return np.stack([fq6_to_limbs(c) for c in x])


def fq12_from_limbs(a) -> tuple:
    a = np.asarray(a)
    return tuple(fq6_from_limbs(a[i]) for i in range(2))


FQ12_ONE_LIMBS = None  # initialised below


def _init_constants():
    global FQ12_ONE_LIMBS
    from . import fields as F
    FQ12_ONE_LIMBS = fq12_to_limbs(F.FQ12_ONE)


_init_constants()
