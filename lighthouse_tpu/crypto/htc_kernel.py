"""Pallas TPU kernel for batched hash-to-curve onto G2 (RFC 9380 SSWU).

The message-hashing half of BLS verification — the H(m) of e(pk, H(m)) —
which the reference gets from blst's assembly ``hash_to_g2``
(``/root/reference/crypto/bls/src/impls/blst.rs:14``).  The host computes
``expand_message_xmd`` (SHA-256, microseconds) and ships the two Fq2 field
elements per message; everything algebraic runs on-device, batched over
lanes:

    u → simplified SWU onto E' (branchless 8-candidate sqrt, one 758-bit
    Fq2 ladder) → 3-isogeny to E (projective, no inversions) → u0+u1 point
    add → Budroni–Pintore psi cofactor clearing (two |x|-ladders) → affine.

Each grid cell handles 128 messages as 256 SSWU lanes (u0 block | u1
block interleaved per cell); output columns feed the Miller kernel's G2
input directly.  Constants live in :data:`..pairing_kernel.CONSTS_PLANES`;
the sqrt-ladder exponent bits ride in SMEM like the x/p−2 bit strings.
Host oracles: :func:`..hash_to_curve.map_to_curve_sswu` / ``iso_map`` /
``clear_cofactor`` (asserted equal in tests).
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import limb_field as LF
from . import hash_to_curve as H2C
from .pairing_kernel import (
    _KC, _bind_consts, _const_args, LIMBS, U32, BLOCK_ROWS, LANE_BLOCK,
    X_BITS_FULL, k_mont_mul, k_is_zero, k_sub, k_neg, _cond_sub_raw,
    fq2_add, fq2_sub, fq2_neg, fq2_conj, fq2_mul, fq2_mul_many, fq2_inv,
    point_add, point_select, point_identity, _G2ops,
    pack_planes, unpack_planes, CONSTS_PLANES, _COMPILER_PARAMS,
)

# LSB-first bits of (p²+7)/16 — the sqrt-ladder exponent (758 bits).
E16_BITS_LSB = np.array(
    [(H2C.E16_EXP >> i) & 1 for i in range(H2C.E16_EXP.bit_length())],
    dtype=np.int32)


def _htc_const_args():
    return _const_args() + (
        jnp.asarray(E16_BITS_LSB.reshape(-1, 1)),)


def _htc_const_specs():
    return [pl.BlockSpec(memory_space=pltpu.VMEM),   # consts
            pl.BlockSpec(memory_space=pltpu.SMEM),   # x bits
            pl.BlockSpec(memory_space=pltpu.SMEM),   # p−2 bits
            pl.BlockSpec(memory_space=pltpu.VMEM),   # band-sel matrix
            pl.BlockSpec(memory_space=pltpu.SMEM)]   # e16 bits


def _mat(c, m: int):
    """Materialize a (26, 1) constant plane to (26, m) REAL lanes.

    ``jnp.broadcast_to`` keeps a lane-broadcast layout inside Mosaic, and
    a later lane-concatenate of broadcast pieces crashes its vector
    layout pass (``vector_extract_rule: limits[i] <= dim(i)``) — observed
    on every H2C constant.  ``pltpu.roll``-era Mosaic provides
    ``pltpu.repeat`` as the explicit materialization; eager/CPU test
    drives (no Mosaic) use the plain broadcast."""
    if _KC.get("in_mosaic"):
        return pltpu.repeat(c, m, 1)
    return jnp.broadcast_to(c, (LIMBS, m))


def _kc2(name: str, m: int):
    """Fq2 constant materialized to (2 × (26, m))."""
    return (_mat(_KC[name + "0"], m), _mat(_KC[name + "1"], m))


def _fq2_zero(m: int):
    return (jnp.zeros((LIMBS, m), U32), jnp.zeros((LIMBS, m), U32))


def _fq2_one(m: int):
    return (_mat(_KC["ONE"], m), jnp.zeros((LIMBS, m), U32))


def _fq2_sel(take, a, b):
    return (jnp.where(take, a[0], b[0]), jnp.where(take, a[1], b[1]))


def k_fq2_eq(a, b):
    """(1, m) bool — equality mod N for lazy (< 2N) inputs."""
    return k_is_zero(k_sub(a[0], b[0])) & k_is_zero(k_sub(a[1], b[1]))


def k_fq2_is_zero(a):
    return k_is_zero(a[0]) & k_is_zero(a[1])


def k_canonical(a):
    """Montgomery-domain lazy plane → canonical (< N) value limbs."""
    v = k_mont_mul(a, jnp.broadcast_to(_KC["RAW_ONE"], a.shape))
    return _cond_sub_raw(v, _KC["N"])


def k_sgn0_fq2(a):
    """RFC 9380 sgn0 over Fq2 — (1, m) int32 ∈ {0, 1}."""
    c0 = k_canonical(a[0])
    c1 = k_canonical(a[1])
    s0 = (c0[0:1] & np.uint32(1)).astype(jnp.int32)
    z0 = jnp.all(c0 == 0, axis=0, keepdims=True).astype(jnp.int32)
    s1 = (c1[0:1] & np.uint32(1)).astype(jnp.int32)
    return s0 | (z0 & s1)


def k_fq2_pow_e16(a):
    """a^((p²+7)/16), LSB-first ladder: base-square and conditional
    multiply share ONE wide mont_mul per bit."""
    m = a[0].shape[1]
    res = _fq2_one(m)
    ebits = _KC["e16"]

    def body(i, carry):
        res, base = carry
        prods = fq2_mul_many([(base, base), (res, base)])
        take = jnp.full((1, m), ebits[i, 0] == 1)
        return (_fq2_sel(take, prods[1], res), prods[0])

    res, _ = jax.lax.fori_loop(0, E16_BITS_LSB.shape[0], body, (res, a))
    return res


def k_sswu_map(t):
    """Simplified SWU onto E' — branchless twin of
    :func:`..hash_to_curve.map_to_curve_sswu` (same outputs, asserted in
    tests).  t: Fq2 planes (2 × (26, m)) → affine (x, y) on E'."""
    m = t[0].shape[1]
    Zc = _kc2("H2C_Z", m)
    tt = fq2_mul(t, t)
    tv1 = fq2_mul(Zc, tt)                           # Z t²
    tv2 = fq2_add(fq2_mul(tv1, tv1), tv1)           # Z²t⁴ + Zt²
    d_zero = k_fq2_is_zero(tv2)
    x1 = fq2_mul(_kc2("H2C_NEGBA", m), fq2_add(_fq2_one(m), fq2_inv(tv2)))
    x1 = _fq2_sel(d_zero, _kc2("H2C_X1EXC", m), x1)
    A = _kc2("H2C_A", m)
    B = _kc2("H2C_B", m)
    gx1 = fq2_add(fq2_mul(fq2_mul(x1, x1), x1), fq2_add(fq2_mul(A, x1), B))
    c = k_fq2_pow_e16(gx1)
    # 8-candidate sqrt (see ..hash_to_curve.sqrt_or_z_times).
    y1 = _fq2_zero(m)
    s = _fq2_zero(m)
    is_qr = jnp.zeros((1, m), bool)
    zgx1 = fq2_mul(Zc, gx1)
    for k in range(4):
        cand = fq2_mul(c, _kc2(f"H2C_E8I{k}", m))
        ok = k_fq2_eq(fq2_mul(cand, cand), gx1)
        y1 = _fq2_sel(ok, cand, y1)
        is_qr = is_qr | ok
    for k in range(4):
        cand = fq2_mul(c, _kc2(f"H2C_T{k}", m))
        ok = k_fq2_eq(fq2_mul(cand, cand), zgx1)
        s = _fq2_sel(ok, cand, s)
    x2 = fq2_mul(tv1, x1)
    y2 = fq2_mul(fq2_mul(tv1, t), s)
    x = _fq2_sel(is_qr, x1, x2)
    y = _fq2_sel(is_qr, y1, y2)
    flip = k_sgn0_fq2(t) != k_sgn0_fq2(y)
    y = _fq2_sel(flip, fq2_neg(y), y)
    return x, y


def k_iso_map_proj(x, y):
    """3-isogeny E' → E as projective output (no inversions): x = XN/XD,
    y·YN/YD → (XN·YD, y·YN·XD, XD·YD).  Twin of
    :func:`..hash_to_curve.iso_map`."""
    m = x[0].shape[1]
    x_2 = fq2_mul(x, x)
    x_3 = fq2_mul(x_2, x)

    def poly(tag, degree, monic):
        acc = _kc2(f"H2C_{tag}0", m)
        pows = (None, x, x_2, x_3)
        terms = []
        for i in range(1, degree + 1):
            if monic and i == degree:
                continue
            terms.append(fq2_mul(_kc2(f"H2C_{tag}{i}", m), pows[i]))
        for tm in terms:
            acc = fq2_add(acc, tm)
        if monic:
            acc = fq2_add(acc, pows[degree])
        return acc

    xn = poly("XN", 3, monic=False)
    xd = poly("XD", 2, monic=True)
    yn = fq2_mul(y, poly("YN", 3, monic=False))
    yd = poly("YD", 3, monic=True)
    return (fq2_mul(xn, yd), fq2_mul(yn, xd), fq2_mul(xd, yd))


def k_g2_neg(p):
    return (p[0], fq2_neg(p[1]), p[2])


def k_g2_identity(m: int):
    """Materialized projective G2 identity (0 : 1 : 0)."""
    return (_fq2_zero(m), _fq2_one(m), _fq2_zero(m))


def k_g2_mul_x_abs(p):
    """[|x|]·P, MSB-first double-and-add over the 64 static x bits."""
    m = p[0][0].shape[1]
    acc = k_g2_identity(m)
    xbits = _KC["xbits"]

    def body(i, acc):
        acc = point_add(_G2ops, acc, acc)
        added = point_add(_G2ops, acc, p)
        take = jnp.full((1, m), xbits[i, 0] == 1)
        return point_select(_G2ops, take, added, acc)

    return jax.lax.fori_loop(0, X_BITS_FULL.shape[0], body, acc)


def k_psi(p):
    """Untwist-Frobenius-twist endomorphism, projective (twin of
    :func:`..hash_to_curve.psi`)."""
    m = p[0][0].shape[1]
    conj = tuple(fq2_conj(c) for c in p)
    return (fq2_mul(_kc2("H2C_PSI_CX", m), conj[0]),
            fq2_mul(_kc2("H2C_PSI_CY", m), conj[1]),
            conj[2])


def k_clear_cofactor(p):
    """Budroni–Pintore: h_eff·P = ([x²]P − [x]P − P) + ψ([x]P − P) +
    ψ²([2]P) — twin of :func:`..hash_to_curve.clear_cofactor`."""
    t1 = k_g2_neg(k_g2_mul_x_abs(p))            # [x]P (x < 0)
    t2 = k_g2_neg(k_g2_mul_x_abs(t1))           # [x²]P
    acc = point_add(_G2ops, t2, k_g2_neg(t1))
    acc = point_add(_G2ops, acc, k_g2_neg(p))
    acc = point_add(_G2ops, acc, k_psi(point_add(_G2ops, t1, k_g2_neg(p))))
    return point_add(_G2ops, acc, k_psi(k_psi(point_add(_G2ops, p, p))))


def _hash_g2_kernel(cref, xbits_ref, pbits_ref, band_ref, e16_ref, u_ref,
                    out_ref):
    _bind_consts(cref, xbits_ref, pbits_ref, band_ref)
    _KC["e16"] = e16_ref
    # in_mosaic is a trace-time flag: scope it to this trace so an eager /
    # interpret drive of the k_* helpers afterwards doesn't inherit it
    # (pltpu.repeat outside Mosaic would crash — ADVICE r4).
    _KC["in_mosaic"] = True
    try:
        M = LANE_BLOCK
        planes = unpack_planes(u_ref[:], 2)
        t = (planes[0], planes[1])              # (26, 2M): [u0 | u1] blocks
        x, y = k_sswu_map(t)
        q = k_iso_map_proj(x, y)
        # Combine u0 + u1: roll lane halves together (aligned 128-concat).
        rolled = tuple((jnp.concatenate([c0[:, M:], c0[:, :M]], axis=1),
                        jnp.concatenate([c1[:, M:], c1[:, :M]], axis=1))
                       for (c0, c1) in q)
        p = point_add(_G2ops, q, rolled)
        p = tuple((c0[:, :M], c1[:, :M]) for (c0, c1) in p)
        p = k_clear_cofactor(p)
        zi = fq2_inv(p[2])
        xa = fq2_mul(p[0], zi)
        ya = fq2_mul(p[1], zi)
        out_ref[:] = pack_planes([xa[0], xa[1], ya[0], ya[1]])
    finally:
        _KC["in_mosaic"] = False


@jax.jit
def hash_g2_kernel_call(u_planes):
    """u (64, 2M) interleaved per 128-message cell (cell g's lanes
    [g·256, g·256+128) hold u0, [g·256+128, g·256+256) hold u1) →
    (128, M) affine G2 columns, Miller-kernel G2 layout."""
    m2 = u_planes.shape[1]
    if m2 % (2 * LANE_BLOCK):
        raise ValueError("pad hash lanes to 2 · 128 per cell")
    g = m2 // (2 * LANE_BLOCK)
    return pl.pallas_call(
        _hash_g2_kernel,
        grid=(g,),
        in_specs=_htc_const_specs() + [
            pl.BlockSpec((2 * BLOCK_ROWS, 2 * LANE_BLOCK), lambda i: (0, i),
                         memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((4 * BLOCK_ROWS, LANE_BLOCK), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((4 * BLOCK_ROWS, g * LANE_BLOCK),
                                       jnp.uint32),
        compiler_params=_COMPILER_PARAMS,
    )(*_htc_const_args(), u_planes)


# -- host marshalling ---------------------------------------------------------


from functools import lru_cache


@lru_cache(maxsize=1 << 14)
def _u_cols(msg: bytes) -> bytes:
    """Montgomery u-value columns for one message (2 × 64 rows), memoised
    — repeated messages across verify calls (same attestation data on
    many subnets) skip the expand+to_mont work."""
    u0, u1 = H2C.hash_to_field_fq2(msg, 2)
    out = np.zeros((2, 2 * BLOCK_ROWS), np.uint32)
    for j, u in enumerate((u0, u1)):
        out[j, 0:26] = LF.to_mont(u[0])
        out[j, 32:58] = LF.to_mont(u[1])
    return out.tobytes()


def u_planes_for_messages(messages, n_cells: int) -> np.ndarray:
    """expand_message_xmd each message (host SHA-256) and pack the Fq2
    u-values into the kernel's interleaved Montgomery column layout.
    ``messages``: list of (cell, slot, bytes); cells beyond the list pad
    with zero (still well-defined SSWU inputs, masked downstream)."""
    out = np.zeros((2 * BLOCK_ROWS, n_cells * 2 * LANE_BLOCK), np.uint32)
    for cell, slot, msg in messages:
        cols = np.frombuffer(_u_cols(bytes(msg)), np.uint32).reshape(2, -1)
        base = cell * 2 * LANE_BLOCK
        out[:, base + slot] = cols[0]
        out[:, base + LANE_BLOCK + slot] = cols[1]
    return out
