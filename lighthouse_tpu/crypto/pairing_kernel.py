"""Pallas TPU kernels for batched BLS12-381 pairing — the production path.

The XLA limb modules (:mod:`.limb_field`/:mod:`.limb_pairing`) are
semantically exact but dispatch-bound: a Miller loop lowers to ~400k tiny
kernel launches, and at ~0.4 ms/launch on the axon tunnel that is minutes
per batch.  Here the whole loop body lives inside single Pallas programs —
the same move as the Merkle sub-tree kernel (:mod:`..ops.merkle_kernel`) —
so one launch runs the full 63-iteration Miller loop for a lane-batch of
pairs with every intermediate in VMEM/registers.

Data layout: **limb planes**.  An Fq element batch is a ``(26, M)`` uint32
array — 16-bit limbs down the sublanes, M independent elements across the
vector lanes.  Tower elements are python tuples of planes (Fq2 = 2, Fq6 =
3×Fq2, Fq12 = 2×Fq6), and every tower multiply concatenates its base
products along the lane axis so the kernel issues ONE wide ``mont_mul``
per level — Karatsuba all the way down (3/6/18 ⇒ 54 base products per
Fq12 multiply instead of schoolbook 144).

Mosaic rejects captured array constants, so every field/Frobenius constant
is packed into one ``(rows, 1)`` uint32 input (:data:`CONSTS_PLANES`) and
the static exponent bit strings ride along as SMEM inputs; kernels call
:func:`_bind_consts` first, and the in-kernel helpers read the bound
slices.  Semantics are bit-identical to the XLA path (Montgomery residues
< 2N, full-width reduction, HHT cubed final exponentiation), so the host
oracle (:mod:`.pairing`) validates both.

Kernels:

- :func:`miller_kernel_call` — batched Miller loops (63-iter fori_loop
  in-kernel, conditional add-step lane-selected per the static bit string).
- :func:`product_kernel_call` — masked lane product folded (lane-roll
  butterfly) down to 128 residue-class products; the host multiplies those
  and runs ONE shared :func:`..pairing.final_exponentiation_cubed`.
- :func:`prepare_kernel_call` — per-set G1 pubkey aggregation (K-major
  lane blocks, sequential-K fori accumulate), 64-bit RLC double-and-add
  ladders for the aggregates and for −c_i·G, and batched Fermat-ladder
  affine conversion; the signature side of the RLC rides the pairing
  bilinearity (∏ e(c_i·pk_i, H_i)·∏ e(−c_i·G, σ_i) == 1), so no G2
  ladder exists at all.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import fields as F
from . import limb_field as LF
from . import limb_pairing as XP

LIMBS = 26
M16 = np.uint32(0xFFFF)
U32 = jnp.uint32

X_BITS_MILLER = XP.X_BITS_MILLER     # 63 bits, MSB-first (implicit top 1)
X_BITS_FULL = XP.X_BITS_FULL         # 64 bits
P_MINUS_2_BITS = XP.P_MINUS_2_BITS   # 379 bits


# -- packed constants --------------------------------------------------------

def _build_consts() -> tuple[np.ndarray, dict]:
    """Stack every plane constant into one (rows, 1) u32 array + slice map.

    Each block is padded to 32 rows so every in-kernel slice starts on a
    sublane-tile boundary — Mosaic gives offset layouts to unaligned
    slices, and a later lane-concat of mixed-offset pieces fails to lower.
    """
    blocks: list[np.ndarray] = []
    index: dict[str, tuple[int, int]] = {}

    def put(name: str, limbs: np.ndarray):
        start = sum(b.shape[0] for b in blocks)
        arr = np.asarray(limbs, np.uint32).reshape(-1, 1)
        pad = (-arr.shape[0]) % 32
        if pad:
            arr = np.concatenate([arr, np.zeros((pad, 1), np.uint32)])
        blocks.append(arr)
        index[name] = (start, start + len(np.asarray(limbs).reshape(-1)))

    put("N", LF.N_LIMBS)
    put("NPRIME", LF._NPRIME_LIMBS)
    put("N2", LF.N2_LIMBS)
    put("ONE", LF.ONE_MONT)
    for k in (2, 4, 8, 16):
        put(f"K{k}", LF.int_to_limbs(k * F.P))
    for k in range(8):
        put(f"ZP{k}", LF.int_to_limbs(k * F.P))
    for n in (1, 2, 3):
        gam = np.asarray(XP._GAMMA[n])  # (2, 3, 2, 26)
        for i in range(2):
            for j in range(3):
                for u in range(2):
                    put(f"FROB{n}_{i}{j}{u}", gam[i, j, u])
    from . import curve as C
    ng = C.g1_neg(C.G1_GEN)
    put("NEGG_X", LF.to_mont(ng[0]))
    put("NEGG_Y", LF.to_mont(ng[1]))

    # Hash-to-curve constants (SSWU + isogeny + psi cofactor clearing).
    from . import hash_to_curve as H2C

    def put2(name: str, v):
        put(name + "0", LF.to_mont(v[0] % F.P))
        put(name + "1", LF.to_mont(v[1] % F.P))

    put2("H2C_A", H2C.A_TWIST)
    put2("H2C_B", H2C.B_TWIST)
    put2("H2C_Z", H2C.Z_SSWU)
    neg_b_over_a = F.fq2_mul(F.fq2_neg(H2C.B_TWIST), F.fq2_inv(H2C.A_TWIST))
    put2("H2C_NEGBA", neg_b_over_a)
    x1_exc = F.fq2_mul(H2C.B_TWIST,
                       F.fq2_inv(F.fq2_mul(H2C.Z_SSWU, H2C.A_TWIST)))
    put2("H2C_X1EXC", x1_exc)
    for k in range(4):
        put2(f"H2C_E8I{k}", H2C.E8_INV_POWS[k])
        put2(f"H2C_T{k}", H2C.T_KS[k])
    for tag, coeffs in (("XN", H2C._ISO3_X_NUM), ("XD", H2C._ISO3_X_DEN),
                        ("YN", H2C._ISO3_Y_NUM), ("YD", H2C._ISO3_Y_DEN)):
        for i, cf in enumerate(coeffs):
            put2(f"H2C_{tag}{i}", cf)
    put2("H2C_PSI_CX", H2C._PSI_CX)
    put2("H2C_PSI_CY", H2C._PSI_CY)
    put("RAW_ONE", LF.int_to_limbs(1))  # mont→canonical via mont_mul
    return np.concatenate(blocks, axis=0), index


CONSTS_PLANES, _CONST_INDEX = _build_consts()

# (52, 1352) f32 selection matrix for the MXU band product (transposed so
# the in-kernel contraction is SEL @ feat → (52, M) — see k_band_mxu).
BAND_SEL_T = LF.band_sel_matrix(2 * LIMBS).T.copy()

# Bound during kernel tracing: name → plane value; plus bit-string refs.
_KC: dict = {}


def _bind_consts(cref, xbits_ref=None, pbits_ref=None,
                 band_ref=None) -> None:
    c = cref[:]
    for name, (a, b) in _CONST_INDEX.items():
        _KC[name] = c[a:b]
    for n in (1, 2, 3):
        _KC[f"FROBT{n}"] = tuple(
            tuple((_KC[f"FROB{n}_{i}{j}0"], _KC[f"FROB{n}_{i}{j}1"])
                  for j in range(3)) for i in range(2))
    _KC["xbits"] = xbits_ref
    _KC["pbits"] = pbits_ref
    # MXU band-selection matrix; None (eager/legacy drives) falls back to
    # the VPU pad-and-add band inside k_mont_mul.
    _KC["band"] = band_ref
    # Default OFF: only the hash-to-curve kernel trace flips this (its
    # pltpu.repeat materialization is Mosaic-only); re-binding here keeps
    # the process-global flag from leaking into later eager/CPU drives.
    _KC["in_mosaic"] = False


def _const_specs():
    return [pl.BlockSpec(memory_space=pltpu.VMEM),   # consts
            pl.BlockSpec(memory_space=pltpu.SMEM),   # x bits
            pl.BlockSpec(memory_space=pltpu.SMEM),   # p−2 bits
            pl.BlockSpec(memory_space=pltpu.VMEM)]   # band-sel matrix


def _const_args():
    return (jnp.asarray(CONSTS_PLANES),
            jnp.asarray(X_BITS_FULL.reshape(-1, 1).astype(np.int32)),
            jnp.asarray(P_MINUS_2_BITS.reshape(-1, 1).astype(np.int32)),
            jnp.asarray(BAND_SEL_T))


# ---------------------------------------------------------------------------
# In-kernel base-field ops on (26, M) planes
# ---------------------------------------------------------------------------


def k_carry(t, ncols: int, keep_carry: bool = False):
    """Ripple-normalize ``ncols`` uint32 columns (< 2^23) to 16-bit limbs."""
    rows = []
    c = jnp.zeros_like(t[0:1])
    for k in range(ncols):
        v = t[k:k + 1] + c
        rows.append(v & M16)
        c = v >> np.uint32(16)
    if keep_carry:
        rows.append(c)
    return jnp.concatenate(rows, axis=0)


def k_carry_i32(d, ncols: int):
    """Signed ripple for int32 columns (value in [0, 2^(16·ncols)))."""
    rows = []
    c = jnp.zeros_like(d[0:1])
    for k in range(ncols):
        v = d[k:k + 1] + c
        rows.append(v & np.int32(0xFFFF))
        c = v >> 16
    return jnp.concatenate(rows, axis=0).astype(U32)


def _cond_sub_raw(x, k_plane):
    d = x.astype(jnp.int32) - k_plane.astype(jnp.int32)
    rows = []
    c = jnp.zeros_like(d[0:1])
    for k in range(LIMBS):
        v = d[k:k + 1] + c
        rows.append(v & np.int32(0xFFFF))
        c = v >> 16
    norm = jnp.concatenate(rows, axis=0).astype(U32)
    return jnp.where(c == 0, norm, x)


def k_add(a, b):
    """a + b < 2N (cond-subtracted), matching :func:`..limb_field.add`."""
    return _cond_sub_raw(k_carry(a + b, LIMBS), _KC["N2"])


def k_sub(a, b):
    d = a.astype(jnp.int32) + _KC["N2"].astype(jnp.int32) - b.astype(jnp.int32)
    return _cond_sub_raw(k_carry_i32(d, LIMBS), _KC["N2"])


def k_neg(a):
    d = _KC["N2"].astype(jnp.int32) - a.astype(jnp.int32)
    return k_carry_i32(d, LIMBS)


def k_muls(a, s: int):
    """a · s for small 0 ≤ s ≤ 16, reduced below 2N (value < 32N < 2^416)."""
    if not 0 <= s <= 16:
        raise ValueError("small-scalar multiply supports 0..16")
    x = k_carry(a * np.uint32(s), LIMBS)
    for k in (16, 8, 4, 2):
        x = _cond_sub_raw(x, _KC[f"K{k}"])
    return x


def k_band(a, b, ncols: int):
    """Schoolbook column sums of a·b over planes, pad-and-add form.
    Columns < 52·2^16 < 2^23."""
    t = jnp.zeros((ncols, a.shape[1]), U32)
    for i in range(LIMBS):
        p = a[i:i + 1] * b
        lo = p & M16
        hi = p >> np.uint32(16)
        wl = min(LIMBS, ncols - i)
        if wl > 0:
            t = t + jnp.pad(lo[:wl], ((i, ncols - i - wl), (0, 0)))
        wh = min(LIMBS, ncols - i - 1)
        if wh > 0:
            t = t + jnp.pad(hi[:wh], ((i + 1, ncols - i - 1 - wh), (0, 0)))
    return t


def k_band_mxu(a, b, ncols: int):
    """MXU band product on planes: the column accumulation of
    :func:`k_band` as ONE (ncols, 1352) × (1352, M) f32 matmul against
    the bound selection matrix (:data:`BAND_SEL_T`).  Exact: partial
    terms < 2^16, column sums ≤ 52 terms < 2^22 — inside f32's
    integer-exact range; bit-identical to :func:`k_band` (asserted in
    tests/test_bls_shard.py and scripts/validate_bls_shard.py)."""
    # [:ncols] both LOADS the bound Ref (dot_general rejects raw Refs)
    # and drops the rows a narrow band never needs (ncols=26 halves the
    # m-band matmul).  Prefix slices keep the sublane offset at 0.
    sel = _KC["band"][:ncols]
    los, his = [], []
    for i in range(LIMBS):
        p = a[i:i + 1] * b                  # row i of the outer product
        los.append(p & M16)
        his.append(p >> np.uint32(16))
    feat = jnp.concatenate(los + his, axis=0).astype(jnp.float32)
    t = jax.lax.dot_general(
        sel, feat, dimension_numbers=(((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)  # (ncols, M)
    return t.astype(U32)


def _k_band_any(a, b, ncols: int):
    """Band-product dispatch: MXU matmul when enabled AND a selection
    matrix rode in with the consts; VPU pad-and-add otherwise."""
    if LF.use_mxu() and _KC.get("band") is not None:
        return k_band_mxu(a, b, ncols)
    return k_band(a, b, ncols)


def k_mont_mul(a, b):
    """Batched Montgomery product on planes — same algorithm and bounds as
    :func:`..limb_field.mont_mul` (full-width reduction).

    The final carry pass collects only the high 26 rows into a FRESH
    concat: slicing rows [26:52] out of a 53-row array would give the
    value a sublane-offset layout, which poisons every later lane-concat
    it reaches (Mosaic can't mix offset layouts in one concatenate)."""
    t = _k_band_any(a, b, 2 * LIMBS)
    t_low = k_carry(t[:LIMBS], LIMBS)
    m = k_carry(_k_band_any(t_low, _KC["NPRIME"], LIMBS), LIMBS)
    u = _k_band_any(m, _KC["N"], 2 * LIMBS)
    s = t + u
    rows = []
    c = jnp.zeros_like(s[0:1])
    for k in range(2 * LIMBS):
        v = s[k:k + 1] + c
        if k >= LIMBS:
            rows.append(v & M16)
        c = v >> np.uint32(16)
    # (T + mN)/R < 2N < 2^382 ⇒ the final carry-out is always zero.
    return jnp.concatenate(rows, axis=0)


def k_is_zero(a):
    """(1, M) bool: a ≡ 0 mod N for lazy values < 8N."""
    acc = None
    for k in range(8):
        eq = jnp.all(a == _KC[f"ZP{k}"], axis=0, keepdims=True)
        acc = eq if acc is None else (acc | eq)
    return acc


def k_fq_inv(a):
    """Fermat ladder a^(p−2); inv(0) = 0.  (26, M) planes."""
    one = jnp.broadcast_to(_KC["ONE"], a.shape)
    pbits = _KC["pbits"]

    def body(i, acc):
        acc = k_mont_mul(acc, acc)
        take = pbits[i, 0] == 1
        return jnp.where(take, k_mont_mul(acc, a), acc)

    return jax.lax.fori_loop(0, P_MINUS_2_BITS.shape[0], body, one)


# ---------------------------------------------------------------------------
# Tower on plane tuples: Fq2 = (c0, c1); Fq6 = 3×Fq2; Fq12 = 2×Fq6
# ---------------------------------------------------------------------------


def _mont_many(pairs):
    """One wide mont_mul over a list of (a, b) plane pairs → list of planes."""
    a = jnp.concatenate([p[0] for p in pairs], axis=1)
    b = jnp.concatenate([p[1] for p in pairs], axis=1)
    out = k_mont_mul(a, b)
    m = pairs[0][0].shape[1]
    return [out[:, i * m:(i + 1) * m] for i in range(len(pairs))]


def fq2_add(a, b):
    return (k_add(a[0], b[0]), k_add(a[1], b[1]))


def fq2_sub(a, b):
    return (k_sub(a[0], b[0]), k_sub(a[1], b[1]))


def fq2_neg(a):
    return (k_neg(a[0]), k_neg(a[1]))


def fq2_conj(a):
    return (a[0], k_neg(a[1]))


def fq2_muls(a, s: int):
    return (k_muls(a[0], s), k_muls(a[1], s))


def fq2_mul_by_xi(a):
    """ξ = 1 + u:  (a0 − a1) + (a0 + a1)u."""
    return (k_sub(a[0], a[1]), k_add(a[0], a[1]))


def _fq2_mul_parts(a, b):
    """Karatsuba part list: [a0b0, a1b1, (a0+a1)(b0+b1)]."""
    return [(a[0], b[0]), (a[1], b[1]),
            (k_add(a[0], a[1]), k_add(b[0], b[1]))]


def _fq2_from_parts(p):
    m0, m1, m2 = p
    return (k_sub(m0, m1), k_sub(m2, k_add(m0, m1)))


def fq2_mul(a, b):
    return _fq2_from_parts(_mont_many(_fq2_mul_parts(a, b)))


def fq2_mul_many(pairs):
    """Batch several independent Fq2 products into one mont_mul."""
    parts = []
    for a, b in pairs:
        parts.extend(_fq2_mul_parts(a, b))
    flat = _mont_many(parts)
    return [_fq2_from_parts(flat[3 * i:3 * i + 3]) for i in range(len(pairs))]


def _fq6_mul_pairs(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    return [(a0, b0), (a1, b1), (a2, b2),
            (fq2_add(a0, a1), fq2_add(b0, b1)),
            (fq2_add(a1, a2), fq2_add(b1, b2)),
            (fq2_add(a0, a2), fq2_add(b0, b2))]


def _fq6_from_parts(v):
    v0, v1, v2, v01, v12, v02 = v
    c0 = fq2_add(v0, fq2_mul_by_xi(fq2_sub(v12, fq2_add(v1, v2))))
    c1 = fq2_add(fq2_sub(v01, fq2_add(v0, v1)), fq2_mul_by_xi(v2))
    c2 = fq2_add(fq2_sub(v02, fq2_add(v0, v2)), v1)
    return (c0, c1, c2)


def fq6_mul(a, b):
    return _fq6_from_parts(fq2_mul_many(_fq6_mul_pairs(a, b)))


def fq6_add(a, b):
    return tuple(fq2_add(x, y) for x, y in zip(a, b))


def fq6_sub(a, b):
    return tuple(fq2_sub(x, y) for x, y in zip(a, b))


def fq6_neg(a):
    return tuple(fq2_neg(x) for x in a)


def fq6_mul_by_v(a):
    return (fq2_mul_by_xi(a[2]), a[0], a[1])


def fq12_mul(a, b):
    """Karatsuba-2 over Fq6: 3 Fq6 products in one wide mont_mul."""
    a0, a1 = a
    b0, b1 = b
    pairs = (_fq6_mul_pairs(a0, b0) + _fq6_mul_pairs(a1, b1)
             + _fq6_mul_pairs(fq6_add(a0, a1), fq6_add(b0, b1)))
    flat = fq2_mul_many(pairs)
    v00 = _fq6_from_parts(flat[0:6])
    v11 = _fq6_from_parts(flat[6:12])
    vxx = _fq6_from_parts(flat[12:18])
    c0 = fq6_add(v00, fq6_mul_by_v(v11))
    c1 = fq6_sub(vxx, fq6_add(v00, v11))
    return (c0, c1)


def fq12_sqr(a):
    return fq12_mul(a, a)


def fq12_conj(a):
    return (a[0], fq6_neg(a[1]))


def fq12_select(take, a, b):
    return tuple(tuple((jnp.where(take, x, y), jnp.where(take, u, v))
                       for (x, u), (y, v) in zip(ca, cb))
                 for ca, cb in zip(a, b))


def fq12_one_like(m: int):
    one = jnp.broadcast_to(_KC["ONE"], (LIMBS, m))
    zero = jnp.zeros((LIMBS, m), U32)
    return (((one, zero), (zero, zero), (zero, zero)),
            ((zero, zero), (zero, zero), (zero, zero)))


def fq12_frobenius(a, n: int):
    tab = _KC[f"FROBT{n}"]
    pairs = []
    for i in range(2):
        for j in range(3):
            c = a[i][j]
            if n % 2:
                c = fq2_conj(c)
            g = tab[i][j]
            gb = (jnp.broadcast_to(g[0], c[0].shape),
                  jnp.broadcast_to(g[1], c[1].shape))
            pairs.append((c, gb))
    muls = fq2_mul_many(pairs)
    return ((muls[0], muls[1], muls[2]), (muls[3], muls[4], muls[5]))


def fq2_inv(a):
    n = k_add(k_mont_mul(a[0], a[0]), k_mont_mul(a[1], a[1]))
    ni = k_fq_inv(n)
    return (k_mont_mul(a[0], ni), k_mont_mul(k_neg(a[1]), ni))


def fq6_inv(a):
    a0, a1, a2 = a
    p = fq2_mul_many([(a0, a0), (a1, a2), (a2, a2), (a1, a1),
                      (a0, a1), (a0, a2)])
    a00, a12, a22, a11, a01, a02 = p
    c0 = fq2_sub(a00, fq2_mul_by_xi(a12))
    c1 = fq2_sub(fq2_mul_by_xi(a22), a01)
    c2 = fq2_sub(a11, a02)
    q = fq2_mul_many([(a0, c0), (a2, c1), (a1, c2)])
    nrm = fq2_add(q[0], fq2_mul_by_xi(fq2_add(q[1], q[2])))
    ni = fq2_inv(nrm)
    inv = fq2_mul_many([(c0, ni), (c1, ni), (c2, ni)])
    return (inv[0], inv[1], inv[2])


def fq12_inv(a):
    a0, a1 = a
    s0 = fq6_mul(a0, a0)
    s1 = fq6_mul(a1, a1)
    nrm = fq6_sub(s0, fq6_mul_by_v(s1))
    ni = fq6_inv(nrm)
    return (fq6_mul(a0, ni), fq6_mul(fq6_neg(a1), ni))


def fq12_is_one(a):
    one = fq12_one_like(a[0][0][0].shape[1])
    acc = None
    for i in range(2):
        for j in range(3):
            for u in range(2):
                z = k_is_zero(k_sub(a[i][j][u], one[i][j][u]))
                acc = z if acc is None else (acc & z)
    return acc


# -- plane packing: 32-row blocks ↔ tuples ----------------------------------
#
# Ref I/O uses one 32-row block per Fq plane (26 limb rows + 6 zero rows):
# slicing a ref at a non-multiple-of-8 row gives the value a sublane-offset
# layout, and Mosaic cannot lane-concat mixed-offset pieces (same reason the
# constant blocks are 32-row padded).

BLOCK_ROWS = 32


def pack_planes(planes):
    """List of (26, M) planes → (32·k, M) block layout."""
    m = planes[0].shape[1]
    z = jnp.zeros((BLOCK_ROWS - LIMBS, m), U32)
    out = []
    for p in planes:
        out.append(p)
        out.append(z)
    return jnp.concatenate(out, axis=0)


def unpack_planes(x, k: int):
    return [x[i * BLOCK_ROWS:i * BLOCK_ROWS + LIMBS] for i in range(k)]


def pack_fq12(a):
    return pack_planes([a[i][j][u] for i in range(2) for j in range(3)
                        for u in range(2)])


def unpack_fq12(x):
    c = unpack_planes(x, 12)
    return (((c[0], c[1]), (c[2], c[3]), (c[4], c[5])),
            ((c[6], c[7]), (c[8], c[9]), (c[10], c[11])))


def unpack_fq2s(x, k: int):
    c = unpack_planes(x, 2 * k)
    return [(c[2 * i], c[2 * i + 1]) for i in range(k)]


# ---------------------------------------------------------------------------
# Generic projective point ops (RCB complete addition), G1/G2
# ---------------------------------------------------------------------------


class _G1ops:
    coord_planes = LIMBS
    mul_many = staticmethod(_mont_many)
    add = staticmethod(k_add)
    sub = staticmethod(k_sub)

    @staticmethod
    def b3(t):
        return k_muls(t, 12)

    muls = staticmethod(k_muls)

    @staticmethod
    def zero_is(z):
        return k_is_zero(z)

    @staticmethod
    def one_like(m):
        return jnp.broadcast_to(_KC["ONE"], (LIMBS, m))

    @staticmethod
    def zero_like(m):
        return jnp.zeros((LIMBS, m), U32)


class _G2ops:
    coord_planes = 2 * LIMBS
    mul_many = staticmethod(fq2_mul_many)
    add = staticmethod(fq2_add)
    sub = staticmethod(fq2_sub)

    @staticmethod
    def b3(t):
        return fq2_muls(fq2_mul_by_xi(t), 12)

    muls = staticmethod(fq2_muls)

    @staticmethod
    def zero_is(z):
        return k_is_zero(z[0]) & k_is_zero(z[1])

    @staticmethod
    def one_like(m):
        return (jnp.broadcast_to(_KC["ONE"], (LIMBS, m)),
                jnp.zeros((LIMBS, m), U32))

    @staticmethod
    def zero_like(m):
        return (jnp.zeros((LIMBS, m), U32), jnp.zeros((LIMBS, m), U32))


def point_add(ops, p, q):
    """Complete addition (same formulas/order as limb_curve.point_add)."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    r1 = ops.mul_many([
        (X1, X2), (Y1, Y2), (Z1, Z2),
        (ops.add(X1, Y1), ops.add(X2, Y2)),
        (ops.add(Y1, Z1), ops.add(Y2, Z2)),
        (ops.add(X1, Z1), ops.add(X2, Z2))])
    t0, t1, t2, pxy, pyz, pxz = r1
    s3 = ops.sub(pxy, ops.add(t0, t1))
    s4 = ops.sub(pyz, ops.add(t1, t2))
    s5 = ops.sub(pxz, ops.add(t0, t2))
    b3t2 = ops.b3(t2)
    um = ops.sub(t1, b3t2)
    up = ops.add(t1, b3t2)
    r2 = ops.mul_many([
        (s3, um), (s4, s5), (up, um), (t0, s5), (s4, up), (t0, s3)])
    a_s3um, a_s4s5, a_upum, a_t0s5, a_s4up, a_t0s3 = r2
    X3 = ops.sub(a_s3um, ops.b3(a_s4s5))
    Y3 = ops.add(a_upum, ops.muls(ops.b3(a_t0s5), 3))
    Z3 = ops.add(a_s4up, ops.muls(a_t0s3, 3))
    return (X3, Y3, Z3)


def point_select(ops, take, p, q):
    def sel(a, b):
        if isinstance(a, tuple):
            return tuple(jnp.where(take, x, y) for x, y in zip(a, b))
        return jnp.where(take, a, b)
    return tuple(sel(a, b) for a, b in zip(p, q))


def point_identity(ops, m: int):
    return (ops.zero_like(m), ops.one_like(m), ops.zero_like(m))


def scalar_mul(ops, p, lo, hi, nbits: int = 64):
    """Per-lane double-and-add; lo/hi are (1, M) uint32 scalar words."""
    m = (p[0][0] if isinstance(p[0], tuple) else p[0]).shape[1]
    acc = point_identity(ops, m)

    def body(i, carry):
        acc, base = carry
        word = jnp.where(i < 32, lo, hi)
        bit = (word >> (i.astype(U32) % np.uint32(32))) & np.uint32(1)
        added = point_add(ops, acc, base)
        acc = point_select(ops, bit == 1, added, acc)
        base = point_add(ops, base, base)
        return (acc, base)

    acc, _ = jax.lax.fori_loop(0, nbits, body, (acc, p))
    return acc


# ---------------------------------------------------------------------------
# Miller loop kernel
# ---------------------------------------------------------------------------


LANE_BLOCK = 128  # Mosaic lane-concat pieces must be 128-aligned

# The Miller/prepare/hash kernels' wide-concat mont_mul temporaries brush
# against Mosaic's default 16 MB scoped-VMEM budget (v5e VMEM is far
# larger); raise the per-kernel limit rather than contorting the code.
# jax ≥ 0.5 renamed TPUCompilerParams → CompilerParams; accept both so
# the module imports (for warmup shape-lowering and donation tests)
# under either.
_CompilerParamsCls = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams
_COMPILER_PARAMS = _CompilerParamsCls(vmem_limit_bytes=64 * 1024 * 1024)


def _line_fq12(A, B, C, m):
    zero = (jnp.zeros((LIMBS, m), U32), jnp.zeros((LIMBS, m), U32))
    return ((A, B, zero), (zero, C, zero))


def _fq2_mul_fq(a, s):
    o = _mont_many([(a[0], s), (a[1], s)])
    return (o[0], o[1])


def _miller_dbl_step(f, T, xP, yP):
    """Doubling half of a Miller iteration: f ← f²·l_{T,T}(P), T ← 2T.
    Line: A = 3X³−2Y²Z, B = −3X²Z·xP, C = 2YZ²·yP."""
    m = xP.shape[1]
    X, Y, Z = T
    XX, YY, ZZ = fq2_mul_many([(X, X), (Y, Y), (Z, Z)])
    X3, Y2Z, X2Z, YZ2 = fq2_mul_many([(X, XX), (YY, Z), (XX, Z), (Y, ZZ)])
    A = fq2_sub(fq2_muls(X3, 3), fq2_muls(Y2Z, 2))
    B = fq2_neg(_fq2_mul_fq(fq2_muls(X2Z, 3), xP))
    C = _fq2_mul_fq(fq2_muls(YZ2, 2), yP)
    l_dbl = _line_fq12(A, B, C, m)
    T2 = point_add(_G2ops, T, T)
    return fq12_mul(fq12_sqr(f), l_dbl), T2


def _miller_add_step(f, T2, Qx, Qy, Q, xP, yP):
    """Addition half: f ← f·l_{T,Q}(P), T ← T + Q (chord through T2, Q)."""
    m = xP.shape[1]
    X, Y, Z = T2
    r = fq2_mul_many([(Qy, Z), (Qx, Z)])
    Nn = fq2_sub(r[0], Y)
    Dd = fq2_sub(r[1], X)
    r2 = fq2_mul_many([(Nn, Qx), (Qy, Dd)])
    A = fq2_sub(r2[0], r2[1])
    B = fq2_neg(_fq2_mul_fq(Nn, xP))
    C = _fq2_mul_fq(Dd, yP)
    l_add = _line_fq12(A, B, C, m)
    T3 = point_add(_G2ops, T2, Q)
    return fq12_mul(f, l_add), T3


def _miller_kernel(cref, xbits_ref, pbits_ref, band_ref, g1_ref, g2_ref, out_ref):
    """One 63-iteration fori; the add-step runs under ``lax.cond`` on the
    static bit, so the 58 zero bits of |x| (Hamming weight 6) skip the
    add-step's ~38% of the loop's products instead of computing and
    discarding it.  (A fully segment-unrolled variant blew the 16 MB
    scoped-VMEM budget — straight-line segments keep too many
    simultaneously-live buffers; the cond body stays loop-scoped.)"""
    _bind_consts(cref, xbits_ref, pbits_ref, band_ref)
    xP, yP = unpack_planes(g1_ref[:], 2)
    Qx, Qy = unpack_fq2s(g2_ref[:], 2)
    m = xP.shape[1]
    Q = (Qx, Qy, _G2ops.one_like(m))
    f0 = fq12_one_like(m)
    xbits = _KC["xbits"]

    def body(i, carry):
        f, T = carry
        f, T = _miller_dbl_step(f, T, xP, yP)
        bit = xbits[i + 1, 0]  # skip the implicit leading 1
        return jax.lax.cond(
            bit == 1,
            lambda f, T: _miller_add_step(f, T, Qx, Qy, Q, xP, yP),
            lambda f, T: (f, T),
            f, T)

    f, _ = jax.lax.fori_loop(0, X_BITS_MILLER.shape[0], body, (f0, Q))
    out_ref[:] = pack_fq12(fq12_conj(f))  # x < 0


@jax.jit
def miller_kernel_call(g1_planes, g2_planes):
    """g1 (64, M) affine blocks, g2 (128, M) → f (384, M) Fq12 blocks.

    M must be a multiple of 128; the grid runs one 128-lane block per cell
    (bounds both VMEM and per-launch latency)."""
    m = g1_planes.shape[1]
    if m % LANE_BLOCK:
        raise ValueError("pad miller lanes to a multiple of 128")
    g = m // LANE_BLOCK
    return pl.pallas_call(
        _miller_kernel,
        grid=(g,),
        in_specs=_const_specs() + [
            pl.BlockSpec((2 * BLOCK_ROWS, LANE_BLOCK), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4 * BLOCK_ROWS, LANE_BLOCK), lambda i: (0, i),
                         memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((12 * BLOCK_ROWS, LANE_BLOCK), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((12 * BLOCK_ROWS, m), jnp.uint32),
        compiler_params=_COMPILER_PARAMS,
    )(*_const_args(), g1_planes, g2_planes)


def _const_block_specs():
    """Const specs for gridded kernels: every cell sees the full blocks."""
    cs = CONSTS_PLANES.shape[0]
    return [pl.BlockSpec((cs, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(BAND_SEL_T.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM)]


# ---------------------------------------------------------------------------
# Lane-product kernel (butterfly to 128 class products)
# ---------------------------------------------------------------------------


def _product_kernel(cref, xbits_ref, pbits_ref, band_ref, f_ref, mask_ref,
                    out_ref, *, lanes: int):
    _bind_consts(cref, xbits_ref, pbits_ref, band_ref)
    f = unpack_fq12(f_ref[:])
    mask = mask_ref[:]
    f = fq12_select(mask != 0, f, fq12_one_like(lanes))
    w = lanes // 2
    while w >= LANE_BLOCK:
        # roll by w lanes via aligned concat; multiply-accumulate.
        def roll(x):
            return jnp.concatenate([x[:, w:], x[:, :w]], axis=1)

        g = tuple(tuple((roll(c0), roll(c1)) for (c0, c1) in c6) for c6 in f)
        f = fq12_mul(f, g)
        w //= 2
    out_ref[:] = pack_fq12(f)


FQ12_ROWS = 12 * BLOCK_ROWS


@jax.jit
def product_kernel_call(f_planes, mask):
    """Masked lane product, reduced to 128 residue-class products.

    f (384, M) blocks, mask (1, M) int32, M a power of two ≥ 128.  Returns
    (384, M) blocks where lane j holds the product of lanes ≡ j (mod 128);
    the host multiplies the first 128 lanes' values for the total.
    """
    m = f_planes.shape[1]
    if m < LANE_BLOCK or m & (m - 1):
        raise ValueError("lane count must be a power of two ≥ 128")
    return pl.pallas_call(
        partial(_product_kernel, lanes=m),
        in_specs=_const_specs() + [pl.BlockSpec(memory_space=pltpu.VMEM),
                                   pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((12 * BLOCK_ROWS, m), jnp.uint32),
        compiler_params=_COMPILER_PARAMS,
    )(*_const_args(), f_planes, mask)


def _product_chunk_kernel(cref, xbits_ref, pbits_ref, band_ref, f_ref,
                          mask_ref, out_ref):
    """One 256-lane chunk → 128 residue-class products (lane j and j+128
    hold the same value after the fold; only [0:128] is written)."""
    _bind_consts(cref, xbits_ref, pbits_ref, band_ref)
    f = unpack_fq12(f_ref[:])
    mask = mask_ref[:]
    f = fq12_select(mask != 0, f, fq12_one_like(2 * LANE_BLOCK))
    g = _fq12_roll(f, LANE_BLOCK)
    f = fq12_mul(f, g)
    half = tuple(tuple((c0[:, :LANE_BLOCK], c1[:, :LANE_BLOCK])
                       for (c0, c1) in c6) for c6 in f)
    out_ref[:] = pack_fq12(half)


@jax.jit
def product_chunks_kernel_call(f_planes, mask):
    """Per-chunk masked lane fold: (384, C·256) Miller outputs →
    (384, C·128) residue-class products, one grid cell per chunk.  The
    concatenated output feeds :func:`finalize_kernel_call` directly."""
    m = f_planes.shape[1]
    if m % (2 * LANE_BLOCK):
        raise ValueError("lane count must be C · 256")
    C = m // (2 * LANE_BLOCK)
    return pl.pallas_call(
        _product_chunk_kernel,
        grid=(C,),
        in_specs=_const_specs() + [
            pl.BlockSpec((12 * BLOCK_ROWS, 2 * LANE_BLOCK), lambda c: (0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2 * LANE_BLOCK), lambda c: (0, c),
                         memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((12 * BLOCK_ROWS, LANE_BLOCK), lambda c: (0, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((12 * BLOCK_ROWS, C * LANE_BLOCK),
                                       jnp.uint32),
        compiler_params=_COMPILER_PARAMS,
    )(*_const_args(), f_planes, mask)


def _miller_fold_kernel(cref, xbits_ref, pbits_ref, band_ref, g1_ref,
                        g2_ref, mask_ref, out_ref):
    """FUSED Miller scan + masked per-chunk lane fold: one 256-lane cell
    runs the 63-iteration Miller loop AND the 256→128 residue-class
    product in the same program, so the σ/RLC product fold stops being a
    separate dispatch (VERDICT r5 item 2).  The fold reuses the Miller
    loop's VMEM-resident f — no (384, 256) HBM round-trip between the
    two stages."""
    _bind_consts(cref, xbits_ref, pbits_ref, band_ref)
    xP, yP = unpack_planes(g1_ref[:], 2)
    Qx, Qy = unpack_fq2s(g2_ref[:], 2)
    m = xP.shape[1]                     # 2 · LANE_BLOCK lanes per cell
    Q = (Qx, Qy, _G2ops.one_like(m))
    f0 = fq12_one_like(m)
    xbits = _KC["xbits"]

    def body(i, carry):
        f, T = carry
        f, T = _miller_dbl_step(f, T, xP, yP)
        bit = xbits[i + 1, 0]           # skip the implicit leading 1
        return jax.lax.cond(
            bit == 1,
            lambda f, T: _miller_add_step(f, T, Qx, Qy, Q, xP, yP),
            lambda f, T: (f, T),
            f, T)

    f, _ = jax.lax.fori_loop(0, X_BITS_MILLER.shape[0], body, (f0, Q))
    f = fq12_conj(f)                    # x < 0
    f = fq12_select(mask_ref[:] != 0, f, fq12_one_like(m))
    f = fq12_mul(f, _fq12_roll(f, LANE_BLOCK))
    half = tuple(tuple((c0[:, :LANE_BLOCK], c1[:, :LANE_BLOCK])
                       for (c0, c1) in c6) for c6 in f)
    out_ref[:] = pack_fq12(half)


@jax.jit
def miller_fold_kernel_call(g1_planes, g2_planes, mask):
    """g1 (64, C·256) affine blocks, g2 (128, C·256), mask (1, C·256)
    int32 → (384, C·128) folded residue-class products — the fused twin
    of :func:`miller_kernel_call` + :func:`product_chunks_kernel_call`.
    The output feeds :func:`finalize_kernel_call` directly."""
    m = g1_planes.shape[1]
    if m % (2 * LANE_BLOCK):
        raise ValueError("pad fused miller lanes to a multiple of 256")
    C = m // (2 * LANE_BLOCK)
    return pl.pallas_call(
        _miller_fold_kernel,
        grid=(C,),
        in_specs=_const_specs() + [
            pl.BlockSpec((2 * BLOCK_ROWS, 2 * LANE_BLOCK), lambda c: (0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4 * BLOCK_ROWS, 2 * LANE_BLOCK), lambda c: (0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2 * LANE_BLOCK), lambda c: (0, c),
                         memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((12 * BLOCK_ROWS, LANE_BLOCK), lambda c: (0, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((12 * BLOCK_ROWS, C * LANE_BLOCK),
                                       jnp.uint32),
        compiler_params=_COMPILER_PARAMS,
    )(*_const_args(), g1_planes, g2_planes, mask)


# ---------------------------------------------------------------------------
# Sigma kernel: per-chunk RLC-scaled signature aggregation (G2)
# ---------------------------------------------------------------------------
#
# The signature side of the batch equation collapses to ONE pairing lane:
#     ∏ e(−c_i·G, σ_i) = e(−G, Σ c_i·σ_i)
# so instead of one Miller lane per set, each 128-set chunk runs a 64-bit
# G2 double-and-add ladder (the same RLC scalars as the pk side) and a
# lane butterfly to fold the chunk's scaled signatures into one point;
# the XLA glue combines the per-chunk partials and hands the single
# aggregate to a dedicated Miller cell paired with the constant −G.


def _sigma_kernel(cref, xbits_ref, pbits_ref, band_ref, sig_ref, mask_ref,
                  lo_ref, hi_ref, out_ref):
    _bind_consts(cref, xbits_ref, pbits_ref, band_ref)
    S = PREP_S
    cols = unpack_fq2s(sig_ref[:], 2)  # [x, y] as Fq2 planes
    live = mask_ref[:] != 0
    pt = point_select(_G2ops, live,
                      (cols[0], cols[1], _G2ops.one_like(S)),
                      point_identity(_G2ops, S))
    scaled = scalar_mul(_G2ops, pt, lo_ref[:], hi_ref[:])
    # Butterfly fold: after log2(S) roll-multiplies every lane holds the
    # full chunk sum.
    w = S // 2
    while w >= 1:
        rolled = tuple(
            (_roll_lanes(c0, w), _roll_lanes(c1, w)) for (c0, c1) in scaled)
        scaled = point_add(_G2ops, scaled, rolled)
        w //= 2
    out_ref[:] = pack_planes([scaled[0][0], scaled[0][1],
                              scaled[1][0], scaled[1][1],
                              scaled[2][0], scaled[2][1]])


@jax.jit
def sigma_kernel_call(sig_cols, mask, lo, hi):
    """sig (128, C·128) affine G2 signature columns, mask/lo/hi (1, C·128)
    → (192, C·128) projective per-chunk Σ c_s·σ_s (every lane of a chunk's
    block holds that chunk's full sum)."""
    m = sig_cols.shape[1]
    if m % PREP_S:
        raise ValueError("sigma lanes must be C · 128")
    C = m // PREP_S
    return pl.pallas_call(
        _sigma_kernel,
        grid=(C,),
        in_specs=_const_specs() + [
            pl.BlockSpec((4 * BLOCK_ROWS, PREP_S), lambda c: (0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, PREP_S), lambda c: (0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, PREP_S), lambda c: (0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, PREP_S), lambda c: (0, c),
                         memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((6 * BLOCK_ROWS, PREP_S), lambda c: (0, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((6 * BLOCK_ROWS, C * PREP_S),
                                       jnp.uint32),
        compiler_params=_COMPILER_PARAMS,
    )(*_const_args(), sig_cols, mask, lo, hi)


def sigma_combine(partials):
    """(192, C·128) per-chunk projective partials → affine Σ over chunks
    as ONE miller-ready G2 column (128,) — XLA glue (tiny work, once per
    verify call).  Returns (g2_col, is_identity)."""
    m = partials.shape[1]
    C = m // PREP_S
    # lane c·128 of each chunk block → (C, 3, 2, 26) limb layout
    comps = partials.reshape(6, BLOCK_ROWS, m)[:, :LIMBS, :]  # (6, 26, m)
    pts = comps[:, :, ::PREP_S]                               # (6, 26, C)
    pts = jnp.transpose(pts, (2, 0, 1)).reshape(C, 3, 2, LIMBS)
    from . import limb_curve as LC
    acc = pts[0]
    for c in range(1, C):
        acc = LC.point_add(LC.G2_OPS, acc, pts[c])
    is_ident = XP.T.fq2_is_zero(acc[2])
    aff = XP.g2_proj_to_affine(acc[None])[0]                  # (2, 2, 26)
    col = jnp.zeros((4 * BLOCK_ROWS,), jnp.uint32)
    col = col.at[0:LIMBS].set(aff[0, 0])
    col = col.at[BLOCK_ROWS:BLOCK_ROWS + LIMBS].set(aff[0, 1])
    col = col.at[2 * BLOCK_ROWS:2 * BLOCK_ROWS + LIMBS].set(aff[1, 0])
    col = col.at[3 * BLOCK_ROWS:3 * BLOCK_ROWS + LIMBS].set(aff[1, 1])
    return col, is_ident


# ---------------------------------------------------------------------------
# Finalize kernel: full lane fold + in-kernel final exponentiation
# ---------------------------------------------------------------------------


def k_fq12_pow_x_abs(f):
    """f^|x| (BLS parameter ladder), 64 static bits from SMEM."""
    m = f[0][0][0].shape[1]
    one = fq12_one_like(m)
    xbits = _KC["xbits"]

    def body(i, acc):
        acc = fq12_sqr(acc)
        take = xbits[i, 0] == 1
        return fq12_select(take, fq12_mul(acc, f), acc)

    return jax.lax.fori_loop(0, X_BITS_FULL.shape[0], body, one)


def k_pow_u(f):
    """f^u for the (negative) BLS parameter — cyclotomic f only."""
    return fq12_conj(k_fq12_pow_x_abs(f))


def k_final_exp_easy(f):
    """Easy part f^((q⁶−1)(q²+1)) — needs the true Fq12 inverse."""
    m = fq12_mul(fq12_conj(f), fq12_inv(f))
    return fq12_mul(fq12_frobenius(m, 2), m)


def k_final_exp_hard(m):
    """HHT hard part ×3: m^(3·(p⁴−p²+1)/r) for cyclotomic m."""
    m1 = fq12_mul(k_pow_u(m), fq12_conj(m))              # m^(u−1)
    k2 = fq12_mul(k_pow_u(m1), fq12_conj(m1))            # ^(u−1)
    k3 = fq12_mul(k_pow_u(k2), fq12_frobenius(k2, 1))    # ^(u+p)
    k4 = fq12_mul(fq12_mul(k_pow_u(k_pow_u(k3)), fq12_frobenius(k3, 2)),
                  fq12_conj(k3))                         # ^(u²+p²−1)
    return fq12_mul(k4, fq12_mul(fq12_sqr(m), m))


def k_final_exp_cubed(f):
    """f^(3·(q¹²−1)/r) — same HHT decomposition as the host oracle
    (:func:`..pairing.final_exponentiation_cubed`) and the XLA twin
    (:func:`..limb_pairing.final_exponentiation_cubed`)."""
    return k_final_exp_hard(k_final_exp_easy(f))


def _roll_lanes(x, w: int):
    """Rotate lanes left by w.  Aligned concat when both pieces are
    128-multiples; ``pltpu.roll`` for sub-128 shifts."""
    m = x.shape[1]
    if w % LANE_BLOCK == 0 and (m - w) % LANE_BLOCK == 0:
        return jnp.concatenate([x[:, w:], x[:, :w]], axis=1)
    return pltpu.roll(x, m - w, 1)


def _fq12_roll(f, w: int):
    return tuple(tuple((_roll_lanes(c0, w), _roll_lanes(c1, w))
                       for (c0, c1) in c6) for c6 in f)


def _finalize_easy_kernel(cref, xbits_ref, pbits_ref, band_ref, f_ref, out_ref):
    """(384, 128) residue-class products (dead lanes already 1) → full
    lane fold + the EASY part of the final exponentiation
    (f^((q⁶−1)(q²+1)), which needs the true Fq12 inverse).  Split from
    the hard part so each program stays within the scoped-VMEM budget."""
    _bind_consts(cref, xbits_ref, pbits_ref, band_ref)
    f = unpack_fq12(f_ref[:])
    w = f[0][0][0].shape[1] // 2
    while w >= 1:
        f = fq12_mul(f, _fq12_roll(f, w))
        w //= 2
    out_ref[:] = pack_fq12(k_final_exp_easy(f))


def _finalize_hard_kernel(cref, xbits_ref, pbits_ref, band_ref, m_ref, out_ref):
    """Easy-part output → HHT hard part ×3 → ``∏ == 1`` int32 flag."""
    _bind_consts(cref, xbits_ref, pbits_ref, band_ref)
    m = unpack_fq12(m_ref[:])
    g = k_final_exp_hard(m)
    ok = fq12_is_one(g).astype(jnp.int32)  # (1, 128); all lanes equal
    out_ref[0, 0] = ok[0, 0]


def blocks_to_limb_fq12(f_planes):
    """(384, M) kernel block layout → (M, 2, 3, 2, 26) XLA-twin limb
    layout (the :mod:`..limb_pairing` convention)."""
    m = f_planes.shape[1]
    comps = f_planes.reshape(12, BLOCK_ROWS, m)[:, :LIMBS, :]  # (12, 26, M)
    comps = jnp.transpose(comps, (2, 0, 1))                    # (M, 12, 26)
    return comps.reshape(m, 2, 3, 2, LIMBS)


@jax.jit
def finalize_xla_tail(f_planes):
    """(384, 128) → verdict via the scanned XLA twin
    (:mod:`..limb_pairing`) — the Mosaic-free fallback finalize tail."""
    f = blocks_to_limb_fq12(f_planes)               # (128, 2, 3, 2, 26)
    prod = XP._product_reduce(f)
    ok = XP.fq12_is_one(XP.final_exponentiation_cubed(prod))
    return ok.astype(jnp.int32).reshape(1, 1)


def _finalize_call_body(f_planes):
    """Fold an entire batch's (384, M) lane products (M a power of two,
    ≥ 128) into one Fq12, run the shared final exponentiation on-device,
    and return a (1, 1) int32 ``is_one`` flag — the only bytes the host
    ever pulls back for a verify call.

    Widths above 128 are halved with the gridded 256→128 Pallas product
    cells (bounded VMEM per cell); the 128→1 fold + easy part and the
    HHT hard part run as two Pallas programs (split so each fits the
    scoped-VMEM budget, raised via ``_COMPILER_PARAMS``).
    :func:`finalize_xla_tail` is the scanned-XLA fallback."""
    m = f_planes.shape[1]
    if m < LANE_BLOCK or m & (m - 1):
        raise ValueError("lane count must be a power of two ≥ 128")
    while f_planes.shape[1] > LANE_BLOCK:
        ones = jnp.ones((1, f_planes.shape[1]), jnp.int32)
        f_planes = product_chunks_kernel_call(f_planes, ones)
    easy = pl.pallas_call(
        _finalize_easy_kernel,
        in_specs=_const_specs() + [pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((12 * BLOCK_ROWS, LANE_BLOCK),
                                       jnp.uint32),
        compiler_params=_COMPILER_PARAMS,
    )(*_const_args(), f_planes)
    return pl.pallas_call(
        _finalize_hard_kernel,
        in_specs=_const_specs() + [pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        compiler_params=_COMPILER_PARAMS,
    )(*_const_args(), easy)


finalize_kernel_call = jax.jit(_finalize_call_body)
# Donated twin for the dispatcher's hot path: the (384, M) product
# concat is batch-local and never re-read, so its buffer (up to MBs at
# wide M) is recycled in place.  Callers that reuse their input
# (profiling loops, tests) keep the undonated entry above.
finalize_kernel_call_donated = jax.jit(_finalize_call_body,
                                       donate_argnums=(0,))


# ---------------------------------------------------------------------------
# Prepare kernel: G1 aggregation + RLC ladders + affine conversion
# ---------------------------------------------------------------------------

PREP_S = 128  # sets per prepare launch (lane-block aligned)


def _prepare_kernel(cref, xbits_ref, pbits_ref, band_ref, pk_ref, kmask_ref,
                    lo_ref, hi_ref, g1_out_ref, flags_ref, *, K: int):
    _bind_consts(cref, xbits_ref, pbits_ref, band_ref)
    S = PREP_S
    acc = point_identity(_G1ops, S)

    def body(k, acc):
        off = k * S
        x, y = unpack_planes(pk_ref[:, pl.ds(off, S)], 2)
        live = kmask_ref[:, pl.ds(off, S)] != 0
        blk = point_select(_G1ops, live, (x, y, _G1ops.one_like(S)),
                           point_identity(_G1ops, S))
        return point_add(_G1ops, acc, blk)

    acc = jax.lax.fori_loop(0, K, body, acc)
    # Live sets with identity aggregates are invalid (blst/PythonBackend
    # rule); reported per-lane and folded into the batch verdict.
    flags_ref[:] = (k_is_zero(acc[2])).astype(jnp.int32)
    scaled = scalar_mul(_G1ops, acc, lo_ref[:], hi_ref[:])
    zi = k_fq_inv(scaled[2])
    xa = k_mont_mul(scaled[0], zi)
    ya = k_mont_mul(scaled[1], zi)
    g1_out_ref[:] = pack_planes([xa, ya])


@partial(jax.jit, static_argnames=("K",))
def prepare_kernel_call(pk_planes, kmask, lo, hi, *, K: int):
    """pk (64, C·K·128) K-major blocks of AFFINE G1 pubkeys per chunk
    (chunk c's key k of set s at column c·K·128 + k·128 + s); kmask
    (1, C·K·128) int32; lo/hi (1, C·128) uint32 RLC scalar words.  The
    grid runs one cell per 128-set chunk.

    Returns (g1_aff (64, C·128) blocks, ident_flags (1, C·128) int32):
    lane s of chunk c holds the affine c_i·aggpk_i, to be paired with
    H(m_i).  The signature side of the RLC lives in ONE extra Miller
    lane built by :func:`sigma_kernel_call` + :func:`sigma_combine`:
    ∏ e(c_i·aggpk_i, H_i) · e(−G, Σ c_i·σ_i) == 1.
    """
    S = PREP_S
    if pk_planes.shape[1] % (K * S):
        raise ValueError("pk lanes must be C · K · 128")
    C = pk_planes.shape[1] // (K * S)
    return pl.pallas_call(
        partial(_prepare_kernel, K=K),
        grid=(C,),
        in_specs=_const_specs() + [
            pl.BlockSpec((2 * BLOCK_ROWS, K * S), lambda c: (0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, K * S), lambda c: (0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S), lambda c: (0, c), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S), lambda c: (0, c), memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((2 * BLOCK_ROWS, S), lambda c: (0, c),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, S), lambda c: (0, c),
                                memory_space=pltpu.VMEM)),
        out_shape=(jax.ShapeDtypeStruct((2 * BLOCK_ROWS, S * C),
                                        jnp.uint32),
                   jax.ShapeDtypeStruct((1, S * C), jnp.int32)),
        compiler_params=_COMPILER_PARAMS,
    )(*_const_args(), pk_planes, kmask, lo, hi)
