"""EIP-2386 hierarchical-deterministic wallets — the ``eth2_wallet`` crate
(``/root/reference/crypto/eth2_wallet/``).

A wallet is an encrypted seed (the same EIP-2335 crypto module as a
keystore) plus bookkeeping: uuid, name, type ``hierarchical deterministic``
and a ``nextaccount`` counter; validator keystores derive from the seed at
EIP-2334 paths ``m/12381/3600/<account>/0/0``.
"""

from __future__ import annotations

import json
import uuid as uuid_mod
from dataclasses import dataclass, field

from .key_derivation import derive_path, validator_signing_path
from .keystore import Keystore, KeystoreError
from . import bls


class WalletError(ValueError):
    pass


@dataclass
class Wallet:
    """EIP-2386 JSON wallet (type ``hierarchical deterministic``)."""

    crypto: dict
    name: str
    uuid: str
    nextaccount: int = 0
    version: int = 1
    type: str = "hierarchical deterministic"

    @classmethod
    def create(cls, name: str, password: str, seed: bytes,
               scrypt_n: int = 16384) -> "Wallet":
        """Encrypt ``seed`` under ``password`` (same KDF/cipher/checksum
        module as EIP-2335 keystores, per EIP-2386 §Crypto)."""
        if not 16 <= len(seed) <= 64:
            raise WalletError("seed must be 16..64 bytes")
        ks = Keystore.encrypt(seed, password, pubkey=b"", path="",
                              kdf="scrypt", scrypt_n=scrypt_n)
        return cls(crypto=ks.crypto, name=name,
                   uuid=str(uuid_mod.uuid4()))

    def decrypt_seed(self, password: str) -> bytes:
        ks = Keystore(crypto=self.crypto, pubkey="", path="",
                      uuid=self.uuid, version=4)
        return ks.decrypt(password)

    def next_validator(self, wallet_password: str,
                       keystore_password: str,
                       scrypt_n: int = 16384) -> Keystore:
        """Derive the keystore for account ``nextaccount`` and advance the
        counter (`eth2_wallet` ``next_validator``)."""
        seed = self.decrypt_seed(wallet_password)
        path = validator_signing_path(self.nextaccount)
        sk_int = derive_path(seed, path)
        sk = bls.SecretKey(sk_int)
        ks = Keystore.encrypt(sk.serialize(), keystore_password,
                              pubkey=sk.public_key().serialize(),
                              path=path, scrypt_n=scrypt_n)
        self.nextaccount += 1
        return ks

    # -- JSON ----------------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            "crypto": self.crypto,
            "name": self.name,
            "nextaccount": self.nextaccount,
            "type": self.type,
            "uuid": self.uuid,
            "version": self.version,
        })

    @classmethod
    def from_json(cls, text: str) -> "Wallet":
        raw = json.loads(text)
        if raw.get("type") != "hierarchical deterministic":
            raise WalletError("unsupported wallet type")
        if int(raw.get("version", 0)) != 1:
            raise WalletError("unsupported wallet version")
        return cls(crypto=raw["crypto"], name=raw["name"],
                   uuid=raw["uuid"], nextaccount=int(raw["nextaccount"]))
