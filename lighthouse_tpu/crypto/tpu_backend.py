"""The ``tpu`` BLS backend — batched signature verification on the device.

This is the role blst plays for the reference (``bls::impls::supranational``,
``/root/reference/crypto/bls/src/impls/blst.rs``): the production backend
behind the backend-registry seam in :mod:`.bls`.  All three public verify
entry points funnel into ONE fused device program per (sets, keys) shape
bucket:

    per-set pubkey tree-aggregation (G1)
      → per-set random-linear-combination scaling (64-bit ladders, G1+G2)
      → signature accumulation (G2 tree sum)
      → batched Miller loops over all pairs
      → one shared final exponentiation of the lane product
      → == 1

replicating ``verify_multiple_aggregate_signatures`` semantics
(``impls/blst.rs:36-119``) including the consensus-critical edge rules:
empty set lists, empty signing-key lists, missing/infinity signatures and
identity aggregate pubkeys all fail verification (host-side pre-checks +
an on-device identity-aggregate flag).

Host work is marshalling only: affine points → Montgomery limb arrays
(memoised per point, the ``validator_pubkey_cache.rs`` role) and
hash-to-curve of messages (host SSWU for now).  Shapes are bucketed to
powers of two so XLA compiles a handful of programs, then every call hits
the jit cache.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from . import curve as C
from . import limb_curve as LC
from . import limb_field as LF
from . import limb_pairing as LP
from ..ops.merkle import _next_pow2
from .hash_to_curve import hash_to_g2

_NEG_G1_GEN = LC.g1_to_limbs(C.g1_neg(C.G1_GEN))
_G1_IDENT = LC.g1_to_limbs(None)
_G2_IDENT = LC.g2_to_limbs(None)


@lru_cache(maxsize=1 << 16)
def _g1_limbs(point) -> bytes:
    return LC.g1_to_limbs(point).tobytes()


@lru_cache(maxsize=1 << 16)
def _g2_limbs(point) -> bytes:
    return LC.g2_to_limbs(point).tobytes()


@lru_cache(maxsize=1 << 14)
def _h_limbs(message: bytes) -> bytes:
    return LC.g2_to_limbs(hash_to_g2(message)).tobytes()


def _g1_arr(point) -> np.ndarray:
    return np.frombuffer(_g1_limbs(point), np.uint32).reshape(3, LF.LIMBS)


def _g2_arr(point) -> np.ndarray:
    return np.frombuffer(_g2_limbs(point), np.uint32).reshape(3, 2, LF.LIMBS)


def _h_arr(message: bytes) -> np.ndarray:
    return np.frombuffer(_h_limbs(bytes(message)), np.uint32).reshape(3, 2, LF.LIMBS)


@jax.jit
def _verify_sets_kernel(pk, kmask, sig, h, scal, smask):
    """Fused batch verify.  Shapes: pk (S,K,3,26), kmask (S,K) bool,
    sig/h (S,3,2,26) projective, scal (S,2) uint32 lo/hi, smask (S,) bool.
    S and K are powers of two.  Returns a scalar bool."""
    S, K = pk.shape[0], pk.shape[1]
    ident1 = jnp.asarray(_G1_IDENT)
    pkm = LC.point_select(kmask, pk, ident1, LC.G1_OPS)
    agg = LC.tree_sum(LC.G1_OPS, pkm, K)              # (S,3,26)
    # A live set whose aggregate pubkey is the identity is invalid
    # (`PythonBackend.verify_signature_sets` / blst's aggregate move).
    any_bad = jnp.any(smask & LF.is_zero(agg[..., 2, :]))
    aggc = LC.scalar_mul(LC.G1_OPS, agg, scal)        # (S,3,26)
    sigc = LC.scalar_mul(LC.G2_OPS, sig, scal)        # (S,3,2,26)
    sigsum = LC.tree_sum(LC.G2_OPS, sigc, S)          # (3,2,26)
    # Pairing lanes: i<S → (c_i·aggpk_i, H_i); lane S → (−g1, Σc_i·sig_i);
    # the rest of the 2S block is masked padding.
    g1_lanes = jnp.concatenate(
        [aggc, jnp.asarray(_NEG_G1_GEN)[None],
         jnp.broadcast_to(jnp.asarray(_G1_IDENT), (S - 1, 3, LF.LIMBS))])
    g2_lanes = jnp.concatenate(
        [h, sigsum[None],
         jnp.broadcast_to(jnp.asarray(_G2_IDENT), (S - 1, 3, 2, LF.LIMBS))])
    lane_mask = jnp.concatenate(
        [smask, jnp.array([True]), jnp.zeros(S - 1, bool)])
    ok = LP.multi_pairing_is_one(g1_lanes, g2_lanes, lane_mask)
    return ok & ~any_bad


def _dispatch(entries, rand_fn) -> bool:
    """entries: list of (agg_sig_point | None meaning infinity is already
    rejected, [pubkey points], message bytes).  rand_fn() → 64-bit scalar."""
    S = _next_pow2(len(entries))
    K = _next_pow2(max(len(e[1]) for e in entries))
    pk = np.broadcast_to(_G1_IDENT, (S, K, 3, LF.LIMBS)).copy()
    kmask = np.zeros((S, K), bool)
    sig = np.broadcast_to(_G2_IDENT, (S, 3, 2, LF.LIMBS)).copy()
    h = np.broadcast_to(_G2_IDENT, (S, 3, 2, LF.LIMBS)).copy()
    scal = np.zeros((S, 2), np.uint32)
    smask = np.zeros(S, bool)
    for i, (sig_pt, keys, msg) in enumerate(entries):
        for j, kp in enumerate(keys):
            pk[i, j] = _g1_arr(kp)
        kmask[i, :len(keys)] = True
        if sig_pt is not None:
            sig[i] = _g2_arr(sig_pt)
        h[i] = _h_arr(msg)
        c = rand_fn()
        scal[i] = (c & 0xFFFFFFFF, c >> 32)
        smask[i] = True
    ok = _verify_sets_kernel(jnp.asarray(pk), jnp.asarray(kmask),
                             jnp.asarray(sig), jnp.asarray(h),
                             jnp.asarray(scal), jnp.asarray(smask))
    return bool(ok)


class TpuBackend:
    """Device-batched verification registered as ``tpu`` in :mod:`.bls`."""

    name = "tpu"

    def verify(self, signature, pubkeys, message) -> bool:
        if signature.point is None or not pubkeys:
            return False
        return _dispatch(
            [(signature.point, [k.point for k in pubkeys], bytes(message))],
            rand_fn=lambda: 1)

    def aggregate_verify(self, signature, pubkeys, messages) -> bool:
        if signature.point is None or not pubkeys \
                or len(pubkeys) != len(messages):
            return False
        # Distinct message per signer: one single-key set per message, the
        # aggregate signature attached to the first set, scalars all 1.
        entries = [(None, [pk.point], bytes(m))
                   for pk, m in zip(pubkeys, messages)]
        entries[0] = (signature.point, entries[0][1], entries[0][2])
        return _dispatch(entries, rand_fn=lambda: 1)

    def verify_signature_sets(self, sets) -> bool:
        import secrets
        if not sets:
            return False
        entries = []
        for s in sets:
            if s.signature is None or s.signature.point is None:
                return False
            if not s.signing_keys:
                return False
            entries.append((s.signature.point,
                            [k.point for k in s.signing_keys],
                            bytes(s.message)))

        def rand_nonzero():
            c = 0
            while c == 0:
                c = secrets.randbits(64)
            return c

        return _dispatch(entries, rand_fn=rand_nonzero)


def register() -> None:
    from . import bls
    bls.register_backend("tpu", TpuBackend())


register()
