"""The ``tpu`` BLS backend — batched signature verification on the device.

This is the role blst plays for the reference (``bls::impls::supranational``,
``/root/reference/crypto/bls/src/impls/blst.rs``): the production backend
behind the backend-registry seam in :mod:`.bls`.  All three public verify
entry points funnel into ONE fused device program per (sets, keys) shape
bucket:

    per-set pubkey tree-aggregation (G1)
      → per-set random-linear-combination scaling (64-bit ladders, G1+G2)
      → signature accumulation (G2 tree sum)
      → batched Miller loops over all pairs
      → one shared final exponentiation of the lane product
      → == 1

replicating ``verify_multiple_aggregate_signatures`` semantics
(``impls/blst.rs:36-119``) including the consensus-critical edge rules:
empty set lists, empty signing-key lists, missing/infinity signatures and
identity aggregate pubkeys all fail verification (host-side pre-checks +
an on-device identity-aggregate flag).

Host work is marshalling only: affine points → Montgomery limb arrays
(memoised per point, the ``validator_pubkey_cache.rs`` role) and
hash-to-curve of messages (host SSWU for now).  Shapes are bucketed to
powers of two so XLA compiles a handful of programs, then every call hits
the jit cache.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from . import curve as C
from . import limb_curve as LC
from . import limb_field as LF
from . import limb_pairing as LP
from ..ops.merkle import _next_pow2
from .hash_to_curve import hash_to_g2

_NEG_G1_GEN = LC.g1_to_limbs(C.g1_neg(C.G1_GEN))
_G1_IDENT = LC.g1_to_limbs(None)
_G2_IDENT = LC.g2_to_limbs(None)


@lru_cache(maxsize=1 << 16)
def _g1_limbs(point) -> bytes:
    return LC.g1_to_limbs(point).tobytes()


@lru_cache(maxsize=1 << 16)
def _g2_limbs(point) -> bytes:
    return LC.g2_to_limbs(point).tobytes()


@lru_cache(maxsize=1 << 14)
def _h_point(message: bytes):
    """Memoised hash-to-curve; both path-specific encodings derive from it."""
    return hash_to_g2(message)


def _h_limbs(message: bytes) -> bytes:
    return LC.g2_to_limbs(_h_point(message)).tobytes()


def _g1_arr(point) -> np.ndarray:
    return np.frombuffer(_g1_limbs(point), np.uint32).reshape(3, LF.LIMBS)


def _g2_arr(point) -> np.ndarray:
    return np.frombuffer(_g2_limbs(point), np.uint32).reshape(3, 2, LF.LIMBS)


def _h_arr(message: bytes) -> np.ndarray:
    return np.frombuffer(_h_limbs(bytes(message)), np.uint32).reshape(3, 2, LF.LIMBS)


@jax.jit
def _verify_sets_kernel(pk, kmask, sig, h, scal, smask):
    """Fused batch verify.  Shapes: pk (S,K,3,26), kmask (S,K) bool,
    sig/h (S,3,2,26) projective, scal (S,2) uint32 lo/hi, smask (S,) bool.
    S and K are powers of two.  Returns a scalar bool."""
    S, K = pk.shape[0], pk.shape[1]
    ident1 = jnp.asarray(_G1_IDENT)
    pkm = LC.point_select(kmask, pk, ident1, LC.G1_OPS)
    agg = LC.tree_sum(LC.G1_OPS, pkm, K)              # (S,3,26)
    # A live set whose aggregate pubkey is the identity is invalid
    # (`PythonBackend.verify_signature_sets` / blst's aggregate move).
    any_bad = jnp.any(smask & LF.is_zero(agg[..., 2, :]))
    aggc = LC.scalar_mul(LC.G1_OPS, agg, scal)        # (S,3,26)
    sigc = LC.scalar_mul(LC.G2_OPS, sig, scal)        # (S,3,2,26)
    sigsum = LC.tree_sum(LC.G2_OPS, sigc, S)          # (3,2,26)
    # Pairing lanes: i<S → (c_i·aggpk_i, H_i); lane S → (−g1, Σc_i·sig_i);
    # the rest of the 2S block is masked padding.
    g1_lanes = jnp.concatenate(
        [aggc, jnp.asarray(_NEG_G1_GEN)[None],
         jnp.broadcast_to(jnp.asarray(_G1_IDENT), (S - 1, 3, LF.LIMBS))])
    g2_lanes = jnp.concatenate(
        [h, sigsum[None],
         jnp.broadcast_to(jnp.asarray(_G2_IDENT), (S - 1, 3, 2, LF.LIMBS))])
    lane_mask = jnp.concatenate(
        [smask, jnp.array([True]), jnp.zeros(S - 1, bool)])
    ok = LP.multi_pairing_is_one(g1_lanes, g2_lanes, lane_mask)
    return ok & ~any_bad


# ---------------------------------------------------------------------------
# Pallas path (production TPU): prepare → miller → product → host final exp
# ---------------------------------------------------------------------------

def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


@lru_cache(maxsize=1 << 16)
def _g1_aff_col(point) -> bytes:
    """Affine G1 → (64,) block-layout column (x at rows 0, y at 32)."""
    col = np.zeros(64, np.uint32)
    col[0:26] = LF.to_mont(point[0])
    col[32:58] = LF.to_mont(point[1])
    return col.tobytes()


@lru_cache(maxsize=1 << 16)
def _g2_aff_col(point) -> bytes:
    """Affine G2 → (128,) block-layout column (x0/x1/y0/y1 at 0/32/64/96)."""
    (x0, x1), (y0, y1) = point
    col = np.zeros(128, np.uint32)
    col[0:26] = LF.to_mont(x0)
    col[32:58] = LF.to_mont(x1)
    col[64:90] = LF.to_mont(y0)
    col[96:122] = LF.to_mont(y1)
    return col.tobytes()


class _DevicePubkeyTable:
    """HBM-resident decompressed pubkey columns — the device half of the
    reference's ``ValidatorPubkeyCache`` (``validator_pubkey_cache.rs:18``):
    each distinct pubkey is marshalled to its (64,) affine limb column
    exactly once; verify calls ship uint32 indices and the device gathers.

    New columns append with a device-side ``.at[].set`` (a 256-byte h2d +
    on-device copy — never a full-table re-upload); capacity doubling pads
    on-device.  Bounded by ``max_keys`` (≈ a registry's worth): at the
    bound the LEAST-RECENTLY-USED half of the keys is evicted and the
    survivors compacted (generational halving), so adversarial never-seen
    keys can't grow the table without bound while hot validator keys stay
    resident; the next ``device()`` call re-uploads the compacted table
    once."""

    def __init__(self, initial: int = 1 << 15, max_keys: int = 1 << 21):
        self._initial = initial
        self._max_keys = max_keys
        self._reset()

    def _reset(self) -> None:
        self._index: dict = {}
        self._last_used: dict = {}
        self._gen = 0
        self._host = np.zeros((64, self._initial), np.uint32)
        self._n = 1  # column 0 stays zero for masked slots
        self._device = None

    def maybe_reset(self) -> None:
        """Call BETWEEN batches only: evicting mid-marshal would
        invalidate indices already recorded for the in-flight batch.

        Generational halving (ADVICE r4): at the bound, keep the most
        recently USED half of the keys and compact, instead of dropping
        the whole table — a full reset would force a re-marshal +
        re-upload of every hot validator key in one latency spike on the
        block-verification path.  Recency (not insertion order) decides
        survival: hot validator keys are touched by every batch they
        appear in, so a flood of adversarial never-seen keys ages out
        while the working set stays resident."""
        self._gen += 1
        if self._n < self._max_keys:
            return
        keep = (self._n - 1) // 2
        survivors = sorted(
            self._index.items(),
            key=lambda kv: (self._last_used.get(kv[0], 0), kv[1]),
            reverse=True)[:keep]
        survivors.sort(key=lambda kv: kv[1])  # stable column order
        cols = [old for _, old in survivors]
        cap = self._initial  # shrink to next pow2 >= survivors (+ col 0)
        while cap < len(cols) + 1:
            cap *= 2
        host = np.zeros((64, cap), np.uint32)
        host[:, 1:len(cols) + 1] = self._host[:, cols]  # one gather
        index = {pt: i + 1 for i, (pt, _) in enumerate(survivors)}
        self._host, self._index, self._n = host, index, len(cols) + 1
        self._last_used = {pt: self._last_used.get(pt, 0) for pt in index}
        self._device = None  # next device() re-uploads the compacted table

    def index_of(self, point) -> int:
        self._last_used[point] = self._gen
        i = self._index.get(point)
        if i is None:
            if self._n == self._host.shape[1]:
                self._host = np.concatenate(
                    [self._host, np.zeros_like(self._host)], axis=1)
                if self._device is not None:
                    self._device = jnp.pad(
                        self._device,
                        ((0, 0), (0, self._device.shape[1])))
            col = np.frombuffer(_g1_aff_col(point), np.uint32)
            self._host[:, self._n] = col
            if self._device is not None:
                self._device = self._device.at[:, self._n].set(
                    jnp.asarray(col))
            i = self._index[point] = self._n
            self._n += 1
        return i

    def device(self):
        if self._device is None:
            self._device = jnp.asarray(self._host)
        return self._device


_PK_TABLE = _DevicePubkeyTable()


def _sigma_g1_cell() -> np.ndarray:
    """(64, 128) Miller-cell G1 input whose lane 0 is the affine −G (the
    pair of the aggregated-signature lane); other lanes are masked."""
    out = np.zeros((64, 128), np.uint32)
    out[:, 0] = np.frombuffer(_g1_aff_col(C.g1_neg(C.G1_GEN)), np.uint32)
    return out


_SIGMA_G1_CELL = _sigma_g1_cell()


def _fused_pipeline_body(table, idx, kmask, lo, hi, u_planes, sig_cols,
                         sigmask, setlive, *, K: int):
    """Batch verify up to the 128-class lane products, as one device
    program per (C, K, capacity) shape bucket: pubkey gather →
    hash-to-curve of every message → prepare (G1 aggregation + RLC
    ladder) → per-chunk RLC signature aggregation (the σ side collapses
    to ONE Miller lane via e(−G, Σ c_i·σ_i)) → FUSED Miller + masked
    lane fold (one Pallas program per 256-lane chunk — the fold no
    longer pays its own dispatch) → (384, 128) residue products + bad
    flag."""
    from . import pairing_kernel as PK
    from . import htc_kernel as HK

    S = PK.PREP_S
    C = sig_cols.shape[1] // S
    pk = jnp.take(table, idx, axis=1)                   # (64, C·K·S)
    g1_aggpk, flags = PK.prepare_kernel_call(pk, kmask, lo, hi, K=K)
    h_cols = HK.hash_g2_kernel_call(u_planes)           # (128, C·S)
    partials = PK.sigma_kernel_call(sig_cols, sigmask, lo, hi)
    sig_col, sig_ident = PK.sigma_combine(partials)

    lanes = (C + 1) * S
    pad = (-lanes) % (2 * S)
    g1 = jnp.concatenate(
        [g1_aggpk, jnp.asarray(_SIGMA_G1_CELL)]
        + ([jnp.zeros((64, pad), jnp.uint32)] if pad else []), axis=1)
    g2_sig = jnp.zeros((128, S), jnp.uint32).at[:, 0].set(sig_col)
    g2 = jnp.concatenate(
        [h_cols, g2_sig]
        + ([jnp.zeros((128, pad), jnp.uint32)] if pad else []), axis=1)
    sig_live = jnp.any(sigmask != 0) & ~sig_ident
    sig_cell_mask = jnp.zeros((1, S), jnp.int32).at[0, 0].set(
        sig_live.astype(jnp.int32))
    lane_mask = jnp.concatenate(
        [setlive, sig_cell_mask]
        + ([jnp.zeros((1, pad), jnp.int32)] if pad else []), axis=1)

    prod = PK.miller_fold_kernel_call(g1, g2, lane_mask)
    while prod.shape[1] > PK.LANE_BLOCK:
        if (prod.shape[1] // PK.LANE_BLOCK) % 2:  # odd block count
            prod = jnp.concatenate([prod, jnp.asarray(_ONE_BLOCK)], axis=1)
        ones = jnp.ones((1, prod.shape[1]), jnp.int32)
        prod = PK.product_chunks_kernel_call(prod, ones)
    bad = jnp.any((flags != 0) & (setlive != 0))
    return prod, bad


_fused_pipeline = partial(
    jax.jit, static_argnames=("K",))(_fused_pipeline_body)
# The per-batch marshalled arrays (indices, masks, scalar words, u
# planes, signature columns) are DONATED: they are built fresh for every
# dispatch and never re-read on the host, so XLA may overwrite them
# in place instead of device-side copying ~tens of MB per batch.  The
# pubkey table (arg 0) is the one long-lived input and stays undonated.
_fused_pipeline_donated = partial(
    jax.jit, static_argnames=("K",),
    donate_argnums=tuple(range(1, 9)))(_fused_pipeline_body)


def fused_pipeline_jit(donate: bool | None = None):
    """The jitted fused-pipeline entry the dispatcher uses on this
    backend — donated on TPU (default), plain elsewhere (CPU donation is
    a no-op that only warns).  The warmup path lowers THIS so its
    persisted executables match the slot path's cache keys."""
    if donate is None:
        donate = _use_pallas()
    return _fused_pipeline_donated if donate else _fused_pipeline


@jax.jit
def _combine_verdict(ok, bads):
    return (ok[0, 0] != 0) & ~jnp.any(bads)


def _fq12_one_block() -> np.ndarray:
    """(384, 128) kernel-block-layout Fq12 ONE — pads the cross-group
    product concat to a power of two (acts as a masked-out lane)."""
    out = np.zeros((384, 128), np.uint32)
    out[0:26, :] = np.asarray(LF.ONE_MONT)[:, None]
    return out


_ONE_BLOCK = _fq12_one_block()


def _rlc_message_sig_columns(entries, C, rand_fn):
    """The per-set column marshalling SHARED by the general pipeline and
    the shared-key collapsed path — one definition of the device layout
    (RLC lo/hi scalar words, interleaved HTC u-planes, affine signature
    columns, set-liveness), two consumers.  Returns
    (set_col, lo, hi, u_planes, sig_cols, sigmask, setlive)."""
    from . import htc_kernel as HK
    from . import pairing_kernel as PK

    S = PK.PREP_S
    n = len(entries)
    sets = np.arange(n)
    set_col = (sets // S) * S + (sets % S)

    rands = np.fromiter((rand_fn() for _ in range(n)), np.uint64, n)
    lo = np.zeros((1, C * S), np.uint32)
    hi = np.zeros((1, C * S), np.uint32)
    lo[0, set_col] = (rands & 0xFFFFFFFF).astype(np.uint32)
    hi[0, set_col] = (rands >> 32).astype(np.uint32)

    u_cols = np.frombuffer(
        b"".join(HK._u_cols(bytes(e[2])) for e in entries),
        np.uint32).reshape(n, 2, 2 * HK.BLOCK_ROWS)
    u_planes = np.zeros((2 * HK.BLOCK_ROWS, C * 2 * S), np.uint32)
    ubase = (sets // S) * 2 * S + (sets % S)
    u_planes[:, ubase] = u_cols[:, 0].T
    u_planes[:, ubase + S] = u_cols[:, 1].T

    sig_cols = np.zeros((128, C * S), np.uint32)
    sigmask = np.zeros((1, C * S), np.int32)
    have_sig = np.fromiter((e[0] is not None for e in entries), bool, n)
    if have_sig.any():
        sig_bytes = b"".join(_g2_aff_col(e[0])
                             for e in entries if e[0] is not None)
        cols = np.frombuffer(sig_bytes, np.uint32).reshape(-1, 128).T
        sig_cols[:, set_col[have_sig]] = cols
        sigmask[0, set_col[have_sig]] = 1
    setlive = np.zeros((1, C * S), np.int32)
    setlive[0, set_col] = 1
    return set_col, lo, hi, u_planes, sig_cols, sigmask, setlive


def _marshal_group(entries, rand_fn):
    """One sub-batch's host marshalling: pubkey-table indices, RLC scalar
    words, u-values, signature columns, masks.  Column placement is
    vectorized — the only per-entry Python work left is the pubkey-table
    dict lookups, the memoised u-column lookups, and ``rand_fn``.

    Returns HOST numpy arrays (+ the static K bucket): the H2D transfer
    is a separate pipeline stage (async ``device_put`` by the staged
    executor) so marshalling of the next sub-batch overlaps this one's
    transfer and compute."""
    from . import pairing_kernel as PK

    S = PK.PREP_S
    n = len(entries)
    C = _next_pow2((n + S - 1) // S)
    K = _next_pow2(max(len(e[1]) for e in entries))

    nkeys = np.fromiter((len(e[1]) for e in entries), np.int64, n)
    total_keys = int(nkeys.sum())
    flat_idx = np.fromiter(
        (_PK_TABLE.index_of(kp) for e in entries for kp in e[1]),
        np.int32, total_keys)
    sets = np.arange(n)
    c_arr, s_arr = sets // S, sets % S
    starts = np.concatenate([[0], np.cumsum(nkeys)[:-1]])
    within = np.arange(total_keys) - np.repeat(starts, nkeys)
    kcol = (np.repeat(c_arr * K * S + s_arr, nkeys)
            + within.astype(np.int64) * S)
    idx = np.zeros(C * K * S, np.int32)
    kmask = np.zeros((1, C * K * S), np.int32)
    idx[kcol] = flat_idx
    kmask[0, kcol] = 1

    (_set_col, lo, hi, u_planes, sig_cols, sigmask,
     setlive) = _rlc_message_sig_columns(entries, C, rand_fn)
    return (idx, kmask, lo, hi, u_planes, sig_cols, sigmask, setlive, K)


# Stats of the most recent pipelined dispatch, surfaced by bench.py
# (``stage_overlap_efficiency`` et al).
LAST_PIPELINE_STATS: dict = {}


def _pipeline_sets() -> int:
    """Sub-batch size (sets per device dispatch) for the staged
    pipeline.  0 disables sub-batching — one monolithic marshal +
    dispatch per K-group, the pre-pipeline behaviour.

    Default 1024 (was 256): with the fused Miller+fold kernel one
    dispatch carries a C=8 bucket, so the fixed per-dispatch stages
    (finalize's shared final exponentiation, the host sync, the kernel
    launch overheads) amortize over 4× more sets — the r5 stage profile
    put final_exp at 51.7 ms against 32.4 ms of C=2 Miller, i.e. the
    fixed tail dominated narrow buckets."""
    from ..common.knobs import knob_int
    return knob_int("LIGHTHOUSE_TPU_PIPELINE_SETS")


def _split_batches(entries) -> list:
    """Work list for the staged executor: entries group by K =
    next-pow2(signer count) (one 512-key sync-committee set must not pad
    a thousand single-key sets to K=512), and each group splits into
    sub-batches of ≤ ``_pipeline_sets()`` sets so host marshalling of
    sub-batch i+1 overlaps device compute of sub-batch i.

    Sub-batching is only sound when EVERY entry carries its own
    signature (each sub-batch then verifies an independent pairing
    product): ``aggregate_verify`` attaches one signature to the whole
    entry list — splitting it would check ∏ e(pk, H) == 1 without the
    σ lane — so such batches stay monolithic per group."""
    groups: dict = {}
    for e in entries:
        groups.setdefault(_next_pow2(max(1, len(e[1]))), []).append(e)
    sub = _pipeline_sets()
    splittable = sub > 0 and all(e[0] is not None for e in entries)
    work = []
    for k in sorted(groups):
        g = groups[k]
        if splittable:
            work.extend(g[j:j + sub] for j in range(0, len(g), sub))
        else:
            work.append(g)
    return work


def _dispatch_pallas(entries, rand_fn) -> bool:
    """Marshal a batch and run the fused device pipeline:

        ∏ e(c_i·aggpk_i, H(m_i)) · e(−G, Σ c_i·σ_i) == 1

    (the signature side of the RLC collapses to one pairing lane — the
    same aggregation blst's ``verify_multiple_aggregate_signatures``
    performs).  Work splits per :func:`_split_batches` and runs through
    the staged executor: marshalling of sub-batch i+1 overlaps the async
    ``device_put`` + compute of sub-batch i (no ``block_until_ready``
    between stages), and the marshalled arrays are donated to the jit so
    the device reuses their buffers in place.  Every sub-batch's
    (384, 128) residue product concats into ONE shared finalize (fold +
    final exponentiation — its ~13-minute XLA compile happens once
    across all buckets, not per (C, K)), and the host pulls back a
    single bool: still exactly one host sync per verify call.
    Message hashing is host SHA-256 (expand_message_xmd) + the device
    SSWU kernel — no host curve math at all."""
    from . import pairing_kernel as PK
    from ..parallel.pipeline import StagedExecutor

    _PK_TABLE.maybe_reset()
    work = _split_batches(entries)
    fused = fused_pipeline_jit()
    ex = StagedExecutor("bls_pipeline", subsystem="bls")

    def dispatch(staged):
        (idx, kmask, lo, hi, u, sig, sigmask, setlive, K) = staged
        # Table snapshot AFTER this sub-batch's marshalling registered
        # its new keys; later sub-batches' appends build NEW functional
        # arrays and cannot disturb an in-flight dispatch.
        table = _PK_TABLE.device()
        return fused(table, idx, kmask, lo, hi, u, sig, sigmask,
                     setlive, K=K)

    results = ex.map(work, lambda batch: _marshal_group(batch, rand_fn),
                     dispatch)
    prods = [r[0] for r in results]
    bads = [r[1] for r in results]
    g = _next_pow2(len(prods))
    prods += [jnp.asarray(_ONE_BLOCK)] * (g - len(prods))
    prod = prods[0] if g == 1 else jnp.concatenate(prods, axis=1)
    # `prod` is batch-local (fused output or fresh concat) — donated.
    ok = (PK.finalize_kernel_call_donated(prod) if _use_pallas()
          else PK.finalize_kernel_call(prod))
    verdict = bool(_combine_verdict(ok, jnp.stack(bads)))
    eff = ex.overlap_efficiency()
    LAST_PIPELINE_STATS.update(
        dispatches=len(work),
        staging_fallbacks=ex.stats["fallbacks"],
        host_prep_ms=round(ex.stats["host_prep_s"] * 1e3, 1),
        overlap_prep_ms=round(ex.stats["overlap_prep_s"] * 1e3, 1),
        overlap_efficiency=None if eff is None else round(eff, 3))
    return verdict


# Stage decomposition of the most recent shared-key (fast-aggregate)
# dispatch — populated when STAGE_TIMINGS is on (bench.py flips it for
# one attributed run; the throughput runs stay sync-free).
LAST_FAST_AGG_TIMINGS: dict = {}
STAGE_TIMINGS = False


def _dedup_shared_keygroups(entries):
    """Collapse entries sharing an IDENTICAL pubkey list to one
    aggregated key (sync-committee shape: 256 messages × the same 512
    pubkeys — ``fast_aggregate_verify``, BASELINE row 4).  The per-set
    RLC scalar multiplies the SAME aggregate, so aggregating once
    (native jacobian sum, ~3 ms for 512 keys; pure-python fallback when
    the .so is unavailable) replaces 256 × 511 device G1 adds and moves
    the sets into the hot K=1 pipeline bucket — and, when the whole
    batch shares one key, into the collapsed one-Miller-lane path
    (:func:`_dispatch_shared`).

    Returns (entries', all_valid): an infinity aggregate means an
    invalid set → caller returns False (matching
    ``aggregate_public_keys`` → None → False)."""
    import time

    from . import bls
    counts: dict = {}
    for e in entries:
        if len(e[1]) > 4:
            counts[tuple(e[1])] = counts.get(tuple(e[1]), 0) + 1
    shared = {k for k, n in counts.items() if n >= 2}
    if not shared:
        return entries, True
    t0 = time.perf_counter()
    agg: dict = {}
    for k in shared:
        agg[k] = bls.aggregate_points(list(k))
        if agg[k] is None:
            return entries, False
    LAST_FAST_AGG_TIMINGS["aggregate_keys_ms"] = round(
        (time.perf_counter() - t0) * 1e3, 2)
    out = []
    for e in entries:
        key = tuple(e[1])
        if key in shared:
            out.append((e[0], [agg[key]], e[2]))
        else:
            out.append(e)
    return out, True


# ---------------------------------------------------------------------------
# Shared-key collapse: the winning fast_aggregate_verify path
# ---------------------------------------------------------------------------
#
# When every set in the batch signs with the SAME aggregated pubkey P
# (the sync-committee shape after _dedup_shared_keygroups), bilinearity
# collapses the whole batch to TWO Miller lanes:
#
#     ∏_i e(c_i·P, H(m_i)) · e(−G, Σ c_i·σ_i)
#   = e(P, Σ c_i·H(m_i)) · e(−G, Σ c_i·σ_i)          == 1
#
# so the per-set cost drops from a Miller lane + a G1 ladder to one G2
# RLC ladder term (the same σ-side fold the pipeline already runs) —
# hash-to-curve is the only per-set stage left.


def _shared_min_sets() -> int:
    """Batch size from which the collapsed path wins (two fixed Miller
    lanes + final exp amortize); below it the general path's latency is
    comparable and not worth a second compiled program."""
    from ..common.knobs import knob_int
    return knob_int("LIGHTHOUSE_TPU_SHARED_MIN")


def _shared_group_key(entries):
    """The common single pubkey point if the WHOLE batch shares one
    signing key (post-dedup) and every entry carries its own signature;
    None otherwise."""
    if len(entries) < _shared_min_sets():
        return None
    first = entries[0][1]
    if len(first) != 1:
        return None
    pt = first[0]
    for e in entries:
        if e[0] is None or len(e[1]) != 1 or e[1][0] != pt:
            return None
    return pt


@jax.jit
def _verify_shared_kernel(pk1, sig, h, scal, smask):
    """Collapsed batch verify: pk1 (3, 26) shared aggregate pubkey,
    sig/h (S, 3, 2, 26) projective, scal (S, 2), smask (S,) bool; S a
    power of two.  Two Miller lanes total."""
    S = sig.shape[0]
    hc = LC.scalar_mul(LC.G2_OPS, h, scal)            # c_i · H(m_i)
    sigc = LC.scalar_mul(LC.G2_OPS, sig, scal)        # c_i · σ_i
    hsum = LC.tree_sum(LC.G2_OPS, hc, S)              # (3, 2, 26)
    sigsum = LC.tree_sum(LC.G2_OPS, sigc, S)
    # A live batch under an identity aggregate key is invalid (the same
    # rule the general kernel flags per-set).
    bad = jnp.any(smask) & LF.is_zero(pk1[2])
    g1_lanes = jnp.stack([pk1, jnp.asarray(_NEG_G1_GEN)])
    g2_lanes = jnp.stack([hsum, sigsum])
    ok = LP.multi_pairing_is_one(g1_lanes, g2_lanes,
                                 jnp.ones(2, dtype=bool))
    return ok & ~bad


def _stage_sync(timings, name, t0, *values):
    """When STAGE_TIMINGS is on, fence the queued work and record the
    stage's wall time; otherwise leave the dispatch fully async."""
    import time
    if not STAGE_TIMINGS:
        return t0
    jax.block_until_ready(values)
    t1 = time.perf_counter()
    timings[name] = round((t1 - t0) * 1e3, 2)
    return t1


def _dispatch_shared_xla(entries, pk_pt, rand_fn) -> bool:
    """XLA (dry-run / off-TPU) collapsed path."""
    import time

    S = _next_pow2(len(entries))
    sig = np.broadcast_to(_G2_IDENT, (S, 3, 2, LF.LIMBS)).copy()
    h = np.broadcast_to(_G2_IDENT, (S, 3, 2, LF.LIMBS)).copy()
    scal = np.zeros((S, 2), np.uint32)
    smask = np.zeros(S, bool)
    t0 = time.perf_counter()
    for i, (sig_pt, _keys, msg) in enumerate(entries):
        sig[i] = _g2_arr(sig_pt)
        h[i] = _h_arr(msg)
        c = rand_fn()
        scal[i] = (c & 0xFFFFFFFF, c >> 32)
        smask[i] = True
    timings = LAST_FAST_AGG_TIMINGS
    t0 = _stage_sync(timings, "marshal_htc_ms", t0)
    ok = _verify_shared_kernel(jnp.asarray(_g1_arr(pk_pt)),
                               jnp.asarray(sig), jnp.asarray(h),
                               jnp.asarray(scal), jnp.asarray(smask))
    _stage_sync(timings, "rlc_fold_miller_final_ms", t0, ok)
    timings["sets"] = len(entries)
    timings["path"] = "xla_shared"
    return bool(ok)


def _dispatch_shared_pallas(entries, pk_pt, rand_fn) -> bool:
    """Pallas (TPU) collapsed path, built ENTIRELY from the pipeline's
    existing kernels: hash-to-curve → two σ-style RLC fold passes (one
    over H columns, one over σ columns) → one fused Miller+fold cell
    with 2 live lanes → shared finalize."""
    import time

    from . import htc_kernel as HK
    from . import pairing_kernel as PK

    S = PK.PREP_S
    n = len(entries)
    # NOT named C: that would shadow the curve module used below.
    n_chunks = _next_pow2((n + S - 1) // S)

    t0 = time.perf_counter()
    (_set_col, lo, hi, u_planes, sig_cols, sigmask,
     setlive) = _rlc_message_sig_columns(entries, n_chunks, rand_fn)
    timings = LAST_FAST_AGG_TIMINGS
    t0 = _stage_sync(timings, "marshal_ms", t0)

    pk_col = np.zeros((64, 2 * S), np.uint32)
    pk_col[:, 0] = np.frombuffer(_g1_aff_col(pk_pt), np.uint32)
    pk_col[:, 1] = np.frombuffer(_g1_aff_col(C.g1_neg(C.G1_GEN)), np.uint32)

    h_cols = HK.hash_g2_kernel_call(jnp.asarray(u_planes))
    t0 = _stage_sync(timings, "htc_ms", t0, h_cols)
    if STAGE_TIMINGS:
        # Attribution run: break the tail at the RLC fold boundary.
        lo_d, hi_d = jnp.asarray(lo), jnp.asarray(hi)
        live = jnp.asarray(setlive)
        h_col, h_ident = PK.sigma_combine(
            PK.sigma_kernel_call(h_cols, live, lo_d, hi_d))
        s_col, s_ident = PK.sigma_combine(
            PK.sigma_kernel_call(jnp.asarray(sig_cols), jnp.asarray(sigmask),
                                 lo_d, hi_d))
        t0 = _stage_sync(timings, "rlc_fold_ms", t0, h_col, s_col)
        ok = _shared_tail_from_folds(jnp.asarray(pk_col), h_col, h_ident,
                                     s_col, s_ident)
        _stage_sync(timings, "miller_final_ms", t0, ok)
    else:
        ok = _shared_tail(jnp.asarray(pk_col), h_cols,
                          jnp.asarray(sig_cols), jnp.asarray(setlive),
                          jnp.asarray(sigmask), jnp.asarray(lo),
                          jnp.asarray(hi))
    verdict = bool(ok)
    timings["sets"] = n
    timings["path"] = "pallas_shared"
    return verdict


@jax.jit
def _shared_tail_from_folds(pk_col, h_col, h_ident, s_col, s_ident):
    from . import pairing_kernel as PK

    S2 = pk_col.shape[1]
    g2 = jnp.zeros((128, S2), jnp.uint32)
    g2 = g2.at[:, 0].set(h_col).at[:, 1].set(s_col)
    mask = jnp.zeros((1, S2), jnp.int32)
    mask = mask.at[0, 0].set((~h_ident).astype(jnp.int32))
    mask = mask.at[0, 1].set((~s_ident).astype(jnp.int32))
    prod = PK.miller_fold_kernel_call(pk_col, g2, mask)
    ok = PK.finalize_kernel_call(prod)
    return ok[0, 0] != 0


@jax.jit
def _shared_tail(pk_col, h_cols, sig_cols, setlive, sigmask, lo, hi):
    """One device program for the collapsed path's algebra: two σ-style
    RLC folds (H side and σ side) → 2-live-lane fused Miller+fold →
    shared finalize.  One host sync (the returned bool)."""
    from . import pairing_kernel as PK

    h_col, h_ident = PK.sigma_combine(
        PK.sigma_kernel_call(h_cols, setlive, lo, hi))
    s_col, s_ident = PK.sigma_combine(
        PK.sigma_kernel_call(sig_cols, sigmask, lo, hi))
    return _shared_tail_from_folds(pk_col, h_col, h_ident, s_col, s_ident)


def _dispatch_shared(entries, pk_pt, rand_fn) -> bool:
    if pk_pt is None:
        return False  # identity aggregate key — invalid batch
    if _use_pallas():
        return _dispatch_shared_pallas(entries, pk_pt, rand_fn)
    return _dispatch_shared_xla(entries, pk_pt, rand_fn)


def _marshal_xla(entries, rand_fn):
    """Host marshalling for the pure-XLA kernel: limb arrays for one
    (sub-)batch, shapes bucketed to powers of two."""
    S = _next_pow2(len(entries))
    K = _next_pow2(max(len(e[1]) for e in entries))
    pk = np.broadcast_to(_G1_IDENT, (S, K, 3, LF.LIMBS)).copy()
    kmask = np.zeros((S, K), bool)
    sig = np.broadcast_to(_G2_IDENT, (S, 3, 2, LF.LIMBS)).copy()
    h = np.broadcast_to(_G2_IDENT, (S, 3, 2, LF.LIMBS)).copy()
    scal = np.zeros((S, 2), np.uint32)
    smask = np.zeros(S, bool)
    for i, (sig_pt, keys, msg) in enumerate(entries):
        for j, kp in enumerate(keys):
            pk[i, j] = _g1_arr(kp)
        kmask[i, :len(keys)] = True
        if sig_pt is not None:
            sig[i] = _g2_arr(sig_pt)
        h[i] = _h_arr(msg)
        c = rand_fn()
        scal[i] = (c & 0xFFFFFFFF, c >> 32)
        smask[i] = True
    return (pk, kmask, sig, h, scal, smask)


def _dispatch(entries, rand_fn) -> bool:
    """entries: list of (agg_sig_point | None meaning infinity is already
    rejected, [pubkey points], message bytes).  rand_fn() → 64-bit scalar.

    Off-TPU, batches larger than the pipeline sub-batch run through the
    SAME staged executor as the Pallas path (marshal i+1 overlaps the
    kernel on i; each sub-batch is an independent product so the AND of
    the verdicts equals the monolithic verdict) — guarded like
    :func:`_split_batches` to entries that each carry a signature."""
    # Fresh stage split per dispatch — per-key overwrites would otherwise
    # leak keys from a previous dispatch (or a different path's run)
    # into the decomposition bench.py reads back.
    LAST_FAST_AGG_TIMINGS.clear()
    entries, valid = _dedup_shared_keygroups(entries)
    if not valid:
        return False
    shared_pt = _shared_group_key(entries)
    if shared_pt is not None:
        # The whole batch signs under one aggregated key: collapse to
        # e(P, Σ c_i·H_i) · e(−G, Σ c_i·σ_i) — two Miller lanes total.
        return _dispatch_shared(entries, shared_pt, rand_fn)
    if _use_pallas():
        return _dispatch_pallas(entries, rand_fn)
    # Off-TPU XLA path: the SAME K-grouped work list as the Pallas path
    # (`_split_batches`) — a mixed-width batch (the overlapped block
    # batch: ~committee-width attestation sets + single-key proposer/
    # randao/exit sets + a 512-key sync aggregate) no longer pads every
    # set's pubkey lanes to the batch max K.  Each work item is an
    # independent RLC product, so the AND of verdicts equals the
    # monolithic verdict.
    work = _split_batches(entries)
    if len(work) > 1:
        if _pipeline_sets() <= 0:
            # PIPELINE_SETS=0 disables the staged machinery (the
            # debugging oracle): K-groups dispatch sequentially, one
            # monolithic marshal + kernel each.
            return all(_verify_xla_direct(batch, rand_fn)
                       for batch in work)
        from ..parallel.pipeline import StagedExecutor
        ex = StagedExecutor("bls_pipeline", subsystem="bls")
        outs = ex.map(
            work,
            lambda batch: _marshal_xla(batch, rand_fn),
            lambda staged: _verify_sets_kernel(*staged))
        return all(bool(o) for o in outs)
    return _verify_xla_direct(entries, rand_fn)


def _verify_xla_direct(batch, rand_fn) -> bool:
    """One monolithic marshal + XLA kernel call, with the implicit jit
    staging accounted into the device ledger (the staged-executor paths
    account theirs at the staging seam)."""
    import time as _time
    from ..common.device_ledger import LEDGER

    args = _marshal_xla(batch, rand_fn)
    LEDGER.note_transfer(
        "h2d", sum(int(a.nbytes) for a in args if hasattr(a, "nbytes")),
        subsystem="bls")
    t0 = _time.perf_counter()
    ok = bool(_verify_sets_kernel(*args))
    LEDGER.note_dispatch("bls", (_time.perf_counter() - t0) * 1e3)
    LEDGER.note_transfer("d2h", 1, subsystem="bls")
    return ok


def _host_fastpath_max() -> int:
    """Batch sizes up to this verify on the HOST via the native C++
    pairing instead of the device (VERDICT r4 #4): the axon tunnel adds
    ~100 ms fixed roundtrip per device sync, while the native host verify
    costs ~30 ms/set — so tiny batches (the gossip-block proposer check)
    are latency-bound on dispatch, not compute.  Default crossover 4;
    co-located deployments (µs dispatch) should set
    LIGHTHOUSE_TPU_HOST_FASTPATH_MAX=0 to keep everything on-device."""
    from ..common.knobs import knob_int
    return knob_int("LIGHTHOUSE_TPU_HOST_FASTPATH_MAX")


def _host_fast(n_sets: int) -> bool:
    if n_sets > _host_fastpath_max():
        return False
    from . import native
    return native.ready()  # honors the NO_NATIVE kill-switch


class TpuBackend:
    """Device-batched verification registered as ``tpu`` in :mod:`.bls`."""

    name = "tpu"

    def verify(self, signature, pubkeys, message) -> bool:
        if signature.point is None or not pubkeys:
            return False
        if _host_fast(1):
            from .bls import _BACKENDS
            return _BACKENDS["python"].verify(signature, pubkeys, message)
        return _dispatch(
            [(signature.point, [k.point for k in pubkeys], bytes(message))],
            rand_fn=lambda: 1)

    def aggregate_verify(self, signature, pubkeys, messages) -> bool:
        if signature.point is None or not pubkeys \
                or len(pubkeys) != len(messages):
            return False
        if _host_fast(len(messages)):
            from .bls import _BACKENDS
            return _BACKENDS["python"].aggregate_verify(
                signature, pubkeys, messages)
        # Distinct message per signer: one single-key set per message, the
        # aggregate signature attached to the first set, scalars all 1.
        entries = [(None, [pk.point], bytes(m))
                   for pk, m in zip(pubkeys, messages)]
        entries[0] = (signature.point, entries[0][1], entries[0][2])
        return _dispatch(entries, rand_fn=lambda: 1)

    def verify_signature_sets(self, sets) -> bool:
        import secrets
        if not sets:
            return False
        if _host_fast(len(sets)):
            from .bls import _BACKENDS
            return _BACKENDS["python"].verify_signature_sets(sets)
        entries = []
        for s in sets:
            if s.signature is None or s.signature.point is None:
                return False
            if not s.signing_keys:
                return False
            entries.append((s.signature.point,
                            [k.point for k in s.signing_keys],
                            bytes(s.message)))

        def rand_nonzero():
            c = 0
            while c == 0:
                c = secrets.randbits(64)
            return c

        return _dispatch(entries, rand_fn=rand_nonzero)


def register() -> None:
    from . import bls
    bls.register_backend("tpu", TpuBackend())


register()
