"""The ``tpu`` BLS backend — batched signature verification on the device.

This is the role blst plays for the reference (``bls::impls::supranational``,
``/root/reference/crypto/bls/src/impls/blst.rs``): the production backend
behind the backend-registry seam in :mod:`.bls`.  All three public verify
entry points funnel into ONE fused device program per (sets, keys) shape
bucket:

    per-set pubkey tree-aggregation (G1)
      → per-set random-linear-combination scaling (64-bit ladders, G1+G2)
      → signature accumulation (G2 tree sum)
      → batched Miller loops over all pairs
      → one shared final exponentiation of the lane product
      → == 1

replicating ``verify_multiple_aggregate_signatures`` semantics
(``impls/blst.rs:36-119``) including the consensus-critical edge rules:
empty set lists, empty signing-key lists, missing/infinity signatures and
identity aggregate pubkeys all fail verification (host-side pre-checks +
an on-device identity-aggregate flag).

Host work is marshalling only: affine points → Montgomery limb arrays
(memoised per point, the ``validator_pubkey_cache.rs`` role) and
hash-to-curve of messages (host SSWU for now).  Shapes are bucketed to
powers of two so XLA compiles a handful of programs, then every call hits
the jit cache.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from . import curve as C
from . import limb_curve as LC
from . import limb_field as LF
from . import limb_pairing as LP
from ..ops.merkle import _next_pow2
from .hash_to_curve import hash_to_g2

_NEG_G1_GEN = LC.g1_to_limbs(C.g1_neg(C.G1_GEN))
_G1_IDENT = LC.g1_to_limbs(None)
_G2_IDENT = LC.g2_to_limbs(None)


@lru_cache(maxsize=1 << 16)
def _g1_limbs(point) -> bytes:
    return LC.g1_to_limbs(point).tobytes()


@lru_cache(maxsize=1 << 16)
def _g2_limbs(point) -> bytes:
    return LC.g2_to_limbs(point).tobytes()


@lru_cache(maxsize=1 << 14)
def _h_point(message: bytes):
    """Memoised hash-to-curve; both path-specific encodings derive from it."""
    return hash_to_g2(message)


def _h_limbs(message: bytes) -> bytes:
    return LC.g2_to_limbs(_h_point(message)).tobytes()


def _g1_arr(point) -> np.ndarray:
    return np.frombuffer(_g1_limbs(point), np.uint32).reshape(3, LF.LIMBS)


def _g2_arr(point) -> np.ndarray:
    return np.frombuffer(_g2_limbs(point), np.uint32).reshape(3, 2, LF.LIMBS)


def _h_arr(message: bytes) -> np.ndarray:
    return np.frombuffer(_h_limbs(bytes(message)), np.uint32).reshape(3, 2, LF.LIMBS)


@jax.jit
def _verify_sets_kernel(pk, kmask, sig, h, scal, smask):
    """Fused batch verify.  Shapes: pk (S,K,3,26), kmask (S,K) bool,
    sig/h (S,3,2,26) projective, scal (S,2) uint32 lo/hi, smask (S,) bool.
    S and K are powers of two.  Returns a scalar bool."""
    S, K = pk.shape[0], pk.shape[1]
    ident1 = jnp.asarray(_G1_IDENT)
    pkm = LC.point_select(kmask, pk, ident1, LC.G1_OPS)
    agg = LC.tree_sum(LC.G1_OPS, pkm, K)              # (S,3,26)
    # A live set whose aggregate pubkey is the identity is invalid
    # (`PythonBackend.verify_signature_sets` / blst's aggregate move).
    any_bad = jnp.any(smask & LF.is_zero(agg[..., 2, :]))
    aggc = LC.scalar_mul(LC.G1_OPS, agg, scal)        # (S,3,26)
    sigc = LC.scalar_mul(LC.G2_OPS, sig, scal)        # (S,3,2,26)
    sigsum = LC.tree_sum(LC.G2_OPS, sigc, S)          # (3,2,26)
    # Pairing lanes: i<S → (c_i·aggpk_i, H_i); lane S → (−g1, Σc_i·sig_i);
    # the rest of the 2S block is masked padding.
    g1_lanes = jnp.concatenate(
        [aggc, jnp.asarray(_NEG_G1_GEN)[None],
         jnp.broadcast_to(jnp.asarray(_G1_IDENT), (S - 1, 3, LF.LIMBS))])
    g2_lanes = jnp.concatenate(
        [h, sigsum[None],
         jnp.broadcast_to(jnp.asarray(_G2_IDENT), (S - 1, 3, 2, LF.LIMBS))])
    lane_mask = jnp.concatenate(
        [smask, jnp.array([True]), jnp.zeros(S - 1, bool)])
    ok = LP.multi_pairing_is_one(g1_lanes, g2_lanes, lane_mask)
    return ok & ~any_bad


# ---------------------------------------------------------------------------
# Pallas path (production TPU): prepare → miller → product → host final exp
# ---------------------------------------------------------------------------

def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


@lru_cache(maxsize=1 << 16)
def _g1_aff_col(point) -> bytes:
    """Affine G1 → (64,) block-layout column (x at rows 0, y at 32)."""
    col = np.zeros(64, np.uint32)
    col[0:26] = LF.to_mont(point[0])
    col[32:58] = LF.to_mont(point[1])
    return col.tobytes()


@lru_cache(maxsize=1 << 16)
def _g2_aff_col(point) -> bytes:
    """Affine G2 → (128,) block-layout column (x0/x1/y0/y1 at 0/32/64/96)."""
    (x0, x1), (y0, y1) = point
    col = np.zeros(128, np.uint32)
    col[0:26] = LF.to_mont(x0)
    col[32:58] = LF.to_mont(x1)
    col[64:90] = LF.to_mont(y0)
    col[96:122] = LF.to_mont(y1)
    return col.tobytes()


def _h_aff_col(message: bytes) -> bytes:
    return _g2_aff_col(_h_point(message))


def _lane_fq12(planes: np.ndarray, lane: int):
    """(384, M) device blocks → host Fq12 tuple for one lane."""
    c = [LF.from_mont(planes[i * 32:i * 32 + 26, lane]) for i in range(12)]
    return (((c[0], c[1]), (c[2], c[3]), (c[4], c[5])),
            ((c[6], c[7]), (c[8], c[9]), (c[10], c[11])))


def _dispatch_pallas(entries, rand_fn) -> bool:
    """Chunked device pipeline replicating ``_verify_sets_kernel`` semantics:

        ∏ e(c_i·aggpk_i, H(m_i)) · ∏ e(−c_i·G, σ_i) == 1

    (the signature side of the RLC rides the pairing bilinearity — no G2
    ladder).  Each 128-set chunk runs the prepare kernel + one 256-lane
    Miller launch; lane products land on the host for ONE shared
    final exponentiation across the whole call.
    """
    from . import pairing_kernel as PK
    from .pairing import final_exponentiation_cubed
    from . import fields as F

    S = PK.PREP_S
    acc = F.FQ12_ONE
    for base in range(0, len(entries), S):
        chunk = entries[base:base + S]
        n = len(chunk)
        K = _next_pow2(max(len(e[1]) for e in chunk))
        pk = np.zeros((96, K * S), np.uint32)
        kmask = np.zeros((1, K * S), np.int32)
        lo = np.zeros((1, S), np.uint32)
        hi = np.zeros((1, S), np.uint32)
        g2 = np.zeros((128, 2 * S), np.uint32)
        lane_mask = np.zeros((1, 2 * S), np.int32)
        one_col = np.zeros(26, np.uint32)
        one_col[:] = np.asarray(LF.ONE_MONT)
        for s, (sig_pt, keys, msg) in enumerate(chunk):
            for k, kp in enumerate(keys):
                col = k * S + s
                pk[0:64, col] = np.frombuffer(_g1_aff_col(kp), np.uint32)
                pk[64:90, col] = one_col  # projective Z = 1
                kmask[0, col] = 1
            c = rand_fn()
            lo[0, s] = c & 0xFFFFFFFF
            hi[0, s] = c >> 32
            g2[:, s] = np.frombuffer(_h_aff_col(bytes(msg)), np.uint32)
            lane_mask[0, s] = 1
            if sig_pt is not None:
                g2[:, S + s] = np.frombuffer(_g2_aff_col(sig_pt), np.uint32)
                lane_mask[0, S + s] = 1
        g1_aff, idflags = PK.prepare_kernel_call(
            jnp.asarray(pk), jnp.asarray(kmask), jnp.asarray(lo),
            jnp.asarray(hi), K=K)
        if bool(np.asarray(idflags)[0, :n].any()):
            return False  # a live set's aggregate pubkey is the identity
        f = PK.miller_kernel_call(g1_aff, jnp.asarray(g2))
        prod = np.asarray(PK.product_kernel_call(f, jnp.asarray(lane_mask)))
        for lane in range(S):
            acc = F.fq12_mul(acc, _lane_fq12(prod, lane))
    return final_exponentiation_cubed(acc) == F.FQ12_ONE


def _dispatch(entries, rand_fn) -> bool:
    """entries: list of (agg_sig_point | None meaning infinity is already
    rejected, [pubkey points], message bytes).  rand_fn() → 64-bit scalar."""
    if _use_pallas():
        return _dispatch_pallas(entries, rand_fn)
    S = _next_pow2(len(entries))
    K = _next_pow2(max(len(e[1]) for e in entries))
    pk = np.broadcast_to(_G1_IDENT, (S, K, 3, LF.LIMBS)).copy()
    kmask = np.zeros((S, K), bool)
    sig = np.broadcast_to(_G2_IDENT, (S, 3, 2, LF.LIMBS)).copy()
    h = np.broadcast_to(_G2_IDENT, (S, 3, 2, LF.LIMBS)).copy()
    scal = np.zeros((S, 2), np.uint32)
    smask = np.zeros(S, bool)
    for i, (sig_pt, keys, msg) in enumerate(entries):
        for j, kp in enumerate(keys):
            pk[i, j] = _g1_arr(kp)
        kmask[i, :len(keys)] = True
        if sig_pt is not None:
            sig[i] = _g2_arr(sig_pt)
        h[i] = _h_arr(msg)
        c = rand_fn()
        scal[i] = (c & 0xFFFFFFFF, c >> 32)
        smask[i] = True
    ok = _verify_sets_kernel(jnp.asarray(pk), jnp.asarray(kmask),
                             jnp.asarray(sig), jnp.asarray(h),
                             jnp.asarray(scal), jnp.asarray(smask))
    return bool(ok)


class TpuBackend:
    """Device-batched verification registered as ``tpu`` in :mod:`.bls`."""

    name = "tpu"

    def verify(self, signature, pubkeys, message) -> bool:
        if signature.point is None or not pubkeys:
            return False
        return _dispatch(
            [(signature.point, [k.point for k in pubkeys], bytes(message))],
            rand_fn=lambda: 1)

    def aggregate_verify(self, signature, pubkeys, messages) -> bool:
        if signature.point is None or not pubkeys \
                or len(pubkeys) != len(messages):
            return False
        # Distinct message per signer: one single-key set per message, the
        # aggregate signature attached to the first set, scalars all 1.
        entries = [(None, [pk.point], bytes(m))
                   for pk, m in zip(pubkeys, messages)]
        entries[0] = (signature.point, entries[0][1], entries[0][2])
        return _dispatch(entries, rand_fn=lambda: 1)

    def verify_signature_sets(self, sets) -> bool:
        import secrets
        if not sets:
            return False
        entries = []
        for s in sets:
            if s.signature is None or s.signature.point is None:
                return False
            if not s.signing_keys:
                return False
            entries.append((s.signature.point,
                            [k.point for k in s.signing_keys],
                            bytes(s.message)))

        def rand_nonzero():
            c = 0
            while c == 0:
                c = secrets.randbits(64)
            return c

        return _dispatch(entries, rand_fn=rand_nonzero)


def register() -> None:
    from . import bls
    bls.register_backend("tpu", TpuBackend())


register()
