"""Batched G1/G2 group arithmetic on the TPU (projective, branch-free).

Device counterpart of the host :mod:`..curve` Jacobian code and of blst's
point arithmetic (``/root/reference/crypto/bls/src/impls/blst.rs`` backend).
Everything here is *complete*: the Renes–Costello–Batina addition law for
``a = 0`` short-Weierstrass curves evaluates correctly for every input pair
— doubling, inverses, the identity — with zero branches, which is exactly
what a SIMD lane wants (the reference's CPU code branches per case;
branching per lane would serialise the batch).

Identity = (0 : 1 : 0).  Points are homogeneous projective with limb-field
coordinates: G1 over Fq ``(..., 3, 26)``, G2 over Fq2 ``(..., 3, 2, 26)``
(axis -2/-3 … the X/Y/Z axis sits before the field-coefficient axes).

Curve constants: ``b = 4`` (G1), ``b' = 4(1+u)`` (G2) — so the ``b3 = 3b``
multiplications reduce to cheap small-scalar limb ops (×12, ξ·×12).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np
import jax.numpy as jnp

from . import limb_field as LF
from . import limb_tower as T


@dataclass(frozen=True)
class CurveOps:
    """Field vtable binding the generic group law to Fq (G1) or Fq2 (G2)."""
    name: str
    fmul: Callable      # batched field multiply
    b3_mul: Callable    # cheap multiply by 3b
    stack_axis: int     # axis for stacking parallel field muls
    coeff_ndim: int     # trailing dims of one field element

    def stack(self, items):
        return jnp.stack(items, axis=self.stack_axis)

    def parts(self, arr, k):
        ax = self.stack_axis
        return [jnp.take(arr, i, axis=ax) for i in range(k)]

    def point(self, x, y, z):
        return jnp.stack([x, y, z], axis=self.stack_axis)

    def coords(self, p):
        return self.parts(p, 3)


G1_OPS = CurveOps(
    name="g1",
    fmul=LF.mont_mul,
    b3_mul=lambda t: LF.muls(t, 12),
    stack_axis=-2,
    coeff_ndim=1,
)

G2_OPS = CurveOps(
    name="g2",
    fmul=T.fq2_mul,
    b3_mul=lambda t: LF.muls(T.fq2_mul_by_xi(t), 12),
    stack_axis=-3,
    coeff_ndim=2,
)


def point_add(ops: CurveOps, p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Complete addition (Renes–Costello–Batina, a = 0):

    with t0 = X1X2, t1 = Y1Y2, t2 = Z1Z2, s3 = X1Y2+X2Y1,
    s4 = Y1Z2+Y2Z1, s5 = X1Z2+X2Z1, and u± = Y1Y2 ± b3·Z1Z2:

        X3 = s3·u− − b3·s4·s5
        Y3 = u+·u− + 3·b3·t0·s5
        Z3 = s4·u+ + 3·t0·s3
    """
    X1, Y1, Z1 = ops.coords(p)
    X2, Y2, Z2 = ops.coords(q)
    # Round 1: six independent multiplies, one batched call.
    r1 = ops.fmul(
        ops.stack([X1, Y1, Z1,
                   LF.add(X1, Y1), LF.add(Y1, Z1), LF.add(X1, Z1)]),
        ops.stack([X2, Y2, Z2,
                   LF.add(X2, Y2), LF.add(Y2, Z2), LF.add(X2, Z2)]))
    t0, t1, t2, pxy, pyz, pxz = ops.parts(r1, 6)
    s3 = LF.sub(pxy, LF.add(t0, t1))   # X1Y2 + X2Y1
    s4 = LF.sub(pyz, LF.add(t1, t2))   # Y1Z2 + Y2Z1
    s5 = LF.sub(pxz, LF.add(t0, t2))   # X1Z2 + X2Z1
    b3t2 = ops.b3_mul(t2)
    um = LF.sub(t1, b3t2)              # u−
    up = LF.add(t1, b3t2)              # u+
    # Round 2: six more independent multiplies.
    r2 = ops.fmul(
        ops.stack([s3, s4, up, t0, s4, t0]),
        ops.stack([um, s5, um, s5, up, s3]))
    a_s3um, a_s4s5, a_upum, a_t0s5, a_s4up, a_t0s3 = ops.parts(r2, 6)
    X3 = LF.sub(a_s3um, ops.b3_mul(a_s4s5))
    Y3 = LF.add(a_upum, LF.muls(ops.b3_mul(a_t0s5), 3))
    Z3 = LF.add(a_s4up, LF.muls(a_t0s3, 3))
    return ops.point(X3, Y3, Z3)


def point_double(ops: CurveOps, p: jnp.ndarray) -> jnp.ndarray:
    return point_add(ops, p, p)


def point_neg(ops: CurveOps, p: jnp.ndarray) -> jnp.ndarray:
    X, Y, Z = ops.coords(p)
    return ops.point(X, LF.neg(Y), Z)


def point_select(mask: jnp.ndarray, p: jnp.ndarray, q: jnp.ndarray,
                 ops: CurveOps) -> jnp.ndarray:
    """Per-lane ``mask ? p : q``; mask shape = batch dims."""
    m = mask.reshape(mask.shape + (1,) * (ops.coeff_ndim + 1))
    return jnp.where(m, p, q)


def identity_like(ops: CurveOps, batch_shape: tuple) -> np.ndarray:
    """(0 : 1 : 0) broadcast to the batch."""
    coeff = (2, LF.LIMBS) if ops.coeff_ndim == 2 else (LF.LIMBS,)
    pt = np.zeros((3,) + coeff, dtype=np.uint32)
    one = np.asarray(LF.ONE_MONT)
    if ops.coeff_ndim == 2:
        pt[1, 0] = one
    else:
        pt[1] = one
    return np.broadcast_to(pt, batch_shape + pt.shape).copy()


def scalar_mul(ops: CurveOps, p: jnp.ndarray, scalars: jnp.ndarray,
               bits: int = 64) -> jnp.ndarray:
    """Batched double-and-add: per-lane point × per-lane scalar.

    ``p``: (..., 3, coeffs); ``scalars``: (...,) uint64 as 2×uint32 —
    pass as ``(..., 2)`` uint32 (lo, hi).  LSB-first ladder, ``bits`` fixed
    iterations (64 default — the RLC batch-verify coefficients of
    ``impls/blst.rs:36-119`` are 64-bit).
    """
    import jax

    batch = p.shape[:-(ops.coeff_ndim + 1)]  # strip X/Y/Z + coeff dims
    acc = jnp.asarray(identity_like(ops, batch))
    lo = scalars[..., 0]
    hi = scalars[..., 1]

    def body(carry, i):
        acc, base = carry
        word = jnp.where(i < 32, lo, hi)
        bit = (word >> (i.astype(jnp.uint32) % np.uint32(32))) & np.uint32(1)
        added = point_add(ops, acc, base)
        acc = point_select(bit.astype(bool), added, acc, ops)
        base = point_add(ops, base, base)
        return (acc, base), None

    (acc, _), _ = jax.lax.scan(body, (acc, p), jnp.arange(bits))
    return acc


def tree_sum(ops: CurveOps, pts: jnp.ndarray, axis_len: int) -> jnp.ndarray:
    """Sum ``axis_len`` points along the axis before X/Y/Z (pad with the
    identity to a power of two first).  log2 rounds of batched adds."""
    k = axis_len
    if k & (k - 1):
        raise ValueError("pad point count to a power of two")
    ax = ops.stack_axis - 1  # the summation axis sits before X/Y/Z
    while k > 1:
        k //= 2
        lo = jnp.take(pts, jnp.arange(k), axis=ax)
        hi = jnp.take(pts, jnp.arange(k, 2 * k), axis=ax)
        pts = point_add(ops, lo, hi)
    return jnp.squeeze(pts, axis=ax)


# ---------------------------------------------------------------------------
# Host conversions (affine tuples ↔ projective limbs)
# ---------------------------------------------------------------------------

def g1_to_limbs(p) -> np.ndarray:
    """Host affine G1 (x, y) or None → (3, 26) projective Montgomery limbs."""
    if p is None:
        return np.stack([np.asarray(LF.ZERO), np.asarray(LF.ONE_MONT),
                         np.asarray(LF.ZERO)])
    return np.stack([LF.to_mont(p[0]), LF.to_mont(p[1]),
                     np.asarray(LF.ONE_MONT)])


def g2_to_limbs(p) -> np.ndarray:
    """Host affine G2 ((x0,x1), (y0,y1)) or None → (3, 2, 26) limbs."""
    zero2 = np.zeros((2, LF.LIMBS), np.uint32)
    one2 = np.stack([np.asarray(LF.ONE_MONT), np.asarray(LF.ZERO)])
    if p is None:
        return np.stack([zero2, one2, zero2])
    return np.stack([T.fq2_to_limbs(p[0]), T.fq2_to_limbs(p[1]), one2])


def g1_from_limbs(arr) -> tuple | None:
    from . import fields as F
    arr = np.asarray(arr)
    x, y, z = (LF.from_mont(arr[0]), LF.from_mont(arr[1]), LF.from_mont(arr[2]))
    if z == 0:
        return None
    zi = F.fq_inv(z)
    return (x * zi % F.P, y * zi % F.P)


def g2_from_limbs(arr) -> tuple | None:
    from . import fields as F
    arr = np.asarray(arr)
    x = T.fq2_from_limbs(arr[0])
    y = T.fq2_from_limbs(arr[1])
    z = T.fq2_from_limbs(arr[2])
    if z == (0, 0):
        return None
    zi = F.fq2_inv(z)
    return (F.fq2_mul(x, zi), F.fq2_mul(y, zi))
